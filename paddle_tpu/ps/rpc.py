"""TCP PS transport: Python wrappers over the native service
(csrc/ps_service.cc) — the DCN path for multi-host CPU tables.

Reference counterpart: BrpcPsServer/BrpcPsClient
(ps/service/brpc_ps_{server,client}.cc) and the PsService command
dispatch (sendrecv.proto). Behavioral parity points:
- key routing: server = key % num_servers (brpc_ps_client.cc:568),
  one request per server per pull, sub-responses joined client-side;
- dense params split evenly across servers (DenseDimPerShard :607);
- insert-on-miss pull, client-side duplicate-key merge before push;
- barrier via the server-side BarrierTable (all trainers arrive).

``NativePsServer`` hosts the C++ service in-process (the reference runs
brpc servers in the trainer-0/daemon processes the same way);
``RpcPsClient`` implements the PSClient interface over N servers.
"""

from __future__ import annotations

import ctypes
import os
import struct
# lock discipline (tools/lint/py_locks.py; docs/STATIC_ANALYSIS.md):
# all client/server mutexes are LEAVES. `_mu` is the per-connection
# wire mutex (serializes connect/call/close on ONE socket — the IO is
# the protected resource); `_conns_mu` only swaps connection lists
# (connects build OUTSIDE it); `_pool_mu`/`_count_mu`/`_pause_mu`
# guard scalars. `_ef_mu` guards the error-feedback residual store
# (gather/quantize/scatter is atomic per push; network sends happen
# outside it).
# LOCK LEAF: _mu _pause_mu _conns_mu _pool_mu _count_mu _ef_mu
import threading
import time
from collections import Counter
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..core import sync as _sync
from ..core.enforce import (NotFoundError, PreconditionNotMetError,
                            PsTransportError, QuotaExceededError,
                            ThrottledError, WrongShardError,
                            WrongTenantError, enforce)
from ..core.flags import define_flag, flag
from ..core.profiler import RecordEvent
from ..obs import flightrec as _flightrec
from ..obs import registry as _obs_registry
from ..obs import trace as _trace
from ..obs.registry import CounterGroup
from .accessor import AccessorConfig
from .client import PSClient
from .faultpoints import faultpoint
from .native import load_native, table_native_params
from .table import (TableConfig, format_shard_row, merge_duplicate_keys,
                    parse_shard_row)

# transport robustness knobs (the brpc client's FLAGS_pserver_* family,
# brpc_ps_client.cc:24-45); env-overridable as FLAGS_pserver_*
define_flag("pserver_connect_timeout_ms", 10000,
            "PS client TCP connect deadline (0 = blocking)")
define_flag("pserver_timeout_ms", 30000,
            "PS client per-call IO deadline (0 = block forever)")
define_flag("pserver_max_retry", 3,
            "attempts per PS call across reconnects before failing")
define_flag("pserver_retry_backoff_ms", 100,
            "base backoff between PS call retries (doubles per attempt)")
define_flag("pserver_long_call_timeout_ms", 600000,
            "deadline for table-scale commands (save/load/export/shrink/"
            "compact/ssd-create) whose runtime grows with table size")
define_flag("pserver_barrier_timeout_ms", 1800000,
            "barrier wait bound — generous (peers may legitimately be "
            "minutes behind) but finite, so a dead server still surfaces")
define_flag("ps_rpc_parallel", True,
            "fan multi-shard PS calls out concurrently (one in-flight "
            "call per server connection, results scattered back by "
            "routing index) so per-step latency is max(shards), not "
            "sum(shards); False forces the serial per-server loop "
            "(debugging / deterministic call interleaving)")
# serve-path QoS class (ps/serving frontends; first concrete step of the
# ROADMAP item-5 QoS ladder): serving reads are latency-bound and
# shedding-friendly, so they get a SHORT deadline and at most one
# attempt instead of riding the training client's patient retry budget —
# and their OWN circuit-breaker thresholds, so a serving brown-out can
# never trip the training client's breaker (or vice versa)
define_flag("pserver_serve_timeout_ms", 2000,
            "per-call IO deadline for qos='serve' PS clients (serving "
            "reads fail fast and shed instead of queueing behind long "
            "training calls)")
define_flag("pserver_serve_max_retry", 1,
            "attempts per PS call for qos='serve' clients (1 = no "
            "retry: the frontend's admission control owns the retry "
            "policy, not the transport)")
define_flag("ps_serve_breaker_failures", 2,
            "consecutive transport failures before a SERVE-qos client "
            "opens an endpoint's breaker (trip faster than training: "
            "every blocked serve call is user-visible latency)")
define_flag("ps_serve_breaker_cooldown_ms", 500,
            "open-breaker cooldown for serve-qos clients before one "
            "half-open probe")
define_flag("ps_push_ef_max_rows", 1 << 20,
            "per-table cap on client-side error-feedback residual rows "
            "(push_wire_dtype='int8'): past it the whole table's "
            "residuals drain over the fp32 wire and the store restarts "
            "empty — bounds client RAM at ~4*gd bytes/row without ever "
            "dropping training signal")

__all__ = ["NativePsServer", "RpcPsClient", "RemoteSparseTable",
           "rpc_available", "make_conn", "send_replicate",
           "PsTransportError"]

# command ids (ps_service.cc Cmd enum)
_CREATE_SPARSE = 1
_CREATE_DENSE = 2
_PULL_SPARSE = 3
_PUSH_SPARSE = 4
_PULL_DENSE = 5
_PUSH_DENSE = 6
_SET_DENSE = 7
_SIZE = 8
_SHRINK = 9
_SAVE_BEGIN = 10  # legacy two-phase (local engine ABI)
_SAVE_FETCH = 11
_INSERT_FULL = 12
_EXPORT = 13
_BARRIER = 14
_STOP = 15
_PING = 16
_GLOBAL_STEP = 17
_CREATE_GEO = 18
_PUSH_GEO = 19
_PULL_GEO = 20
_SAVE_ALL = 21
_SPILL = 22
_STATS = 23
_COMPACT = 24
_LOAD_COLD = 34
_SAVE_FILE = 35
_LOAD_FILE = 36
# HA / replication commands (ps_service.cc kReplicate..kDenseRestore;
# ps/ha.py is the driver — see docs/OPERATIONS.md §6)
_REPLICATE = 37
_EPOCH = 38
_REPL_STATE = 39
_DIGEST = 40
_DENSE_SNAP = 41
_DENSE_RESTORE = 42
_OBS_SNAP = 43
# live elastic resharding (ps/reshard.py; docs/OPERATIONS.md §15):
# n = modulus (0 = read ownership), aux = residue (-1 = fence out)
_RETAIN = 44
# multi-tenancy (ps/tenancy.py; docs/OPERATIONS.md §20): hello binds a
# connection to tenant n with a token payload; config is operator-plane
# tenant install/usage-meter. The tenant tag rides the table_id HIGH
# BYTE (_TENANT_SHIFT) — the ReqHeader is contract-pinned and never grows
_TENANT_HELLO = 45
_TENANT_CONFIG = 46
_TENANT_SHIFT = 24  # csrc kTenantShift

# push-value wire encodings (csrc PushWireFlag — kPushSparse aux bits;
# TableConfig.push_wire_dtype resolves them at create time). Pinned
# against the csrc enum by graftlint pass 8 (wire_contract FLAG_CONTRACT)
_PUSH_WIRE_F16 = 1
_PUSH_WIRE_I8 = 2
_PUSH_WIRE_BLOCK_SHIFT = 8

_DENSE_OPT_IDS = {"sgd": 0, "adam": 1, "sum": 2}

# client-op names the registry family ``ps_client_ops`` pre-binds (a
# fixed set: handle creation happens once per client, at __init__)
_OP_NAMES = ("pull_sparse", "push_sparse", "pull_dense", "push_dense",
             "push_geo", "pull_geo", "export_full", "import_full",
             "global_step")
_CLIENT_SEQ = iter(range(1, 1 << 30))  # per-process client tag allocator

# wire frame header sizes (csrc ReqHeader / response header) — the
# request header is the 28 legacy bytes + the fixed trace-context
# field; test_obs.py pins ha._HDR.size against the same sum
_REQ_HEADER_BYTES = 28 + _trace.WIRE_CONTEXT_BYTES
_RESP_HEADER_BYTES = 16  # [u64 payload_len][i64 status]


def _long_ms() -> int:
    """Deadline for commands whose runtime scales with table size."""
    return int(flag("pserver_long_call_timeout_ms"))


def _run_with_span(span, task):
    """Fan-out worker shim: run ``task`` with the submitting thread's
    span adopted (obs/trace.py with_span)."""
    with _trace.with_span(span):
        return task()


_EMPTY_RESP = b""


def _configure_rpc(lib: ctypes.CDLL) -> None:
    lib.pss_create.restype = ctypes.c_void_p
    lib.pss_create.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.pss_port.restype = ctypes.c_int
    lib.pss_port.argtypes = [ctypes.c_void_p]
    lib.pss_stopped.restype = ctypes.c_int
    lib.pss_stopped.argtypes = [ctypes.c_void_p]
    lib.pss_stop.argtypes = [ctypes.c_void_p]
    lib.pss_destroy.argtypes = [ctypes.c_void_p]
    lib.psc_connect.restype = ctypes.c_void_p
    lib.psc_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.psc_connect2.restype = ctypes.c_void_p
    lib.psc_connect2.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_int]
    lib.psc_close.argtypes = [ctypes.c_void_p]
    lib.psc_call.restype = ctypes.c_int64
    lib.psc_call.argtypes = [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint32,
                             ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
                             ctypes.c_uint64]
    lib.psc_call2.restype = ctypes.c_int64
    lib.psc_call2.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                              ctypes.c_uint32, ctypes.c_int64, ctypes.c_int32,
                              ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32]
    lib.psc_resp_len.restype = ctypes.c_uint64
    lib.psc_resp_len.argtypes = [ctypes.c_void_p]
    lib.psc_resp_copy.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    # scatter-gather + zero-copy response symbols (rebuild the .so if a
    # stale build lacks them — _rpc_lib raises through the AttributeError)
    lib.psc_callv.restype = ctypes.c_int64
    lib.psc_callv.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                              ctypes.c_uint32, ctypes.c_int64, ctypes.c_int32,
                              ctypes.c_int32,
                              ctypes.POINTER(ctypes.c_void_p),
                              ctypes.POINTER(ctypes.c_uint64),
                              ctypes.c_int32]
    lib.psc_resp_ptr.restype = ctypes.c_void_p
    lib.psc_resp_ptr.argtypes = [ctypes.c_void_p]
    # trace-context call (obs plane): psc_callv + the fixed 16-byte
    # (trace_id, span_id) header field (zeroes when untraced)
    lib.psc_callv2.restype = ctypes.c_int64
    lib.psc_callv2.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                               ctypes.c_uint32, ctypes.c_int64,
                               ctypes.c_int32, ctypes.c_int32,
                               ctypes.POINTER(ctypes.c_void_p),
                               ctypes.POINTER(ctypes.c_uint64),
                               ctypes.c_int32, ctypes.c_uint64,
                               ctypes.c_uint64]
    # HA / replication / chaos server ABI (ps/ha.py ReplicationManager)
    lib.pss_set_replication.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                        ctypes.c_int64]
    lib.pss_oplog_next.restype = ctypes.c_int64
    lib.pss_oplog_next.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.pss_staged_len.restype = ctypes.c_uint64
    lib.pss_staged_len.argtypes = [ctypes.c_void_p]
    lib.pss_staged_ptr.restype = ctypes.c_void_p
    lib.pss_staged_ptr.argtypes = [ctypes.c_void_p]
    for fn in ("pss_oplog_seq", "pss_oplog_pending", "pss_oplog_dropped",
               "pss_catalog_count", "pss_epoch", "pss_applied_seq"):
        getattr(lib, fn).restype = ctypes.c_int64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib.pss_catalog_get.restype = ctypes.c_int64
    lib.pss_catalog_get.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.pss_pause_mutations.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pss_set_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    # serving-plane attach mode (paddle_tpu/serving; rebuild a stale .so
    # if these are missing — _rpc_lib raises through the AttributeError)
    lib.pss_set_read_only.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.pss_read_only.restype = ctypes.c_int
    lib.pss_read_only.argtypes = [ctypes.c_void_p]
    lib.pss_dense_version.restype = ctypes.c_int64
    lib.pss_dense_version.argtypes = [ctypes.c_void_p]
    lib.pss_arm_fault.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint32, ctypes.c_int64,
                                  ctypes.c_int64]


def _rpc_lib() -> ctypes.CDLL:
    lib = load_native()
    if lib is None:
        raise PreconditionNotMetError("native library unavailable (no toolchain)")
    if not getattr(lib, "_rpc_configured", False):
        try:
            _configure_rpc(lib)
        except AttributeError as e:
            raise PreconditionNotMetError(f"native library lacks ps-service symbols: {e}")
        lib._rpc_configured = True
    return lib


def rpc_available() -> bool:
    try:
        _rpc_lib()
        return True
    except PreconditionNotMetError:
        return False


class NativePsServer:
    """In-process native PS server (accept loop + handler threads live in
    C++). ``port=0`` binds an ephemeral port (read ``.port``)."""

    def __init__(self, port: int = 0, n_trainers: int = 1) -> None:
        self._lib = _rpc_lib()
        self._h = self._lib.pss_create(port, n_trainers)
        enforce(self._h is not None, f"failed to bind PS server port {port}")
        self.port = int(self._lib.pss_port(self._h))
        self._pause_mu = _sync.Lock()
        self._pause_depth = 0

    def stop(self) -> None:
        if self._h:
            self._lib.pss_stop(self._h)

    @property
    def stopped(self) -> bool:
        return self._h is None or bool(self._lib.pss_stopped(self._h))

    # -- HA / replication surface (ps/ha.py ReplicationManager) ----------

    def set_replication(self, enable: bool, cap_entries: int = 0) -> None:
        """Start/stop tapping mutating request frames into the oplog
        ring (bounded at ``cap_entries``; overflow drops the oldest and
        the shipper detects the seq gap → full snapshot resync)."""
        self._lib.pss_set_replication(self._h, 1 if enable else 0,
                                      int(cap_entries))

    def oplog_next(self, timeout_ms: int = 100):
        """Pop the next oplog entry (SINGLE consumer — the shipper
        thread). Returns ``(seq, frame_bytes)``, ``(-1, None)`` on
        timeout, ``(-2, None)`` once the server is stopping and the
        ring has drained."""
        seq = int(self._lib.pss_oplog_next(self._h, int(timeout_ms)))
        if seq < 0:
            return seq, None
        n = int(self._lib.pss_staged_len(self._h))
        buf = ctypes.create_string_buffer(n)
        ctypes.memmove(buf, self._lib.pss_staged_ptr(self._h), n)
        return seq, buf.raw

    def oplog_seq(self) -> int:
        return int(self._lib.pss_oplog_seq(self._h))

    def oplog_pending(self) -> int:
        return int(self._lib.pss_oplog_pending(self._h))

    def oplog_dropped(self) -> int:
        return int(self._lib.pss_oplog_dropped(self._h))

    def catalog(self):
        """Every create-table frame seen so far (replayed to a
        rejoining backup before the data snapshot)."""
        out = []
        for i in range(int(self._lib.pss_catalog_count(self._h))):
            n = int(self._lib.pss_catalog_get(self._h, i))
            if n < 0:
                continue
            buf = ctypes.create_string_buffer(n)
            ctypes.memmove(buf, self._lib.pss_staged_ptr(self._h), n)
            out.append(buf.raw)
        return out

    def pause_mutations(self, paused: bool) -> None:
        """Quiesce writers (they block, within their IO deadline) while
        a snapshot + seq rebase takes a consistent cut. Pause/resume
        pairs NEST (depth-counted): a job-checkpoint gate
        (io/job_checkpoint.py) overlapping a rejoin full-sync
        (ha.ReplicationManager._full_sync) must not have the inner
        pair's resume release the outer gate mid-capture."""
        with self._pause_mu:
            # validate BEFORE mutating: an unmatched resume must not
            # leave the counter at -1 (the next legitimate pause would
            # then "reach" depth 0 and never pause the C side — a
            # silently inconsistent checkpoint cut)
            enforce(paused or self._pause_depth > 0,
                    "pause_mutations(False) without a matching pause")
            self._pause_depth += 1 if paused else -1
            self._lib.pss_pause_mutations(
                self._h, 1 if self._pause_depth > 0 else 0)

    @property
    def epoch(self) -> int:
        return int(self._lib.pss_epoch(self._h))

    def set_epoch(self, epoch: int) -> None:
        self._lib.pss_set_epoch(self._h, int(epoch))

    @property
    def applied_seq(self) -> int:
        return int(self._lib.pss_applied_seq(self._h))

    # -- serving-plane attach mode (paddle_tpu/serving) ------------------

    def set_read_only(self, on: bool) -> None:
        """Serving-replica mode: direct training-plane mutations (push,
        geo, shrink, create-exports, bulk load) bounce with
        ``kErrReadOnly``; insert-on-miss pulls are downgraded to plain
        reads (missing rows read as zeros — the serving contract for
        out-of-population features). The replication/bootstrap plane
        (kReplicate, snapshot inserts, dense restore, creates) stays
        open — it is how this replica stays fresh."""
        self._lib.pss_set_read_only(self._h, 1 if on else 0)

    @property
    def read_only(self) -> bool:
        return bool(self._lib.pss_read_only(self._h))

    @property
    def dense_version(self) -> int:
        """Count of applied dense mutations (direct or replicated) —
        the serving replica's feed watcher triggers dense-tower
        refreshes off this counter instead of diffing table bytes."""
        return int(self._lib.pss_dense_version(self._h))

    def arm_fault(self, name: str, cmd: int = 0, after: int = 1,
                  param: int = 0) -> None:
        """Arm a server-side faultpoint (kill-shard / drop-frame /
        close-socket / delay-ms): fires once ``after`` matching requests
        (``cmd`` 0 = any) have been handled; delay-ms stays armed with
        ``param`` ms. The deterministic 'die mid-run' switch the chaos
        tests flip (csrc/ps_service.cc fault_action)."""
        self._lib.pss_arm_fault(self._h, name.encode(), int(cmd),
                                int(after), int(param))

    def close(self) -> None:
        if self._h:
            self._lib.pss_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _ServerConn:
    """One TCP connection (C++ PsConn) with the call/resp protocol,
    hardened like the brpc channel (brpc_ps_client.cc:24-45): connect
    and per-call IO deadlines from the FLAGS_pserver_* family, bounded
    retry with exponential backoff, and reconnect-on-reset (a transport
    failure leaves the framed stream undefined, so the socket is
    rebuilt, never reused). Retries give at-least-once semantics for
    non-idempotent commands (push, global_step) exactly as brpc's
    channel retry does; ``retries=0`` opts a call out (barrier)."""

    def __init__(self, lib: ctypes.CDLL, host: str, port: int,
                 io_timeout_flag: str = "pserver_timeout_ms",
                 max_retry_flag: str = "pserver_max_retry",
                 hello: Optional[Tuple[int, bytes]] = None) -> None:
        self._lib = lib
        self._host, self._port = host, port
        self.endpoint = f"{host}:{port}"
        self._h = None
        # QoS class: serve-path conns resolve their (shorter) IO deadline
        # and (smaller) attempt budget from different flags — both are
        # read live at (re)connect/call time like the train path always did
        self._io_flag = io_timeout_flag
        self._retry_flag = max_retry_flag
        # tenant binding, replayed after EVERY (re)connect: the binding
        # is per-SOCKET server-side, and a silently rebuilt socket would
        # otherwise come back on the operator plane (tenant 0) — a
        # transport blip must never widen a tenant's blast radius.
        # Passing ``hello`` at construction binds the very first socket
        # too (tenant-scoped clients hand it through conn_kw, so
        # failover/reshard replacement conns inherit the binding).
        self._hello: Optional[Tuple[int, bytes]] = (
            (int(hello[0]), bytes(hello[1])) if hello else None)
        # serializes the whole call/close/reconnect/set_timeout sequence:
        # the C++ mutex only protects a single psc_call, but reconnect
        # DELETES the PsConn — without this lock a trainer-thread retry
        # could free the handle under the Communicator's in-flight push
        self._mu = _sync.RLock()
        self._connect()

    def _connect(self) -> None:
        self._h = self._lib.psc_connect2(
            self._host.encode(), self._port,
            int(flag("pserver_connect_timeout_ms")),
            int(flag(self._io_flag)))
        if not self._h:
            raise PsTransportError(
                f"cannot connect to PS server {self._host}:{self._port} "
                f"(connect timeout {flag('pserver_connect_timeout_ms')} ms)")
        if self._hello is not None:
            tenant, token = self._hello
            ptrs = (ctypes.c_void_p * 1)()
            lens = (ctypes.c_uint64 * 1)()
            nparts = 0
            if token:
                ptrs[0] = ctypes.cast(ctypes.c_char_p(token),
                                      ctypes.c_void_p)
                lens[0] = len(token)
                nparts = 1
            st, _ = self._call_once(_TENANT_HELLO, 0, tenant, 0,
                                    ptrs, lens, nparts, None, False)
            if st < 0:
                self.close()
                raise WrongTenantError(
                    f"tenant {tenant} hello refused by "
                    f"{self._host}:{self._port} on reconnect "
                    f"(status {st})")

    def close(self) -> None:
        with self._mu:
            if self._h:
                self._lib.psc_close(self._h)
                self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _call_once(self, cmd, table_id, n, aux, parts, lens, nparts,
                   timeout_ms, view):
        # the sampled span open on THIS thread rides the frame header's
        # fixed context field; (0, 0) — one module-flag check — otherwise
        trace_id, span_id = _trace.wire_context()
        status = int(self._lib.psc_callv2(
            self._h, cmd, table_id, n, aux, nparts, parts, lens,
            -1 if timeout_ms is None else timeout_ms, trace_id, span_id))
        if status <= -1000:
            # undefined stream state: drop the socket before any retry
            self.close()
            kind = "timed out" if status == -1001 else "reset/refused"
            raise PsTransportError(
                f"PS transport to {self._host}:{self._port} {kind} "
                f"(cmd {cmd})")
        rlen = int(self._lib.psc_resp_len(self._h))
        if span_id:  # traced: attach wire bytes to the client span
            sp = _trace.current_span()
            if sp is not None:
                sp.add_bytes(tx=_REQ_HEADER_BYTES
                             + sum(lens[i] for i in range(nparts)),
                             rx=_RESP_HEADER_BYTES + rlen)
        if not rlen:
            return status, _EMPTY_RESP
        if view:
            # zero-copy view over the calling thread's native response
            # buffer — valid ONLY until this thread's next call on any
            # connection (thread-local storage); consumers scatter it
            # into their output arrays before returning
            ptr = self._lib.psc_resp_ptr(self._h)
            return status, np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
                shape=(rlen,))
        resp = ctypes.create_string_buffer(rlen)
        self._lib.psc_resp_copy(self._h, resp)
        return status, resp.raw

    def call(self, cmd: int, table_id: int = 0, n: int = 0, aux: int = 0,
             payload: Union[bytes, np.ndarray, Sequence[np.ndarray],
                            None] = None,
             retries: Optional[int] = None,
             timeout_ms: Optional[int] = None,
             view: bool = False):
        """``payload``: bytes, one ndarray, or a sequence of C-contiguous
        ndarrays sent scatter-gather (concatenated on the wire with NO
        client-side re-materialization — the arrays themselves are the
        frame). ``retries``: attempts beyond the first (default
        FLAGS_pserver_max_retry - 1). ``timeout_ms``: whole-call deadline
        override for this call (long table-scale commands, barrier);
        None = FLAGS_pserver_timeout_ms, 0 = no deadline. ``view``: the
        response is returned as a uint8 ndarray view over this THREAD's
        reused native buffer — zero-copy, but only valid until the same
        thread's next call; pass False (bytes copy) to retain it."""
        if payload is None:
            parts: Tuple = ()
        elif isinstance(payload, (bytes, bytearray, np.ndarray)):
            parts = (payload,)
        else:
            parts = tuple(payload)
        nparts = len(parts)
        ptrs = (ctypes.c_void_p * max(nparts, 1))()
        lens = (ctypes.c_uint64 * max(nparts, 1))()
        keep = []  # pins bytes parts for the whole call (incl. retries)
        for i, part in enumerate(parts):
            if isinstance(part, np.ndarray):
                # the frame is read linearly from the base pointer — a
                # strided view would silently ship the wrong elements
                enforce(part.flags["C_CONTIGUOUS"],
                        "scatter-gather payload parts must be "
                        "C-contiguous (use np.ascontiguousarray)")
                ptrs[i] = part.ctypes.data
                lens[i] = part.nbytes
            else:
                b = bytes(part)
                keep.append(b)
                ptrs[i] = ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p)
                lens[i] = len(b)
        if retries is None:
            retries = max(0, int(flag(self._retry_flag)) - 1)
        backoff = int(flag("pserver_retry_backoff_ms")) / 1000.0
        last: Optional[Exception] = None
        for attempt in range(retries + 1):
            try:
                # chaos site: delay-ms / drop-frame / close-socket land
                # here, INSIDE the retry loop, so an injected fault walks
                # the exact transport-recovery path a real one would
                faultpoint("rpc.call", cmd=cmd, close=self.close)
                with self._mu:  # one caller owns connect/call/close at a time
                    if self._h is None:
                        self._connect()
                    return self._call_once(cmd, table_id, n, aux, ptrs, lens,
                                           nparts, timeout_ms, view)
            except PsTransportError as e:
                last = e
                if attempt < retries:
                    # the re-send is a REPLAY of the same logical op —
                    # the open span (if any) records it, so the merged
                    # timeline shows retried RPCs, not phantom extras
                    _trace.mark_retried()
                    time.sleep(backoff * (2 ** attempt))
        raise PsTransportError(
            f"PS server {self._host}:{self._port} unreachable after "
            f"{retries + 1} attempt(s): {last}")

    def check(self, cmd: int, table_id: int = 0, n: int = 0, aux: int = 0,
              payload=None, **kw):
        status, resp = self.call(cmd, table_id, n, aux, payload, **kw)
        if status == -2:
            raise NotFoundError(f"table {table_id} not created on server")
        if status == -7:
            raise PreconditionNotMetError(
                f"PS server {self.endpoint} is READ-ONLY (serving "
                f"replica) — training-plane command {cmd} refused")
        if status == -8:
            raise WrongShardError(
                f"PS server {self.endpoint} no longer owns a key in "
                f"this request (cmd {cmd}, table {table_id}) — the "
                "shard topology moved (live reshard); re-resolve the "
                "routing table and replay")
        if status == -9:
            raise WrongTenantError(
                f"PS server {self.endpoint} refused cmd {cmd} on table "
                f"{table_id}: outside this connection's tenant namespace "
                "(or unknown tenant / bad hello token / operator-plane "
                "command from a tenant connection)")
        if status == -10:
            raise QuotaExceededError(
                f"PS server {self.endpoint} refused row-creating cmd "
                f"{cmd} on table {table_id}: tenant row/SSD-byte quota "
                "exhausted — shrink tables or raise the quota; other "
                "tenants' rows are never evicted to make room")
        if status == -11:
            # the shed response carries the server's backoff hint
            # resp may be bytes or a uint8 ndarray view — len() works
            # for both; a payload-less shed falls back to 1 ms
            retry_ms = (struct.unpack("<q", bytes(resp[:8]))[0]
                        if len(resp) >= 8 else 1)
            raise ThrottledError(
                f"PS server {self.endpoint} shed cmd {cmd}: tenant "
                f"request budget dry, retry after {retry_ms} ms",
                retry_after_ms=retry_ms)
        enforce(status >= 0, f"PS command {cmd} failed with status {status}")
        return status, resp

    # -- tenancy (ps/tenancy.py drives these; docs/OPERATIONS.md §20) ----

    def tenant_hello(self, tenant: int, token: bytes) -> None:
        """Bind THIS connection to ``tenant`` (1..255). Every later
        frame on the socket is admitted against that tenant's namespace,
        token bucket and quotas; a rebind is refused server-side. The
        binding is recorded and REPLAYED after any reconnect, so a
        transport blip can't drop the socket back onto the operator
        plane."""
        token = bytes(token)
        self.check(_TENANT_HELLO, 0, int(tenant), 0, token, retries=0)
        self._hello = (int(tenant), token)

    def tenant_config(self, tenant: int, *, pclass: int = 1,
                      rate: float = 0.0, burst: float = 0.0,
                      max_rows: int = 0, max_ssd_bytes: int = 0,
                      token: bytes = b"") -> None:
        """Install/update a tenant on this server (operator plane only).
        ``rate``/``burst`` meter the token bucket in cost units (1 per
        frame + 1 per key); 0 = unmetered. ``pclass`` 0 = serve (queues
        briefly when dry), >= 1 = batch (sheds immediately)."""
        token = bytes(token)
        payload = struct.pack("<IiddqqII", int(tenant), int(pclass),
                              float(rate), float(burst), int(max_rows),
                              int(max_ssd_bytes), len(token), 0) + token
        self.check(_TENANT_CONFIG, 0, 1, 0, payload)

    def tenant_usage(self, tenant: int) -> Dict[str, float]:
        """Read a tenant's billing meter: resident rows, SSD bytes, shed
        and quota-refusal counters, current bucket tokens, class."""
        _, resp = self.check(_TENANT_CONFIG, int(tenant), 0, 0, None)
        rows, ssd_bytes, throttled, refused, tokens, pclass = \
            struct.unpack("<qqqqdq", bytes(resp[:48]))
        return {"rows": rows, "ssd_bytes": ssd_bytes,
                "throttled": throttled, "quota_refused": refused,
                "tokens": tokens, "pclass": pclass}


class _ColdBounce(Exception):
    """Internal to RpcPsClient.load_cold: carries the UNSENT remainder
    of a shard's slice when a chunk bounces kErrWrongShard mid-load
    (earlier chunks on that shard already landed — exactly-once replay
    must exclude them)."""

    def __init__(self, pending):
        super().__init__("load_cold chunk bounced")
        self.pending = pending


def make_conn(endpoint: str) -> "_ServerConn":
    """One hardened connection to ``endpoint`` ("host:port") — the
    replication shipper's channel to a backup (ps/ha.py)."""
    host, port = endpoint.rsplit(":", 1)
    return _ServerConn(_rpc_lib(), host, int(port))


def send_replicate(conn: "_ServerConn", frame: bytes, seq: int,
                   epoch: int, retries: Optional[int] = None) -> int:
    """Ship one oplog entry (``frame`` = [ReqHeader][payload] as produced
    by ``NativePsServer.oplog_next``) to a backup as a kReplicate
    command. Returns the server's status: the acked seq, or the negative
    error (-5 stale epoch = we are fenced; -6 seq gap = backup needs a
    full snapshot resync). The chaos site ``repl.ship`` can corrupt the
    epoch stamp to exercise the fencing path deterministically."""
    spec = faultpoint("repl.ship", close=conn.close)
    if spec is not None and spec.action == "corrupt-epoch":
        epoch = spec.param
    status, _ = conn.call(_REPLICATE, 0, n=int(seq), aux=int(epoch),
                          payload=frame, retries=retries)
    return int(status)


def _sparse_config_payload(cfg: TableConfig) -> bytes:
    ip, fp = table_native_params(cfg.shard_num, cfg.accessor,
                                 cfg.accessor_config or AccessorConfig(),
                                 cfg.seed)
    return ip.tobytes() + fp.tobytes()


def _quant_push_int8(grad: np.ndarray, block: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Block-wise symmetric int8 over the gradient block (the PR 3
    comm_fusion scheme, numpy form): per-block fp32 absmax scales,
    blocks tile a ROW (nblk = ceil(gd/block); the last block may be
    ragged — the zero pad never raises a block's absmax). Returns
    (q int8 [n, gd], scales f32 [n, nblk])."""
    n, gd = grad.shape
    nblk = -(-gd // block)
    pad = nblk * block - gd
    g = np.pad(grad, ((0, 0), (0, pad))) if pad else grad
    gb = g.reshape(n, nblk, block)
    amax = np.max(np.abs(gb), axis=2)
    scales = (amax / np.float32(127.0)).astype(np.float32)
    inv = np.where(scales > 0, np.float32(1.0) / scales,
                   np.float32(0.0)).astype(np.float32)
    q = np.clip(np.rint(gb * inv[:, :, None]), -127, 127).astype(np.int8)
    return np.ascontiguousarray(q.reshape(n, nblk * block)[:, :gd]), scales


def _dequant_push_int8(q: np.ndarray, scales: np.ndarray, block: int
                       ) -> np.ndarray:
    """Inverse of :func:`_quant_push_int8` — float32(q) * scale, the
    IDENTICAL f32 multiply csrc decode_push_rows applies, so the
    client's error-feedback residual is computed against exactly the
    values the server (and every replaying backup) adds to the rows."""
    n, gd = q.shape
    nblk = scales.shape[1]
    pad = nblk * block - gd
    qq = np.pad(q, ((0, 0), (0, pad))) if pad else q
    out = qq.reshape(n, nblk, block).astype(np.float32) * scales[:, :, None]
    return out.reshape(n, nblk * block)[:, :gd]


class RpcPsClient(PSClient):
    """PSClient over N TCP servers. Sparse keys route by
    ``key % num_servers``; dense tables split into contiguous
    even slices per server (DenseDimPerShard semantics).

    Multi-shard commands fan out CONCURRENTLY (one worker per server
    connection, one in-flight call per connection, sub-responses
    scattered back by routing index) unless ``FLAGS_ps_rpc_parallel``
    is off — per-call wall-clock is max over shards instead of the
    serial loop's sum. The per-connection mutex still serializes
    overlapping operations from different trainer threads on the same
    connection, so interleaved pull/push streams stay frame-correct.
    """

    def __init__(self, endpoints: Sequence[str],
                 router: Optional[object] = None,
                 qos: str = "train",
                 tenant: Optional[Tuple[int, bytes]] = None) -> None:
        lib = _rpc_lib()
        self._lib = lib
        enforce(qos in ("train", "serve"),
                f"RpcPsClient qos must be 'train' or 'serve', got {qos!r}")
        #: QoS class. "serve" = the read-mostly online-serving path:
        #: short per-call deadline (FLAGS_pserver_serve_timeout_ms), no
        #: transport retries by default (the frontend's admission control
        #: owns retry policy), and — when a router is attached — its OWN
        #: breaker thresholds/instances, so serving reads can neither
        #: trip the training client's breaker nor wedge behind long
        #: training calls (docs/OPERATIONS.md §12).
        self.qos = qos
        conn_kw = {}
        if qos == "serve":
            conn_kw = dict(io_timeout_flag="pserver_serve_timeout_ms",
                           max_retry_flag="pserver_serve_max_retry")
        if tenant is not None:
            # tenant-scoped client (ps/tenancy.py TenantClient): EVERY
            # connection this client ever builds — including failover
            # and reshard replacements — binds to the tenant before the
            # first data frame, so no code path can leak an
            # operator-plane socket into tenant traffic
            conn_kw = dict(conn_kw, hello=(int(tenant[0]),
                                           bytes(tenant[1])))
        self._conn_kw = conn_kw
        self._conns: List[_ServerConn] = []
        for ep in endpoints:
            host, port = ep.rsplit(":", 1)
            self._conns.append(_ServerConn(lib, host, int(port), **conn_kw))
        self._sparse_dims: Dict[int, Tuple[int, int, int]] = {}  # pull/push/full
        self._sparse_cfgs: Dict[int, TableConfig] = {}
        self._dense_dims: Dict[int, int] = {}
        self._geo_dims: Dict[int, int] = {}
        self._wire_f16: Dict[int, bool] = {}  # table → fp16 pull values
        # table → (push wire dtype, int8 block, error feedback on)
        self._push_wire: Dict[int, Tuple[str, int, bool]] = {}
        # error-feedback residual store: table → {key → f32 grad-block
        # residual}. Folded into the next push of that key, drained
        # over the fp32 wire at drain_push_residuals() (quiesce/
        # checkpoint cuts — no training signal lives here across a cut)
        self._push_ef: Dict[int, Dict[int, np.ndarray]] = {}
        self._ef_mu = _sync.Lock()  # LOCK: _ef_mu (leaf — see header)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_mu = _sync.Lock()
        #: HA router (ps/ha.py HARouter): resolves the epoch-stamped
        #: routing table, gates endpoints through the circuit breaker,
        #: and answers "who replaced this dead primary?". None = the
        #: static single-replica topology (behavior unchanged).
        self._router = router
        self._conns_mu = _sync.Lock()  # serializes failover conn swaps
        # live resharding (ps/reshard.py): a grow replaces the fan-out
        # pool with a wider one; pools that may still carry in-flight
        # fan-outs retire here and shut down at close()
        self._retired_pools: List[ThreadPoolExecutor] = []
        # per-op RPC counts, REGISTRY-BACKED (obs/registry.py): one
        # count per client op regardless of shard fan-out, under the
        # job-wide family ``ps_client_ops`` labeled by op and a
        # per-process client tag. ``op_counts``/``reset_op_counts``
        # stay the exact per-client accessors the hot-tier 0-RPC gate
        # and tools/sparse_hot_bench.py always read (CounterGroup keeps
        # a lock-free local mirror), so PR 6/7 tests pass unchanged.
        self._client_tag = f"{qos}{next(_CLIENT_SEQ)}"
        self._ops = CounterGroup("ps_client_ops", _OP_NAMES,
                                 max_series=1024, client=self._client_tag)
        self._op_base: Dict[str, int] = {op: 0 for op in _OP_NAMES}
        self._count_mu = _sync.Lock()
        # per-table wire/density handles, bound at table-create time
        # (the cold path — the metric-in-hot-path lint rule's contract)
        self._tbl_obs: Dict[int, Dict[str, object]] = {}

    def _op_count(self, op: str) -> None:
        with self._count_mu:
            self._ops[op] += 1

    @property
    def op_counts(self) -> Counter:
        """Per-op counts since the last :meth:`reset_op_counts` (thin
        shim over the registry-backed handles; zero entries omitted)."""
        with self._count_mu:
            return Counter({op: self._ops[op] - self._op_base[op]
                            for op in _OP_NAMES
                            if self._ops[op] - self._op_base[op]})

    def reset_op_counts(self) -> Dict[str, int]:
        """Snapshot-and-zero: returns the counts accumulated since the
        last reset (delta reads for the bench / 0-RPC assertions). The
        registry totals keep running — only this client's delta window
        resets."""
        with self._count_mu:
            out = {}
            for op in _OP_NAMES:
                d = self._ops[op] - self._op_base[op]
                if d:
                    out[op] = d
                self._op_base[op] = self._ops[op]
        return out

    def _bind_table_obs(self, table_id: int) -> Optional[Dict[str, object]]:
        """Pre-bind this table's wire-accounting handles (per-table
        bytes/rows per direction + observed-density gauges — the
        measured-sparsity feed for ROADMAP item 3 auto-placement).
        Called from the create path only; the hot path does one dict
        lookup. With FLAGS_obs_metrics=0 nothing is bound at all: the
        accounting blocks (including their np.count_nonzero density
        scans — the costliest part of the instrumentation) must
        short-circuit on the .get() miss, not feed null handles."""
        if not _obs_registry.metrics_enabled():
            self._tbl_obs.pop(table_id, None)
            return None
        # lazy: distributed/__init__ pulls jax-heavy modules and
        # distributed.fleet imports back into ps.* (cycle) — this is
        # the cold create path, so the import cost lands exactly once
        from ..distributed.placement import DensitySeries
        t = str(table_id)
        reg = _obs_registry.REGISTRY

        def window(direction: str) -> DensitySeries:
            # the windowed density series (EWMA via the Gauge's alpha-
            # 0.2 view + min/max over the last W samples) the placement
            # pass reads instead of one batch's last-write sample
            return DensitySeries(
                gauge=reg.gauge("ps_client_density", table=t,
                                dir=direction),
                gmin=reg.gauge("ps_client_density_min", table=t,
                               dir=direction),
                gmax=reg.gauge("ps_client_density_max", table=t,
                               dir=direction))

        m = {
            "pull_bytes": reg.counter("ps_client_wire_bytes",
                                      table=t, dir="pull"),
            "push_bytes": reg.counter("ps_client_wire_bytes",
                                      table=t, dir="push"),
            "pull_rows": reg.counter("ps_client_wire_rows",
                                     table=t, dir="pull"),
            "push_rows": reg.counter("ps_client_wire_rows",
                                     table=t, dir="push"),
            "pull_window": window("pull"),
            "push_window": window("push"),
        }
        self._tbl_obs[table_id] = m
        return m

    def density_series(self, table_id: int, direction: str = "push"):
        """The windowed density series for one (table, direction) —
        the measured-sparsity feed distributed/placement.py consumes.
        None when metrics are compiled out (FLAGS_obs_metrics=0) or the
        table was not created via this client."""
        m = self._tbl_obs.get(table_id)
        return None if m is None else m.get(f"{direction}_window")

    @property
    def num_servers(self) -> int:
        return len(self._conns)

    # -- HA failover (router-gated; no-ops when router is None) -----------

    def _swap_conn(self, s: int, endpoint: str) -> None:
        """Point shard ``s`` at ``endpoint`` (promoted backup). Another
        thread may have swapped already — endpoint equality makes the
        swap idempotent; the loser's stale conn is closed. The TCP
        connect happens OUTSIDE _conns_mu: _shard_op takes that lock on
        the data hot path, and holding it through a connect deadline
        would stall every healthy shard's ops behind one failover
        (blocking-under-lock lint rule)."""
        with self._conns_mu:
            if s >= len(self._conns) or \
                    self._conns[s].endpoint == endpoint:
                return
        host, port = endpoint.rsplit(":", 1)
        fresh = _ServerConn(self._lib, host, int(port), **self._conn_kw)
        with self._conns_mu:
            if s >= len(self._conns) or \
                    self._conns[s].endpoint == endpoint:
                stale = fresh       # raced: another swap (or a shrink) won
            else:
                stale, self._conns[s] = self._conns[s], fresh
        stale.close()

    def refresh_routing(self) -> bool:
        """Re-resolve every shard's endpoint AND the shard COUNT from
        the router's current routing table; returns True if the
        connection set changed. Callers holding failed futures
        (communicator pull prefetch) refresh and replay; a
        :class:`~paddle_tpu.core.enforce.WrongShardError` bounce
        (live reshard moved a key class) lands here too — the client
        rebuilds its topology and the op replays the bounced keys.
        Without a router this is a no-op."""
        if self._router is None:
            return False
        _, eps = self._router.routing()
        if not eps:
            return False
        with self._conns_mu:
            if [c.endpoint for c in self._conns] == list(eps):
                return False
            have = {c.endpoint for c in self._conns}
        # build the NEW connections OUTSIDE _conns_mu: every _shard_op
        # takes that lock on the data hot path, and a TCP connect here
        # can block up to the connect deadline per endpoint — holding
        # the lock through it would stall all concurrent ops for the
        # whole flip. On a partial failure the already-built strays
        # close instead of leaking.
        built: Dict[str, _ServerConn] = {}
        try:
            for ep in eps:
                if ep not in have:
                    host, port = ep.rsplit(":", 1)
                    built[ep] = _ServerConn(self._lib, host, int(port),
                                            **self._conn_kw)
        except BaseException:
            for c in built.values():
                c.close()
            raise
        stale: List[_ServerConn] = []
        with self._conns_mu:
            old = self._conns
            conns: List[_ServerConn] = []
            for ep in eps:
                cur = next((c for c in old if c.endpoint == ep), None)
                if cur is not None:
                    conns.append(cur)  # keep live conns across the flip
                elif ep in built:
                    conns.append(built.pop(ep))
                else:
                    # endpoint appeared between snapshot and build (a
                    # concurrent refresh raced us): rare — pay the
                    # in-lock connect only for this stray
                    host, port = ep.rsplit(":", 1)
                    conns.append(_ServerConn(self._lib, host, int(port),
                                             **self._conn_kw))  # graftlint: lock-ok rare stray from a raced refresh; rebuilding outside would just re-race
            stale = [c for c in old if c not in conns]
            self._conns = conns
        for c in built.values():  # built for an endpoint a concurrent
            c.close()             # refresh already covered — unused
        for c in stale:
            c.close()
        # widen the fan-out pool if the topology grew; the old pool may
        # carry in-flight fan-outs, so it retires instead of shutting
        # down under them (close() drains the retirees)
        with self._pool_mu:
            if self._pool is not None and \
                    len(self._conns) > self._pool._max_workers:
                self._retired_pools.append(self._pool)
                self._pool = None
        return True

    def _shard_op(self, s: int, fn):
        """Run ``fn(conn)`` against shard ``s``'s current server. With a
        router: breaker-gate the endpoint (an OPEN breaker fails fast
        instead of paying the full timeout·retries again), and on a
        TRANSPORT death (PsTransportError — the connection is gone, not
        a server-side rejection) ask the router for the promoted
        replacement (it watches the epoch-stamped routing table) and
        replay ``fn`` there. Application errors (NotFoundError, enforce
        failures on negative statuses) pass straight through and never
        touch the breaker — a healthy server's rejection must not open
        its breaker or trigger a failover wait."""
        with self._conns_mu:
            if s >= len(self._conns):
                # a live reshard SHRANK the topology under this op: the
                # shard index no longer exists — same recovery as a
                # server-side kErrWrongShard bounce (re-resolve+replay)
                raise WrongShardError(
                    f"shard {s} is beyond the current topology "
                    f"({len(self._conns)} servers) — stale routing")
            c = self._conns[s]
        r = self._router
        if r is None:
            return fn(c)
        ep = c.endpoint
        if not r.allow(ep):
            # breaker open: don't burn a timeout — jump straight to
            # re-resolution (the coordinator may have promoted already)
            self._raise_if_shrunk(s, r)
            new_ep = r.failover(s, ep)
            if new_ep is None or new_ep == ep:
                raise PsTransportError(
                    f"PS shard {s} endpoint {ep} circuit breaker open "
                    f"and no promoted replacement published")
            self._swap_conn(s, new_ep)
            c = self._conns[s]
            ep = c.endpoint
        try:
            out = fn(c)
        except PsTransportError as e:
            r.record(ep, ok=False)
            # tail note (no dump): the transport death + replay land in
            # the flight recorder's event ring so a later bundle shows
            # the failing requests leading up to whatever triggered it
            rec = _flightrec.installed()
            if rec is not None:
                rec.note("transport_error", shard=s, endpoint=ep,
                         error=f"{type(e).__name__}: {e}")
            # a shard index the routing table no longer carries is a
            # SHRINK, not a dead primary: convert to the misroute path
            # now instead of waiting the failover budget for a
            # promotion that can never come
            self._raise_if_shrunk(s, r)
            new_ep = r.failover(s, ep)
            if new_ep is None or new_ep == ep:
                raise
            self._swap_conn(s, new_ep)
            # the promoted-backup REPLAY of the same logical op: the
            # open span keeps its id (no orphan/duplicate spans in the
            # merged trace) and is marked retried
            _trace.mark_retried()
            out = fn(self._conns[s])
            r.record(new_ep, ok=True)
            return out
        except BaseException:
            # an application-level rejection means the server RESPONDED:
            # the transport is alive — record success so a HALF_OPEN
            # probe releases (otherwise the probe slot leaks and the
            # breaker locks the healthy endpoint out forever)
            r.record(ep, ok=True)
            raise
        r.record(ep, ok=True)
        return out

    @staticmethod
    def _raise_if_shrunk(s: int, router) -> None:
        _, eps = router.routing()
        if eps and s >= len(eps):
            raise WrongShardError(
                f"shard {s} left the topology ({len(eps)} shards "
                "published) — stale routing")

    def _direct(self, server: int, fn):
        """Server-TARGETED call: no breaker, no failover replay. For
        introspection (repl_state, epoch, dense snapshots) the answer
        must come from the addressed server or fail — a transparent
        replay on a promoted replacement would report the wrong
        server's state as if it were the dead one's."""
        return fn(self._conns[server])

    def _task(self, s: int, fn):
        """Zero-arg fan-out task bound to shard index (NOT to a conn
        object — failover may swap the conn between submit and run)."""
        return lambda: self._shard_op(s, fn)

    # -- live-reshard misroute replay (ps/reshard.py) ---------------------

    _REROUTE_HOPS = 8

    def _bounce_guard(self, s: int, fn, misrouted: List, sel, n_keys: int):
        """Fan-out task wrapper for keyed ops: a kErrWrongShard bounce
        (or a stale shard index after a shrink) records WHICH key
        positions bounced instead of failing the op — the server
        rejected the frame whole, so the op re-resolves the topology
        and replays exactly those keys, each applied exactly once.
        Without a router there is nothing to re-resolve; the error
        propagates. ``misrouted`` appends are GIL-atomic (list.append
        from fan-out workers)."""
        def run():
            try:
                self._shard_op(s, fn)
            except WrongShardError:
                if self._router is None:
                    raise
                misrouted.append(np.arange(n_keys, dtype=np.int64)
                                 if sel is None else sel)
        return run

    def _reroute_backoff(self, hops: int) -> None:
        """Between misroute replays: re-resolve the routing table, and
        when it has not changed yet (a cutover installs the ownership
        fence a moment before it publishes the flipped routing doc)
        back off briefly — the publish is milliseconds away, not a
        failover wait. Raises once the hop budget is spent: a topology
        that stays stale means the reshard wedged mid-cutover."""
        enforce(hops < self._REROUTE_HOPS,
                f"misrouted PS op: topology still stale after {hops} "
                "re-resolves (reshard wedged mid-cutover?)",
                WrongShardError)
        if not self.refresh_routing() and hops > 0:
            time.sleep(min(0.002 * (2 ** hops), 0.1))

    def close(self) -> None:
        with self._pool_mu:
            pool, self._pool = self._pool, None
            retired, self._retired_pools = self._retired_pools, []
        if pool is not None:
            pool.shutdown(wait=True)
        for p in retired:
            p.shutdown(wait=True)
        for c in self._conns:
            c.close()

    # -- concurrent shard fan-out ----------------------------------------

    def _executor(self) -> ThreadPoolExecutor:
        with self._pool_mu:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=len(self._conns),
                    thread_name_prefix="ps-rpc")
            return self._pool

    def _fanout(self, tasks: List):
        """Run one zero-arg task per participating server. Parallel when
        FLAGS_ps_rpc_parallel and more than one task; the serial path
        preserves server order exactly. Always drains every task before
        returning/raising (no call may still be in flight when the op
        ends — barrier semantics depend on it); the first exception
        propagates. Returns results in task order."""
        if len(tasks) <= 1 or not flag("ps_rpc_parallel"):
            return [t() for t in tasks]
        # trace-context propagation: the op's sampled span lives in the
        # CALLER thread's TLS; fan-out workers re-enter it so their
        # wire frames carry the context and their retries mark it
        cur = _trace.current_span()
        if cur is not None:
            tasks = [
                (lambda t=t: _run_with_span(cur, t)) for t in tasks]
        futs = [self._executor().submit(t) for t in tasks]
        results, first_err = [], None
        for f in futs:
            try:
                results.append(f.result())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_err is None:
                    first_err = e
                results.append(None)
        if first_err is not None:
            raise first_err
        return results

    # -- table lifecycle --------------------------------------------------

    def create_sparse_table(self, table_id: int, config: Optional[TableConfig] = None) -> None:
        cfg = config or TableConfig(table_id=table_id)
        wire = getattr(cfg, "pull_wire_dtype", "fp32")
        enforce(wire in ("fp32", "fp16"),
                f"TableConfig.pull_wire_dtype must be 'fp32' or 'fp16', "
                f"got {wire!r}")
        push_wire = getattr(cfg, "push_wire_dtype", "fp32")
        enforce(push_wire in ("fp32", "fp16", "int8"),
                f"TableConfig.push_wire_dtype must be 'fp32', 'fp16' or "
                f"'int8', got {push_wire!r}")
        block = int(getattr(cfg, "push_wire_block", 128))
        enforce(1 <= block <= 0xFFFF,
                f"TableConfig.push_wire_block must be in [1, 65535], "
                f"got {block}")
        ssd_vals = getattr(cfg, "ssd_value_dtype", "fp32")
        enforce(ssd_vals in ("fp32", "fp16"),
                f"TableConfig.ssd_value_dtype must be 'fp32' or 'fp16', "
                f"got {ssd_vals!r}")
        self._sparse_cfgs[table_id] = cfg
        self._wire_f16[table_id] = wire == "fp16"
        self._push_wire[table_id] = (
            push_wire, block, bool(getattr(cfg, "push_error_feedback", True)))
        base = _sparse_config_payload(cfg)
        if cfg.storage == "ssd":
            enforce(cfg.ssd_path is not None,
                    "TableConfig.storage='ssd' requires ssd_path")

        def mk(idx, c):
            payload = base
            if cfg.storage == "ssd":
                # each (table, server) pair owns its own disk directory;
                # one job path can host many tables and same-host servers.
                # storage low byte = 1 (ssd); bit 8 = fp16 value columns
                # in the cold-tier records (ssd_value_dtype)
                storage = 1 | (0x100 if ssd_vals == "fp16" else 0)
                path = f"{cfg.ssd_path}/table{table_id}/server{idx}".encode()
                payload = (base + np.asarray([storage], np.int32).tobytes()
                           + np.asarray([len(path)], np.uint32).tobytes()
                           + path)
            # parallel across servers: an SSD create replays the whole
            # cold-tier log, so a cluster restart pays max(server logs)
            _, resp = c.check(_CREATE_SPARSE, table_id, payload=payload,
                              timeout_ms=_long_ms())
            dims = np.frombuffer(resp, np.int32)
            return int(dims[0]), int(dims[1]), int(dims[2])

        all_dims = self._fanout([self._task(i, lambda c, idx=i: mk(idx, c))
                                 for i in range(self.num_servers)])
        enforce(len(set(all_dims)) == 1,
                f"servers disagree on table {table_id} dims: {all_dims} "
                "(mismatched accessor configs across trainers?)")
        self._sparse_dims[table_id] = all_dims[0]
        self._bind_table_obs(table_id)

    # -- SSD-tier management (no-ops on RAM-only tables) ------------------

    def spill(self, table_id: int, hot_budget: int) -> int:
        """Per-server spill to at most hot_budget hot rows each; returns
        total rows spilled."""
        return sum(self._fanout(
            [self._task(s, lambda c: int(
                c.check(_SPILL, table_id, n=int(hot_budget),
                        timeout_ms=_long_ms(), retries=0)[0]))
             for s in range(self.num_servers)]))

    def table_stats(self, table_id: int) -> Dict[str, int]:
        def one(c):
            _, resp = c.check(_STATS, table_id)
            s3 = np.frombuffer(resp, np.int64)
            return int(s3[0]), int(s3[1]), int(s3[2])

        stats = self._fanout([self._task(s, one)
                              for s in range(self.num_servers)])
        return {"hot_rows": sum(s[0] for s in stats),
                "cold_rows": sum(s[1] for s in stats),
                "disk_bytes": sum(s[2] for s in stats)}

    def compact(self, table_id: int) -> int:
        # default retries (unlike shrink/spill): a compaction that is
        # re-run after a deadline expiry just rewrites live records
        # again — idempotent, so at-least-once delivery is safe, and a
        # loaded host blowing the long-call deadline once shouldn't
        # fail the daily boundary
        return sum(self._fanout(
            [self._task(s, lambda c: int(
                c.check(_COMPACT, table_id, timeout_ms=_long_ms())[0]))
             for s in range(self.num_servers)]))

    def create_dense_table(self, table_id: int, dim: int, optimizer: str = "adam",
                           lr: float = 0.001) -> None:
        self._dense_dims[table_id] = dim
        self._bind_table_obs(table_id)
        for s in range(self.num_servers):
            shard_dim = len(self._dense_slice(dim, s))
            payload = (np.asarray([shard_dim, _DENSE_OPT_IDS[optimizer]], np.int32).tobytes()
                       + np.asarray([lr], np.float32).tobytes())
            self._shard_op(s, lambda c, pl=payload: c.check(
                _CREATE_DENSE, table_id, payload=pl))

    def create_geo_table(self, table_id: int, dim: int) -> None:
        self._geo_dims[table_id] = dim
        payload = np.asarray([dim], np.int32).tobytes()
        for s in range(self.num_servers):
            self._shard_op(s, lambda c: c.check(_CREATE_GEO, table_id,
                                                payload=payload))

    def _dense_slice(self, dim: int, server: int) -> range:
        per = (dim + self.num_servers - 1) // self.num_servers
        lo = min(per * server, dim)
        return range(lo, min(lo + per, dim))

    def _route(self, keys: np.ndarray) -> np.ndarray:
        return (keys % np.uint64(self.num_servers)).astype(np.int64)

    def _dims(self, table_id: int) -> Tuple[int, int, int]:
        try:
            return self._sparse_dims[table_id]
        except KeyError:
            raise NotFoundError(f"sparse table {table_id} not created via this client")

    # -- PSClient interface -----------------------------------------------

    def sparse_config(self, table_id: int) -> TableConfig:
        """The TableConfig this client created ``table_id`` with — the
        accessor metadata a full-row view (RemoteSparseTable) needs."""
        cfg = self._sparse_cfgs.get(table_id)
        enforce(cfg is not None,
                f"sparse table {table_id} not created via this client")
        return cfg

    def pull_sparse(self, table_id, keys, create=True, slots=None):
        # client-side CostProfiler scope (brpc_ps_client's
        # pserver_client_pull_sparse probe)
        self._op_count("pull_sparse")
        with RecordEvent("pserver_client_pull_sparse"):
            return self._pull_sparse(table_id, keys, create, slots)

    def _shard_sel(self, sv: np.ndarray):
        """(server, sel) for servers with work; ``sel`` is None when one
        server owns every key (skip the gather copy)."""
        out = []
        for s in range(self.num_servers):
            sel = np.flatnonzero(sv == s)
            if len(sel) == len(sv):
                out.append((s, None))
            elif len(sel):
                out.append((s, sel))
        return out

    def _pull_sparse(self, table_id, keys, create=True, slots=None,
                     _hops=0):
        keys = np.ascontiguousarray(keys, np.uint64)
        pull_dim = self._dims(table_id)[0]
        out = np.zeros((len(keys), pull_dim), np.float32)
        sv = self._route(keys)
        slots_arr = (np.ascontiguousarray(slots, np.int32) if slots is not None
                     else np.zeros(len(keys), np.int32))
        f16 = self._wire_f16.get(table_id, False)
        aux = (1 if create else 0) | (2 if f16 else 0)

        def one(c, sel):
            kp = keys if sel is None else keys[sel]
            sp = slots_arr if sel is None else slots_arr[sel]
            _, resp = c.check(_PULL_SPARSE, table_id, n=len(kp), aux=aux,
                              payload=(kp, sp), view=True)
            vals = (resp.view(np.float16).astype(np.float32) if f16
                    else resp.view(np.float32))
            # scatter before returning: `resp` is this worker thread's
            # reused native buffer (dead at its next call)
            if sel is None:
                out[:] = vals.reshape(len(kp), pull_dim)
            else:
                out[sel] = vals.reshape(len(kp), pull_dim)

        misrouted: List[np.ndarray] = []
        self._fanout([self._bounce_guard(s, lambda c, sel=sel: one(c, sel),
                                         misrouted, sel, len(keys))
                      for s, sel in self._shard_sel(sv)])
        if misrouted:
            self._reroute_backoff(_hops)
            idx = np.concatenate(misrouted)
            out[idx] = self._pull_sparse(table_id, keys[idx], create,
                                         slots_arr[idx], _hops=_hops + 1)
        m = self._tbl_obs.get(table_id) if _hops == 0 else None
        if m is not None:
            m["pull_rows"].add(len(keys))
            m["pull_bytes"].add(keys.nbytes + slots_arr.nbytes
                                + out.size * (2 if f16 else 4))
            if out.size:
                m["pull_window"].update(
                    float(np.count_nonzero(out)) / out.size)
        return out

    def push_sparse(self, table_id, keys, values):
        self._op_count("push_sparse")
        with RecordEvent("pserver_client_push_sparse"):
            return self._push_sparse(table_id, keys, values)

    def _push_sparse(self, table_id, keys, values, _wire=None):
        keys = np.ascontiguousarray(keys, np.uint64)
        values = np.ascontiguousarray(values, np.float32)
        # client-side dedup-merge (brpc client merges duplicate keys
        # before send)
        keys, values = merge_duplicate_keys(keys, values)
        wire, block, ef_on = ((_wire, 0, False) if _wire is not None else
                              self._push_wire.get(table_id,
                                                  ("fp32", 0, False)))
        gd = values.shape[1] - 3 if values.ndim == 2 else 0
        if wire == "fp32" or gd <= 0:
            enc, aux = None, 0
            wire_bytes = keys.nbytes + values.nbytes
        else:
            # quantize ONCE for the whole merged batch, BEFORE routing:
            # a misroute replay re-sends the same encoded slices, so the
            # error-feedback residual (already advanced for these rows)
            # is never double-counted and every shard applies exactly
            # the bytes this encode produced
            head = np.ascontiguousarray(values[:, :3])
            grad = values[:, 3:]
            if wire == "fp16":
                enc = (head, np.ascontiguousarray(grad.astype(np.float16)))
                aux = _PUSH_WIRE_F16
            else:
                blk = min(block, gd)
                if ef_on:
                    with self._ef_mu:
                        g = grad + self._ef_gather(table_id, keys, gd)
                        q, scales = _quant_push_int8(g, blk)
                        self._ef_scatter(
                            table_id, keys,
                            g - _dequant_push_int8(q, scales, blk))
                        overflow = len(self._push_ef.get(table_id, ())) > \
                            int(flag("ps_push_ef_max_rows"))
                else:
                    q, scales = _quant_push_int8(grad, blk)
                    overflow = False
                enc = (head, scales, q)
                aux = _PUSH_WIRE_I8 | (blk << _PUSH_WIRE_BLOCK_SHIFT)
            wire_bytes = keys.nbytes + sum(a.nbytes for a in enc)
        self._push_encoded(table_id, keys,
                           values if enc is None else None, enc, aux, 0)
        if enc is not None and aux & _PUSH_WIRE_I8 and ef_on and overflow:
            # bounded client RAM: past the cap the whole table's
            # residuals drain over the fp32 wire (outside _ef_mu — the
            # drain is itself a network push)
            self.drain_push_residuals(table_id)
        m = self._tbl_obs.get(table_id)
        if m is not None:
            m["push_rows"].add(len(keys))
            # ACTUAL wire bytes (quantized payload, not the fp32 rows)
            # — the counter the ≥3x sparse-push-reduction CI gate reads
            m["push_bytes"].add(wire_bytes)
            # observed push density over the GRADIENT block (the push
            # layout's leading slot/show/click columns are always set):
            # the per-table measured sparsity the Parallax placement
            # pass (distributed/placement.py) reads as its signal,
            # smoothed into an EWMA + min/max-over-window series
            g = values[:, 3:] if values.ndim == 2 and \
                values.shape[1] > 3 else values
            if g.size:
                m["push_window"].update(
                    float(np.count_nonzero(g)) / g.size)

    def _push_encoded(self, table_id, keys, values, enc, aux, _hops):
        """Route + fan out one ALREADY-ENCODED push batch. ``enc`` is
        None (fp32 wire: ``values`` ships raw) or the tuple of encoded
        parts (head [, scales], grad) whose row-slices replay verbatim
        on a kErrWrongShard bounce."""
        sv = self._route(keys)

        def one(c, sel):
            kp = keys if sel is None else keys[sel]
            if enc is None:
                parts = (kp, values if sel is None else values[sel])
            else:
                parts = (kp,) + tuple(
                    a if sel is None else np.ascontiguousarray(a[sel])
                    for a in enc)
            c.check(_PUSH_SPARSE, table_id, n=len(kp), aux=aux,
                    payload=parts)

        misrouted: List[np.ndarray] = []
        self._fanout([self._bounce_guard(s, lambda c, sel=sel: one(c, sel),
                                         misrouted, sel, len(keys))
                      for s, sel in self._shard_sel(sv)])
        if misrouted:
            # the bounced slice changed state NOWHERE (whole-frame
            # rejection), so replaying only it applies each gradient
            # exactly once even though the other shards' slices landed
            self._reroute_backoff(_hops)
            idx = np.concatenate(misrouted)
            self._push_encoded(
                table_id, keys[idx],
                None if values is None else values[idx],
                None if enc is None else tuple(a[idx] for a in enc),
                aux, _hops + 1)

    # -- error-feedback residuals (push_wire_dtype="int8") ----------------

    def _ef_gather(self, table_id: int, keys: np.ndarray, gd: int
                   ) -> np.ndarray:
        """Residual rows for ``keys`` (zeros for keys never quantized).
        Caller holds _ef_mu."""
        store = self._push_ef.setdefault(table_id, {})
        out = np.zeros((len(keys), gd), np.float32)
        for i, k in enumerate(keys.tolist()):
            r = store.get(k)
            if r is not None:
                out[i] = r
        return out

    def _ef_scatter(self, table_id: int, keys: np.ndarray,
                    resid: np.ndarray) -> None:
        """Store the fresh residuals (caller holds _ef_mu)."""
        store = self._push_ef.setdefault(table_id, {})
        for i, k in enumerate(keys.tolist()):
            store[k] = resid[i].copy()

    def push_residual_rows(self, table_id: Optional[int] = None) -> int:
        """Residual rows currently held client-side (tests/introspection;
        0 after a drain — the digest-consistency contract)."""
        with self._ef_mu:
            if table_id is not None:
                return len(self._push_ef.get(table_id, ()))
            return sum(len(s) for s in self._push_ef.values())

    def drain_push_residuals(self, table_id: Optional[int] = None) -> int:
        """Push every queued error-feedback residual over the fp32 wire
        and clear the store; returns rows drained. Communicator.quiesce()
        calls this (like its queued pushes) so a checkpoint cut is
        digest-consistent: after the drain, NO training signal lives
        client-side — the captured server rows are the whole state.
        Drain rows carry show=1.0/click=0: the AdaGrad family divides
        the gradient by push_show, so a zero show would amplify the
        residual ~1e10x instead of applying it (one synthetic
        impression per drained key is the corresponding stats cost)."""
        with self._ef_mu:
            if table_id is None:
                drained = {t: s for t, s in self._push_ef.items() if s}
                self._push_ef = {}
            else:
                drained = {table_id: self._push_ef.pop(table_id, {})}
        total = 0
        for tid, store in drained.items():
            if not store:
                continue
            keys = np.fromiter(store.keys(), np.uint64, len(store))
            pd = self._dims(tid)[1]
            vals = np.zeros((len(keys), pd), np.float32)
            vals[:, 1] = 1.0  # show (see docstring)
            resid = np.stack(list(store.values()))
            vals[:, 3:3 + resid.shape[1]] = resid
            self._op_count("push_sparse")
            self._push_sparse(tid, keys, vals, _wire="fp32")
            total += len(keys)
        return total

    def pull_dense(self, table_id):
        self._op_count("pull_dense")
        try:
            dim = self._dense_dims[table_id]
        except KeyError:
            raise NotFoundError(f"dense table {table_id} not created via this client")
        out = np.zeros(dim, np.float32)

        def one(c, sl):
            _, resp = c.check(_PULL_DENSE, table_id, view=True)
            out[sl.start : sl.stop] = resp.view(np.float32)

        self._fanout([self._task(s, lambda c, sl=self._dense_slice(dim, s):
                                 one(c, sl))
                      for s in range(self.num_servers)
                      if len(self._dense_slice(dim, s))])
        m = self._tbl_obs.get(table_id)
        if m is not None:
            m["pull_bytes"].add(out.nbytes)
        return out

    def push_dense(self, table_id, grad):
        self._op_count("push_dense")
        grad = np.ascontiguousarray(grad, np.float32)
        dim = self._dense_dims[table_id]
        m = self._tbl_obs.get(table_id)
        if m is not None:
            m["push_bytes"].add(grad.nbytes)
            if grad.size:
                # dense-gradient sparsity: the Parallax signal for
                # moving a sparse-ish dense grad ONTO the PS wire
                m["push_window"].update(
                    float(np.count_nonzero(grad)) / grad.size)
        # contiguous slice views — the gradient ships straight from the
        # caller's buffer, no per-server copy at all
        self._fanout(
            [self._task(s, lambda c, sl=self._dense_slice(dim, s):
                        c.check(_PUSH_DENSE, table_id,
                                payload=grad[sl.start : sl.stop]))
             for s in range(self.num_servers)
             if len(self._dense_slice(dim, s))])

    def set_dense(self, table_id, values):
        values = np.ascontiguousarray(values, np.float32)
        dim = self._dense_dims[table_id]
        self._fanout(
            [self._task(s, lambda c, sl=self._dense_slice(dim, s):
                        c.check(_SET_DENSE, table_id,
                                payload=values[sl.start : sl.stop]))
             for s in range(self.num_servers)
             if len(self._dense_slice(dim, s))])

    def push_geo(self, table_id, keys, deltas, _hops=0):
        if _hops == 0:
            self._op_count("push_geo")
        keys = np.ascontiguousarray(keys, np.uint64)
        deltas = np.ascontiguousarray(deltas, np.float32)
        sv = self._route(keys)

        def one(c, sel):
            kp = keys if sel is None else keys[sel]
            dp = deltas if sel is None else deltas[sel]
            c.check(_PUSH_GEO, table_id, n=len(kp), payload=(kp, dp))

        misrouted: List[np.ndarray] = []
        self._fanout([self._bounce_guard(s, lambda c, sel=sel: one(c, sel),
                                         misrouted, sel, len(keys))
                      for s, sel in self._shard_sel(sv)])
        if misrouted:
            self._reroute_backoff(_hops)
            idx = np.concatenate(misrouted)
            self.push_geo(table_id, keys[idx], deltas[idx], _hops=_hops + 1)

    def pull_geo(self, table_id):
        self._op_count("pull_geo")
        dim = self._geo_dims[table_id]

        def one(c):
            cnt, resp = c.check(_PULL_GEO, table_id, view=True)
            if not cnt:
                return None
            # copy out of the thread's reused view before returning
            return (resp[: cnt * 8].view(np.uint64).copy(),
                    resp[cnt * 8 :].view(np.float32)
                    .reshape(cnt, dim).copy())

        got = [g for g in self._fanout([self._task(s, one)
                                        for s in range(self.num_servers)])
               if g]
        if not got:
            return np.zeros(0, np.uint64), np.zeros((0, dim), np.float32)
        return (np.concatenate([k for k, _ in got]),
                np.concatenate([d for _, d in got]))

    def barrier(self):
        # all-trainer barrier lives on server 0 (BarrierTable placement);
        # a long-but-finite deadline (peers may legitimately lag, but a
        # silently dead server must still surface) and retries=0 so a
        # flaky link can't double-arrive on the SAME server. Routed
        # through _shard_op: a barrier racing a primary→backup promotion
        # re-resolves the routing table and re-arrives on the PROMOTED
        # server instead of surfacing a spurious dead-server error (the
        # old primary never registered the failed arrival, so this
        # cannot double-count). Known tradeoff: a barrier that expires
        # its 30-min deadline against a HEALTHY server (peers truly
        # wedged) is indistinguishable from a dead server at the
        # transport level, so it pays one failover wait and counts one
        # breaker failure — acceptable at that timescale.
        self._shard_op(0, lambda c: c.check(
            _BARRIER, retries=0,
            timeout_ms=int(flag("pserver_barrier_timeout_ms"))))

    def global_step(self, increment: int = 1) -> int:
        self._op_count("global_step")
        status, _ = self._shard_op(
            0, lambda c: c.check(_GLOBAL_STEP, n=increment))
        return status

    def shrink(self, table_id):
        # parallel: the shrink sweep is a whole-table rewrite per server
        # (~minutes at 1e8 rows) — the daily boundary pays max, not sum
        return sum(self._fanout(
            [self._task(s, lambda c: c.check(_SHRINK, table_id,
                                             timeout_ms=_long_ms(),
                                             retries=0)[0])
             for s in range(self.num_servers)]))

    def size(self, table_id) -> int:
        return sum(self._fanout(
            [self._task(s, lambda c: c.check(_SIZE, table_id)[0])
             for s in range(self.num_servers)]))

    # -- HA helpers (ps/ha.py drives these; docs/OPERATIONS.md §6) --------

    def digest(self, table_id: int) -> List[int]:
        """Per-server order-independent content digests (kDigest) — two
        replicas of a shard holding bit-identical rows digest equal."""
        def one(c):
            _, resp = c.check(_DIGEST, table_id)
            return int(np.frombuffer(resp, np.uint64)[0])

        return self._fanout([self._task(s, one)
                             for s in range(self.num_servers)])

    # -- live-reshard control surface (ps/reshard.py drives these) --------

    def digest_routed(self, table_id: int) -> List[int]:
        """Per-server digests of each server's ROUTED key class
        (``key % num_servers == s``) — the capture-consistent
        companion of :meth:`snapshot_items`: mid-reshard, a migrating
        class in flight on two servers digests exactly once. Identity
        to :meth:`digest` in steady state. SSD-backed tables have no
        filtered digest (and cannot reshard — the controller refuses
        them — so no in-flight class can ever double-count): they take
        the plain per-server digest."""
        cfg = self._sparse_cfgs.get(table_id)
        if cfg is not None and cfg.storage == "ssd":
            return self.digest(table_id)
        n = self.num_servers
        return [self.digest_at(s, table_id, n, s) for s in range(n)]

    def digest_at(self, server: int, table_id: int, modulus: int = 0,
                  residue: int = 0) -> int:
        """ONE server's content digest, optionally restricted to keys
        with ``key % modulus == residue`` (kDigest n/aux). Digests are
        wrapping sums of per-row hashes, so class digests ADD — the
        reshard controller's no-row-lost-or-doubled check is an O(1)
        equality over these. Server-targeted (no failover replay): the
        answer must come from the addressed replica or fail."""
        _, resp = self._direct(server, lambda c: c.check(
            _DIGEST, table_id, n=int(modulus), aux=int(residue),
            timeout_ms=_long_ms()))
        return int(np.frombuffer(resp, np.uint64)[0])

    def retain(self, server: int, modulus: int, residue: int) -> int:
        """Install ``server``'s key-ownership predicate and (when
        ``0 <= residue < modulus``) drop every row outside it — the
        reshard cutover's key-range filter (kRetain; tapped, so the
        shard's backups converge). ``residue=-1`` fences the server out
        of the data plane entirely (a retiring shard: every keyed op
        bounces kErrWrongShard until the stale client re-resolves).
        Returns rows erased."""
        status, _ = self._direct(server, lambda c: c.check(
            _RETAIN, n=int(modulus), aux=int(residue),
            timeout_ms=_long_ms(), retries=0))
        return int(status)

    def ownership(self, server: int) -> Tuple[int, int]:
        """One server's (modulus, residue) ownership predicate
        ((0, 0) = owns everything — the static-topology default)."""
        _, resp = self._direct(server, lambda c: c.check(_RETAIN, n=0))
        st = np.frombuffer(resp, np.int64)
        return int(st[0]), int(st[1])

    def server_epoch(self, server: int, set_to: Optional[int] = None) -> int:
        """Read (or set) one server's routing epoch (kEpoch). The
        failover coordinator sets the promoted backup's epoch BEFORE
        publishing the new routing table, fencing the demoted primary's
        replication stream."""
        status, _ = self._direct(
            server, lambda c: c.check(
                _EPOCH, n=-1 if set_to is None else int(set_to)))
        return status

    def repl_state(self, server: int) -> Tuple[int, int, int, int]:
        """(applied_seq, epoch, oplog_seq, oplog_pending) of one server
        (kReplState read) — enough to run a cross-process replication
        drain barrier with no shared store (ha.drain_remote)."""
        _, resp = self._direct(
            server, lambda c: c.check(_REPL_STATE, n=-1))
        st = np.frombuffer(resp, np.int64)
        return int(st[0]), int(st[1]), int(st[2]), int(st[3])

    def dense_snapshot(self, table_id: int, server: int) -> bytes:
        """One server's dense-table full state (values + optimizer
        moments + step; kDenseSnap) — the rejoin snapshot payload."""
        _, resp = self._direct(
            server, lambda c: c.check(_DENSE_SNAP, table_id,
                                      timeout_ms=_long_ms()))
        return bytes(resp)

    def dense_restore(self, table_id: int, server: int, blob: bytes) -> None:
        self._direct(
            server, lambda c: c.check(_DENSE_RESTORE, table_id, payload=blob,
                                      timeout_ms=_long_ms()))


    def _embedx_dim(self, table_id: int) -> int:
        cfg = self._sparse_cfgs[table_id]
        return (cfg.accessor_config or AccessorConfig()).embedx_dim

    def _embedx_state_dim(self, table_id: int) -> int:
        """xs from full_dim = 7 + ed + xd + xs with ed derived from the
        config's embed rule (dim 1)."""
        from .sgd_rule import make_sgd_rule

        cfg = self._sparse_cfgs[table_id]
        acc = cfg.accessor_config or AccessorConfig()
        return make_sgd_rule(acc.embedx_sgd_rule, acc.embedx_dim, acc.sgd).state_dim

    # -- save/load (per-server shard files; accessor text format) ---------

    def _save_all_items(self, server: int, table_id: int, mode: int = 0
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """One server's full-row export via the single atomic kSaveAll
        command (snapshot+stream — concurrent savers cannot interleave a
        begin/fetch pair): (keys [n] u64, values [n, full_dim] f32)."""
        full_dim = self._dims(table_id)[2]
        cnt, resp = self._shard_op(server, lambda c: c.check(
            _SAVE_ALL, table_id, aux=mode,
            timeout_ms=_long_ms(), retries=0))
        keys = np.frombuffer(resp[: cnt * 8], np.uint64)
        values = np.frombuffer(resp[cnt * 8:], np.float32).reshape(
            cnt, full_dim)
        return keys, values

    def snapshot_items(self, table_id, mode: int = 0
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """Whole-table export staged in RAM across every server —
        the job-checkpoint capture path (io/job_checkpoint.py):
        binary-exact full rows (keys [n] u64, values [n, full_dim]
        f32), so the restored table digests identical to the capture.
        Take it under a mutation gate (ha.CheckpointGate) for a
        consistent cut; kSaveAll itself reads a paused primary fine.
        Servers export in PARALLEL (_fanout) — the gate hold, i.e. the
        training stall, is max(shards), not sum(shards).

        Each server's export is filtered to the rows the CURRENT
        routing assigns it (``key % num_servers == s``): during a live
        reshard's bootstrap window the migrating key class exists on
        TWO servers (the copy is the mechanism), and an unfiltered
        union would capture it twice — the routed filter makes the
        capture exactly-once at every instant. In steady state every
        row already satisfies it (modulo routing), so this is the
        identity."""
        n = self.num_servers
        parts = self._fanout(
            [lambda s=s: self._save_all_items(s, table_id, mode)
             for s in range(n)])  # zero-arg tasks:
        # _save_all_items is already _shard_op-wrapped (failover replay)
        routed = []
        for s, (k, v) in enumerate(parts):
            own = (k % np.uint64(n)).astype(np.int64) == s
            routed.append((k[own], v[own]) if not own.all() else (k, v))
        keys = np.concatenate([k for k, _ in routed])
        values = np.concatenate([v for _, v in routed])
        return keys, values

    def save(self, table_id, dirname, mode=0):
        """Same on-disk format as MemorySparseTable.save (format_shard_row
        + meta.json) — checkpoints are portable between the local and rpc
        transports. Files are keyed by server index."""
        import json

        os.makedirs(dirname, exist_ok=True)
        xd = self._embedx_dim(table_id)
        ed = self._dims(table_id)[2] - 7 - xd - self._embedx_state_dim(table_id)
        total = 0
        for s in range(self.num_servers):
            keys, values = self._save_all_items(s, table_id, mode)
            cnt = len(keys)
            path = os.path.join(dirname, f"part-{s:05d}.shard")
            with open(path, "w") as f:
                for j in range(cnt):
                    f.write(format_shard_row(keys[j], values[j], ed, xd) + "\n")
            total += cnt
        with open(os.path.join(dirname, "meta.json"), "w") as f:
            json.dump({"shard_num": self.num_servers, "embedx_dim": xd,
                       "accessor": self._sparse_cfgs[table_id].accessor,
                       "mode": mode}, f)
        return total

    def load(self, table_id, dirname):
        import json

        with open(os.path.join(dirname, "meta.json")) as f:
            meta = json.load(f)
        full_dim = self._dims(table_id)[2]
        xd = self._embedx_dim(table_id)
        ed = full_dim - 7 - xd - self._embedx_state_dim(table_id)
        enforce(meta["embedx_dim"] == xd,
                f"embedx_dim mismatch: file {meta['embedx_dim']} != table {xd}")
        total = 0
        for s in range(meta["shard_num"]):
            path = os.path.join(dirname, f"part-{s:05d}.shard")
            if not os.path.exists(path):
                continue
            keys, rows = [], []
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    k, row = parse_shard_row(parts, ed, xd, full_dim)
                    keys.append(k)
                    rows.append(row)
            if not keys:
                continue
            # re-route by current server count (files may come from a
            # different cluster size or the local transport)
            self.import_full(table_id, np.asarray(keys, np.uint64), np.stack(rows))
            total += len(keys)
        return total

    def export_full(self, table_id, keys, create=False, slots=None,
                    _hops=0):
        """(values [n, full_dim], found [n]) across servers. With
        ``create``, missing rows are inserted server-side in the same
        traversal (the multi-node pass-build BuildPull,
        ps_gpu_wrapper.cc:299)."""
        if _hops == 0:
            self._op_count("export_full")
        keys = np.ascontiguousarray(keys, np.uint64)
        full_dim = self._dims(table_id)[2]
        out = np.zeros((len(keys), full_dim), np.float32)
        found = np.zeros(len(keys), bool)
        slots_arr = (np.ascontiguousarray(slots, np.int32)
                     if slots is not None else np.zeros(len(keys), np.int32))
        sv = self._route(keys)

        def one(c, sel):
            kp = keys if sel is None else keys[sel]
            parts = (kp, slots_arr if sel is None else slots_arr[sel]) \
                if create else (kp,)
            _, resp = c.check(_EXPORT, table_id, n=len(kp),
                              aux=1 if create else 0, payload=parts,
                              timeout_ms=_long_ms(), view=True)
            nb = len(kp) * full_dim * 4
            vals = resp[:nb].view(np.float32).reshape(len(kp), full_dim)
            if sel is None:
                out[:] = vals
                found[:] = resp[nb:] != 0
            else:
                out[sel] = vals
                found[sel] = resp[nb:] != 0

        misrouted: List[np.ndarray] = []
        self._fanout([self._bounce_guard(s, lambda c, sel=sel: one(c, sel),
                                         misrouted, sel, len(keys))
                      for s, sel in self._shard_sel(sv)])
        if misrouted:
            self._reroute_backoff(_hops)
            idx = np.concatenate(misrouted)
            out[idx], found[idx] = self.export_full(
                table_id, keys[idx], create, slots_arr[idx],
                _hops=_hops + 1)
        m = self._tbl_obs.get(table_id) if _hops == 0 else None
        if m is not None:
            m["pull_rows"].add(len(keys))
            m["pull_bytes"].add(keys.nbytes + out.nbytes + found.nbytes)
        return out, found

    def import_full(self, table_id, keys, values, _hops=0):
        if _hops == 0:
            self._op_count("import_full")
        keys = np.ascontiguousarray(keys, np.uint64)
        values = np.ascontiguousarray(values, np.float32)
        sv = self._route(keys)

        def one(c, sel):
            kp = keys if sel is None else keys[sel]
            vp = values if sel is None else values[sel]
            c.check(_INSERT_FULL, table_id, n=len(kp), payload=(kp, vp),
                    timeout_ms=_long_ms())

        misrouted: List[np.ndarray] = []
        self._fanout([self._bounce_guard(s, lambda c, sel=sel: one(c, sel),
                                         misrouted, sel, len(keys))
                      for s, sel in self._shard_sel(sv)])
        if misrouted:
            self._reroute_backoff(_hops)
            idx = np.concatenate(misrouted)
            self.import_full(table_id, keys[idx], values[idx],
                             _hops=_hops + 1)
        m = self._tbl_obs.get(table_id) if _hops == 0 else None
        if m is not None:
            m["push_rows"].add(len(keys))
            m["push_bytes"].add(keys.nbytes + values.nbytes)

    def load_cold(self, table_id, keys, values, chunk: int = 1 << 21,
                  _hops=0) -> int:
        """Bulk cold-tier model load across servers (the 1e9-row build
        path): keys route by ``key % num_servers``; each server's slice
        ships in bounded chunks (frames stay far under the 4 GiB cap and
        client RAM stays flat). SSD-backed tables append to their disk
        logs; RAM tables hot-insert. Returns rows durably loaded."""
        keys = np.ascontiguousarray(keys, np.uint64)
        values = np.ascontiguousarray(values, np.float32)
        full_dim = self._dims(table_id)[2]
        enforce(values.shape == (len(keys), full_dim),
                f"load_cold values shape {values.shape} != "
                f"({len(keys)}, {full_dim})")
        sv = self._route(keys)
        done_rows = [0] * self.num_servers

        def one(c, s, sel):
            # chunks WITHIN a server stay sequential (bounded frames,
            # flat client RAM); servers load in parallel. Completed
            # chunks accumulate per shard so a misroute replay after a
            # mid-load reshard replays only this shard's UNSENT keys
            # (a bounced chunk changed nothing server-side).
            for lo in range(0, len(sel), chunk):
                part = sel[lo : lo + chunk]
                try:
                    cnt, _ = c.check(_LOAD_COLD, table_id, n=len(part),
                                     payload=(keys[part], values[part]),
                                     timeout_ms=_long_ms())
                except WrongShardError:
                    raise _ColdBounce(sel[lo:])
                done_rows[s] += int(cnt)

        misrouted: List[np.ndarray] = []

        def guarded(s, sel):
            def run():
                try:
                    self._shard_op(s, lambda c: one(c, s, sel))
                except _ColdBounce as b:
                    if self._router is None:
                        raise WrongShardError(
                            "load_cold bounced with no router to "
                            "re-resolve — stale static topology")
                    misrouted.append(b.pending)
                except WrongShardError:
                    # stale shard index (shrunk topology): nothing of
                    # this shard's slice was sent
                    if self._router is None:
                        raise
                    misrouted.append(sel)
            return run

        self._fanout([guarded(s, np.flatnonzero(sv == s))
                      for s in range(self.num_servers)])
        total = sum(done_rows)
        if misrouted:
            self._reroute_backoff(_hops)
            idx = np.concatenate(misrouted)
            total += self.load_cold(table_id, keys[idx], values[idx],
                                    chunk=chunk, _hops=_hops + 1)
        return total

    _SAVE_FORMATS = {None: (0, ""), "gzip": (1, ".gz"), "raw": (2, ".bin")}

    def save_local(self, table_id, dirname, mode: int = 0,
                   converter: Optional[str] = None) -> int:
        """Server-side save: each server streams ITS shard straight to
        ``dirname/part-{s:05d}.shard[.gz|.bin]`` — nothing crosses the
        wire, so populations that cannot stage in RAM (or in one 4 GiB
        frame) save fine. ``dirname`` must be reachable by the servers
        (same host or shared FS — the reference's HDFS/AFS role).
        Converters: "gzip" = zlib'd text (portable, compact on
        low-entropy rows, CPU-bound at 1e9 rows); "raw" = fixed binary
        records (runs at IO speed — the zlib+printf CPU cost measured
        ~212k rows/s/core on the 0.67e9-row artifact vanishes — at
        56+ B/row uncompressed); None = plain text."""
        enforce(converter in self._SAVE_FORMATS,
                f"server-side save supports converter None|'gzip'|'raw', "
                f"got {converter!r}")
        fmt, suffix = self._SAVE_FORMATS[converter]
        os.makedirs(dirname, exist_ok=True)
        aux = int(mode) | (fmt << 8)
        # parallel: each server streams ITS shard to its own file —
        # checkpoint wall-clock is the largest shard, not the sum
        total = sum(self._fanout(
            [self._task(s, lambda c, path=os.path.join(
                dirname, f"part-{s:05d}.shard{suffix}"):
                int(c.check(_SAVE_FILE, table_id, aux=aux,
                            payload=path.encode(), timeout_ms=0,
                            retries=0)[0]))
             for s in range(self.num_servers)]))
        import json

        with open(os.path.join(dirname, "meta.json"), "w") as f:
            json.dump({"shard_num": self.num_servers,
                       "embedx_dim": self._embedx_dim(table_id),
                       "accessor": self._sparse_cfgs[table_id].accessor,
                       "mode": mode, "converter": converter}, f)
        return total

    def load_local(self, table_id, dirname) -> int:
        """Server-side load of a ``save_local`` checkpoint. Requires the
        SAME server count the save was made with (file s holds exactly
        the keys ≡ s mod shard_num — a different count would misroute);
        for re-sharding restores use ``load`` (client-side re-route)."""
        import json

        with open(os.path.join(dirname, "meta.json")) as f:
            meta = json.load(f)
        enforce(meta["shard_num"] == self.num_servers,
                f"save_local checkpoint has {meta['shard_num']} shards but "
                f"{self.num_servers} servers are up — use load() to "
                f"re-route client-side")
        conv = meta.get("converter")
        enforce(conv in self._SAVE_FORMATS,
                f"unknown save_local converter {conv!r} in meta.json")
        fmt, suffix = self._SAVE_FORMATS[conv]
        aux = fmt << 8
        return sum(self._fanout(
            [self._task(s, lambda c, path=path:
                        int(c.check(_LOAD_FILE, table_id, aux=aux,
                                    payload=path.encode(), timeout_ms=0,
                                    retries=0)[0]))
             for s in range(self.num_servers)
             for path in [os.path.join(dirname,
                                       f"part-{s:05d}.shard{suffix}")]
             if os.path.exists(path)]))

    def stop_servers(self) -> None:
        for c in self._conns:
            try:
                c.call(_STOP, retries=0)  # a gone server is already stopped
            except Exception:
                pass


class RemoteSparseTable:
    """Table-shaped view over a sparse table living on RPC servers.

    The adapter that makes the GPUPS pass path multi-node: the
    HBM embedding cache and CtrPassTrainer consume the local Table API
    (accessor metadata + export_full/import_full/pull/push/save/load);
    this class serves that API from ``RpcPsClient`` — begin_pass's
    insert-on-miss state export becomes the reference's BuildPull from
    remote shards (ps_gpu_wrapper.cc:299: "multi-node: brpc to remote
    shards"), end_pass's import_full the EndPass flush-back.

    Construct after ``client.create_sparse_table(table_id, cfg)`` with
    the same config (the accessor metadata must match the servers').
    """

    def __init__(self, client: RpcPsClient, table_id: int,
                 config: TableConfig) -> None:
        from .accessor import make_accessor

        self._client = client
        self._table_id = int(table_id)
        self.config = config
        self.accessor = make_accessor(config.accessor, config.accessor_config)

    # -- the surface HbmEmbeddingCache / CtrPassTrainer consume ----------

    def pull_sparse(self, keys, slots=None, create=True):
        return self._client.pull_sparse(self._table_id, keys, create=create,
                                        slots=slots)

    def push_sparse(self, keys, push_values):
        self._client.push_sparse(self._table_id, keys, push_values)

    def export_full(self, keys, create=False, slots=None):
        return self._client.export_full(self._table_id, keys, create=create,
                                        slots=slots)

    def import_full(self, keys, values):
        self._client.import_full(self._table_id, keys, values)

    def size(self) -> int:
        return self._client.size(self._table_id)

    def shrink(self) -> int:
        return self._client.shrink(self._table_id)

    def save(self, dirname: str, mode: int = 0) -> int:
        return self._client.save(self._table_id, dirname, mode=mode)

    def load(self, dirname: str) -> int:
        return self._client.load(self._table_id, dirname)

    def load_cold(self, keys, values) -> int:
        return self._client.load_cold(self._table_id, keys, values)

    def save_local(self, dirname: str, mode: int = 0,
                   converter: Optional[str] = None) -> int:
        return self._client.save_local(self._table_id, dirname, mode=mode,
                                       converter=converter)

    def load_local(self, dirname: str) -> int:
        return self._client.load_local(self._table_id, dirname)

    def snapshot_items(self, mode: int = 0):
        return self._client.snapshot_items(self._table_id, mode=mode)

    def refresh_routing(self) -> bool:
        """Re-resolve the client's shard topology (live reshard): a
        capture path that only ever READS (kSaveAll/kDigest are not
        key-fenced) would otherwise keep snapshotting the pre-reshard
        server set — a silently PARTIAL capture. The job-checkpoint
        manager calls this under its gate before every capture."""
        return self._client.refresh_routing()

    def spill(self, hot_budget: int) -> int:
        return self._client.spill(self._table_id, hot_budget)

    def stats(self) -> Dict[str, int]:
        return self._client.table_stats(self._table_id)

    def digest(self) -> List[int]:
        # routed per-server digests: exactly-once per key class even
        # mid-reshard (steady state: identical to the plain kDigest
        # sum) — the job-checkpoint capture digests THE SAME row set
        # snapshot_items exports
        return self._client.digest_routed(self._table_id)

    @property
    def full_dim(self) -> int:
        return self._client._dims(self._table_id)[2]
