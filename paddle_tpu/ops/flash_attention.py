"""Pallas flash attention for TPU.

The hot op the reference leaves to cuDNN/hand-CUDA becomes a Pallas
kernel pair (fwd + bwd) built for the MXU: blockwise QK^T with an online
softmax held in VMEM scratch, O accumulated in fp32, causal blocks
skipped whole. Returns the per-row log-sum-exp so the cp ring
(parallel/ring_attention.py) can merge per-device partial attentions
without renormalizing through HBM.

Layout: [B, L, H, D] (framework-wide attention layout); internally
reshaped to [B*H, L, D] and padded to MXU tiles (D→128 multiples,
L→block multiples). ``q_offset``/``k_offset`` shift the causal mask for
sequence-sharded (cp) blocks; they may be traced values (axis_index).

Backward: standard flash backward — recompute P = exp(S - lse) blockwise;
dV = P^T dO, dS = P ∘ (dO V^T - Δ), dQ = dS K, dK = dS^T Q with
Δ = rowsum(dO ∘ O) computed outside (one fused elementwise pass).
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(pltpu, "CompilerParams"):
    # jax < 0.6 names the pallas params class TPUCompilerParams
    pltpu.CompilerParams = pltpu.TPUCompilerParams

__all__ = ["flash_attention", "flash_attention_with_lse"]

NEG = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _out_struct(shape, dtype, *inputs):
    """ShapeDtypeStruct carrying the union of the inputs' varying-manual-
    axes type — required for pallas_call under shard_map (check_vma)."""
    vma = frozenset()
    try:
        for x in inputs:
            vma = vma | jax.typeof(x).vma
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_scr, l_scr, *, scale, causal, bq, bk, mxu):
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_scr[:] = jnp.full_like(m_scr, NEG)
        l_scr[:] = jnp.zeros_like(l_scr)

    q_off, k_off, k_len = offs_ref[0], offs_ref[1], offs_ref[3]
    i = pl.program_id(1)
    row0 = q_off + i * bq
    col0 = k_off + j * bk

    def body():
        # MXU operands in `mxu` dtype (bf16 default: single-pass MXU with
        # fp32 accumulation; fp32 operands = multi-pass, ~3x the cycles)
        q = q_ref[0].astype(mxu)          # [bq, D]
        k = k_ref[0].astype(mxu)          # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = cols < (k_off + k_len)
        if causal:
            rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = mask & (cols <= rows)
        s = jnp.where(mask, s, NEG)

        m_prev = m_scr[:, :1]                      # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)             # m_prev=NEG → 0
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(mxu), v_ref[0].astype(mxu),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # causal block skip: block fully in the future → nothing to do
        pl.when(row0 + bq - 1 >= col0)(body)
    else:
        body()

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[:, :1]
        o_ref[0] = (acc[:] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m_scr[:, :1] + jnp.log(jnp.maximum(l, 1e-30)), NEG)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd(q, k, v, scale, causal, q_offset, k_offset, bq, bk, interpret, mxu):
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    nq, nk = Lq // bq, Lk // bk
    offs = jnp.asarray(
        jnp.stack([jnp.asarray(q_offset, jnp.int32),
                   jnp.asarray(k_offset, jnp.int32),
                   jnp.asarray(Lq, jnp.int32),
                   jnp.asarray(k.shape[1], jnp.int32)]), jnp.int32)

    grid = (BH, nq, nk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, mxu=mxu)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i, j, offs: (b, i, 0)),
                pl.BlockSpec((1, bk, D), lambda b, i, j, offs: (b, j, 0)),
                pl.BlockSpec((1, bk, D), lambda b, i, j, offs: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, bq, D), lambda b, i, j, offs: (b, i, 0)),
                pl.BlockSpec((1, bq, 128), lambda b, i, j, offs: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, D), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
                pltpu.VMEM((bq, 128), jnp.float32),
            ],
        ),
        out_shape=[
            _out_struct((BH, Lq, D), q.dtype, q, k, v, offs),
            _out_struct((BH, Lq, 128), jnp.float32, q, k, v, offs),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(offs, q, k, v)
    return out, lse[:, :, 0]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc, *, scale, causal, bq, bk, mxu):
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_off, k_off, k_len = offs_ref[0], offs_ref[1], offs_ref[3]
    i = pl.program_id(1)
    row0 = q_off + i * bq
    col0 = k_off + j * bk

    def body():
        q = q_ref[0].astype(mxu)
        k = k_ref[0].astype(mxu)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = cols < (k_off + k_len)
        if causal:
            rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = mask & (cols <= rows)
        lse = lse_ref[0][:, :1]
        p = jnp.where(mask & (lse > NEG / 2), jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do_ref[0].astype(mxu),
                                 v_ref[0].astype(mxu),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dq_acc[:] += jax.lax.dot_general(ds.astype(mxu), k,
                                         (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(row0 + bq - 1 >= col0)(body)
    else:
        body()

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, bq, bk, mxu):
    i = pl.program_id(2)           # q-block index (inner loop)
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_off, k_off, k_len = offs_ref[0], offs_ref[1], offs_ref[3]
    j = pl.program_id(1)           # k-block index (outer grid dim)
    row0 = q_off + i * bq
    col0 = k_off + j * bk

    def body():
        q = q_ref[0].astype(mxu)
        k = k_ref[0].astype(mxu)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        cols = col0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = cols < (k_off + k_len)
        if causal:
            rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = mask & (cols <= rows)
        lse = lse_ref[0][:, :1]
        p = jnp.where(mask & (lse > NEG / 2), jnp.exp(s - lse), 0.0)
        do = do_ref[0].astype(mxu)
        dv_acc[:] += jax.lax.dot_general(p.astype(mxu), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0].astype(mxu),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[:] += jax.lax.dot_general(ds.astype(mxu), q,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32) * scale

    if causal:
        pl.when(row0 + bq - 1 >= col0)(body)
    else:
        body()

    @pl.when(i == nq - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(scale, causal, bq, bk, interpret, mxu, res, grads):
    q, k, v, out, lse, offs = res
    do, dlse = grads
    BH, Lq, D = q.shape
    Lk = k.shape[1]
    nq, nk = Lq // bq, Lk // bk
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)                                  # [BH, Lq]
    if dlse is not None:
        # d(lse)/dS = P, so an lse cotangent enters dS = P∘(dP - Δ + dlse)
        # — fold it into Δ rather than touching the kernels
        delta = delta - dlse.astype(jnp.float32)
    lse_pad = jnp.broadcast_to(lse[..., None], (BH, Lq, 128))
    delta_pad = jnp.broadcast_to(delta[..., None], (BH, Lq, 128))

    common_in = [
        pl.BlockSpec((1, bq, D), lambda b, i, j, offs: (b, i, 0)),      # q
        pl.BlockSpec((1, bk, D), lambda b, i, j, offs: (b, j, 0)),      # k
        pl.BlockSpec((1, bk, D), lambda b, i, j, offs: (b, j, 0)),      # v
        pl.BlockSpec((1, bq, D), lambda b, i, j, offs: (b, i, 0)),      # do
        pl.BlockSpec((1, bq, 128), lambda b, i, j, offs: (b, i, 0)),    # lse
        pl.BlockSpec((1, bq, 128), lambda b, i, j, offs: (b, i, 0)),    # delta
    ]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, mxu=mxu),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, nq, nk),
            in_specs=common_in,
            out_specs=[pl.BlockSpec((1, bq, D), lambda b, i, j, offs: (b, i, 0))],
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        ),
        out_shape=[_out_struct((BH, Lq, D), q.dtype, q, k, v, do, offs)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(offs, q, k, v, do, lse_pad, delta_pad)[0]

    # swap block index roles: outer dim walks k blocks, inner walks q
    dkv_in = [
        pl.BlockSpec((1, bq, D), lambda b, j, i, offs: (b, i, 0)),      # q
        pl.BlockSpec((1, bk, D), lambda b, j, i, offs: (b, j, 0)),      # k
        pl.BlockSpec((1, bk, D), lambda b, j, i, offs: (b, j, 0)),      # v
        pl.BlockSpec((1, bq, D), lambda b, j, i, offs: (b, i, 0)),      # do
        pl.BlockSpec((1, bq, 128), lambda b, j, i, offs: (b, i, 0)),    # lse
        pl.BlockSpec((1, bq, 128), lambda b, j, i, offs: (b, i, 0)),    # delta
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, mxu=mxu),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, nk, nq),
            in_specs=dkv_in,
            out_specs=[
                pl.BlockSpec((1, bk, D), lambda b, j, i, offs: (b, j, 0)),
                pl.BlockSpec((1, bk, D), lambda b, j, i, offs: (b, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                            pltpu.VMEM((bk, D), jnp.float32)],
        ),
        out_shape=[_out_struct((BH, Lk, D), k.dtype, q, k, v, do, offs),
                   _out_struct((BH, Lk, D), v.dtype, q, k, v, do, offs)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(offs, q, k, v, do, lse_pad, delta_pad)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 7, 8, 9, 10))
def _flash(q, k, v, scale, causal, q_offset, k_offset, bq, bk, interpret, precision):
    (out, _), _ = _flash_fwd(q, k, v, scale, causal, q_offset, k_offset,
                             bq, bk, interpret, precision)
    return out


def _flash_fwd(q, k, v, scale, causal, q_offset, k_offset, bq, bk, interpret, precision):
    mxu = jnp.float32 if precision == "highest" else jnp.bfloat16
    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                      jnp.asarray(k_offset, jnp.int32),
                      jnp.asarray(q.shape[1], jnp.int32),
                      jnp.asarray(k.shape[1], jnp.int32)])
    out, lse = _fwd(q, k, v, scale, causal, q_offset, k_offset, bq, bk,
                    interpret, mxu)
    return (out, lse), (q, k, v, out, lse, offs)


def _flash_fwd_rule(q, k, v, scale, causal, q_offset, k_offset, bq, bk,
                    interpret, precision):
    (out, lse), res = _flash_fwd(q, k, v, scale, causal, q_offset, k_offset,
                                 bq, bk, interpret, precision)
    return out, (res, (q_offset, k_offset))


def _flash_bwd_rule(scale, causal, bq, bk, interpret, precision, saved, g):
    res, (q_offset, k_offset) = saved
    mxu = jnp.float32 if precision == "highest" else jnp.bfloat16
    dq, dk, dv = _bwd(scale, causal, bq, bk, interpret, mxu, res, (g, None))
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 7, 8, 9, 10))
def _flash_pair(q, k, v, scale, causal, q_offset, k_offset, bq, bk,
                interpret, precision):
    (out, lse), _ = _flash_fwd(q, k, v, scale, causal, q_offset, k_offset,
                               bq, bk, interpret, precision)
    return out, lse


def _flash_pair_fwd_rule(q, k, v, scale, causal, q_offset, k_offset, bq, bk,
                         interpret, precision):
    (out, lse), res = _flash_fwd(q, k, v, scale, causal, q_offset, k_offset,
                                 bq, bk, interpret, precision)
    return (out, lse), res


def _flash_pair_bwd_rule(scale, causal, bq, bk, interpret, precision, res, g):
    do, dlse = g
    mxu = jnp.float32 if precision == "highest" else jnp.bfloat16
    dq, dk, dv = _bwd(scale, causal, bq, bk, interpret, mxu, res, (do, dlse))
    return dq, dk, dv, None, None


_flash_pair.defvjp(_flash_pair_fwd_rule, _flash_pair_bwd_rule)


def flash_attention_with_lse(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False,
    q_offset=0, k_offset=0,
    block_q: int = 512, block_k: int = 512,
    interpret: Optional[bool] = None,
    precision: str = "default",
) -> Tuple[jax.Array, jax.Array]:
    """flash attention returning (out, lse) — lse: [B, L, H] fp32.
    Differentiable in q/k/v including through lse (the cp ring merges
    per-device partials with lse weights, so its VJP needs dlse)."""
    out, lse, meta = _run_padded(q, k, v, causal, q_offset, k_offset,
                                 block_q, block_k, interpret, precision,
                                 with_lse=True)
    return out, lse


def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = False,
    q_offset=0, k_offset=0,
    block_q: int = 512, block_k: int = 512,
    interpret: Optional[bool] = None,
    precision: str = "default",
) -> jax.Array:
    """Differentiable flash attention, [B, L, H, D] in and out."""
    out, _, _ = _run_padded(q, k, v, causal, q_offset, k_offset,
                            block_q, block_k, interpret, precision,
                            with_lse=False)
    return out


def _run_padded(q, k, v, causal, q_offset, k_offset, block_q, block_k,
                interpret, precision, with_lse):
    if interpret is None:
        interpret = not _on_tpu()
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    bq = min(block_q, _round_up(Lq, 8))
    bk = min(block_k, _round_up(Lk, 8))
    Lq_p, Lk_p = _round_up(Lq, bq), _round_up(Lk, bk)
    D_p = _round_up(D, 128)

    def to_bh(x, L, L_p):
        x = jnp.moveaxis(x, 2, 1).reshape(B * H, L, D)
        return jnp.pad(x, ((0, 0), (0, L_p - L), (0, D_p - D)))

    qp, kp, vp = to_bh(q, Lq, Lq_p), to_bh(k, Lk, Lk_p), to_bh(v, Lk, Lk_p)

    if with_lse:
        out, lse = _flash_pair(qp, kp, vp, scale, causal, q_offset,
                               k_offset, bq, bk, interpret, precision)
    else:
        out = _flash(qp, kp, vp, scale, causal, q_offset, k_offset, bq, bk,
                     interpret, precision)
        lse = None
    out = out[:, :Lq, :D].reshape(B, H, Lq, D)
    out = jnp.moveaxis(out, 1, 2)
    if lse is not None:
        lse = lse[:, :Lq].reshape(B, H, Lq)
        lse = jnp.moveaxis(lse, 1, 2)          # [B, L, H]
    return out, lse, (bq, bk)
