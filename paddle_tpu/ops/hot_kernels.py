"""Fused Pallas kernels for the persistent hot-embedding tier.

The PAPER.md north star says "PHI sparse kernels lower to Pallas"; the
PR 6 tier left the warm path as three separate XLA ops — two bucket-row
gathers for the probe (ps/device_hash.py ``dynamic_map_lookup``), a row
gather for the pull and a unique/gather/update/scatter chain for the
push — each materializing its [n, ·] intermediates through HBM. This
module fuses them into two kernels (the GPUPS HashTable::get /
update_value analogues, optimizer.cuh.h one-thread-per-row shape):

- :func:`hot_probe_gather` — bucketized linear-probe lookup FUSED with
  the value-row gather: the probe's bucket lines and the matched row's
  value line are touched in one kernel pass, the [n, B] bucket
  intermediates never leave VMEM. Grid is (key-block × bank): with the
  map's NUMA-style banks each program loads ONE bank's bucket region
  and ONE bank's row block — the per-program VMEM footprint is
  ``map_bytes/banks + state_bytes/banks``, which is what makes the
  fused formulation fit on-chip at production capacities.
- :func:`hot_scatter_apply` — the push half: in-batch dedup'd gradients
  (the merge_grad unique+segment-sum, identical to
  ``cache_push_sparse``) feed a kernel that walks the touched rows
  once — read row, apply the f32-sealed CTR rule
  (ops/sparse_optimizer.py ``fused_row_update``, the ONE shared
  definition), write row — so only O(batch) rows cross HBM and the
  gathered/updated [n, width] intermediates never materialize.

Both kernels run ``interpret=True`` off-TPU (the CPU CI fallback — the
kernel body is staged as ordinary jax ops, so it compiles and stays
bit-identical); the jnp formulation remains the default off-TPU AND the
reference oracle behind ``HotTierConfig.kernels`` ("auto" | "pallas" |
"jnp"). Bit-parity contract: the kernels share the hash math
(``dynamic_probe_buckets``) and the rule math (``fused_row_update``)
with the jnp path by IMPORT, not by copy — tests/test_hot_kernels.py
pins Pallas(interpret) ≡ jnp ≡ the host engines for adagrad and adam,
unaligned n included.

Known TPU caveat (MEASURED.md discipline): the in-kernel gathers and
the per-row ``fori_loop`` in the scatter kernel are Mosaic
dynamic-indexing paths whose relative cost is unmeasured on real
silicon — the CPU CI box only proves correctness (interpret mode). Keep
``kernels="auto"`` (jnp off-TPU) for performance work until the chip
rung lands.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..core.enforce import enforce
from .sparse_optimizer import fused_row_update, rule_state_dim

__all__ = ["hot_probe_gather", "hot_probe", "hot_scatter_apply",
           "resolve_hot_kernels"]


def resolve_hot_kernels(mode: str) -> bool:
    """Resolve HotTierConfig.kernels → use the Pallas kernels? "auto"
    picks Pallas on TPU (the chip the kernels exist for) and the jnp
    reference path elsewhere; "pallas" forces the kernels (interpret
    mode off-TPU — the parity/CI configuration); "jnp" forces the
    reference path (the oracle)."""
    enforce(mode in ("auto", "pallas", "jnp"),
            f"kernels must be 'auto', 'pallas' or 'jnp', got {mode!r}")
    if mode == "auto":
        return jax.default_backend() == "tpu"
    return mode == "pallas"


def _interp(interpret: Optional[bool]) -> bool:
    # trace-time config (a python bool/None, never a tracer)
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


def _probe_body(maph, mapl, mapr, seed, hi, lo, probe_buckets: int,
                nbuckets: int, banks: int, bank: Optional[jax.Array]):
    """The in-kernel probe: identical hash/compare/select math as
    ``dynamic_map_lookup`` (shared ``dynamic_probe_buckets``), operating
    on ONE bank's bucket region (``bank`` = this program's bank id, or
    None for the unbanked full region)."""
    from ..ps.device_hash import dynamic_probe_buckets

    if bank is None:
        buckets = dynamic_probe_buckets(nbuckets, hi, lo, seed,
                                        probe_buckets, banks)
    else:
        # region-relative: the refs hold only this bank's [nbpb, B]
        # slice, so probe with the LOCAL window (banks=1 of the region)
        buckets = dynamic_probe_buckets(nbuckets // banks, hi, lo, seed,
                                        probe_buckets, 1)
    found = jnp.full(hi.shape, -1, jnp.int32)
    for b in buckets:
        bh = jnp.take(maph, b, axis=0)      # [bn, B] — stays in VMEM
        bl = jnp.take(mapl, b, axis=0)
        br = jnp.take(mapr, b, axis=0)
        match = (bh == hi[:, None]) & (bl == lo[:, None]) & (br >= 0)
        hit = jnp.max(jnp.where(match, br, -1), axis=1)
        found = jnp.where(found >= 0, found, hit)
    return found


def _bank_of_dev(hi: jax.Array, lo: jax.Array, banks: int) -> jax.Array:
    from ..ps.device_hash import _BANK_SEED, _mix32

    return (_mix32(hi, lo, jnp.uint32(_BANK_SEED))
            & jnp.uint32(banks - 1)).astype(jnp.int32)


# graftlint: hot-path
def hot_probe_gather(
    map_state: Dict[str, jax.Array],
    keys_hi: jax.Array,   # [n] uint32
    keys_lo: jax.Array,   # [n] uint32
    tier_state: Dict[str, jax.Array],
    *,
    probe_buckets: int,
    banks: int = 1,
    block: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Fused probe+gather: keys → (rows [n] i32, −1 = missing;
    pulled [n, 1+embedx_dim] f32, zeros for missing rows) in ONE kernel
    pass. Bit-identical to ``dynamic_map_lookup`` + ``cache_pull``.

    With ``banks > 1`` the grid is (key-block, bank): each program sees
    one bank's bucket region and one bank's row block, and only lanes
    whose key hashes to that bank contribute (the tier's allocation
    contract places a key's row inside its bank's row block, so the
    bank-local gather is total). Output blocks are revisited across the
    bank dimension and merged with ``where`` — the standard Pallas
    grid-reduction pattern.
    """
    n = keys_hi.shape[0]
    nbuckets, bslots = map_state["row"].shape
    C = tier_state["embed_w"].shape[0]
    xd = tier_state["embedx_w"].shape[1]
    enforce(C % banks == 0 and nbuckets % banks == 0,
            f"capacity {C} / nbuckets {nbuckets} must divide banks {banks}")
    Cb = C // banks
    nbpb = nbuckets // banks
    seed2d = map_state["seed"].reshape(1, 1)
    bn = min(block, n)
    grid = (pl.cdiv(n, bn), banks)

    def kern(seed_ref, hi_ref, lo_ref, maph_ref, mapl_ref, mapr_ref,
             ew_ref, xw_ref, o_rows, o_pull):
        bank = pl.program_id(1)
        hi = hi_ref[...]
        lo = lo_ref[...]
        seed = seed_ref[0, 0]
        found = _probe_body(maph_ref[...], mapl_ref[...], mapr_ref[...],
                            seed, hi, lo, probe_buckets, nbuckets, banks,
                            bank if banks > 1 else None)
        # bank-local gather: rows of this bank live in [bank*Cb, ..)
        loc = found - bank * Cb if banks > 1 else found
        safe = jnp.clip(loc, 0, Cb - 1)
        pulled = jnp.concatenate(
            [jnp.take(ew_ref[...], safe, axis=0),
             jnp.take(xw_ref[...], safe, axis=0)], axis=1)
        pulled = jnp.where((found >= 0)[:, None], pulled, 0.0)
        if banks > 1:
            mine = _bank_of_dev(hi, lo, banks) == bank
            # revisit-merge: bank 0 initializes, later banks fold in
            @pl.when(bank == 0)
            def _():
                o_rows[...] = jnp.where(mine, found, -1)
                o_pull[...] = jnp.where(mine[:, None], pulled, 0.0)

            @pl.when(bank > 0)
            def _():
                o_rows[...] = jnp.where(mine, found, o_rows[...])
                o_pull[...] = jnp.where(mine[:, None], pulled, o_pull[...])
        else:
            o_rows[...] = found
            o_pull[...] = pulled

    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, b: (0, 0)),            # seed
            pl.BlockSpec((bn,), lambda i, b: (i,)),               # hi
            pl.BlockSpec((bn,), lambda i, b: (i,)),               # lo
            pl.BlockSpec((nbpb, bslots), lambda i, b: (b, 0)),    # map hi
            pl.BlockSpec((nbpb, bslots), lambda i, b: (b, 0)),    # map lo
            pl.BlockSpec((nbpb, bslots), lambda i, b: (b, 0)),    # map row
            pl.BlockSpec((Cb, 1), lambda i, b: (b, 0)),           # embed_w
            pl.BlockSpec((Cb, xd), lambda i, b: (b, 0)),          # embedx_w
        ],
        out_specs=[
            pl.BlockSpec((bn,), lambda i, b: (i,)),
            pl.BlockSpec((bn, 1 + xd), lambda i, b: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((n, 1 + xd), jnp.float32),
        ],
        interpret=_interp(interpret),
    )(seed2d, keys_hi.astype(jnp.uint32), keys_lo.astype(jnp.uint32),
      map_state["hi"], map_state["lo"], map_state["row"],
      tier_state["embed_w"], tier_state["embedx_w"])
    return out[0], out[1]


# graftlint: hot-path
def hot_probe(
    map_state: Dict[str, jax.Array],
    keys_hi: jax.Array,
    keys_lo: jax.Array,
    *,
    probe_buckets: int,
    banks: int = 1,
    block: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Probe-only kernel (rows [n] i32, −1 = missing): the sharded
    tier's LOCAL half — each device resolves its batch slice against
    the replicated map, then the row exchange (not the gather) crosses
    chips, so there is nothing to fuse the gather into here."""
    n = keys_hi.shape[0]
    nbuckets, bslots = map_state["row"].shape
    enforce(nbuckets % banks == 0,
            f"nbuckets {nbuckets} must divide banks {banks}")
    nbpb = nbuckets // banks
    seed2d = map_state["seed"].reshape(1, 1)
    bn = min(block, n)
    grid = (pl.cdiv(n, bn), banks)

    def kern(seed_ref, hi_ref, lo_ref, maph_ref, mapl_ref, mapr_ref,
             o_rows):
        bank = pl.program_id(1)
        hi = hi_ref[...]
        lo = lo_ref[...]
        found = _probe_body(maph_ref[...], mapl_ref[...], mapr_ref[...],
                            seed_ref[0, 0], hi, lo, probe_buckets,
                            nbuckets, banks, bank if banks > 1 else None)
        if banks > 1:
            mine = _bank_of_dev(hi, lo, banks) == bank
            @pl.when(bank == 0)
            def _():
                o_rows[...] = jnp.where(mine, found, -1)

            @pl.when(bank > 0)
            def _():
                o_rows[...] = jnp.where(mine, found, o_rows[...])
        else:
            o_rows[...] = found

    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1), lambda i, b: (0, 0)),
            pl.BlockSpec((bn,), lambda i, b: (i,)),
            pl.BlockSpec((bn,), lambda i, b: (i,)),
            pl.BlockSpec((nbpb, bslots), lambda i, b: (b, 0)),
            pl.BlockSpec((nbpb, bslots), lambda i, b: (b, 0)),
            pl.BlockSpec((nbpb, bslots), lambda i, b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((bn,), lambda i, b: (i,)),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
        interpret=_interp(interpret),
    )(seed2d, keys_hi.astype(jnp.uint32), keys_lo.astype(jnp.uint32),
      map_state["hi"], map_state["lo"], map_state["row"])


_COLS = ("show", "click", "embed_w", "embed_state", "embedx_w",
         "embedx_state", "has_embedx")


# graftlint: hot-path
def hot_scatter_apply(
    state: Dict[str, jax.Array],
    rows: jax.Array,    # [n] tier rows (may repeat; ≥ C = dropped)
    grads: jax.Array,   # [n, 1+dim] embed_g ++ embedx_g
    shows: jax.Array,   # [n]
    clicks: jax.Array,  # [n]
    cfg,                # embedding_cache.CacheConfig
    *,
    interpret: Optional[bool] = None,
) -> Dict[str, jax.Array]:
    """Fused push: merge_grad dedup (unique + segment-sum — EXACTLY
    ``cache_push_sparse``'s prologue, so the f32 merge association is
    identical) → ONE kernel that walks the deduped rows, applies the
    sealed CTR rule (``fused_row_update`` per row — the optimizer.cuh.h
    one-thread-per-row shape) and scatters the updated row back in
    place. Only the touched rows cross HBM; the gathered/updated
    [n, width] intermediates of the jnp path never materialize.

    Drop-in ``cache_push`` replacement with sparse-mode semantics —
    bit-identical to ``cache_push_sparse`` with the jnp rule path
    (tests/test_hot_kernels.py pins it for adagrad, std_adagrad and
    adam, unaligned n included)."""
    from ..ps.embedding_cache import merge_sparse_grads

    n = rows.shape[0]
    C = state["embed_w"].shape[0]
    dim = state["embedx_w"].shape[1]
    sgd = cfg.sgd

    # merge_grad — the ONE shared dedup (bit-parity with cache_push_sparse)
    uniq, show_sum, click_sum, g = merge_sparse_grads(rows, grads, shows,
                                                      clicks, C)

    es = rule_state_dim(cfg.embed_rule, 1)
    xs = rule_state_dim(cfg.embedx_rule, dim)
    enforce(state["embed_state"].shape[1] == es
            and state["embedx_state"].shape[1] == xs,
            f"optimizer-state width mismatch: embed_state "
            f"{state['embed_state'].shape} vs {es}, embedx_state "
            f"{state['embedx_state'].shape} vs {xs}")
    # zero-width optimizer state (naive rule) → one dummy column through
    # the kernel, original restored after (the ctr_sparse_rows pattern)
    kstate = dict(state)
    if es == 0:
        kstate["embed_state"] = jnp.zeros((C, 1), jnp.float32)
    if xs == 0:
        kstate["embedx_state"] = jnp.zeros((C, 1), jnp.float32)
    widths = {k: kstate[k].shape[1] if kstate[k].ndim == 2 else None
              for k in _COLS}

    upd = functools.partial(
        fused_row_update, embed_rule=cfg.embed_rule,
        embedx_rule=cfg.embedx_rule, dim=dim, lr=sgd.learning_rate,
        initial_g2sum=sgd.initial_g2sum, wmin=sgd.weight_bounds[0],
        wmax=sgd.weight_bounds[1], beta1=sgd.beta1, beta2=sgd.beta2,
        eps=sgd.ada_epsilon, nonclk_coeff=cfg.nonclk_coeff,
        click_coeff=cfg.click_coeff, embedx_threshold=cfg.embedx_threshold,
        create_applies_grad=cfg.create_applies_grad)

    def kern(*refs):
        in_refs = refs[:7]
        rows_ref, ds_ref, dc_ref, ge_ref, gx_ref = refs[7:12]
        out_refs = refs[12:]
        # untouched rows round-trip bit-for-bit: start from the input
        for i_ref, o_ref in zip(in_refs, out_refs):
            o_ref[...] = i_ref[...]

        def body(i, carry):
            r = rows_ref[i]

            # sentinel C (padding / missing) AND negatives drop — the
            # jnp path's scatter ``mode="drop"`` semantics
            @pl.when(jnp.logical_and(r >= 0, r < C))
            def _():
                rr = jnp.clip(r, 0, C - 1)
                cols = []
                for ref in in_refs:
                    if len(ref.shape) == 1:
                        cols.append(ref[pl.ds(rr, 1)])
                    else:
                        cols.append(ref[pl.ds(rr, 1), :])
                outs = upd(*cols, ds_ref[pl.ds(i, 1)], dc_ref[pl.ds(i, 1)],
                           ge_ref[pl.ds(i, 1), :], gx_ref[pl.ds(i, 1), :])
                for o_ref, val in zip(out_refs, outs):
                    if len(o_ref.shape) == 1:
                        o_ref[pl.ds(rr, 1)] = val
                    else:
                        o_ref[pl.ds(rr, 1), :] = val
            return carry

        jax.lax.fori_loop(0, n, body, 0)

    def col_spec(k):
        w = widths[k]
        if w is None:
            return pl.BlockSpec((C,), lambda: (0,))
        return pl.BlockSpec((C, w), lambda: (0, 0))

    state_specs = [col_spec(k) for k in _COLS]
    out_shapes = [jax.ShapeDtypeStruct(kstate[k].shape, kstate[k].dtype)
                  for k in _COLS]
    out = pl.pallas_call(
        kern,
        grid=(),
        in_specs=state_specs + [
            pl.BlockSpec((n,), lambda: (0,)),        # uniq rows
            pl.BlockSpec((n,), lambda: (0,)),        # show deltas
            pl.BlockSpec((n,), lambda: (0,)),        # click deltas
            pl.BlockSpec((n, 1), lambda: (0, 0)),    # embed grads
            pl.BlockSpec((n, dim), lambda: (0, 0)),  # embedx grads
        ],
        out_specs=state_specs,
        out_shape=out_shapes,
        interpret=_interp(interpret),
    )(*[kstate[k] for k in _COLS], uniq, show_sum, click_sum,
      g[:, :1], g[:, 1:])
    new = dict(zip(_COLS, out))
    if es == 0:
        new["embed_state"] = state["embed_state"]
    if xs == 0:
        new["embedx_state"] = state["embedx_state"]
    return new
