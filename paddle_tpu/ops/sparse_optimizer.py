"""Pallas fused sparse-optimizer kernel (CTR AdaGrad row update).

The reference applies its sparse optimizer on-device inside the
hashtable update kernels (`/root/reference/paddle/fluid/framework/fleet/
heter_ps/optimizer.cuh.h:27-100` — update_lr/update_mf/update_value with
show/click coeffs, bounds, lazy mf creation), one GPU thread per row.
The TPU decomposition is different: random-access gather/scatter stays
on XLA (the hardware's bulk path — per-row DMA loops in Pallas
serialize), and the PER-ROW OPTIMIZER MATH between gather and scatter is
this one fused Pallas kernel: all seven state columns of a block of
touched rows update in a single VMEM pass (one read + one write per
operand instead of XLA's per-op fusion groups).

Used by ``ps.embedding_cache.cache_push`` on TPU (jnp fallback
elsewhere / interpret mode in tests); bit-parity with the jnp path is
tested in tests/test_sparse_optimizer.py.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["ctr_adagrad_rows"]


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _kernel(show_ref, click_ref, ew_ref, eg2_ref, xw_ref, xg2_ref, has_ref,
            dshow_ref, dclick_ref, ge_ref, gx_ref,
            o_show, o_click, o_ew, o_eg2, o_xw, o_xg2, o_has,
            *, lr, initial_g2sum, wmin, wmax, nonclk_coeff, click_coeff,
            embedx_threshold):
    show = show_ref[...] + dshow_ref[...]
    click = click_ref[...] + dclick_ref[...]
    scale = jnp.maximum(dshow_ref[...], 1e-10)[:, None]

    # embed (1-d) AdaGrad — sparse_sgd_rule.cc:87 / optimizer.cuh.h:35
    ge = ge_ref[...] / scale
    eg2 = eg2_ref[...]
    ratio_e = jnp.sqrt(initial_g2sum / (initial_g2sum + eg2))
    ew = jnp.clip(ew_ref[...] - lr * ge * ratio_e, wmin, wmax)
    eg2_new = eg2 + jnp.mean(ge * ge, axis=1, keepdims=True)

    # lazy embedx creation on the show/click score (optimizer.cuh.h:81)
    score = (show - click) * nonclk_coeff + click * click_coeff
    had = has_ref[...] > 0
    create = jnp.logical_and(jnp.logical_not(had),
                             score >= embedx_threshold)
    # embedx (dim-d) AdaGrad, applied only where mf already existed
    gx = gx_ref[...] / scale
    xg2 = xg2_ref[...]
    ratio_x = jnp.sqrt(initial_g2sum / (initial_g2sum + xg2))
    xw_new = jnp.clip(xw_ref[...] - lr * gx * ratio_x, wmin, wmax)
    xg2_new = xg2 + jnp.mean(gx * gx, axis=1, keepdims=True)

    o_show[...] = show
    o_click[...] = click
    o_ew[...] = ew
    o_eg2[...] = eg2_new
    o_xw[...] = jnp.where(had[:, None], xw_new, xw_ref[...])
    o_xg2[...] = jnp.where(had[:, None], xg2_new, xg2_ref[...])
    o_has[...] = jnp.where(create, 1.0, has_ref[...])


def ctr_adagrad_rows(
    rows_state: Tuple[jax.Array, ...],  # show, click, ew, eg2, xw, xg2, has
    dshow: jax.Array,   # [n] merged show deltas
    dclick: jax.Array,  # [n]
    g_embed: jax.Array,   # [n, 1] merged embed grads
    g_embedx: jax.Array,  # [n, dim]
    *,
    lr: float, initial_g2sum: float, weight_bounds: Tuple[float, float],
    nonclk_coeff: float, click_coeff: float, embedx_threshold: float,
    block: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, ...]:
    """Fused per-row CTR AdaGrad over gathered rows; returns the updated
    seven state columns in the same order. Rows are pre-merged uniques
    (the caller's segment-sum); padding rows are fine — the caller's
    scatter drops them."""
    show, click, ew, eg2, xw, xg2, has = rows_state
    n = show.shape[0]
    dim = xw.shape[1]
    if interpret is None:
        interpret = not _on_tpu()
    bn = min(block, n)
    grid = (pl.cdiv(n, bn),)

    def spec1(): return pl.BlockSpec((bn,), lambda i: (i,))
    def spec2(d): return pl.BlockSpec((bn, d), lambda i: (i, 0))

    kern = functools.partial(
        _kernel, lr=lr, initial_g2sum=initial_g2sum,
        wmin=weight_bounds[0], wmax=weight_bounds[1],
        nonclk_coeff=nonclk_coeff, click_coeff=click_coeff,
        embedx_threshold=embedx_threshold)
    out_shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                  for a in (show, click, ew, eg2, xw, xg2, has)]
    out_specs = [spec1(), spec1(), spec2(1), spec2(1), spec2(dim),
                 spec2(1), spec1()]
    in_specs = [spec1(), spec1(), spec2(1), spec2(1), spec2(dim), spec2(1),
                spec1(), spec1(), spec1(), spec2(1), spec2(dim)]
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(show, click, ew, eg2, xw, xg2, has, dshow, dclick, g_embed, g_embedx)
