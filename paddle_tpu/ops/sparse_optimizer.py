"""Pallas fused sparse-optimizer kernel (per-row CTR update, all rules).

The reference applies its sparse optimizer on-device inside the
hashtable update kernels (`/root/reference/paddle/fluid/framework/fleet/
heter_ps/optimizer.cuh.h:27-100` — update_lr/update_mf/update_value with
show/click coeffs, bounds, lazy mf creation), one GPU thread per row;
the CPU server supports the full rule family (sparse_sgd_rule.h:27-135:
naive / AdaGrad shared-g2sum / StdAdaGrad per-dim / Adam). The TPU
decomposition: random-access gather/scatter stays on XLA (the hardware's
bulk path — per-row DMA loops in Pallas serialize), and the PER-ROW
OPTIMIZER MATH between gather and scatter is one fused Pallas kernel:
every state column of a block of touched rows updates in a single VMEM
pass. All four reference rules are supported for both the embed (1-d)
and embedx (dim-d) blocks; the rule math lives in ``rule_update`` which
is shared verbatim by the kernel body and the jnp fallback
(``ps.embedding_cache.cache_push`` uses the kernel on TPU, jnp
elsewhere; bit-parity is tested in tests/test_sparse_optimizer.py).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..core.enforce import enforce

__all__ = ["ctr_sparse_rows", "rule_update", "rule_state_dim",
           "rule_init_state"]


def rule_state_dim(rule: str, dim: int) -> int:
    """Optimizer-state floats per feature (sparse_sgd_rule slot dims)."""
    return {"naive": 0, "adagrad": 1, "std_adagrad": dim,
            "adam": 2 * dim + 2}[rule]


def rule_init_state(rule: str, n: int, dim: int, *, beta1: float,
                    beta2: float):
    """Fresh-feature optimizer state (zeros; Adam's beta powers start at
    beta1/beta2 — sparse_sgd_rule.cc InitValueWork)."""
    sd = rule_state_dim(rule, dim)
    st = jnp.zeros((n, sd), jnp.float32)
    if rule == "adam":
        st = st.at[:, 2 * dim].set(beta1).at[:, 2 * dim + 1].set(beta2)
    return st


def _m32(a, b):
    """f32 multiply with PINNED operand binding and IEEE rounding.

    The sparse rules must produce the same bits as the host engines
    (csrc builds with -ffp-contract=off; numpy never contracts) — the
    hot embedding tier round-trips rows between them. Two XLA behaviors
    break that on a plain ``a * b`` chain:

    - LLVM contracts a single-use `mul` feeding an `add`/`sub` into one
      FMA (no intermediate rounding);
    - the HLO algebraic simplifier re-associates scalar-constant mul
      chains (``lr*sg*ratio`` becomes ``sg*(lr*ratio)`` — the constant
      sinks onto the narrower broadcast operand).

    Every pure seal was tried and folded away (optimization_barrier,
    reduce_precision(8,23), bitcast pairs, min/max(±inf), +0.0); what
    holds is making the product MULTI-USE via ``t + 0*t``: LLVM only
    forms fmuladd from a single-use mul, XLA keeps ``0*x`` under strict
    inf/nan semantics, and the add consumer breaks the mul-chain pattern
    the re-associator matches on. Cost: one extra fused mul+add per
    element. Known edge: t=±inf becomes NaN here (0·inf) — already
    -diverged training only, and the nan/inf guard surfaces it anyway."""
    t = a * b
    return t + jnp.float32(0.0) * t


def rule_update(rule: str, w, state, g, scale, *, lr, initial_g2sum,
                wmin, wmax, beta1, beta2, eps):
    """One batched rule step on touched rows: (w [n,d], state [n,sd],
    g [n,d] merged grads, scale [n,1] push_show) -> (w', state').
    Mirrors this repo's host rules (ps/sgd_rule.py) exactly — which
    follow sparse_sgd_rule.cc (SURVEY Appendix A.2) except that Adam
    adds epsilon to the bias-corrected sqrt(v_hat) rather than the
    reference's raw sqrt(v) (an eps-placement difference only). Adam
    ignores the scale like the reference."""
    clip = lambda x: jnp.clip(x, wmin, wmax)
    lrf = jnp.float32(lr)
    if rule == "naive":
        return clip(w - _m32(lrf, g)), state
    if rule == "adagrad":  # one shared g2sum per feature
        sg = g / scale
        ratio = jnp.sqrt(initial_g2sum / (initial_g2sum + state))
        w2 = clip(w - _m32(_m32(lrf, sg), ratio))
        # g2sum accumulates in the native table's association (sequential
        # over dims, ONE divide at the end — sparse_table.h kRuleAdaGrad);
        # jnp.mean's tree reduce re-associates the f32 sum and breaks
        # bit-parity with the host/PS rows the hot tier must round-trip
        add = _m32(sg[:, 0], sg[:, 0])
        for i in range(1, g.shape[1]):
            add = add + _m32(sg[:, i], sg[:, i])
        return w2, state + (add / jnp.float32(g.shape[1]))[:, None]
    if rule == "std_adagrad":  # per-dim g2sum
        sg = g / scale
        ratio = jnp.sqrt(initial_g2sum / (initial_g2sum + state))
        return (clip(w - _m32(_m32(lrf, sg), ratio)), state + _m32(sg, sg))
    if rule == "adam":
        d = w.shape[1]
        m, v = state[:, :d], state[:, d:2 * d]
        b1p, b2p = state[:, 2 * d:2 * d + 1], state[:, 2 * d + 1:2 * d + 2]
        # (1 - beta) must round through f32 like the native rule's
        # `1.0f - cfg.beta1` — the python-double difference (1e-8 on
        # beta1=0.9) compounds into m/v and breaks row bit-parity
        b1f, b2f = jnp.float32(beta1), jnp.float32(beta2)
        one = jnp.float32(1.0)
        m2 = _m32(b1f, m) + _m32(one - b1f, g)
        v2 = _m32(b2f, v) + _m32(_m32(one - b2f, g), g)
        m_hat = m2 / (one - b1p)
        v_hat = v2 / (one - b2p)
        w2 = clip(w - _m32(lrf, m_hat) / (jnp.sqrt(v_hat) + eps))
        return w2, jnp.concatenate(
            [m2, v2, _m32(b1p, b1f), _m32(b2p, b2f)], axis=1)
    raise KeyError(f"unknown sparse sgd rule {rule!r}")


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def fused_row_update(show, click, ew, estate, xw, xstate, has,
                     dshow, dclick, ge, gx,
                     *, embed_rule, embedx_rule, dim, lr, initial_g2sum,
                     wmin, wmax, beta1, beta2, eps, nonclk_coeff,
                     click_coeff, embedx_threshold, create_applies_grad):
    """The complete per-row CTR update on plain arrays (touched rows,
    pre-merged): show/click accumulation, embed rule step, lazy embedx
    creation, embedx rule step. ONE definition shared by the Pallas
    kernel body and the jnp fallback — divergence between the two paths
    is structurally impossible. Returns the seven updated columns.

    State arrays may carry one extra dummy column when the rule is
    stateless (the kernel's block specs need width >= 1); the rule
    ignores it and it round-trips unchanged."""
    upd = functools.partial(rule_update, lr=lr, initial_g2sum=initial_g2sum,
                            wmin=wmin, wmax=wmax, beta1=beta1, beta2=beta2,
                            eps=eps)
    # Mosaic lowers [n] -> [n,1] reshapes only for 32-bit types, so bool
    # masks broadcast to columns via f32 + compare, never via i1 reshape
    col = lambda m: m.astype(jnp.float32)[:, None] > 0.5

    show_new = show + dshow
    click_new = click + dclick
    scale = jnp.maximum(dshow, 1e-10)[:, None]

    es = rule_state_dim(embed_rule, 1)
    xs = rule_state_dim(embedx_rule, dim)
    ew_new, es_new = upd(embed_rule, ew, estate[:, :max(es, 1)], ge, scale)

    # lazy embedx creation on the show/click score: created rows start
    # from INIT state; create_applies_grad selects CPU (create + apply,
    # ctr_accessor.cc order) vs GPU (create only, optimizer.cuh.h:81-94)
    # the host computes this over totals too (pstpu::show_click_score);
    # both products sealed so the create-threshold compare sees the same
    # bits as the PS and creation fires on the same push
    score = (_m32(show_new - click_new, jnp.float32(nonclk_coeff))
             + _m32(click_new, jnp.float32(click_coeff)))
    had = has > 0
    create = jnp.logical_and(jnp.logical_not(had),
                             score >= embedx_threshold)
    apply_mask = jnp.logical_or(had, create) if create_applies_grad else had
    n = show.shape[0]
    if xs > 0:
        init = rule_init_state(embedx_rule, n, dim, beta1=beta1, beta2=beta2)
        st_base = jnp.where(col(create), init, xstate)
    else:
        st_base = xstate[:, :max(xs, 1)]
    xw_new, xs_new = upd(embedx_rule, xw, st_base, gx, scale)

    return (show_new, click_new, ew_new,
            es_new if es > 0 else estate,
            jnp.where(col(apply_mask), xw_new, xw),
            jnp.where(col(apply_mask), xs_new, st_base) if xs > 0 else xstate,
            jnp.where(create, 1.0, has))


def _kernel(show_ref, click_ref, ew_ref, es_ref, xw_ref, xs_ref, has_ref,
            dshow_ref, dclick_ref, ge_ref, gx_ref,
            o_show, o_click, o_ew, o_es, o_xw, o_xs, o_has,
            **fused_kwargs):
    outs = fused_row_update(
        show_ref[...], click_ref[...], ew_ref[...], es_ref[...],
        xw_ref[...], xs_ref[...], has_ref[...],
        dshow_ref[...], dclick_ref[...], ge_ref[...], gx_ref[...],
        **fused_kwargs)
    for ref, val in zip((o_show, o_click, o_ew, o_es, o_xw, o_xs, o_has),
                        outs):
        ref[...] = val


def ctr_sparse_rows(
    rows_state: Tuple[jax.Array, ...],  # show, click, ew, estate, xw, xstate, has
    dshow: jax.Array,   # [n] merged show deltas
    dclick: jax.Array,  # [n]
    g_embed: jax.Array,   # [n, 1] merged embed grads
    g_embedx: jax.Array,  # [n, dim]
    *,
    embed_rule: str, embedx_rule: str,
    lr: float, initial_g2sum: float, weight_bounds: Tuple[float, float],
    beta1: float, beta2: float, eps: float,
    nonclk_coeff: float, click_coeff: float, embedx_threshold: float,
    create_applies_grad: bool = True,
    block: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, ...]:
    """Fused per-row CTR update over gathered rows; returns the updated
    seven state columns in the same order. Rows are pre-merged uniques
    (the caller's segment-sum); padding rows are fine — the caller's
    scatter drops them. State columns may be zero-width (naive rule): a
    one-column dummy is threaded through the kernel and sliced away."""
    show, click, ew, estate, xw, xstate, has = rows_state
    n = show.shape[0]
    dim = xw.shape[1]
    es = rule_state_dim(embed_rule, 1)
    xs = rule_state_dim(embedx_rule, dim)
    # enforce (not assert): a mismatched cache/table state layout must
    # fail loudly even under python -O, not corrupt rows silently
    enforce(estate.shape[1] == es and xstate.shape[1] == xs,
            f"optimizer-state width mismatch: estate {estate.shape} vs "
            f"{es}, xstate {xstate.shape} vs {xs}")
    if interpret is None:
        interpret = not _on_tpu()
    # zero-width state -> one dummy column through the kernel
    estate_k = estate if es > 0 else jnp.zeros((n, 1), jnp.float32)
    xstate_k = xstate if xs > 0 else jnp.zeros((n, 1), jnp.float32)
    wes, wxs = estate_k.shape[1], xstate_k.shape[1]
    bn = min(block, n)
    grid = (pl.cdiv(n, bn),)

    def spec1(): return pl.BlockSpec((bn,), lambda i: (i,))
    def spec2(d): return pl.BlockSpec((bn, d), lambda i: (i, 0))

    kern = functools.partial(
        _kernel, embed_rule=embed_rule, embedx_rule=embedx_rule, dim=dim,
        lr=lr, initial_g2sum=initial_g2sum,
        wmin=weight_bounds[0], wmax=weight_bounds[1],
        beta1=beta1, beta2=beta2, eps=eps,
        nonclk_coeff=nonclk_coeff, click_coeff=click_coeff,
        embedx_threshold=embedx_threshold,
        create_applies_grad=create_applies_grad)
    out_shapes = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                  for a in (show, click, ew, estate_k, xw, xstate_k, has)]
    out_specs = [spec1(), spec1(), spec2(1), spec2(wes), spec2(dim),
                 spec2(wxs), spec1()]
    in_specs = [spec1(), spec1(), spec2(1), spec2(wes), spec2(dim),
                spec2(wxs), spec1(), spec1(), spec1(), spec2(1), spec2(dim)]
    out = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(show, click, ew, estate_k, xw, xstate_k, has, dshow, dclick,
      g_embed, g_embedx)
    o_show, o_click, o_ew, o_es, o_xw, o_xs, o_has = out
    if es == 0:
        o_es = estate
    if xs == 0:
        o_xs = xstate
    return o_show, o_click, o_ew, o_es, o_xw, o_xs, o_has
