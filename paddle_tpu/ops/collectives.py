"""Collective communication over mesh axes.

The TPU-native Communicator replacing the reference's three comm stacks
(SURVEY §2.4): NCCL collective ops (``paddle/fluid/operators/collective/``
— c_allreduce_{sum,max,min,prod}, c_allgather, c_broadcast,
c_reducescatter, alltoall, c_concat, c_split, partial_send/recv), the
eager ``ProcessGroup`` family (``distributed/collective/ProcessGroup.h``),
and the Gloo CPU path. All of them collapse into XLA collectives over
named mesh axes: a "ring_id"/"process group" is an axis name; the compiler
schedules the transfer over ICI inside the step program.

Two execution contexts:
- inside ``shard_map`` (explicit SPMD): these call ``lax.psum`` etc. on
  the bound axis — exact control, used by TP/PP/ring-attention internals;
- outside (GSPMD/pjit): prefer sharding annotations and let XLA insert
  collectives; these wrappers then raise a clear error if the axis is
  unbound rather than silently doing nothing.

The ``ProcessGroup`` class offers the reference's eager API shape
(all_reduce/broadcast/all_gather/…) for porting user code.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..core.enforce import InvalidArgumentError

__all__ = [
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "all_to_all",
    "ppermute",
    "broadcast",
    "reduce",
    "axis_index",
    "axis_size",
    "barrier",
    "split_axis",
    "psum_replicated",
    "spec_reduced_grads",
    "ProcessGroup",
    "ReduceOp",
]

AxisName = Union[str, Sequence[str]]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def all_reduce(x: jax.Array, axis: AxisName, op: str = ReduceOp.SUM) -> jax.Array:
    """c_allreduce_{sum,max,min,prod} → lax.p{sum,max,min,prod}."""
    if op == ReduceOp.SUM:
        return lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis)
    if op == ReduceOp.PROD:
        # no pprod primitive; gather + prod handles zeros/negatives exactly
        return jnp.prod(lax.all_gather(x, axis), axis=0)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis)
    raise InvalidArgumentError(f"unknown reduce op {op!r}")


def all_gather(x: jax.Array, axis: AxisName, concat_axis: int = 0, tiled: bool = True) -> jax.Array:
    """c_allgather / c_concat: gather shards along ``concat_axis``."""
    return lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis: AxisName, scatter_axis: int = 0) -> jax.Array:
    """c_reducescatter: sum across the axis, keep this rank's shard."""
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)


def all_to_all(
    x: jax.Array,
    axis: AxisName,
    split_axis_: int = 0,
    concat_axis: int = 0,
) -> jax.Array:
    """alltoall op (MoE global_scatter/gather building block)."""
    return lax.all_to_all(x, axis, split_axis=split_axis_, concat_axis=concat_axis, tiled=True)


def ppermute(x: jax.Array, axis: str, perm: Sequence[tuple]) -> jax.Array:
    """partial_send/recv pairs → a single compiled permutation
    (PP p2p and ring-attention KV rotation both use this)."""
    return lax.ppermute(x, axis, perm)


def _psum_replicated_impl(x, axis_name):
    """psum of a value whose DOWNSTREAM cotangent is replicated over
    ``axis_name`` (every shard computes the same loss from the summed
    result): the correct per-shard gradient is that cotangent unscaled.
    jax 0.4.x shard_map transposes a plain psum into another psum (with
    either check_rep setting), which would scale such gradients by the
    axis size — the custom VJP pins the identity backward, and stays
    correct under the vma-era semantics too. ``axis_name`` may be one
    axis or a tuple of axes (the mp CE reductions and the hybrid loss
    reduction both route through here — shared by mp_layers/hybrid)."""
    return lax.psum(x, axis_name)


# axis_name is static (a string or tuple), not a differentiable input
psum_replicated = jax.custom_vjp(_psum_replicated_impl, nondiff_argnums=(1,))
psum_replicated.defvjp(
    lambda x, axis_name: (lax.psum(x, axis_name), None),
    lambda axis_name, _, ct: (ct,))


def spec_reduced_grads(grads, specs, mesh_shape) -> jax.Array:
    """Explicit spec-driven gradient reduction for a ``check_rep=False``
    / ``check_vma=False`` shard_map step where autodiff inserts NO
    cross-rank reductions (every differentiated psum pinned via
    :func:`psum_replicated`): each rank then holds only its own partial
    contribution, and the true gradient of a param is the psum over
    every mesh axis the param is NOT sharded on — batch/sequence shards
    and tensor-parallel partials sum to the full gradient, while
    disjoint contributions (pipeline-stage-owned aux params) are zero
    off their owning rank. Axes IN the param's spec hold that rank's
    own shard and are left alone. Shared by the hybrid trainer and the
    TP parity tests (one definition for the next jax-drift fix)."""
    def reduce_one(g, spec):
        in_spec = {a for e in tuple(spec)
                   for a in (e if isinstance(e, tuple) else (e,)) if a}
        red = tuple(a for a in mesh_shape
                    if a not in in_spec and mesh_shape[a] > 1)
        return lax.psum(g, red) if red else g

    return jax.tree_util.tree_map(reduce_one, grads, specs)


def shift(x: jax.Array, axis: str, offset: int = 1) -> jax.Array:
    """Ring rotation by ``offset`` hops (helper over ppermute)."""
    n = lax.axis_size(axis)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def broadcast(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """c_broadcast: all ranks take root's value."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def reduce(x: jax.Array, axis: str, root: int = 0, op: str = ReduceOp.SUM) -> jax.Array:
    """c_reduce: full value on root, zeros elsewhere (SPMD can't have
    rank-dependent shapes, so non-root ranks carry zeros)."""
    total = all_reduce(x, axis, op)
    idx = lax.axis_index(axis)
    return jnp.where(idx == root, total, jnp.zeros_like(total))


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def barrier(axis: str) -> None:
    """Inside a compiled program a barrier is implicit in any collective;
    provided for API parity (BarrierTable / gloo barrier)."""
    return None


def split_axis(x: jax.Array, axis: str, dim: int = -1) -> jax.Array:
    """c_split: each rank keeps its slice of ``dim`` (inverse of
    all_gather). Requires dim divisible by axis size."""
    n = lax.axis_size(axis)
    i = lax.axis_index(axis)
    if x.shape[dim] % n != 0:
        raise InvalidArgumentError(
            f"split_axis: dim {dim} (size {x.shape[dim]}) not divisible by axis {axis!r} size {n}"
        )
    size = x.shape[dim] // n
    return lax.dynamic_slice_in_dim(x, i * size, size, axis=dim)


class ProcessGroup:
    """Eager-API shape of the reference ProcessGroup (ProcessGroup.h:53),
    bound to a mesh axis. Methods are usable inside shard_map'd code;
    results are returned (no Task futures — XLA schedules async)."""

    def __init__(self, axis: str) -> None:
        self.axis = axis

    def all_reduce(self, x, op: str = ReduceOp.SUM):
        return all_reduce(x, self.axis, op)

    def all_gather(self, x, concat_axis: int = 0):
        return all_gather(x, self.axis, concat_axis)

    def reduce_scatter(self, x, scatter_axis: int = 0):
        return reduce_scatter(x, self.axis, scatter_axis)

    def all_to_all(self, x, split_axis_: int = 0, concat_axis: int = 0):
        return all_to_all(x, self.axis, split_axis_, concat_axis)

    def broadcast(self, x, root: int = 0):
        return broadcast(x, self.axis, root)

    def reduce(self, x, root: int = 0, op: str = ReduceOp.SUM):
        return reduce(x, self.axis, root, op)

    def rank(self):
        return axis_index(self.axis)

    def size(self):
        return axis_size(self.axis)

    def barrier(self):
        return barrier(self.axis)
