"""Device-resident graph table: in-graph neighbor sampling and random
walks.

The reference keeps a GPU mirror of the graph for walk generation —
``fleet/heter_ps/graph_gpu_ps_table.h`` (node/edge arrays in device
memory, sample kernels) feeding ``GraphDataGenerator``'s deepwalk-style
walks into training. TPU-native form: the adjacency lives in HBM as a
**degree-capped padded neighbor matrix** (static shapes — XLA needs
them; the cap is explicit and counted, never silent), node ids map to
rows through the same per-pass cuckoo map the embedding cache uses
(ps/device_hash.py), and sampling/walks are pure jax.random programs
that fuse into the training step:

- ``sample_neighbors(state, rng, hi, lo, k)`` — uniform with
  replacement over each node's true neighbors (the GPU
  ``graph_neighbor_sample`` kernel's contract), padded + masked;
- ``random_walk(state, rng, hi, lo, length)`` — ``lax.scan`` of
  gather+sample steps; a walk that reaches a degree-0 or unknown node
  stays there (mask marks the live prefix, the generator's walk
  truncation).

Weighted sampling uses each row's prefix-CDF + ``searchsorted`` —
O(log max_deg) per draw, branch-free.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.enforce import enforce
from ..ps.device_hash import DeviceKeyMap, device_hash_lookup, split_keys

__all__ = ["DeviceGraph"]


class DeviceGraph:
    """Padded-CSR device mirror of a host ``GraphTable``.

    ``state`` pytree (HBM-resident, feed through jitted steps):
      nbr_hi/nbr_lo [N, max_deg] u32   neighbor key halves (padded 0)
      cdf           [N, max_deg] f32   per-row weight prefix-CDF (0 pad)
      deg           [N]          i32   KEPT degree (min(true, max_deg);
                                       truncation is counted in
                                       ``capped_rows``)
      map                              cuckoo node-key→row map
    """

    def __init__(self, state: Dict[str, jax.Array], max_deg: int,
                 capped_rows: int) -> None:
        self.state = state
        self.max_deg = int(max_deg)
        #: rows whose true degree exceeded max_deg (their kept neighbors
        #: are the first max_deg by insertion order) — surfaced, never
        #: silent (the GPU table truncates the same way)
        self.capped_rows = int(capped_rows)

    # -- build (host → HBM; the build_graph_from_cpu role) ---------------

    @staticmethod
    def from_graph_table(graph, max_deg: int = 32,
                         sharding=None) -> "DeviceGraph":
        """Upload a host ``ps/graph_table.py`` GraphTable (or anything
        with ``all_nodes`` + per-node neighbors/weights via
        ``_shard``)."""
        nodes = graph.all_nodes()
        nbrs = np.zeros((len(nodes), max_deg), np.uint64)
        w = np.zeros((len(nodes), max_deg), np.float32)
        deg = np.zeros(len(nodes), np.int32)
        for i, nid in enumerate(nodes):
            shard, lock = graph._shard(int(nid))
            with lock:
                cand = shard.neighbors.get(int(nid), [])
                ww = shard.weights.get(int(nid), [])
            deg[i] = len(cand)
            k = min(len(cand), max_deg)
            nbrs[i, :k] = np.asarray(cand[:k], np.uint64)
            w[i, :k] = np.asarray(ww[:k], np.float32)
        return DeviceGraph.from_arrays(np.asarray(nodes, np.uint64), nbrs,
                                       deg, w, sharding=sharding)

    @staticmethod
    def from_arrays(nodes: np.ndarray, nbrs: np.ndarray, deg: np.ndarray,
                    weights: Optional[np.ndarray] = None,
                    sharding=None) -> "DeviceGraph":
        n, max_deg = nbrs.shape
        enforce(len(nodes) == n and len(deg) == n, "shape mismatch")
        capped_rows = int((np.asarray(deg) > max_deg).sum())
        kept = np.minimum(deg, max_deg)
        if weights is None:
            weights = (np.arange(max_deg)[None, :] < kept[:, None]
                       ).astype(np.float32)
        w = np.where(np.arange(max_deg)[None, :] < kept[:, None],
                     np.maximum(weights, 0.0), 0.0)
        cdf = np.cumsum(w, axis=1, dtype=np.float32)
        hi, lo = split_keys(nbrs.reshape(-1))
        key_map = DeviceKeyMap(keys=nodes,
                               rows=np.arange(n, dtype=np.int32),
                               sharding=sharding)
        state = {
            "nbr_hi": jnp.asarray(hi.reshape(n, max_deg)),
            "nbr_lo": jnp.asarray(lo.reshape(n, max_deg)),
            "cdf": jnp.asarray(cdf),
            "deg": jnp.asarray(kept.astype(np.int32)),
            "map": key_map.state,
        }
        if sharding is not None:
            state = {k: (jax.device_put(v, sharding) if k != "map" else v)
                     for k, v in state.items()}
        return DeviceGraph(state, max_deg, capped_rows)

    # -- in-graph ops ----------------------------------------------------

    @staticmethod
    def lookup_rows(state, hi, lo):
        """[n] int32 rows, −1 for unknown nodes."""
        return device_hash_lookup(state["map"], hi, lo)

    @staticmethod
    def _samplable(state, rows):
        """Valid row AND kept degree > 0 AND positive weight mass — a
        known node whose kept weights all clamp to 0 must mask out, not
        surface the padding key as a 'neighbor'."""
        r = jnp.clip(rows, 0, state["deg"].shape[0] - 1)
        return ((rows >= 0) & (jnp.take(state["deg"], r) > 0)
                & (state["cdf"][r, -1] > 0))

    @staticmethod
    def _draw(state, rng, rows, shape):
        """Weighted draw of ONE neighbor slot per (row, draw): CDF
        inverse via searchsorted. rows −1/degree-0 → slot 0 (callers
        mask)."""
        r = jnp.clip(rows, 0, state["cdf"].shape[0] - 1)
        cdf = state["cdf"][r]                     # [..., max_deg]
        total = cdf[..., -1:]
        u = jax.random.uniform(rng, shape) * jnp.maximum(total[..., 0], 1e-30)
        slot = jnp.sum((cdf < u[..., None]).astype(jnp.int32), axis=-1)
        return jnp.minimum(slot, state["cdf"].shape[1] - 1)

    @staticmethod
    def sample_neighbors(state, rng, hi, lo, k: int
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """[n] node key halves → (nbr_hi [n,k], nbr_lo [n,k], mask [n,k])
        — k weighted draws WITH replacement per node (the GPU sample
        kernel's contract; without-replacement stays host/server-side)."""
        rows = DeviceGraph.lookup_rows(state, hi, lo)
        ok = DeviceGraph._samplable(state, rows)
        slot = DeviceGraph._draw(state, rng, rows[:, None], (hi.shape[0], k))
        r = jnp.clip(rows, 0, state["deg"].shape[0] - 1)
        nh = jnp.take_along_axis(state["nbr_hi"][r], slot, axis=1)
        nl = jnp.take_along_axis(state["nbr_lo"][r], slot, axis=1)
        mask = jnp.broadcast_to(ok[:, None], nh.shape)
        return (jnp.where(mask, nh, 0), jnp.where(mask, nl, 0), mask)

    @staticmethod
    def random_walk(state, rng, hi, lo, length: int
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Deepwalk generator: [n] start keys → (walk_hi, walk_lo
        [n, length+1], live [n, length+1]) — a lax.scan of single-draw
        steps; dead ends freeze (live goes False from there on)."""
        n = hi.shape[0]

        def step(carry, key):
            chi, clo, alive = carry
            rows = DeviceGraph.lookup_rows(state, chi, clo)
            ok = alive & DeviceGraph._samplable(state, rows)
            slot = DeviceGraph._draw(state, key, rows, (n,))
            r = jnp.clip(rows, 0, state["deg"].shape[0] - 1)
            nh = jnp.take_along_axis(state["nbr_hi"][r], slot[:, None],
                                     axis=1)[:, 0]
            nl = jnp.take_along_axis(state["nbr_lo"][r], slot[:, None],
                                     axis=1)[:, 0]
            nh = jnp.where(ok, nh, chi)
            nl = jnp.where(ok, nl, clo)
            return (nh, nl, ok), (nh, nl, ok)

        keys = jax.random.split(rng, length)
        init = (hi.astype(jnp.uint32), lo.astype(jnp.uint32),
                jnp.ones(n, bool))
        _, (wh, wl, alive) = lax.scan(step, init, keys)
        walk_hi = jnp.concatenate([hi[None, :], wh], axis=0).T
        walk_lo = jnp.concatenate([lo[None, :], wl], axis=0).T
        live = jnp.concatenate([jnp.ones((1, n), bool), alive], axis=0).T
        return walk_hi, walk_lo, live
