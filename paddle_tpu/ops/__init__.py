from . import collectives
from .device_graph import DeviceGraph


def __getattr__(name):
    # PEP-562 lazy: hot_kernels pulls in pallas + ps.device_hash — keep
    # it off the bare `paddle_tpu.ops` import path (the obs/__init__
    # exporter precedent)
    if name == "hot_kernels":
        import importlib

        return importlib.import_module(".hot_kernels", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = ["collectives", "DeviceGraph", "hot_kernels"]
