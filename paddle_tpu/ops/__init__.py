from . import collectives
from .device_graph import DeviceGraph

__all__ = ["collectives", "DeviceGraph"]
