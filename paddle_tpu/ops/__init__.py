from . import collectives

__all__ = ["collectives"]
