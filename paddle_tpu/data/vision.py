"""Vision datasets (``paddle.vision.datasets`` surface: MNIST,
FashionMNIST, Cifar10/100).

The reference downloads archives on first use (vision/datasets/mnist.py
etc.). This build runs in zero-egress environments, so each dataset
loads from a local copy when present (same on-disk formats: IDX for
MNIST, the python pickle batches for CIFAR) and otherwise falls back to
a deterministic synthetic sample generator with class-dependent
structure (``backend="synthetic"``) — enough signal for training and
tests without network access.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
from typing import Optional, Tuple

import numpy as np

from ..core.enforce import InvalidArgumentError, enforce

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100"]


class _ArrayDataset:
    def __init__(self, images: np.ndarray, labels: np.ndarray) -> None:
        self.images = images
        self.labels = labels

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx):
        return self.images[idx], self.labels[idx]


def _synthetic_images(n: int, shape: Tuple[int, ...], num_classes: int,
                      seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Class-dependent blobs: class k lights a k-dependent patch, so a
    small model separates classes (used by tests and zero-egress runs)."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, n).astype(np.int64)
    images = rng.normal(0.1, 0.1, (n,) + shape).astype(np.float32)
    c, h, w = shape
    ph = max(h // 4, 1)
    for k in range(num_classes):
        sel = labels == k
        r = (k * ph) % max(h - ph, 1)
        col = (k * ph) % max(w - ph, 1)
        images[sel, :, r : r + ph, col : col + ph] += 0.9
    return images, labels


class MNIST(_ArrayDataset):
    """IDX-format loader (train-images-idx3-ubyte[.gz] etc. under
    ``image_path`` dir) with synthetic fallback. mode: train|test."""

    NUM_CLASSES = 10
    SHAPE = (1, 28, 28)
    FILES = {
        "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, mode: str = "train", image_path: Optional[str] = None,
                 backend: str = "auto", synthetic_size: int = 2048,
                 seed: int = 0) -> None:
        enforce(mode in ("train", "test"), f"mode train|test, got {mode!r}",
                InvalidArgumentError)
        imgs = labels = None
        if backend in ("auto", "idx") and image_path:
            imgs, labels = self._try_load_idx(image_path, mode)
            enforce(imgs is not None or backend == "auto",
                    f"no IDX files for mode={mode} under {image_path}",
                    InvalidArgumentError)
        if imgs is None:
            imgs, labels = _synthetic_images(
                synthetic_size, self.SHAPE, self.NUM_CLASSES,
                seed + (0 if mode == "train" else 1))
        super().__init__(imgs, labels)

    @classmethod
    def _try_load_idx(cls, root: str, mode: str):
        img_name, lbl_name = cls.FILES[mode]

        def find(name):
            for cand in (name, name + ".gz"):
                p = os.path.join(root, cand)
                if os.path.exists(p):
                    return p
            return None

        img_p, lbl_p = find(img_name), find(lbl_name)
        if not img_p or not lbl_p:
            return None, None

        def read(path):
            op = gzip.open if path.endswith(".gz") else open
            with op(path, "rb") as f:
                return f.read()

        raw = read(img_p)
        magic, n, h, w = struct.unpack(">IIII", raw[:16])
        imgs = (np.frombuffer(raw, np.uint8, offset=16)
                .reshape(n, 1, h, w).astype(np.float32) / 255.0)
        raw = read(lbl_p)
        _, n2 = struct.unpack(">II", raw[:8])
        labels = np.frombuffer(raw, np.uint8, offset=8).astype(np.int64)
        return imgs, labels


class FashionMNIST(MNIST):
    """Same IDX format, different archive contents."""


class Cifar10(_ArrayDataset):
    """CIFAR python-pickle batches under ``data_path`` (cifar-10-batches-py)
    with synthetic fallback."""

    NUM_CLASSES = 10
    SHAPE = (3, 32, 32)

    def __init__(self, mode: str = "train", data_path: Optional[str] = None,
                 backend: str = "auto", synthetic_size: int = 2048,
                 seed: int = 0) -> None:
        enforce(mode in ("train", "test"), f"mode train|test, got {mode!r}",
                InvalidArgumentError)
        imgs = labels = None
        if backend in ("auto", "pickle") and data_path:
            imgs, labels = self._try_load(data_path, mode)
        if imgs is None:
            imgs, labels = _synthetic_images(
                synthetic_size, self.SHAPE, self.NUM_CLASSES,
                seed + (0 if mode == "train" else 1))
        super().__init__(imgs, labels)

    def _batch_files(self, root: str, mode: str):
        if mode == "train":
            return [os.path.join(root, f"data_batch_{i}") for i in range(1, 6)]
        return [os.path.join(root, "test_batch")]

    def _label_key(self):
        return b"labels"

    def _try_load(self, root: str, mode: str):
        files = [p for p in self._batch_files(root, mode) if os.path.exists(p)]
        if not files:
            return None, None
        xs, ys = [], []
        for p in files:
            with open(p, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.append(np.asarray(d[self._label_key()], np.int64))
        imgs = (np.concatenate(xs).reshape(-1, 3, 32, 32).astype(np.float32)
                / 255.0)
        return imgs, np.concatenate(ys)


class Cifar100(Cifar10):
    NUM_CLASSES = 100

    def _batch_files(self, root: str, mode: str):
        return [os.path.join(root, "train" if mode == "train" else "test")]

    def _label_key(self):
        return b"fine_labels"
