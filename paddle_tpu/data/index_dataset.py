"""Tree/graph index for retrieval models
(reference ``paddle/fluid/distributed/index_dataset/``:
``index_wrapper.{h,cc}`` TreeIndex, ``index_sampler.{h,cc}``
LayerWiseSampler/BeamSearchSampler, proto ``index_dataset.proto``).

The reference builds a K-ary tree over items (TDM — tree-based deep
matching): every item is a leaf; training samples positives along the
item's root→leaf path and negatives uniformly from the same layers.
Kept host-side (index construction and sampling are pointer-chasing,
not MXU work); sampler outputs are **fixed-shape arrays** ready to feed
jitted towers."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.enforce import NotFoundError, enforce

__all__ = ["TreeIndex", "LayerWiseSampler"]


class TreeIndex:
    """K-ary item tree (index_wrapper.h TreeIndex).

    Nodes are numbered breadth-first from 1 (root). Items occupy the
    leaves in the given order; internal "codes" match the reference's
    Kraft-coding: child c of node n is ``n*k + 1 + c`` with 0-based
    node 0 as root."""

    def __init__(self, item_ids: Sequence[int], branch: int = 2) -> None:
        enforce(branch >= 2, "branch factor >= 2")
        enforce(len(item_ids) > 0, "need at least one item")
        self.branch = branch
        self.item_ids = np.asarray(list(item_ids), np.int64)
        n = len(self.item_ids)
        # depth so the deepest layer holds >= n leaves
        self.height = 1
        while branch ** self.height < n:
            self.height += 1
        # leaf codes (deepest layer, breadth-first numbering from 0=root)
        first_leaf = (branch ** self.height - 1) // (branch - 1)
        self._leaf_codes = first_leaf + np.arange(n, dtype=np.int64)
        self._item_to_code: Dict[int, int] = {
            int(i): int(c) for i, c in zip(self.item_ids, self._leaf_codes)}
        self._code_to_item: Dict[int, int] = {
            int(c): int(i) for i, c in zip(self.item_ids, self._leaf_codes)}

    # -- structure queries (index_wrapper.h) ------------------------------

    def total_node_num(self) -> int:
        return int(self._leaf_codes[-1]) + 1

    def emb_size(self) -> int:  # reference naming for total node count
        return self.total_node_num()

    def get_ancestor(self, code: int, level_up: int) -> int:
        for _ in range(level_up):
            code = (code - 1) // self.branch
        return code

    def get_travel_codes(self, item_id: int) -> np.ndarray:
        """Root→leaf path codes for an item (get_travel_codes
        index_wrapper.cc) ordered leaf→root like the reference."""
        code = self._item_to_code.get(int(item_id))
        if code is None:
            raise NotFoundError(f"item {item_id} not in tree")
        path = []
        while True:
            path.append(code)
            if code == 0:
                break
            code = (code - 1) // self.branch
        return np.asarray(path, np.int64)

    def get_layer_codes(self, level: int) -> np.ndarray:
        """All codes at a layer (0 = root)."""
        enforce(0 <= level <= self.height, f"level in [0,{self.height}]")
        first = (self.branch ** level - 1) // (self.branch - 1)
        count = self.branch ** level
        if level == self.height:
            return self._leaf_codes.copy()
        return first + np.arange(count, dtype=np.int64)

    def get_items_of_codes(self, codes: Sequence[int]) -> List[Optional[int]]:
        return [self._code_to_item.get(int(c)) for c in codes]


class LayerWiseSampler:
    """index_sampler.h LayerWiseSampler: for each (user, item) pair,
    emit per-layer training examples — the positive ancestor at that
    layer plus ``layer_counts[l]`` uniform negatives from the same
    layer (excluding the positive).

    Returns fixed-shape arrays: codes ``[n_pairs, total, ]`` flattened
    with labels, ready for a static-shape jitted tower."""

    def __init__(self, tree: TreeIndex, layer_counts: Sequence[int],
                 seed: int = 0, start_sample_layer: int = 1) -> None:
        enforce(len(layer_counts) == tree.height - start_sample_layer + 1,
                f"need one negative-count per sampled layer "
                f"({tree.height - start_sample_layer + 1})")
        self.tree = tree
        self.layer_counts = [int(c) for c in layer_counts]
        self.start_layer = int(start_sample_layer)
        self._rng = np.random.default_rng(seed)

    def sample(self, item_ids: Sequence[int]
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """→ (pair_index, codes, labels), each 1-D of equal length:
        one positive (label 1) + negatives (label 0) per layer per item."""
        idx_out: List[int] = []
        codes_out: List[int] = []
        labels_out: List[int] = []
        for pi, item in enumerate(item_ids):
            path = self.tree.get_travel_codes(item)  # leaf→root
            # path[0]=leaf (layer height) … path[-1]=root (layer 0)
            for li, layer in enumerate(
                    range(self.start_layer, self.tree.height + 1)):
                pos = path[self.tree.height - layer]
                layer_codes = self.tree.get_layer_codes(layer)
                idx_out.append(pi)
                codes_out.append(int(pos))
                labels_out.append(1)
                negs_wanted = self.layer_counts[li]
                cand = layer_codes[layer_codes != pos]
                if len(cand) and negs_wanted:
                    k = min(negs_wanted, len(cand))
                    for c in self._rng.choice(cand, size=k, replace=False):
                        idx_out.append(pi)
                        codes_out.append(int(c))
                        labels_out.append(0)
        return (np.asarray(idx_out, np.int64),
                np.asarray(codes_out, np.int64),
                np.asarray(labels_out, np.int64))
