"""Slot-record dataset pipeline.

Rebuild of the reference's C++ data layer (SURVEY §2.1 L7): `Dataset` /
`InMemoryDataset` / `QueueDataset` (data_set.h:47,170,328) fed by the
MultiSlot text format (data_feed.cc:893 ParseOneInstance — §A.5) with
in-memory local/global shuffle and channel→batch delivery, plus the
Python `fleet.data_generator` emit side
(fleet/data_generator/data_generator.py).

TPU-first differences:
- records are SoA per slot (values + per-record lengths) — the
  SlotRecord compact representation (data_feed.h:1390), not per-instance
  object trees; parsing is the native C parser (csrc/slot_parser.cc);
- batches come out as fixed-shape numpy arrays (padded/truncated to a
  per-slot max) so the jitted step sees one shape — XLA's static-shape
  requirement; the reference's GPU path packs batches the same way
  (MiniBatchGpuPack, data_feed.h:528);
- global shuffle hash-partitions records by line hash across workers
  through a user-provided exchange function (the GlooWrapper all-to-all
  role) and falls back to local shuffle when absent.
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import os
import hashlib
import sys
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..core.enforce import enforce, enforce_eq
from ..ps.native import SlotParser

__all__ = ["SlotDesc", "DataGenerator", "InMemoryDataset", "QueueDataset"]


@dataclasses.dataclass
class SlotDesc:
    """One slot of the MultiSlot schema (DataFeedDesc.multi_slot_desc)."""

    name: str
    is_float: bool = False
    is_used: bool = True
    max_len: int = 1          # batch padding length (CTR slots are len-1)


class DataGenerator:
    """fleet.data_generator compatible emitter: subclass and implement
    ``generate_sample(line)`` → iterator yielding ``[(slot, [values])]``;
    ``run_from_stdin`` serializes to MultiSlot text lines."""

    def __init__(self) -> None:
        self._batch = 1

    def set_batch(self, batch: int) -> None:
        self._batch = batch

    def generate_sample(self, line: Optional[str]):
        raise NotImplementedError

    def _serialize(self, sample: Sequence[Tuple[str, Sequence[Any]]]) -> str:
        parts: List[str] = []
        for _, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def run_from_stdin(self, fin=None, fout=None) -> None:
        fin = fin or sys.stdin
        fout = fout or sys.stdout
        for line in fin:
            it = self.generate_sample(line)
            for sample in it() if callable(it) else it:
                fout.write(self._serialize(sample) + "\n")

    def run_from_memory(self, lines: Optional[Sequence[str]] = None) -> List[str]:
        out: List[str] = []
        for line in (lines if lines is not None else [None]):
            it = self.generate_sample(line)
            for sample in it() if callable(it) else it:
                out.append(self._serialize(sample))
        return out


class _SlotColumns:
    """SoA storage for parsed records of one file chunk."""

    def __init__(self, slots: Sequence[SlotDesc], parsed: Dict[str, tuple]) -> None:
        self.values = {s.name: parsed[s.name][0] for s in slots if s.is_used}
        self.lengths = {s.name: parsed[s.name][1] for s in slots if s.is_used}
        names = [s.name for s in slots if s.is_used]
        self.num = len(self.lengths[names[0]]) if names else 0


class _RecordStore:
    """All loaded records as per-slot value arrays + offsets; supports
    permutation (shuffle) and slicing into batches."""

    def __init__(self, slots: Sequence[SlotDesc]) -> None:
        self.slots = [s for s in slots if s.is_used]
        self._vals: Dict[str, List[np.ndarray]] = {s.name: [] for s in self.slots}
        self._lens: Dict[str, List[np.ndarray]] = {s.name: [] for s in self.slots}
        self.num_records = 0

    def append(self, cols: _SlotColumns) -> None:
        for s in self.slots:
            self._vals[s.name].append(cols.values[s.name])
            self._lens[s.name].append(cols.lengths[s.name])
        self.num_records += cols.num

    def finalize(self) -> None:
        for s in self.slots:
            self._vals[s.name] = [np.concatenate(self._vals[s.name])] if self._vals[s.name] else [
                np.zeros(0, np.float32 if s.is_float else np.uint64)]
            self._lens[s.name] = [np.concatenate(self._lens[s.name])] if self._lens[s.name] else [
                np.zeros(0, np.int32)]

    def _offsets(self, name: str) -> np.ndarray:
        lens = self._lens[name][0]
        off = np.zeros(len(lens) + 1, np.int64)
        np.cumsum(lens, out=off[1:])
        return off

    def _gather_rows(self, name: str, indices: np.ndarray):
        """Vectorized variable-length row gather: (lens, values) of the
        given records for one slot. Safe for empty index sets."""
        off = self._offsets(name)
        lens = self._lens[name][0][indices]
        vals = self._vals[name][0]
        total = int(lens.sum())
        if total == 0:
            return lens, np.zeros(0, vals.dtype)
        starts = off[:-1][indices]
        idx = np.repeat(starts, lens) + (
            np.arange(total)
            - np.repeat(np.concatenate([[0], np.cumsum(lens)[:-1]]), lens))
        return lens, vals[idx.astype(np.int64)]

    def permute(self, perm: np.ndarray) -> None:
        for s in self.slots:
            new_lens, new_vals = self._gather_rows(s.name, perm)
            self._vals[s.name][0] = new_vals
            self._lens[s.name][0] = new_lens

    def batch(self, lo: int, hi: int) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
        """Fixed-shape batch: values padded/truncated to slot.max_len."""
        out: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        n = hi - lo
        for s in self.slots:
            off = self._offsets(s.name)
            lens = self._lens[s.name][0][lo:hi]
            vals = self._vals[s.name][0]
            dtype = np.float32 if s.is_float else np.uint64
            padded = np.zeros((n, s.max_len), dtype)
            take = np.minimum(lens, s.max_len)
            for_i = np.arange(n)
            mask_rows = np.repeat(for_i, take)
            col_idx = np.concatenate([np.arange(t) for t in take]) if n else np.zeros(0, np.int64)
            src_idx = np.repeat(off[lo:hi], take) + col_idx
            padded[mask_rows, col_idx] = vals[src_idx.astype(np.int64)]
            out[s.name] = (padded, take.astype(np.int32))
        return out

    def feasigns(self) -> np.ndarray:
        keys = [self._vals[s.name][0] for s in self.slots if not s.is_float]
        return np.concatenate(keys) if keys else np.zeros(0, np.uint64)

    # -- record subset wire format (global-shuffle exchange) -------------

    def extract_bytes(self, indices: np.ndarray) -> bytes:
        """Serialize the given records: [u32 n] then per slot (in slot
        order) [u32 n_values][lens i32][values raw]."""
        indices = np.ascontiguousarray(indices, np.int64)
        parts = [np.asarray([len(indices)], np.uint32).tobytes()]
        for s in self.slots:
            lens, gather = self._gather_rows(s.name, indices)
            parts.append(np.asarray([len(gather)], np.uint32).tobytes())
            parts.append(np.ascontiguousarray(lens, np.int32).tobytes())
            parts.append(np.ascontiguousarray(gather).tobytes())
        return b"".join(parts)

    def _parse_record_blob(self, blob: bytes):
        """Validate + decode one extract_bytes blob → (n, cols_v, cols_l).
        A malformed transport result (truncated, reordered, echoed back)
        must fail HERE, not as an IndexError in a later batch gather."""
        view = memoryview(blob)
        enforce(len(blob) >= 4, "record blob too short for its header")
        (n,) = np.frombuffer(view[:4], np.uint32)
        o = 4
        cols_v, cols_l = {}, {}
        for s in self.slots:
            enforce(o + 4 <= len(blob), f"record blob truncated at slot {s.name!r}")
            (nv,) = np.frombuffer(view[o:o + 4], np.uint32)
            o += 4
            lens = np.frombuffer(view[o:o + 4 * int(n)], np.int32)
            o += 4 * int(n)
            dtype = np.float32 if s.is_float else np.uint64
            nbytes = int(nv) * dtype().itemsize
            enforce(o + nbytes <= len(blob),
                    f"record blob truncated in slot {s.name!r} values")
            enforce(len(lens) == int(n) and int(lens.sum()) == int(nv),
                    f"record blob inconsistent for slot {s.name!r} "
                    f"(lens sum {int(lens.sum()) if len(lens) == int(n) else '?'} "
                    f"vs {int(nv)} values)")
            vals = np.frombuffer(view[o:o + nbytes], dtype)
            o += nbytes
            cols_v[s.name] = vals.copy()
            cols_l[s.name] = lens.copy()
        enforce(o == len(blob), "record blob has trailing bytes")
        return int(n), cols_v, cols_l

    def ingest_bytes(self, blob: bytes) -> int:
        """Append records serialized by :meth:`extract_bytes` (slot
        schemas must match). Returns the record count ingested."""
        return self.ingest_many([blob])

    def ingest_many(self, blobs) -> int:
        """Append records from several blobs with ONE concatenation per
        slot column (the per-source repeated full-array copies would
        dominate a many-worker shuffle)."""
        parsed = [self._parse_record_blob(b) for b in blobs if b]
        total = sum(n for n, _, _ in parsed)
        if not total:
            return 0
        for s in self.slots:
            self._vals[s.name][0] = np.concatenate(
                [self._vals[s.name][0]] + [cv[s.name] for n, cv, _ in parsed if n])
            self._lens[s.name][0] = np.concatenate(
                [self._lens[s.name][0]] + [cl[s.name] for n, _, cl in parsed if n])
        self.num_records += total
        return total

    def keep_only(self, indices: np.ndarray) -> None:
        """Drop every record not in ``indices`` (order preserved)."""
        self.permute(np.ascontiguousarray(indices, np.int64))
        self.num_records = len(indices)


class InMemoryDataset:
    """data_set.h InMemoryDataset analogue: load files, shuffle, batch.

    Usage (mirrors fleet dataset API):
        ds = InMemoryDataset(slots)
        ds.set_filelist(["part-*"])
        ds.load_into_memory()
        ds.local_shuffle()            # or ds.global_shuffle(exchange_fn)
        for batch in ds.batch_iter(4096): ...
    """

    def __init__(self, slots: Sequence[SlotDesc], seed: int = 0) -> None:
        self.slots = list(slots)
        self._files: List[str] = []
        self._store: Optional[_RecordStore] = None
        self._rng = np.random.default_rng(seed)
        self.parse_errors = 0
        self._pipe_command: Optional[str] = None

    # -- config -----------------------------------------------------------

    def set_filelist(self, patterns: Sequence[str]) -> None:
        files: List[str] = []
        for p in patterns:
            hit = sorted(_glob.glob(p))
            files.extend(hit if hit else [p])
        self._files = files

    def set_pipe_command(self, cmd: Optional[str]) -> None:
        """Preprocess each input file through a shell command before slot
        parsing — the reference DataFeed's ``pipe_command`` (PaddleRec
        jobs run their feature extractors this way: the raw log streams
        through the command's stdin and MultiSlot lines come out).
        ``None`` restores direct reads (the native threaded feed)."""
        self._pipe_command = cmd

    # -- load -------------------------------------------------------------

    def _parse_text(self, text: str) -> _SlotColumns:
        p = SlotParser([(s.name, s.is_float, s.is_used) for s in self.slots])
        p.parse(text)
        self.parse_errors += p.errors
        return _SlotColumns(self.slots, p.fetch())

    def load_into_memory(self, num_threads: int = 4) -> int:
        """Parallel load via the native channel feed (data_feed.cc —
        reader threads overlap IO+parse, the reference's
        channel-based DataFeed); Python fallback reads serially."""
        store = _RecordStore(self.slots)
        for f in self._files:  # fail fast on bad paths (the native feed
            if not os.path.exists(f):  # would just count an error)
                raise FileNotFoundError(f"dataset file not found: {f}")
        if self._pipe_command:
            # pipe path: one preprocessor subprocess per file, overlapped
            # by a thread pool (the reference forks pipe_command per
            # reader thread the same way); output parses like file text
            import subprocess
            from concurrent.futures import ThreadPoolExecutor

            def run_pipe(path):
                with open(path, "rb") as fh:
                    out = subprocess.run(
                        self._pipe_command, shell=True, stdin=fh,
                        capture_output=True)
                if out.returncode != 0:
                    raise RuntimeError(
                        f"pipe_command failed on {path} "
                        f"(rc {out.returncode}): "
                        f"{out.stderr.decode(errors='replace')[:500]}")
                # lenient decode: a stray non-UTF-8 byte in a raw log
                # becomes a parse error (the native feed's tolerance),
                # not a crash without file context
                return out.stdout.decode(errors="replace")

            with ThreadPoolExecutor(max_workers=num_threads) as pool:
                # submit in waves so finished whole-file outputs don't
                # pile up unboundedly ahead of the serial parser (the
                # native feed's channel provides this backpressure)
                files = list(self._files)
                for lo in range(0, len(files), num_threads):
                    for text in pool.map(run_pipe,
                                         files[lo:lo + num_threads]):
                        store.append(self._parse_text(text))
            store.finalize()
            self._store = store
            return store.num_records
        try:
            from ..ps.native import NativeDataFeed

            feed = NativeDataFeed(
                [(s.name, s.is_float, s.is_used) for s in self.slots],
                self._files, num_threads=num_threads)
            for parsed in feed:
                store.append(_SlotColumns(self.slots, parsed))
            self.parse_errors += feed.errors
            feed.close()
        except RuntimeError:
            for f in self._files:
                with open(f, "r") as fh:
                    store.append(self._parse_text(fh.read()))
        store.finalize()
        self._store = store
        return store.num_records

    def load_from_lines(self, lines: Sequence[str]) -> int:
        """Feed pre-generated MultiSlot lines (DataGenerator output)."""
        store = _RecordStore(self.slots)
        store.append(self._parse_text("\n".join(lines) + ("\n" if lines else "")))
        store.finalize()
        self._store = store
        return store.num_records

    # -- shuffle ----------------------------------------------------------

    def local_shuffle(self) -> None:
        enforce(self._store is not None, "load_into_memory first")
        perm = self._rng.permutation(self._store.num_records)
        self._store.permute(perm)

    def global_shuffle(
        self,
        exchange: Optional[Callable[[List[bytes]], List[bytes]]] = None,
        worker_id: int = 0,
        worker_num: int = 1,
        util=None,
    ) -> None:
        """Redistribute RECORDS across workers, then shuffle locally —
        the reference's GlooWrapper-backed dataset global shuffle
        (data_set.cc: each worker assigns every local record a random
        destination, ships the serialized records all-to-all, ingests
        what arrives, then shuffles locally).

        Transport: pass ``util`` (``fleet.util`` — uses
        ``all_to_all_bytes``) or a raw ``exchange(blobs)->blobs``
        callable taking one serialized-record blob per destination and
        returning one per source. Single worker (or neither transport):
        reduces to a seeded local shuffle."""
        enforce(self._store is not None, "load_into_memory first")
        if util is not None:
            # the util's bound rank/world are authoritative — mismatched
            # caller-supplied ids would silently lose/duplicate records
            u_rank, u_world = util.rank, util.world_size
            enforce(worker_id in (0, u_rank) and worker_num in (1, u_world),
                    f"worker_id/num ({worker_id}/{worker_num}) contradict "
                    f"the bound util rank/world ({u_rank}/{u_world})")
            worker_id, worker_num = u_rank, u_world
            if exchange is None:
                exchange = util.all_to_all_bytes
        if worker_num <= 1 or exchange is None:
            self.local_shuffle()
            return
        st = self._store
        n = st.num_records
        dest = self._rng.integers(0, worker_num, size=n)
        # own partition stays in place (keep_only below) — ship an empty
        # blob to self rather than round-tripping it through the store
        blobs = [st.extract_bytes(np.flatnonzero(dest == w))
                 if w != worker_id else b""
                 for w in range(worker_num)]
        received = exchange(blobs)
        enforce(len(received) == worker_num,
                "exchange must return one blob per source worker")
        st.keep_only(np.flatnonzero(dest == worker_id))
        st.ingest_many(blob for src, blob in enumerate(received)
                       if src != worker_id)  # own partition kept in place
        self.local_shuffle()

    # -- consume ----------------------------------------------------------

    @property
    def num_records(self) -> int:
        return self._store.num_records if self._store else 0

    def pass_feasigns(self) -> np.ndarray:
        """All uint64 feasigns of the loaded pass (for cache.begin_pass —
        the PreBuildTask dedup input)."""
        enforce(self._store is not None, "load_into_memory first")
        return self._store.feasigns()

    def batch_iter(self, batch_size: int, drop_last: bool = True,
                   start_batch: int = 0
                   ) -> Iterator[Dict[str, Tuple[np.ndarray, np.ndarray]]]:
        """``start_batch`` is the resume cursor (the job-checkpoint
        stream position, io/job_checkpoint.py): skip that many leading
        batches — for the in-memory store a pure index offset, so a
        restarted job re-enters the stream at the cut for free. The
        record order must match the saved run's (same seed/shuffle)."""
        enforce(self._store is not None, "load_into_memory first")
        n = self._store.num_records
        end = n - (n % batch_size) if drop_last else n
        for lo in range(start_batch * batch_size, end, batch_size):
            yield self._store.batch(lo, min(lo + batch_size, n))

    def release_memory(self) -> None:
        self._store = None

    # -- SlotRecord binary format (data_feed.h:1390 SlotRecord role) ------

    def save_slot_record(self, path: str) -> int:
        """Write the loaded pass as ONE compact binary file: a JSON
        header describing per-slot column layout, then the raw value and
        length arrays back to back. The reference's SlotRecord is its
        compact binary representation feeding GPUPS
        (SlotRecordInMemoryDataFeed, data_feed.h:1390) — this is the
        at-rest form: parse text once, reload every later pass at
        memory-bandwidth speed. Returns the number of records."""
        import json as _json

        enforce(self._store is not None, "load_into_memory first")
        st = self._store
        header: Dict = {"num_records": st.num_records, "slots": []}
        blobs: List[np.ndarray] = []
        off = 0
        for s in st.slots:
            vals = st._vals[s.name][0]
            lens = st._lens[s.name][0]
            # load hardcodes 4-byte lengths; catch a drifted dtype at
            # save time rather than as garbled batches after reload
            enforce_eq(lens.dtype, np.dtype(np.int32),
                       f"slot {s.name!r} length dtype")
            ent = {"name": s.name, "is_float": bool(s.is_float),
                   "max_len": int(s.max_len),
                   "val_dtype": str(vals.dtype), "val_off": off,
                   "val_n": int(len(vals))}
            off += vals.nbytes
            ent.update({"len_off": off, "len_n": int(len(lens))})
            off += lens.nbytes
            header["slots"].append(ent)
            blobs += [vals, lens]
        hdr = _json.dumps(header).encode()
        with open(path, "wb") as f:
            f.write(b"PTSR0001")
            f.write(np.asarray([len(hdr)], np.uint64).tobytes())
            f.write(hdr)
            for b in blobs:
                # buffer protocol — no transient bytes copy of multi-GB
                # columns
                f.write(memoryview(np.ascontiguousarray(b)).cast("B"))
            f.flush()
            os.fsync(f.fileno())
        return st.num_records

    def load_slot_record(self, path: str, mmap: bool = True) -> int:
        """Load a pass saved by :meth:`save_slot_record`. With ``mmap``
        the column arrays are memory-mapped (zero-copy until touched) —
        multi-pass training re-reads the same pass file per day without
        re-parsing text."""
        import json as _json

        with open(path, "rb") as f:
            magic = f.read(8)
            enforce(magic == b"PTSR0001", f"not a SlotRecord file: {path}")
            (hlen,) = np.frombuffer(f.read(8), np.uint64)
            header = _json.loads(f.read(int(hlen)).decode())
            base = f.tell()
        by_name = {e["name"]: e for e in header["slots"]}
        store = _RecordStore(self.slots)
        data = (np.memmap(path, np.uint8, mode="r", offset=base) if mmap
                else np.fromfile(path, np.uint8, offset=base))
        # fail fast on truncated/partial files: every declared column
        # must fit the actual data section, and lengths must cover the
        # declared record count
        for e in header["slots"]:
            end = e["len_off"] + e["len_n"] * 4
            enforce(end <= len(data),
                    f"SlotRecord file truncated: {path} (need {end} data "
                    f"bytes for slot {e['name']!r}, have {len(data)})")
            enforce_eq(e["len_n"], header["num_records"],
                       f"slot {e['name']!r} length column count")
        for s in store.slots:
            enforce(s.name in by_name, f"slot {s.name!r} missing in {path}")
            e = by_name[s.name]
            enforce_eq(bool(s.is_float), e["is_float"],
                       f"slot {s.name!r} float/id type mismatch")
            vd = np.dtype(e["val_dtype"])
            vals = data[e["val_off"]: e["val_off"] + e["val_n"] * vd.itemsize].view(vd)
            lens = data[e["len_off"]: e["len_off"] + e["len_n"] * 4].view(np.int32)
            store._vals[s.name] = [vals]
            store._lens[s.name] = [lens]
        store.num_records = int(header["num_records"])
        self._store = store
        return store.num_records


class QueueDataset:
    """Streaming variant (data_set.h QueueDataset): parse files chunk by
    chunk, yield batches without materializing the pass; no shuffle."""

    def __init__(self, slots: Sequence[SlotDesc], chunk_lines: int = 65536) -> None:
        self.slots = list(slots)
        self.chunk_lines = chunk_lines
        self._files: List[str] = []
        self.parse_errors = 0

    def set_filelist(self, patterns: Sequence[str]) -> None:
        files: List[str] = []
        for p in patterns:
            hit = sorted(_glob.glob(p))
            files.extend(hit if hit else [p])
        self._files = files

    def batch_iter(self, batch_size: int, start_batch: int = 0
                   ) -> Iterator[Dict[str, Tuple[np.ndarray, np.ndarray]]]:
        """``start_batch`` resumes the stream at a saved cursor
        (io/job_checkpoint.py): the skipped batches' lines are read but
        never slot-parsed — the fast-forward costs IO, not parse."""
        skip = int(start_batch)
        carry: List[str] = []
        for f in self._files:
            with open(f, "r") as fh:
                while True:
                    lines = fh.readlines(self.chunk_lines * 64)
                    if not lines:
                        break
                    carry.extend(lines)
                    while len(carry) >= batch_size:
                        chunk, carry = carry[:batch_size], carry[batch_size:]
                        if skip > 0:
                            skip -= 1
                            continue
                        ds = InMemoryDataset(self.slots)
                        ds.load_from_lines([l.rstrip("\n") for l in chunk])
                        self.parse_errors += ds.parse_errors
                        yield ds._store.batch(0, ds.num_records)
