"""In-memory DataLoader (``paddle.io.DataLoader`` analogue, dense path).

Static batch shapes (drop_last by default) keep XLA from recompiling; the
slot-record/streaming pipeline for the PS stack lives in
``paddle_tpu.data.dataset``.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DataLoader", "TensorDataset"]


class TensorDataset:
    """Aligned arrays dataset (features..., labels...)."""

    def __init__(self, *arrays: np.ndarray) -> None:
        self.arrays = [np.asarray(a) for a in arrays]
        n = len(self.arrays[0])
        for a in self.arrays:
            assert len(a) == n, "all arrays must share leading dim"
        self._len = n

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.arrays)


class DataLoader:
    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = True,
        seed: Optional[int] = None,
    ) -> None:
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            self._rng.shuffle(order)
        end = n - n % self.batch_size if self.drop_last else n
        for start in range(0, end, self.batch_size):
            idx = order[start : start + self.batch_size]
            yield self.dataset[idx]
