from .loader import DataLoader, TensorDataset
from .dataset import DataGenerator, InMemoryDataset, QueueDataset, SlotDesc
from .index_dataset import LayerWiseSampler, TreeIndex

__all__ = ["DataLoader", "TensorDataset",
           "DataGenerator", "InMemoryDataset", "QueueDataset", "SlotDesc",
           "TreeIndex", "LayerWiseSampler"]
