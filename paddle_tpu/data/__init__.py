from .loader import DataLoader, TensorDataset
from .dataset import DataGenerator, InMemoryDataset, QueueDataset, SlotDesc
from .index_dataset import LayerWiseSampler, TreeIndex
from .vision import MNIST, Cifar10, Cifar100, FashionMNIST

__all__ = ["DataLoader", "TensorDataset",
           "DataGenerator", "InMemoryDataset", "QueueDataset", "SlotDesc",
           "TreeIndex", "LayerWiseSampler",
           "MNIST", "FashionMNIST", "Cifar10", "Cifar100"]
