from .loader import DataLoader, TensorDataset

__all__ = ["DataLoader", "TensorDataset"]
