from .loader import DataLoader, TensorDataset
from .dataset import DataGenerator, InMemoryDataset, QueueDataset, SlotDesc

__all__ = ["DataLoader", "TensorDataset",
           "DataGenerator", "InMemoryDataset", "QueueDataset", "SlotDesc"]
