"""Async host→device batch feeder.

The reference's trainers never block on input: `DataFeed` threads parse
and stage batches while the device consumes the previous one
(`/root/reference/paddle/fluid/framework/data_feed.h` channels,
`MiniBatchGpuPack` data_feed.h:528 staging GPU batches ahead). Here the
same double-buffering wraps any host-batch iterator: a daemon thread
applies ``transform`` (e.g. ``jnp.asarray`` / ``jax.device_put``) and
keeps ``depth`` device-resident batches in flight, so the train loop's
dispatch overlaps the H2D transfer of the next batch — on a tunneled
chip with ~2 ms/MB transfers this is the difference between
transfer-bound and compute-bound stepping.
"""

from __future__ import annotations

import queue
import threading
import weakref
from typing import Any, Callable, Iterable, Iterator, Optional

__all__ = ["DevicePrefetcher", "device_prefetch"]

_STOP = object()


class DevicePrefetcher:
    """Iterate ``source`` with ``depth`` transformed batches in flight."""

    def __init__(self, source: Iterable, depth: int = 2,
                 transform: Optional[Callable[[Any], Any]] = None) -> None:
        q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        stop = threading.Event()
        err_box: list = []
        self._q = q
        self._err_box = err_box
        self._stop = stop

        def run() -> None:  # closes over locals ONLY — never `self`, so
            try:            # an abandoned prefetcher can be GC'd
                for item in source:
                    if stop.is_set():
                        return
                    if transform is not None:
                        item = transform(item)
                    while True:
                        try:
                            q.put(item, timeout=0.5)
                            break
                        except queue.Full:
                            if stop.is_set():
                                return
            except BaseException as e:  # noqa: BLE001 — re-raised in consumer
                err_box.append(e)
            finally:
                while True:  # always deliver the terminator
                    try:
                        q.put(_STOP, timeout=0.5)
                        return
                    except queue.Full:
                        if stop.is_set():
                            return

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="device-prefetcher")
        self._thread.start()
        # abandoned mid-stream → stop the producer (it would otherwise
        # spin forever pinning `depth` device batches)
        weakref.finalize(self, stop.set)

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        item = self._q.get()
        if item is _STOP:
            if self._err_box:
                raise self._err_box[0]
            raise StopIteration
        return item

    def close(self) -> None:
        """Stop early; drains so the producer can exit."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass


def device_prefetch(source: Iterable, depth: int = 2):
    """Prefetch with the default transform: every array leaf of a
    tuple/list/dict batch goes to the default device via jnp.asarray."""
    import jax.numpy as jnp
    import numpy as np

    def to_device(item):
        if isinstance(item, (tuple, list)):
            return type(item)(to_device(x) for x in item)
        if isinstance(item, dict):
            return {k: to_device(v) for k, v in item.items()}
        if isinstance(item, np.ndarray):
            return jnp.asarray(item)
        return item

    return DevicePrefetcher(source, depth=depth, transform=to_device)
