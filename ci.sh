#!/usr/bin/env bash
# CI gate (reference L0's cmake+ctest role): native build, fast test
# gate, then the full matrix. Usage: ./ci.sh [fast|full]
set -euo pipefail
cd "$(dirname "$0")"

echo "== native build =="
make -C paddle_tpu/csrc -s

echo "== fast gate (default: -m 'not slow') =="
python -m pytest tests/ -q -x

if [[ "${1:-fast}" == "full" ]]; then
  echo "== full matrix (slow tests included) =="
  python -m pytest tests/ -q -m ""
  echo "== driver artifacts =="
  python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8); print('dryrun OK')"
fi
echo "CI OK"
