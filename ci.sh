#!/usr/bin/env bash
# CI gate (reference L0's cmake+ctest role): graftlint, native build,
# fast test gate, then the full matrix.
# Usage: ./ci.sh [lint [--changed]|sched|fast|full|chaos|ckpt|hot_tier|serving|serving_fleet|recsys|obs|slo|reshard|reconcile|endurance|tenancy]
#   sched — graftsched gate: deterministic-schedule exploration of the
#   control-plane protocol harnesses (tools/sched/models.py) — the
#   preemption-bound-2 schedule space EXHAUSTED plus seeded random
#   walks, every failure replayable from the printed seed, dynamic
#   lock-order observations cross-checked against the py_locks decls.
#   The JSON summary is archived like the lint one (SCHED_JSON).
#   chaos — PS high-availability fast-gate: every failover/replication
#   test with faultpoints armed (incl. the slow e2e kill-shard runs)
#   plus the chaos_ps demo with its recovery/overhead acceptance checks.
#   ckpt — crash-consistent job-checkpoint gate: the full
#   test_job_checkpoint.py matrix incl. the slow SIGKILL-the-job
#   mid-save e2e (restart + checksum-fallback + bit-identical resume),
#   plus the chaos_ckpt demo's save/restore/pause-window measurements.
#   hot_tier — persistent HBM hot-embedding-tier gate: RPC-only parity
#   (bit-identical through eviction churn + checkpoint/restore) and the
#   sparse_hot bench with its 0-RPC warm-steady-state assertion.
#   serving — online-serving-plane gate: the full serving suite (incl.
#   the chaos-gated kill-the-primary-mid-serve reattach/convergence
#   acceptance test) plus the serving bench with its zero-RPC-warm and
#   freshness thresholds asserted.
#   obs — unified observability plane gate: the obs suite (registry /
#   trace propagation / failover-replay span marking / aggregation)
#   plus the overhead bench asserting metrics-always-on ≤2% vs the
#   metrics-compiled-out baseline, the fixed 16-byte trace-context
#   header (tracing off adds ZERO bytes beyond it), and the ≥3-process
#   job snapshot with per-table wire bytes + observed density; the
#   trace demo re-generates the flow-linked cross-process timeline.
#   slo — continuous-telemetry gate: the time-series/SLO/flight-recorder
#   suites (incl. the slow kill-shard e2e), then the slo_demo run — a
#   delay-ms faultpoint armed mid-stream must make the watchdog fire the
#   step-time burn-rate alert, dump a postmortem bundle that parses and
#   contains the firing window, and the live exporter's /metrics must
#   validate as well-formed OpenMetrics; the overhead bench re-asserts
#   the sampler+watchdog cost inside the 2% budget.
#   endurance — cold-tier scale gate: the ssd cold-tier suite (admission
#   / compact index / block compression / io-budgeted bg compaction,
#   incl. the SIGKILL-mid-compaction chaos test), then the endurance
#   demo — a Zipf stream over a universe 50x the hot budget must admit
#   <=1/3 of offered uniques at the default threshold, measure <=16
#   index bytes per cold row, keep serve pull p99 bounded while the
#   background compactor churns, and checkpoint/restore digest-exact
#   mid-compaction (SSD_ENDURANCE.json is the archived artifact).
#   reshard — live elastic resharding + SLO-driven autoscaling gate:
#   the full reshard/autoscale suites incl. the slow chaos e2e (grow
#   2→4 and shrink back mid-CtrStreamTrainer with an armed kill-shard
#   during one migration — digests prove zero lost/doubled rows, final
#   state bit-identical to an unresharded oracle), then the closed-loop
#   diurnal-ramp demo: an injected traffic wave fires the step-time
#   SLO, the autoscaler grows the shard set live, the wave passes, the
#   alert clears and it shrinks back — RESHARD.json records the
#   cutover pause p50/p95 (asserted well under the full-copy bootstrap
#   time) and the scale-event journal.
#   reconcile — declarative-control-plane gate: the spec/reconciler/
#   simulator suite incl. the slow compound-transition chaos e2e
#   (canary open + grow 2→4 as ONE spec update with a kill-shard armed
#   mid-bootstrap, bit-identical to a sequential direct-primitive
#   oracle), then the game-day chaos schedule (tools/gameday.py —
#   every transition driven by writing desired state; GAMEDAY.json is
#   the committed artifact) and the policy simulator replaying both
#   committed traces at 1000-shard scale in well under a minute.
#   tenancy — multi-tenant isolation gate: the full tenancy suite
#   (wire-enforced namespaces, weighted admission, per-tenant quotas,
#   tenant-scoped control plane — incl. the slow abusive-neighbor
#   interference e2e), then the tenancy bench: a four-tenant workload
#   zoo (CTR streaming / routed-MoE / GNN sampling / TDM retrieval)
#   shares one cluster with a deliberately abusive tenant, and the
#   gate asserts the abuser's MARGINAL p99 damage stays bounded while
#   its meter shows throttles + quota refusals, every cross-tenant
#   probe bounces, and the neighbors' namespaces stay digest-identical
#   (TENANCY.json is the archived quiet-host artifact).
set -euo pipefail
cd "$(dirname "$0")"

# graftlint first, in every mode: a host-sync, lock-order or
# wire-contract violation fails in seconds, not after the pytest matrix
# (docs/STATIC_ANALYSIS.md). The JSON summary (per-pass wall time +
# finding counts, allowlist why-tags) is archived so a newly slow or
# noisy pass is visible in the log; run.py itself warns past the 10 s
# soft budget. `./ci.sh lint --changed` lints only files changed vs
# merge-base(HEAD, origin/main) — the sub-second pre-commit loop.
echo "== graftlint (10 passes: tracer/hot-path/locks-cc/locks-py/wire/conv/obs/loops/sync-shim/actuation) =="
LINT_JSON=${LINT_JSON:-/tmp/ci_lint_summary.json}
# --changed is a lint-mode-only knob: the full gates must always lint
# the whole tree (staleness + cross-module reachability need it)
if [[ "${1:-fast}" == "lint" && "${2:-}" == "--changed" ]]; then
  python tools/lint/run.py --json "$LINT_JSON" --changed
else
  python tools/lint/run.py --json "$LINT_JSON"
fi
python - "$LINT_JSON" <<'PYEOF'
import json, sys
s = json.load(open(sys.argv[1]))
per = s.get("per_pass", {})
slow = sorted(per.items(), key=lambda kv: -kv[1]["wall_ms"])[:3]
print("lint summary archived -> %s  (%.1fs total; slowest: %s)" % (
    sys.argv[1], s.get("wall_s", 0),
    ", ".join("%s %.0fms" % (k, v["wall_ms"]) for k, v in slow)))
PYEOF

if [[ "${1:-fast}" == "lint" ]]; then
  echo "CI OK (lint only)"
  exit 0
fi

echo "== native build =="
make -C paddle_tpu/csrc -s

if [[ "${1:-fast}" == "sched" ]]; then
  echo "== graftsched (schedule exploration: 3 protocol harnesses) =="
  # ~20k schedules in well under a minute on the CI host; the 240 s
  # budget is the wedge guard, not the expected cost. SCHED_SEED pins
  # the random-walk base seed for a bisection; every failure prints its
  # own standalone replay seed regardless.
  SCHED_JSON=${SCHED_JSON:-/tmp/ci_sched_summary.json}
  python tools/sched/run.py --json "$SCHED_JSON" --budget-s 240 \
    ${SCHED_SEED:+--seed "$SCHED_SEED"}
  python - "$SCHED_JSON" <<'PYEOF'
import json, sys
s = json.load(open(sys.argv[1]))
print("sched summary archived -> %s  (%d schedules, %.1fs)" % (
    sys.argv[1], s.get("total_schedules", 0),
    s.get("wall_ms", 0) / 1000.0))
PYEOF
  echo "CI OK (sched)"
  exit 0
fi

if [[ "${1:-fast}" == "endurance" ]]; then
  echo "== endurance gate: cold-tier admission/index/compression/io-budget =="
  # the suite first: a format or reconcile regression fails in seconds,
  # before the demo pays its stream (incl. the armed-SIGKILL chaos run)
  python -m pytest tests/test_ssd_cold_tier.py -q
  echo "== ssd endurance demo (Zipf stream, universe 50x hot budget) =="
  # the admission / index / digest asserts are exact; the p99 ratio and
  # RSS bounds carry shared-1-core-host headroom (the committed
  # SSD_ENDURANCE.json shows the quiet-host numbers: ~1.5x churn p99,
  # ~22 MB growth) — one retry absorbs ambient-load outliers
  check_endurance() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      SSD_END_OUT=${SSD_END_OUT:-/tmp/ci_ssd_endurance.json} \
      python tools/ssd_endurance_demo.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['universe'] >= 10 * d['hot_budget'], d
# THE admission acceptance: >=3x fewer rows than offered uniques at
# the default threshold (the singleton tail never earns a row)
assert d['offered_over_admitted'] >= 3.0, d
assert d['admit_rejects'] > 0, d
# THE index acceptance: <=16 measured bytes per cold row (44.7 baseline)
assert 0 < d['index_bytes_per_row'] <= 16.0, d
# io-budget isolation: serve p99 under compactor churn stays within a
# bounded multiple of the no-compaction baseline
assert d['pull_p99_ratio'] <= 10.0, d
assert d['bg_compactions'] > 0 and d['bg_backlog_final'] == 0, d
assert d['io_bg_bytes'] > 0, d
# durability: checkpoint taken mid-compaction restores digest-exact
assert d['digest_exact'] and d['digest_stable_under_churn'], d
assert d['restored_rows'] == d['saved_rows'] > 0, d
# RSS tracks the hot budget + index, never the universe
assert d['rss_growth_bytes'] <= 256 * 1024 * 1024, d
print('endurance OK: %.1fx admission leverage (%d uniques -> %d rows), '
      '%.1f index B/row, churn p99 %.2fx baseline (%.1fms), '
      'digest-exact restore of %d rows'
      % (d['offered_over_admitted'], d['offered_uniques'],
         d['admitted_rows'], d['index_bytes_per_row'],
         d['pull_p99_ratio'], d['pull_p99_ms_churn'],
         d['restored_rows']))"
  }
  check_endurance || { echo "endurance retry (ambient-load outlier)"; \
    check_endurance; }
  echo "CI OK (endurance)"
  exit 0
fi

if [[ "${1:-fast}" == "chaos" ]]; then
  echo "== chaos gate: PS HA failover/replication (faultpoints armed) =="
  # -m "" includes the slow e2e runs: kill-shard mid-CtrStreamTrainer
  # with sync-replication bit-identity, and the SIGKILL'd multiprocess
  # failover — the paths this gate exists to keep deterministic
  python -m pytest tests/test_ps_ha.py -q -m ""
  echo "== chaos_ps demo (recovery time + replication overhead) =="
  # the overhead measurement is an interleaved A/B on a shared host —
  # one retry absorbs ambient-load outliers (the A/A control measures
  # a ~10% noise floor on 2-core CI boxes; see tools/chaos_ps.py)
  check_chaos() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" CHAOS_TRIALS=3 CHAOS_AB_ROUNDS=6 \
      python tools/chaos_ps.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['recovery_ms_p95'] > 0 and d['recovery_trials'] >= 3, d
assert d['repl_overhead_pct'] <= 10.0, d
print('chaos_ps OK: recovery p50=%.0fms p95=%.0fms, repl overhead %.1f%%'
      % (d['recovery_ms_p50'], d['recovery_ms_p95'],
         d['repl_overhead_pct']))"
  }
  check_chaos || { echo "chaos_ps retry (ambient-load outlier)"; check_chaos; }
  echo "CI OK (chaos)"
  exit 0
fi

if [[ "${1:-fast}" == "ckpt" ]]; then
  echo "== ckpt gate: crash-consistent job checkpointing (SIGKILL e2e) =="
  # -m "" includes the slow acceptance run: SIGKILL the whole job
  # (trainers + PS) mid-save under an armed kill-job faultpoint,
  # restart, fall back past a deliberately-corrupted newest checkpoint
  # (checksum-detected), resume bit-identical to a fault-free oracle
  python -m pytest tests/test_job_checkpoint.py -q -m ""
  echo "== chaos_ckpt demo (save/restore latency + pause window) =="
  PYTHONPATH="$PWD:${PYTHONPATH:-}" CHAOS_CKPT_TRIALS=3 \
    CHAOS_CKPT_ROWS=20000 python tools/chaos_ckpt.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['fallback_ok'], d
assert d['save_ms_p95'] > 0 and d['restore_ms_p95'] > 0, d
assert 0 < d['pause_ms_p95'] < d['save_ms_p95'], d  # gate excludes bulk IO
print('chaos_ckpt OK: save p95=%.0fms restore p95=%.0fms pause p95=%.1fms'
      % (d['save_ms_p95'], d['restore_ms_p95'], d['pause_ms_p95']))"
  echo "CI OK (ckpt)"
  exit 0
fi

if [[ "${1:-fast}" == "hot_tier" ]]; then
  echo "== hot_tier gate: HBM tier ≡ RPC-only parity + 0-RPC warm steps =="
  # test_hot_kernels.py is the Pallas(interpret) ≡ jnp kernel parity
  # matrix (probe+gather / scatter+apply, all rules, unaligned n);
  # test_hot_tier.py carries the tier-level matrix (eviction churn,
  # adam, checkpoint/restore, banked sharded mesh) incl. the pallas
  # variants — both run before the bench so a rule/kernels regression
  # fails in seconds
  python -m pytest tests/test_hot_tier.py tests/test_hot_kernels.py -q -m ""
  echo "== sparse_hot bench (single-chip + multi-host rung) =="
  PYTHONPATH="$PWD:${PYTHONPATH:-}" SHB_SAMPLES=2048 \
    python tools/sparse_hot_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
# THE acceptance counter: a warm steady-state step performs ZERO PS
# RPCs (RpcPsClient.op_counts delta over the measured epoch)
assert d['hot_tier']['rpc_per_step'] == 0.0, d['hot_tier']
assert d['hot_tier']['hit_rate'] == 1.0, d['hot_tier']
assert d['rpc_only']['rpc_per_step'] > 0, d['rpc_only']
# the multi-host rung (8 virtual CPU devices in a subprocess when the
# backend is single-device): warm sharded steps are 0-RPC too, and the
# hlo_bytes proof — the routed all_to_all id/vector exchange moves
# FEWER collective bytes than the gathered (all_gather+reduce_scatter)
# formulation. Byte counts come from the compiled HLO, so this assert
# is deterministic on a noisy box where timing is not.
s = d['sharded']; assert 'error' not in s, s
assert s['rpc_per_step'] == 0.0 and s['hit_rate'] == 1.0, s
assert s['shards'] == 8 and s['banks'] == 8, s
ex = s['exchange']
assert 0 < ex['alltoall']['exchange_bytes'] \
    < ex['gathered']['exchange_bytes'], ex
print('sparse_hot OK: %.0f samples/s single (%.2fx vs rpc-only), '
      '%.0f samples/s sharded, a2a exchange %.2fx of gathered bytes'
      % (d['value'], d['speedup_vs_rpc_only'], s['samples_per_sec'],
         ex['alltoall_over_gathered']))"
  echo "CI OK (hot_tier)"
  exit 0
fi

if [[ "${1:-fast}" == "serving" ]]; then
  echo "== serving gate: oplog-fed replicas + frontend (chaos incl.) =="
  # -m "" for symmetry with the other gates (the serving suite is all
  # fast today — the failover acceptance test included)
  python -m pytest tests/test_serving.py -q -m ""
  echo "== serving bench (warm p99 + push→servable freshness) =="
  # thresholds carry shared-2-core-host headroom (the committed
  # SERVING.json shows the quiet-host numbers: single-digit warm p99,
  # freshness p95 well under the 100 ms SLO); one retry absorbs
  # ambient-load outliers, the zero-RPC and zero-failure asserts are
  # exact on every attempt
  check_serving() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu SB_REQUESTS=1000 \
      python tools/serving_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['warm']['rpc_per_request'] == 0.0, d['warm']
assert d['warm']['shed'] == 0 and d['warm']['deadline_misses'] == 0, d['warm']
assert d['freshness_failures'] == 0, d['freshness']
assert d['warm']['request_ms']['p99_ms'] <= 50.0, d['warm']
assert d['freshness']['p95_ms'] <= 250.0, d['freshness']
print('serving OK: warm p99=%.1fms qps=%.0f, push→servable p95=%.1fms'
      % (d['warm']['request_ms']['p99_ms'], d['warm']['qps'],
         d['freshness']['p95_ms']))"
  }
  check_serving || { echo "serving retry (ambient-load outlier)"; check_serving; }
  echo "CI OK (serving)"
  exit 0
fi

if [[ "${1:-fast}" == "serving_fleet" ]]; then
  echo "== serving_fleet gate: router / fleet / rollout suite =="
  # -m "" for symmetry; the suite is all fast (stub-member router
  # semantics + real-replica fleet joins/drains/crash + the rollout
  # lifecycle incl. the primary-promotion re-attach heal)
  python -m pytest tests/test_serving_fleet.py -q -m ""
  echo "== fleet bench (open-loop replay + chaos + canary cycle) =="
  # gate the INVARIANTS exactly (zero errors through a kill-replica
  # round AND a draining restart, hedge rate bounded, warm-handoff
  # misses < cold-join misses, canary split exact + digest-pinned
  # rollback) and the throughput only loosely — absolute qps/p99 on a
  # shared 1-core box swing 2-3x with ambient load (the committed
  # SERVING_FLEET.json is the quiet-host run that also meets the
  # ≥baseline-qps / ≤2x-p99 acceptance); one retry absorbs outliers
  check_fleet() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      SFB_KEYS=8000 SFB_STEADY=2000 SFB_CHUNK=800 \
      python tools/serving_fleet_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['steady']['errors'] == 0, d['steady']
assert d['chaos_kill']['errors'] == 0, d['chaos_kill']
assert d['drain_restart']['errors'] == 0, d['drain_restart']
assert d['chaos_kill']['members_after'] == d['chaos_kill']['members_before'] - 1
assert d['steady']['hedge_rate'] <= 0.25, d['steady']
assert d['join']['warm']['misses'] < d['join']['cold']['misses'], d['join']
assert d['canary']['split_exact'], d['canary']
assert d['canary']['rollback_digest_ok'], d['canary']
assert d['steady']['achieved_qps'] >= 0.5 * d['steady']['target_qps'], d['steady']
print('serving_fleet OK: steady %.0f qps (p99 %.1f ms), capacity %.0f qps, '
      'kill+drain 0 errors, hedge %.1f%%, warm/cold misses %d/%d'
      % (d['steady']['achieved_qps'], d['steady']['request_ms']['p99_ms'],
         d['saturation']['achieved_qps'], 100 * d['steady']['hedge_rate'],
         d['join']['warm']['misses'], d['join']['cold']['misses']))"
  }
  check_fleet || { echo "serving_fleet retry (ambient-load outlier)"; check_fleet; }
  echo "CI OK (serving_fleet)"
  exit 0
fi

if [[ "${1:-fast}" == "recsys" ]]; then
  echo "== recsys gate: retrieval→ranking pipeline suite (incl. slow e2e) =="
  # -m "" deliberately includes the slow multi-process chaos e2e test
  python -m pytest tests/test_recsys_pipeline.py -q -m ""
  echo "== recsys replay (ramp + flash crowd + chaos + canary, multi-host members) =="
  # gate the INVARIANTS exactly (zero errors through the chaos kill and
  # the flash crowd, autoscaler journaled a grow, ranking actually
  # coalesced across requests, fleet-wide freshness bounded while the
  # trainer streams, canary/promote/rollback verified over the wire)
  # and latency only against the request deadline — absolute p99 on a
  # shared 1-core box swings with ambient load; one retry absorbs it.
  # The committed RECSYS_E2E.json is the quiet-host run of this exact
  # profile.
  check_recsys() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      RRB_KEYS=8000 RRB_MEMBERS=2 RRB_BASE_QPS=10 RRB_PEAK_QPS=40 \
      RRB_SPIKE_X=4 RRB_SLO_MS=60 RRB_DEADLINE_MS=8000 \
      RRB_RAMP_S=10 RRB_SPIKE_S=6 RRB_TAIL_S=6 RRB_SCALE_WAIT_S=45 \
      python tools/recsys_replay.py | tee /tmp/recsys_e2e_ci.json \
      | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['errors_total'] == 0, d['errors_total']
for ph in ('ramp', 'spike', 'tail'):
    assert d[ph]['within_deadline'], (ph, d[ph])
assert d['ramp']['members_before'] >= 2 and d['ramp']['killed'], d['ramp']
assert d['autoscale']['grew'], d['autoscale']
assert d['pipeline']['coalesce_factor'] > 1.0, d['pipeline']
assert d['spike']['coalesce_factor'] > 1.5, d['spike']
f = d['freshness_under_training']
assert f['failures'] == 0 and f['probes'] >= 5, f
assert f['p95_s'] is not None and f['p95_s'] <= 5.0, f
assert d['canary']['both_versions_served'], d['canary']
assert d['canary']['promoted_all'], d['canary']
assert d['canary']['rollback_digest_ok'], d['canary']
assert all(m['multi_host'] for m in d['members'].values()), d['members']
print('recsys OK: e2e %.0f qps, ramp/spike/tail p99 %.0f/%.0f/%.0f ms, '
      'coalesce %.2fx (spike %.2fx), freshness p95 %.2f s, '
      'grew=%s, 0 errors through chaos'
      % (d['value'], d['ramp']['e2e_ms']['p99_ms'],
         d['spike']['e2e_ms']['p99_ms'], d['tail']['e2e_ms']['p99_ms'],
         d['pipeline']['coalesce_factor'], d['spike']['coalesce_factor'],
         f['p95_s'], d['autoscale']['grew']))"
  }
  check_recsys || { echo "recsys retry (ambient-load outlier)"; check_recsys; }
  python -c "
import json
d = json.loads([l for l in open('/tmp/recsys_e2e_ci.json')
                if l.startswith('{')][-1])
open('RECSYS_E2E.json', 'w').write(json.dumps(d, indent=4) + '\n')
" 2>/dev/null || true
  echo "CI OK (recsys)"
  exit 0
fi

if [[ "${1:-fast}" == "slo" ]]; then
  echo "== slo gate: continuous telemetry / watchdog / flight recorder =="
  # -m "" includes the slow e2e: kill-shard mid-CtrStreamTrainer →
  # failover/breaker alerts + a postmortem bundle with the failing
  # request spans and the recovery visible in the metric timeline
  python -m pytest tests/test_slo.py tests/test_flightrec.py -q -m ""
  echo "== slo demo (injected degradation → alert → bundle → exporter) =="
  check_slo() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      SLO_OUT=/tmp/ci_obs_timeseries.json python tools/slo_demo.py \
      | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['alert']['rule'] == 'step_time_p95', d['alert']
assert d['alert_cleared'], d
assert d['bundle']['alert_in_degraded_window'], d['bundle']
assert d['bundle']['spans'] > 0, d['bundle']
assert d['bundle']['alert_instants_in_trace'] > 0, d['bundle']
assert d['openmetrics_ok'] and d['openmetrics_families'] > 5, d
assert d['timeline_alert_instants'] > 0, d
print('slo demo OK: alert @%.1fms threshold, bundle %s (%d spans), '
      '%d OpenMetrics families'
      % (d['threshold_ms'], d['bundle']['reason'], d['bundle']['spans'],
         d['openmetrics_families']))"
  }
  check_slo || { echo "slo demo retry (ambient-load outlier)"; check_slo; }
  echo "== obs overhead bench (sampler+watchdog inside the 2% budget) =="
  # same one-retry discipline as the obs gate: the min-over-passes
  # estimator still loses to whole-pass noisy-neighbor weather on this
  # VM (±30% swings observed at zero local load)
  check_slo_overhead() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      python tools/obs_overhead_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['value'] <= 2.0, d
assert d['sampler_ticks'] > 0 and d['watchdog_evaluations'] > 0, d
assert d['alerts_fired'] == 0, d  # healthy run: nothing may fire
print('slo overhead OK: %+.2f%% with %d sampler ticks, %d rule evals'
      % (d['value'], d['sampler_ticks'], d['watchdog_evaluations']))"
  }
  check_slo_overhead || { echo "slo overhead retry (ambient-load outlier)"; \
    check_slo_overhead; }
  echo "CI OK (slo)"
  exit 0
fi

if [[ "${1:-fast}" == "reshard" ]]; then
  echo "== reshard gate: live elastic resharding + SLO autoscaling =="
  # -m "" includes the slow chaos e2e: grow 2→4 + shrink 4→2 mid-
  # CtrStreamTrainer with a kill-shard during one migration, final
  # state bit-identical to an unresharded oracle
  python -m pytest tests/test_reshard.py tests/test_autoscale.py -q -m ""
  echo "== reshard demo (wave → SLO fire → grow → clear → shrink) =="
  check_reshard() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      RESHARD_OUT=/tmp/ci_reshard.json python tools/reshard_demo.py \
      | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['scaled_up']['to_shards'] == 4, d['scaled_up']
assert d['scaled_down']['to_shards'] == 2, d['scaled_down']
assert d['alert_cleared'] and d['shards_final'] == 2, d
# gate-hold must be a small fraction of the full-copy bootstrap —
# the reason snapshot+tail+fence beats stop-the-world
assert 0 < d['gate_hold_over_copy'] < 0.5, d
assert d['trainer_np_target'] == 2, d
print('reshard demo OK: wave fired %s, grow pause %.0fms vs copy '
      '%.0fms (ratio %.2f), shrink pause %.0fms, journal closed the '
      'loop'
      % (d['alert']['rule'], d['scaled_up']['cutover_pause_ms'],
         d['scaled_up']['bootstrap_s'] * 1e3, d['gate_hold_over_copy'],
         d['scaled_down']['cutover_pause_ms']))"
  }
  check_reshard || { echo "reshard demo retry (ambient-load outlier)"; \
    check_reshard; }
  echo "CI OK (reshard)"
  exit 0
fi

if [[ "${1:-fast}" == "reconcile" ]]; then
  echo "== reconcile gate: declarative control plane (spec/reconciler/simulator) =="
  # -m "" includes the slow compound-transition chaos e2e: canary open
  # + grow 2→4 proposed as ONE spec update, kill-shard mid-bootstrap,
  # digests/params bit-identical to a sequential direct-primitive oracle
  python -m pytest tests/test_reconcile.py -q -m ""
  echo "== game-day chaos schedule (spec-driven drill, armed faultpoints) =="
  # grow-under-fire / canary open+rollback via spec / shrink back —
  # every transition written as desired state, the journal must close
  # the loop on every step and the content digest must round-trip
  check_gameday() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      GAMEDAY_OUT=${GAMEDAY_OUT:-/tmp/ci_gameday.json} \
      python tools/gameday.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['digest_ok'] and d['traffic']['errors'] == 0, d
assert d['shards_final'] == 2, d
assert d['promotions'] >= 1, d   # the kill really fired mid-grow
steps = {s['step'] for s in d['schedule']}
assert steps == {'grow_under_fire', 'canary_open', 'canary_rollback',
                 'shrink'}, steps
assert all(s['converged'] for s in d['schedule']), d['schedule']
print('gameday OK: %d schedule steps converged, %d promotions under '
      'fire, digest round-tripped, %d pulls 0 errors (%.1fs)'
      % (len(d['schedule']), d['promotions'], d['traffic']['pulls'],
         d['wall_s']))"
  }
  check_gameday || { echo "gameday retry (ambient-load outlier)"; \
    check_gameday; }
  echo "== policy simulator (committed traces, 1000-shard scale) =="
  # the acceptance case: the stock policy rides RESHARD.json's diurnal
  # wave cleanly AND a hysteresis inversion is caught as oscillation —
  # both replays must finish inside the wall budget
  PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu python -c "
from paddle_tpu.ps.autoscale import AutoscaleConfig
from paddle_tpu.ps.simulate import (diurnal_wave_profile,
                                    flash_crowd_profile, simulate)
stock = simulate(AutoscaleConfig(min_shards=256, max_shards=1024),
                 diurnal_wave_profile('RESHARD.json', base_shards=512))
assert stock.wall_s < 60.0 and stock.max_shards_seen() == 1024, vars(stock)
assert stock.oscillations(15.0) == 0, stock.scale_events
broken = simulate(AutoscaleConfig(min_shards=256, max_shards=1024,
                                  cooldown_up_s=0.0, cooldown_down_s=0.0,
                                  clear_hold_s=0.0),
                  diurnal_wave_profile('RESHARD.json', base_shards=512),
                  fire_after_ticks=1, clear_after_ticks=1)
assert broken.oscillations(15.0) >= 5, broken.scale_events
flash = simulate(AutoscaleConfig(min_shards=256, max_shards=1024),
                 flash_crowd_profile('RECSYS_E2E.json', base_shards=256))
assert flash.wall_s < 60.0 and flash.oscillations(15.0) == 0, vars(flash)
print('simulator OK: diurnal %d ticks %.3fs wall (peak %d, 0 osc), '
      'inverted hysteresis caught (%d rapid reversals), flash crowd '
      'peak %d -> final %d'
      % (stock.ticks, stock.wall_s, stock.max_shards_seen(),
         broken.oscillations(15.0), flash.max_shards_seen(),
         flash.final_shards))"
  echo "CI OK (reconcile)"
  exit 0
fi

if [[ "${1:-fast}" == "obs" ]]; then
  echo "== obs gate: unified observability plane =="
  python -m pytest tests/test_obs.py -q -m ""
  echo "== obs overhead bench (metrics ≤2% on the DeepFM stream step) =="
  # interleaved A/B over ONE shared cluster, trimmed-mean of paired
  # per-round ratios, min over up to 3 passes (noisy-neighbor VM —
  # see the bench docstring); one retry covers the residual. The wire
  # asserts (fixed header, zero extra bytes with tracing off) and the
  # snapshot asserts (≥3 processes, wire bytes, density) are exact.
  check_obs() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      python tools/obs_overhead_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['value'] <= 2.0, d
assert d['wire_header_bytes'] == 28 + d['trace_ctx_bytes'], d
assert d['tracing_off_extra_header_bytes'] == 0, d
assert d['job_processes'] >= 3, d
assert any(v > 0 for v in d['server_wire_bytes'].values()), d
assert d['client_density'] and \
    all(0 < v <= 1.0 for v in d['client_density'].values()), d
print('obs overhead OK: %+.2f%% (on %.1fms / off %.1fms), header %dB '
      'fixed, %d-process snapshot'
      % (d['value'], d['step_ms_metrics_on'], d['step_ms_metrics_off'],
         d['wire_header_bytes'], d['job_processes']))"
  }
  check_obs || { echo "obs overhead retry (ambient-load outlier)"; check_obs; }
  echo "== obs trace demo (flow-linked cross-process timeline) =="
  PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
    OBS_TRACE_OUT=/tmp/ci_obs_trace.json python tools/obs_trace_demo.py \
    | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['flow_links'] > 0 and d['client_pull_spans'] > 0, d
assert d['server_pull_spans'] > 0 and d['job_processes'] >= 3, d
print('obs trace demo OK: %d flow links across %d events, %d processes'
      % (d['flow_links'], d['events'], d['job_processes']))"
  echo "CI OK (obs)"
  exit 0
fi

if [[ "${1:-fast}" == "tenancy" ]]; then
  echo "== tenancy gate: multi-tenant isolation suite (incl. slow interference e2e) =="
  # -m "" deliberately includes the slow abusive-neighbor e2e (four
  # well-behaved tenants + a flood that must throttle/quota-refuse
  # without moving a neighbor's p99 or writing one foreign row)
  python -m pytest tests/test_tenancy.py -q -m ""
  echo "== tenancy bench (workload zoo + abusive neighbor, marginal-p99 isolation) =="
  # the namespace/quota/digest asserts are exact on every attempt; the
  # p99 gate is the abuser's MARGINAL damage (abused vs shared — the
  # zoo running without the abuser), because solo→shared movement on a
  # shared 1-core box is CPU scheduling, not an isolation failure. The
  # 5x + 20 ms bound carries ambient-load headroom (the committed
  # TENANCY.json shows the quiet-host worst ratio: ~1.3x); one retry
  # absorbs the residual outliers.
  check_tenancy() {
    PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu \
      python tools/tenancy_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines()
                if l.startswith('{')][-1])
assert 'error' not in d, d
for n, t in d['tenants'].items():
    assert t['abused']['p99_ms'] <= 5.0 * t['shared']['p99_ms'] + 20.0, (n, t)
assert d['abuse']['flood']['throttled'] > 0, d['abuse']
assert d['abuse']['rows_within_cap'], d['abuse']
assert d['isolation']['cross_tenant_breaches'] == 0, d['isolation']
assert d['isolation']['cross_tenant_probes_bounced'] > 0, d['isolation']
assert d['isolation']['digest_stable_under_abuse'], d['isolation']
assert d['isolation']['wb_rows_unchanged'], d['isolation']
worst = max(d['tenants'].items(), key=lambda kv: kv[1]['p99_ratio'])
print('tenancy OK: worst marginal p99 %.2fx (%s), abuser throttled %d / '
      'quota-refused %d, %d cross-tenant probes bounced, 0 breaches'
      % (worst[1]['p99_ratio'], worst[0],
         d['abuse']['flood']['throttled'], d['abuse']['flood']['quota'],
         d['isolation']['cross_tenant_probes_bounced']))"
  }
  check_tenancy || { echo "tenancy retry (ambient-load outlier)"; check_tenancy; }
  echo "CI OK (tenancy)"
  exit 0
fi

echo "== hot-tier fast checks (parity / eviction churn / 0-RPC warm) =="
# the hot tier's bit-parity contract is the cheapest place to catch a
# sparse-rule or flush-back regression — fail it before the full matrix
# (test_hot_kernels.py = the fused Pallas-kernel half of the contract)
python -m pytest tests/test_hot_tier.py tests/test_hot_kernels.py -q

echo "== comm-fusion fast checks (fused dense-DP collectives + hlo_bytes) =="
# fail the fused-bucket/quantized-collective layer in seconds, before the
# full matrix — these cover the wire-byte acceptance gates directly
python -m pytest tests/test_comm_fusion.py tests/test_hlo_bytes.py -q

echo "== sparse-wire + placement fast checks (quantized push wire / swap) =="
# the ISSUE 14 loop: quantized push wire (EF parity, drain-at-quiesce,
# replicated-frame bit-identity, csrc dequant rejection) and the
# density-measured placement swap at a live reshard epoch fence —
# cheapest place to catch an encode/decode or swap-accounting regression
python -m pytest tests/test_sparse_wire.py tests/test_placement.py -q

echo "== fast gate (default: -m 'not slow') =="
# hot-tier/comm-fusion/hlo_bytes/sparse-wire already ran above — don't
# pay them twice
python -m pytest tests/ -q -x \
  --ignore=tests/test_comm_fusion.py --ignore=tests/test_hlo_bytes.py \
  --ignore=tests/test_hot_tier.py --ignore=tests/test_hot_kernels.py \
  --ignore=tests/test_sparse_wire.py --ignore=tests/test_placement.py

if [[ "${1:-fast}" == "full" ]]; then
  echo "== full matrix (slow tests included) =="
  python -m pytest tests/ -q -m ""
  echo "== driver artifacts =="
  python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8); print('dryrun OK')"
  echo "== artifact tools smoke (tiny shapes, CPU) =="
  PYTHONPATH="$PWD:${PYTHONPATH:-}" SSD_DEMO_POP=200000 SSD_DEMO_PASS_KEYS=20000 \
    SSD_DEMO_PASSES=1 python tools/ssd_scale_demo.py | python -c \
    "import json,sys; d=json.load(sys.stdin); assert 'error' not in d, d; print('ssd_scale_demo OK')"
  PYTHONPATH="$PWD:${PYTHONPATH:-}" WD_POP=200000 WD_RECORDS=5000 WD_DAYS=1 \
    python tools/widedeep_daily.py | python -c \
    "import json,sys; d=json.load(sys.stdin); assert 'error' not in d, d; print('widedeep_daily OK')"
  PYTHONPATH="$PWD:${PYTHONPATH:-}" ANCHOR_POP=130000 ANCHOR_DAYS=1 \
    ANCHOR_STEPS_PER_DAY=20 ANCHOR_BATCH=256 ANCHOR_EVAL_EVERY=5 \
    ANCHOR_OUT=/tmp/ci_anchor_v2.json \
    python tools/make_anchor_v2.py | python -c \
    "import json,sys; d=json.loads(sys.stdin.read().splitlines()[-1]); \
assert d['gates']['parity_ok'], d; print('anchor_v2 parity OK')"
  # bench/tpu_smoke intentionally exit 0 on failure (one-JSON-line
  # driver contract), so they must run as SUBPROCESSES with the check
  # in a separate process — an in-process runpy assert would be skipped
  # by their sys.exit(0) error paths
  SMOKE_OUT=/tmp/ci_tpu_smoke_light.json SMOKE_LIGHT=1 SMOKE_INIT_TIMEOUT=30 \
    SMOKE_PLATFORM=cpu python tools/tpu_smoke.py > /dev/null
  python -c "
import json
d = json.load(open('/tmp/ci_tpu_smoke_light.json')); assert d['ok'], d
print('tpu_smoke (light) OK')"
  # BENCH_SPARSE_HOT=0: the dedicated sparse_hot gate below already
  # runs (and asserts on) the hot-tier bench — the embedded emission
  # would pay two more PS clusters + 4 DeepFM epochs here, unasserted
  BENCH_STEPS=5 BENCH_WARMUP=1 BENCH_PASS_KEYS=$((1 << 14)) \
    BENCH_INIT_TIMEOUT=60 BENCH_PLATFORM=cpu BENCH_SPARSE_HOT=0 \
    python bench.py | python -c "
import json, sys
line = [l for l in sys.stdin.read().splitlines() if l.startswith('{')][-1]
d = json.loads(line); assert d['value'] > 0 and 'error' not in d, d
print('bench (cpu) OK')"
  # sparse push-wire ladder: the int8 wire must actually shrink the
  # SPARSE RPC push stream — ≥3× fewer bytes than fp32, asserted from
  # the PR 8 per-table byte counters (steady-state wire; the terminal
  # error-feedback drain is reported apart as a checkpoint-boundary
  # cost). Byte counts are exact — deterministic on a noisy box.
  PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu SWB_STEPS=8 \
    python tools/sparse_wire_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines() if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['value'] >= 3.0, d
by = {r['wire']: r for r in d['ladder']}
assert by['int8']['residual_rows_drained'] > 0, by  # EF really drained
assert by['fp16']['push_wire_bytes'] < by['fp32']['push_wire_bytes'], by
print('sparse wire ladder OK (int8 moves %.2fx fewer push bytes; '
      'fp16 %.2fx)' % (d['value'], d['ratio_fp32_over_fp16']))"
  # dense-DP comm ladder: int8 must actually shrink the wire (hlo_bytes-
  # measured ≥3.5× fewer collective bytes than fused fp32)
  JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    DCB_BATCH=256 DCB_STEPS=3 DCB_HIDDEN=128 \
    python tools/dense_comm_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines() if l.startswith('{')][-1])
assert 'error' not in d, d
ladder = {r['mode']: r for r in d['ladder']}
i8 = ladder['fused+int8']['collective_wire_bytes_per_step']
f32 = ladder['fused+fp32']['collective_wire_bytes_per_step']
assert f32 >= 3.5 * i8, ladder
print('dense comm ladder OK (int8 moves %.1fx fewer bytes)' % (f32 / i8))"
  # hot-embedding tier: a warm steady-state step must perform ZERO PS
  # RPCs (RpcPsClient.op_counts — the ISSUE 6 acceptance counter) and
  # the tier must not lose to the RPC-only path it replaces.
  # SHB_SHARDED=0: the dedicated hot_tier gate asserts the multi-host
  # rung (8-virtual-dev subprocess + exchange-byte proof) — the
  # embedded copy here would pay another PS cluster + mesh compile
  # unasserted
  PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu SHB_SAMPLES=2048 \
    SHB_SHARDED=0 python tools/sparse_hot_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines() if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['hot_tier']['rpc_per_step'] == 0.0, d['hot_tier']
assert d['hot_tier']['hit_rate'] == 1.0, d['hot_tier']
print('sparse_hot OK: 0 rpc/step warm, %.2fx vs rpc-only'
      % d['speedup_vs_rpc_only'])"
  # serving plane: warm requests perform ZERO RPCs and every freshness
  # probe lands (the dedicated `serving` gate asserts the latency
  # thresholds too — this full-gate copy pins the exact invariants at
  # a smaller scale)
  PYTHONPATH="$PWD:${PYTHONPATH:-}" JAX_PLATFORMS=cpu SB_KEYS=5000 \
    SB_REQUESTS=500 SB_PROBES=10 python tools/serving_bench.py | python -c "
import json, sys
d = json.loads([l for l in sys.stdin.read().splitlines() if l.startswith('{')][-1])
assert 'error' not in d, d
assert d['warm']['rpc_per_request'] == 0.0, d['warm']
assert d['freshness_failures'] == 0, d['freshness']
print('serving OK: warm p99=%.1fms, push→servable p95=%.1fms, 0 rpc warm'
      % (d['warm']['request_ms']['p99_ms'], d['freshness']['p95_ms']))"
  # the graceful-degradation ladder must actually engage (a hardware
  # compile failure in a new hot path costs an attempt, not the metric)
  BENCH_STEPS=3 BENCH_WARMUP=1 BENCH_BATCH=256 BENCH_PASS_KEYS=$((1 << 13)) \
    BENCH_INIT_TIMEOUT=60 BENCH_PLATFORM=cpu BENCH_SPARSE_HOT=0 \
    BENCH_FORCE_FAIL=amp+dense,dense python bench.py | python -c "
import json, sys
line = [l for l in sys.stdin.read().splitlines() if l.startswith('{')][-1]
d = json.loads(line)
assert d['value'] > 0 and d['mode'] == 'sparse' and d['degraded_from'], d
print('bench degradation ladder OK')"

  echo "== TSAN sweep (table/RPC/graph concurrency surfaces) =="
  # gate: OUR instrumented .so must stay report-free; third-party libs
  # (libjax_common Eigen/MLIR pools, libgcc unwind) are uninstrumented
  # and their shutdown-order mutex noise is filtered by the grep below,
  # not silently swallowed — the log files stay in /tmp for inspection.
  # The EXIT trap restores the normal flavor even when the sweep fails
  # (a leftover TSAN .so breaks every later non-preloaded import).
  trap 'make -C paddle_tpu/csrc -s' EXIT
  make -C paddle_tpu/csrc SANITIZE=thread -s
  rm -f /tmp/ci_tsan_report*
  # exitcode=0: TSAN's default exit-66-if-anything-reported would mask
  # pytest's own status behind unavoidable third-party noise — the grep
  # below is the gate for OUR code, pytest's exit code for the tests
  # OPENBLAS_NUM_THREADS=1: numpy-2.x's OpenBLAS pool spawns at import
  # and deadlocks every LATER fork under the sanitizer preload (the
  # first lazy `np.testing` import runs an lscpu subprocess — the whole
  # sweep wedged there, 0% CPU). BLAS parallelism buys nothing under a
  # 10-20x sanitizer anyway.
  # shim pass-through smoke FIRST: under the sanitizer the sync shim
  # must hand back raw threading primitives (scheduler uninstalled) so
  # TSAN instruments the real locks — a shim that wrapped them in
  # Python objects would mask every native-level report below
  LD_PRELOAD="$(gcc -print-file-name=libtsan.so)" OPENBLAS_NUM_THREADS=1 \
    TSAN_OPTIONS="suppressions=$PWD/paddle_tpu/csrc/tsan.supp,halt_on_error=0,exitcode=0,log_path=/tmp/ci_tsan_report" \
    python -c "
import queue, threading
from paddle_tpu.core import sync as _sync
assert _sync.current_scheduler() is None
assert isinstance(_sync.Lock(), type(threading.Lock()))
assert isinstance(_sync.Condition(), threading.Condition)
assert isinstance(_sync.Queue(maxsize=2), queue.Queue)
t = _sync.Thread(target=lambda: None, name='shim-smoke'); t.start(); t.join()
print('sync shim pass-through OK (sanitizer sees raw primitives)')"
  LD_PRELOAD="$(gcc -print-file-name=libtsan.so)" OPENBLAS_NUM_THREADS=1 \
    TSAN_OPTIONS="suppressions=$PWD/paddle_tpu/csrc/tsan.supp,halt_on_error=0,exitcode=0,log_path=/tmp/ci_tsan_report" \
    python -m pytest tests/test_table_concurrency.py tests/test_ssd_table.py \
      tests/test_native_table.py tests/test_ps_rpc.py \
      tests/test_rpc_robustness.py tests/test_dist_graph.py \
      tests/test_rpc_parallel.py tests/test_ps_ha.py \
      tests/test_job_checkpoint.py tests/test_serving.py \
      tests/test_serving_fleet.py \
      tests/test_recsys_pipeline.py \
      tests/test_obs.py tests/test_slo.py tests/test_flightrec.py \
      tests/test_reshard.py tests/test_autoscale.py \
      tests/test_reconcile.py \
      tests/test_sparse_wire.py tests/test_tenancy.py -q -m ""
  if grep -l "libpaddle_tpu_native" /tmp/ci_tsan_report* 2>/dev/null; then
    echo "TSAN: reports implicate libpaddle_tpu_native.so (see /tmp/ci_tsan_report*)"
    exit 1
  fi
  echo "TSAN sweep OK (no reports in our .so)"

  echo "== ASAN sweep (same surfaces; heap/stack/use-after-free) =="
  # same contract as TSAN: detect_leaks=0 because the uninstrumented
  # Python/jax runtime "leaks" by design at interpreter exit; exitcode=0
  # so pytest's status gates the tests and the grep gates OUR .so
  make -C paddle_tpu/csrc SANITIZE=address -s
  rm -f /tmp/ci_asan_report*
  LD_PRELOAD="$(gcc -print-file-name=libasan.so)" OPENBLAS_NUM_THREADS=1 \
    ASAN_OPTIONS="detect_leaks=0,halt_on_error=0,exitcode=0,log_path=/tmp/ci_asan_report" \
    python -c "
import queue, threading
from paddle_tpu.core import sync as _sync
assert _sync.current_scheduler() is None
assert isinstance(_sync.Lock(), type(threading.Lock()))
assert isinstance(_sync.Condition(), threading.Condition)
assert isinstance(_sync.Queue(maxsize=2), queue.Queue)
t = _sync.Thread(target=lambda: None, name='shim-smoke'); t.start(); t.join()
print('sync shim pass-through OK (sanitizer sees raw primitives)')"
  LD_PRELOAD="$(gcc -print-file-name=libasan.so)" OPENBLAS_NUM_THREADS=1 \
    ASAN_OPTIONS="detect_leaks=0,halt_on_error=0,exitcode=0,log_path=/tmp/ci_asan_report" \
    python -m pytest tests/test_table_concurrency.py tests/test_ssd_table.py \
      tests/test_native_table.py tests/test_ps_rpc.py \
      tests/test_rpc_robustness.py tests/test_dist_graph.py \
      tests/test_rpc_parallel.py tests/test_ps_ha.py \
      tests/test_job_checkpoint.py tests/test_serving.py \
      tests/test_serving_fleet.py \
      tests/test_recsys_pipeline.py \
      tests/test_obs.py tests/test_slo.py tests/test_flightrec.py \
      tests/test_reshard.py tests/test_autoscale.py \
      tests/test_reconcile.py \
      tests/test_sparse_wire.py tests/test_tenancy.py -q -m ""
  if grep -l "libpaddle_tpu_native" /tmp/ci_asan_report* 2>/dev/null; then
    echo "ASAN: reports implicate libpaddle_tpu_native.so (see /tmp/ci_asan_report*)"
    exit 1
  fi
  echo "ASAN sweep OK (no reports in our .so)"

  echo "== UBSAN sweep (same surfaces; UB: overflow/alignment/bounds) =="
  # UBSAN's runtime is linked into the sanitized .so itself, so no
  # LD_PRELOAD; halt_on_error=0 collects every report into the log
  make -C paddle_tpu/csrc SANITIZE=undefined -s
  rm -f /tmp/ci_ubsan_report*
  OPENBLAS_NUM_THREADS=1 \
    UBSAN_OPTIONS="print_stacktrace=1,halt_on_error=0,log_path=/tmp/ci_ubsan_report" \
    python -c "
import queue, threading
from paddle_tpu.core import sync as _sync
assert _sync.current_scheduler() is None
assert isinstance(_sync.Lock(), type(threading.Lock()))
assert isinstance(_sync.Condition(), threading.Condition)
assert isinstance(_sync.Queue(maxsize=2), queue.Queue)
t = _sync.Thread(target=lambda: None, name='shim-smoke'); t.start(); t.join()
print('sync shim pass-through OK (sanitizer sees raw primitives)')"
  OPENBLAS_NUM_THREADS=1 \
    UBSAN_OPTIONS="print_stacktrace=1,halt_on_error=0,log_path=/tmp/ci_ubsan_report" \
    python -m pytest tests/test_table_concurrency.py tests/test_ssd_table.py \
      tests/test_native_table.py tests/test_ps_rpc.py \
      tests/test_rpc_robustness.py tests/test_dist_graph.py \
      tests/test_rpc_parallel.py tests/test_ps_ha.py \
      tests/test_job_checkpoint.py tests/test_serving.py \
      tests/test_serving_fleet.py \
      tests/test_recsys_pipeline.py \
      tests/test_obs.py tests/test_slo.py tests/test_flightrec.py \
      tests/test_reshard.py tests/test_autoscale.py \
      tests/test_reconcile.py \
      tests/test_sparse_wire.py tests/test_tenancy.py -q -m ""
  if grep -l "libpaddle_tpu_native" /tmp/ci_ubsan_report* 2>/dev/null; then
    echo "UBSAN: reports implicate libpaddle_tpu_native.so (see /tmp/ci_ubsan_report*)"
    exit 1
  fi
  echo "UBSAN sweep OK (no reports in our .so)"

  make -C paddle_tpu/csrc -s   # restore the normal flavor now
  trap - EXIT
fi
echo "CI OK"
