"""Auto-parallel tests (reference unittests/auto_parallel/
test_engine_api.py, test_shard_tensor_api.py patterns, on the 8-device
CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.auto_parallel import (
    Engine,
    ProcessMesh,
    annotate,
    shard_op,
    shard_tensor,
)


class TestProcessMesh:
    def test_shape_and_names(self):
        pm = ProcessMesh(shape=(2, 4), dim_names=("x", "y"))
        assert pm.ndim == 2
        assert pm.jax_mesh.shape == {"x": 2, "y": 4}

    def test_too_many_devices(self):
        with pytest.raises(Exception):
            ProcessMesh(shape=(1000,), dim_names=("dp",))


class TestShardTensor:
    def test_concrete_array(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))
        x = shard_tensor(np.zeros((16, 4), np.float32), pm, [0, None])
        assert x.sharding.spec[0] == "dp"

    def test_replicated_mapping(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))
        x = shard_tensor(np.zeros((16, 4), np.float32), pm, [-1, None])
        assert x.sharding.spec == jax.sharding.PartitionSpec(None, None) or \
            x.sharding.spec == jax.sharding.PartitionSpec()

    def test_in_graph_constraint(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))

        @jax.jit
        def f(x):
            y = x * 2
            return annotate(y, pm, [0, None])

        out = f(jnp.ones((16, 4)))
        np.testing.assert_allclose(np.asarray(out), 2.0)

    def test_shard_op(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))
        fn = shard_op(lambda x: x + 1, pm, [[0, None]])
        out = jax.jit(fn)(jnp.zeros((8, 2)))
        np.testing.assert_allclose(np.asarray(out), 1.0)


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


class TestEngine:
    def _data(self, n_batches=6, bs=16):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(8, 4)).astype(np.float32)
        out = []
        for _ in range(n_batches):
            x = rng.normal(size=(bs, 8)).astype(np.float32)
            y = (x @ w).argmax(-1).astype(np.int32)
            out.append((x, y))
        return out

    def test_fit_reduces_loss(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy,
                     optimizer.Adam(5e-3),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        data = self._data()
        losses = eng.fit(data, epochs=8)
        assert losses[-1] < losses[0]

    def test_predict_shape(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        out = eng.predict(np.zeros((16, 8), np.float32))
        assert out.shape == (16, 4)

    def test_evaluate(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        val = eng.evaluate(self._data(2))
        assert np.isfinite(val)

    def test_completion_reports_shardings(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        x, y = self._data(1)[0]
        info = eng.completion(x, y)
        assert "input_shardings" in info and "output_shardings" in info


def test_engine_save_load_roundtrip(tmp_path):
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh

    pt.seed(0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.normal(size=(32, 1)).astype(np.float32)

    def make():
        return Engine(nn.Linear(8, 1), nn.functional.mse_loss,
                      optimizer.Adam(1e-2),
                      process_mesh=ProcessMesh(shape=(2,), dim_names=("dp",)))

    pt.seed(0)
    e = make()
    e.fit([(x, y)], epochs=3)
    pred = np.asarray(e.predict(x))
    e.save(str(tmp_path / "snap"))

    pt.seed(0)
    e2 = make()
    e2.load(str(tmp_path / "snap"))
    np.testing.assert_allclose(np.asarray(e2.predict(x)), pred, atol=1e-6)
    # optimizer state restored too: one more identical fit step matches
    l1 = e.fit([(x, y)], epochs=1)
    l2 = e2.fit([(x, y)], epochs=1)
    np.testing.assert_allclose(l2, l1, atol=1e-6)
