"""Auto-parallel tests (reference unittests/auto_parallel/
test_engine_api.py, test_shard_tensor_api.py patterns, on the 8-device
CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from jax.sharding import PartitionSpec

from paddle_tpu.distributed import auto_parallel as auto
from paddle_tpu.distributed.auto_parallel import (
    Engine,
    ProcessMesh,
    annotate,
    shard_op,
    shard_tensor,
)


class TestProcessMesh:
    def test_shape_and_names(self):
        pm = ProcessMesh(shape=(2, 4), dim_names=("x", "y"))
        assert pm.ndim == 2
        assert pm.jax_mesh.shape == {"x": 2, "y": 4}

    def test_too_many_devices(self):
        with pytest.raises(Exception):
            ProcessMesh(shape=(1000,), dim_names=("dp",))


class TestShardTensor:
    def test_concrete_array(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))
        x = shard_tensor(np.zeros((16, 4), np.float32), pm, [0, None])
        assert x.sharding.spec[0] == "dp"

    def test_replicated_mapping(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))
        x = shard_tensor(np.zeros((16, 4), np.float32), pm, [-1, None])
        assert x.sharding.spec == jax.sharding.PartitionSpec(None, None) or \
            x.sharding.spec == jax.sharding.PartitionSpec()

    def test_in_graph_constraint(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))

        @jax.jit
        def f(x):
            y = x * 2
            return annotate(y, pm, [0, None])

        out = f(jnp.ones((16, 4)))
        np.testing.assert_allclose(np.asarray(out), 2.0)

    def test_shard_op(self):
        pm = ProcessMesh(shape=(8,), dim_names=("dp",))
        fn = shard_op(lambda x: x + 1, pm, [[0, None]])
        out = jax.jit(fn)(jnp.zeros((8, 2)))
        np.testing.assert_allclose(np.asarray(out), 1.0)


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


class TestEngine:
    def _data(self, n_batches=6, bs=16):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(8, 4)).astype(np.float32)
        out = []
        for _ in range(n_batches):
            x = rng.normal(size=(bs, 8)).astype(np.float32)
            y = (x @ w).argmax(-1).astype(np.int32)
            out.append((x, y))
        return out

    def test_fit_reduces_loss(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy,
                     optimizer.Adam(5e-3),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        data = self._data()
        losses = eng.fit(data, epochs=8)
        assert losses[-1] < losses[0]

    def test_predict_shape(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        out = eng.predict(np.zeros((16, 8), np.float32))
        assert out.shape == (16, 4)

    def test_evaluate(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        val = eng.evaluate(self._data(2))
        assert np.isfinite(val)

    def test_completion_reports_shardings(self):
        pt.seed(0)
        eng = Engine(_MLP(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                     ProcessMesh(shape=(8,), dim_names=("dp",)))
        x, y = self._data(1)[0]
        info = eng.completion(x, y)
        assert "input_shardings" in info and "output_shardings" in info


def test_engine_save_load_roundtrip(tmp_path):
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer
    from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh

    pt.seed(0)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.normal(size=(32, 1)).astype(np.float32)

    def make():
        return Engine(nn.Linear(8, 1), nn.functional.mse_loss,
                      optimizer.Adam(1e-2),
                      process_mesh=ProcessMesh(shape=(2,), dim_names=("dp",)))

    pt.seed(0)
    e = make()
    e.fit([(x, y)], epochs=3)
    pred = np.asarray(e.predict(x))
    e.save(str(tmp_path / "snap"))

    pt.seed(0)
    e2 = make()
    e2.load(str(tmp_path / "snap"))
    np.testing.assert_allclose(np.asarray(e2.predict(x)), pred, atol=1e-6)
    # optimizer state restored too: one more identical fit step matches
    l1 = e.fit([(x, y)], epochs=1)
    l2 = e2.fit([(x, y)], epochs=1)
    np.testing.assert_allclose(l2, l1, atol=1e-6)


def test_engine_annotated_save_load_keeps_placement(tmp_path):
    """VERDICT r3 weak #4: load() into an annotated engine must restore
    the SHARDED placement prepare() chose (params AND optimizer slots),
    and training must continue exactly as if no save/load happened."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 16)).astype(np.float32)
    y = rng.integers(0, 4, 16).astype(np.int32)
    data = [((x,), (y,))] * 3
    mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))

    def build():
        pt.seed(0)
        return auto.Engine(_Mlp(), nn.functional.cross_entropy,
                           optimizer.Adam(1e-2), mesh,
                           batch_dim_mesh_axis="dp",
                           annotations={"fc2.weight": [-1, 1]})

    e = build()
    e.fit(data)
    pre = {n: (tuple(a.sharding.spec), a.addressable_shards[0].data.shape)
           for n, a in e._state["params"].items()}
    assert any("mp" in spec for spec, _ in pre.values())
    e.save(str(tmp_path / "snap"))
    cont = e.fit(data)  # the no-save/load oracle trajectory

    e2 = build()
    e2.load(str(tmp_path / "snap"))
    # placements (spec AND local shard shape) equal pre-save, params
    # and every optimizer slot
    for n, a in e2._state["params"].items():
        assert (tuple(a.sharding.spec),
                a.addressable_shards[0].data.shape) == pre[n], n
    for sub in e2._opt_state["slots"].values():
        if isinstance(sub, dict):
            for n, s in sub.items():
                if n in pre and hasattr(s, "sharding"):
                    assert tuple(s.sharding.spec) == pre[n][0], f"slot {n}"
    # training continues identically
    cont2 = e2.fit(data)
    np.testing.assert_allclose(cont2, cont, rtol=2e-5, atol=1e-6)


def test_engine_load_reshards_into_different_mesh(tmp_path):
    """A checkpoint saved by a replicated engine restores into an
    ANNOTATED engine on a different mesh factorization — load() is a
    reshard (reference reshard.py role), not a layout replay."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(16, 16)).astype(np.float32)
    y = rng.integers(0, 4, 16).astype(np.int32)

    pt.seed(0)
    src = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                      optimizer.Adam(1e-2),
                      auto.ProcessMesh(shape=(8,), dim_names=("dp",)))
    src.fit([((x,), (y,))] * 2)
    pred = np.asarray(src.predict(x))
    src.save(str(tmp_path / "snap"))

    pt.seed(0)
    dst = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                      optimizer.Adam(1e-2),
                      auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp")),
                      batch_dim_mesh_axis="dp",
                      annotations={"fc2.weight": [-1, 1]})
    dst.load(str(tmp_path / "snap"))
    w = dst._state["params"]["fc2.weight"]
    assert "mp" in tuple(w.sharding.spec)  # restored SHARDED, not repl
    np.testing.assert_allclose(np.asarray(dst.predict(x)), pred, atol=1e-5)
    assert np.isfinite(dst.fit([((x,), (y,))])).all()


class _Mlp(nn.Layer):
    def __init__(self, d=16, h=32, out=4):
        super().__init__()
        self.fc1 = nn.Linear(d, h)
        self.ln = nn.LayerNorm(h)
        self.fc2 = nn.Linear(h, h)
        self.fc3 = nn.Linear(h, out)

    def forward(self, x):
        return self.fc3(jax.nn.relu(self.fc2(self.ln(jax.nn.relu(self.fc1(x))))))


class TestCompletion:
    """complete_shardings — the Completer (completion.py): one or two
    hints propagate to every parameter."""

    def _mesh(self):
        return auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))

    def test_one_column_hint_shards_the_pair(self):
        mesh = self._mesh()
        specs = auto.complete_shardings(_Mlp(), mesh,
                                        {"fc2.weight": [-1, 1]})
        P = PartitionSpec
        assert specs["fc2.weight"] == P(None, "mp")
        assert specs["fc2.bias"] == P("mp")       # follows the out dim
        assert specs["fc3.weight"] == P("mp")  # row-parallel partner
        assert specs["fc3.bias"] == P()           # psum'd output
        assert specs["fc1.weight"] == P()         # upstream untouched
        assert specs["ln.weight"] == P()          # norms replicate
        assert len(specs) == len(dict(_Mlp().named_parameters()))

    def test_row_hint_completes_backward(self):
        """A row-parallel hint demands a column-parallel producer: the
        backward pass assigns it through the feature-preserving LN."""
        mesh = self._mesh()
        specs = auto.complete_shardings(_Mlp(), mesh,
                                        {"fc2.weight": [1, -1]})
        P = PartitionSpec
        assert specs["fc2.weight"] == P("mp")
        assert specs["fc1.weight"] == P(None, "mp")  # derived col partner
        assert specs["fc1.bias"] == P("mp")
        assert specs["fc2.bias"] == P()
        assert specs["fc3.weight"] == P()

    def test_engine_with_hint_matches_replicated(self):
        """Engine with one completion hint follows the same loss
        trajectory as the fully replicated engine (sharding changes the
        layout, not the math), and the params really are sharded."""
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 16)).astype(np.float32)
        y = rng.integers(0, 4, 16).astype(np.int32)
        data = [((x,), (y,))] * 4

        def build(annotations):
            pt.seed(0)
            return auto.Engine(
                _Mlp(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                self._mesh(), batch_dim_mesh_axis="dp",
                annotations=annotations)

        ref = build(None)
        la = ref.fit(data)
        eng = build({"fc2.weight": [-1, 1]})
        lb = eng.fit(data)
        np.testing.assert_allclose(lb, la, rtol=2e-5, atol=1e-6)
        w = eng._state["params"]["fc2.weight"]
        assert "mp" in tuple(w.sharding.spec), w.sharding
        assert w.addressable_shards[0].data.shape[1] * 4 == w.shape[1]


def test_reshard_cross_mesh():
    """reshard — the Resharder (reshard.py): move a tensor between
    different shardings AND different process meshes (program-section
    boundary); values survive bit-exact."""
    a = auto.ProcessMesh(shape=(8,), dim_names=("x",))
    b = auto.ProcessMesh(shape=(2, 2), dim_names=("p", "q"))  # sub-mesh
    v = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)
    on_a = auto.shard_tensor(v, a, [0, None])
    moved = auto.reshard(on_a, b, [1, 0])
    np.testing.assert_array_equal(np.asarray(moved), np.asarray(v))
    assert moved.sharding.spec == PartitionSpec("q", "p")
    # traced: constraint form compiles and preserves values
    # traced reshard stays within one mesh's device set (cross-mesh
    # movement is an eager/runtime operation, as in the reference)
    out = jax.jit(lambda t: auto.reshard(t * 2.0, b, [None, 1]))(moved)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(v) * 2.0)


class TestPlanner:
    """plan_strategy — the Planner role: pick (dp, mp) and the hints
    from a memory budget; completion derives the rest."""

    def test_fits_one_device_pure_dp(self):
        mesh, ann = auto.plan_strategy(_Mlp(), n_devices=8,
                                       per_device_bytes=1e9)
        assert mesh.jax_mesh.shape == {"dp": 8, "mp": 1}
        assert ann == {}

    def test_tight_budget_goes_tensor_parallel(self):
        m = _Mlp(d=16, h=32)
        pbytes = sum(int(np.prod(p.shape)) * 4
                     for _, p in m.named_parameters())
        # budget below the 4x state need forces mp=2
        mesh, ann = auto.plan_strategy(m, n_devices=8,
                                       per_device_bytes=pbytes * 2.5)
        assert mesh.jax_mesh.shape == {"dp": 4, "mp": 2}
        assert ann, "expected tensor-parallel hints"
        # hints alternate col ([-1,1]) then row ([1,-1]) — Megatron pairs
        vals = list(ann.values())
        assert vals[0] == [-1, 1]
        if len(vals) > 1:
            assert vals[1] == [1, -1]
        # the hints + completion produce a full, runnable spec map
        specs = auto.complete_shardings(m, mesh, ann)
        assert len(specs) == len(dict(m.named_parameters()))
        assert any("mp" in tuple(s) for s in specs.values())

    def test_planned_engine_trains(self):
        pt.seed(0)
        m = _Mlp()
        pbytes = sum(int(np.prod(p.shape)) * 4
                     for _, p in m.named_parameters())
        mesh, ann = auto.plan_strategy(m, n_devices=8,
                                       per_device_bytes=pbytes * 2.5)
        eng = auto.Engine(m, nn.functional.cross_entropy, optimizer.SGD(0.1),
                          mesh, batch_dim_mesh_axis="dp", annotations=ann)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 16)).astype(np.float32)
        y = rng.integers(0, 4, 16).astype(np.int32)
        losses = eng.fit([((x,), (y,))] * 6)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_non_power_of_two_devices_get_divisor_mp(self):
        m = _Mlp(d=16, h=32)
        pbytes = sum(int(np.prod(p.shape)) * 4
                     for _, p in m.named_parameters())
        mesh, ann = auto.plan_strategy(m, n_devices=6,
                                       per_device_bytes=pbytes)
        # largest power-of-two divisor of 6 is 2 — plan, don't crash
        assert mesh.jax_mesh.shape == {"dp": 3, "mp": 2}
        assert ann

    def test_unshardable_model_falls_back_to_pure_dp(self):
        class Odd(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(7, 9)  # no dim divisible by 2

            def forward(self, x):
                return self.fc(x)

        mesh, ann = auto.plan_strategy(Odd(), n_devices=8,
                                       per_device_bytes=1.0)
        assert mesh.jax_mesh.shape == {"dp": 8, "mp": 1}
        assert ann == {}

    def test_large_embedding_gets_vocab_parallel_hint(self):
        class EmbNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.emb = nn.Embedding(4096, 16)
                self.head = nn.Linear(16, 4)

            def forward(self, x):
                return self.head(self.emb(x))

        m = EmbNet()
        pbytes = sum(int(np.prod(p.shape)) * 4
                     for _, p in m.named_parameters())
        mesh, ann = auto.plan_strategy(m, n_devices=8,
                                       per_device_bytes=pbytes * 2.5)
        assert mesh.jax_mesh.shape["mp"] == 2
        assert ann.get("emb.weight") == [1, -1]  # vocab-parallel


class TestCostModel:
    """choose_strategy + estimate_plan_cost — the reference planner's
    cost-model search (planner_v2.py + cost_model.py): enumerate
    feasible (dp, mp) factorizations, score estimated step comm time,
    pick the cheapest that fits memory."""

    def test_roomy_budget_prefers_pure_dp(self):
        mesh, ann, cands = auto.choose_strategy(
            _Mlp(), batch_tokens=4096, n_devices=8, per_device_bytes=16e9)
        assert dict(mesh.jax_mesh.shape) == {"dp": 8, "mp": 1, "pp": 1}
        assert ann == {}
        # the candidate list is the auditable scoreboard
        assert any(c["mp"] > 1 for c in cands)
        pure = next(c for c in cands if c["mp"] == 1)
        assert all(pure["total_s"] <= c["total_s"] for c in cands
                   if c["fits"])

    def test_tight_budget_picks_cheapest_feasible(self):
        m = _Mlp(d=16, h=32)
        pbytes = sum(int(np.prod(p.shape)) * 4
                     for _, p in m.named_parameters())
        mesh, ann, cands = auto.choose_strategy(
            m, batch_tokens=64, n_devices=8,
            per_device_bytes=pbytes * 2.5)
        assert mesh.jax_mesh.shape["mp"] >= 2
        assert ann
        # the selected candidate (sh/recompute variants share a mesh, so
        # look up the chosen flag, not the first dp/mp match)
        chosen = next(c for c in cands if c.get("chosen"))
        assert chosen["fits"]
        feas = [c for c in cands if c["fits"]]
        assert all(chosen["total_s"] <= c["total_s"] for c in feas)
        # memory estimate actually shrinks with mp (same sh/rc variant)
        by_mp = {c["mp"]: c["per_device_state_bytes"] for c in cands
                 if c["sh"] == 0 and not c["recompute"]}
        assert by_mp[2] < by_mp[1]

    def test_cross_host_dp_charges_dcn(self):
        """With the dp axis laid across hosts, the same plan's dp
        all-reduce must cost more than single-host — the cluster spec
        is load-bearing, not decorative."""
        m = _Mlp()
        one = auto.estimate_plan_cost(
            m, auto.ProcessMesh(shape=(8, 1), dim_names=("dp", "mp")),
            {}, batch_tokens=4096, cluster=auto.ClusterSpec(hosts=1))
        two = auto.estimate_plan_cost(
            m, auto.ProcessMesh(shape=(8, 1), dim_names=("dp", "mp")),
            {}, batch_tokens=4096, cluster=auto.ClusterSpec(hosts=2))
        assert two["dp_allreduce_s"] > one["dp_allreduce_s"] * 5
        assert two["dp_allreduce_bytes"] == one["dp_allreduce_bytes"]

    def test_mp_cost_scales_with_batch(self):
        m = _Mlp(d=16, h=32)
        mesh = auto.ProcessMesh(shape=(4, 2), dim_names=("dp", "mp"))
        ann = {"fc2.weight": [1, -1]}  # row-parallel: psums activations
        small = auto.estimate_plan_cost(m, mesh, ann, batch_tokens=64)
        big = auto.estimate_plan_cost(m, mesh, ann, batch_tokens=6400)
        assert big["mp_activation_s"] > small["mp_activation_s"] * 50
        # dp all-reduce is batch-independent
        assert big["dp_allreduce_s"] == small["dp_allreduce_s"]

    def test_chosen_plan_trains_end_to_end(self):
        pt.seed(0)
        m = _Mlp()
        pbytes = sum(int(np.prod(p.shape)) * 4
                     for _, p in m.named_parameters())
        mesh, ann, _ = auto.choose_strategy(
            m, batch_tokens=16, n_devices=8,
            per_device_bytes=pbytes * 2.5)
        eng = auto.Engine(m, nn.functional.cross_entropy, optimizer.SGD(0.1),
                          mesh, batch_dim_mesh_axis="dp", annotations=ann)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 16)).astype(np.float32)
        y = rng.integers(0, 4, 16).astype(np.int32)
        losses = eng.fit([((x,), (y,))] * 6)
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_nothing_fits_falls_back_to_memory_minimizing(self):
        """When no plan fits the budget, the binding constraint is
        memory: choose_strategy must return the candidate with the
        smallest per-device state. With the sh axis in the search that
        is ZeRO-3 (+recompute) over the full dp width — every state
        term divides by ALL devices with no mp remainder — not the
        largest-mp plan the 2-axis search used to fall back to."""
        m = _Mlp(d=16, h=32)
        mesh, ann, cands = auto.choose_strategy(
            m, batch_tokens=64, n_devices=8, per_device_bytes=1.0)
        assert not any(c["fits"] for c in cands)
        best = next(c for c in cands if c.get("chosen"))
        assert best["per_device_state_bytes"] == min(
            c["per_device_state_bytes"] for c in cands)
        assert best["sh"] == 3 and best["recompute"]
        # with sh excluded (an executor that can't ZeRO), the fallback
        # reverts to the largest usable mp
        mesh2, ann2, cands2 = auto.choose_strategy(
            m, batch_tokens=64, n_devices=8, per_device_bytes=1.0,
            allow_sh=False)
        assert not any(c["fits"] for c in cands2)
        assert mesh2.jax_mesh.shape["mp"] > 1 and ann2


class TestTracedCompletion:
    """Graph-aware completion (completion.py, VERDICT r3 #3): the jaxpr
    trace handles branching/residual models the sequential walk cannot —
    ERNIE's fused QKV, residual skips, repeated blocks."""

    def _ernie(self):
        from paddle_tpu.models.ernie import Ernie, ErnieConfig

        pt.seed(0)
        cfg = ErnieConfig(vocab_size=128, hidden_size=32, num_heads=4,
                          ffn_size=64, num_layers=2, max_seq_len=16,
                          mp_axis=None, cp_axis=None, ep_axis=None)
        return Ernie(cfg), jax.ShapeDtypeStruct((2, 16), np.int32)

    def test_two_hints_shard_the_whole_encoder(self):
        """One col hint on block-0 QKV + one on block-0 ffn-in expand
        across blocks and complete to the full Megatron layout: col QKV
        + row out-proj, col ffn-in + row ffn-out, sharded QKV bias,
        replicated norms."""
        model, ids = self._ernie()
        mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        specs = auto.complete_shardings(
            model, mesh,
            {"blocks.0.attn.qkv_w": [-1, 1], "blocks.0.ffn.w_in": [-1, 1]},
            example_inputs=[ids])
        P = PartitionSpec
        for b in range(2):
            assert specs[f"blocks.{b}.attn.qkv_w"] == P(None, "mp"), b
            assert specs[f"blocks.{b}.attn.qkv_b"] == P("mp"), b
            assert specs[f"blocks.{b}.attn.proj_w"] == P("mp"), b
            assert specs[f"blocks.{b}.ffn.w_in"] == P(None, "mp"), b
            assert specs[f"blocks.{b}.ffn.b_in"] == P("mp"), b
            assert specs[f"blocks.{b}.ffn.w_out"] == P("mp"), b
        # row outputs psum -> replicated biases; norms replicate
        assert specs["blocks.0.attn.proj_b"] == P()
        assert specs["blocks.0.ln1.weight"] == P()
        assert specs["embed.word_emb"] == P()

    def test_ernie_sharded_matches_replicated(self):
        """The deliverable: ERNIE sharded from TWO hints follows the
        replicated loss trajectory (GSPMD completes the intermediates
        around the placed params)."""
        from paddle_tpu.models.ernie import parallel_cross_entropy  # noqa: F401

        rng = np.random.default_rng(0)
        ids = rng.integers(0, 128, size=(4, 16)).astype(np.int32)
        labels = rng.integers(0, 128, size=(4, 16)).astype(np.int32)

        def lm_loss(out, lbl):
            return nn.functional.cross_entropy(
                out.reshape(-1, out.shape[-1]), lbl.reshape(-1))

        def build(annotations):
            model, sds = self._ernie()
            return auto.Engine(
                model, lm_loss, optimizer.SGD(0.05),
                auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp")),
                batch_dim_mesh_axis="dp", annotations=annotations,
                example_inputs=[sds])

        data = [((ids,), (labels,))] * 3
        ref = build(None).fit(data)
        eng = build({"blocks.0.attn.qkv_w": [-1, 1],
                     "blocks.0.ffn.w_in": [-1, 1]})
        got = eng.fit(data)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-5)
        w = eng._state["params"]["blocks.1.ffn.w_out"]
        assert "mp" in tuple(w.sharding.spec)  # really sharded

    def test_conv_chain_completes_channel_parallel(self):
        """Convolutions trace as col/row pairs on their channel dims
        (conv_general_dilated rhs_spec): a col hint on conv1's
        out-channels derives conv2 as the in-channel row partner."""
        from paddle_tpu.distributed.completion import trace_param_graph

        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.c1 = nn.Conv2D(3, 16, 3, padding=1)
                self.c2 = nn.Conv2D(16, 32, 3, padding=1)

            def forward(self, x):
                return self.c2(jax.nn.relu(self.c1(x)))

        sds = jax.ShapeDtypeStruct((2, 3, 8, 8), np.float32)
        g = trace_param_graph(ConvNet(), [sds])
        uses = {u.name: u for u in g.uses}
        assert uses["c1.weight"].kind == "conv"
        assert uses["c1.weight"].out_dim == 0          # out-channels
        assert uses["c1.weight"].contracted_dim == 1   # in-channels
        assert "c1.weight" in uses["c2.weight"].preds
        mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        specs = auto.complete_shardings(
            ConvNet(), mesh, {"c1.weight": [1, -1, -1, -1]},
            example_inputs=[sds])
        P = PartitionSpec
        assert specs["c1.weight"] == P("mp")           # col: out-chan
        assert specs["c2.weight"] == P(None, "mp")     # row: in-chan

    def test_two_tower_hint_stays_in_its_tower(self):
        """Branch isolation (DSSM-style two towers): a col hint in tower
        A derives A's row partner but must NOT leak into tower B — the
        towers share only the INPUT, and the sibling rule requires the
        same activation, which B's deeper layers don't see."""

        class TwoTower(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a1 = nn.Linear(16, 32)
                self.a2 = nn.Linear(32, 8)
                self.b1 = nn.Linear(16, 32)
                self.b2 = nn.Linear(32, 8)

            def forward(self, x):
                a = self.a2(jax.nn.relu(self.a1(x)))
                b = self.b2(jax.nn.relu(self.b1(x)))
                return (a * b).sum(-1)

        sds = jax.ShapeDtypeStruct((4, 16), np.float32)
        mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        specs = auto.complete_shardings(
            TwoTower(), mesh, {"a1.weight": [-1, 1]}, example_inputs=[sds])
        P = PartitionSpec
        assert specs["a1.weight"] == P(None, "mp")
        assert specs["a2.weight"] == P("mp")       # A's row partner
        # the SIBLING rule legitimately cols b1 (same input activation
        # — Megatron-valid, b2 closes it); but A's pair must not force
        # anything deeper in B than its own col/row pair
        assert specs["b2.weight"] in (P(), P("mp"))
        if specs["b1.weight"] == P(None, "mp"):
            assert specs["b2.weight"] == P("mp")   # closed pair, valid
        else:
            assert specs["b2.weight"] == P()

    def test_separate_inputs_are_not_siblings(self):
        """Advisor r4 (medium): two first-layer matmuls consuming
        DIFFERENT raw inputs both have empty param-ancestor sets; the
        sibling rule must key on the concrete activation (act_id), so a
        col hint on tower A's first layer does NOT col-shard tower B's
        first layer (they share no activation at all)."""

        class TwoInput(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a1 = nn.Linear(16, 32)
                self.b1 = nn.Linear(24, 32)

            def forward(self, x, y):
                return (self.a1(x) + self.b1(y)).sum(-1)

        sx = jax.ShapeDtypeStruct((4, 16), np.float32)
        sy = jax.ShapeDtypeStruct((4, 24), np.float32)
        mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        specs = auto.complete_shardings(
            TwoInput(), mesh, {"a1.weight": [-1, 1]},
            example_inputs=[sx, sy])
        P = PartitionSpec
        assert specs["a1.weight"] == P(None, "mp")
        assert specs["b1.weight"] == P(), specs["b1.weight"]

    def test_shared_jitted_subfn_not_siblings(self):
        """jax caches the jaxpr of a repeatedly-called jitted
        sub-function, so inner vars are the SAME objects on every
        invocation — activation identity must be fresh per invocation
        (per walk), or two towers calling the same jitted tower fn with
        different params collide on act_id and false-sibling."""
        import jax as _jax

        @_jax.jit
        def tower(w1, w2, x):
            return _jax.nn.relu(x @ w1) @ w2

        class SharedFn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a1 = nn.Linear(16, 32)
                self.a2 = nn.Linear(32, 8)
                self.b1 = nn.Linear(16, 32)
                self.b2 = nn.Linear(32, 8)

            def forward(self, x, y):
                a = tower(self.a1.weight, self.a2.weight, x)
                b = tower(self.b1.weight, self.b2.weight, y)
                return (a + b).sum(-1)

        sx = jax.ShapeDtypeStruct((4, 16), np.float32)
        sy = jax.ShapeDtypeStruct((4, 16), np.float32)
        mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        specs = auto.complete_shardings(
            SharedFn(), mesh, {"a1.weight": [-1, 1]},
            example_inputs=[sx, sy])
        P = PartitionSpec
        assert specs["a1.weight"] == P(None, "mp")
        assert specs["a2.weight"] == P("mp")       # its own row partner
        assert specs["b1.weight"] == P(), specs["b1.weight"]
        assert specs["b2.weight"] == P(), specs["b2.weight"]

    def test_conv_spatial_hint_propagates_nothing(self):
        """A hint on a conv KERNEL dim is not a Megatron role (review
        finding): honor the placement if divisible, derive no partners."""
        from paddle_tpu import nn as pnn

        class ConvNet(pnn.Layer):
            def __init__(self):
                super().__init__()
                self.c1 = pnn.Conv2D(3, 16, 4, padding=1)
                self.c2 = pnn.Conv2D(16, 32, 3, padding=1)

            def forward(self, x):
                return self.c2(jax.nn.relu(self.c1(x)))

        sds = jax.ShapeDtypeStruct((2, 3, 8, 8), np.float32)
        mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        specs = auto.complete_shardings(
            ConvNet(), mesh, {"c1.weight": [-1, -1, 1, -1]},  # kernel H
            example_inputs=[sds])
        # kernel dim 4 % mp 4 == 0: placement honored, but c2 stays
        # UNSHARDED — no bogus row partner
        assert specs["c1.weight"] == PartitionSpec(None, None, "mp")
        assert specs["c2.weight"] == PartitionSpec()

    def test_conv_annotations_charge_mp_cost(self):
        """4-D conv channel-parallel annotations must charge mp
        activation comm (review finding: a zero-cost mp biases the
        planner toward sharding conv models)."""

        class ConvNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.c1 = nn.Conv2D(8, 64, 3, padding=1)
                self.c2 = nn.Conv2D(64, 64, 3, padding=1)

            def forward(self, x):
                return self.c2(jax.nn.relu(self.c1(x)))

        mesh = auto.ProcessMesh(shape=(2, 4), dim_names=("dp", "mp"))
        # col(out-chan dim 0) + row(in-chan dim 1): OIHW convention
        cost = auto.estimate_plan_cost(
            ConvNet(), mesh,
            {"c1.weight": [1, -1, -1, -1],   # mp on dim 0 = col
             "c2.weight": [-1, 1, -1, -1]},  # mp on dim 1 = row
            batch_tokens=4096)
        assert cost["mp_activation_s"] > 0
        assert cost["mp_gather_bytes"] == 0  # the pair closed
        lone = auto.estimate_plan_cost(
            ConvNet(), mesh, {"c1.weight": [1, -1, -1, -1]},
            batch_tokens=4096)
        assert lone["mp_gather_bytes"] > 0   # unpaired col gathers

    def test_traced_planner_rule_is_megatron_exact(self):
        """mp_annotations_traced pairs by DATAFLOW: residual edges do
        not mis-pair (the registration-order rule's failure mode)."""
        from paddle_tpu.distributed.completion import mp_annotations_traced

        model, ids = self._ernie()
        ann = mp_annotations_traced(model, 4, 1, [ids])
        assert ann["embed.word_emb"] == [1, -1]      # vocab-parallel
        for b in range(2):
            assert ann[f"blocks.{b}.attn.qkv_w"] == [-1, 1]
            assert ann[f"blocks.{b}.attn.proj_w"] == [1, -1]
            assert ann[f"blocks.{b}.ffn.w_in"] == [-1, 1]
            assert ann[f"blocks.{b}.ffn.w_out"] == [1, -1]
        assert ann["head.w"] == [-1, 1]              # col head -> par CE


class TestPlannerPP:
    """choose_strategy's pp axis (VERDICT r3 #3): pipeline partitioning
    enters the search with a bubble cost term."""

    def _stacked_odd(self, n_blocks=8, d=33):
        """Repeated blocks with ODD dims: mp cannot shard anything, so
        only pp can relieve memory."""

        class Block(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(d, d)

            def forward(self, x):
                return jax.nn.relu(self.fc(x))

        class Stacked(nn.Layer):
            def __init__(self):
                super().__init__()
                self.blocks = nn.LayerList([Block() for _ in range(n_blocks)])

            def forward(self, x):
                for b in self.blocks:
                    x = b(x)
                return x

        return Stacked()

    def test_pp_relieves_memory_when_mp_cannot(self):
        m = self._stacked_odd()
        pbytes = sum(int(np.prod(p.shape)) * 4
                     for _, p in m.named_parameters())
        # budget: fits only at a >=2-way split; mp shards nothing (odd).
        # allow_sh=False: with ZeRO in the search, sh-2 fits with no
        # bubble and correctly wins — this test pins the PP axis itself
        mesh, ann, cands = auto.choose_strategy(
            m, batch_tokens=64, n_devices=8,
            per_device_bytes=pbytes * 4.0 / 2 * 1.01, allow_sh=False)
        assert mesh.jax_mesh.shape["pp"] >= 2
        assert mesh.jax_mesh.shape["mp"] == 1 and ann == {}
        chosen = next(c for c in cands
                      if (c["dp"], c["mp"], c["pp"]) == tuple(
                          mesh.jax_mesh.shape.values()))
        assert chosen["fits"] and chosen["pp_bubble_s"] > 0

    def test_pp_capped_by_block_depth(self):
        """A model with no repeated blocks never gets pp > 1."""
        _, _, cands = auto.choose_strategy(
            _Mlp(), batch_tokens=64, n_devices=8, per_device_bytes=1.0)
        assert all(c["pp"] == 1 for c in cands)

    def test_pipeline_stages_counts_layerlists(self):
        from paddle_tpu.distributed.auto_parallel import _pipeline_stages

        assert _pipeline_stages(_Mlp()) == 1
        assert _pipeline_stages(self._stacked_odd(n_blocks=6)) == 6

    def test_bubble_shrinks_with_microbatches(self):
        m = self._stacked_odd(d=32)  # even: mp usable too, but test pp
        mesh = auto.ProcessMesh(shape=(2, 1, 4), dim_names=("dp", "mp", "pp"))
        few = auto.estimate_plan_cost(m, mesh, {}, 4096, microbatches=2)
        many = auto.estimate_plan_cost(m, mesh, {}, 4096, microbatches=32)
        assert few["pp_bubble_s"] > many["pp_bubble_s"] * 10


class TestUnpairedColGatherCost:
    """ADVICE r3: a column-parallel annotation with no row partner must
    charge its output all-gather — otherwise the search is biased toward
    mp for models with a lone col layer."""

    def test_lone_col_charges_gather(self):
        m = _Mlp(d=16, h=32)
        mesh = auto.ProcessMesh(shape=(4, 2), dim_names=("dp", "mp"))
        lone = auto.estimate_plan_cost(m, mesh, {"fc2.weight": [-1, 1]},
                                       batch_tokens=4096)
        assert lone["mp_gather_bytes"] > 0
        assert lone["mp_activation_s"] > 0

    def test_paired_col_row_charges_no_gather(self):
        m = _Mlp(d=16, h=32)
        mesh = auto.ProcessMesh(shape=(4, 2), dim_names=("dp", "mp"))
        paired = auto.estimate_plan_cost(
            m, mesh, {"fc2.weight": [-1, 1], "fc3.weight": [1, -1]},
            batch_tokens=4096)
        assert paired["mp_gather_bytes"] == 0

def test_parallel_experts_are_not_pipeline_stages():
    """A homogeneous LayerList applied in PARALLEL (MoE experts) must
    not count as pipeline depth — the traced dataflow shows no
    block-to-block edges (review finding: structural guess alone
    over-pipelines)."""
    from paddle_tpu.distributed.auto_parallel import _pipeline_stages
    from paddle_tpu.distributed.completion import trace_param_graph

    class Experts(nn.Layer):
        def __init__(self):
            super().__init__()
            self.experts = nn.LayerList(
                [nn.Linear(16, 16) for _ in range(4)])

        def forward(self, x):
            return sum(e(x) for e in self.experts)

    class Block(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(16, 16)

        def forward(self, x):
            return jax.nn.relu(self.fc(x))

    class Stacked(nn.Layer):
        def __init__(self):
            super().__init__()
            self.blocks = nn.LayerList([Block() for _ in range(4)])

        def forward(self, x):
            for b in self.blocks:
                x = b(x)
            return x

    sds = jax.ShapeDtypeStruct((4, 16), np.float32)
    m = Experts()
    g = trace_param_graph(m, [sds])
    assert _pipeline_stages(m, g) == 1          # trace: parallel
    assert _pipeline_stages(m) == 4             # structural fallback
    seq = Stacked()
    gs = trace_param_graph(seq, [sds])
    assert _pipeline_stages(seq, gs) == 4       # trace: sequential


def test_engine_rejects_pp_mesh():
    from paddle_tpu.core.enforce import EnforceNotMet

    mesh = auto.ProcessMesh(shape=(2, 1, 4), dim_names=("dp", "mp", "pp"))
    eng = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                      optimizer.SGD(0.1), mesh,
                      batch_dim_mesh_axis="dp")
    with pytest.raises(EnforceNotMet, match="pipeline"):
        eng.prepare()


@pytest.mark.slow
def test_planner_pp_plan_executes_via_hybrid_trainer():
    """Closing the planner/executor loop (reference planner_v2 →
    Partitioner+pipeline runtime): an ERNIE whose dims mp cannot shard
    (all odd) under a tight budget gets a pp>1 plan from
    choose_strategy, and hybrid_trainer_from_plan runs that plan through
    the pipeline trainer — a real train step, loss finite and falling."""
    from paddle_tpu.models.ernie import Ernie, ErnieConfig

    pt.seed(0)
    cfg = ErnieConfig(vocab_size=101, hidden_size=33, num_heads=3,
                      ffn_size=55, num_layers=4, max_seq_len=16,
                      dropout=0.0)
    model = Ernie(cfg)
    pbytes = sum(int(np.prod(p.shape)) * 4
                 for _, p in model.named_parameters())
    sds = jax.ShapeDtypeStruct((2, 16), np.int32)
    # allow_sh=False: with ZeRO in the search space a memory-bound plan
    # correctly prefers sh over pp (no bubble) — this test exercises the
    # pp EXECUTION path, so restrict the planner to dp×mp×pp
    mesh, ann, cands = auto.choose_strategy(
        model, batch_tokens=64, n_devices=8,
        per_device_bytes=pbytes * 4.0 / 2 * 1.01,
        example_inputs=[sds], allow_sh=False)
    dims = dict(zip(mesh.dim_names, mesh.shape))
    assert dims["pp"] >= 2 and dims["mp"] == 1 and ann == {}, dims

    trainer = auto.hybrid_trainer_from_plan(cfg, mesh, optimizer.Adam(3e-3),
                                            num_micro=2)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(4, 16)),
                      jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(4, 16)),
                         jnp.int32)
    losses = [float(trainer.train_step(ids, labels)) for _ in range(4)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses


def test_engine_plan_auto_semi_automatic():
    """Engine(plan='auto') — the reference Engine's semi-auto mode: the
    cost-model planner derives mesh AND annotations; the user supplies
    only model/loss/optimizer (+ example_inputs for traced hints)."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 16)).astype(np.float32)
    y = rng.integers(0, 4, 16).astype(np.int32)
    pt.seed(0)
    eng = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                      optimizer.SGD(0.1), plan="auto",
                      example_inputs=[jax.ShapeDtypeStruct((16, 16),
                                                           np.float32)])
    assert "pp" not in dict(zip(eng.process_mesh.dim_names,
                                eng.process_mesh.shape)) or \
        dict(zip(eng.process_mesh.dim_names, eng.process_mesh.shape))["pp"] == 1
    losses = eng.fit([((x,), (y,))] * 4)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    # conflicting arguments rejected
    with pytest.raises(Exception, match="auto"):
        auto.Engine(_Mlp(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                    plan="auto",
                    process_mesh=auto.ProcessMesh(shape=(8,),
                                                  dim_names=("dp",)))
    with pytest.raises(Exception, match="plan"):
        auto.Engine(_Mlp(), nn.functional.cross_entropy, optimizer.SGD(0.1),
                    plan="semi")


class TestPlannerShAxis:
    """choose_strategy's sh (ZeRO) axis + recompute (VERDICT r4 #5):
    memory relief no longer has pp as its only lever."""

    def _model(self):
        pt.seed(0)
        return _Mlp(d=64, h=128)

    def test_zero2_fits_gets_sh_not_pp(self):
        """A model that fits under ZeRO-2 but not plain dp (or any dp×mp
        — odd dims shard nothing, no repeated blocks so pp is capped at
        1) must get an sh plan: memory relief the 3-axis search could
        not provide at all. Budget sits between the sh1 and sh2 memory
        lines, so the planner must actually reach for stage 2."""
        pt.seed(0)
        m = _Mlp(d=15, h=33)  # odd dims: mp shards nothing; max_pp == 1
        cands0 = auto.estimate_plan_cost(
            m, auto.ProcessMesh(shape=(8, 1, 1),
                                dim_names=("dp", "mp", "pp")), {},
            batch_tokens=64)
        sh1 = auto.estimate_plan_cost(
            m, auto.ProcessMesh(shape=(8, 1, 1),
                                dim_names=("dp", "mp", "pp")), {},
            batch_tokens=64, sh=1)
        sh2 = auto.estimate_plan_cost(
            m, auto.ProcessMesh(shape=(8, 1, 1),
                                dim_names=("dp", "mp", "pp")), {},
            batch_tokens=64, sh=2)
        budget = (sh1["per_device_state_bytes"]
                  + sh2["per_device_state_bytes"]) / 2
        assert budget < cands0["per_device_state_bytes"]
        mesh, ann, cands = auto.choose_strategy(
            m, batch_tokens=64, n_devices=8, per_device_bytes=budget)
        best = next(c for c in cands if c.get("chosen"))
        dims = dict(zip(mesh.dim_names, mesh.shape))
        assert dims["pp"] == 1 and dims["mp"] == 1, dims
        assert best["sh"] == 2 and best["fits"], best
        assert not best["recompute"]  # stage relief suffices; no extra fwd

    def test_sh_memory_ladder(self):
        """Each ZeRO stage monotonically reduces per-device state, and
        stage 3 charges the extra param all-gather."""
        m = self._model()
        mesh = auto.ProcessMesh(shape=(8, 1, 1), dim_names=("dp", "mp", "pp"))
        costs = [auto.estimate_plan_cost(m, mesh, {}, batch_tokens=256,
                                         sh=s) for s in (0, 1, 2, 3)]
        mems = [c["per_device_state_bytes"] for c in costs]
        assert mems[0] > mems[1] > mems[2] > mems[3]
        assert costs[3]["sh_extra_s"] > 0
        assert costs[0]["sh_extra_s"] == 0
        assert costs[2]["total_s"] == costs[0]["total_s"]  # rs+ag ≡ ring

    def test_recompute_trades_memory_for_compute(self):
        m = self._model()
        mesh = auto.ProcessMesh(shape=(8, 1, 1), dim_names=("dp", "mp", "pp"))
        base = auto.estimate_plan_cost(m, mesh, {}, batch_tokens=65536)
        rc = auto.estimate_plan_cost(m, mesh, {}, batch_tokens=65536,
                                     recompute=True)
        assert rc["activation_bytes"] < base["activation_bytes"]
        assert rc["recompute_s"] > 0 and rc["total_s"] > base["total_s"]
        assert base["recompute_s"] == 0

    def test_sh_noop_on_single_dp(self):
        m = self._model()
        mesh = auto.ProcessMesh(shape=(1, 1, 1), dim_names=("dp", "mp", "pp"))
        c = auto.estimate_plan_cost(m, mesh, {}, batch_tokens=64, sh=3)
        assert c["sh"] == 0  # ZeRO over a 1-wide dp axis is a no-op

    def test_tie_break_prefers_least_mechanism(self):
        """With a roomy budget every stage fits at equal comm cost —
        the chosen plan must be sh=0, recompute=False."""
        m = self._model()
        _, _, cands = auto.choose_strategy(
            m, batch_tokens=64, n_devices=8, per_device_bytes=1e12)
        best = next(c for c in cands if c.get("chosen"))
        assert best["sh"] == 0 and best["recompute"] is False


@pytest.mark.slow
def test_planner_sh_pp_plan_executes_via_hybrid_trainer():
    """Execute a pp>1 plan WITH a ZeRO group on the 8-device mesh
    (hybrid trainer's sh axis) and check loss parity vs the same model
    trained on one device — the planner→executor bridge at a non-toy
    factorization (VERDICT r4 #5 'drive one pp>1 plan end-to-end')."""
    from paddle_tpu.models.ernie import ErnieConfig

    cfg = ErnieConfig(vocab_size=64, hidden_size=32, num_heads=4,
                      ffn_size=64, num_layers=2, max_seq_len=16,
                      dropout=0.0)
    mesh = auto.ProcessMesh(shape=(4, 1, 2), dim_names=("dp", "mp", "pp"))
    pt.seed(0)
    # sh=2 is a group WIDTH (2 of the 4 dp ranks form the ZeRO slot
    # group) — a stage-1 execution at half width; see the fn docstring
    trainer = auto.hybrid_trainer_from_plan(cfg, mesh, optimizer.SGD(0.1),
                                            num_micro=2, sh=2)
    assert "sh" in trainer.mesh.shape and trainer.mesh.shape["sh"] == 2

    rng = np.random.default_rng(0)
    # batch divides num_micro × (dp_inner × sh): 2 micros × 4 = 8
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(8, 16)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(8, 16)),
                         jnp.int32)

    # single-device oracle: serial Ernie assembled from the trainer's
    # OWN params (init order differs between the staged and serial
    # constructions) — the test_hybrid parity pattern
    from test_hybrid import _serial_loss_from_trainer

    serial = _serial_loss_from_trainer(trainer, trainer.cfg, ids, labels)
    first = float(trainer.train_step(ids, labels))
    np.testing.assert_allclose(first, serial, rtol=1e-4)
    losses = [first] + [float(trainer.train_step(ids, labels))
                        for _ in range(3)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0], losses


class TestEngineStage1:
    """Engine executes stage-1 ZeRO (optimizer-state sharding over dp)
    by placement: slots persist device-sharded between steps, the
    update computes shard-locally, GSPMD gathers params for fwd — the
    executor for the planner's sh=1 plans (stages 2-3 stay with
    parallel.spmd/sharding and are rejected loudly)."""

    def _data(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 16)).astype(np.float32)
        y = rng.integers(0, 4, 16).astype(np.int32)
        return [((x,), (y,))] * 5

    def test_slots_sharded_and_parity(self):
        data = self._data()
        pt.seed(0)
        ref = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                          optimizer.Adam(1e-2),
                          auto.ProcessMesh(shape=(8,), dim_names=("dp",)),
                          batch_dim_mesh_axis="dp").fit(data)
        pt.seed(0)
        eng = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                          optimizer.Adam(1e-2),
                          auto.ProcessMesh(shape=(8,), dim_names=("dp",)),
                          batch_dim_mesh_axis="dp", sharding_stage=1)
        got = eng.fit(data)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        # the slots really are sharded over dp — and STAY sharded after
        # compiled steps (out_shardings pin)
        slots = eng._opt_state["slots"]
        leaf = None
        for sub in slots.values() if isinstance(slots, dict) else []:
            if isinstance(sub, dict) and "fc1.weight" in sub:
                leaf = sub["fc1.weight"]
                break
        assert leaf is not None, slots.keys() if isinstance(slots, dict) else slots
        assert "dp" in jax.tree_util.tree_leaves(
            [tuple(leaf.sharding.spec)])[0:] or "dp" in tuple(leaf.sharding.spec)
        # params stay replicated (stage 1 shards STATE, not params)
        assert tuple(eng._state["params"]["fc1.weight"].sharding.spec) in (
            (), (None,), (None, None))

    def test_stage2_rejected_loudly(self):
        with pytest.raises(Exception, match="stage"):
            auto.Engine(_Mlp(), nn.functional.cross_entropy,
                        optimizer.SGD(0.1), sharding_stage=2)

    def test_stage1_save_load_restores_sharded_slots(self, tmp_path):
        """A stage-1 engine restore must land the optimizer slots back
        on their dp-sharded placements (prepare and load share
        _place_state) — a restore that silently came back replicated
        would undo the stage's memory relief; trajectory stays exact."""
        data = self._data()
        pt.seed(0)
        e = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                        optimizer.Adam(1e-2),
                        auto.ProcessMesh(shape=(8,), dim_names=("dp",)),
                        batch_dim_mesh_axis="dp", sharding_stage=1)
        e.fit(data)
        e.save(str(tmp_path / "snap"))
        ref = e.fit(data)

        pt.seed(0)
        e2 = auto.Engine(_Mlp(), nn.functional.cross_entropy,
                         optimizer.Adam(1e-2),
                         auto.ProcessMesh(shape=(8,), dim_names=("dp",)),
                         batch_dim_mesh_axis="dp", sharding_stage=1)
        e2.load(str(tmp_path / "snap"))
        slots = e2._opt_state["slots"]
        sub = next(s for s in slots.values()
                   if isinstance(s, dict) and "fc1.weight" in s)
        assert "dp" in tuple(sub["fc1.weight"].sharding.spec)
        got = e2.fit(data)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_plan_auto_adopts_stage(self):
        """plan='auto' searches sh up to stage 1 and the Engine adopts
        the chosen stage (a memory-bound model picks stage 1)."""
        m = _Mlp(d=15, h=33)  # odd dims: mp shards nothing
        mesh_dims = auto.ProcessMesh(shape=(8, 1, 1),
                                     dim_names=("dp", "mp", "pp"))
        sh1 = auto.estimate_plan_cost(m, mesh_dims, {}, batch_tokens=64,
                                      sh=1)
        sh0 = auto.estimate_plan_cost(m, mesh_dims, {}, batch_tokens=64)
        budget = (sh0["per_device_state_bytes"]
                  + sh1["per_device_state_bytes"]) / 2
        pt.seed(0)
        eng = auto.Engine(m, nn.functional.cross_entropy,
                          optimizer.Adam(1e-2), plan="auto",
                          batch_tokens=64, per_device_bytes=budget,
                          example_inputs=[jax.ShapeDtypeStruct(
                              (16, 15), np.float32)])
        assert eng.sharding_stage == 1
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 15)).astype(np.float32)
        y = rng.integers(0, 4, 16).astype(np.int32)
        losses = eng.fit([((x,), (y,))] * 4)
        assert np.isfinite(losses).all() and losses[-1] < losses[0]


@pytest.mark.slow
def test_planner_bridge_realistic_width():
    """The planner→hybrid bridge at NON-TOY width (VERDICT r4 weak #4:
    'only exercised at toy shapes'): choose_strategy plans a real
    128-hidden 4-layer ERNIE under a pp-forcing budget, the bridge
    executes the plan on the 8-device mesh, first loss matches the
    serial oracle and training proceeds."""
    from paddle_tpu.models.ernie import Ernie, ErnieConfig

    pt.seed(0)
    cfg = ErnieConfig(vocab_size=512, hidden_size=128, num_heads=4,
                      ffn_size=256, num_layers=4, max_seq_len=64,
                      dropout=0.0)
    model = Ernie(cfg)
    pbytes = sum(int(np.prod(p.shape)) * 4
                 for _, p in model.named_parameters())
    sds = jax.ShapeDtypeStruct((2, 64), np.int32)
    mesh, ann, cands = auto.choose_strategy(
        model, batch_tokens=128, n_devices=8,
        per_device_bytes=pbytes * 4.0 / 2 * 1.01,
        example_inputs=[sds], allow_sh=False)
    dims = dict(zip(mesh.dim_names, mesh.shape))
    assert dims["pp"] >= 2, dims  # the budget forces a pipeline split

    pt.seed(0)
    trainer = auto.hybrid_trainer_from_plan(cfg, mesh, optimizer.Adam(3e-3),
                                            num_micro=2)
    rng = np.random.default_rng(0)
    batch = max(4, 2 * dims["dp"] * 2)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(batch, 64)),
                      jnp.int32)
    labels = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1), jnp.int32)

    from test_hybrid import _serial_loss_from_trainer

    serial = _serial_loss_from_trainer(trainer, trainer.cfg, ids, labels)
    first = float(trainer.train_step(ids, labels))
    np.testing.assert_allclose(first, serial, rtol=1e-4)
    losses = [first] + [float(trainer.train_step(ids, labels))
                        for _ in range(5)]
    assert losses[-1] < losses[0] - 0.05, losses


def test_dp_axis_shard_charges_no_mp_cost():
    """A param sharded on the DP axis (ZeRO-style placement) is not an
    mp collective — the cost walk keys on the mp axis only (review
    finding: phantom psums inflated mixed plans)."""
    m = _Mlp(d=16, h=32)
    mesh = auto.ProcessMesh(shape=(4, 2), dim_names=("dp", "mp"))
    cost = auto.estimate_plan_cost(m, mesh, {"fc2.weight": [0, -1]},
                                   batch_tokens=4096)
    assert cost["mp_activation_s"] == 0 and cost["mp_gather_bytes"] == 0
