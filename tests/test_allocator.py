"""Host arena allocator (core/allocator.py + csrc/allocator.cc) — the
auto-growth best-fit strategy of the reference's host allocator facade
(memory/allocation/auto_growth_best_fit_allocator.cc): reuse, coalesce,
stats, lifetime-tied numpy arrays.
"""

import gc

import numpy as np
import pytest

from paddle_tpu.ps.native import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native lib unavailable")


def _arena(chunk=1 << 20):
    from paddle_tpu.core.allocator import HostArena

    return HostArena(chunk_size=chunk)


def test_alloc_free_reuse():
    a = _arena()
    b1 = a.alloc(1000)
    p1 = b1.ptr
    a.free(b1)
    b2 = a.alloc(900)  # best-fit should hand back the same block
    assert b2.ptr == p1
    a.free(b2)
    s = a.stats()
    assert s["in_use"] == 0 and s["chunks"] == 1


def test_auto_growth_and_peak():
    a = _arena(chunk=1 << 16)  # 64 KiB chunks
    blocks = [a.alloc(40 << 10) for _ in range(4)]  # forces 4 chunks
    s = a.stats()
    assert s["chunks"] == 4
    assert s["in_use"] >= 4 * (40 << 10)
    for b in blocks:
        a.free(b)
    s2 = a.stats()
    assert s2["in_use"] == 0
    assert s2["peak"] >= s["in_use"]
    assert s2["reserved"] == s["reserved"]  # chunks retained for reuse


def test_coalescing_allows_big_realloc():
    a = _arena(chunk=1 << 16)
    blocks = [a.alloc(1 << 12) for _ in range(16)]  # fill one chunk
    assert a.stats()["chunks"] == 1
    for b in blocks:
        a.free(b)
    # freed neighbours must coalesce back into one block able to serve
    # a chunk-sized request without growing
    big = a.alloc(1 << 16)
    assert a.stats()["chunks"] == 1
    a.free(big)


def test_double_free_rejected():
    a = _arena()
    b = a.alloc(128)
    a.free(b)
    with pytest.raises(Exception):
        a.free(b)


def test_ndarray_lifetime_recycles():
    a = _arena()
    arr = a.ndarray((256, 4), np.float32)
    arr[:] = 3.5
    assert a.stats()["in_use"] > 0
    view = arr[10:20]
    del arr
    gc.collect()
    assert a.stats()["in_use"] > 0  # view keeps the block alive
    np.testing.assert_array_equal(view, np.full((10, 4), 3.5, np.float32))
    del view
    gc.collect()
    assert a.stats()["in_use"] == 0  # block recycled


def test_default_arena_facade():
    from paddle_tpu.core.allocator import arena_ndarray, default_arena

    x = arena_ndarray((16,), np.int64)
    x[:] = np.arange(16)
    assert default_arena().stats()["in_use"] > 0
    np.testing.assert_array_equal(x, np.arange(16))
