"""Multi-HOST dense tensor-parallel training: two localhost
jax.distributed processes × 4 virtual devices form one global dp×mp
mesh; a Megatron col/row-parallel MLP trains with the mp collectives
crossing the process boundary inside the compiled step (the DCN-spanning
version of the reference's collective fleet path — test_dist_base.py's
compare-vs-single-process pattern)."""

import textwrap

import pytest

from conftest import launch_two_workers

_WORKER = textwrap.dedent("""
    import jax.numpy as jnp
    from jax import lax, shard_map
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # transpose so each mp PAIR spans the two processes (global
    # device order is process-major): the Megatron psum really
    # crosses the process boundary
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4).T, ("dp", "mp"))
    rngh = np.random.default_rng(0)
    D, H, O, B = 8, 16, 4, 16
    W1 = rngh.normal(0, 0.5, (D, H)).astype(np.float32)
    W2 = rngh.normal(0, 0.5, (H, O)).astype(np.float32)
    x = rngh.normal(size=(B, D)).astype(np.float32)
    y = rngh.integers(0, O, B).astype(np.int32)

    def to_global(a, spec):
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(a.shape, sh, lambda i: a[i])

    pspecs = {"w1": P(None, "mp"), "w2": P("mp", None)}
    params = {"w1": to_global(W1, pspecs["w1"]),
              "w2": to_global(W2, pspecs["w2"])}
    xg, yg = to_global(x, P("dp", None)), to_global(y, P("dp"))

    def body(params, x, y):
        def loss_fn(p):
            h = jax.nn.relu(x @ p["w1"])        # column-parallel
            o = lax.psum(h @ p["w2"], "mp")     # row-parallel + psum
            logp = jax.nn.log_softmax(o)
            l = -jnp.take_along_axis(logp, y[:, None], 1).mean()
            return lax.pmean(l, "dp")
        loss, g = jax.value_and_grad(loss_fn)(params)
        new = jax.tree_util.tree_map(lambda p, gg: p - 0.3 * gg, params, g)
        return new, loss

    step = jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P("dp", None), P("dp")),
        out_specs=(pspecs, P())))

    # serial oracle computed in-process on full arrays
    def serial_body(w1, w2, x, y):
        o = jax.nn.relu(x @ w1) @ w2
        logp = jax.nn.log_softmax(o)
        l = -jnp.take_along_axis(logp, y[:, None], 1).mean()
        return l

    sw1, sw2 = jnp.asarray(W1), jnp.asarray(W2)
    serial_grad = jax.jit(jax.value_and_grad(serial_body, argnums=(0, 1)))

    losses, serial_losses = [], []
    for i in range(6):
        params, loss = step(params, xg, yg)
        losses.append(float(loss))
        sl, (g1, g2) = serial_grad(sw1, sw2, jnp.asarray(x), jnp.asarray(y))
        sw1, sw2 = sw1 - 0.3 * g1, sw2 - 0.3 * g2
        serial_losses.append(float(sl))

    # the 8-device cross-process trajectory equals the serial one
    np.testing.assert_allclose(losses, serial_losses, rtol=1e-5, atol=1e-6)
    assert losses[-1] < losses[0] - 0.05, losses
    # my addressable shards of the updated params match the serial result
    for key, ref in (("w1", sw1), ("w2", sw2)):
        refn = np.asarray(ref)
        for shard in params[key].addressable_shards:
            np.testing.assert_allclose(np.asarray(shard.data),
                                       refn[shard.index], rtol=1e-5,
                                       atol=1e-6, err_msg=key)
    print("WORKER_OK", rank, flush=True)
""")


@pytest.mark.slow
def test_two_process_tensor_parallel_training(tmp_path):
    launch_two_workers(_WORKER, tmp_path)
