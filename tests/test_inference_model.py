"""save_inference_model / load_inference_model (io/inference.py): the
fleet.save_inference_model → Paddle-Inference-Predictor role as a
portable StableHLO export — roundtrip, frozen exports, param swapping,
and cross-process serving.
"""

import os
import subprocess
import sys
import textwrap

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.io.inference import load_inference_model, save_inference_model
from paddle_tpu.models.lenet import LeNet


def _model_and_inputs():
    pt.seed(0)
    model = LeNet(num_classes=10)
    state = nn.get_state(model)

    def predict(state, x):
        out, _ = nn.functional_call(model, state, x, training=False)
        return out

    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(2, 1, 28, 28)), jnp.float32)
    return model, state, predict, x


def test_roundtrip_and_param_swap(tmp_path, rng):
    model, state, predict, x = _model_and_inputs()
    want = np.asarray(predict(state, x))

    save_inference_model(str(tmp_path / "m"), predict, state, (x,))
    pred = load_inference_model(str(tmp_path / "m"))
    np.testing.assert_allclose(np.asarray(pred(x)), want, rtol=1e-6)

    # swap newer params in without re-export
    state2 = {"params": {k: v * 0.5 for k, v in state["params"].items()},
              "buffers": state["buffers"]}
    pred.set_params(state2)
    got2 = np.asarray(pred(x))
    assert not np.allclose(got2, want)
    np.testing.assert_allclose(got2, np.asarray(predict(state2, x)),
                               rtol=1e-6)


def test_reload_params_values_only(tmp_path, rng):
    # the serving half of the refresh_inference_params delta: another
    # process rewrites params.npz (export-loop refresh or a serving
    # replica's feed-triggered dense sync) and a LOADED predictor swaps
    # values in place — no re-deserialize, no re-compile
    from paddle_tpu.io.inference import refresh_inference_params

    model, state, predict, x = _model_and_inputs()
    save_inference_model(str(tmp_path / "m"), predict, state, (x,))
    pred = load_inference_model(str(tmp_path / "m"))
    want = np.asarray(pred(x))

    state2 = {"params": {k: v * 0.5 for k, v in state["params"].items()},
              "buffers": state["buffers"]}
    refresh_inference_params(str(tmp_path / "m"), state2)
    np.testing.assert_allclose(np.asarray(pred(x)), want, rtol=1e-6)  # stale
    pred.reload_params()
    got = np.asarray(pred(x))
    assert not np.allclose(got, want)
    np.testing.assert_allclose(got, np.asarray(predict(state2, x)),
                               rtol=1e-6)

    # frozen exports have nothing to swap — fail loudly, not silently
    save_inference_model(str(tmp_path / "f"), predict, state, (x,),
                         freeze=True)
    frozen = load_inference_model(str(tmp_path / "f"))
    with pytest.raises(Exception, match="frozen"):
        frozen.reload_params()


def test_frozen_export(tmp_path):
    model, state, predict, x = _model_and_inputs()
    want = np.asarray(predict(state, x))
    save_inference_model(str(tmp_path / "f"), predict, state, (x,),
                         freeze=True)
    pred = load_inference_model(str(tmp_path / "f"))
    np.testing.assert_allclose(np.asarray(pred(x)), want, rtol=1e-6)
    # frozen exports embed weights — no params checkpoint is written
    assert not any(f.startswith("params") for f in os.listdir(tmp_path / "f"))
    with pytest.raises(Exception):
        pred.set_params(state)


def test_cross_process_serving(tmp_path):
    """The artifact loads and serves in a FRESH process (deploy story)."""
    model, state, predict, x = _model_and_inputs()
    want = np.asarray(predict(state, x))
    save_inference_model(str(tmp_path / "m"), predict, state, (x,))
    np.save(tmp_path / "x.npy", np.asarray(x))
    np.save(tmp_path / "want.npy", want)

    script = tmp_path / "serve.py"
    script.write_text(textwrap.dedent(f"""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from paddle_tpu.io.inference import load_inference_model
        pred = load_inference_model({str(tmp_path / 'm')!r})
        x = np.load({str(tmp_path / 'x.npy')!r})
        want = np.load({str(tmp_path / 'want.npy')!r})
        got = np.asarray(pred(x))
        np.testing.assert_allclose(got, want, rtol=1e-5)
        print("SERVE_OK")
    """))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)
    out = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "SERVE_OK" in out.stdout


def test_hapi_model_save_inference(tmp_path, rng):
    """hapi Model.save(path, training=False) exports the serving
    artifact (reference hapi/model.py inference-model export)."""
    from paddle_tpu import optimizer
    from paddle_tpu.hapi import Model

    pt.seed(0)
    net = LeNet(num_classes=10)
    m = Model(net)
    m.prepare(optimizer.Adam(1e-3), nn.functional.cross_entropy)
    x = np.random.default_rng(0).normal(size=(2, 1, 28, 28)).astype(np.float32)
    want = np.asarray(m.predict_batch(x))

    m.save(str(tmp_path / "serve"), training=False, example_inputs=(x,))
    pred = load_inference_model(str(tmp_path / "serve"))
    np.testing.assert_allclose(np.asarray(pred(x)), want, rtol=1e-5)

    # bare-array convention (same as predict_batch)
    m.save(str(tmp_path / "serve2"), training=False, example_inputs=x)
    pred2 = load_inference_model(str(tmp_path / "serve2"))
    np.testing.assert_allclose(np.asarray(pred2(x)), want, rtol=1e-5)

    with pytest.raises(Exception):
        m.save(str(tmp_path / "bad"), training=False)  # needs examples


def test_ctr_serving_export(tmp_path, rng):
    """export_ctr_inference: the CTR probe→pull→forward→sigmoid path
    exports as one portable program with PRUNED serving tables (no
    optimizer state); the loaded predictor matches in-process scores
    and zero-fills out-of-pass keys."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.io.inference import load_inference_model
    from paddle_tpu.models.ctr import (CtrConfig, DeepFM,
                                       export_ctr_inference)
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import (CacheConfig,
                                               HbmEmbeddingCache,
                                               cache_pull)
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    pt.seed(0)
    S, D, dim = 4, 3, 4
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=dim,
                    dnn_hidden=(8,))
    table = MemorySparseTable(TableConfig(
        shard_num=2, accessor_config=AccessorConfig(embedx_dim=dim)))
    cache_cfg = CacheConfig(capacity=1 << 8, embedx_dim=dim,
                            embedx_threshold=0.0)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    lo = rng.integers(1, 1000, size=(64, S)).astype(np.uint64)
    pool = lo + (np.arange(S, dtype=np.uint64) << np.uint64(32))
    cache.begin_pass(pool.reshape(-1))
    # give the tables non-trivial values
    cache.state["embed_w"] = jnp.asarray(
        rng.normal(size=cache.state["embed_w"].shape).astype(np.float32))
    cache.state["embedx_w"] = jnp.asarray(
        rng.normal(size=cache.state["embedx_w"].shape).astype(np.float32))

    model = DeepFM(cfg)
    export_ctr_inference(str(tmp_path / "serve"), model, cache,
                         slot_ids=np.arange(S), num_dense=D)
    pred = load_inference_model(str(tmp_path / "serve"))

    lo32 = (pool[:8] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    dense = rng.normal(size=(8, D)).astype(np.float32)
    got = np.asarray(pred(jnp.asarray(lo32), jnp.asarray(dense)))

    # in-process reference: host lookup + pull + forward
    rows = cache.lookup(pool[:8].reshape(-1))
    emb = cache_pull(cache.state, jnp.asarray(rows, jnp.int32)).reshape(
        8, S, -1)
    out, _ = nn.functional_call(
        model, {"params": dict(model.named_parameters()), "buffers": {}},
        emb, jnp.asarray(dense), training=False)
    want = np.asarray(jax.nn.sigmoid(out))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert ((got > 0) & (got < 1)).all()

    # out-of-pass keys → sentinel → zero embeddings (not garbage)
    lo_miss = np.full((2, S), 0xFFFFFF, np.uint32)
    p_miss = np.asarray(pred(jnp.asarray(lo_miss),
                             jnp.zeros((2, D), np.float32)))
    out0, _ = nn.functional_call(
        model, {"params": dict(model.named_parameters()), "buffers": {}},
        jnp.zeros((2, S, 1 + dim)), jnp.zeros((2, D)), training=False)
    np.testing.assert_allclose(p_miss, np.asarray(jax.nn.sigmoid(out0)),
                               rtol=1e-5, atol=1e-6)

    # the export carries NO optimizer state (persistables pruning)
    import json as _json
    man = _json.load(open(tmp_path / "serve" / "manifest.json"))
    assert man["freeze"] is False
    from paddle_tpu.io.checkpoint import load_checkpoint
    saved = load_checkpoint(str(tmp_path / "serve" / "params"))["model"]
    assert set(saved["tables"].keys()) == {"embed_w", "embedx_w"}

    # refresh_only (the online path): mutate the tables, overwrite only
    # the serving values — the program file is untouched byte-for-byte,
    # and a fresh predictor serves the NEW values
    import os
    prog = tmp_path / "serve" / "model.stablehlo"
    prog_bytes = prog.read_bytes()
    prog_mtime = os.path.getmtime(prog)
    cache.state["embed_w"] = cache.state["embed_w"] * 2.0
    export_ctr_inference(str(tmp_path / "serve"), model, cache,
                         slot_ids=np.arange(S), num_dense=D,
                         refresh_only=True)
    assert prog.read_bytes() == prog_bytes
    assert os.path.getmtime(prog) == prog_mtime
    pred2 = load_inference_model(str(tmp_path / "serve"))
    got2 = np.asarray(pred2(jnp.asarray(lo32), jnp.asarray(dense)))
    rows2 = cache.lookup(pool[:8].reshape(-1))
    emb2 = cache_pull(cache.state, jnp.asarray(rows2, jnp.int32)).reshape(
        8, S, -1)
    out2, _ = nn.functional_call(
        model, {"params": dict(model.named_parameters()), "buffers": {}},
        emb2, jnp.asarray(dense), training=False)
    np.testing.assert_allclose(got2, np.asarray(jax.nn.sigmoid(out2)),
                               rtol=1e-5, atol=1e-6)
    assert not np.allclose(got2, got)  # the refresh really moved scores

    # refresh without a prior export fails loudly
    import pytest as _pytest
    with _pytest.raises(Exception, match="refresh"):
        export_ctr_inference(str(tmp_path / "nowhere"), model, cache,
                             slot_ids=np.arange(S), num_dense=D,
                             refresh_only=True)


def test_family_serving_exports(tmp_path, rng):
    """The export generalizes across the family: DIN (with_real — the
    attention mask derives from the sentinel in-graph) and ESMM
    (multitask — sigmoid per output leaf)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.io.inference import load_inference_model
    from paddle_tpu.models.ctr import export_ctr_inference
    from paddle_tpu.models.din import DIN
    from paddle_tpu.models.multitask import ESMM
    from paddle_tpu.models.ctr import CtrConfig
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import (CacheConfig,
                                               HbmEmbeddingCache,
                                               cache_pull)
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    pt.seed(0)
    S, D, dim = 6, 3, 4
    table = MemorySparseTable(TableConfig(
        shard_num=2, accessor_config=AccessorConfig(embedx_dim=dim)))
    cache_cfg = CacheConfig(capacity=1 << 8, embedx_dim=dim,
                            embedx_threshold=0.0)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    lo = rng.integers(1, 500, size=(32, S)).astype(np.uint64)
    pool = lo + (np.arange(S, dtype=np.uint64) << np.uint64(32))
    cache.begin_pass(pool.reshape(-1))
    cache.state["embedx_w"] = jnp.asarray(
        rng.normal(size=cache.state["embedx_w"].shape).astype(np.float32))

    B = 4
    lo32 = (pool[:B] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    dense = rng.normal(size=(B, D)).astype(np.float32)
    rows = cache.lookup(pool[:B].reshape(-1))
    emb = cache_pull(cache.state, jnp.asarray(rows, jnp.int32)).reshape(
        B, S, -1)

    # DIN: with_real (target cols 0-1, behavior cols 2-5). The LAST
    # two behavior positions of every row are OUT-OF-PASS keys — the
    # in-graph sentinel must zero both their embeddings AND their
    # real-mask entries (an all-in-pass batch would leave the mask
    # path untested: it would equal the constant ones reference)
    din = DIN(num_target_cols=2, num_behavior_cols=4, num_dense=D,
              embedx_dim=dim, dnn_hidden=(8,))
    export_ctr_inference(str(tmp_path / "din"), din, cache,
                         slot_ids=np.arange(S), num_dense=D,
                         with_real=True)
    lo32_miss = lo32.copy()
    lo32_miss[:, -2:] = 0xFFFFFF  # not in the pass
    got = np.asarray(load_inference_model(str(tmp_path / "din"))(
        jnp.asarray(lo32_miss), jnp.asarray(dense)))
    emb_m = np.asarray(emb).copy()
    emb_m[:, -2:, :] = 0.0
    real_m = np.ones((B, S), np.float32)
    real_m[:, -2:] = 0.0
    out, _ = nn.functional_call(
        din, {"params": dict(din.named_parameters()), "buffers": {}},
        jnp.asarray(emb_m), jnp.asarray(real_m), jnp.asarray(dense),
        training=False)
    np.testing.assert_allclose(got, np.asarray(jax.nn.sigmoid(out)),
                               rtol=1e-5, atol=1e-6)

    # ESMM: per-leaf sigmoid over (ctr, cvr)
    esmm = ESMM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=dim,
                          dnn_hidden=(8,)))
    export_ctr_inference(str(tmp_path / "esmm"), esmm, cache,
                         slot_ids=np.arange(S), num_dense=D)
    pred = load_inference_model(str(tmp_path / "esmm"))
    pctr, pctcvr = pred(jnp.asarray(lo32), jnp.asarray(dense))
    logits, _ = nn.functional_call(
        esmm, {"params": dict(esmm.named_parameters()), "buffers": {}},
        emb, jnp.asarray(dense), training=False)
    # serving MUST ship the model's own predict mapping: ESMM's second
    # output is pCTCVR = pCTR * pCVR, the quantity offline eval scored
    want_pctr, want_pctcvr = ESMM.predict(logits)
    np.testing.assert_allclose(np.asarray(pctr), np.asarray(want_pctr),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(pctcvr),
                               np.asarray(want_pctcvr),
                               rtol=1e-5, atol=1e-6)
    assert (np.asarray(pctcvr) <= np.asarray(pctr) + 1e-6).all()
