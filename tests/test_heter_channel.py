"""Cross-process heterogeneous pipeline: CPU-stage process streams
micro-batches over the native tensor channel (csrc/tensor_channel.cc —
heter_client.h:83 SendAndRecv) to a device-stage process whose jitted
step sends results back. In-process framing/backpressure tests plus the
two-subprocess round trip (heter_pipeline_trainer.cc topology).
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from paddle_tpu.parallel.heter_channel import (STOP, ChannelClient,
                                               ChannelServer, channel_source)
from paddle_tpu.ps.native import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native lib unavailable")


def test_roundtrip_types_and_shapes(rng):
    srv = ChannelServer(capacity=4)
    cli = ChannelClient("127.0.0.1", srv.port)
    item = {
        "f32": rng.normal(size=(3, 5)).astype(np.float32),
        "u64": rng.integers(0, 1 << 60, size=7, dtype=np.uint64),
        "i32scalar": np.asarray(-3, np.int32),
        "empty": np.zeros((0, 4), np.float32),
    }
    cli.send(item)
    got = srv.recv(timeout=10)
    for k in item:
        np.testing.assert_array_equal(got[k], item[k])
        assert got[k].dtype == item[k].dtype
    cli.send_stop()
    assert srv.recv(timeout=10) is STOP
    srv.close()
    cli.close()


def test_stop_terminates_source(rng):
    srv = ChannelServer(capacity=4)
    cli = ChannelClient("127.0.0.1", srv.port)
    for i in range(5):
        cli.send({"i": np.asarray(i)})
    cli.send_stop()
    items = list(channel_source(srv, timeout=10))
    assert [int(x["i"]) for x in items] == list(range(5))
    srv.close()
    cli.close()


_DEV_STAGE = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax; jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.parallel.heter_channel import (ChannelServer,
        ChannelClient, channel_source)

    in_port, out_port = int(sys.argv[1]), int(sys.argv[2])
    srv = ChannelServer(port=in_port, capacity=4)

    @jax.jit
    def dense_tail(x):                # the device-stage section
        return jnp.sum(x * 2.0), jnp.mean(x)

    cli = ChannelClient("127.0.0.1", out_port)
    for item in channel_source(srv, timeout=60):
        s, m = dense_tail(jnp.asarray(item["x"]))
        cli.send({"idx": item["idx"], "sum": np.asarray(s),
                  "mean": np.asarray(m)})
    cli.send_stop()
    srv.close(); cli.close()
""")

_CPU_STAGE = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np
    from paddle_tpu.parallel.heter_channel import ChannelClient
    from paddle_tpu.parallel.heter_pipeline import (HeterPipelineTrainer,
        SectionConfig)

    dev_port = int(sys.argv[1])
    cli = ChannelClient("127.0.0.1", dev_port)
    rng = np.random.default_rng(0)
    batches = [{"idx": np.asarray(i),
                "x": rng.normal(size=(4, 8)).astype(np.float32)}
               for i in range(6)]

    def host_head(item):              # CPU-stage section: normalize
        x = item["x"]
        return {"idx": item["idx"], "x": (x - x.mean()) / (x.std() + 1e-6)}

    def sink(item):
        cli.send(item)
        return item

    tr = HeterPipelineTrainer([SectionConfig(host_head, place="cpu"),
                               SectionConfig(sink, place="cpu")])
    tr.run(iter(batches), collect=False)
    cli.send_stop()
    cli.close()
""")


@pytest.mark.slow
def test_two_process_cpu_to_device_pipeline(tmp_path):
    import socket

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)

    results = ChannelServer(capacity=16)
    in_port = free_port()

    dev = tmp_path / "dev.py"
    dev.write_text(_DEV_STAGE)
    cpu = tmp_path / "cpu.py"
    cpu.write_text(_CPU_STAGE)
    p_dev = subprocess.Popen(
        [sys.executable, str(dev), str(in_port), str(results.port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    p_cpu = subprocess.Popen(
        [sys.executable, str(cpu), str(in_port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)

    got = {}
    try:
        for item in channel_source(results, timeout=120):
            got[int(item["idx"])] = (float(item["sum"]), float(item["mean"]))
    finally:
        for p in (p_cpu, p_dev):
            out, err = p.communicate(timeout=60)
            assert p.returncode == 0, err[-2000:]
        results.close()

    assert sorted(got) == list(range(6))
    # recompute expectation: sum(2 * normalize(x)) and mean(normalize(x))
    rng = np.random.default_rng(0)
    for i in range(6):
        x = rng.normal(size=(4, 8)).astype(np.float32)
        xn = (x - x.mean()) / (x.std() + 1e-6)
        s, m = got[i]
        np.testing.assert_allclose(s, float(np.sum(xn * 2.0)), atol=1e-4)
        np.testing.assert_allclose(m, float(np.mean(xn)), atol=1e-5)


def test_scalar_shape_preserved(rng):
    srv = ChannelServer(capacity=2)
    cli = ChannelClient("127.0.0.1", srv.port)
    cli.send({"s": np.asarray(7, np.int64), "v": np.asarray([7], np.int64)})
    got = srv.recv(timeout=10)
    assert got["s"].shape == () and got["v"].shape == (1,)
    srv.close(); cli.close()
