"""Device-resident graph table (ops/device_graph.py): in-graph neighbor
sampling and deepwalk random walks vs the host GraphTable adjacency
(the graph_gpu_ps_table.h / GraphDataGenerator roles, TPU-native)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.device_graph import DeviceGraph
from paddle_tpu.ps.device_hash import split_keys
from paddle_tpu.ps.graph_table import GraphTable
from paddle_tpu.ps.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native cuckoo unavailable")


def _graph(rng, n_nodes=64, n_edges=400):
    g = GraphTable(shard_num=4)
    nodes = np.arange(1, n_nodes + 1, dtype=np.uint64)
    g.add_graph_node(nodes)
    src = rng.choice(nodes, n_edges)
    dst = rng.choice(nodes, n_edges)
    w = rng.uniform(0.5, 2.0, n_edges).astype(np.float32)
    g.add_edges(src, dst, w)
    adj = {}
    for s, d in zip(src, dst):
        adj.setdefault(int(s), set()).add(int(d))
    return g, nodes, adj


def _keys64(hi, lo):
    return (np.asarray(hi, np.uint64) << np.uint64(32)) | np.asarray(
        lo, np.uint64)


def test_sample_neighbors_stays_on_edges(rng):
    g, nodes, adj = _graph(rng)
    dg = DeviceGraph.from_graph_table(g, max_deg=32)
    assert dg.capped_rows == 0
    q = rng.choice(nodes, 20, replace=False)
    hi, lo = split_keys(q)
    fn = jax.jit(lambda r, h, l: DeviceGraph.sample_neighbors(
        dg.state, r, h, l, 8))
    nh, nl, mask = fn(jax.random.key(0), jnp.asarray(hi), jnp.asarray(lo))
    nh, nl, mask = map(np.asarray, (nh, nl, mask))
    for i, nid in enumerate(q):
        cand = adj.get(int(nid), set())
        if not cand:
            assert not mask[i].any()
            continue
        assert mask[i].all()  # with replacement: every draw valid
        got = set(_keys64(nh[i], nl[i]).tolist())
        assert got <= cand, (nid, got - cand)


def test_sample_unknown_and_isolated_nodes_masked(rng):
    g, nodes, _ = _graph(rng)
    g.add_graph_node([999])  # isolated: registered, no edges
    dg = DeviceGraph.from_graph_table(g, max_deg=32)
    q = np.asarray([999, 123456789], np.uint64)  # isolated + unknown
    hi, lo = split_keys(q)
    _, _, mask = DeviceGraph.sample_neighbors(
        dg.state, jax.random.key(1), jnp.asarray(hi), jnp.asarray(lo), 4)
    assert not np.asarray(mask).any()


def test_random_walks_follow_edges_and_freeze_at_dead_ends(rng):
    g = GraphTable(shard_num=2)
    # a path graph 1→2→3→4 plus a sink node 4 (no out-edges): walks
    # must follow the chain and freeze at the sink
    g.add_edges([1, 2, 3], [2, 3, 4])
    dg = DeviceGraph.from_graph_table(g, max_deg=4)
    hi, lo = split_keys(np.asarray([1, 4], np.uint64))
    wh, wl, live = jax.jit(lambda r, h, l: DeviceGraph.random_walk(
        dg.state, r, h, l, 5))(jax.random.key(0), jnp.asarray(hi),
                               jnp.asarray(lo))
    walks = _keys64(np.asarray(wh), np.asarray(wl))
    live = np.asarray(live)
    np.testing.assert_array_equal(walks[0, :4], [1, 2, 3, 4])
    assert live[0, :4].all() and not live[0, 4:].any()
    np.testing.assert_array_equal(walks[0, 4:], 4)  # frozen at the sink
    np.testing.assert_array_equal(walks[1], 4)      # started at the sink
    assert live[1, 0] and not live[1, 1:].any()


def test_weighted_sampling_respects_weights(rng):
    g = GraphTable(shard_num=2)
    # node 1 → {2 (w 9), 3 (w 1)}: draws should favor 2 roughly 9:1
    g.add_edges([1, 1], [2, 3], [9.0, 1.0])
    dg = DeviceGraph.from_graph_table(g, max_deg=4)
    hi, lo = split_keys(np.asarray([1], np.uint64))
    nh, nl, mask = DeviceGraph.sample_neighbors(
        dg.state, jax.random.key(2), jnp.asarray(hi), jnp.asarray(lo), 2000)
    drawn = _keys64(np.asarray(nh)[0], np.asarray(nl)[0])
    frac2 = (drawn == 2).mean()
    assert 0.85 < frac2 < 0.95, frac2  # 9:1 odds within sampling noise


def test_degree_cap_is_counted_not_silent(rng):
    g = GraphTable(shard_num=2)
    g.add_edges(np.ones(10, np.int64), np.arange(2, 12))
    dg = DeviceGraph.from_graph_table(g, max_deg=4)
    assert dg.capped_rows == 1
    hi, lo = split_keys(np.asarray([1], np.uint64))
    nh, nl, mask = DeviceGraph.sample_neighbors(
        dg.state, jax.random.key(3), jnp.asarray(hi), jnp.asarray(lo), 16)
    # capped row samples only its kept (first max_deg) neighbors
    drawn = set(_keys64(np.asarray(nh)[0], np.asarray(nl)[0]).tolist())
    assert drawn <= {2, 3, 4, 5}


def test_zero_weight_mass_node_is_masked(rng):
    """A known node whose kept weights all clamp to zero must mask out —
    not surface the padding key 0 as a live neighbor/walk step."""
    nodes = np.asarray([5], np.uint64)
    nbrs = np.asarray([[7, 8, 0, 0]], np.uint64)
    deg = np.asarray([2], np.int32)
    dg = DeviceGraph.from_arrays(nodes, nbrs, deg,
                                 weights=np.zeros((1, 4), np.float32))
    hi, lo = split_keys(nodes)
    _, _, mask = DeviceGraph.sample_neighbors(
        dg.state, jax.random.key(0), jnp.asarray(hi), jnp.asarray(lo), 4)
    assert not np.asarray(mask).any()
    wh, wl, live = DeviceGraph.random_walk(
        dg.state, jax.random.key(0), jnp.asarray(hi), jnp.asarray(lo), 3)
    assert not np.asarray(live)[0, 1:].any()
    np.testing.assert_array_equal(_keys64(np.asarray(wh), np.asarray(wl))[0], 5)


def test_from_arrays_counts_capping(rng):
    nodes = np.asarray([1], np.uint64)
    nbrs = np.asarray([[2, 3]], np.uint64)
    dg = DeviceGraph.from_arrays(nodes, nbrs, np.asarray([9], np.int32))
    assert dg.capped_rows == 1
