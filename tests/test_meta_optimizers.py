"""Meta-optimizer chain tests (reference fleet/meta_optimizers/ + its
unittests test_fleet_amp_meta_optimizer.py, test_fleet_gradient_merge_
meta_optimizer.py, test_fleet_localsgd_meta_optimizer.py,
test_fleet_lars_meta_optimizer.py, test_fleet_dgc_meta_optimizer.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import optimizer as opt_mod
from paddle_tpu.amp import GradScaler
from paddle_tpu.distributed import DistributedStrategy, apply_strategy
from paddle_tpu.distributed.meta_optimizers import (
    AMPOptimizer,
    DGCMomentumOptimizer,
    GradientMergeOptimizer,
    LocalSGDOptimizer,
)
from paddle_tpu.distributed.recompute import recompute


def _params():
    return {"w": jnp.ones((4, 4), jnp.float32), "b": jnp.zeros((4,), jnp.float32)}


def _grads(scale=1.0):
    return {"w": jnp.full((4, 4), 0.5 * scale, jnp.float32),
            "b": jnp.full((4,), 0.1 * scale, jnp.float32)}


class TestGradientMerge:
    def test_applies_every_k_steps(self):
        inner = opt_mod.SGD(learning_rate=1.0)
        gm = GradientMergeOptimizer(inner, k_steps=3, avg=True)
        params = _params()
        state = gm.init(params)
        for i in range(2):
            params, state = gm.update(_grads(), state, params)
            # held: params unchanged
            np.testing.assert_allclose(params["w"], 1.0)
        params, state = gm.update(_grads(), state, params)
        # applied once with the averaged grad (= the grad itself here)
        np.testing.assert_allclose(params["w"], 1.0 - 0.5, rtol=1e-6)
        assert int(state["count"]) == 0

    def test_sum_mode(self):
        gm = GradientMergeOptimizer(opt_mod.SGD(1.0), k_steps=2, avg=False)
        params = _params()
        state = gm.init(params)
        params, state = gm.update(_grads(), state, params)
        params, state = gm.update(_grads(), state, params)
        np.testing.assert_allclose(params["w"], 1.0 - 2 * 0.5, rtol=1e-6)

    def test_jit_compiles(self):
        gm = GradientMergeOptimizer(opt_mod.Adam(0.01), k_steps=2)
        params = _params()
        state = gm.init(params)
        step = jax.jit(gm.update)
        params, state = step(_grads(), state, params)
        params, state = step(_grads(), state, params)
        assert np.isfinite(np.asarray(params["w"])).all()


class TestAMP:
    def test_skips_nonfinite_and_decays_scale(self):
        amp = AMPOptimizer(opt_mod.SGD(1.0), init_loss_scaling=1024.0,
                           decr_every_n_nan_or_inf=1, decr_ratio=0.5)
        params = _params()
        state = amp.init(params)
        bad = {"w": jnp.full((4, 4), jnp.nan), "b": jnp.zeros((4,))}
        params2, state = amp.update(bad, state, params)
        np.testing.assert_allclose(params2["w"], params["w"])  # skipped
        assert float(state["scaler"].loss_scale) == 512.0

    def test_applies_unscaled(self):
        amp = AMPOptimizer(opt_mod.SGD(1.0), init_loss_scaling=8.0,
                           use_dynamic_loss_scaling=False)
        params = _params()
        state = amp.init(params)
        # grads of the 8x-scaled loss
        scaled_grads = _grads(scale=8.0)
        params, state = amp.update(scaled_grads, state, params)
        np.testing.assert_allclose(params["w"], 1.0 - 0.5, rtol=1e-6)

    def test_scale_growth(self):
        amp = AMPOptimizer(opt_mod.SGD(0.1), init_loss_scaling=4.0,
                           incr_every_n_steps=2, incr_ratio=2.0)
        params = _params()
        state = amp.init(params)
        for _ in range(2):
            params, state = amp.update(_grads(scale=4.0), state, params)
        assert float(state["scaler"].loss_scale) == 8.0


class TestGradScaler:
    def test_roundtrip(self):
        sc = GradScaler(init_loss_scaling=16.0)
        st = sc.init()
        loss = jnp.asarray(2.0)
        assert float(sc.scale(loss, st)) == 32.0
        grads, ok = sc.unscale({"g": jnp.asarray(32.0)}, st)
        assert bool(ok) and float(grads["g"]) == 2.0


class TestDGC:
    def test_residual_bookkeeping(self):
        dgc = DGCMomentumOptimizer(opt_mod.SGD(1.0), momentum=0.0,
                                   rampup_begin_step=0, sparsity=[0.75])
        params = {"w": jnp.zeros((16,), jnp.float32)}
        grads = {"w": jnp.asarray(np.arange(16, dtype=np.float32))}
        state = dgc.init(params)
        params, state = dgc.update(grads, state, params)
        # only the top quartile released; the rest retained in residual v
        released = -np.asarray(params["w"])  # sgd lr=1: delta == released grad
        assert (released > 0).sum() <= 5
        v = np.asarray(state["v"]["w"])
        np.testing.assert_allclose(released + v, np.arange(16), rtol=1e-6)

    def test_pre_rampup_is_momentum(self):
        dgc = DGCMomentumOptimizer(opt_mod.SGD(1.0), momentum=0.0,
                                   rampup_begin_step=100, sparsity=[0.99])
        params = _params()
        state = dgc.init(params)
        params, state = dgc.update(_grads(), state, params)
        np.testing.assert_allclose(params["w"], 1.0 - 0.5, rtol=1e-6)


class TestLocalSGD:
    def test_sync_every_k(self):
        calls = []

        def fake_sync(tree):
            calls.append(1)
            return jax.tree_util.tree_map(lambda x: x * 0 + 7.0, tree)

        ls = LocalSGDOptimizer(opt_mod.SGD(1.0), k_steps=2, sync_fn=fake_sync)
        params = _params()
        state = ls.init(params)
        params, state = ls.update(_grads(), state, params)
        assert float(params["w"][0, 0]) != 7.0
        params, state = ls.update(_grads(), state, params)
        np.testing.assert_allclose(params["w"], 7.0)

    def test_pmean_under_shard_map(self):
        from jax.sharding import Mesh, PartitionSpec as P
        from jax import shard_map

        devs = np.array(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devs, ("dp",))
        ls = LocalSGDOptimizer(opt_mod.SGD(1.0), k_steps=1, axis="dp")
        params = {"w": jnp.zeros((4, 2), jnp.float32)}
        state = ls.init(params)
        grads = {"w": jnp.tile(jnp.arange(4, dtype=jnp.float32)[:, None], (1, 2))}

        def step(p, s, g):
            return ls.update(g, s, p)

        fn = shard_map(step, mesh=mesh,
                       in_specs=(P("dp"), P(), P("dp")),
                       out_specs=(P("dp"), P()))
        new_params, _ = jax.jit(fn)(params, state, grads)
        # per-device grads 0..3, lr 1 → local params -g, pmean → -1.5
        np.testing.assert_allclose(new_params["w"], -1.5)


class TestLarsLamb:
    def test_lars_trust_ratio(self):
        lars = opt_mod.Lars(learning_rate=0.1, momentum=0.9, lars_coeff=0.001)
        params = _params()
        state = lars.init(params)
        new_params, state = lars.update(_grads(), state, params)
        assert not np.allclose(new_params["w"], params["w"])
        # zero-norm bias path falls back to plain lr (no NaN)
        assert np.isfinite(np.asarray(new_params["b"])).all()

    def test_lamb_matches_adam_direction(self):
        lamb = opt_mod.Lamb(learning_rate=0.01, lamb_weight_decay=0.0)
        params = _params()
        state = lamb.init(params)
        new_params, _ = lamb.update(_grads(), state, params)
        assert np.all(np.asarray(new_params["w"]) < 1.0)

    def test_rmsprop(self):
        rms = opt_mod.RMSProp(learning_rate=0.01)
        params = _params()
        state = rms.init(params)
        new_params, _ = rms.update(_grads(), state, params)
        assert np.isfinite(np.asarray(new_params["w"])).all()


class TestStrategyCompiler:
    def test_chain_order(self):
        strategy = DistributedStrategy(amp=True, gradient_merge=True,
                                       gradient_merge_configs={"k_steps": 2})
        base = opt_mod.Momentum(0.1)
        chained = apply_strategy(base, strategy)
        assert isinstance(chained, AMPOptimizer)
        assert isinstance(chained.inner, GradientMergeOptimizer)
        assert chained.inner.inner is base

    def test_lars_swap(self):
        strategy = DistributedStrategy(lars=True)
        chained = apply_strategy(opt_mod.Momentum(0.1), strategy)
        assert isinstance(chained, opt_mod.Lars)

    def test_dgc_requires_momentum(self):
        strategy = DistributedStrategy(dgc=True)
        with pytest.raises(Exception):
            apply_strategy(opt_mod.Adam(0.1), strategy)

    def test_full_chain_trains(self):
        strategy = DistributedStrategy(amp=True, gradient_merge=True,
                                       gradient_merge_configs={"k_steps": 2},
                                       localsgd=True,
                                       localsgd_configs={"k_steps": 4})
        # localsgd pmean needs an axis; use identity sync for the
        # single-process numerical check
        from paddle_tpu.distributed.meta_optimizers import LocalSGDOptimizer as LS

        opt = apply_strategy(opt_mod.SGD(0.5), strategy)
        # swap in identity sync (no named axis outside shard_map)
        node = opt
        while node is not None:
            if isinstance(node, LS):
                node._sync = lambda t: t
            node = getattr(node, "inner", None)
        params = _params()
        state = opt.init(params)
        step = jax.jit(opt.update)
        for _ in range(4):
            params, state = step(_grads(), state, params)
        assert np.isfinite(np.asarray(params["w"])).all()
        assert float(params["w"][0, 0]) < 1.0


class TestRecompute:
    def test_matches_plain_grad(self):
        def f(x):
            return jnp.sum(jnp.tanh(x @ x.T))

        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)), jnp.float32)
        g_plain = jax.grad(f)(x)
        g_remat = jax.grad(lambda x: recompute(f, x))(x)
        # remat re-executes the forward inside the backward; XLA fuses
        # the two programs differently (jax 0.4.37 CPU: ~3e-6 rel on a
        # couple of elements), so bitwise equality is not the contract —
        # f32-roundoff agreement is
        np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_remat),
                                   rtol=1e-5, atol=1e-6)

    def test_policy_names(self):
        def f(x):
            return jnp.sum(x * x)

        x = jnp.ones((4,))
        for pol in ("full", "dots", "nothing_saveable"):
            assert np.isfinite(float(recompute(f, x, policy=pol)))


def test_asp_24_sparsity_masks_params():
    import jax.numpy as jnp

    from paddle_tpu import optimizer
    from paddle_tpu.distributed.meta_optimizers import ASPOptimizer

    opt = ASPOptimizer(optimizer.SGD(0.1))
    w = jnp.asarray(np.arange(1.0, 9.0).reshape(2, 4))  # rows [1..4], [5..8]
    params = {"w": w}
    st = opt.init(params)
    mask = st["asp_mask"]["w"]
    # 2:4: keep the two largest of every 4 -> cols 2,3 of each row
    assert mask.tolist() == [[False, False, True, True]] * 2
    g = {"w": jnp.ones_like(w)}
    new_params, st = opt.update(g, st, params)
    # pruned slots stay zero; kept slots took the SGD step
    assert (np.asarray(new_params["w"])[:, :2] == 0).all()
    np.testing.assert_allclose(np.asarray(new_params["w"])[:, 2:],
                               np.asarray(w)[:, 2:] - 0.1)


def test_asp_skips_unprunable_shapes():
    import jax.numpy as jnp

    from paddle_tpu import optimizer
    from paddle_tpu.distributed.meta_optimizers import ASPOptimizer

    opt = ASPOptimizer(optimizer.SGD(0.1))
    params = {"b": jnp.ones(5), "w3": jnp.ones((2, 3))}  # bias + indivisible
    st = opt.init(params)
    assert st["asp_mask"]["b"].all() and st["asp_mask"]["w3"].all()


def test_select_runtime_mapping():
    from paddle_tpu.distributed.meta_optimizers import select_runtime
    from paddle_tpu.distributed.strategy import DistributedStrategy

    assert select_runtime(DistributedStrategy())["runtime"] == "single"
    assert select_runtime(DistributedStrategy(a_sync=True))["runtime"] == "ps"
    r = select_runtime(DistributedStrategy(sharding=True,
                                           sharding_configs={"stage": 2, "sharding_degree": 4}))
    assert r == {"runtime": "spmd", "kwargs": {"zero_stage": 2, "sharding_degree": 4}}
    r = select_runtime(DistributedStrategy(without_graph_optimization=True))
    assert r["runtime"] == "spmd" and r["kwargs"]["zero_stage"] == 0
    r = select_runtime(DistributedStrategy(pipeline=True))
    assert r["runtime"] == "hybrid" and r["kwargs"]["pp"] >= 2
    r = select_runtime(DistributedStrategy(tensor_parallel=True,
                                           tensor_parallel_configs={"tensor_parallel_degree": 4}))
    assert r["runtime"] == "hybrid" and r["kwargs"]["mp"] == 4
    r = select_runtime(DistributedStrategy(
        hybrid_configs={"dp_degree": 2, "mp_degree": 2, "pp_degree": 2,
                        "cp_degree": 1, "ep_degree": 1}))
    assert r["runtime"] == "hybrid"
