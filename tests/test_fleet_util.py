"""fleet.util multi-worker collectives over the TCPStore coordination
plane (the GlooWrapper reduce role, framework/fleet/gloo_wrapper.h:134 +
metrics_py.cc): subprocess workers must see the true global reduction,
not their local values.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np

_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax; jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.distributed.fleet import Fleet
    from paddle_tpu.distributed.role_maker import UserDefinedRoleMaker, Role

    rank = int(sys.argv[1]); world = int(sys.argv[2])
    rm = UserDefinedRoleMaker(
        current_id=rank, role=Role.WORKER, worker_num=world,
        server_endpoints=["127.0.0.1:0"],
        trainer_endpoints=[f"127.0.0.1:{6200+i}" for i in range(world)])
    f = Fleet().init(rm)
    f.init_worker()
    got = f.util.all_reduce(np.asarray([1.0 * (rank + 1), 2.0]), mode="sum")
    f.util.barrier()
    mx = f.util.all_reduce(np.float32(rank), mode="max")
    f.util.barrier()  # keep rank 0's store daemon alive until all read
    print("RESULT", got[0], got[1], float(mx), flush=True)
    f.stop_worker()
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_util_allreduce_across_processes(tmp_path):
    world = 3
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PADDLE_UTIL_STORE_PORT=str(port),
               JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(world)],
        env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for r in range(world)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=120)
        assert p.returncode == 0, err[-2000:]
        outs.append(out)
    # sum over ranks of [rank+1, 2] = [6, 6]; max(rank) = 2
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("RESULT")][0]
        _, a, b, c = line.split()
        assert float(a) == 6.0 and float(b) == 6.0 and float(c) == 2.0, line


def test_util_identity_single_worker():
    from paddle_tpu.distributed.fleet import Fleet

    f = Fleet().init()
    v = np.asarray([3.0, 4.0])
    np.testing.assert_array_equal(f.util.all_reduce(v), v)
    f.util.barrier()  # no-op


_SHUFFLE_WORKER = textwrap.dedent("""
    import os, sys
    import numpy as np
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax; jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.distributed.fleet import Fleet
    from paddle_tpu.distributed.role_maker import UserDefinedRoleMaker, Role

    rank = int(sys.argv[1]); world = int(sys.argv[2])
    rm = UserDefinedRoleMaker(
        current_id=rank, role=Role.WORKER, worker_num=world,
        server_endpoints=["127.0.0.1:0"],
        trainer_endpoints=[f"127.0.0.1:{6300+i}" for i in range(world)])
    f = Fleet().init(rm)
    f.init_worker()

    slots = [SlotDesc("ids", is_float=False, max_len=1)]
    lo, hi = rank * 50, rank * 50 + 50
    ds = InMemoryDataset(slots, seed=rank)
    ds.load_from_lines([f"1 {i}" for i in range(lo, hi)])
    ds.global_shuffle(worker_id=rank, worker_num=world, util=f.util)
    f.util.barrier()

    # union across workers must be exactly 0..99: all_reduce a count
    # histogram of the ids this worker now holds
    ids = ds.pass_feasigns().astype(np.int64)
    hist = np.bincount(ids, minlength=100).astype(np.float64)
    total = f.util.all_reduce(hist, mode="sum")
    assert total.shape[0] >= 100 and (total[:100] == 1.0).all(), total[:100]
    f.util.barrier()
    print("SHUFFLE_OK", rank, ds.num_records, flush=True)
    f.stop_worker()
""")


def test_global_shuffle_across_processes(tmp_path):
    world = 2
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PADDLE_UTIL_STORE_PORT=str(port),
               JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("XLA_FLAGS", None)
    script = tmp_path / "worker.py"
    script.write_text(_SHUFFLE_WORKER)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(world)],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for r in range(world)]
    try:
        for r, p in enumerate(procs):
            out, err = p.communicate(timeout=180)
            assert p.returncode == 0, f"rank {r}:\n{err[-3000:]}"
            assert f"SHUFFLE_OK {r}" in out, out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
