"""1F1B + interleaved pipeline schedules (parallel/pipeline_1f1b.py):
loss/training parity with F-then-B and the serial model, and the bounded
activation-memory property vs F-then-B (section_worker.cc:139-189,
pipeline_parallel.py:30).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.parallel.pipeline import LayerDesc, PipelineLayer, PipelineTrainer


class Block(nn.Layer):
    def __init__(self, d):
        super().__init__()
        self.fc = nn.Linear(d, d)

    def forward(self, x):
        return jax.nn.relu(self.fc(x)) + x


def build(seed, d=8, stages=4):
    pt.seed(seed)
    return PipelineLayer(
        [LayerDesc(Block, d) for _ in range(stages)],
        embed=nn.Linear(4, d),
        head=nn.Linear(d, 3),
    )


def _data(n=16):
    x = np.random.default_rng(1).normal(size=(n, 4)).astype(np.float32)
    y = np.random.default_rng(2).integers(0, 3, n).astype(np.int32)
    return jnp.asarray(x), jnp.asarray(y)


def _micro_mean_loss(micro):
    """Serial-reference loss: mean over micro-batches of per-micro CE."""
    def loss(out, yy):
        m = out.shape[0] // micro
        losses = [nn.functional.cross_entropy(out[i*m:(i+1)*m],
                                              yy[i*m:(i+1)*m])
                  for i in range(micro)]
        return jnp.mean(jnp.stack(losses))
    return loss


def test_1f1b_matches_f_then_b_trajectory():
    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 4})
    x, y = _data(16)
    a = PipelineTrainer(build(0), optimizer.SGD(0.2),
                        nn.functional.cross_entropy, mesh, num_micro=4,
                        schedule="f_then_b")
    b = PipelineTrainer(build(0), optimizer.SGD(0.2),
                        nn.functional.cross_entropy, mesh, num_micro=4,
                        schedule="1f1b")
    for i in range(5):
        la = float(a.train_step(x, y))
        lb = float(b.train_step(x, y))
        np.testing.assert_allclose(lb, la, rtol=1e-4, atol=1e-6,
                                   err_msg=f"step {i}")


def test_interleave_matches_f_then_b_trajectory():
    # 8 logical stages on 4 ranks, 2 virtual chunks each
    mesh = mesh_mod.make_mesh({"dp": 2, "pp": 4})
    x, y = _data(16)
    # f_then_b needs stages == pp ranks, so the common reference for the
    # 8-logical-stage interleave is the serial model.
    serial = build(0, stages=8)
    b = PipelineTrainer(build(0, stages=8), optimizer.SGD(0.2),
                        nn.functional.cross_entropy, mesh, num_micro=4,
                        schedule="interleave", num_virtual=2)
    micro = 4
    from paddle_tpu.executor import Trainer

    s = Trainer(serial, optimizer.SGD(0.2), _micro_mean_loss(micro))
    for i in range(5):
        lb = float(b.train_step(x, y))
        ls = float(s.train_step(x, y))
        np.testing.assert_allclose(lb, ls, rtol=1e-3, atol=1e-5,
                                   err_msg=f"step {i}")


@pytest.mark.parametrize("S,V,M", [(2, 2, 3), (2, 3, 5), (4, 2, 6),
                                   (4, 2, 7), (2, 2, 4), (4, 4, 5)])
def test_interleave_arbitrary_micro_matches_serial(S, V, M):
    """Property grid over (pp size, virtual chunks, micro count) with M
    NOT divisible by S (plus one divisible control): the padded-tail
    interleave schedule must match the serial model's loss exactly for
    every geometry (pipeline_parallel.py:30 accepts arbitrary M)."""
    mesh = mesh_mod.make_mesh({"dp": 8 // S, "pp": S})
    n = M * (8 // S)  # one row per dp shard per micro-batch
    rng = np.random.default_rng(S * 100 + V * 10 + M)
    x = jnp.asarray(rng.normal(size=(n, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 3, n), jnp.int32)

    b = PipelineTrainer(build(0, stages=S * V), optimizer.SGD(0.2),
                        nn.functional.cross_entropy, mesh, num_micro=M,
                        schedule="interleave", num_virtual=V)
    from paddle_tpu.executor import Trainer

    s = Trainer(build(0, stages=S * V), optimizer.SGD(0.2),
                _micro_mean_loss(M))
    for i in range(3):
        lb = float(b.train_step(x, y))
        ls = float(s.train_step(x, y))
        np.testing.assert_allclose(lb, ls, rtol=1e-3, atol=1e-5,
                                   err_msg=f"S={S} V={V} M={M} step {i}")


@pytest.mark.slow
def test_1f1b_bounds_activation_memory():
    """At M >> S the F-then-B autodiff schedule stashes O(M) activations;
    1F1B keeps a fixed 2S-slot ring. Compare compiled temp-buffer sizes."""
    mesh = mesh_mod.make_mesh({"dp": 1, "pp": 4, "mp": 2})
    d, M, n = 64, 32, 64
    x = jnp.asarray(np.random.default_rng(0).normal(size=(n, 4)), jnp.float32)
    y = jnp.asarray(np.zeros(n, np.int32))

    def temp_bytes(schedule):
        tr = PipelineTrainer(build(0, d=d), optimizer.SGD(0.1),
                             nn.functional.cross_entropy, mesh, num_micro=M,
                             schedule=schedule)
        xm = x.reshape(M, n // M, 4)
        ym = y.reshape(M, n // M)
        rng = jax.random.key(0)
        lowered = tr._step.lower(tr._params, tr.opt_state, xm, ym, rng)
        ma = lowered.compile().memory_analysis()
        if ma is None:
            pytest.skip("memory_analysis unavailable on this backend")
        return ma.temp_size_in_bytes

    ftb = temp_bytes("f_then_b")
    ofo = temp_bytes("1f1b")
    # the 1F1B program's transient working set must be well below F-then-B
    assert ofo < 0.7 * ftb, (ofo, ftb)


@pytest.mark.slow
def test_dp_sharded_batch_matches_serial():
    """dp now SHARDS micro-batches (previously replicated): both
    schedules on a dp=2×pp=4 mesh must follow the serial single-model
    trajectory on identical data — the dp loss/grad reduction has to be
    exact, not just self-consistent."""
    from paddle_tpu.executor import Trainer

    x, y = _data(16)
    micro = 4

    for schedule in ("f_then_b", "1f1b"):
        mesh = mesh_mod.make_mesh({"dp": 2, "pp": 4})
        tr = PipelineTrainer(build(0), optimizer.SGD(0.2),
                             nn.functional.cross_entropy, mesh,
                             num_micro=micro, schedule=schedule)
        serial = Trainer(build(0), optimizer.SGD(0.2), _micro_mean_loss(micro))
        for i in range(4):
            lp = float(tr.train_step(x, y))
            ls = float(serial.train_step(x, y))
            np.testing.assert_allclose(lp, ls, rtol=1e-3, atol=1e-5,
                                       err_msg=f"{schedule} step {i}")


def test_pipeline_trainer_save_load_resume(tmp_path):
    """Checkpoint/resume for the pipeline trainer (both schedules):
    restored runs continue the exact trajectory."""
    x, y = _data(16)
    for schedule in ("f_then_b", "1f1b"):
        mesh = mesh_mod.make_mesh({"dp": 2, "pp": 4})
        a = PipelineTrainer(build(0), optimizer.SGD(0.2),
                            nn.functional.cross_entropy, mesh, num_micro=4,
                            schedule=schedule)
        for _ in range(2):
            a.train_step(x, y)
        a.save(str(tmp_path / schedule))
        la = [float(a.train_step(x, y)) for _ in range(2)]

        b = PipelineTrainer(build(1), optimizer.SGD(0.2),
                            nn.functional.cross_entropy, mesh, num_micro=4,
                            schedule=schedule)
        b.load(str(tmp_path / schedule))
        assert b.global_step == 2
        lb = [float(b.train_step(x, y)) for _ in range(2)]
        np.testing.assert_allclose(lb, la, rtol=1e-5, err_msg=schedule)
