"""HBM embedding cache: pass lifecycle, in-graph pull/push math parity
with the host-table AdaGrad rule, flush-back correctness (reference:
heter_ps/test_comm.cu pull/push on fake keys + EndPass dump)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ps import (
    AccessorConfig,
    CacheConfig,
    HbmEmbeddingCache,
    MemorySparseTable,
    SGDRuleConfig,
    TableConfig,
    cache_pull,
    cache_push,
)


def make_setup(embedx_threshold=0.5, capacity=64):
    sgd = SGDRuleConfig(learning_rate=0.1, initial_g2sum=3.0)
    acc = AccessorConfig(embedx_dim=4, embedx_threshold=embedx_threshold, sgd=sgd)
    table = MemorySparseTable(TableConfig(shard_num=2, accessor_config=acc))
    cache = HbmEmbeddingCache(
        table, CacheConfig(capacity=capacity, embedx_dim=4, sgd=sgd,
                           embedx_threshold=embedx_threshold)
    )
    return table, cache


def test_pass_lifecycle_pull_push_flush():
    table, cache = make_setup()
    keys = np.asarray([10, 20, 30, 40], np.uint64)
    n = cache.begin_pass(keys)
    assert n == 4

    rows = cache.lookup(keys)
    vals = cache_pull(cache.state, jnp.asarray(rows))
    assert vals.shape == (4, 5)

    # push one gradient step with shows
    grads = jnp.ones((4, 5), jnp.float32) * 0.5
    cache.state = jax.jit(
        lambda st, r, g: cache_push(st, r, g, jnp.ones(4), jnp.ones(4), cache.config)
    )(cache.state, jnp.asarray(rows), grads)

    after = np.asarray(cache_pull(cache.state, jnp.asarray(rows)))
    assert np.abs(after[:, 0]).sum() > 0  # embed moved

    cache.end_pass()
    assert cache.state is None
    # host table saw the flushed values
    host_vals = table.pull_sparse(keys)
    np.testing.assert_allclose(host_vals[:, 0], 1.0, rtol=1e-5)  # shows
    np.testing.assert_allclose(host_vals[:, 2], after[:, 0], rtol=1e-5)  # embed_w


def test_cache_push_matches_host_adagrad():
    """Device AdaGrad must equal the host sparse_sgd_rule math."""
    table, cache = make_setup(embedx_threshold=100.0)  # keep embedx lazy
    keys = np.asarray([7], np.uint64)
    cache.begin_pass(keys)
    rows = jnp.asarray(cache.lookup(keys))

    g = 0.3
    show = 2.0
    w_before = float(np.asarray(cache.state["embed_w"])[int(rows[0]), 0])
    cache.state = cache_push(
        cache.state, rows,
        jnp.asarray([[g, 0, 0, 0, 0]], jnp.float32),
        jnp.asarray([show]), jnp.asarray([0.0]), cache.config,
    )
    dev_w = float(np.asarray(cache.state["embed_w"])[int(rows[0]), 0])

    # host-side reference math (delta, since init weight is random ±1e-4)
    scaled = g / show
    expect = -0.1 * scaled * np.sqrt(3.0 / 3.0)
    np.testing.assert_allclose(dev_w - w_before, expect, rtol=1e-4)
    g2 = float(np.asarray(cache.state["embed_state"])[int(rows[0]), 0])
    np.testing.assert_allclose(g2, scaled * scaled, rtol=1e-5)


def test_duplicate_rows_merge_like_reference():
    """Duplicate keys in a batch merge (sum) before one rule application —
    the cub merge_grad semantics."""
    table, cache = make_setup(embedx_threshold=100.0)
    keys = np.asarray([5], np.uint64)
    cache.begin_pass(keys)
    r = int(cache.lookup(keys)[0])
    w_before = float(np.asarray(cache.state["embed_w"])[r, 0])
    rows = jnp.asarray([r, r, r])
    grads = jnp.asarray([[0.1, 0, 0, 0, 0]] * 3, jnp.float32)
    st = cache_push(cache.state, rows, grads, jnp.ones(3), jnp.zeros(3), cache.config)
    # one merged update: g_sum=0.3, show_sum=3
    scaled = 0.3 / 3.0
    expect = -0.1 * scaled
    np.testing.assert_allclose(
        float(np.asarray(st["embed_w"])[r, 0]) - w_before, expect, rtol=1e-4
    )
    assert float(np.asarray(st["show"])[r]) == 3.0


def test_lazy_embedx_materializes_on_device():
    table, cache = make_setup(embedx_threshold=2.0)
    keys = np.asarray([9], np.uint64)
    cache.begin_pass(keys)
    r = int(cache.lookup(keys)[0])
    rows = jnp.asarray([r])
    # first push: score below threshold (show=1 → score=0.1)
    st = cache_push(cache.state, rows, jnp.ones((1, 5)) * 0.1,
                    jnp.ones(1), jnp.zeros(1), cache.config)
    assert float(np.asarray(st["has_embedx"])[r]) == 0.0
    # heavy clicks push it over (click_coeff=1)
    st2 = cache_push(st, rows, jnp.ones((1, 5)) * 0.1,
                     jnp.asarray([5.0]), jnp.asarray([5.0]), cache.config)
    assert float(np.asarray(st2["has_embedx"])[r]) == 1.0


def test_lookup_outside_pass_raises():
    table, cache = make_setup()
    cache.begin_pass(np.asarray([1, 2], np.uint64))
    with pytest.raises(Exception):
        cache.lookup(np.asarray([999], np.uint64))


def test_roundtrip_preserves_g2sum_across_passes():
    table, cache = make_setup(embedx_threshold=100.0)
    keys = np.asarray([11], np.uint64)
    cache.begin_pass(keys)
    rows = jnp.asarray(cache.lookup(keys))
    st = cache_push(cache.state, rows, jnp.asarray([[0.5, 0, 0, 0, 0]]),
                    jnp.ones(1), jnp.zeros(1), cache.config)
    g2_first = float(np.asarray(st["embed_state"])[int(rows[0]), 0])
    cache.state = st
    cache.end_pass()

    cache.begin_pass(keys)
    r2 = int(cache.lookup(keys)[0])
    g2_reloaded = float(np.asarray(cache.state["embed_state"])[r2, 0])
    np.testing.assert_allclose(g2_reloaded, g2_first, rtol=1e-6)
