"""SPMD DP/ZeRO trainer: multi-device parity with single-device training
(the reference's test_dist_base.py compares distributed losses against a
single-process run)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.executor import Trainer
from paddle_tpu.parallel import SpmdTrainer


def make_data(n=64, din=8, dout=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, din)).astype(np.float32)
    y = rng.integers(0, dout, n).astype(np.int32)
    return x, y


def fresh_model(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))


@pytest.mark.parametrize("zero_stage", [0, 1, 3])
def test_spmd_matches_single_device(zero_stage):
    x, y = make_data()
    mesh = mesh_mod.make_mesh({"dp": 2, "sharding": 4})

    single = Trainer(fresh_model(0), optimizer.SGD(0.1), nn.functional.cross_entropy)
    spmd = SpmdTrainer(
        fresh_model(0), optimizer.SGD(0.1), nn.functional.cross_entropy, mesh,
        zero_stage=zero_stage,
    )
    for i in range(5):
        l1 = single.train_step(jnp.asarray(x), jnp.asarray(y))
        l2 = spmd.train_step(jnp.asarray(x), jnp.asarray(y))
        np.testing.assert_allclose(float(l1), float(l2), rtol=2e-4)
    # final params agree
    p1 = single.state["params"]
    p2 = jax.device_get(spmd.state["params"])
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]), rtol=2e-3, atol=2e-5)


def test_zero_state_is_sharded():
    x, y = make_data()
    mesh = mesh_mod.make_mesh({"dp": 1, "sharding": 8})
    spmd = SpmdTrainer(
        fresh_model(0), optimizer.Adam(1e-2), nn.functional.cross_entropy, mesh,
        zero_stage=1,
    )
    spmd.train_step(jnp.asarray(x), jnp.asarray(y))
    # Adam m-slot for the 16x3 weight should be sharded over 'sharding'
    m_slot = spmd.opt_state["slots"]["m"]["0.weight"]
    shards = m_slot.sharding
    assert any("sharding" in (s or ()) for s in shards.spec), shards.spec


def test_zero3_params_sharded():
    mesh = mesh_mod.make_mesh({"dp": 1, "sharding": 8})
    spmd = SpmdTrainer(
        fresh_model(0), optimizer.SGD(0.1), nn.functional.cross_entropy, mesh,
        zero_stage=3,
    )
    w = spmd.state["params"]["0.weight"]
    assert any("sharding" in (s or ()) for s in w.sharding.spec), w.sharding.spec


def test_spmd_trainer_save_load_resume(tmp_path):
    """SpmdTrainer checkpoint/resume (ZeRO stage 1): the restored run
    continues the exact trajectory with state re-placed per the
    trainer's sharding rules."""
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer
    from paddle_tpu.core import mesh as mesh_mod
    from paddle_tpu.parallel.spmd import SpmdTrainer

    mesh = mesh_mod.make_mesh({"dp": 2, "sharding": 4})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=32).astype(np.int32)

    pt.seed(0)
    a = SpmdTrainer(nn.Linear(8, 4), optimizer.Adam(1e-2),
                    nn.functional.cross_entropy, mesh, zero_stage=1)
    for _ in range(3):
        a.train_step(x, y)
    a.save(str(tmp_path / "snap"))
    la = [float(a.train_step(x, y)) for _ in range(3)]

    pt.seed(5)
    b = SpmdTrainer(nn.Linear(8, 4), optimizer.Adam(1e-2),
                    nn.functional.cross_entropy, mesh, zero_stage=1)
    b.load(str(tmp_path / "snap"))
    assert b.global_step == 3
    lb = [float(b.train_step(x, y)) for _ in range(3)]
    np.testing.assert_allclose(lb, la, rtol=1e-5)
