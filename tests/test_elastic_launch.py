"""Elastic launcher loop (distributed/launch.py elastic_launch_local):
a trainer crashes mid-job, the supervisor's ElasticManager decides
RESTART, the world relaunches with the trainer count and endpoint env
REWRITTEN, and the survivor generation finishes the whole job from its
on-disk progress — manager.py:439-532 + the launcher restart path, on
one host."""

import os
import sys
import textwrap

from paddle_tpu.distributed.launch import JobSpec, elastic_launch_local

_TRAINER = textwrap.dedent("""
    import os, sys, time

    rank = int(os.environ["PADDLE_TRAINER_ID"])
    n = int(os.environ["PADDLE_TRAINERS_NUM"])
    eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert len(eps) == n, (eps, n)  # endpoint rewrite matches world size
    work = sys.argv[1]

    crash_marker = os.path.join(work, "crashed_once")
    if rank == 1 and not os.path.exists(crash_marker):
        open(crash_marker, "w").close()
        os._exit(17)  # simulated hard failure mid-job

    # resumable work: 10 items partitioned by rank; done-files are the
    # checkpoint (io/auto_checkpoint's role, minimal form)
    for item in range(10):
        if item % n == rank:
            p = os.path.join(work, f"item_{item}")
            if not os.path.exists(p):
                with open(p, "w") as f:
                    f.write(f"np={n}")
            time.sleep(0.05)
    """)


def test_elastic_launch_restarts_and_completes(tmp_path):
    script = tmp_path / "trainer.py"
    script.write_text(_TRAINER)
    work = tmp_path / "work"
    work.mkdir()

    rc = elastic_launch_local(
        JobSpec([str(script), str(work)], nproc=2),
        min_np=1, max_np=2, heartbeat_interval=0.1, heartbeat_ttl=0.5,
        elastic_timeout=0.5, timeout=60)
    assert rc == 0
    assert (work / "crashed_once").exists()
    done = sorted(p.name for p in work.glob("item_*"))
    assert len(done) == 10, done  # every item completed exactly once
    # the surviving generation ran with the REWRITTEN world size: the
    # dead rank's items carry np=1
    assert (work / "item_1").read_text() == "np=1"


def test_elastic_launch_gives_up_below_min_np(tmp_path):
    script = tmp_path / "always_crash.py"
    script.write_text("import os; os._exit(3)\n")
    rc = elastic_launch_local(
        JobSpec([str(script)], nproc=2),
        min_np=2, max_np=2, heartbeat_interval=0.1, heartbeat_ttl=0.4,
        elastic_timeout=0.4, max_restarts=2, timeout=60)
    assert rc != 0
