"""DeepFM / Wide&Deep over the HBM cache: the full GPUPS-style pass
(begin_pass → jitted pull/train/push steps → end_pass) learns a synthetic
CTR signal and flushes updated features back to the host table."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.metrics.auc import AUC
from paddle_tpu.models.ctr import CtrConfig, DeepFM, WideDeep, make_ctr_train_step
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

CFG = CtrConfig(num_sparse_slots=4, num_dense=3, embedx_dim=4,
                dnn_hidden=(16, 16))


def _synth(rng, n, cfg, vocab=64):
    """Synthetic CTR task: some feasigns are 'clicky'."""
    keys = rng.integers(0, vocab, size=(n, cfg.num_sparse_slots)).astype(np.uint64)
    # slot offset so the same id in different slots is a different feasign
    keys = keys + (np.arange(cfg.num_sparse_slots, dtype=np.uint64) << 32)
    dense = rng.normal(size=(n, cfg.num_dense)).astype(np.float32)
    clicky = (keys & np.uint64(0xFFFF)) % np.uint64(5) == 0
    score = clicky.sum(axis=1) + dense[:, 0]
    labels = (score + rng.normal(scale=0.5, size=n) > 1.0).astype(np.int32)
    return keys, dense, labels


@pytest.mark.parametrize("model_cls", [DeepFM, WideDeep])
def test_ctr_learns_and_flushes(model_cls):
    pt.seed(0)
    rng = np.random.default_rng(0)
    cache_cfg = CacheConfig(capacity=1024, embedx_dim=CFG.embedx_dim,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=CFG.embedx_dim)))
    cache = HbmEmbeddingCache(table, cache_cfg)

    model = model_cls(CFG)
    opt = optimizer.Adam(learning_rate=1e-2)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_ctr_train_step(model, opt, cache_cfg)

    keys, dense, labels = _synth(rng, 2048, CFG)
    cache.begin_pass(keys)
    B = 256
    auc_first = auc_last = None
    metric = AUC()
    for epoch in range(6):
        metric.reset()
        for i in range(0, len(keys), B):
            k = keys[i:i + B]
            rows = jnp.asarray(cache.lookup(k.reshape(-1)).reshape(k.shape))
            params, opt_state, cache.state, loss = step(
                params, opt_state, cache.state, rows,
                jnp.asarray(dense[i:i + B]), jnp.asarray(labels[i:i + B]))
        # evaluate on the training pass (signal check, not generalization)
        from paddle_tpu.ps.embedding_cache import cache_pull
        from paddle_tpu import nn
        for i in range(0, len(keys), B):
            k = keys[i:i + B]
            rows = jnp.asarray(cache.lookup(k.reshape(-1)).reshape(k.shape))
            emb = cache_pull(cache.state, rows.reshape(-1)).reshape(
                rows.shape[0], CFG.num_sparse_slots, -1)
            out, _ = nn.functional_call(model, params, emb,
                                        jnp.asarray(dense[i:i + B]),
                                        training=False)
            metric.update(np.asarray(nn.functional.sigmoid(out)),
                          labels[i:i + B])
        if auc_first is None:
            auc_first = metric.accumulate()
        auc_last = metric.accumulate()
    assert auc_last > 0.75, (auc_first, auc_last)
    assert auc_last > auc_first - 0.02

    # end_pass flushes learned weights back to the host table
    cache.end_pass()
    pulled = table.pull_sparse(np.unique(keys), create=False)
    assert np.abs(pulled[:, 2]).sum() > 0  # embed_w learned non-zero
