"""DeepFM / Wide&Deep over the HBM cache: the full GPUPS-style pass
(begin_pass → jitted pull/train/push steps → end_pass) learns a synthetic
CTR signal and flushes updated features back to the host table."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.metrics.auc import AUC
from paddle_tpu.models.ctr import (CtrConfig, DCN, DeepFM, WideDeep,
                                   XDeepFM, make_ctr_train_step)
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

CFG = CtrConfig(num_sparse_slots=4, num_dense=3, embedx_dim=4,
                dnn_hidden=(16, 16))


def _synth(rng, n, cfg, vocab=64):
    """Synthetic CTR task: some feasigns are 'clicky'."""
    keys = rng.integers(0, vocab, size=(n, cfg.num_sparse_slots)).astype(np.uint64)
    # slot offset so the same id in different slots is a different feasign
    keys = keys + (np.arange(cfg.num_sparse_slots, dtype=np.uint64) << 32)
    dense = rng.normal(size=(n, cfg.num_dense)).astype(np.float32)
    clicky = (keys & np.uint64(0xFFFF)) % np.uint64(5) == 0
    score = clicky.sum(axis=1) + dense[:, 0]
    labels = (score + rng.normal(scale=0.5, size=n) > 1.0).astype(np.int32)
    return keys, dense, labels


@pytest.mark.parametrize("model_cls", [DeepFM, WideDeep, DCN, XDeepFM])
def test_ctr_learns_and_flushes(model_cls):
    pt.seed(0)
    rng = np.random.default_rng(0)
    cache_cfg = CacheConfig(capacity=1024, embedx_dim=CFG.embedx_dim,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=CFG.embedx_dim)))
    cache = HbmEmbeddingCache(table, cache_cfg)

    model = model_cls(CFG)
    opt = optimizer.Adam(learning_rate=1e-2)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_ctr_train_step(model, opt, cache_cfg)

    keys, dense, labels = _synth(rng, 2048, CFG)
    cache.begin_pass(keys)
    B = 256
    auc_first = auc_last = None
    metric = AUC()
    for epoch in range(6):
        metric.reset()
        for i in range(0, len(keys), B):
            k = keys[i:i + B]
            rows = jnp.asarray(cache.lookup(k.reshape(-1)).reshape(k.shape))
            params, opt_state, cache.state, loss = step(
                params, opt_state, cache.state, rows,
                jnp.asarray(dense[i:i + B]), jnp.asarray(labels[i:i + B]))
        # evaluate on the training pass (signal check, not generalization)
        from paddle_tpu.ps.embedding_cache import cache_pull
        from paddle_tpu import nn
        for i in range(0, len(keys), B):
            k = keys[i:i + B]
            rows = jnp.asarray(cache.lookup(k.reshape(-1)).reshape(k.shape))
            emb = cache_pull(cache.state, rows.reshape(-1)).reshape(
                rows.shape[0], CFG.num_sparse_slots, -1)
            out, _ = nn.functional_call(model, params, emb,
                                        jnp.asarray(dense[i:i + B]),
                                        training=False)
            metric.update(np.asarray(nn.functional.sigmoid(out)),
                          labels[i:i + B])
        if auc_first is None:
            auc_first = metric.accumulate()
        auc_last = metric.accumulate()
    assert auc_last > 0.75, (auc_first, auc_last)
    assert auc_last > auc_first - 0.02

    # end_pass flushes learned weights back to the host table
    cache.end_pass()
    pulled = table.pull_sparse(np.unique(keys), create=False)
    assert np.abs(pulled[:, 2]).sum() > 0  # embed_w learned non-zero


def test_pooled_step_matches_single_valued(rng):
    """With every slot max_len=1 the pooled step must be bit-identical
    to make_ctr_train_step."""
    import jax.numpy as jnp

    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import (CtrConfig, DeepFM,
                                       make_ctr_pooled_train_step,
                                       make_ctr_train_step)
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    import paddle_tpu as pt

    S, dim, B = 5, 4, 32
    cfg = CtrConfig(num_sparse_slots=S, num_dense=3, embedx_dim=dim,
                    dnn_hidden=(16,))
    ccfg = CacheConfig(capacity=256, embedx_dim=dim, embedx_threshold=0.0)

    def build():
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=2, accessor_config=AccessorConfig(embedx_dim=dim)))
        cache = HbmEmbeddingCache(table, ccfg)
        cache.begin_pass(np.arange(1, 200, dtype=np.uint64))
        model = DeepFM(cfg)
        opt = optimizer.Adam(1e-2)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        return cache, model, opt, params, opt.init(params)

    keys = rng.integers(1, 200, size=(B, S)).astype(np.uint64)
    dense = rng.normal(size=(B, 3)).astype(np.float32)
    labels = (rng.random(B) < 0.4).astype(np.int32)

    cache1, m1, o1, p1, s1 = build()
    step1 = make_ctr_train_step(m1, o1, ccfg, donate=False)
    rows1 = jnp.asarray(cache1.lookup(keys.reshape(-1)).reshape(B, S))
    p1, s1, st1, l1 = step1(p1, s1, cache1.state, rows1, dense, labels)

    cache2, m2, o2, p2, s2 = build()
    step2 = make_ctr_pooled_train_step(m2, o2, ccfg, np.arange(S),
                                       donate=False)
    rows2 = jnp.asarray(cache2.lookup(keys.reshape(-1)).reshape(B, S))
    p2, s2, st2, l2 = step2(p2, s2, cache2.state, rows2, dense, labels)

    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-6)
    for k in st1:
        np.testing.assert_allclose(np.asarray(st2[k]), np.asarray(st1[k]),
                                   atol=1e-6, err_msg=k)


def test_pooled_step_variable_length_slots(rng):
    """Multi-valued slots: padded positions (sentinel rows) contribute
    nothing; real positions all receive the slot gradient; training
    learns."""
    import jax.numpy as jnp

    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import (CtrConfig, DeepFM,
                                       make_ctr_pooled_train_step)
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    import paddle_tpu as pt

    pt.seed(0)
    S, dim, B = 3, 4, 64
    max_lens = [2, 3, 1]          # slots 0..2, T = 6 padded key columns
    seg = np.repeat(np.arange(S), max_lens)
    cfg = CtrConfig(num_sparse_slots=S, num_dense=2, embedx_dim=dim,
                    dnn_hidden=(16,))
    ccfg = CacheConfig(capacity=512, embedx_dim=dim, embedx_threshold=0.0)
    C = ccfg.capacity
    table = MemorySparseTable(TableConfig(
        shard_num=2, accessor_config=AccessorConfig(embedx_dim=dim)))
    cache = HbmEmbeddingCache(table, ccfg)
    cache.begin_pass(np.arange(1, 300, dtype=np.uint64))
    model = DeepFM(cfg)
    opt = optimizer.Adam(1e-2)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    ostate = opt.init(params)
    step = make_ctr_pooled_train_step(model, opt, ccfg, seg, donate=False)

    losses = []
    # 160 iters: under jax 0.4.37 this trajectory plateaus near 0.69
    # until ~iter 130 and then drops hard to ~0.3 (measured); the
    # 40-iter bound was tuned on a version whose breakthrough came
    # earlier. Same signal, same endpoint — later knee.
    for it in range(160):
        T = len(seg)
        keys = rng.integers(1, 300, size=(B, T)).astype(np.uint64)
        rows = cache.lookup(keys.reshape(-1)).reshape(B, T)
        # random tail padding within each slot -> sentinel C
        lens = {s: rng.integers(1, ml + 1, size=B)
                for s, ml in enumerate(max_lens)}
        col = 0
        for s, ml in enumerate(max_lens):
            for j in range(ml):
                rows[lens[s] <= j, col] = C
                col += 1
        dense = rng.normal(size=(B, 2)).astype(np.float32)
        labels = (keys[:, 0] % 2).astype(np.int32)
        params, ostate, cache.state, loss = step(
            params, ostate, cache.state, jnp.asarray(rows), dense, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.05, (losses[0], losses[-1])
    # the padding invariant: sentinel pushes must NOT leak into rows
    # outside the pass working set — the pass allocated rows 0..298
    # (299 keys), so every later row's stats stay exactly zero
    st = cache.state
    shows = np.asarray(st["show"])
    assert shows[:299].max() > 0
    np.testing.assert_array_equal(shows[299:], 0.0)
    np.testing.assert_array_equal(np.asarray(st["embed_w"])[299:], 0.0)


def test_packed_step_matches_from_keys(rng):
    """Single-buffer packed wire format: bitwise-identical results to
    the three-array from-keys step (same dtypes both sides)."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import (CtrConfig, DeepFM, pack_ctr_batch,
                                       make_ctr_train_step_from_keys,
                                       make_ctr_train_step_packed)
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    S, D, B, dim = 6, 4, 32, 4
    ccfg = CacheConfig(capacity=512, embedx_dim=dim, embedx_threshold=0.0)

    def build():
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=2, accessor_config=AccessorConfig(embedx_dim=dim)))
        cache = HbmEmbeddingCache(table, ccfg, device_map=True)
        pool = rng2.integers(1, 1 << 18, size=(80, S)).astype(np.uint64)
        pool += np.arange(S, dtype=np.uint64) << np.uint64(32)
        cache.begin_pass(pool.reshape(-1))
        model = DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D,
                                 embedx_dim=dim, dnn_hidden=(16,)))
        opt = optimizer.Adam(1e-2)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        return cache, pool, model, opt, params, opt.init(params)

    rng2 = np.random.default_rng(7)
    cache1, pool, m1, o1, p1, s1 = build()
    rng2 = np.random.default_rng(7)
    cache2, _, m2, o2, p2, s2 = build()

    idx = rng.integers(0, 80, size=B)
    lo32 = (pool[idx] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    dense = rng.normal(size=(B, D)).astype(np.float16)
    labels = (rng.random(B) < 0.4).astype(np.int8)

    step_k = make_ctr_train_step_from_keys(m1, o1, ccfg,
                                           slot_ids=np.arange(S),
                                           donate=False)
    p1, s1, st1, l1 = step_k(p1, s1, cache1.state, cache1.device_map.state,
                             jnp.asarray(lo32), jnp.asarray(dense),
                             jnp.asarray(labels))

    step_p = make_ctr_train_step_packed(m2, o2, ccfg, np.arange(S), B, D,
                                        donate=False)
    packed = jnp.asarray(pack_ctr_batch(lo32, dense, labels))
    p2, s2, st2, l2 = step_p(p2, s2, cache2.state, cache2.device_map.state,
                             packed)

    np.testing.assert_array_equal(float(l2), float(l1))
    for k in st1:
        np.testing.assert_array_equal(np.asarray(st2[k]), np.asarray(st1[k]),
                                      err_msg=k)


def test_packed_wire_rejects_f16_overflow(rng):
    from paddle_tpu.models.ctr import pack_ctr_batch

    lo32 = rng.integers(0, 100, size=(4, 2)).astype(np.uint32)
    labels = np.zeros(4, np.int8)
    ok = rng.normal(size=(4, 3)).astype(np.float32)
    pack_ctr_batch(lo32, ok, labels)  # fine
    bad = ok.copy()
    bad[1, 2] = 1e6  # overflows f16
    with pytest.raises(Exception, match="f16 wire"):
        pack_ctr_batch(lo32, bad, labels)


def test_slab_step_matches_sequential_packed(rng):
    """The slab lax.scan (N steps per dispatch) walks a bitwise-identical
    trajectory to N sequential packed steps — the slab is a pure dispatch
    amortization, not a numerics change."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import (CtrConfig, DeepFM,
                                       make_ctr_train_step_packed,
                                       make_ctr_train_step_slab,
                                       make_random_packs)
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    S, D, B, dim, slab = 6, 4, 32, 4, 5
    ccfg = CacheConfig(capacity=512, embedx_dim=dim, embedx_threshold=0.0)

    def build():
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=2, accessor_config=AccessorConfig(embedx_dim=dim)))
        cache = HbmEmbeddingCache(table, ccfg, device_map=True)
        rng2 = np.random.default_rng(7)
        pool = rng2.integers(1, 1 << 18, size=(80, S)).astype(np.uint64)
        pool += np.arange(S, dtype=np.uint64) << np.uint64(32)
        cache.begin_pass(pool.reshape(-1))
        model = DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D,
                                 embedx_dim=dim, dnn_hidden=(16,)))
        opt = optimizer.Adam(1e-2)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        return cache, pool, model, opt, params, opt.init(params)

    cache1, pool, m1, o1, p1, s1 = build()
    cache2, _, m2, o2, p2, s2 = build()

    packs = make_random_packs(rng, pool, B, D, slab, p_click=0.4)

    step_p = make_ctr_train_step_packed(m1, o1, ccfg, np.arange(S), B, D,
                                        donate=False)
    losses1 = []
    st1 = cache1.state
    for pk in packs:
        p1, s1, st1, l1 = step_p(p1, s1, st1, cache1.device_map.state,
                                 jnp.asarray(pk))
        losses1.append(float(l1))

    step_s = make_ctr_train_step_slab(m2, o2, ccfg, np.arange(S), B, D,
                                      slab=slab, donate=False)
    p2, s2, st2, losses2 = step_s(p2, s2, cache2.state,
                                  cache2.device_map.state,
                                  jnp.asarray(np.stack(packs)))

    np.testing.assert_array_equal(np.asarray(losses2),
                                  np.asarray(losses1, np.float32))
    for k in st1:
        np.testing.assert_array_equal(np.asarray(st2[k]), np.asarray(st1[k]),
                                      err_msg=k)
