"""Fleet-executor actor runtime tests (reference
distributed/fleet_executor/test/: interceptor_ping_pong_test.cc,
compute_interceptor_test.cc, source_interceptor_test.cc,
sink_interceptor_test.cc patterns)."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet_executor import (
    AmplifierInterceptor,
    Carrier,
    ComputeInterceptor,
    FleetExecutor,
    InterceptorMessage,
    MessageBus,
    MessageType,
    SinkInterceptor,
    SourceInterceptor,
    TaskNode,
)


class TestMessageBus:
    def test_route_and_unknown(self):
        bus = MessageBus()
        q = bus.register(1)
        bus.send(InterceptorMessage(0, 1, MessageType.DATA_IS_READY, "x"))
        assert q.get_nowait().payload == "x"
        with pytest.raises(Exception):
            bus.send(InterceptorMessage(0, 99, MessageType.DATA_IS_READY))

    def test_duplicate_register(self):
        bus = MessageBus()
        bus.register(1)
        with pytest.raises(Exception):
            bus.register(1)


class TestPipeline:
    def test_source_compute_sink(self):
        # 0 → 1 (×2) → 2 (+1) → 3, 8 microbatches
        nodes = [
            TaskNode(0, role="source", max_run_times=8, downstreams=[(1, 2)]),
            TaskNode(1, fn=lambda x: x * 2, max_run_times=8,
                     upstreams=[0], downstreams=[(2, 2)]),
            TaskNode(2, fn=lambda x: x + 1, max_run_times=8,
                     upstreams=[1], downstreams=[(3, 2)]),
            TaskNode(3, role="sink", max_run_times=8, upstreams=[2]),
        ]
        feeds = {0: list(range(8))}
        outs = FleetExecutor(nodes).run(feeds, timeout=30.0)
        assert outs[3] == [i * 2 + 1 for i in range(8)]

    def test_credit_bounds_in_flight(self):
        """buffer_size=1 on a slow consumer: the fast producer can never
        be more than 1 microbatch ahead (compute_interceptor.cc credit
        accounting)."""
        in_flight = []
        lock = threading.Lock()
        outstanding = {"n": 0, "max": 0}

        def produce(x):
            with lock:
                outstanding["n"] += 1
                outstanding["max"] = max(outstanding["max"], outstanding["n"])
            return x

        def consume(x):
            time.sleep(0.01)
            with lock:
                outstanding["n"] -= 1
            return x

        nodes = [
            TaskNode(0, role="source", fn=produce, max_run_times=6,
                     downstreams=[(1, 1)]),
            TaskNode(1, fn=consume, max_run_times=6, upstreams=[0],
                     downstreams=[(2, 1)]),
            TaskNode(2, role="sink", max_run_times=6, upstreams=[1]),
        ]
        outs = FleetExecutor(nodes).run({0: list(range(6))}, timeout=30.0)
        assert outs[2] == list(range(6))
        # credit window 1 on edge 0→1 plus one being consumed
        assert outstanding["max"] <= 2

    def test_fan_in_two_upstreams(self):
        nodes = [
            TaskNode(0, role="source", max_run_times=4, downstreams=[(2, 2)]),
            TaskNode(1, role="source", max_run_times=4, downstreams=[(2, 2)]),
            TaskNode(2, fn=lambda a, b: a + b, max_run_times=4,
                     upstreams=[0, 1], downstreams=[(3, 2)]),
            TaskNode(3, role="sink", max_run_times=4, upstreams=[2]),
        ]
        outs = FleetExecutor(nodes).run(
            {0: [1, 2, 3, 4], 1: [10, 20, 30, 40]}, timeout=30.0)
        assert outs[3] == [11, 22, 33, 44]

    def test_amplifier_accumulates(self):
        """period=4: gradient-merge-like window — sink sees 2 outputs,
        each the sum of 4 microbatches (amplifier_interceptor.cc
        run_per_steps semantics)."""
        nodes = [
            TaskNode(0, role="source", max_run_times=8, downstreams=[(1, 8)]),
            TaskNode(1, fn=lambda xs: sum(xs), role="amplifier", period=4,
                     max_run_times=8, upstreams=[0], downstreams=[(2, 2)]),
            TaskNode(2, role="sink", max_run_times=2, upstreams=[1]),
        ]
        outs = FleetExecutor(nodes).run({0: list(range(8))}, timeout=30.0)
        assert outs[2] == [0 + 1 + 2 + 3, 4 + 5 + 6 + 7]

    def test_amplifier_partial_window_rejected(self):
        nodes = [
            TaskNode(0, role="source", max_run_times=6, downstreams=[(1, 6)]),
            TaskNode(1, role="amplifier", period=4, max_run_times=6,
                     upstreams=[0], downstreams=[(2, 2)]),
            TaskNode(2, role="sink", max_run_times=1, upstreams=[1]),
        ]
        with pytest.raises(Exception, match="multiple of period"):
            FleetExecutor(nodes).run({0: list(range(6))}, timeout=5.0)

    def test_jitted_section_per_microbatch(self):
        """ComputeInterceptor driving a compiled TPU/CPU section — the
        actual heter-pipeline use."""
        import jax
        import jax.numpy as jnp

        section = jax.jit(lambda x: jnp.sum(x * 2.0))
        nodes = [
            TaskNode(0, role="source", max_run_times=3, downstreams=[(1, 2)]),
            TaskNode(1, fn=lambda x: float(section(jnp.asarray(x))),
                     max_run_times=3, upstreams=[0], downstreams=[(2, 2)]),
            TaskNode(2, role="sink", max_run_times=3, upstreams=[1]),
        ]
        feeds = {0: [np.ones(4, np.float32) * i for i in range(3)]}
        outs = FleetExecutor(nodes).run(feeds, timeout=60.0)
        assert outs[2] == [0.0, 8.0, 16.0]

    def test_timeout_raises(self):
        # sink expects 4 but source only feeds 2
        nodes = [
            TaskNode(0, role="source", max_run_times=2, downstreams=[(1, 2)]),
            TaskNode(1, fn=lambda x: x, max_run_times=4, upstreams=[0],
                     downstreams=[(2, 2)]),
            TaskNode(2, role="sink", max_run_times=4, upstreams=[1]),
        ]
        with pytest.raises(Exception):
            FleetExecutor(nodes).run({0: [0, 1]}, timeout=1.0)

    def test_error_propagates(self):
        def boom(x):
            raise ValueError("boom")

        nodes = [
            TaskNode(0, role="source", max_run_times=1, downstreams=[(1, 1)]),
            TaskNode(1, fn=boom, max_run_times=1, upstreams=[0],
                     downstreams=[(2, 1)]),
            TaskNode(2, role="sink", max_run_times=1, upstreams=[1]),
        ]
        t0 = time.monotonic()
        with pytest.raises(ValueError, match="boom"):
            FleetExecutor(nodes).run({0: [1]}, timeout=30.0)
        # the stage error surfaces promptly, not as a timeout
        assert time.monotonic() - t0 < 5.0

    def test_duplicate_task_ids(self):
        with pytest.raises(Exception):
            FleetExecutor([TaskNode(0, role="source"), TaskNode(0, role="sink")])
