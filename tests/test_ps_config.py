"""PaddleRec YAML config derivation (ps/config.py) — the reference's
test_the_one_ps config-diff pattern: load each sync_mode's config and
assert the derived strategy/table/model/trainer WITHOUT running a job.
"""

import numpy as np
import pytest

from paddle_tpu.core.enforce import InvalidArgumentError
from paddle_tpu.ps.config import load_ps_config

_BASE = """
hyper_parameters:
  optimizer:
    class: Adam
    learning_rate: 0.0001
  sparse_inputs_slots: 27
  sparse_feature_number: 1000001
  sparse_feature_dim: 10
  dense_input_dim: 13
  fc_sizes: [400, 400, 400]

runner:
  sync_mode: "{mode}"
  thread_num: 16
{extra}"""


def _load(tmp_path, mode, extra=""):
    p = tmp_path / f"{mode}.yaml"
    p.write_text(_BASE.format(mode=mode, extra=extra))
    return load_ps_config(str(p))


def test_async_config(tmp_path):
    job = _load(tmp_path, "async")
    assert job.strategy.a_sync and not job.strategy.geo_sgd_mode
    assert job.trainer == "CtrStreamTrainer"
    assert job.num_sparse_slots == 26          # label slot excluded
    assert job.table.accessor_config.embedx_dim == 9  # feature_dim - 1
    assert job.table.shard_num == 16
    assert job.fc_sizes == (400, 400, 400)
    cfg = job.make_model_config()
    assert cfg.num_sparse_slots == 26 and cfg.num_dense == 13
    assert cfg.embedx_dim == 9
    opt = job.make_optimizer()
    assert type(opt).__name__ == "Adam"


def test_sync_config(tmp_path):
    job = _load(tmp_path, "sync")
    assert not job.strategy.a_sync
    assert job.strategy.is_sync_mode
    assert job.trainer == "CtrStreamTrainer"


def test_geo_config(tmp_path):
    job = _load(tmp_path, "geo", extra="  geo_step: 400\n")
    assert job.strategy.a_sync and job.strategy.geo_sgd_mode
    assert job.strategy.geo_configs["geo_step"] == 400


def test_gpubox_selects_pass_path(tmp_path):
    job = _load(tmp_path, "gpubox")
    assert job.strategy.a_sync_configs.get("use_ps_gpu") == 1
    assert job.trainer == "CtrPassTrainer"


def test_heter_selects_pass_path(tmp_path):
    job = _load(tmp_path, "heter")
    assert job.trainer == "CtrPassTrainer"
    assert "heter_worker_device_guard" in job.strategy.a_sync_configs


def test_bad_mode_rejected(tmp_path):
    with pytest.raises(InvalidArgumentError, match="sync_mode"):
        _load(tmp_path, "bogus")


def test_dict_source_and_job_runs_one_pass(tmp_path):
    """Beyond config-diff: the derived objects actually train one tiny
    pass end-to-end through the selected (gpubox → pass) path."""
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models.ctr import DeepFM, make_ctr_train_step_from_keys
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
    from paddle_tpu.ps.table import MemorySparseTable

    job = load_ps_config({
        "hyper_parameters": {
            "optimizer": {"class": "Adam", "learning_rate": 0.001},
            "sparse_inputs_slots": 7, "sparse_feature_number": 4096,
            "sparse_feature_dim": 5, "dense_input_dim": 4,
            "fc_sizes": [16],
        },
        "runner": {"sync_mode": "gpubox", "thread_num": 4},
    })
    assert job.trainer == "CtrPassTrainer"
    pt.seed(0)
    cfg = job.make_model_config()
    table = MemorySparseTable(job.table)
    ccfg = CacheConfig(capacity=1 << 12, embedx_dim=cfg.embedx_dim,
                       embedx_threshold=0.0)
    cache = HbmEmbeddingCache(table, ccfg, device_map=True)
    rng = np.random.default_rng(0)
    S = cfg.num_sparse_slots
    pool = (rng.integers(1, 1 << 16, size=(50, S)).astype(np.uint64)
            + (np.arange(S, dtype=np.uint64) << np.uint64(32)))
    cache.begin_pass(pool.reshape(-1))
    model = DeepFM(cfg)
    opt = job.make_optimizer()
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    step = make_ctr_train_step_from_keys(model, opt, ccfg,
                                         slot_ids=np.arange(S))
    idx = rng.integers(0, 50, size=16)
    lo32 = jnp.asarray((pool[idx] & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    dense = jnp.asarray(rng.normal(size=(16, cfg.num_dense)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 2, 16), jnp.int32)
    _, _, cache.state, loss = step(params, opt.init(params), cache.state,
                                   cache.device_map.state, lo32, dense,
                                   labels)
    assert np.isfinite(float(loss))
    cache.end_pass()


def test_yaml_null_blocks_handled(tmp_path):
    p = tmp_path / "null.yaml"
    p.write_text("hyper_parameters:\n")
    with pytest.raises(Exception, match="hyper_parameters"):
        load_ps_config(str(p))
    job = load_ps_config({"hyper_parameters": {"fc_sizes": None},
                          "runner": {"sync_mode": "async"}})
    assert job.fc_sizes == (400, 400, 400)


def test_null_scalars_and_lowercase_optimizer(tmp_path):
    p = tmp_path / "nulls.yaml"
    p.write_text(
        "hyper_parameters:\n"
        "  optimizer:\n"
        "    class: adam\n"
        "    learning_rate:\n"
        "  sparse_inputs_slots:\n"
        "  sparse_feature_dim: 10\n"
        "runner:\n"
        "  sync_mode: async\n"
        "  thread_num:\n")
    job = load_ps_config(str(p))
    assert job.num_sparse_slots == 26      # default despite explicit null
    assert job.thread_num == 16
    assert job.learning_rate == 1e-3
    assert type(job.make_optimizer()).__name__ == "Adam"  # lowercase ok
