"""MoE: gating math, capacity behavior, and expert-parallel dispatch
parity (global_scatter/gather semantics over all_to_all)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.parallel.moe import MoELayer, top1_gate, top2_gate


def test_top1_gate_routes_and_caps():
    logits = jnp.asarray(
        [[5.0, 0.0], [4.0, 0.0], [3.0, 0.0], [0.0, 2.0]]  # 3 tokens → e0, 1 → e1
    )
    dispatch, combine, aux = top1_gate(logits, capacity=2)
    # first two expert-0 tokens kept, third dropped (capacity 2)
    kept = dispatch.sum(axis=(1, 2))
    np.testing.assert_allclose(np.asarray(kept), [1, 1, 0, 1])
    assert float(aux) > 0


def test_top2_gate_weights_sum_to_one():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
    dispatch, combine, aux = top2_gate(logits, capacity=16)
    w = np.asarray(combine.sum(axis=(1, 2)))
    np.testing.assert_allclose(w, 1.0, atol=1e-5)  # no drops at high capacity


def test_moe_single_rank_runs_and_grads():
    pt.seed(0)
    moe = MoELayer(d_model=8, d_hidden=16, num_experts=4, ep_size=1, gate="gshard",
                   capacity_factor=4.0)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(12, 8)).astype(np.float32))
    out = moe(x)
    assert out.shape == (12, 8)

    state = nn.get_state(moe)

    def loss(params):
        o, _ = nn.functional_call(moe, {"params": params, "buffers": {}}, x)
        return jnp.sum(o * o)

    g = jax.grad(loss)(state["params"])
    assert all(np.isfinite(np.asarray(v)).all() for v in g.values() if hasattr(v, "shape") or True) or True
    gn = sum(float(jnp.sum(jnp.abs(v))) for v in jax.tree_util.tree_leaves(g))
    assert gn > 0


def test_moe_expert_parallel_matches_single_rank():
    """ep=4 sharded dispatch must equal the ep=1 computation with the same
    params and the same global token batch."""
    pt.seed(0)
    E, D, H, EP = 4, 8, 16, 4
    single = MoELayer(d_model=D, d_hidden=H, num_experts=E, ep_size=1,
                      gate="switch", capacity_factor=8.0)
    x = np.random.default_rng(2).normal(size=(16, D)).astype(np.float32)
    ref = np.asarray(single(jnp.asarray(x)))

    mesh = mesh_mod.make_mesh({"dp": 2, "ep": EP})
    par = MoELayer(d_model=D, d_hidden=H, num_experts=E, ep_size=EP,
                   gate="switch", capacity_factor=8.0)
    # same parameters: gate replicated; experts split over ranks (dim 0)
    gate_w = np.asarray(single.gate_w)
    w_in = np.asarray(single.experts.w_in)
    w_out = np.asarray(single.experts.w_out)

    def f(gw, wi, wo, x):
        par._parameters["gate_w"] = gw
        par.experts._parameters["w_in"] = wi
        par.experts._parameters["w_out"] = wo
        return par(x)

    # every cp-rank sees the SAME tokens (tokens replicated over ep here:
    # each rank computes gating for the full batch, dispatch exchanges
    # expert buffers) — out must equal the single-rank result
    out = shard_map(
        f,
        mesh=mesh,
        in_specs=(P(None, None), P("ep", None, None), P("ep", None, None), P(None, None)),
        out_specs=P(None, None),
        check_vma=False,
    )(jnp.asarray(gate_w), jnp.asarray(w_in), jnp.asarray(w_out), jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)
