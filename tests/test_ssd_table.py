"""SsdSparseTable: two-tier (RAM + disk log) table semantics.

Covers the tier protocol (promote-on-access, spill), crash recovery by
log replay, two-tier shrink/save, compaction, and drop-in use under the
HBM embedding cache. Reference lineage: the rocksdb SSD-table direction
scaffolded at ps/table/depends/rocksdb_warpper.h (SURVEY §2.2).
"""

import numpy as np
import pytest

from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.native import native_available
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, SsdSparseTable, TableConfig

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library unavailable")


def _acc(**kw):
    kw.setdefault("sgd", SGDRuleConfig(initial_range=0.0))
    kw.setdefault("embedx_dim", 4)
    kw.setdefault("embedx_threshold", 0.0)
    return AccessorConfig(**kw)


def _cfg(**kw):
    kw.setdefault("shard_num", 4)
    kw.setdefault("accessor_config", _acc())
    return TableConfig(**kw)


def _push_batch(table, rng, n=200, key_hi=1000):
    keys = rng.integers(1, key_hi, size=n).astype(np.uint64)
    push = np.zeros((n, table.accessor.push_dim), np.float32)
    push[:, 0] = (keys % 8).astype(np.float32)          # slot
    push[:, 1] = 1.0                                    # show
    push[:, 2] = (rng.random(n) < 0.3).astype(np.float32)  # click
    push[:, 3:] = rng.normal(size=(n, push.shape[1] - 3)).astype(np.float32)
    table.push_sparse(keys, push)
    return keys


def test_parity_with_memory_table(tmp_path):
    rng1, rng2 = np.random.default_rng(3), np.random.default_rng(3)
    mem = MemorySparseTable(_cfg())
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg())
    for _ in range(5):
        _push_batch(mem, rng1)
        _push_batch(ssd, rng2)
    probe = np.arange(1, 1000, dtype=np.uint64)
    np.testing.assert_allclose(
        ssd.pull_sparse(probe, create=False),
        mem.pull_sparse(probe, create=False), atol=1e-6)
    assert ssd.size() == mem.size()


def test_spill_promote_roundtrip(tmp_path):
    rng = np.random.default_rng(4)
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg())
    keys = _push_batch(ssd, rng, n=400, key_hi=500)
    before = ssd.pull_sparse(np.unique(keys), create=False)
    total = ssd.size()

    spilled = ssd.spill(hot_budget=total // 4)
    st = ssd.stats()
    assert spilled > 0 and st["cold_rows"] == spilled
    assert st["hot_rows"] + st["cold_rows"] == total

    # pulls see identical values regardless of tier; access promotes
    after = ssd.pull_sparse(np.unique(keys), create=False)
    np.testing.assert_allclose(after, before, atol=1e-6)
    st2 = ssd.stats()
    assert st2["cold_rows"] == 0 and st2["hot_rows"] == total


def test_push_into_cold_rows_promotes(tmp_path):
    """Pushing to a spilled key must promote it and apply the gradient
    exactly as a hot push would (mirror against a RAM table)."""
    rng1, rng2 = np.random.default_rng(5), np.random.default_rng(5)
    mem = MemorySparseTable(_cfg())
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg())
    _push_batch(mem, rng1, n=300, key_hi=300)
    _push_batch(ssd, rng2, n=300, key_hi=300)
    ssd.spill(hot_budget=0)  # everything cold
    assert ssd.stats()["hot_rows"] == 0
    _push_batch(mem, rng1, n=300, key_hi=300)
    _push_batch(ssd, rng2, n=300, key_hi=300)
    probe = np.arange(1, 300, dtype=np.uint64)
    np.testing.assert_allclose(
        ssd.pull_sparse(probe, create=False),
        mem.pull_sparse(probe, create=False), atol=1e-6)


def test_log_replay_recovery(tmp_path):
    """Rows on disk survive process restart (reopen replays the logs);
    hot-tier rows are volatile unless spilled or saved — spill all, then
    reopen and compare."""
    rng = np.random.default_rng(6)
    path = str(tmp_path / "t")
    ssd = SsdSparseTable(path, _cfg())
    keys = np.unique(_push_batch(ssd, rng, n=500, key_hi=800))
    want = ssd.pull_sparse(keys, create=False)
    ssd.spill(hot_budget=0)
    ssd.flush()
    ssd.close()

    back = SsdSparseTable(path, _cfg())
    st = back.stats()
    assert st["hot_rows"] == 0 and st["cold_rows"] == len(keys)
    np.testing.assert_allclose(back.pull_sparse(keys, create=False), want,
                               atol=1e-6)


def test_two_tier_shrink_matches_memory_table(tmp_path):
    """shrink() applies decay + delete on BOTH tiers; mirror a RAM table
    (same pushes, same shrink count and post-state)."""
    cfg_kw = dict(accessor_config=_acc(delete_threshold=0.5,
                                       show_click_decay_rate=0.5))
    rng1, rng2 = np.random.default_rng(7), np.random.default_rng(7)
    mem = MemorySparseTable(_cfg(**cfg_kw))
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg(**cfg_kw))
    _push_batch(mem, rng1, n=300, key_hi=400)
    _push_batch(ssd, rng2, n=300, key_hi=400)
    ssd.spill(hot_budget=ssd.size() // 2)  # half cold, half hot
    e_mem = mem.shrink()
    e_ssd = ssd.shrink()
    assert e_ssd == e_mem
    assert ssd.size() == mem.size()
    probe = np.arange(1, 400, dtype=np.uint64)
    np.testing.assert_allclose(
        ssd.pull_sparse(probe, create=False),
        mem.pull_sparse(probe, create=False), atol=1e-6)


@pytest.mark.parametrize("mode", [0, 1, 2, 3])
def test_save_modes_match_memory_table(tmp_path, mode):
    cfg_kw = dict(accessor_config=_acc(base_threshold=1.0,
                                       delta_threshold=0.1))
    rng1, rng2 = np.random.default_rng(8), np.random.default_rng(8)
    mem = MemorySparseTable(_cfg(**cfg_kw))
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg(**cfg_kw))
    _push_batch(mem, rng1, n=250, key_hi=300)
    _push_batch(ssd, rng2, n=250, key_hi=300)
    ssd.spill(hot_budget=ssd.size() // 3)

    k1, v1 = mem._native.save_items(mode)
    k2, v2 = ssd._native.save_items(mode)
    o1, o2 = np.argsort(k1), np.argsort(k2)
    np.testing.assert_array_equal(k1[o1], k2[o2])
    np.testing.assert_allclose(v1[o1], v2[o2], atol=1e-6)


def test_load_cold_and_compaction(tmp_path):
    rng = np.random.default_rng(9)
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg())
    n = 1000
    keys = np.arange(1, n + 1, dtype=np.uint64)
    values = np.zeros((n, ssd.full_dim), np.float32)
    values[:, 3] = 5.0  # show
    values[:, 5] = rng.normal(size=n).astype(np.float32)  # embed_w
    ssd.load_cold(keys, values)
    st = ssd.stats()
    assert st["cold_rows"] == n and st["hot_rows"] == 0
    got = ssd.pull_sparse(keys[:10], create=False)
    np.testing.assert_allclose(got[:, 2], values[:10, 5], atol=1e-6)

    # churn: repeated spill/promote grows the log; compaction shrinks it
    for _ in range(6):
        ssd.pull_sparse(keys, create=False)   # promote all
        ssd.spill(hot_budget=0)               # spill all (appends)
    grown = ssd.stats()["disk_bytes"]
    ssd.compact()
    shrunk = ssd.stats()["disk_bytes"]
    assert shrunk < grown
    np.testing.assert_allclose(
        ssd.pull_sparse(keys[:10], create=False)[:, 2], values[:10, 5],
        atol=1e-6)


@pytest.mark.parametrize("fmt", ["text", "gzip", "raw"])
def test_streaming_save_file_roundtrip(tmp_path, fmt):
    """SsdSparseTable.save_file/load_file — the streaming single-file
    path (nothing staged in RAM) in all three formats; values land in
    the cold tier and pull back exactly (raw is bit-exact; text within
    %.8g)."""
    rng = np.random.default_rng(4)
    t = SsdSparseTable(str(tmp_path / "a"), _cfg())
    keys = _push_batch(t, rng, n=400, key_hi=5000)
    keys = np.unique(keys)
    want = t.pull_sparse(keys, create=False)
    path = str(tmp_path / f"ck.{fmt}")
    n = t.save_file(path, mode=0, fmt=fmt)
    assert n == t.size()
    t.close()

    t2 = SsdSparseTable(str(tmp_path / "b"), _cfg())
    assert t2.load_file(path, fmt=fmt) == n
    st = t2.stats()
    assert st["cold_rows"] == n and st["hot_rows"] == 0
    got = t2.pull_sparse(keys, create=False)
    if fmt == "raw":
        np.testing.assert_array_equal(got, want)
    else:
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-9)
    # wrong-format reads: a text/gzip file fed to the raw loader is
    # rejected at the header (loud); the reverse (raw fed to the text
    # parser) skips unparseable bytes and loads nothing — count 0, not
    # silent garbage rows
    if fmt != "raw":
        with pytest.raises(RuntimeError):
            t2.load_file(path, fmt="raw")
    else:
        assert t2.load_file(path, fmt="gzip") == 0
    t2.close()


def test_streaming_load_survives_corruption(tmp_path):
    """Disk corruption happens at 1e9-row scale: a truncated raw file
    loads the intact prefix records (no crash, count honest); a text
    file with garbage lines skips them and loads the parseable rest; a
    truncated gzip stream loads what decompressed cleanly."""
    import gzip as _gzip

    rng = np.random.default_rng(6)
    t = SsdSparseTable(str(tmp_path / "a"), _cfg())
    _push_batch(t, rng, n=300, key_hi=4000)
    n = t.size()
    raw = str(tmp_path / "ck.bin")
    gz = str(tmp_path / "ck.gz")
    assert t.save_file(raw, fmt="raw") == n
    assert t.save_file(gz, fmt="gzip") == n
    t.close()

    # truncated raw: drop the trailing half-record + a few rows
    data = open(raw, "rb").read()
    rec = 8 + 4 * 13  # full_dim 13 with the default _cfg accessor
    cut = 16 + rec * (n // 2) + rec // 3   # header + half the rows + torn rec
    open(raw, "wb").write(data[:cut])
    t2 = SsdSparseTable(str(tmp_path / "b"), _cfg())
    assert t2.load_file(raw, fmt="raw") == n // 2
    t2.close()

    # garbage lines interleaved in text: parseable rows still load
    lines = _gzip.open(gz, "rt").readlines()
    lines.insert(1, "not a row at all\n")
    lines.insert(5, "12 nan nan\n")   # short head: skipped
    with _gzip.open(str(tmp_path / "ck2.gz"), "wt") as f:
        f.writelines(lines)
    t3 = SsdSparseTable(str(tmp_path / "c"), _cfg())
    loaded = t3.load_file(str(tmp_path / "ck2.gz"), fmt="gzip")
    assert loaded == n  # both junk lines skipped, every real row kept
    t3.close()

    # truncated gzip stream: the cleanly-decompressed prefix loads
    blob = open(gz, "rb").read()
    open(str(tmp_path / "ck3.gz"), "wb").write(blob[: len(blob) // 2])
    t4 = SsdSparseTable(str(tmp_path / "d"), _cfg())
    got = t4.load_file(str(tmp_path / "ck3.gz"), fmt="gzip")
    assert 0 <= got < n
    t4.close()


@pytest.mark.slow
def test_hash_order_reload_not_quadratic(tmp_path):
    """Round-5 regression (found at 0.66e9 rows): a checkpoint emits
    rows in the SAVER index's hash order; re-inserting keys in home-slot
    order into an UNSALTED linear-probing index is quadratic — the
    occupied slots form one solid run and every insert probes to its end
    (the restore at scale "hung" at ~10M rows/shard with zero IO). The
    per-instance hash salt (pstpu::next_hash_salt) decorrelates saver
    and loader home orders; this drives save_file→load_file at a
    single-shard scale where the unsalted engine takes tens of minutes
    and asserts it completes in bounded time with exact row counts."""
    import ctypes
    import time

    from paddle_tpu.ps.native import load_native

    lib = load_native()
    lib.sst_save_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int32, ctypes.c_int32]
    lib.sst_save_file.restype = ctypes.c_int64
    lib.sst_load_file.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int32]
    lib.sst_load_file.restype = ctypes.c_int64

    n = 8_000_000
    # shard_num=1 concentrates every key in ONE index — the earliest
    # onset of the pathology (matches the production single-residue
    # concentration)
    t = SsdSparseTable(str(tmp_path / "a"), _cfg(shard_num=1))
    fd = t.full_dim
    wave = 1 << 21
    for lo in range(0, n, wave):
        m = min(wave, n - lo)
        keys = (np.arange(m, dtype=np.uint64) + lo + 1)
        vals = np.zeros((m, fd), np.float32)
        vals[:, 3] = 1.0
        vals[:, 5] = 0.01
        t.load_cold(keys, vals)
    ck = str(tmp_path / "part.shard.gz")
    saved = lib.sst_save_file(t._native._h, ck.encode(), 0, 1)
    assert saved == n
    t.close()

    t2 = SsdSparseTable(str(tmp_path / "b"), _cfg(shard_num=1))
    t0 = time.perf_counter()
    got = lib.sst_load_file(t2._native._h, ck.encode(), 1)
    dt = time.perf_counter() - t0
    assert got == n
    # salted: ~20-30s even on the busy 1-core host; unsalted: >10 min
    assert dt < 240, f"hash-order reload took {dt:.0f}s — quadratic again?"
    # spot parity through the full pull path
    rng = np.random.default_rng(0)
    sample = rng.choice(np.arange(1, n + 1, dtype=np.uint64), 200,
                        replace=False)
    vals, found = t2.export_full(sample)
    assert found.all()
    np.testing.assert_allclose(vals[:, 3], 1.0)
    t2.close()


def test_cache_pass_over_ssd_table(tmp_path):
    """HbmEmbeddingCache works unchanged over the SSD table: begin_pass
    promotes/creates, end_pass flushes back hot."""
    from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache

    rng = np.random.default_rng(10)
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg())
    seed_keys = np.unique(_push_batch(ssd, rng, n=200, key_hi=250))
    ssd.spill(hot_budget=0)  # population starts cold

    cache = HbmEmbeddingCache(ssd, CacheConfig(
        capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0))
    pass_keys = np.arange(1, 400, dtype=np.uint64)  # cold + brand-new keys
    cache.begin_pass(pass_keys)
    rows = cache.lookup(seed_keys)
    from paddle_tpu.ps.embedding_cache import cache_pull

    pulled = np.asarray(cache_pull(cache.state, rows))
    want = ssd.pull_sparse(seed_keys, create=False)[:, -pulled.shape[1]:]
    np.testing.assert_allclose(pulled, want, atol=1e-5)
    cache.end_pass()
    assert ssd.size() >= len(pass_keys)


def test_save_load_roundtrip_lands_cold(tmp_path):
    """save() -> load() roundtrip restores into the DISK tier (a
    larger-than-RAM population must not be rehydrated into RAM)."""
    rng = np.random.default_rng(12)
    ssd = SsdSparseTable(str(tmp_path / "a"), _cfg())
    keys = np.unique(_push_batch(ssd, rng, n=300, key_hi=400))
    want = ssd.pull_sparse(keys, create=False)
    n = ssd.save(str(tmp_path / "ckpt"), mode=0)
    assert n == len(keys)

    fresh = SsdSparseTable(str(tmp_path / "b"), _cfg())
    assert fresh.load(str(tmp_path / "ckpt")) == n
    st = fresh.stats()
    assert st["hot_rows"] == 0 and st["cold_rows"] == n
    np.testing.assert_allclose(fresh.pull_sparse(keys, create=False), want,
                               atol=1e-6)


def test_repeated_mode3_saves_bounded_disk(tmp_path):
    """Daily batch saves (mode 3) rewrite every cold row; compaction in
    the save path must keep disk growth bounded."""
    rng = np.random.default_rng(13)
    ssd = SsdSparseTable(str(tmp_path / "t"), _cfg())
    _push_batch(ssd, rng, n=2000, key_hi=3000)
    ssd.spill(hot_budget=0)
    live = ssd.stats()["cold_rows"]
    rec_bytes = 8 + 4 + 4 * ssd.full_dim
    for _ in range(12):
        ssd._native.save_items(mode=3)
    # bound: compaction threshold is 4x live data
    assert ssd.stats()["disk_bytes"] <= 5 * live * rec_bytes


@pytest.mark.slow
def test_pass_trainer_over_ssd_table(tmp_path, rng):
    """CtrPassTrainer (PSGPUTrainer role) runs unchanged over the SSD
    table via the make_sparse_table factory, with spill between passes —
    the GPUPS + SSD tier composition (multi-day stream over a population
    larger than the hot budget)."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.embedding_cache import CacheConfig
    from paddle_tpu.ps.ps_trainer import CtrPassTrainer
    from paddle_tpu.ps.table import make_sparse_table

    S, D = 4, 3
    pt.seed(0)
    lines = []
    for _ in range(1024):
        ids = rng.integers(0, 64, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        parts = [f"1 {v}" for v in ids] + [f"1 {v:.4f}" for v in dense]
        parts.append(f"1 {label}")
        lines.append(" ".join(parts))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)

    table = make_sparse_table(_cfg(storage="ssd",
                                   ssd_path=str(tmp_path / "tbl")))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16, 16))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")

    losses = [tr.train_from_dataset(ds, batch_size=256)["loss"]]
    table.spill(hot_budget=0)  # age the whole population to disk
    assert table.stats()["hot_rows"] == 0
    for _ in range(3):  # later passes promote from disk and keep learning
        losses.append(tr.train_from_dataset(ds, batch_size=256)["loss"])
    assert losses[-1] < losses[0] * 0.95, losses
    assert table.size() > 0


# ---------------------------------------------------------------------------
# fp16 record format (TableConfig.ssd_value_dtype="fp16"; ISSUE 14)
# ---------------------------------------------------------------------------

def _f16_cfg(**kw):
    kw.setdefault("storage", "ssd")
    kw.setdefault("ssd_value_dtype", "fp16")
    return _cfg(**kw)


def _fill(table, rng, n=400):
    keys = rng.integers(1, 1 << 40, n).astype(np.uint64)
    vals = rng.normal(0, 1, (n, table.full_dim)).astype(np.float32)
    vals[:, 0] = (keys % 8).astype(np.float32)  # slot
    table.import_full(keys, vals)
    return keys, vals


def test_fp16_records_digest_widened_canonical_form(tmp_path):
    """The digest of an fp16 table IS the digest of its widened rows
    (snapshot_items) at every moment — with hot rows un-rounded and
    cold rows on the fp16 grid, the canonical form is what every read
    path returns."""
    from paddle_tpu.ps.table import row_digest

    t = SsdSparseTable(tmp_path / "a", _f16_cfg())
    keys, _ = _fill(t, np.random.default_rng(0))
    t.spill(100)  # mixed tiers: some rows rounded, some not
    k, v = t.snapshot_items()
    assert t.digest() == row_digest(k, v)
    # the value columns of COLD rows are exactly fp16-representable
    st = t.stats()
    assert st["cold_rows"] > 0 and st["hot_rows"] > 0
    t.close()


def test_fp16_records_round_trip_snapshot_restore(tmp_path):
    """Fully-spilled fp16 table → snapshot → restore into a fresh fp16
    table via BOTH tiers: digests equal the widened canonical form
    (re-narrowing an fp16-grid value is the identity)."""
    t = SsdSparseTable(tmp_path / "a", _f16_cfg())
    _fill(t, np.random.default_rng(1))
    t.spill(0)  # everything cold → every value column on the fp16 grid
    k, v = t.snapshot_items()
    dg = t.digest()
    cold = SsdSparseTable(tmp_path / "b", _f16_cfg())
    cold.load_cold(k, v)
    assert cold.digest() == dg
    hot = SsdSparseTable(tmp_path / "c", _f16_cfg())
    hot.import_full(k, v)
    assert hot.digest() == dg
    # ...and a full spill of the hot restore re-rounds to the same grid
    hot.spill(0)
    assert hot.digest() == dg
    t.close(); cold.close(); hot.close()


def test_fp16_records_shrink_disk_bytes(tmp_path):
    """The point of the format: cold-tier records are materially
    smaller (embedx 4 + CTR state: 8B key + 4B flag + mixed row)."""
    rng = np.random.default_rng(2)
    keys = rng.integers(1, 1 << 40, 500).astype(np.uint64)
    sizes = {}
    for name, dt in (("f32", "fp32"), ("f16", "fp16")):
        t = SsdSparseTable(tmp_path / name, _cfg(
            storage="ssd", ssd_value_dtype=dt))
        vals = rng.normal(0, 1, (len(keys), t.full_dim)).astype(np.float32)
        vals[:, 0] = 0
        t.import_full(keys, vals)
        t.spill(0)
        sizes[dt] = t.stats()["disk_bytes"]
        t.close()
    assert sizes["fp16"] < 0.85 * sizes["fp32"], sizes


def test_fp16_crash_replay_and_value_grid(tmp_path):
    """Crash recovery (re-open = log replay) preserves fp16 records
    exactly, and widened value columns round-trip float16 losslessly."""
    path = tmp_path / "a"
    t = SsdSparseTable(path, _f16_cfg())
    _fill(t, np.random.default_rng(3))
    t.spill(0)
    k, v = t.snapshot_items()
    dg = t.digest()
    t.close()  # no clean shutdown protocol — reopen replays the log
    t2 = SsdSparseTable(path, _f16_cfg())
    assert t2.digest() == dg
    k2, v2 = t2.snapshot_items()
    order, order2 = np.argsort(k), np.argsort(k2)
    np.testing.assert_array_equal(v[order], v2[order2])
    # value columns are on the fp16 grid (cold rows), opt state is NOT
    # narrowed: unseen/show/click columns keep full fp32 content
    emb = v2[:, 5]
    np.testing.assert_array_equal(
        emb, emb.astype(np.float16).astype(np.float32))
    t2.close()


def test_fp16_replication_full_sync_digest_equal():
    """HA replication of an fp16 SSD table: before any spill the
    replicated ops apply identically (digests EQUAL across replicas),
    and after a primary-side spill — the documented one-time lossy
    moment replication does not see — the primary's digest still
    equals its widened canonical rows (snapshot/replication always
    exchange the widened form, never raw fp16 records)."""
    from paddle_tpu.ps import ha
    from paddle_tpu.ps.rpc import rpc_available
    from paddle_tpu.ps.table import row_digest

    if not rpc_available():
        pytest.skip("native PS service unavailable")
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        with ha.HACluster(num_shards=1, replication=2, sync=True) as c:
            cli = c.client()
            cli.create_sparse_table(0, _f16_cfg(ssd_path=d))
            rng = np.random.default_rng(4)
            keys = rng.integers(1, 1 << 40, 300).astype(np.uint64)
            cli.pull_sparse(0, keys)
            push = np.zeros((len(keys), 8), np.float32)  # 3 + (1 + xd=4)
            push[:, 1] = 1.0
            push[:, 3:] = 0.05
            cli.push_sparse(0, keys, push)
            c.drain()
            # pre-spill: the replicated stream converges bit-identically
            dg = c.digests(0, 0)
            assert len(set(dg.values())) == 1, dg
            # primary-side spill rounds its coldest rows (kSpill is
            # deliberately unreplicated — OPERATIONS §5b caveat); the
            # primary's digest tracks its OWN widened canonical form
            cli.spill(0, 50)
            k, v = cli.snapshot_items(0)
            primary_dg = cli.digest(0)[0]
            assert primary_dg == row_digest(k, v)
