"""Density-measured auto-placement (distributed/placement.py).

Fast tier: DensitySeries window semantics incl. restart re-base, the
PlacementPolicy hysteresis + Densifying caution, manager fence gating,
and flush-during-residency digest consistency.

Acceptance: a placement swap executed at a LIVE reshard epoch fence —
the variable moves PS→collective mid-CtrStreamTrainer while the
cluster grows 2→4, then back at a manual fence, with zero lost/doubled
rows by PR 4 digests, no trainer-visible error, and final pulled rows
+ dense params BIT-identical to an un-resharded, un-placed oracle.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not __import__("paddle_tpu.ps.rpc", fromlist=["rpc_available"]
                   ).rpc_available(),
    reason="native PS service unavailable")

from paddle_tpu.distributed.placement import (DensitySeries,  # noqa: E402
                                              PlacementConfig,
                                              PlacementManager,
                                              PlacementPolicy)
from paddle_tpu.ps import ha  # noqa: E402
from paddle_tpu.ps.table import TableConfig, row_digest  # noqa: E402

MASK = 0xFFFFFFFFFFFFFFFF
S, D = 3, 2


# ---------------------------------------------------------------------------
# DensitySeries
# ---------------------------------------------------------------------------

def test_density_series_window_and_ewma():
    s = DensitySeries(window=4)
    for v in (0.2, 0.4, 0.9, 0.1, 0.5):
        s.update(v)
    assert s.n == 4                      # bounded window
    assert s.wmin == 0.1 and s.wmax == 0.9
    # EWMA seeded from the FIRST sample, alpha 0.2
    e = 0.2
    for v in (0.4, 0.9, 0.1, 0.5):
        e = 0.8 * e + 0.2 * v
    assert abs(s.ewma - e) < 1e-12


def test_density_series_restart_rebase():
    """A fresh series (client restart) re-bases: the first post-restart
    sample seeds the EWMA (no decay from zero) and the window holds
    only post-restart samples."""
    from paddle_tpu.obs.registry import Registry

    reg = Registry()
    g = reg.gauge("ps_client_density", table="0", dir="push")
    gmin = reg.gauge("ps_client_density_min", table="0", dir="push")
    gmax = reg.gauge("ps_client_density_max", table="0", dir="push")
    s1 = DensitySeries(gauge=g, gmin=gmin, gmax=gmax, window=8)
    for v in (0.01, 0.02, 0.99):
        s1.update(v)
    assert gmin.value == 0.01 and gmax.value == 0.99
    # "restart": a new incarnation binds the same gauges
    s2 = DensitySeries(gauge=g, gmin=gmin, gmax=gmax, window=8)
    s2.update(0.7)
    assert s2.ewma == 0.7                # re-based, not decayed from 0
    assert s2.n == 1
    assert gmin.value == 0.7 and gmax.value == 0.7  # window re-based too


def test_density_series_feeds_registry_family():
    """The client's push path still lands in the PR 8
    ps_client_density family (last-write + the Gauge's own EWMA)."""
    from paddle_tpu.ps.rpc import NativePsServer, RpcPsClient

    srv = NativePsServer()
    try:
        cli = RpcPsClient([f"127.0.0.1:{srv.port}"])
        cli.create_sparse_table(0, TableConfig())
        keys = np.arange(1, 33, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        push = np.zeros((len(keys), 12), np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = 0.5  # fully dense gradient block
        cli.push_sparse(0, keys, push)
        s = cli.density_series(0, "push")
        assert s is not None and s.n == 1 and s.ewma == 1.0
        from paddle_tpu.obs import registry as _reg

        snap = _reg.REGISTRY.snapshot()["metrics"]
        vals = {tuple(sorted(r["labels"].items())): r["value"]
                for r in snap["ps_client_density"]["series"]}
        assert vals[(("dir", "push"), ("table", "0"))] == 1.0
        assert "ps_client_density_min" in snap
        assert "ps_client_density_max" in snap
        cli.close()
    finally:
        srv.stop()
        srv.close()


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

def _fed(values, window=16):
    s = DensitySeries(window=window)
    for v in values:
        s.update(v)
    return s


def test_policy_min_samples_gate():
    p = PlacementPolicy(PlacementConfig(min_samples=8))
    assert p.decide("ps", _fed([0.9] * 7)) is None
    assert p.decide("ps", _fed([0.9] * 8)) == "collective"
    assert p.decide("ps", None) is None


def test_policy_densifying_caution_window_min():
    """One sparse batch inside the window blocks densify even when the
    EWMA clears the bar — density is a measured property of the WINDOW,
    not of the latest batch (the Densifying cautionary baseline)."""
    p = PlacementPolicy(PlacementConfig(densify_threshold=0.6,
                                        sparsify_threshold=0.25,
                                        min_samples=4))
    dense_burst = _fed([0.9] * 10 + [0.1] + [0.9] * 5)   # dipped once
    assert dense_burst.ewma > 0.6
    assert p.decide("ps", dense_burst) is None            # blocked
    steady = _fed([0.9] * 16)
    assert p.decide("ps", steady) == "collective"


def test_policy_hysteresis_band():
    p = PlacementPolicy(PlacementConfig(densify_threshold=0.6,
                                        sparsify_threshold=0.25,
                                        min_samples=4))
    mid = _fed([0.4] * 8)   # inside the band: no flapping either way
    assert p.decide("ps", mid) is None
    assert p.decide("collective", mid) is None
    sparse = _fed([0.05] * 8)
    assert p.decide("collective", sparse) == "ps"
    assert p.decide("ps", sparse) is None


# ---------------------------------------------------------------------------
# manager (real cluster + trainer)
# ---------------------------------------------------------------------------

def _stream_trainer(cli, placement=None):
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.communicator import SyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    comm = SyncCommunicator(cli)
    comm.start()
    pt.seed(0)
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), None, communicator=comm, table_id=0,
        embedx_dim=8, placement=placement,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    return tr, comm


def _data(n, seed=0):
    import sys

    sys.path.insert(0, "tests")
    from test_reshard import _stream_data

    return _stream_data(n, S, D, seed=seed)


def test_fence_gates_the_swap():
    """An armed swap does NOT execute until an epoch fence passes; the
    first poll after fence() applies it at the batch boundary."""
    with ha.HACluster(num_shards=2, replication=1, sync=True) as c:
        cli = c.client()
        cli.create_sparse_table(0, TableConfig(table_id=0, shard_num=4))
        mgr = PlacementManager(cli, 0, PlacementConfig(
            min_samples=4, auto=False))
        tr, comm = _stream_trainer(cli, mgr)
        tr.train_from_dataset(_data(128), batch_size=64)
        mgr.arm("collective")
        tr.train_from_dataset(_data(128, seed=1), batch_size=64)
        assert mgr.placement == "ps"          # no fence yet
        mgr.fence()                            # manual epoch fence
        tr.train_from_dataset(_data(128, seed=2), batch_size=64)
        assert mgr.placement == "collective"
        assert mgr.local_table is not None
        assert [e["to"] for e in mgr.events] == ["collective"]
        comm.stop()


def test_flush_keeps_checkpoint_cut_complete():
    """While collective-resident, flush() writes every local row back:
    the PS digest equals the local rows' digest — a job-checkpoint
    capture taken now is complete without knowing the plane exists."""
    with ha.HACluster(num_shards=2, replication=1, sync=True) as c:
        cli = c.client()
        cli.create_sparse_table(0, TableConfig(table_id=0, shard_num=4))
        mgr = PlacementManager(cli, 0, PlacementConfig(
            min_samples=4, auto=False, require_fence=False))
        tr, comm = _stream_trainer(cli, mgr)
        tr.train_from_dataset(_data(192), batch_size=64)
        mgr.arm("collective")
        tr.train_from_dataset(_data(192, seed=1), batch_size=64)
        assert mgr.placement == "collective"
        rows = mgr.flush()
        assert rows > 0
        k, v = mgr.local_table.snapshot_items()
        assert (sum(cli.digest_routed(0)) & MASK) == row_digest(k, v)
        # reset_to_ps (the restore path) drops residence without a
        # write-back — the next pulls go to the PS again
        mgr.reset_to_ps()
        assert mgr.placement == "ps" and mgr.local_table is None
        comm.stop()


def test_swap_at_live_reshard_fence_bit_identical_to_oracle():
    """THE acceptance: mid-stream, a reshard grow 2→4 fires the epoch
    fence; the armed densify executes at the next batch boundary (rows
    verified by digests), training continues on the collective plane,
    then a manual fence moves it back. Final pulled rows, server
    digests and dense params are BIT-identical to an oracle that never
    resharded and never swapped."""
    import jax
    from paddle_tpu.ps.reshard import ReshardController

    def run(place):
        with ha.HACluster(num_shards=2, replication=1, sync=True) as c:
            cli = c.client()
            cli.create_sparse_table(0, TableConfig(table_id=0, shard_num=4))
            mgr = ctl = None
            if place:
                ctl = ReshardController(c)
                # the CTR stream's gradient block is fully dense →
                # densify arms from measured density, not a manual arm
                mgr = PlacementManager(cli, 0, PlacementConfig(
                    densify_threshold=0.5, min_samples=4), controller=ctl)
            tr, comm = _stream_trainer(cli, mgr)
            tr.train_from_dataset(_data(384), batch_size=64)
            if place:
                assert mgr.placement == "ps"   # armed, but no fence yet
                ctl.grow(2)                    # pre-cutover hook = fence
                tr.on_reshard()                # batch boundary: applies
                assert mgr.placement == "collective"
                assert cli.num_servers == 4
            tr.train_from_dataset(_data(384, seed=1), batch_size=64)
            if place:
                assert mgr.placement == "collective"  # zero PS RPCs here
                mgr.arm("ps")
                mgr.fence()
            tr.train_from_dataset(_data(192, seed=2), batch_size=64)
            if place:
                assert mgr.placement == "ps"
                assert [e["to"] for e in mgr.events] == ["collective", "ps"]
            comm.barrier()
            probe = np.unique(
                (np.arange(0, 48, dtype=np.uint64)[None, :]
                 + (np.arange(S, dtype=np.uint64)[:, None]
                    << np.uint64(32))).reshape(-1))
            pulled = cli.pull_sparse(0, probe, create=False)
            dig = sum(cli.digest_routed(0)) & MASK
            params = jax.tree_util.tree_map(np.asarray, tr.params)
            comm.stop()
            return pulled, dig, params

    pulled_p, dig_p, params_p = run(place=True)
    pulled_o, dig_o, params_o = run(place=False)
    assert dig_p == dig_o                      # zero lost/doubled rows
    np.testing.assert_array_equal(pulled_p, pulled_o)
    for a, b in zip(jax.tree_util.tree_leaves(params_p),
                    jax.tree_util.tree_leaves(params_o)):
        np.testing.assert_array_equal(a, b)
