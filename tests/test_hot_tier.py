"""Persistent HBM hot-embedding tier (ps/hot_tier.py): dynamic map
mechanics, hot-tier ≡ RPC-only bit-parity (dense params + pulled rows,
fp32 path), eviction-churn parity, mid-stream checkpoint/restore parity,
the 0-RPC warm-step contract, and the sharded (mesh) step.

The parity oracle story: the tier's device rule math
(ops/sparse_optimizer) is pinned bit-identical to the host engines, so a
tier-enabled run reproduces the RPC-only trainer's final state EXACTLY
on the fp32 path — except ``delta_score`` (save-layout col 2), which
folds per FLUSH instead of per push (the established end_pass
association; documented non-goal in the hot_tier module docstring)."""

import os
import tempfile

import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
from paddle_tpu.models.ctr import CtrConfig, DeepFM
from paddle_tpu.ps import rpc
from paddle_tpu.ps.communicator import (HalfAsyncCommunicator,
                                         SyncCommunicator)
from paddle_tpu.ps.device_hash import (DynamicDeviceKeyMap,
                                       dynamic_map_lookup, split_keys)
from paddle_tpu.ps.hot_tier import HotEmbeddingTier, HotTierConfig
from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

# save-layout column of delta_score — the one per-flush-vs-per-push
# association difference the parity tests carve out
_DELTA_COL = 2


# ---------------------------------------------------------------------------
# DynamicDeviceKeyMap
# ---------------------------------------------------------------------------


def _dev_lookup(m: DynamicDeviceKeyMap, keys: np.ndarray) -> np.ndarray:
    hi, lo = split_keys(keys)
    import jax.numpy as jnp

    return np.asarray(dynamic_map_lookup(m.device_state(), jnp.asarray(hi),
                                         jnp.asarray(lo), m.probe_buckets))


def test_dynamic_map_insert_lookup_remove():
    m = DynamicDeviceKeyMap(64)
    keys = np.arange(1, 33, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    rows = np.arange(32, dtype=np.int32)
    m.insert(keys, rows)
    np.testing.assert_array_equal(m.lookup_host(keys), rows)
    # absent keys miss
    assert (m.lookup_host(np.asarray([7, 8, 9], np.uint64)) == -1).all()
    # remove half, the rest still resolve
    m.remove(keys[::2])
    got = m.lookup_host(keys)
    assert (got[::2] == -1).all()
    np.testing.assert_array_equal(got[1::2], rows[1::2])
    assert m.used == 16
    # re-inserting a removed key at a new row works (tombstone reuse)
    m.insert(keys[:1], np.asarray([99], np.int32))
    assert m.lookup_host(keys[:1])[0] == 99


def test_dynamic_map_device_lookup_matches_host():
    m = DynamicDeviceKeyMap(128)
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 2**63, 100).astype(np.uint64)
    keys = np.unique(keys)
    m.insert(keys, np.arange(len(keys), dtype=np.int32))
    probe = np.concatenate([keys, rng.integers(1, 2**63, 50).astype(np.uint64)])
    np.testing.assert_array_equal(_dev_lookup(m, probe), m.lookup_host(probe))
    # mutate (patch path: device arrays update incrementally) and re-check
    m.remove(keys[:10])
    m.insert(rng.integers(1, 2**63, 5).astype(np.uint64)
             | np.uint64(1 << 63),
             np.arange(200, 205, dtype=np.int32))
    np.testing.assert_array_equal(_dev_lookup(m, probe), m.lookup_host(probe))


def test_dynamic_map_rebuild_preserves_entries():
    m = DynamicDeviceKeyMap(64, bucket_slots=1, probe_buckets=1)
    rng = np.random.default_rng(1)
    keys = np.unique(rng.integers(1, 2**63, 60).astype(np.uint64))[:48]
    rows = np.arange(len(keys), dtype=np.int32)
    m.insert(keys, rows)  # 1-slot windows → collisions force rebuilds
    np.testing.assert_array_equal(m.lookup_host(keys), rows)
    # explicit grow-rebuild: layout changes, entries don't
    nb0 = m.nbuckets
    m._rebuild(grow=True)
    assert m.nbuckets == 2 * nb0 and m.rebuilds > 0
    np.testing.assert_array_equal(m.lookup_host(keys), rows)
    np.testing.assert_array_equal(_dev_lookup(m, keys), rows)


def test_dynamic_map_over_capacity_rejected():
    m = DynamicDeviceKeyMap(4)
    with pytest.raises(Exception):
        m.insert(np.arange(1, 7, dtype=np.uint64),
                 np.arange(6, dtype=np.int32))


# ---------------------------------------------------------------------------
# trainer parity harness
# ---------------------------------------------------------------------------

S, D = 3, 2


def make_data(n=256, seed=0, nid=48):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        ids = rng.integers(0, nid, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


def make_trainer(table, hot=None, communicator=None, table_id=0):
    pt.seed(0)
    return CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), table, embedx_dim=8,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
        communicator=communicator, table_id=table_id, hot_tier=hot)


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _assert_bitwise_equal(ta, tb):
    for a, b in zip(ta, tb):
        np.testing.assert_array_equal(a, b)


def _sorted_items(table):
    k, v = table.snapshot_items()
    i = np.argsort(k)
    return k[i], v[i]


def _assert_rows_equal_mod_delta(ta, tb):
    ka, va = _sorted_items(ta)
    kb, vb = _sorted_items(tb)
    np.testing.assert_array_equal(ka, kb)
    for c in range(va.shape[1]):
        if c == _DELTA_COL:
            continue
        np.testing.assert_array_equal(va[:, c], vb[:, c],
                                      err_msg=f"save col {c}")


def test_hot_tier_parity_bit_identical():
    """Tier-enabled training ≡ RPC-only oracle: dense params bitwise,
    every pulled-row column bitwise except the per-flush delta_score."""
    ds = make_data()
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    a = make_trainer(ta)
    ra = a.train_from_dataset(ds, batch_size=64)
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, hot=HotTierConfig(capacity=256))
    rb = b.train_from_dataset(ds, batch_size=64)
    b.hot_tier.flush()
    assert ra["loss"] == rb["loss"]
    _assert_bitwise_equal(_leaves(a.params), _leaves(b.params))
    _assert_bitwise_equal(_leaves(a.opt_state), _leaves(b.opt_state))
    _assert_rows_equal_mod_delta(ta, tb)
    st = rb["hot_tier"]
    assert st["misses"] > 0 and st["hits"] > 0 and st["evictions"] == 0
    assert 0 < st["occupancy"] <= st["capacity"]


def test_hot_tier_eviction_churn_parity():
    """Tiny capacity (barely above one batch's working set) forces
    heavy eviction/readmission churn — parity must survive the
    writeback→re-fetch round-trips bit-for-bit."""
    ds = make_data(nid=400)
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    a = make_trainer(ta)
    a.train_from_dataset(ds, batch_size=64)
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, hot=HotTierConfig(capacity=224))
    rb = b.train_from_dataset(ds, batch_size=64)
    st = rb["hot_tier"]
    assert st["evictions"] > 0 and st["writebacks"] > 0
    b.hot_tier.flush()
    _assert_bitwise_equal(_leaves(a.params), _leaves(b.params))
    _assert_rows_equal_mod_delta(ta, tb)


def test_hot_tier_capacity_below_batch_working_set_raises():
    ds = make_data(nid=400)
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, hot=HotTierConfig(capacity=64))  # < 64*3 keys
    with pytest.raises(Exception, match="capacity"):
        b.train_from_dataset(ds, batch_size=64)


def test_hot_tier_checkpoint_restore_parity():
    """Mid-stream checkpoint → fresh process-equivalent restore →
    resume: final table digests AND dense params/opt bitwise equal to an
    uninterrupted tier-enabled oracle checkpointing at the same cadence
    (same flush points ⇒ same delta_score association ⇒ full digest
    equality, not just mod-delta)."""
    from paddle_tpu.io.job_checkpoint import JobCheckpointManager

    tmp = tempfile.mkdtemp()
    ds = make_data(n=640, nid=120)
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    a = make_trainer(ta, hot=HotTierConfig(capacity=256))
    mga = JobCheckpointManager(os.path.join(tmp, "a"), max_keep=8)
    mga.register_sparse("ctr", ta)
    a.train_from_dataset(ds, batch_size=128, checkpoint=mga,
                         checkpoint_every=2)
    mga.stop()
    a.hot_tier.flush()

    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, hot=HotTierConfig(capacity=256))
    mgr = JobCheckpointManager(os.path.join(tmp, "b"), max_keep=8)
    mgr.register_sparse("ctr", tb)
    b.train_from_dataset(ds, batch_size=128, checkpoint=mgr,
                         checkpoint_every=2)
    mgr.wait()
    restored = mgr.load_latest()
    assert restored.cursor["batch"] > 0

    tc = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    c = make_trainer(tc, hot=HotTierConfig(capacity=256))
    restored.restore_sparse("ctr", tc)
    c.restore_train_state(restored.dense)
    # restore drops the resident set (stale vs the rebuilt cold table)
    assert c.hot_tier.stats()["occupancy"] == 0
    out = c.train_from_dataset(ds, batch_size=128,
                               start_batch=restored.cursor)
    assert out["steps"] > 0
    c.hot_tier.flush()
    mgr.stop()
    assert tc.digest() == ta.digest()
    _assert_bitwise_equal(_leaves(a.params), _leaves(c.params))
    _assert_bitwise_equal(_leaves(a.opt_state), _leaves(c.opt_state))


def test_hot_tier_warm_steady_state_zero_rpcs():
    """THE acceptance criterion: once the working set is resident, a
    steady-state epoch over a real RPC PS performs ZERO client ops —
    counted at RpcPsClient, the hot-tier CI gate's counter."""
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    try:
        client.create_sparse_table(
            0, TableConfig(table_id=0, shard_num=4, accessor="ctr"))
        comm = HalfAsyncCommunicator(client)
        comm.start()
        tr = make_trainer(None, hot=HotTierConfig(capacity=512),
                          communicator=comm)
        ds = make_data(n=512, nid=60)
        tr.train_from_dataset(ds, batch_size=128)  # warm-up: admit all
        st1 = tr.hot_tier.stats()
        assert st1["misses"] > 0  # the cold fills happened
        client.reset_op_counts()
        out = tr.train_from_dataset(ds, batch_size=128)  # warm epoch
        counts = client.reset_op_counts()
        assert counts == {}, f"warm epoch performed PS RPCs: {counts}"
        st2 = out["hot_tier"]  # counters are tier-lifetime cumulative
        assert st2["misses"] == st1["misses"], "warm epoch missed"
        assert st2["hits"] > st1["hits"]
        assert st2["cold_fetches"] == st1["cold_fetches"]
        comm.stop()
    finally:
        client.close()
        for s in servers:
            s.stop()


def test_hot_tier_sharded_mesh_step_matches_single_chip():
    """8-shard GSPMD mesh tier (replicated dynamic map + all_to_all
    routed rows) trains to the single-chip tier's results. Dense grads
    psum over the mesh (association differs from the serial sum), so
    this pins a tight tolerance, not bits — within-mesh routed≡gathered
    bitwise parity is pinned by test_sharded_cache.py."""
    ds = make_data(n=512, nid=60)
    mesh = mesh_mod.make_mesh({"ps": 8})
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    a = make_trainer(ta, HotTierConfig(capacity=512))
    ra = a.train_from_dataset(ds, batch_size=128)
    a.hot_tier.flush()
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, HotTierConfig(capacity=512, mesh=mesh, axis="ps"))
    rb = b.train_from_dataset(ds, batch_size=128)
    b.hot_tier.flush()
    assert rb["hot_tier"]["shards"] == 8
    assert abs(ra["loss"] - rb["loss"]) < 1e-6
    for x, y in zip(_leaves(a.params), _leaves(b.params)):
        np.testing.assert_allclose(x, y, rtol=0, atol=1e-6)
    ka, va = _sorted_items(ta)
    kb, vb = _sorted_items(tb)
    np.testing.assert_array_equal(ka, kb)
    np.testing.assert_allclose(va, vb, rtol=0, atol=1e-6)


def test_hot_tier_stats_and_drop():
    """Observability counters (satellite) + drop() semantics."""
    table = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr"))
    tier = HotEmbeddingTier(table, HotTierConfig(capacity=32))
    keys = np.asarray([1, 2, 3, 2, 1], np.uint64)
    tier.ensure(keys)
    st = tier.stats()
    # hit/miss counts are per-occurrence of the PRE-ensure resident set:
    # all five occurrences missed (the batch was fully cold)
    assert st["misses"] == 5 and st["hits"] == 0
    assert st["occupancy"] == 3 and st["dirty"] == 3
    assert st["capacity"] == 32 and st["hit_rate"] == 0.0
    n = tier.flush()
    assert n == 3 and tier.stats()["dirty"] == 0
    tier.ensure(keys)
    assert tier.stats()["hits"] == 5  # all resident now
    tier.drop()
    st = tier.stats()
    assert st["occupancy"] == 0 and st["dirty"] == 0
    # refill on miss after drop
    tier.ensure(keys)
    assert tier.stats()["occupancy"] == 3


# ---------------------------------------------------------------------------
# fused Pallas kernels (ops/hot_kernels.py) — tier-level parity matrix.
# Kernel-level parity (vs the jnp formulations, every rule, unaligned n)
# is pinned in tests/test_hot_kernels.py; here the kernels run inside
# the REAL compiled steps (interpret mode on CPU) and must reproduce
# the jnp tier AND the RPC-only oracle bit-for-bit through eviction
# churn, adam rules, checkpoint/restore and the sharded banked mesh.
# ---------------------------------------------------------------------------


def test_hot_tier_pallas_parity_through_eviction_churn():
    """kernels="pallas" (interpret) ≡ kernels="jnp" ≡ RPC-only oracle
    under heavy eviction/readmission churn: dense params/opt bitwise,
    table rows bitwise between the two tiers (same flush points ⇒ full
    equality incl. delta_score), rows-mod-delta vs the oracle."""
    ds = make_data(nid=400)
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    a = make_trainer(ta)
    a.train_from_dataset(ds, batch_size=64)
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, hot=HotTierConfig(capacity=224, kernels="jnp"))
    b.train_from_dataset(ds, batch_size=64)
    b.hot_tier.flush()
    tc = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    c = make_trainer(tc, hot=HotTierConfig(capacity=224, kernels="pallas"))
    rc = c.train_from_dataset(ds, batch_size=64)
    c.hot_tier.flush()
    st = rc["hot_tier"]
    assert st["evictions"] > 0 and st["kernels"] == "pallas"
    _assert_bitwise_equal(_leaves(a.params), _leaves(c.params))
    _assert_bitwise_equal(_leaves(b.params), _leaves(c.params))
    _assert_bitwise_equal(_leaves(b.opt_state), _leaves(c.opt_state))
    kb, vb = _sorted_items(tb)
    kc, vc = _sorted_items(tc)
    np.testing.assert_array_equal(kb, kc)
    np.testing.assert_array_equal(vb, vc)  # incl. delta_score
    _assert_rows_equal_mod_delta(ta, tc)


def test_hot_tier_pallas_adam_rule_parity():
    """The adam half of the kernel parity matrix at tier level: an
    adam/adam accessor trains bit-identically through the fused
    kernels (m/v moments and beta powers round-trip the writeback)."""
    from paddle_tpu.ps.accessor import AccessorConfig

    acc = AccessorConfig(embed_sgd_rule="adam", embedx_sgd_rule="adam")
    ds = make_data(nid=120)
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr",
                                       accessor_config=acc))
    a = make_trainer(ta)
    a.train_from_dataset(ds, batch_size=64)
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr",
                                       accessor_config=acc))
    b = make_trainer(tb, hot=HotTierConfig(capacity=256, kernels="pallas"))
    b.train_from_dataset(ds, batch_size=64)
    b.hot_tier.flush()
    _assert_bitwise_equal(_leaves(a.params), _leaves(b.params))
    _assert_rows_equal_mod_delta(ta, tb)


def test_hot_tier_pallas_checkpoint_restore_parity():
    """Mid-stream checkpoint → restore → resume with kernels="pallas":
    final digests AND dense state bitwise equal to an uninterrupted
    pallas oracle (the kernels change nothing about the flush-dirty-
    then-snapshot contract)."""
    from paddle_tpu.io.job_checkpoint import JobCheckpointManager

    tmp = tempfile.mkdtemp()
    ds = make_data(n=384, nid=120)
    cfg = lambda: HotTierConfig(capacity=256, kernels="pallas")  # noqa: E731
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    a = make_trainer(ta, hot=cfg())
    mga = JobCheckpointManager(os.path.join(tmp, "a"), max_keep=8)
    mga.register_sparse("ctr", ta)
    a.train_from_dataset(ds, batch_size=128, checkpoint=mga,
                         checkpoint_every=2)
    mga.stop()
    a.hot_tier.flush()

    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, hot=cfg())
    mgr = JobCheckpointManager(os.path.join(tmp, "b"), max_keep=8)
    mgr.register_sparse("ctr", tb)
    b.train_from_dataset(ds, batch_size=128, checkpoint=mgr,
                         checkpoint_every=2)
    mgr.wait()
    restored = mgr.load_latest()

    tc = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    c = make_trainer(tc, hot=cfg())
    restored.restore_sparse("ctr", tc)
    c.restore_train_state(restored.dense)
    assert c.hot_tier.stats()["occupancy"] == 0
    c.train_from_dataset(ds, batch_size=128, start_batch=restored.cursor)
    c.hot_tier.flush()
    mgr.stop()
    assert tc.digest() == ta.digest()
    _assert_bitwise_equal(_leaves(a.params), _leaves(c.params))
    _assert_bitwise_equal(_leaves(a.opt_state), _leaves(c.opt_state))


def test_hot_tier_banked_single_chip_parity():
    """banks > 1 on a single chip (the NUMA bucket-per-bank layout)
    changes row PLACEMENT only: training results are bit-identical to
    the unbanked tier (ample capacity — no eviction-timing skew)."""
    ds = make_data(n=256, nid=60)
    ta = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    a = make_trainer(ta, hot=HotTierConfig(capacity=512))
    a.train_from_dataset(ds, batch_size=64)
    a.hot_tier.flush()
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, hot=HotTierConfig(capacity=512, banks=4,
                                           kernels="pallas"))
    rb = b.train_from_dataset(ds, batch_size=64)
    b.hot_tier.flush()
    assert rb["hot_tier"]["banks"] == 4
    _assert_bitwise_equal(_leaves(a.params), _leaves(b.params))
    ka, va = _sorted_items(ta)
    kb, vb = _sorted_items(tb)
    np.testing.assert_array_equal(ka, kb)
    np.testing.assert_array_equal(va, vb)


def test_hot_tier_sharded_banked_pallas_matches_jnp_bitwise():
    """8-shard mesh, banked map (one bank per shard — a key's row block
    IS its owner's HBM): the pallas sharded step (fused local probe +
    owner-side scatter+apply behind the all_to_all exchange) is
    BIT-identical to the jnp sharded step — same routing, same merge
    association, same sealed rule bits."""
    ds = make_data(n=512, nid=60)
    mesh = mesh_mod.make_mesh({"ps": 8})
    tb = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    b = make_trainer(tb, HotTierConfig(capacity=512, mesh=mesh, axis="ps",
                                       kernels="jnp"))
    rb = b.train_from_dataset(ds, batch_size=128)
    b.hot_tier.flush()
    assert rb["hot_tier"]["shards"] == 8 and rb["hot_tier"]["banks"] == 8
    tc = MemorySparseTable(TableConfig(shard_num=4, accessor="ctr"))
    c = make_trainer(tc, HotTierConfig(capacity=512, mesh=mesh, axis="ps",
                                       kernels="pallas"))
    rc = c.train_from_dataset(ds, batch_size=128)
    c.hot_tier.flush()
    assert rc["loss"] == rb["loss"]
    _assert_bitwise_equal(_leaves(b.params), _leaves(c.params))
    _assert_bitwise_equal(_leaves(b.opt_state), _leaves(c.opt_state))
    kb, vb = _sorted_items(tb)
    kc, vc = _sorted_items(tc)
    np.testing.assert_array_equal(kb, kc)
    np.testing.assert_array_equal(vb, vc)


def test_hot_tier_rejects_mismatched_embedx_dim():
    table = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr"))
    pt.seed(0)
    with pytest.raises(Exception, match="embedx_dim"):
        CtrStreamTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                             dnn_hidden=(8,))),
            optimizer.Adam(1e-2), table, embedx_dim=4,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
            hot_tier=HotEmbeddingTier(
                MemorySparseTable(TableConfig(shard_num=2, accessor="ctr")),
                HotTierConfig(capacity=32)))


def test_hot_tier_writebacks_route_fp32_under_int8_push_wire():
    """ISSUE 14 satellite pin: an int8 PUSH wire (push_wire_dtype) must
    not touch the tier's writeback path — dirty evictions/flushes ship
    as fp32 full-row import_full frames, so the tier stays BIT-identical
    to an fp32-wire RPC-only oracle even when the table config
    quantizes push_sparse. (An oracle pushing through the int8 wire
    would differ — that is the contract being pinned, not assumed.)"""
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    eps = [f"127.0.0.1:{s.port}" for s in servers]
    servers_o = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    eps_o = [f"127.0.0.1:{s.port}" for s in servers_o]
    cli = rpc.RpcPsClient(eps)
    cli_o = rpc.RpcPsClient(eps_o)
    try:
        # tier arm: table CONFIGURED for the quantized push wire; small
        # capacity forces eviction churn so dirty writebacks really flow
        cli.create_sparse_table(0, TableConfig(
            table_id=0, shard_num=4, accessor="ctr",
            push_wire_dtype="int8"))
        # SYNC communicator: the documented bit-parity precondition
        # (async oracle pulls are stale by queue depth — §5d)
        comm = SyncCommunicator(cli)
        comm.start()
        tr = make_trainer(None, hot=HotTierConfig(capacity=224),
                          communicator=comm)
        ds = make_data(nid=400)
        rb = tr.train_from_dataset(ds, batch_size=64)
        assert rb["hot_tier"]["writebacks"] > 0
        tr.hot_tier.flush()
        comm.stop()
        # oracle arm: plain fp32 wire, RPC-only
        cli_o.create_sparse_table(0, TableConfig(
            table_id=0, shard_num=4, accessor="ctr"))
        comm_o = SyncCommunicator(cli_o)
        comm_o.start()
        tr_o = make_trainer(None, communicator=comm_o)
        tr_o.train_from_dataset(ds, batch_size=64)
        comm_o.barrier()
        comm_o.stop()
        _assert_bitwise_equal(_leaves(tr.params), _leaves(tr_o.params))
        ka, va = cli.snapshot_items(0)
        kb, vb = cli_o.snapshot_items(0)
        ia, ib = np.argsort(ka), np.argsort(kb)
        np.testing.assert_array_equal(ka[ia], kb[ib])
        for c in range(va.shape[1]):
            if c == _DELTA_COL:
                continue
            np.testing.assert_array_equal(va[ia][:, c], vb[ib][:, c],
                                          err_msg=f"col {c}")
        # and the int8 wire config left ZERO residuals behind: the tier
        # never pushed through the quantized path at all
        assert cli.push_residual_rows() == 0
    finally:
        cli.close()
        cli_o.close()
        for s in servers + servers_o:
            s.stop()
