"""SLO-driven autoscaler (ps/autoscale.py): hysteresis, cooldowns,
bounds, journal — all under an injected clock and a fake controller
(the real actuator is covered by tests/test_reshard.py) — plus the
watchdog wiring and the elastic desired-np trainer surface."""

import numpy as np
import pytest

from paddle_tpu.distributed import elastic
from paddle_tpu.obs.slo import SloRule, SloWatchdog
from paddle_tpu.obs.timeseries import MetricRing
from paddle_tpu.ps.autoscale import AutoscaleConfig, Autoscaler


class _FakeCluster:
    job_id = "as-test"

    def __init__(self, n=2):
        self.n = n
        self.store = elastic.MemoryStore()

    @property
    def num_shards(self):
        return self.n


class _FakeController:
    def __init__(self, n=2, fail=False):
        self.cluster = _FakeCluster(n)
        self.ops = []
        self.fail = fail

    def grow(self, factor):
        if self.fail:
            raise RuntimeError("boom")
        self.cluster.n *= factor
        self.ops.append(("grow", self.cluster.n))
        return {"cutover_pause_ms": 1.0, "bootstrap_s": 0.01}

    def shrink(self, factor):
        self.cluster.n //= factor
        self.ops.append(("shrink", self.cluster.n))
        return {"cutover_pause_ms": 1.0, "bootstrap_s": 0.01}


class _Alert:
    def __init__(self, rule):
        self.rule = rule


def _scaler(ctrl=None, **cfg_kw):
    ctrl = ctrl or _FakeController()
    t = [0.0]
    cfg = AutoscaleConfig(min_shards=2, max_shards=8, cooldown_up_s=5.0,
                          cooldown_down_s=10.0, clear_hold_s=4.0, **cfg_kw)
    return ctrl, t, Autoscaler(ctrl, config=cfg, clock=lambda: t[0])


def test_scale_up_on_alert_and_cooldown():
    ctrl, t, a = _scaler()
    assert a.step() is None                      # quiet, at min
    a.notify_fire(_Alert("step_time_p95"))
    assert a.step() == "up" and ctrl.cluster.n == 4
    t[0] = 2.0
    assert a.step() is None                      # up-cooldown holds
    t[0] = 6.0
    assert a.step() == "up" and ctrl.cluster.n == 8


def test_max_bound_refuses_and_journals():
    ctrl, t, a = _scaler(ctrl=_FakeController(n=8))
    a.notify_fire(_Alert("serving_p99"))
    assert a.step() is None
    assert a.events[-1]["kind"] == "scale_refused"
    assert a.events[-1]["reason"] == "max_shards"
    assert ctrl.ops == []


def test_scale_down_needs_quiet_hold_and_cooldown():
    ctrl, t, a = _scaler()
    a.notify_fire(_Alert("step_time_p95"))
    assert a.step() == "up"                      # n=4 at t=0
    a.notify_clear(_Alert("step_time_p95"))      # quiet_since = 0
    t[0] = 2.0
    assert a.step() is None                      # quiet-hold (4s) not met
    t[0] = 5.0                                   # quiet met, down-cooldown
    assert a.step() is None                      # (10s from scale) not met
    t[0] = 11.0
    assert a.step() == "down" and ctrl.cluster.n == 2
    t[0] = 30.0
    assert a.step() is None                      # at min: never below


def test_non_up_rule_alerts_are_ignored():
    ctrl, t, a = _scaler()
    a.notify_fire(_Alert("checkpoint_staleness"))  # not an up-rule
    assert a.step() is None
    assert ctrl.ops == []
    assert a.active_up_rules() == []


def test_alert_must_clear_before_down_even_after_cooldowns():
    ctrl, t, a = _scaler()
    a.notify_fire(_Alert("replication_lag"))
    assert a.step() == "up"                      # 2 → 4
    t[0] = 100.0                                 # cooldowns long past —
    assert a.step() == "up"                      # still burning: UP again
    t[0] = 200.0
    assert a.step() is None                      # at max: refused…
    assert all(op != "shrink" for op, _ in ctrl.ops)  # …never DOWN
    assert ctrl.cluster.n == 8


def test_failed_scale_is_journaled_and_cooled_down():
    ctrl = _FakeController(fail=True)
    _, t, a = _scaler(ctrl=ctrl)
    a.notify_fire(_Alert("step_time_p95"))
    assert a.step() is None
    assert a.errors == 1
    assert a.events[-1]["kind"] == "scale_failed"
    t[0] = 1.0
    assert a.step() is None                      # cooldown after failure:
    assert a.errors == 1                         # no hot-looping the break


def test_journal_wall_clock_and_tenant_labels():
    """ISSUE 19 satellite: every journal event carries a wall-clock
    ``wall_s`` (the cross-subsystem alignment key — flight-recorder
    manifests and obs spans stamp the same field) alongside the legacy
    ``t`` alias, and a tenant-scoped autoscaler stamps its tenant on
    every event so one journal stream splits cleanly per tenant."""
    ctrl = _FakeController()
    t = [0.0]
    cfg = AutoscaleConfig(min_shards=2, max_shards=8, cooldown_up_s=5.0,
                          cooldown_down_s=10.0, clear_hold_s=4.0)
    a = Autoscaler(ctrl, config=cfg, clock=lambda: t[0], tenant="ctr_team")
    a.notify_fire(_Alert("step_time_p95"))
    import time as _time
    before = _time.time() - 1.0
    assert a.step() == "up"
    ev = a.events[-1]
    assert ev["tenant"] == "ctr_team"
    # wall_s is REAL wall time (journals are read offline, cross-host),
    # not the injected control-loop clock
    assert ev["wall_s"] >= before
    assert ev["t"] == ev["wall_s"]
    # an unscoped autoscaler journals no tenant key at all — absence
    # (not null) is the single-tenant wire shape
    _, _, a2 = _scaler()
    a2.notify_fire(_Alert("step_time_p95"))
    a2.step()
    assert "tenant" not in a2.events[-1]
    assert a2.events[-1]["wall_s"] >= before


def test_journal_mirrors_into_elastic_store():
    ctrl, t, a = _scaler()
    a.notify_fire(_Alert("step_time_p95"))
    a.step()
    keys = ctrl.cluster.store.list_prefix("ps/as-test/scale/")
    assert len(keys) == 1


def test_trainer_np_target_published():
    ctrl = _FakeController()
    t = [0.0]
    cfg = AutoscaleConfig(min_shards=2, max_shards=8, cooldown_up_s=1.0,
                          trainer_np=lambda shards: shards * 2,
                          elastic_job_id="job-x")
    a = Autoscaler(ctrl, config=cfg, clock=lambda: t[0])
    a.notify_fire(_Alert("step_time_p95"))
    assert a.step() == "up"
    mgr = elastic.ElasticManager(ctrl.cluster.store, "job-x", np=2,
                                 host="h0", min_np=1, max_np=64)
    assert mgr.desired_np() == 8                 # 4 shards × 2
    assert mgr.adopt_desired_np() and mgr.np == 8


def test_elastic_adopt_clamps_and_watch_consumes(monkeypatch):
    store = elastic.MemoryStore()
    mgr = elastic.ElasticManager(store, "j2", np=2, host="h0",
                                 min_np=2, max_np=4)
    assert mgr.desired_np() is None
    assert not mgr.adopt_desired_np()
    elastic.set_desired_np(store, "j2", 16)
    assert mgr.adopt_desired_np() and mgr.np == 4  # clamped to max_np
    # watch_once adopts the target, so quorum is judged against it
    store.put(mgr.member_key("h0"), "{}", ttl=10)
    store.put(mgr.member_key("h1"), "{}", ttl=10)
    elastic.set_desired_np(store, "j2", 2)
    assert mgr.watch_once() == elastic.ElasticStatus.HOLD
    assert mgr.np == 2


# ---------------------------------------------------------------------------
# SloWatchdog push subscriptions drive the loop end to end
# ---------------------------------------------------------------------------

def _ring_with(values, t0=1000.0):
    ring = MetricRing()
    for i, v in enumerate(values):
        ring.append({"metrics": {"g": {"type": "gauge", "series": [
            {"labels": {}, "value": v}]}}}, t=t0 + i)
    return ring, t0 + len(values) - 1


def test_watchdog_fire_and_clear_drive_autoscaler():
    ring, now = _ring_with([5.0, 5.0, 5.0])
    wd = SloWatchdog(ring, [SloRule("step_time_p95", "g", kind="threshold",
                                    agg="max", threshold=1.0,
                                    windows=((10.0, 1.0),))])
    ctrl, t, a = _scaler()
    wd.on_fire(a.notify_fire)
    wd.on_clear(a.notify_clear)
    assert [al.rule for al in wd.evaluate(now=now)] == ["step_time_p95"]
    assert a.active_up_rules() == ["step_time_p95"]
    assert a.step() == "up"
    # recovery: fresh ring values under threshold → clear → (hysteresis
    # later lets it come down; the transition plumbing is what we pin)
    for i in range(3):
        ring.append({"metrics": {"g": {"type": "gauge", "series": [
            {"labels": {}, "value": 0.1}]}}}, t=now + 20 + i)
    wd.evaluate(now=now + 22)
    assert a.active_up_rules() == []
