"""Multi-task CTR models (models/multitask.py): ESMM and MMoE learn two
correlated synthetic tasks through the full GPUPS pass lifecycle
(begin_pass → fused multitask steps → end_pass flush) — the PaddleRec
models/multitask family on the sparse PS path."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.metrics.auc import AUC
from paddle_tpu.models.ctr import CtrConfig
from paddle_tpu.models.multitask import ESMM, MMoE, make_multitask_train_step
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache, cache_pull
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

CFG = CtrConfig(num_sparse_slots=4, num_dense=3, embedx_dim=4,
                dnn_hidden=(16, 16))


def _synth(rng, n, vocab=64):
    """Two correlated tasks: click from clicky feasigns; conversion only
    among clicks, driven by a different feasign subset."""
    keys = rng.integers(0, vocab, size=(n, CFG.num_sparse_slots)).astype(np.uint64)
    keys = keys + (np.arange(CFG.num_sparse_slots, dtype=np.uint64) << np.uint64(32))
    dense = rng.normal(size=(n, CFG.num_dense)).astype(np.float32)
    clicky = (keys & np.uint64(0xFFFF)) % np.uint64(5) == 0
    convy = (keys & np.uint64(0xFFFF)) % np.uint64(7) == 0
    click = (clicky.sum(1) + dense[:, 0]
             + rng.normal(scale=0.5, size=n) > 1.0).astype(np.int32)
    conv = ((convy.sum(1) + rng.normal(scale=0.5, size=n) > 1.0)
            & (click == 1)).astype(np.int32)
    labels = np.stack([click, conv], axis=1)
    return keys, dense, labels


@pytest.mark.parametrize("model_cls", [ESMM, MMoE])
def test_multitask_learns_both_tasks(model_cls):
    pt.seed(0)
    rng = np.random.default_rng(0)
    cache_cfg = CacheConfig(capacity=1024, embedx_dim=CFG.embedx_dim,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=CFG.embedx_dim)))
    cache = HbmEmbeddingCache(table, cache_cfg)

    model = model_cls(CFG)
    opt = optimizer.Adam(learning_rate=1e-2)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_multitask_train_step(model, opt, cache_cfg, donate=False)

    keys, dense, labels = _synth(rng, 2048)
    cache.begin_pass(keys.reshape(-1))
    B = 256
    for epoch in range(14):
        for i in range(0, len(keys), B):
            k = keys[i:i + B]
            rows = jnp.asarray(cache.lookup(k.reshape(-1)).reshape(k.shape))
            params, opt_state, cache.state, loss = step(
                params, opt_state, cache.state, rows,
                jnp.asarray(dense[i:i + B]), jnp.asarray(labels[i:i + B]))
    assert np.isfinite(float(loss))

    # evaluate both tasks on the training pass (signal check)
    m_click, m_conv = AUC(), AUC()
    for i in range(0, len(keys), B):
        k = keys[i:i + B]
        rows = jnp.asarray(cache.lookup(k.reshape(-1)).reshape(k.shape))
        emb = cache_pull(cache.state, rows.reshape(-1)).reshape(
            rows.shape[0], CFG.num_sparse_slots, -1)
        out, _ = nn.functional_call(model, params, emb,
                                    jnp.asarray(dense[i:i + B]),
                                    training=False)
        p1, p2 = model_cls.predict(out)
        m_click.update(np.asarray(p1), labels[i:i + B, 0])
        m_conv.update(np.asarray(p2), labels[i:i + B, 1])
    auc_click, auc_conv = m_click.accumulate(), m_conv.accumulate()
    assert auc_click > 0.75, (model_cls.__name__, auc_click)
    # conversion positives are rare (conv ⊆ click) — a softer gate
    assert auc_conv > 0.72, (model_cls.__name__, auc_conv)

    # flush-back keeps the table trained
    cache.end_pass()
    pulled = table.pull_sparse(np.unique(keys), create=False)
    assert np.abs(pulled[:, 2]).sum() > 0
