"""Fleet facade + communicator modes (reference: test_fleet_base.py,
communicator tests; the sync/async/geo mode ladder of
test_dist_fleet_base.py exercised in-process)."""

import os
import time

import numpy as np
import pytest

from paddle_tpu.distributed import (
    DistributedStrategy,
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
    fleet,
)
from paddle_tpu.ps.table import TableConfig


@pytest.fixture(autouse=True)
def fresh_fleet():
    yield
    fleet.stop_worker()
    fleet._inited = False


def push_vals(n, dim=8, show=1.0):
    pv = np.zeros((n, 4 + dim), np.float32)
    pv[:, 1] = show
    pv[:, 3] = 0.1
    return pv


def test_role_maker_from_env(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
    rm = PaddleCloudRoleMaker()
    assert rm.is_worker() and rm.worker_index() == 2 and rm.worker_num() == 4

    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("POD_IP", "10.0.0.2")
    monkeypatch.setenv("PADDLE_PORT", "8001")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "10.0.0.1:8001,10.0.0.2:8001")
    rm2 = PaddleCloudRoleMaker()
    assert rm2.is_server() and rm2.server_index() == 1 and rm2.server_num() == 2


def test_fleet_init_and_tables():
    fleet.init(UserDefinedRoleMaker(role=Role.WORKER))
    assert fleet.is_worker() and not fleet.is_server()
    table = fleet.register_sparse_table(0, TableConfig(shard_num=2))
    fleet.init_server()
    fleet.run_server()
    keys = np.asarray([1, 2, 3], np.uint64)
    vals = fleet.client.pull_sparse(0, keys)
    assert vals.shape[0] == 3
    assert table.size() == 3


def test_sync_communicator_mode():
    fleet.init(UserDefinedRoleMaker(role=Role.WORKER),
               strategy=DistributedStrategy(a_sync=False))
    fleet.register_sparse_table(0, TableConfig(shard_num=2))
    fleet.init_server()
    fleet.init_worker()
    from paddle_tpu.ps.communicator import SyncCommunicator

    assert isinstance(fleet.communicator, SyncCommunicator)
    keys = np.asarray([5, 6], np.uint64)
    fleet.communicator.send_sparse(0, keys, push_vals(2))
    v = fleet.client.pull_sparse(0, keys)
    np.testing.assert_allclose(v[:, 0], 1.0)  # show landed synchronously


def test_async_communicator_merges_and_pushes():
    fleet.init(UserDefinedRoleMaker(role=Role.WORKER),
               strategy=DistributedStrategy(a_sync=True))
    fleet.register_sparse_table(0, TableConfig(shard_num=2))
    fleet.init_server()
    fleet.init_worker()
    from paddle_tpu.ps.communicator import AsyncCommunicator

    assert isinstance(fleet.communicator, AsyncCommunicator)
    keys = np.asarray([7], np.uint64)
    for _ in range(5):
        fleet.communicator.send_sparse(0, keys, push_vals(1))
    fleet.barrier_worker()
    v = fleet.client.pull_sparse(0, keys)
    np.testing.assert_allclose(v[0, 0], 5.0)  # all 5 shows merged+pushed


def test_geo_communicator_pushes_deltas():
    strategy = DistributedStrategy(a_sync=True, geo_sgd_mode=True,
                                   geo_configs={"geo_step": 2})
    fleet.init(UserDefinedRoleMaker(role=Role.WORKER), strategy=strategy)
    fleet.register_geo_table(1, dim=4)
    fleet.init_server()
    fleet.init_worker()
    from paddle_tpu.ps.communicator import GeoCommunicator

    comm = fleet.communicator
    assert isinstance(comm, GeoCommunicator)
    keys = np.asarray([9], np.uint64)
    comm.send_sparse_delta(1, keys, np.ones((1, 4), np.float32))
    comm.send_sparse_delta(1, keys, np.ones((1, 4), np.float32) * 3)  # triggers flush
    k, d = fleet.client.pull_geo(1)
    assert len(k) == 1 and int(k[0]) == 9
    np.testing.assert_allclose(d[0], 2.0)  # mean of the two deltas


def test_save_load_persistables(tmp_path):
    fleet.init(UserDefinedRoleMaker(role=Role.WORKER))
    fleet.register_sparse_table(0, TableConfig(shard_num=2))
    fleet.init_server()
    keys = np.asarray([11, 12], np.uint64)
    fleet.client.push_sparse(0, keys, push_vals(2, show=4.0))
    saved = fleet.save_persistables(str(tmp_path), mode=0)
    assert saved[0] == 2

    # new process simulation: fresh fleet, load back
    fleet._inited = False
    fleet.init(UserDefinedRoleMaker(role=Role.WORKER))
    fleet.register_sparse_table(0, TableConfig(shard_num=2))
    loaded = fleet.load_model(str(tmp_path))
    assert loaded[0] == 2
    v = fleet.client.pull_sparse(0, keys)
    np.testing.assert_allclose(v[:, 0], 4.0)


def test_file_shard_util():
    files = [f"f{i}" for i in range(10)]
    assert fleet.util.get_file_shard(files, 0, 3) == ["f0", "f3", "f6", "f9"]
    assert fleet.util.get_file_shard(files, 2, 3) == ["f2", "f5", "f8"]


def test_daily_ops_cycle_over_ssd(tmp_path):
    """The production daily loop through the FLEET facade over an SSD
    table: train-ish pushes → base save (mode 2, resets delta) → more
    pushes → delta save (mode 1 keeps only freshly-updated features) →
    shrink (decay + delete) → spill. Accessor lifecycle semantics
    (ctr_accessor.cc:55-135) exercised end to end at the facade level."""
    import numpy as np

    from paddle_tpu.distributed.fleet import Fleet
    from paddle_tpu.distributed.role_maker import Role, UserDefinedRoleMaker
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import TableConfig

    f = Fleet().init(UserDefinedRoleMaker(
        current_id=0, role=Role.WORKER, worker_num=1,
        server_endpoints=["127.0.0.1:0"]))
    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         base_threshold=0.0, delta_threshold=0.05,
                         delete_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    tbl = f.register_sparse_table(0, TableConfig(
        table_id=0, shard_num=4, storage="ssd",
        ssd_path=str(tmp_path / "tiers"), accessor_config=acc))
    rng = np.random.default_rng(0)

    def day_push(keys):
        push = np.zeros((len(keys), tbl.accessor.push_dim), np.float32)
        push[:, 1] = 1.0
        push[:, 2] = (rng.random(len(keys)) < 0.5).astype(np.float32)
        push[:, 3:] = rng.normal(0, 0.1, (len(keys), 5)).astype(np.float32)
        tbl.push_sparse(keys, push)

    day1 = np.arange(1, 301, dtype=np.uint64)
    day_push(day1)
    base = f.save_persistables(str(tmp_path / "base"), mode=2)
    assert base[0] == 300  # base save resets delta_score

    day2 = np.arange(201, 401, dtype=np.uint64)  # 100 old + 100 new keys
    day_push(day2)
    delta = f.save_persistables(str(tmp_path / "delta"), mode=1)
    # delta keeps only features whose delta_score regrew since the base
    # save: exactly the 200 keys pushed on day 2
    assert delta[0] == 200

    erased = f.shrink()
    # deterministic with this config: every feature has show=1 (score
    # 0.098 >= delete_threshold 0 after decay) and unseen_days=1 <= 30,
    # so nothing may be erased — a shrink regression that over-deletes
    # fails here (the erase path itself is pinned by the table tests)
    assert erased[0] == 0, erased
    tbl.spill(hot_budget=0)
    assert tbl.stats()["hot_rows"] == 0
    assert tbl.size() == 400
    f.stop_worker()
