"""Dataset pipeline: DataGenerator → MultiSlot text → InMemoryDataset
parse/shuffle/batch round-trip (reference: data_feed_test.cc + the
fleet.data_generator API)."""

import numpy as np
import pytest

from paddle_tpu.data import DataGenerator, InMemoryDataset, QueueDataset, SlotDesc

SLOTS = [
    SlotDesc("click", is_float=False, max_len=1),
    SlotDesc("feat", is_float=False, max_len=3),
    SlotDesc("dense", is_float=True, max_len=2),
]


class Gen(DataGenerator):
    def generate_sample(self, line):
        def reader():
            i = int(line)
            yield [("click", [i % 2]),
                   ("feat", [100 + i, 200 + i]),
                   ("dense", [i * 0.5, i * 0.25])]
        return reader


def _lines(n=32):
    g = Gen()
    return g.run_from_memory([str(i) for i in range(n)])


def test_generator_serializes_multislot():
    lines = _lines(2)
    assert lines[0] == "1 0 2 100 200 2 0.0 0.0"
    assert lines[1] == "1 1 2 101 201 2 0.5 0.25"


def test_load_and_batch():
    ds = InMemoryDataset(SLOTS)
    n = ds.load_from_lines(_lines(32))
    assert n == 32 and ds.parse_errors == 0
    batches = list(ds.batch_iter(8))
    assert len(batches) == 4
    b0 = batches[0]
    vals, lens = b0["feat"]
    assert vals.shape == (8, 3) and vals.dtype == np.uint64
    np.testing.assert_array_equal(lens, np.full(8, 2, np.int32))
    np.testing.assert_array_equal(vals[:, 2], np.zeros(8))  # padded
    np.testing.assert_array_equal(b0["click"][0][:, 0], np.arange(8) % 2)
    np.testing.assert_allclose(batches[1]["dense"][0][0], [8 * 0.5, 8 * 0.25])


def test_local_shuffle_preserves_records():
    ds = InMemoryDataset(SLOTS, seed=7)
    ds.load_from_lines(_lines(32))
    before = ds.pass_feasigns()
    ds.local_shuffle()
    after = ds.pass_feasigns()
    assert not np.array_equal(before, after)  # order changed
    np.testing.assert_array_equal(np.sort(before), np.sort(after))
    # record integrity: click and feat stay aligned per record
    for b in ds.batch_iter(8):
        feats = b["feat"][0][:, 0].astype(np.int64) - 100
        clicks = b["click"][0][:, 0].astype(np.int64)
        np.testing.assert_array_equal(clicks, feats % 2)


def test_file_roundtrip(tmp_path):
    f1, f2 = tmp_path / "part-0", tmp_path / "part-1"
    lines = _lines(20)
    f1.write_text("\n".join(lines[:10]) + "\n")
    f2.write_text("\n".join(lines[10:]) + "\n")
    ds = InMemoryDataset(SLOTS)
    ds.set_filelist([str(tmp_path / "part-*")])
    assert ds.load_into_memory() == 20
    assert ds.num_records == 20

    qs = QueueDataset(SLOTS)
    qs.set_filelist([str(f1), str(f2)])
    got = sum(b["click"][0].shape[0] for b in qs.batch_iter(5))
    assert got == 20


def test_pass_feasigns_feed_cache():
    ds = InMemoryDataset(SLOTS)
    ds.load_from_lines(_lines(16))
    keys = ds.pass_feasigns()
    # click (16) + feat (32) uint64 keys
    assert keys.dtype == np.uint64 and len(keys) == 48


def test_vision_datasets_synthetic_and_idx(tmp_path):
    import gzip
    import struct

    import numpy as np

    from paddle_tpu.data import MNIST, Cifar10, DataLoader

    # synthetic fallback: deterministic, class-dependent
    ds = MNIST(mode="train", synthetic_size=64)
    assert len(ds) == 64
    x, y = ds[np.arange(8)]
    assert x.shape == (8, 1, 28, 28) and y.shape == (8,)
    ds2 = MNIST(mode="train", synthetic_size=64)
    np.testing.assert_array_equal(ds.labels, ds2.labels)
    assert set(np.unique(ds.labels)) <= set(range(10))

    # IDX file loading (the real MNIST on-disk format)
    n, h, w = 5, 28, 28
    imgs = (np.arange(n * h * w) % 255).astype(np.uint8)
    with gzip.open(tmp_path / "train-images-idx3-ubyte.gz", "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, h, w) + imgs.tobytes())
    with gzip.open(tmp_path / "train-labels-idx1-ubyte.gz", "wb") as f:
        f.write(struct.pack(">II", 2049, n) + bytes([0, 1, 2, 3, 4]))
    ds3 = MNIST(mode="train", image_path=str(tmp_path))
    assert len(ds3) == 5 and ds3.labels.tolist() == [0, 1, 2, 3, 4]
    assert ds3.images.max() <= 1.0

    # cifar synthetic + loader integration
    c = Cifar10(mode="test", synthetic_size=32)
    batches = list(DataLoader(c, batch_size=8))
    assert len(batches) == 4 and batches[0][0].shape == (8, 3, 32, 32)


def test_slot_record_binary_roundtrip(tmp_path, rng):
    """save_slot_record/load_slot_record: batches identical to the
    text-parsed pass (the SlotRecord compact binary role,
    data_feed.h:1390), including variable-length slots, and reload works
    both memory-mapped and eager."""
    slots = [SlotDesc("ids", is_float=False, max_len=3),
             SlotDesc("w", is_float=True, max_len=2),
             SlotDesc("label", is_float=True, max_len=1)]
    lines = []
    for _ in range(257):
        n_ids = rng.integers(1, 4)
        ids = " ".join(str(rng.integers(0, 1000)) for _ in range(n_ids))
        n_w = rng.integers(1, 3)
        w = " ".join(f"{rng.normal():.4f}" for _ in range(n_w))
        lines.append(f"{n_ids} {ids} {n_w} {w} 1 {rng.integers(0, 2)}")
    ds = InMemoryDataset(slots, seed=1)
    ds.load_from_lines(lines)
    want = list(ds.batch_iter(64, drop_last=False))
    n = ds.save_slot_record(str(tmp_path / "pass.bin"))
    assert n == 257

    for mmap in (True, False):
        back = InMemoryDataset(slots, seed=1)
        assert back.load_slot_record(str(tmp_path / "pass.bin"), mmap=mmap) == 257
        got = list(back.batch_iter(64, drop_last=False))
        assert len(got) == len(want)
        for a, b in zip(got, want):
            for k in b:
                np.testing.assert_array_equal(a[k][0], b[k][0])
                np.testing.assert_array_equal(a[k][1], b[k][1])
        # shuffle and feasign extraction work on the reloaded store
        back.local_shuffle()
        np.testing.assert_array_equal(
            np.sort(back.pass_feasigns()), np.sort(ds.pass_feasigns()))


def test_slot_record_binary_rejects_bad_file(tmp_path):
    p = tmp_path / "junk.bin"
    p.write_bytes(b"NOTASLOTRECORD")
    ds = InMemoryDataset([SlotDesc("ids", is_float=False, max_len=1)])
    with pytest.raises(Exception):
        ds.load_slot_record(str(p))


def test_slot_record_binary_rejects_truncated(tmp_path, rng):
    slots = [SlotDesc("ids", is_float=False, max_len=1)]
    ds = InMemoryDataset(slots)
    ds.load_from_lines([f"1 {i}" for i in range(100)])
    p = str(tmp_path / "pass.bin")
    ds.save_slot_record(p)
    import os
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) - 64)
    with pytest.raises(Exception, match="truncated"):
        InMemoryDataset(slots).load_slot_record(p)


def _record_multiset(ds):
    """Canonical multiset of records for cross-partition comparison."""
    recs = []
    st = ds._store
    for i in range(st.num_records):
        recs.append(st.extract_bytes(np.asarray([i])))
    return sorted(recs)


def test_global_shuffle_exchanges_records(rng):
    """Two simulated workers with disjoint record halves: after the
    global shuffle the records are REDISTRIBUTED (data moved between
    workers, none lost or duplicated) — the GlooWrapper data_set.cc
    global-shuffle semantics, not just an index partition."""
    slots = [SlotDesc("ids", is_float=False, max_len=2),
             SlotDesc("w", is_float=True, max_len=1)]

    def lines(lo, hi):
        out = []
        for i in range(lo, hi):
            n = 1 + (i % 2)
            ids = " ".join(str(100 * i + j) for j in range(n))
            out.append(f"{n} {ids} 1 {i / 7:.4f}")
        return out

    workers = []
    for w, (lo, hi) in enumerate([(0, 60), (60, 130)]):
        ds = InMemoryDataset(slots, seed=w)
        ds.load_from_lines(lines(lo, hi))
        workers.append(ds)
    before = sorted(_record_multiset(workers[0]) + _record_multiset(workers[1]))

    # loopback transport: run worker 0's exchange, capturing its outgoing
    # blobs; then worker 1's with the cross-wired blobs
    sent = {}

    def exchange_for(w):
        def exchange(blobs):
            sent[w] = blobs
            if w == 0:
                return [blobs[0], b""]  # worker 1's blob delivered later
            return [sent[0][1], blobs[1]]
        return exchange

    workers[0].global_shuffle(exchange=exchange_for(0), worker_id=0, worker_num=2)
    workers[1].global_shuffle(exchange=exchange_for(1), worker_id=1, worker_num=2)
    # deliver worker 1's outbound partition to worker 0 (post-hoc: the
    # loopback can't block like a real transport)
    workers[0]._store.ingest_bytes(sent[1][0])

    after = sorted(_record_multiset(workers[0]) + _record_multiset(workers[1]))
    assert after == before  # no loss, no duplication
    # data actually crossed the worker boundary in both directions
    assert len(sent[0][1]) > 4 and len(sent[1][0]) > 4
    assert workers[0].num_records + workers[1].num_records == 130


def test_global_shuffle_empty_partitions():
    """Few records over many workers: empty destination partitions and
    an empty own-partition must not crash (regression: the vectorized
    gather broke on zero-length index sets)."""
    slots = [SlotDesc("ids", is_float=False, max_len=1)]
    ds = InMemoryDataset(slots, seed=3)
    ds.load_from_lines(["1 1", "1 2", "1 3"])

    st = ds._store
    assert st.extract_bytes(np.zeros(0, np.int64)) is not None
    got = []

    def exchange(blobs):
        got.append(blobs)
        return [blobs[0]] + [b""] * 7  # peers send nothing back

    ds.global_shuffle(exchange=exchange, worker_id=0, worker_num=8)
    # survivors = records whose random destination was worker 0
    assert 0 <= ds.num_records <= 3
    # and an explicit keep-nothing works
    st.keep_only(np.zeros(0, np.int64))
    assert st.num_records == 0


def test_pipe_command_preprocessing(tmp_path):
    """DataFeed pipe_command parity: raw logs stream through a shell
    preprocessor; the dataset parses the command's output. A failing
    command surfaces loudly with its stderr."""
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    slots = [SlotDesc("a", is_float=False, max_len=1),
             SlotDesc("label", is_float=True, max_len=1)]
    raw = tmp_path / "raw.txt"
    # raw format: "id label" — the pipe turns it into MultiSlot lines
    raw.write_text("7 1\n9 0\n")
    ds = InMemoryDataset(slots)
    ds.set_filelist([str(raw)])
    ds.set_pipe_command("awk '{print \"1 \" $1 \" 1 \" $2}'")
    n = ds.load_into_memory()
    assert n == 2
    batch = next(ds.batch_iter(2, drop_last=False))
    np.testing.assert_array_equal(batch["a"][0][:, 0], [7, 9])
    np.testing.assert_array_equal(batch["label"][0][:, 0], [1.0, 0.0])

    ds2 = InMemoryDataset(slots)
    ds2.set_filelist([str(raw)])
    ds2.set_pipe_command("exit 3")
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="pipe_command failed"):
        ds2.load_into_memory()

    # None restores the direct read path
    ok = tmp_path / "ok.txt"
    ok.write_text("1 7 1 1\n")
    ds3 = InMemoryDataset(slots)
    ds3.set_filelist([str(ok)])
    ds3.set_pipe_command(None)
    assert ds3.load_into_memory() == 1
