"""Pallas flash attention: fwd/bwd parity vs the einsum reference
(interpret mode on CPU; the same kernels compile on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.flash_attention import (flash_attention,
                                            flash_attention_with_lse)
from paddle_tpu.parallel.ring_attention import local_attention


def _qkv(rng, B=2, L=64, H=2, D=16):
    mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_fwd_matches_reference(causal):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    ref = local_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True, precision="highest")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, B=1, L=32, H=2, D=8)

    def ref_loss(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=causal) ** 2)

    def flash_loss(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16,
                              interpret=True, precision="highest")
        return jnp.sum(out ** 2)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_fl, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4, err_msg=name)


def test_unaligned_shapes_padded():
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, B=1, L=50, H=3, D=12)
    ref = local_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          interpret=True, precision="highest")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_offsets_shift_causal_mask():
    """q_offset/k_offset reproduce a cp shard's causal mask: rows of the
    second half attending over the full sequence."""
    rng = np.random.default_rng(3)
    B, L, H, D = 1, 32, 2, 8
    q, k, v = _qkv(rng, B=B, L=L, H=H, D=D)
    full = local_attention(q, k, v, causal=True)
    # shard: second half of queries vs first half of keys (fully visible)
    q2 = q[:, L // 2:]
    out_lo, lse_lo = flash_attention_with_lse(
        q2, k[:, :L // 2], v[:, :L // 2], causal=True,
        q_offset=L // 2, k_offset=0, block_q=16, block_k=16, interpret=True, precision="highest")
    out_hi, lse_hi = flash_attention_with_lse(
        q2, k[:, L // 2:], v[:, L // 2:], causal=True,
        q_offset=L // 2, k_offset=L // 2, block_q=16, block_k=16,
        interpret=True, precision="highest")
    # lse-merge the two halves (the ring-attention combine)
    m = jnp.maximum(lse_lo, lse_hi)
    w_lo = jnp.exp(lse_lo - m)[..., None]
    w_hi = jnp.exp(lse_hi - m)[..., None]
    merged = (out_lo * w_lo + out_hi * w_hi) / (w_lo + w_hi)
    np.testing.assert_allclose(np.asarray(merged),
                               np.asarray(full[:, L // 2:]),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="pallas interpret mode under shard_map lacks vma "
                           "propagation (jax hlo_interpreter dynamic_slice); "
                           "compiled mosaic path is exercised on TPU")
@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_serial(causal):
    """Flash-kernel ring over a cp mesh == full attention (interpret mode)."""
    import os
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.core import mesh as mesh_mod
    from paddle_tpu.parallel.ring_attention import ring_flash_attention

    rng = np.random.default_rng(4)
    B, L, H, D = 1, 32, 2, 8
    q, k, v = _qkv(rng, B=B, L=L, H=H, D=D)
    full = local_attention(q, k, v, causal=causal)
    mesh = mesh_mod.make_mesh({"dp": 2, "cp": 4})

    def f(q, k, v):
        return ring_flash_attention(q, k, v, axis="cp", causal=causal)

    spec = P(None, "cp", None, None)
    out = shard_map(f, mesh=mesh, in_specs=(spec,) * 3, out_specs=spec)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=2e-2, atol=2e-3)


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="see test_ring_flash_matches_serial")
def test_ring_flash_grads_finite():
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.core import mesh as mesh_mod
    from paddle_tpu.parallel.ring_attention import ring_flash_attention

    rng = np.random.default_rng(5)
    B, L, H, D = 1, 32, 2, 8
    q, k, v = _qkv(rng, B=B, L=L, H=H, D=D)
    mesh = mesh_mod.make_mesh({"dp": 2, "cp": 4})
    spec = P(None, "cp", None, None)

    def loss(q, k, v):
        def f(q, k, v):
            out = ring_flash_attention(q, k, v, axis="cp", causal=True)
            return jax.lax.psum(jnp.sum(out ** 2), "cp")
        return shard_map(f, mesh=mesh, in_specs=(spec,) * 3, out_specs=P())(q, k, v)

    # parity oracle: einsum ring == flash ring gradients
    def loss_ref(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        assert np.isfinite(np.asarray(a)).all(), name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-2, atol=5e-3, err_msg=name)
