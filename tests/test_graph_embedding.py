"""DeepWalk skip-gram over the sparse PS path (models/graph_embedding):
the GraphDataGenerator → sparse-training loop of the reference's graph
stack (data_feed gpu_graph mode + graph_gpu_ps_table walks feeding
PullSparse/PushSparseGrad) as one jitted step — walks, window pairing,
negative sampling, pull, SGNS, push, all in-graph."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.models.graph_embedding import (DeepWalkConfig,
                                               init_node_embeddings,
                                               link_prediction_auc,
                                               make_deepwalk_train_step,
                                               node_embeddings, tag_center,
                                               tag_context)
from paddle_tpu.ops.device_graph import DeviceGraph
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.graph_table import GraphTable
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig


def _two_clique_graph(k=10, bridge=1):
    """Two k-cliques (nodes 0..k-1 and k..2k-1) joined by `bridge`
    edges — walks mix within communities, rarely across."""
    g = GraphTable(shard_num=4, seed=0)
    src, dst = [], []
    for base in (0, k):
        for i in range(k):
            for j in range(k):
                if i != j:
                    src.append(base + i)
                    dst.append(base + j)
    for b in range(bridge):
        src += [b, k + b]
        dst += [k + b, b]
    g.add_graph_node(list(range(2 * k)))
    g.add_edges(src, dst)
    return g


def _setup(rng, k=10, dim=16):
    g = _two_clique_graph(k)
    nodes = np.arange(2 * k, dtype=np.uint64)
    dgraph = DeviceGraph.from_graph_table(g, max_deg=32)

    sgd = SGDRuleConfig(learning_rate=0.3, initial_g2sum=1.0)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0, sgd=sgd)
    table = MemorySparseTable(TableConfig(shard_num=4, accessor_config=acc))
    cache_cfg = CacheConfig(capacity=1 << 8, embedx_dim=dim,
                            embedx_threshold=0.0, sgd=sgd)
    init_node_embeddings(table, nodes, rng, scale=0.1)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    cache.begin_pass(np.concatenate([tag_center(nodes), tag_context(nodes)]))
    return g, dgraph, table, cache, cache_cfg, nodes


def test_deepwalk_learns_communities(rng):
    k, dim = 10, 16
    g, dgraph, table, cache, cache_cfg, nodes = _setup(rng, k, dim)
    cfg = DeepWalkConfig(walk_len=6, window=2, negatives=4, embed_dim=dim)
    step = make_deepwalk_train_step(dgraph, cache_cfg, cfg,
                                    pool_lo=nodes.astype(np.uint32))
    ms = cache.device_map.state

    key = jax.random.PRNGKey(0)
    losses = []
    for it in range(60):
        key, k1, k2 = jax.random.split(key, 3)
        starts = jnp.asarray(
            jax.random.randint(k1, (64,), 0, 2 * k), jnp.uint32)
        cache.state, loss = step(cache.state, ms, starts, k2)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

    # link prediction: intra-clique edges vs cross-clique non-edges
    intra = np.array([[i, j] for i in range(k) for j in range(k) if i != j]
                     + [[k + i, k + j] for i in range(k) for j in range(k)
                        if i != j])
    cross = np.array([[i, k + j] for i in range(2, k) for j in range(2, k)])
    auc = link_prediction_auc(cache, intra, cross)
    assert auc > 0.8, auc

    # flush-back: embeddings survive the pass lifecycle
    cache.end_pass()
    cache.begin_pass(np.concatenate([tag_center(nodes), tag_context(nodes)]))
    auc2 = link_prediction_auc(cache, intra, cross)
    np.testing.assert_allclose(auc2, auc, atol=1e-6)


def test_deepwalk_dead_end_pairs_masked(rng):
    """An isolated node's walk freezes at the start; its pairs must be
    fully masked — a push from a frozen self-pair would train
    center==context and corrupt the table."""
    g = GraphTable(shard_num=2, seed=0)
    g.add_graph_node([0, 1, 2])
    g.add_edges([0, 1], [1, 0])  # node 2 isolated
    nodes = np.arange(3, dtype=np.uint64)
    dgraph = DeviceGraph.from_graph_table(g, max_deg=4)
    dim = 8
    # Adam rules: a spurious zero-delta update would still decay m/v
    # and advance the beta powers — so this test catches padded or
    # frozen pairs leaking into the push as STATE corruption, not just
    # weight movement
    sgd = SGDRuleConfig(learning_rate=0.2)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0, sgd=sgd,
                         embed_sgd_rule="adam", embedx_sgd_rule="adam")
    table = MemorySparseTable(TableConfig(shard_num=2, accessor_config=acc))
    cache_cfg = CacheConfig(capacity=1 << 6, embedx_dim=dim,
                            embedx_threshold=0.0, sgd=sgd,
                            embed_rule="adam", embedx_rule="adam")
    init_node_embeddings(table, nodes, rng, scale=0.1)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    cache.begin_pass(np.concatenate([tag_center(nodes), tag_context(nodes)]))
    before = node_embeddings(cache, np.array([2], np.uint64)).copy()

    cfg = DeepWalkConfig(walk_len=4, window=2, negatives=0, embed_dim=dim)
    step = make_deepwalk_train_step(dgraph, cache_cfg, cfg,
                                    pool_lo=nodes.astype(np.uint32))
    state_before = {k: np.asarray(v).copy() for k, v in cache.state.items()}
    starts = jnp.asarray(np.array([2, 2, 2, 2], np.uint32))
    cache.state, loss = step(cache.state, cache.device_map.state, starts,
                             jax.random.PRNGKey(1))
    after = node_embeddings(cache, np.array([2], np.uint64))
    np.testing.assert_array_equal(before, after)
    # node 2's walks froze at the start: with every pair masked, NO row
    # may advance (under Adam even a zero-delta touch decays state)
    for k, v in cache.state.items():
        np.testing.assert_array_equal(np.asarray(v), state_before[k],
                                      err_msg=k)


def test_deepwalk_over_ssd_table(rng, tmp_path):
    """The graph-embedding loop composes with the beyond-RAM tier:
    deepwalk trains over an SSD-backed table (drop-in for
    MemorySparseTable), embeddings survive the flush→reload cycle."""
    from paddle_tpu.ps.table import make_sparse_table

    k, dim = 6, 8
    g = _two_clique_graph(k)
    nodes = np.arange(2 * k, dtype=np.uint64)
    dgraph = DeviceGraph.from_graph_table(g, max_deg=16)
    sgd = SGDRuleConfig(learning_rate=0.3, initial_g2sum=1.0)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0, sgd=sgd)
    table = make_sparse_table(TableConfig(
        shard_num=2, accessor_config=acc, storage="ssd",
        ssd_path=str(tmp_path / "ssd")))
    cache_cfg = CacheConfig(capacity=1 << 7, embedx_dim=dim,
                            embedx_threshold=0.0, sgd=sgd)
    init_node_embeddings(table, nodes, rng, scale=0.1)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    cache.begin_pass(np.concatenate([tag_center(nodes),
                                     tag_context(nodes)]))

    cfg = DeepWalkConfig(walk_len=4, window=2, negatives=2, embed_dim=dim)
    step = make_deepwalk_train_step(dgraph, cache_cfg, cfg,
                                    pool_lo=nodes.astype(np.uint32))
    ms = cache.device_map.state
    key = jax.random.PRNGKey(0)
    losses = []
    for it in range(30):
        key, k1, k2 = jax.random.split(key, 3)
        starts = jnp.asarray(
            jax.random.randint(k1, (32,), 0, 2 * k), jnp.uint32)
        cache.state, loss = step(cache.state, ms, starts, k2)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    before = node_embeddings(cache, nodes[:4]).copy()
    cache.end_pass()

    # reload through the SSD tier: a fresh pass serves the same values
    cache.begin_pass(np.concatenate([tag_center(nodes),
                                     tag_context(nodes)]))
    after = node_embeddings(cache, nodes[:4])
    np.testing.assert_allclose(after, before, rtol=1e-6, atol=1e-7)
