"""Cross-process fleet executor (VERDICT r3 #6): interceptor messages
ride the RemoteMessageBus framed-TCP channel between two REAL
subprocesses — the reference's brpc MessageBus role (message_bus.cc,
carrier.h:49). Source on rank 0; compute + sink on rank 1; the
DATA_IS_USELESS credit returns cross the wire, so the buffer_size
window throttles the source across the process boundary (asserted by
timing: a 1-credit edge into a slow compute forces the source's sends
to serialize behind the consumer)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import json
    import sys
    import time

    from paddle_tpu.distributed.fleet_executor import (
        Carrier, RemoteMessageBus, TaskNode)

    rank = int(sys.argv[1])
    port0, port1 = int(sys.argv[2]), int(sys.argv[3])
    N = 6

    send_times = []

    def stamp(i):
        send_times.append(time.monotonic())
        return i

    def slow_double(x):
        time.sleep(0.05)
        return 2 * x

    # topology (shared by both ranks): source(0)@rank0 ->[credit 1]->
    # compute(1)@rank1 -> sink(2)@rank1
    nodes = [
        TaskNode(task_id=0, role="source", fn=stamp, max_run_times=N,
                 downstreams=[(1, 1)]),
        TaskNode(task_id=1, role="compute", fn=slow_double,
                 max_run_times=N, upstreams=[0], downstreams=[(2, 2)]),
        TaskNode(task_id=2, role="sink", max_run_times=N, upstreams=[1]),
    ]
    placement = {0: 0, 1: 1, 2: 1}
    bus = RemoteMessageBus(
        rank, {0: ("127.0.0.1", port0), 1: ("127.0.0.1", port1)}, placement)
    local = [t for t, r in placement.items() if r == rank]
    carrier = Carrier(nodes, feeds={0: list(range(N))}, bus=bus,
                      local_ids=local)
    carrier.start()
    carrier.wait(timeout=60.0)
    if rank == 1:
        (sink,) = carrier.sinks
        assert sink.outputs == [2 * i for i in range(N)], sink.outputs
    else:
        # credit window 1 + 0.05s compute: send i+1 can only leave after
        # send i's DATA_IS_USELESS returned over the wire, so the sends
        # must span >= (N-2) compute periods (generous margin) — this IS
        # the cross-process backpressure assertion
        span = send_times[-1] - send_times[0]
        assert len(send_times) == N, send_times
        assert span >= 0.05 * (N - 2), f"no backpressure: span={span:.3f}s"
    bus.close()
    print("WORKER_OK", rank, flush=True)
""")


@pytest.mark.slow
def test_two_process_fleet_executor(tmp_path):
    ports = []
    socks = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = []
    for r in range(2):
        env = dict(os.environ, PYTHONPATH=repo + os.pathsep +
                   os.environ.get("PYTHONPATH", ""))
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), str(ports[0]),
             str(ports[1])],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=120)
            assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
            assert f"WORKER_OK {r}" in out, out[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_remote_bus_single_process_loopback():
    """Two RemoteMessageBus instances in one process (distinct ports)
    route a full source->compute->sink pipeline — fast non-slow
    coverage of the wire path."""
    from paddle_tpu.distributed.fleet_executor import (
        Carrier, RemoteMessageBus, TaskNode)

    ports = []
    for _ in range(2):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    addrs = {0: ("127.0.0.1", ports[0]), 1: ("127.0.0.1", ports[1])}
    placement = {0: 0, 1: 1, 2: 1}
    N = 4
    nodes = [
        TaskNode(task_id=0, role="source", max_run_times=N,
                 downstreams=[(1, 2)]),
        TaskNode(task_id=1, role="compute", fn=lambda x: x + 10,
                 max_run_times=N, upstreams=[0], downstreams=[(2, 2)]),
        TaskNode(task_id=2, role="sink", max_run_times=N, upstreams=[1]),
    ]
    bus0 = RemoteMessageBus(0, addrs, placement)
    bus1 = RemoteMessageBus(1, addrs, placement)
    c0 = Carrier(nodes, feeds={0: list(range(N))}, bus=bus0, local_ids=[0])
    c1 = Carrier(nodes, bus=bus1, local_ids=[1, 2])
    c1.start()
    c0.start()
    c1.wait(timeout=30.0)
    c0.wait(timeout=30.0)
    (sink,) = c1.sinks
    assert sink.outputs == [10, 11, 12, 13]
    bus0.close()
    bus1.close()


def _free_ports(n):
    ports = []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    return ports


def test_remote_bus_hmac_roundtrip():
    """With a shared secret, frames carry an HMAC tag and the pipeline
    works unchanged (tag verified before unpickling)."""
    from paddle_tpu.distributed.fleet_executor import (
        Carrier, RemoteMessageBus, TaskNode)

    ports = _free_ports(2)
    addrs = {0: ("127.0.0.1", ports[0]), 1: ("127.0.0.1", ports[1])}
    placement = {0: 0, 1: 1, 2: 1}
    N = 3
    nodes = [
        TaskNode(task_id=0, role="source", max_run_times=N,
                 downstreams=[(1, 2)]),
        TaskNode(task_id=1, role="compute", fn=lambda x: x * 3,
                 max_run_times=N, upstreams=[0], downstreams=[(2, 2)]),
        TaskNode(task_id=2, role="sink", max_run_times=N, upstreams=[1]),
    ]
    secret = b"job-shared-key"
    bus0 = RemoteMessageBus(0, addrs, placement, secret=secret)
    bus1 = RemoteMessageBus(1, addrs, placement, secret=secret)
    c0 = Carrier(nodes, feeds={0: list(range(N))}, bus=bus0, local_ids=[0])
    c1 = Carrier(nodes, bus=bus1, local_ids=[1, 2])
    c1.start()
    c0.start()
    c1.wait(timeout=30.0)
    c0.wait(timeout=30.0)
    assert c1.sinks[0].outputs == [0, 3, 6]
    bus0.close()
    bus1.close()


def test_remote_bus_hmac_rejects_unauthenticated():
    """A raw connection pushing an unsigned pickle frame at a
    secret-protected listener gets dropped BEFORE deserialization: a
    poison payload's reducer never runs and the bus stays healthy."""
    import pickle
    import struct
    import time

    from paddle_tpu.distributed.fleet_executor import (
        InterceptorMessage, MessageType, RemoteMessageBus)

    (port,) = _free_ports(1)
    bus = RemoteMessageBus(0, {0: ("127.0.0.1", port)}, {0: 0},
                           secret=b"right-key")
    inbox = bus.register(7)
    hits = []

    class Poison:
        def __reduce__(self):
            return (hits.append, ("executed",))

    msg = InterceptorMessage(1, 7, MessageType.DATA_IS_READY, Poison())
    body = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    with socket.create_connection(("127.0.0.1", port), timeout=5.0) as s:
        s.sendall(struct.pack("<I", len(body)) + body)  # no HMAC tag
        # server closes on auth failure; recv returns b"" on close
        s.settimeout(5.0)
        assert s.recv(1) == b""
    time.sleep(0.1)
    assert hits == [], "unauthenticated frame was deserialized!"
    assert inbox.empty()
    bus.close()


def test_remote_bus_mismatched_secrets_fail_closed():
    """Two ranks configured with DIFFERENT secrets: every cross-rank
    frame fails verification and is dropped before unpickling — the
    receiving inbox stays empty (fail CLOSED, no partial trust), and
    the receiver records nothing as delivered."""
    import time

    from paddle_tpu.distributed.fleet_executor import (
        InterceptorMessage, MessageType, RemoteMessageBus)

    ports = _free_ports(2)
    addrs = {0: ("127.0.0.1", ports[0]), 1: ("127.0.0.1", ports[1])}
    placement = {0: 0, 7: 1}
    bus0 = RemoteMessageBus(0, addrs, placement, secret=b"key-A")
    bus1 = RemoteMessageBus(1, addrs, placement, secret=b"key-B")
    inbox = bus1.register(7)
    bus0.send(InterceptorMessage(0, 7, MessageType.DATA_IS_READY, "x"))
    time.sleep(0.3)
    assert inbox.empty()  # dropped at the HMAC check, never delivered
    bus0.close()
    bus1.close()


def test_carrier_stop_fast_on_dead_peer():
    """Carrier.stop over a never-started peer must not spin the
    connect-retry loop for connect_timeout per rank (advisor r4): the
    best-effort one-shot connect bounds it to ~2s."""
    import time

    from paddle_tpu.distributed.fleet_executor import (
        Carrier, RemoteMessageBus, TaskNode)

    ports = _free_ports(2)
    addrs = {0: ("127.0.0.1", ports[0]), 1: ("127.0.0.1", ports[1])}
    placement = {0: 0, 1: 1}
    nodes = [
        TaskNode(task_id=0, role="source", max_run_times=1,
                 downstreams=[(1, 1)]),
        TaskNode(task_id=1, role="sink", max_run_times=1, upstreams=[0]),
    ]
    # long connect_timeout: the OLD path would spin ~30s on the dead rank
    bus = RemoteMessageBus(0, addrs, placement, connect_timeout=30.0)
    carrier = Carrier(nodes, feeds={0: [0]}, bus=bus, local_ids=[0])
    t0 = time.monotonic()
    carrier.stop()  # rank 1 never started
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"STOP broadcast stalled {elapsed:.1f}s"
    bus.close()


def test_deliver_unknown_interceptor_logs_and_closes():
    """A frame for an id that never registers is logged + recorded on
    the bus and the connection is closed (not a silent daemon-thread
    death)."""
    import pickle
    import struct
    import time

    from paddle_tpu.distributed.fleet_executor import (
        InterceptorMessage, MessageType, RemoteMessageBus)

    (port,) = _free_ports(1)
    bus = RemoteMessageBus(0, {0: ("127.0.0.1", port)}, {0: 0},
                           register_grace=0.5)
    msg = InterceptorMessage(1, 999, MessageType.DATA_IS_READY, None)
    body = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
    with socket.create_connection(("127.0.0.1", port), timeout=5.0) as s:
        s.sendall(struct.pack("<I", len(body)) + body)
        s.settimeout(20.0)
        # after the (shortened) grace the server closes the connection
        assert s.recv(1) == b""
    deadline = time.monotonic() + 5.0
    while bus.last_error is None and time.monotonic() < deadline:
        time.sleep(0.05)
    assert bus.last_error is not None and "999" in bus.last_error
    bus.close()
