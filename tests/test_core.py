import jax
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core import flags, mesh
from paddle_tpu.core import enforce as _unused  # noqa: F401
from paddle_tpu.core import enforce_module as enforce


def test_flags_define_get_set():
    flags.define_flag("test_only_flag", 3, "test")
    assert pt.get_flags("test_only_flag")["test_only_flag"] == 3
    pt.set_flags({"test_only_flag": 7})
    assert pt.get_flags(["test_only_flag"])["test_only_flag"] == 7
    with pytest.raises(KeyError):
        pt.set_flags({"nonexistent_flag_xyz": 1})


def test_flags_type_coercion():
    flags.define_flag("test_bool_flag", False)
    pt.set_flags({"test_bool_flag": "true"})
    assert pt.get_flags("test_bool_flag")["test_bool_flag"] is True


def test_enforce_helpers():
    enforce.enforce_eq(1, 1)
    with pytest.raises(enforce.InvalidArgumentError):
        enforce.enforce_eq(1, 2)
    with pytest.raises(enforce.PreconditionNotMetError):
        enforce.enforce(False, "nope")
    assert enforce.enforce_not_none(5) == 5


def test_places():
    p = pt.CPUPlace()
    assert p.jax_device().platform == "cpu"
    assert pt.core.device_count("cpu") == 8  # virtual devices from conftest
    with pytest.raises(enforce.InvalidArgumentError):
        pt.core.CUDAPlace(0)


def test_mesh_construction():
    m = mesh.make_mesh({"dp": 2, "mp": 4})
    assert m.shape == {"dp": 2, "mp": 4}
    with pytest.raises(enforce.InvalidArgumentError):
        mesh.make_mesh({"dp": 3})
    hm = mesh.make_hybrid_mesh(dp=2, mp=4)
    assert hm.shape["dp"] == 2 and hm.shape["mp"] == 4 and hm.shape["pp"] == 1


def test_use_mesh_context():
    m = mesh.make_mesh({"dp": 8})
    assert mesh.current_mesh() is None
    with mesh.use_mesh(m):
        assert mesh.current_mesh() is m
    assert mesh.current_mesh() is None


def test_nan_inf_checker():
    from paddle_tpu.core.nan_inf import check_numerics, count_nonfinite

    good = {"a": np.ones(4, np.float32)}
    check_numerics(good)
    bad = {"a": np.array([1.0, np.nan], np.float32)}
    with pytest.raises(enforce.PreconditionNotMetError):
        check_numerics(bad)
    assert int(count_nonfinite(bad)) == 1
    assert int(count_nonfinite(good)) == 0


def test_profiler_host_events():
    from paddle_tpu.core import profiler

    profiler.reset_host_events()
    with profiler.RecordEvent("unit_scope"):
        pass
    stats = profiler.host_event_stats()
    assert stats["unit_scope"]["count"] == 1


def test_profiler_timed_gate_and_retry(monkeypatch):
    """core.profiler.timed: measurements below the fetch-latency noise
    floor retry with 5x iters and ultimately fail LOUDLY (a garbage
    number in a committed artifact is worse than an error)."""
    import jax.numpy as jnp
    import pytest

    from paddle_tpu.core import profiler

    # a real (cheap) op on CPU clears the ~µs fetch latency easily
    t, out = profiler.timed(lambda x: x + 1, jnp.zeros((64,)), iters=3)
    assert t > 0 and float(out[0]) == 1.0

    # force a huge synthetic fetch latency: the op can never clear it
    real_fetch = profiler.fetch_sync
    calls = {"n": 0}

    def slow_fetch(x):
        calls["n"] += 1
        import time as _t
        _t.sleep(0.05)
        return real_fetch(x)

    monkeypatch.setattr(profiler, "fetch_sync", slow_fetch)
    with pytest.raises(RuntimeError, match="noise floor"):
        profiler.timed(lambda x: x + 1, jnp.zeros((4,)), iters=1)
    assert calls["n"] >= 3 * 4  # warmup+3 lat samples+final, per retry
