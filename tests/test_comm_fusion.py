"""Fused-bucket, block-quantized dense-DP gradient collectives
(distributed/comm_fusion.py + the pre-reduction meta-optimizer contract
in meta_optimizers.py + parallel/spmd.py's fused step).

Acceptance gates covered here:
- fused fp32 bucketed reduction is BIT-IDENTICAL to the per-tensor psum
  baseline on the LeNet and DeepFM dense paths (8-device CPU mesh);
- int8 + error feedback trains LeNet (synthetic MNIST-shaped data) to
  within 0.5% of fp32 accuracy;
- the compiled step's dp gradient collectives number ≤ the configured
  bucket count, and int8 moves ≥3.5× fewer collective bytes than fp32
  (tools/hlo_bytes.py on the post-optimization HLO);
- FP16AllReduce routes bf16 onto the WIRE (collective element type in
  the pre-optimization HLO — XLA CPU float-normalization re-widens
  bf16 collectives post-opt; TPU executes them natively);
- composition DGC → fp16_allreduce → localsgd → gradient_merge under
  the pre-reduction contract, incl. GradientMerge's held steps skipping
  the collective entirely (in the HLO conditional).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn, optimizer
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.distributed import DistributedStrategy
from paddle_tpu.distributed.comm_fusion import (CommFusionConfig,
                                                DpGradReducer, build_layout)
from paddle_tpu.distributed.comm_fusion import (_dequant_int8, _pack_bucket,
                                                _quant_int8, _unpack_bucket)
from paddle_tpu.distributed.meta_optimizers import (
    DGCMomentumOptimizer, FP16AllReduceOptimizer, FusedAllReduceOptimizer,
    GradientMergeOptimizer, LocalSGDOptimizer, apply_strategy)
from paddle_tpu.models import LeNet
from paddle_tpu.models.ctr import CtrConfig, DeepFM
from paddle_tpu.parallel import SpmdTrainer

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import hlo_bytes  # noqa: E402


# ---------------------------------------------------------------------------
# layout + quantization units
# ---------------------------------------------------------------------------

def test_layout_caps_dtype_groups_and_cache():
    meta = tuple([((64, 64), "float32")] * 6 + [((128,), "int32")] * 2)
    cfg = CommFusionConfig(bucket_mb=0.02, max_buckets=5)  # 20KiB cap
    layout = build_layout(meta, 4, cfg)
    assert len(layout.buckets) <= 5
    # per-dtype buckets: no bucket mixes dtypes
    for b in layout.buckets:
        assert len({s.dtype for s in b.slots}) == 1
    # every leaf appears exactly once
    seen = sorted(s.index for b in layout.buckets for s in b.slots)
    assert seen == list(range(len(meta)))
    # cache: identical request returns the identical object
    assert build_layout(meta, 4, cfg) is layout
    assert build_layout(meta, 8, cfg) is not layout


def test_layout_grows_cap_to_respect_max_buckets():
    meta = tuple([((1024,), "float32")] * 64)  # 4KiB each
    cfg = CommFusionConfig(bucket_mb=0.001, max_buckets=3)  # 1KiB cap
    layout = build_layout(meta, 2, cfg)
    assert len(layout.buckets) <= 3


def test_layout_terminates_when_dtypes_exceed_max_buckets():
    """One bucket per dtype group is the floor: more distinct dtypes
    than max_buckets must yield that floor, not an infinite cap-growth
    loop (hung trainer construction before the fix)."""
    meta = (((4,), "float32"), ((4,), "bfloat16"), ((4,), "int32"))
    layout = build_layout(meta, 2, CommFusionConfig(max_buckets=1))
    assert len(layout.buckets) == 3


def test_pack_unpack_roundtrip_odd_shapes():
    rng = np.random.default_rng(0)
    leaves = [jnp.asarray(rng.normal(size=s).astype(np.float32))
              for s in [(3, 5), (7,), (1,), (2, 3, 4)]]
    meta = tuple((tuple(x.shape), "float32") for x in leaves)
    layout = build_layout(meta, 4, CommFusionConfig())
    out = [None] * len(leaves)
    for b in layout.buckets:
        buf = _pack_bucket(leaves, b, 4)
        assert buf.shape == (4, b.seg_total)
        for s, leaf in zip(b.slots, _unpack_bucket(buf, b, 4)):
            out[s.index] = leaf
    for a, b_ in zip(leaves, out):
        assert np.array_equal(np.asarray(a), np.asarray(b_))


def test_pack_unpack_zero_size_leaf():
    """0-element leaves get seg_len 0 and pack/unpack as empty slices
    (the `or 1` sizing previously produced a ragged pad and a
    trace-time reshape error)."""
    leaves = [jnp.ones((3, 2), jnp.float32), jnp.zeros((0,), jnp.float32)]
    meta = tuple((tuple(x.shape), "float32") for x in leaves)
    layout = build_layout(meta, 4, CommFusionConfig())
    out = [None] * len(leaves)
    for b in layout.buckets:
        buf = _pack_bucket(leaves, b, 4)
        for s, leaf in zip(b.slots, _unpack_bucket(buf, b, 4)):
            out[s.index] = leaf
    assert out[1].shape == (0,)
    assert np.array_equal(np.asarray(out[0]), np.asarray(leaves[0]))


def test_int8_block_quant_error_bound():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 256)).astype(np.float32) * 10)
    q, sc = _quant_int8(x, 64)
    assert q.dtype == jnp.int8 and sc.shape == (4, 4)
    err = np.abs(np.asarray(x - _dequant_int8(q, sc, 64)))
    amax = np.abs(np.asarray(x)).reshape(4, 4, 64).max(-1)
    assert (err.reshape(4, 4, 64) <= amax[..., None] / 127.0 + 1e-6).all()
    # zero block stays exactly zero
    z = jnp.zeros((1, 64), jnp.float32)
    qz, sz = _quant_int8(z, 64)
    assert np.array_equal(np.asarray(_dequant_int8(qz, sz, 64)), np.asarray(z))


# ---------------------------------------------------------------------------
# parity: fused fp32 ≡ per-tensor psum baseline (bitwise)
# ---------------------------------------------------------------------------

def _bitwise_equal_trees(a, b):
    fa = {jax.tree_util.keystr(k): v
          for k, v in jax.tree_util.tree_leaves_with_path(a)}
    fb = {jax.tree_util.keystr(k): v
          for k, v in jax.tree_util.tree_leaves_with_path(b)}
    assert fa.keys() == fb.keys()
    return all(np.array_equal(np.asarray(fa[k]), np.asarray(fb[k]))
               for k in fa)


def test_fused_fp32_bit_identical_lenet():
    """Acceptance: fusion alone never changes numerics — the per-bucket
    psum is elementwise the same reduction as one psum per tensor."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 1, 28, 28)).astype(np.float32)
    y = (np.arange(16) % 10).astype(np.int32)

    def build(comm):
        pt.seed(0)
        return SpmdTrainer(LeNet(num_classes=10), optimizer.SGD(0.05),
                           nn.functional.cross_entropy, mesh,
                           batch_axes=("dp",), comm=comm)

    base = build(CommFusionConfig(fuse=False))
    fused = build(CommFusionConfig(bucket_mb=0.05, max_buckets=4))
    for _ in range(3):
        lb = base.train_step(x, y)
        lf = fused.train_step(x, y)
    assert float(lb) == float(lf)
    assert _bitwise_equal_trees(jax.device_get(base.state["params"]),
                                jax.device_get(fused.state["params"]))


def test_fused_fp32_bit_identical_deepfm_dense():
    cfg = CtrConfig(num_sparse_slots=6, num_dense=5, embedx_dim=4,
                    dnn_hidden=(32, 16))
    mesh = mesh_mod.make_mesh({"dp": 8})
    rng = np.random.default_rng(0)
    emb = rng.normal(size=(32, 6, 5)).astype(np.float32) * 0.1
    dense = rng.normal(size=(32, 5)).astype(np.float32)
    y = (rng.random(32) < 0.4).astype(np.int32)

    def build(comm):
        pt.seed(0)
        return SpmdTrainer(DeepFM(cfg), optimizer.SGD(0.1),
                           nn.functional.binary_cross_entropy_with_logits,
                           mesh, batch_axes=("dp",), comm=comm)

    base = build(CommFusionConfig(fuse=False))
    fused = build(CommFusionConfig(max_buckets=2))
    for _ in range(3):
        lb = base.train_step((emb, dense), y)
        lf = fused.train_step((emb, dense), y)
    assert float(lb) == float(lf)
    assert _bitwise_equal_trees(jax.device_get(base.state["params"]),
                                jax.device_get(fused.state["params"]))


def test_fused_matches_single_device_trainer():
    """Fused dp=8 follows the serial trajectory exactly (mean-loss
    discipline: local mean + mean-reduce == global mean)."""
    from paddle_tpu.executor import Trainer

    mesh = mesh_mod.make_mesh({"dp": 2, "sharding": 4})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 3, 64).astype(np.int32)

    def fresh():
        pt.seed(0)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))

    serial = Trainer(fresh(), optimizer.SGD(0.1), nn.functional.cross_entropy)
    fused = SpmdTrainer(fresh(), optimizer.SGD(0.1),
                        nn.functional.cross_entropy, mesh,
                        comm=CommFusionConfig())
    for _ in range(5):
        ls = float(serial.train_step(jnp.asarray(x), jnp.asarray(y)))
        lf = float(fused.train_step(x, y))
    np.testing.assert_allclose(ls, lf, rtol=1e-6)


# ---------------------------------------------------------------------------
# int8 + error feedback accuracy (LeNet / synthetic MNIST)
# ---------------------------------------------------------------------------

def _mnist_like(rng, n):
    """10 fixed digit-blob prototypes + noise, 28×28×1."""
    protos = (np.random.default_rng(99).random((10, 28, 28)) < 0.2
              ).astype(np.float32)
    y = rng.integers(0, 10, n).astype(np.int32)
    x = protos[y] + rng.normal(0, 0.25, (n, 28, 28)).astype(np.float32)
    return x[:, None, :, :].astype(np.float32), y


def test_int8_error_feedback_trains_lenet_to_fp32_accuracy():
    """Acceptance: the int8 path with error feedback lands within 0.5%
    of fp32 eval accuracy on the LeNet/MNIST-shaped task."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    rng = np.random.default_rng(0)
    batches = [_mnist_like(rng, 64) for _ in range(4)]
    xte, yte = _mnist_like(np.random.default_rng(7), 256)

    def run(comm):
        pt.seed(0)
        tr = SpmdTrainer(LeNet(num_classes=10), optimizer.Momentum(0.05, 0.9),
                         nn.functional.cross_entropy, mesh,
                         batch_axes=("dp",), comm=comm)
        for i in range(60):
            xtr, ytr = batches[i % len(batches)]
            tr.train_step(xtr, ytr)
        model = tr.sync_model()
        logits = model(jnp.asarray(xte))
        return float(np.mean(np.argmax(np.asarray(logits), -1) == yte))

    acc_fp32 = run(CommFusionConfig())
    acc_int8 = run(CommFusionConfig(quant="int8", block_size=128,
                                    error_feedback=True))
    assert acc_fp32 > 0.85, acc_fp32   # the task is actually learned
    assert acc_int8 >= acc_fp32 - 0.005, (acc_int8, acc_fp32)


def test_int8_error_feedback_residual_is_carried():
    """EF state lives in opt_state, starts zero, becomes nonzero after a
    step (the quantization error is retained, not lost)."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.integers(0, 3, 16).astype(np.int32)
    pt.seed(0)
    tr = SpmdTrainer(nn.Linear(8, 3), optimizer.SGD(0.1),
                     nn.functional.cross_entropy, mesh, batch_axes=("dp",),
                     comm=CommFusionConfig(quant="int8", block_size=64))
    ef0 = jax.device_get(tr.opt_state["ef"])
    assert ef0 and all(np.all(np.asarray(v) == 0) for v in ef0.values())
    tr.train_step(x, y)
    ef1 = jax.device_get(tr.opt_state["ef"])
    assert any(np.any(np.asarray(v) != 0) for v in ef1.values())
    # per-rank: leading world dim, sharded over the dp axes
    leaf = next(iter(tr.opt_state["ef"].values()))
    assert leaf.shape[0] == 8 and "dp" in str(leaf.sharding.spec)


# ---------------------------------------------------------------------------
# wire acceptance via hlo_bytes
# ---------------------------------------------------------------------------

def _fresh_mlp(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(8, 64), nn.ReLU(), nn.Linear(64, 64),
                         nn.ReLU(), nn.Linear(64, 3))


def _compiled(tr, x, y):
    return tr._step.lower(tr.state, tr.opt_state, jax.random.key(0),
                          (jnp.asarray(x),), (jnp.asarray(y),)).compile()


def test_bucket_count_and_int8_byte_acceptance():
    """Acceptance: fused dp grad collectives ≤ configured bucket count;
    int8 moves ≥3.5× fewer wire bytes than fused fp32."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    x = np.zeros((64, 8), np.float32)
    y = np.zeros((64,), np.int32)

    def grad_coll(comm):
        tr = SpmdTrainer(_fresh_mlp(), optimizer.SGD(0.1),
                         nn.functional.cross_entropy, mesh,
                         batch_axes=("dp",), comm=comm)
        rep = hlo_bytes.report_compiled(_compiled(tr, x, y), num_devices=8)
        return hlo_bytes.grad_collectives(rep)

    fused = grad_coll(CommFusionConfig(max_buckets=2))
    assert 1 <= len(fused) <= 2, fused   # ≤ bucket count (one psum each)
    unfused = grad_coll(CommFusionConfig(fuse=False))
    # the baseline starts one-per-tensor; XLA's own combiner may merge
    # some, but the fused program must never have MORE collectives
    assert len(fused) <= len(unfused)
    int8 = grad_coll(CommFusionConfig(quant="int8", max_buckets=2,
                                      block_size=64))
    assert {c["dtype"] for c in int8} == {"s8"}
    wb_f32 = sum(c["wire_bytes"] for c in fused)
    wb_int8 = sum(c["wire_bytes"] for c in int8)
    assert wb_f32 >= 3.5 * wb_int8, (wb_f32, wb_int8)


def test_fp16_allreduce_wire_dtype_regression():
    """Satellite regression: with fp16_allreduce the dp collective's
    ELEMENT TYPE is bf16 — the old cast-and-cast-back passed every
    numeric test while moving zero fewer bytes. Asserted on the
    PRE-optimization HLO: XLA CPU's float-normalization pass legalizes
    bf16 collectives back to f32 (no native bf16 on CPU); TPU backends
    keep and execute the narrow type."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    x = jnp.zeros((64, 8), jnp.float32)
    y = jnp.zeros((64,), jnp.int32)

    def wire_dtypes(strategy):
        tr = SpmdTrainer(_fresh_mlp(), optimizer.SGD(0.1),
                         nn.functional.cross_entropy, mesh,
                         batch_axes=("dp",),
                         comm=CommFusionConfig(max_buckets=2),
                         strategy=strategy)
        low = tr._step.lower(tr.state, tr.opt_state, jax.random.key(0),
                             (x,), (y,))
        rep = hlo_bytes.report(low.as_text("hlo"), num_devices=8)
        return {c["dtype"] for c in hlo_bytes.grad_collectives(rep)}

    assert wire_dtypes(DistributedStrategy(fp16_allreduce=True)) == {"bf16"}
    assert wire_dtypes(None) == {"f32"}


def test_strategy_fuse_all_reduce_ops_enables_fusion():
    """The reference knob names work end to end: fuse_all_reduce_ops +
    comm_fusion_configs on the strategy select the fused path."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    strat = DistributedStrategy(
        fuse_all_reduce_ops=True,
        comm_fusion_configs={"max_buckets": 2, "quant": "int8",
                             "block_size": 64})
    tr = SpmdTrainer(_fresh_mlp(), optimizer.SGD(0.1),
                     nn.functional.cross_entropy, mesh, batch_axes=("dp",),
                     strategy=strat)
    rep = hlo_bytes.report_compiled(
        _compiled(tr, np.zeros((64, 8), np.float32),
                  np.zeros((64,), np.int32)), num_devices=8)
    assert {c["dtype"] for c in hlo_bytes.grad_collectives(rep)} == {"s8"}


# ---------------------------------------------------------------------------
# ZeRO: reduce-scattered shard consumed directly
# ---------------------------------------------------------------------------

def test_zero1_fused_shards_slots_and_matches_stage0():
    """Stage-1 fused: slots live as flat 1/K shards (memory 1/K) and the
    trajectory is BIT-identical to the fused stage-0 run — the shard
    update is the same elementwise math on the reduce-scattered segment
    (reduce-scatter + all-gather ≡ the all-reduce, verified bitwise)."""
    mesh = mesh_mod.make_mesh({"dp": 2, "sharding": 4})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 3, 64).astype(np.int32)

    def build(stage):
        pt.seed(0)
        return SpmdTrainer(_fresh_mlp(), optimizer.Adam(1e-2),
                           nn.functional.cross_entropy, mesh,
                           zero_stage=stage, comm=CommFusionConfig())

    z0, z1 = build(0), build(1)
    for _ in range(4):
        l0 = z0.train_step(x, y)
        l1 = z1.train_step(x, y)
    assert float(l0) == float(l1)
    assert _bitwise_equal_trees(jax.device_get(z0.state["params"]),
                                jax.device_get(z1.state["params"]))
    # slots are FLAT, jointly sharded over (dp, sharding); each device
    # holds 1/8
    m = z1.opt_state["inner"]["slots"]["m"]
    for leaf in jax.tree_util.tree_leaves(m):
        assert leaf.ndim == 1 and leaf.shape[0] % 8 == 0
        assert leaf.addressable_shards[0].data.size * 8 == leaf.size


def test_zero2_fused_hlo_has_reduce_scatter_no_full_allreduce():
    mesh = mesh_mod.make_mesh({"dp": 1, "sharding": 8})
    tr = SpmdTrainer(_fresh_mlp(), optimizer.Adam(1e-2),
                     nn.functional.cross_entropy, mesh, zero_stage=2,
                     comm=CommFusionConfig(max_buckets=2))
    rep = hlo_bytes.report_compiled(
        _compiled(tr, np.zeros((64, 8), np.float32),
                  np.zeros((64,), np.int32)), num_devices=8)
    ops = [c["op"] for c in hlo_bytes.grad_collectives(rep)]
    assert "reduce-scatter" in ops, ops    # grads scatter…
    assert "all-gather" in ops, ops        # …updated params gather
    assert "all-reduce" not in ops, ops    # never allreduce-then-slice


# ---------------------------------------------------------------------------
# meta-optimizer composition under the pre-reduction contract
# ---------------------------------------------------------------------------

def test_composition_order_and_reducer_wiring():
    reducer = DpGradReducer(("dp",), (4,), CommFusionConfig(quant="int8",
                                                            block_size=64))
    strat = DistributedStrategy(
        dgc=True, fp16_allreduce=True, localsgd=True,
        localsgd_configs={"k_steps": 2},
        gradient_merge=True, gradient_merge_configs={"k_steps": 2})
    chain = apply_strategy(optimizer.Momentum(0.1), strat, reducer=reducer)
    assert isinstance(chain, GradientMergeOptimizer)
    assert isinstance(chain.inner, LocalSGDOptimizer)
    assert isinstance(chain.inner.inner, FP16AllReduceOptimizer)
    assert isinstance(chain.inner.inner.inner, DGCMomentumOptimizer)
    assert isinstance(chain.inner.inner.inner.inner, FusedAllReduceOptimizer)
    assert reducer.installed
    # state layout tags: GM acc + DGC u/v + EF are per-rank local
    params = {"w": jnp.ones((8, 8), jnp.float32)}
    st = chain.init(params)
    tags = chain.state_layout(st)
    assert set(jax.tree_util.tree_leaves(tags["acc"])) == {"local"}
    inner3 = tags["inner"]["inner"]["inner"]
    assert set(jax.tree_util.tree_leaves(inner3["u"])) == {"local"}
    assert set(jax.tree_util.tree_leaves(inner3["v"])) == {"local"}
    assert set(jax.tree_util.tree_leaves(inner3["inner"]["ef"])) == {"local"}
    # base optimizer state (SGD: just the step counter) replicates
    assert set(jax.tree_util.tree_leaves(
        inner3["inner"]["inner"])) == {"rep"}


def test_full_stack_dgc_fp16_localsgd_gm_semantics():
    """DGC → fp16 → localsgd → gm on a 4-rank dp group with fully
    per-rank state: held GM steps change nothing, applied steps update
    locally (localsgd: no grad collective), and localsgd's k-th applied
    step re-syncs params across ranks."""
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("dp",))
    reducer = DpGradReducer(("dp",), (4,), CommFusionConfig())
    strat = DistributedStrategy(
        dgc=True, dgc_configs={"rampup_begin_step": 100},  # dense pre-rampup
        fp16_allreduce=True, localsgd=True, localsgd_configs={"k_steps": 2},
        gradient_merge=True, gradient_merge_configs={"k_steps": 2})
    chain = apply_strategy(optimizer.Momentum(0.5, momentum=0.0), strat,
                           reducer=reducer)

    params0 = {"w": jnp.ones((4, 8), jnp.float32)}
    st0 = chain.init(params0)
    R = 4
    expand = lambda t: jax.tree_util.tree_map(
        lambda x: jnp.asarray(np.broadcast_to(
            np.asarray(x), (R,) + np.asarray(x).shape).copy()), t)
    params, st = expand(params0), expand(st0)
    # distinct grads per rank
    g = jnp.asarray(np.arange(R * 32, dtype=np.float32).reshape(R, 4, 8)
                    / 100.0)

    def step(p, s, gr):
        sq = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
        ex = lambda t: jax.tree_util.tree_map(lambda x: x[None], t)
        np_, ns_ = chain.update({"w": gr[0]}, sq(s), sq(p))
        return ex(np_), ex(ns_)

    fn = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P("dp"), P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp")), check_vma=False))

    p1, s1 = fn(params, st, g)
    # GM k=2: step 1 held — params untouched
    assert np.array_equal(np.asarray(p1["w"]), np.asarray(params["w"]))
    p2, s2 = fn(p1, s1, g)
    # step 2 applied with LOCAL grads: ranks diverge (localsgd inner)
    w2 = np.asarray(p2["w"])
    assert not np.array_equal(w2, np.asarray(params["w"]))
    assert not np.allclose(w2[0], w2[1])
    p3, s3 = fn(p2, s2, g)
    assert np.array_equal(np.asarray(p3["w"]), w2)   # held again
    p4, s4 = fn(p3, s3, g)
    # 2nd applied step = localsgd sync: all ranks equal again
    w4 = np.asarray(p4["w"])
    assert not np.array_equal(w4, w2)
    for r in range(1, R):
        np.testing.assert_allclose(w4[r], w4[0], rtol=1e-6)


def test_gradient_merge_held_steps_skip_collective_in_hlo():
    """Satellite: with GM in the chain every dp grad collective lives in
    the HLO conditional's apply branch — a held step executes ZERO grad
    collectives (no wasted ICI traffic)."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    strat = DistributedStrategy(
        dgc=True, fp16_allreduce=True,
        gradient_merge=True, gradient_merge_configs={"k_steps": 2})
    tr = SpmdTrainer(_fresh_mlp(), optimizer.Momentum(0.1),
                     nn.functional.cross_entropy, mesh, batch_axes=("dp",),
                     comm=CommFusionConfig(max_buckets=2), strategy=strat)
    rep = hlo_bytes.report_compiled(
        _compiled(tr, np.zeros((64, 8), np.float32),
                  np.zeros((64,), np.int32)), num_devices=8)
    grad = hlo_bytes.grad_collectives(rep)
    assert grad, "expected dp grad collectives"
    assert all(c["in_conditional"] for c in grad), grad
    # and the chain still trains
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 3, 64).astype(np.int32)
    first = float(tr.train_step(x, y))
    for _ in range(6):
        last = float(tr.train_step(x, y))
    assert np.isfinite(last) and last < first


def test_gm_fused_matches_serial_gm():
    """GM k=2 over the fused dp path ≡ serial GM trainer on the full
    batch (the merged-apply semantics survive the contract change)."""
    from paddle_tpu.executor import Trainer

    mesh = mesh_mod.make_mesh({"dp": 8})
    strat = DistributedStrategy(gradient_merge=True,
                                gradient_merge_configs={"k_steps": 2})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 3, 64).astype(np.int32)

    pt.seed(0)
    serial = Trainer(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                   nn.Linear(16, 3)),
                     apply_strategy(optimizer.SGD(0.1), strat),
                     nn.functional.cross_entropy)
    pt.seed(0)
    fused = SpmdTrainer(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                      nn.Linear(16, 3)),
                        optimizer.SGD(0.1), nn.functional.cross_entropy,
                        mesh, batch_axes=("dp",), comm=CommFusionConfig(),
                        strategy=strat)
    for _ in range(4):
        ls = float(serial.train_step(jnp.asarray(x), jnp.asarray(y)))
        lf = float(fused.train_step(x, y))
    np.testing.assert_allclose(ls, lf, rtol=1e-6)


def test_localsgd_rejected_on_fused_trainer():
    mesh = mesh_mod.make_mesh({"dp": 8})
    strat = DistributedStrategy(localsgd=True)
    with pytest.raises(Exception, match="localsgd"):
        SpmdTrainer(_fresh_mlp(), optimizer.SGD(0.1),
                    nn.functional.cross_entropy, mesh, batch_axes=("dp",),
                    comm=CommFusionConfig(), strategy=strat)


def test_amp_nonfinite_skip_is_uniform_across_ranks():
    """One rank's local nan must make EVERY rank skip (sync_all_finite):
    params stay put, the loss scale halves, training resumes cleanly."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    strat = DistributedStrategy(
        amp=True, amp_configs={"init_loss_scaling": 1024.0,
                               "decr_every_n_nan_or_inf": 1})
    tr = SpmdTrainer(_fresh_mlp(), optimizer.SGD(0.1),
                     nn.functional.cross_entropy, mesh, batch_axes=("dp",),
                     comm=CommFusionConfig(), strategy=strat)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 3, 64).astype(np.int32)
    p0 = jax.device_get(tr.state["params"])
    bad = x.copy()
    bad[:8] = np.nan          # rank 0's dp shard only
    tr.train_step(bad, y)
    p1 = jax.device_get(tr.state["params"])
    assert _bitwise_equal_trees(p0, p1)   # skipped everywhere
    assert float(tr.opt_state["scaler"].loss_scale) == 512.0
    l2 = float(tr.train_step(x, y))       # clean batch applies again
    assert np.isfinite(l2)


def test_fused_trainer_save_load_resume(tmp_path):
    """Expanded per-rank EF state + flat-shard slots survive the
    checkpoint roundtrip; the restored run continues the trajectory."""
    mesh = mesh_mod.make_mesh({"dp": 2, "sharding": 4})
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 3, 32).astype(np.int32)
    comm = CommFusionConfig(quant="int8", block_size=64)

    pt.seed(0)
    a = SpmdTrainer(nn.Linear(8, 3), optimizer.Adam(1e-2),
                    nn.functional.cross_entropy, mesh, zero_stage=1,
                    comm=comm)
    for _ in range(3):
        a.train_step(x, y)
    a.save(str(tmp_path / "snap"))
    la = [float(a.train_step(x, y)) for _ in range(3)]

    pt.seed(5)
    b = SpmdTrainer(nn.Linear(8, 3), optimizer.Adam(1e-2),
                    nn.functional.cross_entropy, mesh, zero_stage=1,
                    comm=comm)
    b.load(str(tmp_path / "snap"))
    lb = [float(b.train_step(x, y)) for _ in range(3)]
    np.testing.assert_allclose(lb, la, rtol=1e-5)


def test_dp1_path_unchanged():
    """A 1-device batch group ignores comm fusion entirely (serial/dp=1
    path byte-for-byte the GSPMD behavior)."""
    mesh = mesh_mod.make_mesh({"dp": 1, "sharding": 1, "mp": 8})
    tr = SpmdTrainer(_fresh_mlp(), optimizer.SGD(0.1),
                     nn.functional.cross_entropy, mesh,
                     comm=CommFusionConfig())
    assert not hasattr(tr, "reducer")
    x = np.zeros((8, 8), np.float32)
    y = np.zeros((8,), np.int32)
    assert np.isfinite(float(tr.train_step(x, y)))


def test_unfused_rung_still_honors_wire_dtype():
    """fuse=False + fp16_allreduce: the per-tensor baseline collectives
    still ride at bf16 (previously the wire override was silently
    dropped on the unfused rung)."""
    mesh = mesh_mod.make_mesh({"dp": 8})
    tr = SpmdTrainer(_fresh_mlp(), optimizer.SGD(0.1),
                     nn.functional.cross_entropy, mesh, batch_axes=("dp",),
                     comm=CommFusionConfig(fuse=False),
                     strategy=DistributedStrategy(fp16_allreduce=True))
    low = tr._step.lower(tr.state, tr.opt_state, jax.random.key(0),
                         (jnp.zeros((64, 8), jnp.float32),),
                         (jnp.zeros((64,), jnp.int32),))
    rep = hlo_bytes.report(low.as_text("hlo"), num_devices=8)
    assert {c["dtype"]
            for c in hlo_bytes.grad_collectives(rep)} == {"bf16"}


def test_reducer_wire_override_and_suspend():
    """Unit: wire_dtype narrows the reduced mean to ~bf16 precision;
    suspended() returns local grads untouched."""
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("dp",))
    r = DpGradReducer(("dp",), (8,), CommFusionConfig())
    g = jnp.asarray(np.linspace(0.001, 1.0, 8 * 16, dtype=np.float32)
                    .reshape(8, 16))

    def f(gr):
        tree = {"g": gr[0]}
        plain, _ = r.reduce(tree, {})
        with r.wire_dtype(jnp.bfloat16):
            cast, _ = r.reduce(tree, {})
        with r.suspended():
            local, _ = r.reduce(tree, {})
        return plain["g"][None], cast["g"][None], local["g"][None]

    plain, cast, local = jax.jit(shard_map(
        f, mesh=mesh, in_specs=(P("dp"),),
        out_specs=(P("dp"),) * 3, check_vma=False))(g)
    expect = np.asarray(g).mean(0)
    np.testing.assert_allclose(np.asarray(plain)[0], expect, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(cast)[0], expect, rtol=2e-2)
    assert float(np.max(np.abs(np.asarray(cast)[0] - expect))) > 0  # lossy
    np.testing.assert_allclose(np.asarray(local)[0], np.asarray(g)[0])
