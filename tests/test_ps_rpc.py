"""Native TCP PS service tests.

Mirrors the reference's in-process service tests
(paddle/fluid/distributed/test/brpc_service_sparse_sgd_test.cc — real
server + client in one process, localhost) and the subprocess cluster
harness (test_dist_fleet_base.py _run_cluster: pserver + trainer
subprocesses on free ports)."""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")


def _acc():
    return AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))


@pytest.fixture
def cluster():
    """Two in-process servers + a connected client."""
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    yield servers, client
    client.close()
    for s in servers:
        s.close()


def test_sparse_pull_push_matches_local_table(cluster):
    _, cli = cluster
    cfg = TableConfig(shard_num=4, accessor_config=_acc())
    cli.create_sparse_table(0, cfg)
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 5000, 300).astype(np.uint64)
    slots = (keys % 26).astype(np.int32)
    assert (cli.pull_sparse(0, keys, slots=slots) == 0).all()

    push = np.zeros((300, 12), np.float32)
    push[:, 0] = slots
    push[:, 1] = 2.0
    push[:, 2] = 1.0
    push[:, 3:] = rng.normal(0, 0.1, (300, 9)).astype(np.float32)
    cli.push_sparse(0, keys, push)

    local = MemorySparseTable(TableConfig(shard_num=4, accessor_config=_acc(),
                                          backend="native"))
    local.pull_sparse(keys, slots)
    local.push_sparse(keys, push)
    np.testing.assert_allclose(
        cli.pull_sparse(0, keys, create=False),
        local.pull_sparse(keys, create=False), atol=1e-6)
    assert cli.size(0) == local.size()


def test_dense_optimizers(cluster):
    _, cli = cluster
    cli.create_dense_table(1, dim=7, optimizer="sgd", lr=0.5)
    cli.set_dense(1, np.arange(7, dtype=np.float32))
    cli.push_dense(1, np.ones(7, np.float32))
    np.testing.assert_allclose(cli.pull_dense(1), np.arange(7) - 0.5)

    cli.create_dense_table(2, dim=3, optimizer="adam", lr=0.1)
    for _ in range(3):
        cli.push_dense(2, np.ones(3, np.float32))
    # match host-side MemoryDenseTable math
    from paddle_tpu.ps.table import MemoryDenseTable
    ref = MemoryDenseTable(3, "adam", 0.1)
    for _ in range(3):
        ref.push_dense(np.ones(3, np.float32))
    np.testing.assert_allclose(cli.pull_dense(2), ref.pull_dense(), atol=1e-6)


def test_geo_accumulate_and_drain(cluster):
    _, cli = cluster
    cli.create_geo_table(3, dim=4)
    cli.push_geo(3, np.array([7, 8], np.uint64), np.ones((2, 4), np.float32))
    cli.push_geo(3, np.array([7], np.uint64), 3 * np.ones((1, 4), np.float32))
    k, d = cli.pull_geo(3)
    got = dict(zip(k.tolist(), d[:, 0].tolist()))
    assert got == {7: 2.0, 8: 1.0}  # mean over pushes per key
    k2, _ = cli.pull_geo(3)
    assert len(k2) == 0  # drained


def test_save_load_roundtrip(cluster, tmp_path):
    _, cli = cluster
    cli.create_sparse_table(0, TableConfig(shard_num=4, accessor_config=_acc()))
    rng = np.random.default_rng(1)
    keys = rng.integers(1, 2000, 200).astype(np.uint64)
    push = np.zeros((200, 12), np.float32)
    push[:, 1] = 2.0
    push[:, 3:] = 0.05
    cli.push_sparse(0, keys, push)
    before = cli.pull_sparse(0, keys, create=False)
    n = cli.save(0, str(tmp_path), 0)
    assert n == cli.size(0)

    # fresh cluster loads the files
    servers2 = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    cli2 = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers2])
    try:
        cli2.create_sparse_table(0, TableConfig(shard_num=4, accessor_config=_acc()))
        assert cli2.load(0, str(tmp_path)) == n
        np.testing.assert_allclose(
            cli2.pull_sparse(0, keys, create=False), before, atol=1e-6)
    finally:
        cli2.close()
        for s in servers2:
            s.close()


def test_export_import_full(cluster):
    _, cli = cluster
    cli.create_sparse_table(0, TableConfig(shard_num=4, accessor_config=_acc()))
    keys = np.array([11, 22, 33], np.uint64)
    push = np.zeros((3, 12), np.float32)
    push[:, 1] = 1.0
    push[:, 3:] = 0.2
    cli.push_sparse(0, keys, push)
    vals, found = cli.export_full(0, np.array([11, 22, 99], np.uint64))
    assert found.tolist() == [True, True, False]
    assert (vals[2] == 0).all()
    # import into a different id routes correctly
    cli.create_sparse_table(5, TableConfig(shard_num=4, accessor_config=_acc()))
    cli.import_full(5, keys, cli.export_full(0, keys)[0])
    np.testing.assert_allclose(
        cli.pull_sparse(5, keys, create=False),
        cli.pull_sparse(0, keys, create=False), atol=1e-6)


def test_barrier_blocks_until_all_trainers():
    server = rpc.NativePsServer(n_trainers=3)
    clients = [rpc.RpcPsClient([f"127.0.0.1:{server.port}"]) for _ in range(3)]
    order = []
    lock = threading.Lock()

    def arrive(i, delay):
        time.sleep(delay)
        clients[i].barrier()
        with lock:
            order.append((i, time.monotonic()))

    ts = [threading.Thread(target=arrive, args=(i, 0.05 * i)) for i in range(3)]
    t0 = time.monotonic()
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)
    assert len(order) == 3
    # nobody released before the last arrival (~0.1s)
    assert min(t for _, t in order) - t0 >= 0.09
    for c in clients:
        c.close()
    server.close()


def test_missing_table_raises(cluster):
    _, cli = cluster
    from paddle_tpu.core.enforce import NotFoundError
    with pytest.raises(NotFoundError):
        cli.pull_sparse(42, np.array([1], np.uint64))


_SERVER_SCRIPT = """
import sys
import time
from paddle_tpu.ps.rpc import NativePsServer
s = NativePsServer(port=int(sys.argv[1]), n_trainers=int(sys.argv[2]))
print("READY", s.port, flush=True)
# serve until a trainer sends STOP (server stops itself) or we are killed
time.sleep(3600)
"""

_TRAINER_SCRIPT = """
import sys
import numpy as np
from paddle_tpu.ps.rpc import RpcPsClient
from paddle_tpu.ps.table import TableConfig
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig

endpoints = sys.argv[1].split(",")
trainer_id = int(sys.argv[2])
cli = RpcPsClient(endpoints)
acc = AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))
cli.create_sparse_table(0, TableConfig(shard_num=4, accessor_config=acc))
keys = np.arange(1, 101, dtype=np.uint64)
cli.pull_sparse(0, keys)
push = np.zeros((100, 12), np.float32)
push[:, 1] = 1.0
push[:, 3:] = 0.1
for _ in range(5):
    cli.push_sparse(0, keys, push)
cli.barrier()
out = cli.pull_sparse(0, keys, create=False)
# both trainers pushed 5 times each -> show == 10 after the barrier
assert np.allclose(out[:, 0], 10.0), out[:, 0][:5]
print("TRAINER_OK", trainer_id, flush=True)
cli.barrier()  # closing barrier: nobody stops servers mid-request
if trainer_id == 0:
    cli.stop_servers()
cli.close()
"""


def test_multiprocess_cluster(tmp_path):
    """2 server processes + 2 trainer processes on localhost (the
    test_dist_fleet_base._run_cluster pattern)."""
    env = None
    servers = []
    for _ in range(2):
        p = subprocess.Popen([sys.executable, "-c", _SERVER_SCRIPT, "0", "2"],
                             stdout=subprocess.PIPE, text=True, env=env,
                             cwd="/root/repo")
        line = p.stdout.readline().strip()
        assert line.startswith("READY"), line
        servers.append((p, int(line.split()[1])))
    endpoints = ",".join(f"127.0.0.1:{port}" for _, port in servers)
    trainers = [
        subprocess.Popen([sys.executable, "-c", _TRAINER_SCRIPT, endpoints, str(i)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, cwd="/root/repo")
        for i in range(2)
    ]
    try:
        for i, t in enumerate(trainers):
            out, _ = t.communicate(timeout=60)
            assert t.returncode == 0, out
            assert f"TRAINER_OK {i}" in out, out
    finally:
        for p, _ in servers:
            p.kill()
        for t in trainers:
            if t.poll() is None:
                t.kill()


_FLEET_SERVER = """
import os
from paddle_tpu.distributed.fleet import Fleet
from paddle_tpu.distributed.strategy import DistributedStrategy
f = Fleet()
f.init(strategy=DistributedStrategy(a_sync=True, ps_transport="rpc"))
assert f.is_server() and f.transport == "rpc"
f.init_server()
print("SERVER_READY", flush=True)
f.run_server()   # blocks until a trainer sends STOP
print("SERVER_DONE", flush=True)
"""

_FLEET_TRAINER = """
import sys
import numpy as np
from paddle_tpu.distributed.fleet import Fleet
from paddle_tpu.distributed.strategy import DistributedStrategy
from paddle_tpu.ps.table import TableConfig
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig

f = Fleet()
f.init(strategy=DistributedStrategy(a_sync=True, ps_transport="rpc"))
assert f.is_worker() and f.transport == "rpc"
f.init_worker()
acc = AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))
f.register_sparse_table(0, TableConfig(shard_num=4, accessor_config=acc))
keys = np.arange(1, 51, dtype=np.uint64)
f.client.pull_sparse(0, keys)
push = np.zeros((50, 12), np.float32)
push[:, 1] = 1.0
push[:, 3:] = 0.1
f.client.push_sparse(0, keys, push)
f.client.barrier()
out = f.client.pull_sparse(0, keys, create=False)
assert np.allclose(out[:, 0], 2.0), out[:5, 0]  # both trainers pushed once
print("FLEET_TRAINER_OK", flush=True)
f.stop_worker()
f.client.barrier()  # closing barrier: nobody stops servers mid-request
if int(sys.argv[1]) == 0:
    f.client.stop_servers()
f.client.close()
"""


def test_fleet_rpc_cluster():
    """Fleet facade over the rpc transport: 2 pserver + 2 trainer
    subprocesses wired by PaddleCloud env vars (role_maker.py env
    contract)."""
    import os
    import socket

    def free_port():
        with socket.socket() as s:
            s.bind(("", 0))
            return s.getsockname()[1]

    ps_ports = [free_port(), free_port()]
    ps_list = ",".join(f"127.0.0.1:{p}" for p in ps_ports)
    base = {"PADDLE_PSERVERS_IP_PORT_LIST": ps_list, "PADDLE_TRAINERS_NUM": "2"}

    servers = []
    for port in ps_ports:
        env = dict(os.environ, **base, TRAINING_ROLE="PSERVER",
                   POD_IP="127.0.0.1", PADDLE_PORT=str(port))
        p = subprocess.Popen([sys.executable, "-c", _FLEET_SERVER],
                             stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                             text=True, env=env, cwd="/root/repo")
        assert "SERVER_READY" in p.stdout.readline()
        servers.append(p)
    trainers = []
    for i in range(2):
        env = dict(os.environ, **base, TRAINING_ROLE="TRAINER",
                   PADDLE_TRAINER_ID=str(i))
        trainers.append(
            subprocess.Popen([sys.executable, "-c", _FLEET_TRAINER, str(i)],
                             stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                             text=True, env=env, cwd="/root/repo"))
    try:
        for t in trainers:
            out, _ = t.communicate(timeout=60)
            assert t.returncode == 0 and "FLEET_TRAINER_OK" in out, out
        for p in servers:
            out, _ = p.communicate(timeout=30)
            assert "SERVER_DONE" in out, out
    finally:
        for p in servers + trainers:
            if p.poll() is None:
                p.kill()


def test_checkpoint_portable_between_local_and_rpc(cluster, tmp_path):
    """Local-transport checkpoints load under rpc and vice versa (the
    ps_transport=auto scaling path)."""
    _, cli = cluster
    local = MemorySparseTable(TableConfig(shard_num=4, accessor_config=_acc()))
    rng = np.random.default_rng(5)
    keys = rng.integers(1, 1000, 150).astype(np.uint64)
    push = np.zeros((150, 12), np.float32)
    push[:, 1] = 2.0
    push[:, 3:] = 0.03
    local.pull_sparse(keys)
    local.push_sparse(keys, push)
    d1 = tmp_path / "local_ck"
    n = local.save(str(d1), 0)

    cli.create_sparse_table(0, TableConfig(shard_num=4, accessor_config=_acc()))
    assert cli.load(0, str(d1)) == n
    np.testing.assert_allclose(
        cli.pull_sparse(0, keys, create=False),
        local.pull_sparse(keys, create=False), atol=1e-6)

    # and back: rpc save -> local load
    d2 = tmp_path / "rpc_ck"
    n2 = cli.save(0, str(d2), 0)
    local2 = MemorySparseTable(TableConfig(shard_num=4, accessor_config=_acc()))
    assert local2.load(str(d2)) == n2
    np.testing.assert_allclose(
        local2.pull_sparse(keys, create=False),
        local.pull_sparse(keys, create=False), atol=1e-6)


def test_ssd_table_over_rpc(tmp_path):
    """A server-side SSD table behind the TCP transport: create with
    storage=ssd, push/pull with tier movement, spill/stats/compact, and
    the values survive a server restart (log replay)."""
    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig

    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    cfg = TableConfig(shard_num=4, accessor_config=acc, storage="ssd",
                      ssd_path=str(tmp_path / "tiers"))
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    ports = [s.port for s in servers]
    cli = rpc.RpcPsClient([f"127.0.0.1:{p}" for p in ports])
    cli.create_sparse_table(0, cfg)

    rng = np.random.default_rng(1)
    keys = np.unique(rng.integers(1, 2000, 500).astype(np.uint64))
    slots = (keys % 8).astype(np.int32)
    push = np.zeros((len(keys), 4 + 4), np.float32)
    push[:, 0] = slots
    push[:, 1] = 1.0
    push[:, 3:] = rng.normal(0, 0.1, (len(keys), 5)).astype(np.float32)
    cli.push_sparse(0, keys, push)
    want = cli.pull_sparse(0, keys, create=False)
    assert np.abs(want).sum() > 0

    total = cli.size(0)
    spilled = cli.spill(0, hot_budget=0)
    st = cli.table_stats(0)
    assert spilled == total and st["cold_rows"] == total and st["hot_rows"] == 0
    # reads promote back; values identical across the tier move
    np.testing.assert_allclose(cli.pull_sparse(0, keys, create=False), want,
                               atol=1e-6)
    assert cli.table_stats(0)["hot_rows"] == total
    cli.spill(0, hot_budget=0)
    assert cli.compact(0) >= 0

    # restart both servers on the same directories: cold rows replay
    cli.close()
    for s in servers:
        s.stop()
    servers2 = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    cli2 = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers2])
    # NB: same per-server subdirectories require same server order
    cli2.create_sparse_table(0, cfg)
    st2 = cli2.table_stats(0)
    assert st2["cold_rows"] == total and st2["hot_rows"] == 0
    np.testing.assert_allclose(cli2.pull_sparse(0, keys, create=False), want,
                               atol=1e-6)
    cli2.close()
    for s in servers2:
        s.stop()


def test_load_cold_and_server_side_save(tmp_path):
    """The 1e9-row composition surface at test scale: client-chunked
    load_cold into server-side SSD cold tiers, server-side streaming
    save (kSaveFile, gzip'd), restart onto FRESH directories, and
    server-side load (kLoadFile) — with value parity end to end, plus
    interop: the C++-written gzip shard files load into a local Python
    table through the converter registry."""
    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig

    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    cfg = TableConfig(shard_num=4, accessor_config=acc, storage="ssd",
                      ssd_path=str(tmp_path / "tiers_a"))
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    cli = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    cli.create_sparse_table(0, cfg)
    full_dim = cli._dims(0)[2]
    assert full_dim == 13  # 7 + adagrad(1) + embedx 4 + adagrad(1)

    rng = np.random.default_rng(2)
    n = 50_000
    keys = np.arange(1, n + 1, dtype=np.uint64)
    vals = np.zeros((n, full_dim), np.float32)
    vals[:, 0] = keys % 8          # slot
    vals[:, 3] = 1.0               # show
    vals[:, 5] = rng.normal(0, 0.01, n).astype(np.float32)   # embed_w
    vals[:, 7] = 1.0               # has_embedx
    vals[:, 8:12] = rng.normal(0, 0.01, (n, 4)).astype(np.float32)
    loaded = cli.load_cold(0, keys, vals, chunk=8192)
    assert loaded == n
    st = cli.table_stats(0)
    assert st["cold_rows"] == n and st["hot_rows"] == 0

    sample = rng.choice(keys, 500, replace=False)
    got, found = cli.export_full(0, sample)
    assert found.all()
    idx = sample.astype(np.int64) - 1
    np.testing.assert_allclose(got, vals[idx], atol=1e-6)

    # server-side gzip'd save: nothing crosses the wire
    ckpt = str(tmp_path / "ckpt")
    saved = cli.save_local(0, ckpt, mode=0, converter="gzip")
    assert saved == n
    import os

    assert os.path.exists(os.path.join(ckpt, "part-00000.shard.gz"))
    assert os.path.exists(os.path.join(ckpt, "part-00001.shard.gz"))

    # fresh directories + fresh servers: restore via server-side load
    cli.close()
    for s in servers:
        s.close()
    cfg_b = TableConfig(shard_num=4, accessor_config=acc, storage="ssd",
                        ssd_path=str(tmp_path / "tiers_b"))
    servers2 = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    cli2 = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers2])
    cli2.create_sparse_table(0, cfg_b)
    restored = cli2.load_local(0, ckpt)
    assert restored == n
    st2 = cli2.table_stats(0)
    assert st2["cold_rows"] == n
    got2, found2 = cli2.export_full(0, sample)
    assert found2.all()
    # text round-trip through %.6g/%.8g: small absolute tolerance
    np.testing.assert_allclose(got2, vals[idx], rtol=1e-6, atol=1e-9)

    # interop: the C++-written gzip checkpoint loads into a local
    # Python-side table (converter registry reads the same files)
    local = MemorySparseTable(TableConfig(shard_num=4, accessor_config=acc))
    assert local.load(ckpt) == n
    lv, lfound = local.export_full(sample)
    assert lfound.all()
    np.testing.assert_allclose(lv, got2, atol=1e-9)
    cli2.close()
    for s in servers2:
        s.close()


def test_server_side_save_raw_binary(tmp_path):
    """converter='raw': fixed binary records (header-checked) — the
    IO-speed alternative to the CPU-bound gzip text save; round-trips
    through fresh servers with value parity, and a wrong-schema load
    (different embedx_dim → different fdim) is rejected at the header."""
    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig

    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    cfg = TableConfig(shard_num=4, accessor_config=acc, storage="ssd",
                      ssd_path=str(tmp_path / "tiers_a"))
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    cli = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    cli.create_sparse_table(0, cfg)
    full_dim = cli._dims(0)[2]

    rng = np.random.default_rng(5)
    n = 20_000
    keys = np.arange(1, n + 1, dtype=np.uint64)
    vals = np.zeros((n, full_dim), np.float32)
    vals[:, 0] = keys % 8
    vals[:, 3] = 1.0
    vals[:, 5] = rng.normal(0, 0.01, n).astype(np.float32)
    vals[:, 7] = 1.0
    vals[:, 8:12] = rng.normal(0, 0.01, (n, 4)).astype(np.float32)
    assert cli.load_cold(0, keys, vals) == n

    ckpt = str(tmp_path / "ckpt_raw")
    assert cli.save_local(0, ckpt, mode=0, converter="raw") == n
    import os

    assert os.path.exists(os.path.join(ckpt, "part-00000.shard.bin"))
    cli.close()
    for s in servers:
        s.close()

    servers2 = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    cli2 = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers2])
    cli2.create_sparse_table(0, TableConfig(
        shard_num=4, accessor_config=acc, storage="ssd",
        ssd_path=str(tmp_path / "tiers_b")))
    assert cli2.load_local(0, ckpt) == n
    sample = rng.choice(keys, 300, replace=False)
    got, found = cli2.export_full(0, sample)
    assert found.all()
    # binary round-trip is BIT-exact (no text formatting in the loop)
    np.testing.assert_array_equal(got, vals[sample.astype(np.int64) - 1])

    # schema guard: a table with a different fdim refuses the file
    acc2 = AccessorConfig(embedx_dim=8, embedx_threshold=0.0,
                          sgd=SGDRuleConfig(initial_range=0.0))
    cli2.create_sparse_table(1, TableConfig(
        shard_num=4, accessor_config=acc2, storage="ssd",
        ssd_path=str(tmp_path / "tiers_c")))
    with pytest.raises(Exception):
        cli2.load_local(1, ckpt)
    cli2.close()
    for s in servers2:
        s.close()


def test_pass_trainer_over_remote_table(tmp_path):
    """Multi-node GPUPS: CtrPassTrainer's pass lifecycle served by TWO
    RPC servers through RemoteSparseTable — begin_pass's insert-on-miss
    state export is the reference's BuildPull from remote shards
    (ps_gpu_wrapper.cc:299), end_pass the flush-back; the remote end
    state matches a local-table run on identical data."""
    import jax
    import paddle_tpu as pt
    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig
    from paddle_tpu.ps.ps_trainer import CtrPassTrainer
    from paddle_tpu.ps.rpc import RemoteSparseTable

    S, D = 3, 2
    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    cfg = TableConfig(shard_num=4, accessor_config=acc)
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(512):
        ids = rng.integers(0, 48, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])

    def run(table):
        pt.seed(0)
        ds = InMemoryDataset(slots, seed=0)
        ds.load_from_lines(lines)
        tr = CtrPassTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                             dnn_hidden=(8,))),
            optimizer.Adam(1e-2), table,
            CacheConfig(capacity=1 << 9, embedx_dim=4, embedx_threshold=0.0),
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
        out = tr.train_from_dataset(ds, batch_size=128)
        assert np.isfinite(out["loss"])
        return out["loss"]

    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(2)]
    cli = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    cli.create_sparse_table(0, cfg)
    remote = RemoteSparseTable(cli, 0, cfg)
    loss_remote = run(remote)

    local = MemorySparseTable(cfg)
    loss_local = run(local)

    np.testing.assert_allclose(loss_remote, loss_local, rtol=1e-5)
    # end-of-pass table contents match across transports
    probe = np.unique((rng.integers(0, 48, 400).astype(np.uint64)
                       + (rng.integers(0, S, 400).astype(np.uint64) << np.uint64(32))))
    np.testing.assert_allclose(
        cli.pull_sparse(0, probe, create=False),
        local.pull_sparse(probe, create=False), atol=1e-5)
    assert remote.size() == local.size()
    cli.close()
    for s in servers:
        s.stop()


def test_stream_trainer_over_remote_table():
    """CtrStreamTrainer (the_one_ps worker loop) pulls/pushes straight
    through RemoteSparseTable — the hogwild CPU path against remote
    servers, no communicator required."""
    import jax
    import paddle_tpu as pt
    import paddle_tpu.ps.rpc as rpc
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
    from paddle_tpu.ps.rpc import RemoteSparseTable

    S, D = 3, 2
    cfg = TableConfig(shard_num=4, accessor_config=AccessorConfig(
        embedx_dim=4, embedx_threshold=0.0))
    server = rpc.NativePsServer(n_trainers=1)
    cli = rpc.RpcPsClient([f"127.0.0.1:{server.port}"])
    cli.create_sparse_table(0, cfg)
    remote = RemoteSparseTable(cli, 0, cfg)

    rng = np.random.default_rng(0)
    lines = []
    for _ in range(512):
        ids = rng.integers(0, 48, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)

    pt.seed(0)
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), remote,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    losses = [tr.train_from_dataset(ds, batch_size=128)["loss"]
              for _ in range(4)]
    assert losses[-1] < losses[0] * 0.95, losses
    assert remote.size() > 0
    cli.close()
    server.stop()


def test_swap_conn_connects_outside_lock_and_handles_races(monkeypatch):
    """Regression (py_locks blocking-under-lock): _swap_conn builds the
    replacement conn OUTSIDE _conns_mu (a connect deadline must not
    stall healthy shards' ops) and closes the fresh conn when a
    concurrent swap or a topology shrink wins the race."""
    from paddle_tpu.ps import rpc as rpc_mod

    class FakeConn:
        def __init__(self, endpoint):
            self.endpoint = endpoint
            self.closed = False

        def close(self):
            self.closed = True

    lock_free_during_connect = []

    def fake_server_conn(lib, host, port, **kw):
        # the regression: the client lock must be FREE while connecting
        lock_free_during_connect.append(
            cli._conns_mu.acquire(timeout=0.5))
        cli._conns_mu.release()
        return FakeConn(f"{host}:{port}")

    monkeypatch.setattr(rpc_mod, "_ServerConn", fake_server_conn)
    cli = rpc_mod.RpcPsClient.__new__(rpc_mod.RpcPsClient)
    cli._conns_mu = threading.Lock()
    cli._lib = None
    cli._conn_kw = {}
    old = FakeConn("127.0.0.1:1000")
    cli._conns = [old]

    cli._swap_conn(0, "127.0.0.1:2000")
    assert lock_free_during_connect == [True]
    assert cli._conns[0].endpoint == "127.0.0.1:2000"
    assert old.closed and not cli._conns[0].closed

    # idempotent: same endpoint again is a no-op (no connect at all)
    cli._swap_conn(0, "127.0.0.1:2000")
    assert len(lock_free_during_connect) == 1

    # raced: another thread swaps to the target endpoint between the
    # check and the install -> the fresh conn is the stray and closes
    current = cli._conns[0]

    def racing_server_conn(lib, host, port, **kw):
        c = FakeConn(f"{host}:{port}")
        cli._conns[0] = FakeConn(f"{host}:{port}")   # the racer wins
        return c

    monkeypatch.setattr(rpc_mod, "_ServerConn", racing_server_conn)
    cli._swap_conn(0, "127.0.0.1:3000")
    assert cli._conns[0].endpoint == "127.0.0.1:3000"
    # a shrink mid-swap: index beyond topology is a clean no-op
    cli._conns = []
    cli._swap_conn(0, "127.0.0.1:4000")
    assert cli._conns == []
