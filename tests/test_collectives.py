import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.ops import collectives as coll


@pytest.fixture(scope="module")
def mesh8():
    return mesh_mod.make_mesh({"x": 8})


def smap(mesh, in_specs, out_specs):
    def deco(f):
        return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)

    return deco


def test_all_reduce_sum(mesh8):
    x = jnp.arange(8.0)

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):
        return coll.all_reduce(x, "x")

    out = f(x)
    assert np.allclose(np.asarray(out), 28.0)


def test_all_reduce_max_min_avg(mesh8):
    x = jnp.arange(8.0)

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):
        return jnp.stack(
            [
                coll.all_reduce(x, "x", coll.ReduceOp.MAX)[0],
                coll.all_reduce(x, "x", coll.ReduceOp.MIN)[0],
                coll.all_reduce(x, "x", coll.ReduceOp.AVG)[0],
            ]
        )[None]

    out = np.asarray(f(x))
    assert np.allclose(out[0], [7.0, 0.0, 3.5])


def test_all_gather_and_split_inverse(mesh8):
    x = jnp.arange(16.0).reshape(8, 2)

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):  # x: [1, 2] per rank
        full = coll.all_gather(x, "x", concat_axis=0)  # [8, 2]
        back = coll.split_axis(full, "x", dim=0)  # [1, 2]
        return back

    out = f(x)
    assert np.allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter(mesh8):
    x = jnp.ones((8, 8))

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):  # [1, 8]
        return coll.reduce_scatter(x.reshape(8), "x").reshape(1, 1)

    out = f(x)
    assert np.allclose(np.asarray(out).reshape(-1), 8.0)


def test_all_to_all(mesh8):
    # rank r holds row of 8 values = r; after a2a column exchange each rank
    # holds one value from every rank
    x = jnp.repeat(jnp.arange(8.0)[:, None], 8, axis=1)

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):  # [1, 8] per rank -> [8, 1] per rank (row i = value from rank i)
        return coll.all_to_all(x, "x", split_axis_=1, concat_axis=0)

    out = np.asarray(f(x))  # stacked per-rank results: [64, 1]
    assert np.allclose(out.reshape(8, 8), np.tile(np.arange(8.0), (8, 1)))


def test_broadcast_and_reduce(mesh8):
    x = jnp.arange(8.0)

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):
        b = coll.broadcast(x, "x", root=3)
        r = coll.reduce(x, "x", root=2)
        return jnp.stack([b[0], r[0]])[None]

    out = np.asarray(f(x)).reshape(8, 2)
    assert np.allclose(out[:, 0], 3.0)  # all ranks got root 3's value
    assert out[2, 1] == 28.0 and np.allclose(np.delete(out[:, 1], 2), 0.0)


def test_shift_ring(mesh8):
    x = jnp.arange(8.0)

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):
        return coll.shift(x, "x", offset=1)

    out = np.asarray(f(x)).reshape(-1)
    assert np.allclose(out, np.roll(np.arange(8.0), 1))


def test_process_group_api(mesh8):
    pg = coll.ProcessGroup("x")
    x = jnp.arange(8.0)

    @smap(mesh8, (P("x"),), P("x"))
    def f(x):
        return pg.all_reduce(x) + pg.rank().astype(jnp.float32)

    out = np.asarray(f(x)).reshape(-1)
    assert np.allclose(out, 28.0 + np.arange(8))
