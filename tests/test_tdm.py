"""TDM tree-based deep match (models/tdm.py): the reference treebased
family — TreeIndex/LayerWiseSampler (index_dataset) feeding a jitted
user×node tower trained over the sparse PS cache, with beam-search
retrieval (BeamSearchSampler role). Synthetic signal: users behave
within an item cluster and the target comes from the same cluster —
after training, beam search must retrieve in-cluster items."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.data.index_dataset import LayerWiseSampler, TreeIndex
from paddle_tpu.models.tdm import (TDM, beam_search_retrieve,
                                   make_tdm_train_step, node_keys,
                                   tdm_sample_batch)
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import CacheConfig, HbmEmbeddingCache
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

N_ITEMS, BRANCH = 32, 2
N_CLUSTERS = 4  # items i belong to cluster i % 4... no: contiguous blocks


def _setup(rng, dim=8):
    # items 0..31 as leaves IN ORDER: contiguous blocks of 8 share a
    # cluster AND a subtree — the tree structure matches the signal,
    # the setting TDM exists for
    tree = TreeIndex(list(range(N_ITEMS)), branch=BRANCH)
    sampler = LayerWiseSampler(
        tree, layer_counts=[1] * tree.height, seed=0,
        start_sample_layer=1)

    sgd = SGDRuleConfig(learning_rate=0.1)
    acc = AccessorConfig(embedx_dim=dim, embedx_threshold=0.0, sgd=sgd)
    table = MemorySparseTable(TableConfig(shard_num=2,
                                          accessor_config=acc))
    cache_cfg = CacheConfig(capacity=1 << 8, embedx_dim=dim,
                            embedx_threshold=0.0, sgd=sgd)
    cache = HbmEmbeddingCache(table, cache_cfg, device_map=True)
    all_codes = np.arange(tree.total_node_num())
    cache.begin_pass(node_keys(all_codes))
    # random-init node embeddings (bilinear-ish objective — see the
    # deepwalk saddle note)
    cache.state["embedx_w"] = jnp.asarray(
        rng.normal(scale=0.1,
                   size=cache.state["embedx_w"].shape).astype(np.float32))
    return tree, sampler, cache, cache_cfg


def _gen_batch(rng, tree, sampler, cache, B=32, U=3):
    cluster = rng.integers(0, N_CLUSTERS, B)
    lo = cluster * (N_ITEMS // N_CLUSTERS)
    behav = lo[:, None] + rng.integers(0, N_ITEMS // N_CLUSTERS, (B, U))
    target = lo + rng.integers(0, N_ITEMS // N_CLUSTERS, B)
    codes, labels = tdm_sample_batch(sampler, target)
    leaf = np.array([int(tree.get_travel_codes(i)[0])
                     for i in range(N_ITEMS)])
    rows_user = cache.lookup(node_keys(leaf[behav].reshape(-1))).reshape(
        B, U)
    rows_node = cache.lookup(node_keys(codes.reshape(-1))).reshape(
        codes.shape)
    return (jnp.asarray(rows_user, jnp.int32),
            jnp.asarray(rows_node, jnp.int32),
            jnp.asarray(labels), cluster, target)


def test_tdm_learns_and_retrieves(rng):
    pt.seed(0)
    dim = 8
    tree, sampler, cache, cache_cfg = _setup(rng, dim)
    model = TDM(embedx_dim=dim, hidden=(32, 16))
    opt = optimizer.Adam(1e-2)
    params = {"params": dict(model.named_parameters()), "buffers": {}}
    opt_state = opt.init(params)
    step = make_tdm_train_step(model, opt, cache_cfg, donate=False)

    losses = []
    for it in range(150):
        ru, rn, lb, _, _ = _gen_batch(rng, tree, sampler, cache)
        params, opt_state, cache.state, loss = step(
            params, opt_state, cache.state, ru, rn, lb)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert np.mean(losses[-10:]) < np.mean(losses[:10]) * 0.8, (
        np.mean(losses[:10]), np.mean(losses[-10:]))

    # retrieval: a user who behaved in cluster c must get mostly
    # in-cluster items from the beam (k=8 of 32 items; chance = 25%)
    hits, total = 0, 0
    for c in range(N_CLUSTERS):
        lo = c * (N_ITEMS // N_CLUSTERS)
        user_items = [lo, lo + 3, lo + 5]
        got = beam_search_retrieve(tree, model, params, cache,
                                   user_items, k=8)
        assert got, "beam returned no items"
        in_cluster = sum(1 for i in got if lo <= i < lo + 8)
        hits += in_cluster
        total += len(got)
    assert hits / total > 0.5, (hits, total)

    # lifecycle: flush + rebuild serves identically
    cache.end_pass()
    cache.begin_pass(node_keys(np.arange(tree.total_node_num())))
    got2 = beam_search_retrieve(tree, model, params, cache,
                                [0, 3, 5], k=8)
    assert got2


def test_tdm_sampler_batch_shape(rng):
    tree = TreeIndex(list(range(16)), branch=2)
    sampler = LayerWiseSampler(tree, layer_counts=[1] * tree.height,
                               seed=0)
    codes, labels = tdm_sample_batch(sampler, np.array([0, 5, 9]))
    assert codes.shape == labels.shape == (3, 2 * tree.height)
    # one positive per sampled layer per pair
    assert (labels.sum(axis=1) == tree.height).all()
    # positives really are the target's ancestors
    for b, item in enumerate((0, 5, 9)):
        path = set(int(x) for x in tree.get_travel_codes(item))
        pos = set(int(c) for c, l in zip(codes[b], labels[b]) if l == 1)
        assert pos <= path
