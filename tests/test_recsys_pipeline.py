"""End-to-end recsys pipeline (ISSUE 18): staged deadline budgets,
cross-request ranking coalescing, the router remaining-budget bugfix,
recsys SLO rules, model inference smoke through the serving read path,
and the multi-host (subprocess) fleet member.

Layers, bottom-up: PipelineFrontend unit behavior over a stub router
(budget carving, early top-K cut + straggler metering, coalesce factor,
rank-queue deadline drops — deterministic under an injected clock); the
ISSUE 18 router pin tests (a hedge/reroute launched late carries the
MEASURED remaining budget, and a nearly-expired request cannot hedge
even when the hedge-loop hands maybe_hedge a stale timestamp);
obs/slo.py recsys_rules both directions; TDM/GRU4Rec/DSSM inference
served through a read-only ServingReplica + CachedLookup; and the
member_host subprocess member — wire lookups, model push, crash
fidelity (chaos: kill a member mid-stream, zero user-visible errors).
"""

import random
import time

import numpy as np
# eager: numpy.testing's lazy import forks (SVE probe) — deadlocks the
# sanitizer sweeps once cluster threads are live (test_serving.py note)
import numpy.testing  # noqa: F401
import pytest

from paddle_tpu.core.enforce import EnforceNotMet
from paddle_tpu.obs import slo
from paddle_tpu.obs import registry as obs_registry
from paddle_tpu.obs.registry import Registry
from paddle_tpu.obs.timeseries import MetricRing
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

from paddle_tpu.data.index_dataset import TreeIndex  # noqa: E402
from paddle_tpu.distributed import elastic  # noqa: E402
from paddle_tpu.models.dssm import DSSM, make_dssm_ranker  # noqa: E402
from paddle_tpu.models.gru4rec import (GRU4Rec,  # noqa: E402
                                       make_gru4rec_ranker)
from paddle_tpu.models.tdm import (TDM, ServingBeamSource,  # noqa: E402
                                   beam_search_retrieve, node_keys)
from paddle_tpu.ps import ha  # noqa: E402
from paddle_tpu.ps.hot_tier import (HotEmbeddingTier,  # noqa: E402
                                    HotTierConfig)
from paddle_tpu.serving import (CachedLookup, DeadlineExceeded,  # noqa: E402
                                PipelineConfig, PipelineFrontend,
                                RequestRejected, RouterConfig,
                                ServingReplica, ServingRouter,
                                spawn_member)


# ---------------------------------------------------------------------------
# stub plumbing: pipeline unit tests (no cluster, no RPC)
# ---------------------------------------------------------------------------

class _Clk:
    """Injectable clock: tests advance ``t`` by assignment."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _rows_for(keys, width=3):
    """Deterministic member/lookup rows: [show=1, key, 1, ...]."""
    k = np.asarray(keys, np.float64)
    out = np.ones((len(k), width), np.float32)
    out[:, 1] = k
    return out


class _FanRR:
    """RoutedRequest-shaped stub the pipeline's fan callbacks drive."""

    def __init__(self, keys, deadline_ms):
        self.keys = np.asarray(keys, np.uint64)
        self.deadline_ms = deadline_ms
        self.value = None
        self.error = None
        self._done = False
        self._cbs = []

    def add_done_callback(self, fn):
        if self._done:
            fn(self)
        else:
            self._cbs.append(fn)

    def settle(self, value=None, error=None):
        self.value, self.error, self._done = value, error, True
        for cb in self._cbs:
            cb(self)


class _PipeRouter:
    """Stub fleet: records every fan sub-request (keys + the deadline
    the pipeline carved); ``auto=True`` answers immediately with
    deterministic rows, ``auto=False`` leaves settling to the test."""

    def __init__(self, auto=True, width=3):
        self.auto = auto
        self.width = width
        self.requests = []

    def submit(self, keys, deadline_ms=None, **kw):
        rr = _FanRR(keys, deadline_ms)
        self.requests.append(rr)
        if self.auto:
            rr.settle(value=_rows_for(keys, self.width))
        return rr


class _PipeLookup:
    """Ranking-side embedding source (one fused gather per batch)."""

    def __init__(self, width=3):
        self.width = width
        self.calls = 0
        self.sizes = []

    def lookup(self, keys):
        self.calls += 1
        self.sizes.append(len(keys))
        return _rows_for(keys, self.width)


_UV = np.array([1.0, 0.0], np.float32)    # retrieval score == key value


def _pipe(router=None, lookup=None, **cfg_kw):
    cfg_kw.setdefault("fanout", 2)
    cfg_kw.setdefault("fan_width", 4)
    cfg_kw.setdefault("topk", 4)
    cfg_kw.setdefault("early_cut_frac", 1.0)
    cfg_kw.setdefault("rank_max_delay_us", 200)
    clock = cfg_kw.pop("clock", time.perf_counter)
    idle = cfg_kw.pop("idle_pop_s", 0.002)
    return PipelineFrontend(router or _PipeRouter(),
                            lookup or _PipeLookup(),
                            config=PipelineConfig(**cfg_kw),
                            clock=clock, idle_pop_s=idle)


# ---------------------------------------------------------------------------
# pipeline: staged budgets, early cut, coalescing
# ---------------------------------------------------------------------------

def test_pipeline_basic_topk_ordering_and_fused_gather():
    router, lookup = _PipeRouter(), _PipeLookup()
    with _pipe(router, lookup) as pipe:
        pr = pipe.submit(_UV, [10, 11], np.arange(1, 9, dtype=np.uint64))
        keys, scores = pr.result(10)
        # retrieval scores == key → top-4 of 1..8, best first; the
        # default ranker (mean-history · candidate) preserves the order
        assert list(keys) == [8, 7, 6, 5]
        assert (np.diff(scores) < 0).all()
        st = pipe.stats()
        assert st["accepted"] == st["served"] == st["early_cuts"] == 1
        assert st["errors"] == st["shed"] == 0
        assert st["stragglers_abandoned"] == st["stragglers_late"] == 0
        # ONE gather carried history + top-K together
        assert lookup.calls == 1 and lookup.sizes[0] == 2 + 4
        assert st["e2e_ms"]["count"] == 1
        assert st["stage_retrieval_ms"]["count"] == 1
        assert st["stage_ranking_ms"]["count"] == 1


def test_pipeline_budget_carving_is_retrieval_share_of_remaining():
    clk = _Clk()
    router = _PipeRouter()
    with _pipe(router, clock=clk, retrieval_frac=0.6) as pipe:
        pr = pipe.submit(_UV, [1, 2], np.arange(8, dtype=np.uint64),
                         deadline_ms=100.0)
        pr.result(10)
        # frozen clock: nothing elapsed between accept and fan-out, so
        # each fan's sub-deadline is EXACTLY the retrieval share
        assert len(router.requests) == 2
        for rr in router.requests:
            assert rr.deadline_ms == pytest.approx(60.0)


def test_pipeline_coalesces_across_concurrent_requests():
    with _pipe(fanout=1, topk=2, rank_max_delay_us=100_000,
               rank_max_batch=64) as pipe:
        pending = [pipe.submit(_UV, [30 + i, 40 + i],
                               np.arange(4 * i, 4 * i + 4, dtype=np.uint64))
                   for i in range(8)]
        for pr in pending:
            keys, scores = pr.result(10)
            assert keys.shape == scores.shape == (2,)
        st = pipe.stats()
        assert st["served"] == 8
        # the whole burst landed in fewer stacked infers than requests
        assert st["rank_batches"] < 8
        assert st["coalesce_factor"] > 1.0


def test_pipeline_early_cut_meters_stragglers():
    router = _PipeRouter(auto=False)
    with _pipe(router, fanout=4, fan_width=2,
               early_cut_frac=0.75) as pipe:
        pr = pipe.submit(_UV, [20, 21], np.arange(1, 9, dtype=np.uint64))
        fans = router.requests
        assert len(fans) == 4
        for rr in fans[:3]:                 # need = ceil(.75×4) = 3
            rr.settle(value=_rows_for(rr.keys))
        keys, _ = pr.result(10)
        # only the settled fans' pool (keys 1..6) competed
        assert list(keys) == [6, 5, 4, 3]
        st = pipe.stats()
        assert st["early_cuts"] == 1
        assert st["stragglers_abandoned"] == 1
        # the abandoned fan answers anyway → metered late, not delivered
        fans[3].settle(value=_rows_for(fans[3].keys))
        assert pipe.stats()["stragglers_late"] == 1
        assert pipe.stats()["served"] == 1


def test_pipeline_fan_failures_partial_and_total():
    router = _PipeRouter(auto=False)
    with _pipe(router, fanout=4, fan_width=2,
               early_cut_frac=0.75) as pipe:
        # partial: one fan fails, the cut still fires off three values
        pr = pipe.submit(_UV, [7, 8], np.arange(1, 9, dtype=np.uint64))
        fans = router.requests
        fans[0].settle(error=RequestRejected("member down"))
        for rr in fans[1:]:
            rr.settle(value=_rows_for(rr.keys))
        keys, _ = pr.result(10)
        assert list(keys) == [8, 7, 6, 5]
        assert pipe.stats()["fan_failures"] == 1
        assert pipe.stats()["errors"] == 0
        # total: every fan fails → the request fails with the last error
        pr2 = pipe.submit(_UV, [7, 8], np.arange(1, 9, dtype=np.uint64))
        for rr in router.requests[4:]:
            rr.settle(error=RequestRejected("fleet gone"))
        with pytest.raises(RequestRejected):
            pr2.result(10)
        st = pipe.stats()
        assert st["fan_failures"] == 1 + 4 and st["errors"] == 1


def test_pipeline_budget_spent_in_retrieval_is_deadline_exceeded():
    clk = _Clk()
    router = _PipeRouter(auto=False)
    with _pipe(router, clock=clk) as pipe:
        pr = pipe.submit(_UV, [1, 2], np.arange(8, dtype=np.uint64),
                         deadline_ms=50.0)
        clk.t = 0.2                          # fans answer after the budget
        for rr in router.requests:
            rr.settle(value=_rows_for(rr.keys))
        with pytest.raises(DeadlineExceeded):
            pr.result(10)
        assert pipe.stats()["retrieval_deadline"] == 1


def test_pipeline_drops_requests_expired_in_rank_queue():
    clk = _Clk()
    # a long coalesce window holds the batch open while the test
    # expires the request's deadline on the injected clock
    with _pipe(clock=clk, rank_max_delay_us=200_000,
               default_deadline_ms=50.0) as pipe:
        pr = pipe.submit(_UV, [1, 2], np.arange(8, dtype=np.uint64))
        clk.t = 1.0
        with pytest.raises(DeadlineExceeded):
            pr.result(10)
        assert pipe.stats()["rank_deadline_dropped"] == 1
        assert pipe.stats()["served"] == 0


def test_pipeline_shape_pins():
    with _pipe() as pipe:
        # candidate count must be exactly fanout × fan_width
        with pytest.raises(EnforceNotMet):
            pipe.submit(_UV, [1, 2], np.arange(5, dtype=np.uint64))
        pipe.submit(_UV, [1, 2], np.arange(8, dtype=np.uint64)).result(10)
        # history length pins on first submit (one stacked ranker shape)
        with pytest.raises(EnforceNotMet):
            pipe.submit(_UV, [1, 2, 3], np.arange(8, dtype=np.uint64))


def test_pipeline_stage_metric_families_in_registry():
    with _pipe() as pipe:
        pipe.submit(_UV, [1, 2], np.arange(8, dtype=np.uint64)).result(10)
        snap = obs_registry.REGISTRY.snapshot()["metrics"]
        stages = {s["labels"].get("stage")
                  for s in snap["serving_stage_latency_s"]["series"]}
        assert {"retrieval", "ranking"} <= stages
        e2e = [s for s in snap["serving_latency_s"]["series"]
               if s["labels"].get("recorder") == "recsys_e2e"]
        assert e2e and sum(s["count"] for s in e2e) >= 1


def test_pipeline_stop_rejects_and_fails_queued():
    with _pipe() as pipe:
        pipe.stop()
        with pytest.raises(RequestRejected):
            pipe.submit(_UV, [1, 2], np.arange(8, dtype=np.uint64))


# ---------------------------------------------------------------------------
# router pin tests (ISSUE 18 bugfix): remaining budget, stale-now hedge
# ---------------------------------------------------------------------------

class _RecSub:
    """PendingResult-shaped stub settled by the test (the frontend's
    zero-arg callback convention)."""

    def __init__(self):
        self._cbs = []
        self._err = None
        self._val = None
        self._done = False

    def add_done_callback(self, fn):
        if self._done:
            fn()
        else:
            self._cbs.append(fn)

    def exception(self):
        return self._err

    def value(self):
        return self._val

    def settle(self, val=None, err=None):
        self._val, self._err, self._done = val, err, True
        for cb in self._cbs:
            cb()


class _RecFrontend:
    """Member frontend recording the deadline each sub-request carried."""

    def __init__(self):
        self.deadlines = []
        self.subs = []
        self.queue_depth = 0
        self.stopped = False

    def submit(self, keys, dense=None, deadline_ms=None):
        self.deadlines.append(float(deadline_ms))
        sub = _RecSub()
        self.subs.append(sub)
        return sub


class _RecMember:
    def __init__(self, name):
        self.endpoint = name
        self.healthy = True
        self.frontend = _RecFrontend()


def _pin_router(clk, **cfg_kw):
    cfg_kw.setdefault("hedge_default_ms", 20.0)
    cfg_kw.setdefault("hedge_min_samples", 1 << 30)
    r = ServingRouter(RouterConfig(**cfg_kw), rng=random.Random(0),
                      clock=clk)
    members = [_RecMember("m0"), _RecMember("m1")]
    for m in members:
        r.attach(m)
    return r, members


def _total_subs(members):
    return sum(len(m.frontend.deadlines) for m in members)


def _all_deadlines(members):
    return [d for m in members for d in m.frontend.deadlines]


def test_hedge_carries_measured_remaining_budget():
    clk = _Clk()
    r, members = _pin_router(clk)
    try:
        rr = r.submit(np.arange(8, dtype=np.uint64), deadline_ms=100.0)
        assert _all_deadlines(members) == [pytest.approx(100.0)]
        # 60 ms into a 100 ms request: the hedge header must say 40,
        # never the original 100 (the pinned bugfix)
        clk.t = 0.060
        assert rr.maybe_hedge() is True
        assert sorted(_all_deadlines(members)) == [
            pytest.approx(40.0), pytest.approx(100.0)]
        assert rr.tried == ["m0", "m1"] or rr.tried == ["m1", "m0"]
        # the hedge wins; the primary's late answer is deduped
        for m in members:
            for sub in m.frontend.subs:
                sub.settle(val=_rows_for(np.arange(8)))
        assert rr.result(10).shape == (8, 3)
    finally:
        r.stop()


def test_nearly_expired_request_cannot_hedge_with_stale_now():
    clk = _Clk()
    r, members = _pin_router(clk)
    try:
        rr = r.submit(np.arange(8, dtype=np.uint64), deadline_ms=100.0)
        # the request expires; the hedge loop wakes with a timestamp it
        # captured BEFORE the batch — the fresh-clock re-check must
        # refuse to launch a duplicate with a fabricated budget
        clk.t = 0.250
        assert rr.maybe_hedge(now=0.030) is False
        assert _total_subs(members) == 1
        assert rr.hedged is False            # aborted, not launched
        # sub-millimeter remaining (0.5 ms < min_sub_budget_ms): same
        clk.t = 0.0995
        assert rr.maybe_hedge(now=0.030) is False
        assert _total_subs(members) == 1
    finally:
        r.stop()


def test_reroute_inherits_remaining_and_expiry_is_final():
    clk = _Clk()
    r, members = _pin_router(clk, hedge=False)
    try:
        # mid-life failure: the reroute carries 100 − 30 = 70 ms
        rr = r.submit(np.arange(8, dtype=np.uint64), deadline_ms=100.0)
        clk.t = 0.030
        first = [m for m in members if m.frontend.subs][0]
        first.frontend.subs[0].settle(err=RuntimeError("conn reset"))
        assert _total_subs(members) == 2
        assert sorted(_all_deadlines(members)) == [
            pytest.approx(70.0), pytest.approx(100.0)]
        other = [m for m in members if m is not first][0]
        other.frontend.subs[0].settle(val=_rows_for(np.arange(8)))
        assert rr.result(10).shape == (8, 3)
        assert r.stats()["reroutes"] == 1

        # budget already spent: a failure must NOT reroute
        base = _total_subs(members)
        clk.t = 1.0
        rr2 = r.submit(np.arange(8, dtype=np.uint64), deadline_ms=50.0)
        clk.t = 1.2
        last = [m for m in members if len(m.frontend.subs)
                and not m.frontend.subs[-1]._done][0]
        last.frontend.subs[-1].settle(err=RuntimeError("conn reset"))
        assert _total_subs(members) == base + 1
        with pytest.raises(RuntimeError):
            rr2.result(10)

        # DeadlineExceeded from a member is final even with budget left
        base = _total_subs(members)
        clk.t = 2.0
        rr3 = r.submit(np.arange(8, dtype=np.uint64), deadline_ms=100.0)
        clk.t = 2.01
        last = [m for m in members if len(m.frontend.subs)
                and not m.frontend.subs[-1]._done][0]
        last.frontend.subs[-1].settle(err=DeadlineExceeded("member"))
        assert _total_subs(members) == base + 1
        with pytest.raises(DeadlineExceeded):
            rr3.result(10)
    finally:
        r.stop()


# ---------------------------------------------------------------------------
# obs: recsys SLO rules, both directions
# ---------------------------------------------------------------------------

def _recsys_ring(pattern, family, labels, dt=0.05):
    """Ring with one recsys-labeled histogram: 'g' ticks observe 0.05
    (good vs threshold 1.0), 'b' ticks 5.0 (bad)."""
    reg = Registry()
    h = reg.histogram(family, buckets=(0.1, 1.0), **labels)
    ring = MetricRing()
    t = 0.0
    for ch in pattern:
        h.observe(0.05 if ch == "g" else 5.0)
        ring.append(reg.snapshot(), t=t)
        t += dt
    return ring, t - dt


def _recsys_rule(name):
    rules = slo.recsys_rules(e2e_p99_s=1.0, stage_retrieval_p99_s=1.0,
                             freshness_training_p95_s=1.0)
    return next(r for r in rules if r.name == name)


def test_recsys_e2e_rule_fires_and_stays_quiet():
    rule = _recsys_rule("recsys_e2e_p99")
    labels = {"recorder": "recsys_e2e", "replica": "-"}
    ring, now = _recsys_ring("g" * 150 + "b" * 150,
                             "serving_latency_s", labels)
    fired = slo.SloWatchdog(ring, [rule]).evaluate(now=now)
    assert [a.rule for a in fired] == ["recsys_e2e_p99"]
    ring2, now2 = _recsys_ring("g" * 300, "serving_latency_s", labels)
    assert slo.SloWatchdog(ring2, [rule]).evaluate(now=now2) == []
    # label selectivity: a burning NON-recsys recorder must not page it
    ring3, now3 = _recsys_ring("b" * 300, "serving_latency_s",
                               {"recorder": "request", "replica": "-"})
    assert slo.SloWatchdog(ring3, [rule]).evaluate(now=now3) == []


def test_recsys_stage_retrieval_rule_selects_its_stage():
    rule = _recsys_rule("recsys_stage_retrieval_p99")
    ring, now = _recsys_ring("g" * 150 + "b" * 150,
                             "serving_stage_latency_s",
                             {"recorder": "pipeline_stage", "replica": "-",
                              "stage": "retrieval"})
    fired = slo.SloWatchdog(ring, [rule]).evaluate(now=now)
    assert [a.rule for a in fired] == ["recsys_stage_retrieval_p99"]
    # a burning RANKING stage is the other triage branch — quiet here
    ring2, now2 = _recsys_ring("b" * 300, "serving_stage_latency_s",
                               {"recorder": "pipeline_stage",
                                "replica": "-", "stage": "ranking"})
    assert slo.SloWatchdog(ring2, [rule]).evaluate(now=now2) == []


def test_freshness_under_training_rule_both_directions():
    rule = _recsys_rule("freshness_under_training")
    labels = {"recorder": "freshness", "replica": "-"}
    ring, now = _recsys_ring("g" * 100 + "b" * 100,
                             "serving_latency_s", labels)
    fired = slo.SloWatchdog(ring, [rule]).evaluate(now=now)
    assert [a.rule for a in fired] == ["freshness_under_training"]
    ring2, now2 = _recsys_ring("g" * 200, "serving_latency_s", labels)
    assert slo.SloWatchdog(ring2, [rule]).evaluate(now=now2) == []


def test_recsys_rules_default_stage_budget_is_retrieval_share():
    rules = {r.name: r for r in slo.recsys_rules(e2e_p99_s=0.5)}
    assert rules["recsys_stage_retrieval_p99"].threshold == \
        pytest.approx(0.3)
    assert rules["recsys_e2e_p99"].labels == {"recorder": "recsys_e2e"}
    assert rules["freshness_under_training"].budget == pytest.approx(0.05)


# ---------------------------------------------------------------------------
# real-cluster plumbing (shared with the model smoke + subprocess tests)
# ---------------------------------------------------------------------------

def _acc(dim=4):
    return AccessorConfig(embedx_dim=dim, embedx_threshold=0.0,
                          sgd=SGDRuleConfig(initial_range=0.01))


def _cfg(dim=4):
    return TableConfig(shard_num=4, accessor_config=_acc(dim))


def _push(rng, keys, width):
    push = np.zeros((len(keys), width), np.float32)
    push[:, 1] = 1.0
    push[:, 2:] = rng.normal(0, 0.1, (len(keys), width - 2)).astype(
        np.float32)
    return push


def _cluster(**kw):
    kw.setdefault("num_shards", 1)
    kw.setdefault("replication", 1)
    kw.setdefault("sync", True)
    return ha.HACluster(**kw)


def _preload(cli, keys, rng, dim=4):
    cli.create_sparse_table(0, _cfg(dim))
    cli.pull_sparse(0, keys)
    width = cli._dims(0)[1]
    cli.push_sparse(0, keys, _push(rng, keys, width))
    return width


def _wait_caught_up(cluster, serve_cli, table_id=0, timeout=15.0):
    deadline = time.monotonic() + timeout
    while True:
        prim = cluster.primary(0)
        dg_p = cluster.digests(table_id, 0).get(prim.endpoint)
        dg_r = serve_cli.digest(table_id)[0]
        if dg_p is not None and dg_p == dg_r:
            return
        assert time.monotonic() < deadline, "replica never caught up"
        time.sleep(0.02)


def _serving_stack(cluster, dim=4, capacity=1 << 12):
    """Read-only replica + caught-up CachedLookup (the serve path every
    model smoke test pulls embeddings through)."""
    rep = ServingReplica(cluster.store, cluster.job_id, shard=0,
                         hb_interval=0.05, hb_ttl=0.5)
    serve = rep.client()
    view = rep.serve_view(0, _cfg(dim), client=serve)
    _wait_caught_up(cluster, serve)
    tier = HotEmbeddingTier(view, HotTierConfig(
        capacity=capacity, create_on_miss=False))
    return rep, CachedLookup(tier, replica=rep, freshness_budget_s=30.0)


def _emb_block(lookup, keys_2d):
    """[B, S] uint64 keys → [B, S, width] served embedding block."""
    keys_2d = np.asarray(keys_2d, np.uint64)
    rows = np.asarray(lookup.lookup(keys_2d.reshape(-1)), np.float32)
    return rows.reshape(keys_2d.shape + (rows.shape[-1],))


# ---------------------------------------------------------------------------
# model inference smoke through the serving read path (satellite 1)
# ---------------------------------------------------------------------------

def test_tdm_beam_search_through_serving_lookup():
    tree = TreeIndex(list(range(16)), branch=2)
    with _cluster() as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(0)
        _preload(cli, node_keys(np.arange(tree.total_node_num())), rng)
        rep, lookup = _serving_stack(cluster)
        try:
            model = TDM(embedx_dim=4, hidden=(8, 8))
            params = {"params": dict(model.named_parameters()),
                      "buffers": {}}
            src = ServingBeamSource(lookup, capacity=1 << 10)
            got = beam_search_retrieve(tree, model, params, src,
                                       [0, 3, 5], k=4)
            assert got and len(got) <= 4
            assert all(0 <= i < 16 for i in got)
            assert src.flushes == 0
            # a second walk reuses the resident block
            got2 = beam_search_retrieve(tree, model, params, src,
                                        [9, 12], k=4)
            assert got2
        finally:
            rep.close()


def test_gru4rec_ranker_over_served_embeddings():
    with _cluster() as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(1)
        keys = np.arange(64, dtype=np.uint64)
        _preload(cli, keys, rng)
        rep, lookup = _serving_stack(cluster)
        try:
            model = GRU4Rec(embedx_dim=4, hidden=8, out_dim=8)
            ranker = make_gru4rec_ranker(model)
            B, H, K = 3, 5, 4
            hist = _emb_block(lookup, keys[:B * H].reshape(B, H))
            cand = _emb_block(lookup, keys[32:32 + B * K].reshape(B, K))
            lengths = np.full(B, H, np.int32)
            scores = ranker(hist, lengths, cand)
            assert scores.shape == (B, K)
            assert np.isfinite(scores).all()
            # cosine of L2-normalized towers, and deterministic
            assert (np.abs(scores) <= 1.0 + 1e-5).all()
            np.testing.assert_array_equal(
                scores, ranker(hist, lengths, cand))
        finally:
            rep.close()


def test_dssm_ranker_over_served_embeddings():
    with _cluster() as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(2)
        keys = np.arange(64, dtype=np.uint64)
        _preload(cli, keys, rng)
        rep, lookup = _serving_stack(cluster)
        try:
            model = DSSM(num_query_slots=3, num_doc_slots=1,
                         embedx_dim=4, hidden=(8,), out_dim=8)
            ranker = make_dssm_ranker(model)
            B, K = 2, 4
            hist = _emb_block(lookup, keys[:B * 3].reshape(B, 3))
            cand = _emb_block(lookup, keys[40:40 + B * K].reshape(B, K))
            scores = ranker(hist, np.full(B, 3, np.int32), cand)
            assert scores.shape == (B, K)
            assert np.isfinite(scores).all()
            assert (np.abs(scores) <= 1.0 + 1e-5).all()
        finally:
            rep.close()
    # contract guard: a multi-slot doc tower cannot be a pipeline ranker
    with pytest.raises(ValueError):
        make_dssm_ranker(DSSM(num_query_slots=2, num_doc_slots=2,
                              embedx_dim=4))


def test_pipeline_with_real_ranker_and_served_lookup():
    """Stub retrieval fleet, REAL ranking stage: coalesced CachedLookup
    gather + stacked GRU4Rec infer, scattered back per request."""
    with _cluster() as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(4)
        keys = np.arange(256, dtype=np.uint64)
        _preload(cli, keys, rng)
        rep, lookup = _serving_stack(cluster)
        try:
            model = GRU4Rec(embedx_dim=4, hidden=8, out_dim=8)
            pipe = PipelineFrontend(
                _PipeRouter(), lookup, ranker=make_gru4rec_ranker(model),
                config=PipelineConfig(fanout=2, fan_width=4, topk=4,
                                      early_cut_frac=1.0,
                                      rank_max_delay_us=20_000),
                idle_pop_s=0.002)
            with pipe:
                pending = [pipe.submit(_UV, keys[i * 2:i * 2 + 2],
                                       keys[64 + 8 * i:72 + 8 * i])
                           for i in range(6)]
                for pr in pending:
                    ks, sc = pr.result(30)
                    assert ks.shape == sc.shape == (4,)
                    assert np.isfinite(sc).all()
                    assert (np.diff(sc) <= 1e-6).all()   # best first
                st = pipe.stats()
                assert st["served"] == 6 and st["errors"] == 0
                assert st["coalesce_factor"] > 1.0
        finally:
            rep.close()


# ---------------------------------------------------------------------------
# multi-host member (subprocess) + chaos
# ---------------------------------------------------------------------------

def test_spawn_member_subprocess_end_to_end(tmp_path):
    store_dir = str(tmp_path / "store")
    with _cluster(store=elastic.FileStore(store_dir)) as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(3)
        keys = np.arange(256, dtype=np.uint64)
        _preload(cli, keys, rng)
        member = spawn_member(f"file:{store_dir}", cluster.job_id,
                              embedx_dim=4, dense_len=8, hb_ttl=1.0)
        rep = None
        try:
            assert member.healthy
            out = member.frontend.submit(keys[:8],
                                         deadline_ms=5000).result(15)
            assert out.shape == (8, 5)
            # the child process serves the SAME rows the parent-side
            # replica reads — the wire is value-faithful
            rep, lookup = _serving_stack(cluster)
            np.testing.assert_allclose(
                out, np.asarray(lookup.lookup(keys[:8]), np.float32),
                rtol=1e-6)
            status = member.replica.status()
            assert status["multi_host"] is True
            # dense model push over the wire: version + digest echo
            v0, d0 = member.model.identity()
            member.model.set(v0 + 1, np.ones(8, np.float32))
            v1, d1 = member.model.identity()
            assert v1 == v0 + 1 and d1 != d0
            # digest pinning rejects a mismatched payload (rollback arm)
            with pytest.raises(RuntimeError):
                member.model.set(v1 + 1, np.zeros(8, np.float32),
                                 expect_digest=d1)
            # warm op + proxied child-frontend stats
            member.warm(keys[:32])
            stats = member.frontend.stats()
            assert stats.get("served", 0) >= 1
        finally:
            if rep is not None:
                rep.close()
            member.frontend.stop()
            member.replica.stop()
    assert member.replica.server.stopped


@pytest.mark.slow
def test_pipeline_chaos_kill_member_zero_visible_errors(tmp_path):
    """ISSUE 18 chaos gate: kill one of two subprocess members while a
    request stream is in flight — reroute + early cut must keep EVERY
    request user-visible-error free."""
    store_dir = str(tmp_path / "store")
    with _cluster(store=elastic.FileStore(store_dir)) as cluster:
        cli = cluster.client()
        rng = np.random.default_rng(5)
        keys = np.arange(1024, dtype=np.uint64)
        _preload(cli, keys, rng)
        members = [spawn_member(f"file:{store_dir}", cluster.job_id,
                                embedx_dim=4, dense_len=8, hb_ttl=1.0)
                   for _ in range(2)]
        router = ServingRouter(RouterConfig(), rng=random.Random(0))
        pipe = PipelineFrontend(
            router, _PipeLookup(width=5),
            config=PipelineConfig(fanout=2, fan_width=8, topk=4,
                                  early_cut_frac=0.5,
                                  default_deadline_ms=4000.0,
                                  rank_max_delay_us=1000))
        try:
            for m in members:
                router.attach(m)
            uv = np.zeros(4, np.float32)
            uv[0] = 1.0
            hist = keys[:4]
            pending = []
            for i in range(40):
                lo = (i * 16) % 992
                pending.append(pipe.submit(uv, hist, keys[lo:lo + 16]))
                if i == 15:          # mid-stream, requests in flight
                    members[0].replica.kill()
                time.sleep(0.01)
            for pr in pending:
                ks, sc = pr.result(30)
                assert ks.shape == (4,) and np.isfinite(sc).all()
            st = pipe.stats()
            assert st["served"] == 40
            assert st["errors"] == 0
        finally:
            pipe.stop()
            router.stop()
            for m in members:
                m.crash()
