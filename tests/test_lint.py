"""graftlint self-tests: every rule in both directions (fires on the
violation fixture, stays quiet on the clean one), allowlist filtering,
and the run.py gate on the real tree."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools", "lint"))

import conventions  # noqa: E402
import lock_order  # noqa: E402
import tracer_safety  # noqa: E402
from common import load_allowlist, split_new_and_allowed  # noqa: E402


def _tracer_diags(tmp_path, source):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return tracer_safety.run(str(tmp_path))


def _rules(diags):
    return {d.rule for d in diags}


# -- tracer-safety ----------------------------------------------------------

def test_host_sync_in_jit_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return x.item()
    """)
    assert _rules(diags) == {"host-sync-item"}
    assert diags[0].path == "paddle_tpu/mod.py"
    assert diags[0].line == 6


def test_host_sync_outside_jit_not_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        def host_helper(x):
            return x.item()
    """)
    assert diags == []


def test_numpy_call_in_traced_callee_flagged(tmp_path):
    # reachability: the violation is in a helper CALLED from jitted code
    diags = _tracer_diags(tmp_path, """
        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        @jax.jit
        def step(x):
            return helper(x)
    """)
    assert _rules(diags) == {"host-sync-np"}


def test_shard_map_callsite_wrap_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax
        from jax import shard_map

        def make(mesh):
            def inner(x):
                jax.device_get(x)
                return x
            return jax.jit(shard_map(inner, mesh=mesh))
    """)
    assert _rules(diags) == {"host-sync-device-get"}


def test_tracer_branch_and_block_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            if jnp.any(x > 0):
                x = x + 1
            x.block_until_ready()
            return x
    """)
    assert _rules(diags) == {"tracer-branch", "host-sync-block"}


def test_float_cast_on_param_flagged_shape_exempt(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            n = int(x.shape[0])   # static: fine
            return x * float(x)   # concretizes: flagged
    """)
    assert _rules(diags) == {"host-float-cast"}
    assert all(d.line == 7 for d in diags)


def test_float_cast_on_derived_value_flagged(tmp_path):
    # taint flows through local assignments, not just direct params
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            y = x * 2
            return float(y)
    """)
    assert _rules(diags) == {"host-float-cast"}
    assert [d.line for d in diags] == [7]


def test_branch_on_param_compare_flagged_config_exempt(tmp_path):
    # `if x > 0` is the canonical TracerBoolConversionError; string
    # equality / is-tests / bare truthiness are static config dispatch
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x, mode="sum", flag=True, opt=None):
            if mode == "sum":      # static config: fine
                x = x + 1
            if opt is None:        # static config: fine
                x = x + 2
            if flag:               # bare truthiness: fine
                x = x + 3
            y = x - 1
            if y > 0:              # tracer compare: flagged
                x = x + 4
            return x
    """)
    assert _rules(diags) == {"tracer-branch"}
    assert [d.line for d in diags] == [13]


def test_host_print_flagged_only_inside_trace(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            print("debug", x)
            return x

        def host_log(x):
            print("fine here", x)
    """)
    assert _rules(diags) == {"host-print"}
    assert [d.line for d in diags] == [6]


def test_global_mutation_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax
        _CALLS = 0

        @jax.jit
        def step(x):
            global _CALLS
            _CALLS += 1
            return x
    """)
    assert _rules(diags) == {"global-mutation"}


def test_ignore_comment_suppresses(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return x.item()  # graftlint: ignore[host-sync-item]
    """)
    assert diags == []


def test_traced_comment_marks_root(tmp_path):
    diags = _tracer_diags(tmp_path, """
        # graftlint: traced
        def bench_hot_path(x):
            return x.item()
    """)
    assert _rules(diags) == {"host-sync-item"}


# -- hot-path host transfers (pass 1b) --------------------------------------

def _hot_diags(tmp_path, source):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return tracer_safety.run_hot_path(str(tmp_path))


def test_hot_path_np_asarray_in_root_flagged(tmp_path):
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: hot-path
        def warm_step(state):
            return np.asarray(state["rows"])
    """)
    assert _rules(diags) == {"hot-host-transfer"}
    assert diags[0].line == 6


def test_hot_path_device_get_in_callee_flagged(tmp_path):
    # reachability: the transfer hides in a helper CALLED from the root
    diags = _hot_diags(tmp_path, """
        import jax
        import numpy as np

        def helper(x):
            return jax.device_get(x)

        # graftlint: hot-path
        def warm_step(state):
            return helper(state)
    """)
    assert _rules(diags) == {"hot-host-transfer"}
    assert diags[0].line == 6


def test_hot_path_cold_marked_callee_not_flagged(tmp_path):
    # a cold-path boundary stops traversal: the writeback/miss handlers
    # own their transfers by design
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: cold-path
        def writeback(state):
            return np.asarray(state["rows"])

        # graftlint: hot-path
        def warm_step(state):
            return writeback(state)
    """)
    assert diags == []


def test_hot_path_unmarked_function_not_flagged(tmp_path):
    # no hot-path roots → host numpy anywhere is fine
    diags = _hot_diags(tmp_path, """
        import numpy as np

        def host_helper(x):
            return np.asarray(x)
    """)
    assert diags == []


def test_hot_path_plain_np_math_not_flagged(tmp_path):
    # only ndarray-MATERIALIZING conversions flag; host math on the
    # control-plane mirror (zeros/where/lexsort...) is the design
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: hot-path
        def warm_step(keys):
            mask = np.zeros(4, bool)
            return np.where(mask, keys, 0)
    """)
    assert diags == []


def test_hot_path_ignore_comment(tmp_path):
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: hot-path
        def warm_step(patches):
            return np.asarray(patches)  # graftlint: ignore[hot-host-transfer]
    """)
    assert diags == []


# -- lock-order -------------------------------------------------------------

def _lock_diags(tmp_path, source, name="fixture.cc"):
    d = tmp_path / "paddle_tpu" / "csrc"
    d.mkdir(parents=True)
    (d / name).write_text(textwrap.dedent(source))
    return lock_order.run(str(tmp_path))


GOOD_CC = """
    // LOCK ORDER: outer_mu < inner_mu
    void f(T* t) {
      std::lock_guard<std::mutex> a(t->mu);  // LOCK: outer_mu
      std::lock_guard<std::mutex> b(t->sub->mu);  // LOCK: inner_mu
    }
"""


def test_lock_order_clean_file_passes(tmp_path):
    assert _lock_diags(tmp_path, GOOD_CC) == []


def test_lock_order_inversion_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        // LOCK ORDER: outer_mu < inner_mu
        void f(T* t) {
          std::lock_guard<std::mutex> b(t->sub->mu);  // LOCK: inner_mu
          std::lock_guard<std::mutex> a(t->mu);  // LOCK: outer_mu
        }
    """)
    assert _rules(diags) == {"lock-order"}


def test_lock_order_cycle_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        // LOCK ORDER: a_mu < b_mu
        // LOCK ORDER: b_mu < a_mu
        void f() {}
    """)
    assert _rules(diags) == {"lock-order-cycle"}


def test_unannotated_nesting_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        void f(T* t) {
          std::lock_guard<std::mutex> a(t->mu);
          std::lock_guard<std::mutex> b(t->other_mu);
        }
    """)
    assert _rules(diags) == {"lock-unannotated"}


def test_scoped_guard_released_before_second_lock(tmp_path):
    # the ps_service.cc kSaveAll pattern: registry lock scoped out
    # before the per-table lock — NOT nested
    diags = _lock_diags(tmp_path, """
        void f(T* t) {
          std::mutex* save_mu;
          {
            std::lock_guard<std::mutex> g(t->tables_mu);
            save_mu = t->lookup();
          }
          std::lock_guard<std::mutex> sg(*save_mu);
        }
    """)
    assert diags == []


def test_lock_leaf_violation_flagged(tmp_path):
    # a LEAF lock must be innermost: acquiring anything while it is
    # held fires, even if an ORDER decl would have allowed the nesting
    diags = _lock_diags(tmp_path, """
        // LOCK LEAF: conn_mu
        // LOCK ORDER: conn_mu < tables_mu
        void f(T* t) {
          std::lock_guard<std::mutex> g(t->conn_mu);
          std::lock_guard<std::mutex> h(t->tables_mu);
        }
    """)
    assert "lock-leaf" in _rules(diags)
    # declaring successors for a leaf is itself a decl error
    assert "lock-order-syntax" in _rules(diags)


def test_lock_leaf_nests_under_ordered_locks(tmp_path):
    # the other direction is the contract: a leaf may be taken while
    # any outer lock is held, with NO ORDER decl needed for it
    diags = _lock_diags(tmp_path, """
        // LOCK ORDER: tables_mu < save_mu
        // LOCK LEAF: bar_mu
        void f(T* t) {
          std::lock_guard<std::mutex> g(t->tables_mu);
          std::lock_guard<std::mutex> h(t->bar_mu);
        }
    """)
    assert diags == []


def test_lock_leaf_malformed_decl_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        // LOCK LEAF: conn-mu!
        void f() {}
    """)
    assert _rules(diags) == {"lock-order-syntax"}


def test_real_csrc_tree_is_clean():
    assert lock_order.run(REPO) == []


# -- conventions ------------------------------------------------------------

def _conv_diags(tmp_path, source, fname="paddle_tpu/mod.py"):
    p = tmp_path / fname
    p.parent.mkdir(parents=True, exist_ok=True)
    if fname.startswith("paddle_tpu"):
        init = tmp_path / "paddle_tpu" / "__init__.py"
        if not init.exists():
            init.write_text("")
    (tmp_path / "tools").mkdir(exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return conventions.run(str(tmp_path))


def test_time_time_flagged_perf_counter_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import time

        def measure(fn):
            t0 = time.perf_counter()   # fine
            fn()
            wall = time.time()         # flagged
            return time.perf_counter() - t0, wall
    """)
    assert [d.rule for d in diags] == ["time-time"]
    assert diags[0].line == 7


def test_from_time_import_time_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from time import time as now

        def deadline():
            return now() + 60
    """)
    assert [d.rule for d in diags] == ["time-time"]
    assert diags[0].line == 5


def test_conventions_tolerates_missing_tools_dir(tmp_path):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    assert conventions.run(str(tmp_path)) == []


def test_bare_except_and_mutable_default_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        def f(xs=[], opts=None):
            try:
                return xs
            except:
                return None
    """)
    assert _rules(diags) == {"bare-except", "mutable-default"}


def test_env_read_outside_config_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os
        PORT = os.environ.get("MY_PORT")
        HOST = os.environ["MY_HOST"]
        DBG = os.getenv("DBG")
    """)
    assert [d.rule for d in diags] == ["env-read"] * 3


def test_env_read_in_config_module_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os
        PORT = os.environ.get("MY_PORT")
    """, fname="paddle_tpu/core/flags.py")
    assert diags == []


def test_cast_roundtrip_direct_chain_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            return g.astype(jnp.bfloat16).astype(jnp.float32)
    """)
    assert _rules(diags) == {"cast-roundtrip"}


def test_cast_roundtrip_tree_map_pair_flagged(tmp_path):
    # the FP16AllReduceOptimizer bug shape: narrow then immediately widen
    diags = _conv_diags(tmp_path, """
        import jax, jax.numpy as jnp
        _tmap = jax.tree_util.tree_map

        def update(self, grads):
            half = _tmap(lambda g: g.astype(self.dtype), grads)
            restored = _tmap(lambda h, g: h.astype(g.dtype), half, grads)
            return restored
    """)
    assert _rules(diags) == {"cast-roundtrip"}
    assert diags[0].line == 7            # flagged at the widening


def test_cast_roundtrip_plain_var_pair_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            h = g.astype(jnp.bfloat16)
            r = h.astype(jnp.float32)
            return r
    """)
    assert _rules(diags) == {"cast-roundtrip"}


def test_cast_roundtrip_intervening_collective_ok(tmp_path):
    # a collective (or any op) between narrow and widen is the REAL
    # wire pattern — must not flag
    diags = _conv_diags(tmp_path, """
        import jax, jax.numpy as jnp
        from jax import lax
        _tmap = jax.tree_util.tree_map

        def update(grads, axes):
            half = _tmap(lambda g: g.astype(jnp.bfloat16), grads)
            reduced = _tmap(lambda h: lax.psum(h, axes), half)
            restored = _tmap(lambda h, g: h.astype(g.dtype), reduced, grads)
            return restored

        def plain(g):
            h = g.astype(jnp.bfloat16)
            s = lax.psum(h, "dp")
            return s.astype(jnp.float32)
    """)
    assert "cast-roundtrip" not in _rules(diags)


def test_cast_roundtrip_single_cast_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            return g.astype(jnp.float32)
    """)
    assert diags == []


def test_sleep_no_backoff_constant_retry_flagged(tmp_path):
    # the thundering-herd shape: fixed interval between retry attempts
    diags = _conv_diags(tmp_path, """
        import time

        def connect(dial):
            while True:
                try:
                    return dial()
                except OSError:
                    time.sleep(0.2)
    """)
    assert _rules(diags) == {"sleep-no-backoff"}


def test_sleep_no_backoff_from_import_alias_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from time import sleep as snooze

        def connect(dial):
            for attempt in range(5):
                try:
                    return dial()
                except OSError:
                    snooze(1)
    """)
    assert _rules(diags) == {"sleep-no-backoff"}


def test_sleep_exponential_backoff_ok(tmp_path):
    # the sanctioned ps/rpc.py pattern: duration grows per attempt
    diags = _conv_diags(tmp_path, """
        import time

        def connect(dial):
            backoff = 0.1
            for attempt in range(5):
                try:
                    return dial()
                except OSError:
                    time.sleep(backoff * (2 ** attempt))
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_polling_loop_without_except_ok(tmp_path):
    # a plain poll loop retries nothing — constant interval is fine
    diags = _conv_diags(tmp_path, """
        import time

        def wait_for(cond):
            while not cond():
                time.sleep(0.01)
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_exiting_handler_ok(tmp_path):
    # the except handler LEAVES the loop (return) — that is an exit
    # path, not a retry; the idle sleep next to it must not flag
    diags = _conv_diags(tmp_path, """
        import time

        def pump(step):
            while True:
                try:
                    step()
                except RuntimeError:
                    return
                time.sleep(0.002)
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_nested_polling_loop_inside_retry_ok(tmp_path):
    # innermost-loop scoping: the constant-sleep POLL loop nested in a
    # retrying outer loop is not itself a retry loop
    diags = _conv_diags(tmp_path, """
        import time

        def run(step, ready, backoff=0.1):
            for attempt in range(3):
                try:
                    while not ready():
                        time.sleep(0.01)
                    return step()
                except OSError:
                    time.sleep(backoff * (2 ** attempt))
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_no_backoff_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import time

        def connect(dial):
            while True:
                try:
                    return dial()
                except OSError:
                    time.sleep(10)  # graftlint: ignore[sleep-no-backoff] — single cooldown
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_cast_roundtrip_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            h = g.astype(jnp.bfloat16)
            r = h.astype(jnp.float32)  # graftlint: ignore[cast-roundtrip] — precision sim
            return r
    """)
    assert "cast-roundtrip" not in _rules(diags)


def test_atomic_publish_unfsynced_replace_flagged(tmp_path):
    # the torn-checkpoint shape: write + rename-publish, no fsync
    diags = _conv_diags(tmp_path, """
        import json
        import os

        def publish(payload, tmp, final):
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, final)
    """)
    assert _rules(diags) == {"atomic-publish"}


def test_atomic_publish_rename_from_import_alias_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from os import rename as mv

        def publish(tmp, final):
            open(tmp, "w").write("x")
            mv(tmp, final)
    """)
    assert _rules(diags) == {"atomic-publish"}


def test_atomic_publish_fsynced_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os

        def publish(tmp, final):
            with open(tmp, "w") as f:
                f.write("x")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
            fd = os.open(os.path.dirname(final), os.O_RDONLY)
            os.fsync(fd)
            os.close(fd)
    """)
    assert "atomic-publish" not in _rules(diags)


def test_atomic_publish_fsync_helper_counts_as_evidence(tmp_path):
    # the io/fs.py helpers carry fsync in their name — calling them is
    # the sanctioned pattern, not a violation
    diags = _conv_diags(tmp_path, """
        import os

        from paddle_tpu.io.fs import fsync_tree

        def publish(tmp, final):
            fsync_tree(tmp)
            os.replace(tmp, final)
    """)
    assert "atomic-publish" not in _rules(diags)


def test_atomic_publish_module_scope_and_ignore(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os

        os.replace("a.tmp", "a")
    """)
    assert _rules(diags) == {"atomic-publish"}
    # module-scope evidence must itself be module-scope: an fsync
    # buried in a (never-called) function body is not evidence for an
    # import-time publish
    diags = _conv_diags(tmp_path, """
        import os

        def helper(p):
            os.fsync(p)

        os.replace("a.tmp", "a")
    """)
    assert _rules(diags) == {"atomic-publish"}
    diags = _conv_diags(tmp_path, """
        import os

        def swap_scratch(a, b):
            os.replace(a, b)  # graftlint: ignore[atomic-publish] — tmp scratch, not a durable publish
    """)
    assert "atomic-publish" not in _rules(diags)


def test_unbounded_queue_flagged_in_threaded_module(tmp_path):
    diags = _conv_diags(tmp_path, """
        import queue
        import threading

        class Pump:
            def __init__(self):
                self.q = queue.Queue()
    """)
    assert _rules(diags) == {"unbounded-queue"}
    # deque without maxlen in a threaded module fires too (the PR 5
    # retrofit class), including the from-import alias form
    diags = _conv_diags(tmp_path, """
        import threading
        from collections import deque as dq

        history = dq()
    """)
    assert _rules(diags) == {"unbounded-queue"}


def test_unbounded_queue_maxsize_zero_is_unbounded(tmp_path):
    # Queue(maxsize=0) means INFINITE — the bound must be real
    diags = _conv_diags(tmp_path, """
        import queue
        import threading

        q = queue.Queue(maxsize=0)
    """)
    assert _rules(diags) == {"unbounded-queue"}


def test_bounded_queue_and_deque_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import collections
        import queue
        import threading

        class Pump:
            def __init__(self, cap):
                self.q = queue.Queue(maxsize=cap)
                self.lifo = queue.LifoQueue(8)
                self.ring = collections.deque(maxlen=512)
    """)
    assert "unbounded-queue" not in _rules(diags)


def test_unbounded_queue_unthreaded_module_ok(tmp_path):
    # no threading import = no producer/consumer concurrency to outrun;
    # a plain deque window in single-threaded code is fine
    diags = _conv_diags(tmp_path, """
        from collections import deque

        def window(it, depth):
            w = deque()
            for x in it:
                w.append(x)
                if len(w) > depth:
                    yield w.popleft()
    """)
    assert "unbounded-queue" not in _rules(diags)


def test_unbounded_queue_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import queue
        import threading

        inbox = queue.Queue()  # graftlint: ignore[unbounded-queue] — credit-bounded
    """)
    assert "unbounded-queue" not in _rules(diags)


# -- allowlist + driver -----------------------------------------------------

def test_allowlist_filters_and_reports_stale(tmp_path):
    from common import Diagnostic
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "a/b.py:3:time-time  # wall timestamp\n"
        "gone.py:1:bare-except  # removed long ago\n")
    entries = load_allowlist(str(allow))
    diags = [Diagnostic("a/b.py", 3, "time-time", "m"),
             Diagnostic("a/b.py", 9, "time-time", "m")]
    new, allowed, stale = split_new_and_allowed(diags, entries)
    assert [d.line for d in new] == [9]
    assert [d.line for d in allowed] == [3]
    assert stale == ["gone.py:1:bare-except"]


def test_allowlist_requires_justification(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("a/b.py:3:time-time\n")
    with pytest.raises(ValueError, match="justification"):
        load_allowlist(str(allow))


def test_run_py_green_on_tree_and_red_on_violation(tmp_path):
    # the committed tree must gate green
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint", "run.py"),
         "--json", str(tmp_path / "s.json")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads((tmp_path / "s.json").read_text())
    assert summary["new"] == 0
    assert set(summary["per_pass"]) == {
        "tracer_safety", "hot_path", "lock_order", "conventions",
        "obs_metrics", "control_loops"}

    # an injected violation must turn the gate red with file:line:rule
    bad = tmp_path / "tree" / "paddle_tpu"
    bad.mkdir(parents=True)
    (bad / "__init__.py").write_text("")
    (bad / "hot.py").write_text(
        "import jax\n\n@jax.jit\ndef step(x):\n    return x.item()\n")
    (tmp_path / "tree" / "tools").mkdir()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint", "run.py"),
         "--root", str(tmp_path / "tree")],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "paddle_tpu/hot.py:5: [host-sync-item]" in out.stdout


# -- metric-in-hot-path (obs_metrics pass) ----------------------------------

import obs_metrics  # noqa: E402


def _obs_diags(tmp_path, source):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return obs_metrics.run(str(tmp_path))


def test_metric_creation_in_loop_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def setup(tables):
            for t in tables:
                h = registry.counter("fam", table=t)
                h.inc()
    """)
    assert _rules(diags) == {"metric-in-hot-path"}
    assert diags[0].line == 6


def test_metric_increment_in_loop_not_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        H = registry.counter("fam", table="0")

        def run(items):
            for it in items:
                H.inc()
    """)
    assert diags == []


def test_metric_creation_in_hot_path_callee_flagged(tmp_path):
    # reachability: the creation hides in a helper CALLED from the root
    diags = _obs_diags(tmp_path, """
        def helper(reg, x):
            c = reg.counter("fam")
            c.inc()
            return x

        # graftlint: hot-path
        def step(reg, x):
            return helper(reg, x)
    """)
    assert _rules(diags) == {"metric-in-hot-path"}
    assert diags[0].line == 3


def test_metric_creation_constructor_scope_not_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        class Tier:
            def __init__(self, reg):
                self.h = reg.counter("fam", tier="0")
                self.g = {k: reg.counter("fam", key=k)
                          for k in ("hits", "misses")}
    """)
    assert diags == []  # comprehension bulk-bind is the sanctioned idiom


def test_metric_creation_behind_cold_path_not_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        # graftlint: cold-path
        def bind(reg):
            return reg.counter("fam")

        # graftlint: hot-path
        def step(reg, x):
            return bind(reg)
    """)
    assert diags == []


def test_metric_countergroup_in_loop_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs.registry import CounterGroup

        def f(xs):
            while xs:
                g = CounterGroup("fam", ("a",))
                xs.pop()
    """)
    assert _rules(diags) == {"metric-in-hot-path"}


def test_metric_variable_family_not_flagged(tmp_path):
    # the registry's own internals forward VARIABLE family names — not
    # a creation site by this rule's (syntactic) definition
    diags = _obs_diags(tmp_path, """
        def forward(reg, name):
            for _ in range(2):
                reg.counter(name)
    """)
    assert diags == []


def test_metric_ignore_comment_suppresses(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def setup(tables):
            for t in tables:
                registry.counter("fam", table=t)  # graftlint: ignore[metric-in-hot-path]
    """)
    assert diags == []


def test_metric_nested_def_in_loop_not_flagged(tmp_path):
    # a def inside a loop does not EXECUTE its body per iteration
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def setup(tables):
            out = []
            for t in tables:
                def bind(t=t):
                    return registry.counter("fam", table=t)
                out.append(bind)
            return out
    """)
    assert diags == []


# -- anonymous-thread (ISSUE 10 satellite) ----------------------------------

def test_anonymous_thread_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import threading

        def start(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
    """)
    assert _rules(diags) == {"anonymous-thread"}


def test_anonymous_thread_from_import_and_alias_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from threading import Thread as T

        def start(fn):
            T(target=fn).start()
    """)
    assert _rules(diags) == {"anonymous-thread"}
    diags = _conv_diags(tmp_path, """
        import threading as th

        def start(fn):
            th.Thread(target=fn).start()
    """)
    assert _rules(diags) == {"anonymous-thread"}


def test_named_thread_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import threading

        def start(fn, shard):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"ps-repl:{shard}")
            t.start()
    """)
    assert "anonymous-thread" not in _rules(diags)


def test_non_thread_call_named_thread_elsewhere_ok(tmp_path):
    # only the threading module's Thread counts — an unrelated Thread
    # symbol (a local class, another library) is not this rule's business
    diags = _conv_diags(tmp_path, """
        class Thread:
            def __init__(self, target=None):
                self.target = target

        def start(fn):
            Thread(target=fn)
    """)
    assert "anonymous-thread" not in _rules(diags)


def test_anonymous_thread_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import threading

        def start(fn):
            threading.Thread(target=fn).start()  # graftlint: ignore[anonymous-thread]
    """)
    assert "anonymous-thread" not in _rules(diags)


def test_anonymous_thread_checked_in_tools_scope(tmp_path):
    # tools/ demo drivers run threads that land in the same merged
    # traces — the rule applies there too (unlike most conventions)
    (tmp_path / "paddle_tpu").mkdir(exist_ok=True)
    (tmp_path / "paddle_tpu" / "__init__.py").write_text("")
    (tmp_path / "tools").mkdir(exist_ok=True)
    (tmp_path / "tools" / "demo.py").write_text(textwrap.dedent("""
        import threading

        t = threading.Thread(target=print)
    """))
    diags = conventions.run(str(tmp_path))
    assert ("tools/demo.py", "anonymous-thread") in {
        (d.path, d.rule) for d in diags}


# ---------------------------------------------------------------------------
# pass 6: control-loop timing injectability (uninjectable-clock)
# ---------------------------------------------------------------------------

import control_loops  # noqa: E402


def _loop_diags(tmp_path, source, fname="paddle_tpu/mod.py"):
    p = tmp_path / fname
    p.parent.mkdir(parents=True, exist_ok=True)
    init = tmp_path / "paddle_tpu" / "__init__.py"
    if not init.exists():
        init.write_text("")
    p.write_text(textwrap.dedent(source))
    return control_loops.run(str(tmp_path))


_LOOP_BODY = """
    import threading
    import time

    class Poller:
        def __init__(self{extra}):
            self._stop = threading.Event()
            self._t = threading.Thread(target=self._loop, daemon=True,
                                       name="poller")

        def _loop(self):
            while not self._stop.is_set():
                time.sleep(0.1)
"""


def test_uninjectable_clock_flagged(tmp_path):
    diags = _loop_diags(tmp_path, _LOOP_BODY.format(extra=""))
    assert _rules(diags) == {"uninjectable-clock"}


def test_uninjectable_clock_cadence_param_passes(tmp_path):
    diags = _loop_diags(tmp_path,
                        _LOOP_BODY.format(extra=", poll_s=0.1"))
    assert not diags


def test_uninjectable_clock_clock_param_passes(tmp_path):
    diags = _loop_diags(tmp_path,
                        _LOOP_BODY.format(extra=", clock=time.monotonic"))
    assert not diags


def test_uninjectable_clock_event_wait_deadline_flagged(tmp_path):
    # <event>.wait(x) IS the loop cadence; a bare .wait() is a signal
    diags = _loop_diags(tmp_path, """
        import threading

        class Waiter:
            def __init__(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="w")

            def _loop(self):
                while not self._stop.wait(0.5):
                    pass
    """)
    assert _rules(diags) == {"uninjectable-clock"}


def test_uninjectable_clock_bare_wait_passes(tmp_path):
    diags = _loop_diags(tmp_path, """
        import threading

        class Waiter:
            def __init__(self):
                self._go = threading.Event()
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="w")

            def _loop(self):
                while True:
                    self._go.wait()
    """)
    assert not diags


def test_uninjectable_clock_helper_one_level_flagged(tmp_path):
    # the _loop delegates its waiting to a self._helper(): still a
    # control loop — the one-level closure catches it
    diags = _loop_diags(tmp_path, """
        import threading
        import time

        class Delegating:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="d")

            def _tick(self):
                time.sleep(0.01)

            def _loop(self):
                while True:
                    self._tick()
    """)
    assert _rules(diags) == {"uninjectable-clock"}


def test_uninjectable_clock_no_thread_passes(tmp_path):
    # sleeping WITHOUT running a thread control loop is not this rule's
    # business (sleep-no-backoff covers retry loops)
    diags = _loop_diags(tmp_path, """
        import time

        class Plain:
            def wait_a_bit(self):
                time.sleep(0.1)
    """)
    assert not diags


def test_uninjectable_clock_ignore_comment(tmp_path):
    diags = _loop_diags(tmp_path, """
        import threading
        import time

        class Poller:  # graftlint: ignore[uninjectable-clock]
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="p")

            def _loop(self):
                time.sleep(0.1)
    """)
    assert not diags


def test_uninjectable_clock_reshard_and_autoscale_ship_clean():
    # the satellite contract: the new control-plane classes themselves
    # pass the rule they motivated
    import os as _os
    from common import REPO_ROOT
    for mod in ("paddle_tpu/ps/reshard.py", "paddle_tpu/ps/autoscale.py"):
        diags = control_loops.check_file(
            _os.path.join(REPO_ROOT, mod), REPO_ROOT)
        assert not diags, diags
