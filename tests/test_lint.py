"""graftlint self-tests: every rule in both directions (fires on the
violation fixture, stays quiet on the clean one), allowlist filtering,
and the run.py gate on the real tree."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools", "lint"))

import conventions  # noqa: E402
import lock_order  # noqa: E402
import tracer_safety  # noqa: E402
from common import load_allowlist, split_new_and_allowed  # noqa: E402


def _tracer_diags(tmp_path, source):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return tracer_safety.run(str(tmp_path))


def _rules(diags):
    return {d.rule for d in diags}


# -- tracer-safety ----------------------------------------------------------

def test_host_sync_in_jit_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return x.item()
    """)
    assert _rules(diags) == {"host-sync-item"}
    assert diags[0].path == "paddle_tpu/mod.py"
    assert diags[0].line == 6


def test_host_sync_outside_jit_not_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        def host_helper(x):
            return x.item()
    """)
    assert diags == []


def test_numpy_call_in_traced_callee_flagged(tmp_path):
    # reachability: the violation is in a helper CALLED from jitted code
    diags = _tracer_diags(tmp_path, """
        import jax
        import numpy as np

        def helper(x):
            return np.asarray(x)

        @jax.jit
        def step(x):
            return helper(x)
    """)
    assert _rules(diags) == {"host-sync-np"}


def test_shard_map_callsite_wrap_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax
        from jax import shard_map

        def make(mesh):
            def inner(x):
                jax.device_get(x)
                return x
            return jax.jit(shard_map(inner, mesh=mesh))
    """)
    assert _rules(diags) == {"host-sync-device-get"}


def test_tracer_branch_and_block_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            if jnp.any(x > 0):
                x = x + 1
            x.block_until_ready()
            return x
    """)
    assert _rules(diags) == {"tracer-branch", "host-sync-block"}


def test_float_cast_on_param_flagged_shape_exempt(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            n = int(x.shape[0])   # static: fine
            return x * float(x)   # concretizes: flagged
    """)
    assert _rules(diags) == {"host-float-cast"}
    assert all(d.line == 7 for d in diags)


def test_float_cast_on_derived_value_flagged(tmp_path):
    # taint flows through local assignments, not just direct params
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            y = x * 2
            return float(y)
    """)
    assert _rules(diags) == {"host-float-cast"}
    assert [d.line for d in diags] == [7]


def test_branch_on_param_compare_flagged_config_exempt(tmp_path):
    # `if x > 0` is the canonical TracerBoolConversionError; string
    # equality / is-tests / bare truthiness are static config dispatch
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x, mode="sum", flag=True, opt=None):
            if mode == "sum":      # static config: fine
                x = x + 1
            if opt is None:        # static config: fine
                x = x + 2
            if flag:               # bare truthiness: fine
                x = x + 3
            y = x - 1
            if y > 0:              # tracer compare: flagged
                x = x + 4
            return x
    """)
    assert _rules(diags) == {"tracer-branch"}
    assert [d.line for d in diags] == [13]


def test_host_print_flagged_only_inside_trace(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            print("debug", x)
            return x

        def host_log(x):
            print("fine here", x)
    """)
    assert _rules(diags) == {"host-print"}
    assert [d.line for d in diags] == [6]


def test_global_mutation_flagged(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax
        _CALLS = 0

        @jax.jit
        def step(x):
            global _CALLS
            _CALLS += 1
            return x
    """)
    assert _rules(diags) == {"global-mutation"}


def test_ignore_comment_suppresses(tmp_path):
    diags = _tracer_diags(tmp_path, """
        import jax

        @jax.jit
        def step(x):
            return x.item()  # graftlint: ignore[host-sync-item]
    """)
    assert diags == []


def test_traced_comment_marks_root(tmp_path):
    diags = _tracer_diags(tmp_path, """
        # graftlint: traced
        def bench_hot_path(x):
            return x.item()
    """)
    assert _rules(diags) == {"host-sync-item"}


# -- hot-path host transfers (pass 1b) --------------------------------------

def _hot_diags(tmp_path, source):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return tracer_safety.run_hot_path(str(tmp_path))


def test_hot_path_np_asarray_in_root_flagged(tmp_path):
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: hot-path
        def warm_step(state):
            return np.asarray(state["rows"])
    """)
    assert _rules(diags) == {"hot-host-transfer"}
    assert diags[0].line == 6


def test_hot_path_device_get_in_callee_flagged(tmp_path):
    # reachability: the transfer hides in a helper CALLED from the root
    diags = _hot_diags(tmp_path, """
        import jax
        import numpy as np

        def helper(x):
            return jax.device_get(x)

        # graftlint: hot-path
        def warm_step(state):
            return helper(state)
    """)
    assert _rules(diags) == {"hot-host-transfer"}
    assert diags[0].line == 6


def test_hot_path_cold_marked_callee_not_flagged(tmp_path):
    # a cold-path boundary stops traversal: the writeback/miss handlers
    # own their transfers by design
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: cold-path
        def writeback(state):
            return np.asarray(state["rows"])

        # graftlint: hot-path
        def warm_step(state):
            return writeback(state)
    """)
    assert diags == []


def test_hot_path_unmarked_function_not_flagged(tmp_path):
    # no hot-path roots → host numpy anywhere is fine
    diags = _hot_diags(tmp_path, """
        import numpy as np

        def host_helper(x):
            return np.asarray(x)
    """)
    assert diags == []


def test_hot_path_plain_np_math_not_flagged(tmp_path):
    # only ndarray-MATERIALIZING conversions flag; host math on the
    # control-plane mirror (zeros/where/lexsort...) is the design
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: hot-path
        def warm_step(keys):
            mask = np.zeros(4, bool)
            return np.where(mask, keys, 0)
    """)
    assert diags == []


def test_hot_path_ignore_comment(tmp_path):
    diags = _hot_diags(tmp_path, """
        import numpy as np

        # graftlint: hot-path
        def warm_step(patches):
            return np.asarray(patches)  # graftlint: ignore[hot-host-transfer]
    """)
    assert diags == []


# -- lock-order -------------------------------------------------------------

def _lock_diags(tmp_path, source, name="fixture.cc"):
    d = tmp_path / "paddle_tpu" / "csrc"
    d.mkdir(parents=True)
    (d / name).write_text(textwrap.dedent(source))
    return lock_order.run(str(tmp_path))


GOOD_CC = """
    // LOCK ORDER: outer_mu < inner_mu
    void f(T* t) {
      std::lock_guard<std::mutex> a(t->mu);  // LOCK: outer_mu
      std::lock_guard<std::mutex> b(t->sub->mu);  // LOCK: inner_mu
    }
"""


def test_lock_order_clean_file_passes(tmp_path):
    assert _lock_diags(tmp_path, GOOD_CC) == []


def test_lock_order_inversion_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        // LOCK ORDER: outer_mu < inner_mu
        void f(T* t) {
          std::lock_guard<std::mutex> b(t->sub->mu);  // LOCK: inner_mu
          std::lock_guard<std::mutex> a(t->mu);  // LOCK: outer_mu
        }
    """)
    assert _rules(diags) == {"lock-order"}


def test_lock_order_cycle_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        // LOCK ORDER: a_mu < b_mu
        // LOCK ORDER: b_mu < a_mu
        void f() {}
    """)
    assert _rules(diags) == {"lock-order-cycle"}


def test_unannotated_nesting_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        void f(T* t) {
          std::lock_guard<std::mutex> a(t->mu);
          std::lock_guard<std::mutex> b(t->other_mu);
        }
    """)
    assert _rules(diags) == {"lock-unannotated"}


def test_scoped_guard_released_before_second_lock(tmp_path):
    # the ps_service.cc kSaveAll pattern: registry lock scoped out
    # before the per-table lock — NOT nested
    diags = _lock_diags(tmp_path, """
        void f(T* t) {
          std::mutex* save_mu;
          {
            std::lock_guard<std::mutex> g(t->tables_mu);
            save_mu = t->lookup();
          }
          std::lock_guard<std::mutex> sg(*save_mu);
        }
    """)
    assert diags == []


def test_lock_leaf_violation_flagged(tmp_path):
    # a LEAF lock must be innermost: acquiring anything while it is
    # held fires, even if an ORDER decl would have allowed the nesting
    diags = _lock_diags(tmp_path, """
        // LOCK LEAF: conn_mu
        // LOCK ORDER: conn_mu < tables_mu
        void f(T* t) {
          std::lock_guard<std::mutex> g(t->conn_mu);
          std::lock_guard<std::mutex> h(t->tables_mu);
        }
    """)
    assert "lock-leaf" in _rules(diags)
    # declaring successors for a leaf is itself a decl error
    assert "lock-order-syntax" in _rules(diags)


def test_lock_leaf_nests_under_ordered_locks(tmp_path):
    # the other direction is the contract: a leaf may be taken while
    # any outer lock is held, with NO ORDER decl needed for it
    diags = _lock_diags(tmp_path, """
        // LOCK ORDER: tables_mu < save_mu
        // LOCK LEAF: bar_mu
        void f(T* t) {
          std::lock_guard<std::mutex> g(t->tables_mu);
          std::lock_guard<std::mutex> h(t->bar_mu);
        }
    """)
    assert diags == []


def test_lock_leaf_malformed_decl_flagged(tmp_path):
    diags = _lock_diags(tmp_path, """
        // LOCK LEAF: conn-mu!
        void f() {}
    """)
    assert _rules(diags) == {"lock-order-syntax"}


def test_real_csrc_tree_is_clean():
    assert lock_order.run(REPO) == []


# -- conventions ------------------------------------------------------------

def _conv_diags(tmp_path, source, fname="paddle_tpu/mod.py"):
    p = tmp_path / fname
    p.parent.mkdir(parents=True, exist_ok=True)
    if fname.startswith("paddle_tpu"):
        init = tmp_path / "paddle_tpu" / "__init__.py"
        if not init.exists():
            init.write_text("")
    (tmp_path / "tools").mkdir(exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return conventions.run(str(tmp_path))


def test_time_time_flagged_perf_counter_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import time

        def measure(fn):
            t0 = time.perf_counter()   # fine
            fn()
            wall = time.time()         # flagged
            return time.perf_counter() - t0, wall
    """)
    assert [d.rule for d in diags] == ["time-time"]
    assert diags[0].line == 7


def test_from_time_import_time_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from time import time as now

        def deadline():
            return now() + 60
    """)
    assert [d.rule for d in diags] == ["time-time"]
    assert diags[0].line == 5


def test_conventions_tolerates_missing_tools_dir(tmp_path):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    assert conventions.run(str(tmp_path)) == []


def test_bare_except_and_mutable_default_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        def f(xs=[], opts=None):
            try:
                return xs
            except:
                return None
    """)
    assert _rules(diags) == {"bare-except", "mutable-default"}


def test_env_read_outside_config_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os
        PORT = os.environ.get("MY_PORT")
        HOST = os.environ["MY_HOST"]
        DBG = os.getenv("DBG")
    """)
    assert [d.rule for d in diags] == ["env-read"] * 3


def test_env_read_in_config_module_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os
        PORT = os.environ.get("MY_PORT")
    """, fname="paddle_tpu/core/flags.py")
    assert diags == []


def test_cast_roundtrip_direct_chain_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            return g.astype(jnp.bfloat16).astype(jnp.float32)
    """)
    assert _rules(diags) == {"cast-roundtrip"}


def test_cast_roundtrip_tree_map_pair_flagged(tmp_path):
    # the FP16AllReduceOptimizer bug shape: narrow then immediately widen
    diags = _conv_diags(tmp_path, """
        import jax, jax.numpy as jnp
        _tmap = jax.tree_util.tree_map

        def update(self, grads):
            half = _tmap(lambda g: g.astype(self.dtype), grads)
            restored = _tmap(lambda h, g: h.astype(g.dtype), half, grads)
            return restored
    """)
    assert _rules(diags) == {"cast-roundtrip"}
    assert diags[0].line == 7            # flagged at the widening


def test_cast_roundtrip_plain_var_pair_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            h = g.astype(jnp.bfloat16)
            r = h.astype(jnp.float32)
            return r
    """)
    assert _rules(diags) == {"cast-roundtrip"}


def test_cast_roundtrip_intervening_collective_ok(tmp_path):
    # a collective (or any op) between narrow and widen is the REAL
    # wire pattern — must not flag
    diags = _conv_diags(tmp_path, """
        import jax, jax.numpy as jnp
        from jax import lax
        _tmap = jax.tree_util.tree_map

        def update(grads, axes):
            half = _tmap(lambda g: g.astype(jnp.bfloat16), grads)
            reduced = _tmap(lambda h: lax.psum(h, axes), half)
            restored = _tmap(lambda h, g: h.astype(g.dtype), reduced, grads)
            return restored

        def plain(g):
            h = g.astype(jnp.bfloat16)
            s = lax.psum(h, "dp")
            return s.astype(jnp.float32)
    """)
    assert "cast-roundtrip" not in _rules(diags)


def test_cast_roundtrip_single_cast_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            return g.astype(jnp.float32)
    """)
    assert diags == []


def test_sleep_no_backoff_constant_retry_flagged(tmp_path):
    # the thundering-herd shape: fixed interval between retry attempts
    diags = _conv_diags(tmp_path, """
        import time

        def connect(dial):
            while True:
                try:
                    return dial()
                except OSError:
                    time.sleep(0.2)
    """)
    assert _rules(diags) == {"sleep-no-backoff"}


def test_sleep_no_backoff_from_import_alias_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from time import sleep as snooze

        def connect(dial):
            for attempt in range(5):
                try:
                    return dial()
                except OSError:
                    snooze(1)
    """)
    assert _rules(diags) == {"sleep-no-backoff"}


def test_sleep_exponential_backoff_ok(tmp_path):
    # the sanctioned ps/rpc.py pattern: duration grows per attempt
    diags = _conv_diags(tmp_path, """
        import time

        def connect(dial):
            backoff = 0.1
            for attempt in range(5):
                try:
                    return dial()
                except OSError:
                    time.sleep(backoff * (2 ** attempt))
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_polling_loop_without_except_ok(tmp_path):
    # a plain poll loop retries nothing — constant interval is fine
    diags = _conv_diags(tmp_path, """
        import time

        def wait_for(cond):
            while not cond():
                time.sleep(0.01)
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_exiting_handler_ok(tmp_path):
    # the except handler LEAVES the loop (return) — that is an exit
    # path, not a retry; the idle sleep next to it must not flag
    diags = _conv_diags(tmp_path, """
        import time

        def pump(step):
            while True:
                try:
                    step()
                except RuntimeError:
                    return
                time.sleep(0.002)
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_nested_polling_loop_inside_retry_ok(tmp_path):
    # innermost-loop scoping: the constant-sleep POLL loop nested in a
    # retrying outer loop is not itself a retry loop
    diags = _conv_diags(tmp_path, """
        import time

        def run(step, ready, backoff=0.1):
            for attempt in range(3):
                try:
                    while not ready():
                        time.sleep(0.01)
                    return step()
                except OSError:
                    time.sleep(backoff * (2 ** attempt))
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_sleep_no_backoff_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import time

        def connect(dial):
            while True:
                try:
                    return dial()
                except OSError:
                    time.sleep(10)  # graftlint: ignore[sleep-no-backoff] — single cooldown
    """)
    assert "sleep-no-backoff" not in _rules(diags)


def test_cast_roundtrip_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import jax.numpy as jnp

        def f(g):
            h = g.astype(jnp.bfloat16)
            r = h.astype(jnp.float32)  # graftlint: ignore[cast-roundtrip] — precision sim
            return r
    """)
    assert "cast-roundtrip" not in _rules(diags)


def test_atomic_publish_unfsynced_replace_flagged(tmp_path):
    # the torn-checkpoint shape: write + rename-publish, no fsync
    diags = _conv_diags(tmp_path, """
        import json
        import os

        def publish(payload, tmp, final):
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, final)
    """)
    assert _rules(diags) == {"atomic-publish"}


def test_atomic_publish_rename_from_import_alias_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from os import rename as mv

        def publish(tmp, final):
            open(tmp, "w").write("x")
            mv(tmp, final)
    """)
    assert _rules(diags) == {"atomic-publish"}


def test_atomic_publish_fsynced_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os

        def publish(tmp, final):
            with open(tmp, "w") as f:
                f.write("x")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
            fd = os.open(os.path.dirname(final), os.O_RDONLY)
            os.fsync(fd)
            os.close(fd)
    """)
    assert "atomic-publish" not in _rules(diags)


def test_atomic_publish_fsync_helper_counts_as_evidence(tmp_path):
    # the io/fs.py helpers carry fsync in their name — calling them is
    # the sanctioned pattern, not a violation
    diags = _conv_diags(tmp_path, """
        import os

        from paddle_tpu.io.fs import fsync_tree

        def publish(tmp, final):
            fsync_tree(tmp)
            os.replace(tmp, final)
    """)
    assert "atomic-publish" not in _rules(diags)


def test_atomic_publish_module_scope_and_ignore(tmp_path):
    diags = _conv_diags(tmp_path, """
        import os

        os.replace("a.tmp", "a")
    """)
    assert _rules(diags) == {"atomic-publish"}
    # module-scope evidence must itself be module-scope: an fsync
    # buried in a (never-called) function body is not evidence for an
    # import-time publish
    diags = _conv_diags(tmp_path, """
        import os

        def helper(p):
            os.fsync(p)

        os.replace("a.tmp", "a")
    """)
    assert _rules(diags) == {"atomic-publish"}
    diags = _conv_diags(tmp_path, """
        import os

        def swap_scratch(a, b):
            os.replace(a, b)  # graftlint: ignore[atomic-publish] — tmp scratch, not a durable publish
    """)
    assert "atomic-publish" not in _rules(diags)


def test_unbounded_queue_flagged_in_threaded_module(tmp_path):
    diags = _conv_diags(tmp_path, """
        import queue
        import threading

        class Pump:
            def __init__(self):
                self.q = queue.Queue()
    """)
    assert _rules(diags) == {"unbounded-queue"}
    # deque without maxlen in a threaded module fires too (the PR 5
    # retrofit class), including the from-import alias form
    diags = _conv_diags(tmp_path, """
        import threading
        from collections import deque as dq

        history = dq()
    """)
    assert _rules(diags) == {"unbounded-queue"}


def test_unbounded_queue_maxsize_zero_is_unbounded(tmp_path):
    # Queue(maxsize=0) means INFINITE — the bound must be real
    diags = _conv_diags(tmp_path, """
        import queue
        import threading

        q = queue.Queue(maxsize=0)
    """)
    assert _rules(diags) == {"unbounded-queue"}


def test_bounded_queue_and_deque_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import collections
        import queue
        import threading

        class Pump:
            def __init__(self, cap):
                self.q = queue.Queue(maxsize=cap)
                self.lifo = queue.LifoQueue(8)
                self.ring = collections.deque(maxlen=512)
    """)
    assert "unbounded-queue" not in _rules(diags)


def test_unbounded_queue_unthreaded_module_ok(tmp_path):
    # no threading import = no producer/consumer concurrency to outrun;
    # a plain deque window in single-threaded code is fine
    diags = _conv_diags(tmp_path, """
        from collections import deque

        def window(it, depth):
            w = deque()
            for x in it:
                w.append(x)
                if len(w) > depth:
                    yield w.popleft()
    """)
    assert "unbounded-queue" not in _rules(diags)


def test_unbounded_queue_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import queue
        import threading

        inbox = queue.Queue()  # graftlint: ignore[unbounded-queue] — credit-bounded
    """)
    assert "unbounded-queue" not in _rules(diags)


# -- allowlist + driver -----------------------------------------------------

def test_allowlist_filters_and_reports_stale(tmp_path):
    from common import Diagnostic
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "a/b.py:3:time-time  # wall timestamp\n"
        "gone.py:1:bare-except  # removed long ago\n")
    entries = load_allowlist(str(allow))
    diags = [Diagnostic("a/b.py", 3, "time-time", "m"),
             Diagnostic("a/b.py", 9, "time-time", "m")]
    new, allowed, stale = split_new_and_allowed(diags, entries)
    assert [d.line for d in new] == [9]
    assert [d.line for d in allowed] == [3]
    assert stale == ["gone.py:1:bare-except"]


def test_allowlist_requires_justification(tmp_path):
    allow = tmp_path / "allow.txt"
    allow.write_text("a/b.py:3:time-time\n")
    with pytest.raises(ValueError, match="justification"):
        load_allowlist(str(allow))


def test_run_py_green_on_tree_and_red_on_violation(tmp_path):
    # the committed tree must gate green
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint", "run.py"),
         "--json", str(tmp_path / "s.json")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    summary = json.loads((tmp_path / "s.json").read_text())
    assert summary["new"] == 0
    assert set(summary["per_pass"]) == {
        "tracer_safety", "hot_path", "lock_order", "py_locks",
        "wire_contract", "conventions", "obs_metrics", "control_loops",
        "sync_shim", "actuation"}

    # an injected violation must turn the gate red with file:line:rule
    bad = tmp_path / "tree" / "paddle_tpu"
    bad.mkdir(parents=True)
    (bad / "__init__.py").write_text("")
    (bad / "hot.py").write_text(
        "import jax\n\n@jax.jit\ndef step(x):\n    return x.item()\n")
    (tmp_path / "tree" / "tools").mkdir()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint", "run.py"),
         "--root", str(tmp_path / "tree")],
        capture_output=True, text=True)
    assert out.returncode == 1
    assert "paddle_tpu/hot.py:5: [host-sync-item]" in out.stdout


# -- metric-in-hot-path (obs_metrics pass) ----------------------------------

import obs_metrics  # noqa: E402


def _obs_diags(tmp_path, source):
    pkg = tmp_path / "paddle_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text(textwrap.dedent(source))
    return obs_metrics.run(str(tmp_path))


def test_metric_creation_in_loop_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def setup(tables):
            for t in tables:
                h = registry.counter("fam", table=t)
                h.inc()
    """)
    assert _rules(diags) == {"metric-in-hot-path"}
    assert diags[0].line == 6


def test_metric_increment_in_loop_not_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        H = registry.counter("fam", table="0")

        def run(items):
            for it in items:
                H.inc()
    """)
    assert diags == []


def test_metric_creation_in_hot_path_callee_flagged(tmp_path):
    # reachability: the creation hides in a helper CALLED from the root
    diags = _obs_diags(tmp_path, """
        def helper(reg, x):
            c = reg.counter("fam")
            c.inc()
            return x

        # graftlint: hot-path
        def step(reg, x):
            return helper(reg, x)
    """)
    assert _rules(diags) == {"metric-in-hot-path"}
    assert diags[0].line == 3


def test_metric_creation_constructor_scope_not_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        class Tier:
            def __init__(self, reg):
                self.h = reg.counter("fam", tier="0")
                self.g = {k: reg.counter("fam", key=k)
                          for k in ("hits", "misses")}
    """)
    assert diags == []  # comprehension bulk-bind is the sanctioned idiom


def test_metric_creation_behind_cold_path_not_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        # graftlint: cold-path
        def bind(reg):
            return reg.counter("fam")

        # graftlint: hot-path
        def step(reg, x):
            return bind(reg)
    """)
    assert diags == []


def test_metric_countergroup_in_loop_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs.registry import CounterGroup

        def f(xs):
            while xs:
                g = CounterGroup("fam", ("a",))
                xs.pop()
    """)
    assert _rules(diags) == {"metric-in-hot-path"}


def test_metric_variable_family_not_flagged(tmp_path):
    # the registry's own internals forward VARIABLE family names — not
    # a creation site by this rule's (syntactic) definition
    diags = _obs_diags(tmp_path, """
        def forward(reg, name):
            for _ in range(2):
                reg.counter(name)
    """)
    assert diags == []


def test_metric_ignore_comment_suppresses(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def setup(tables):
            for t in tables:
                registry.counter("fam", table=t)  # graftlint: ignore[metric-in-hot-path]
    """)
    assert diags == []


def test_metric_nested_def_in_loop_not_flagged(tmp_path):
    # a def inside a loop does not EXECUTE its body per iteration
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def setup(tables):
            out = []
            for t in tables:
                def bind(t=t):
                    return registry.counter("fam", table=t)
                out.append(bind)
            return out
    """)
    assert diags == []


# -- unbounded-label (ISSUE 19 satellite; obs_metrics pass) ------------------

def test_unbounded_label_id_value_flagged(tmp_path):
    # the canonical offense: a per-request identity as a label value,
    # no explicit cardinality bound — fires at ANY scope
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def bind(reg, user_id, request_id):
            a = reg.counter("fam", user=user_id)
            b = reg.gauge("fam2", req=str(request_id))
            return a, b
    """)
    assert _rules(diags) == {"unbounded-label"}
    assert [d.line for d in diags] == [5, 6]


def test_unbounded_label_splat_flagged(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def bind(reg, labels):
            return reg.counter("fam", **labels)
    """)
    assert _rules(diags) == {"unbounded-label"}
    assert "**labels" in diags[0].message


def test_unbounded_label_max_series_not_flagged(tmp_path):
    # explicit max_series= IS the fix: the author sized the family
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def bind(reg, user_id, labels):
            a = reg.counter("fam", max_series=64, user=user_id)
            b = reg.histogram("fam2", max_series=128, **labels)
            return a, b
    """)
    assert diags == []


def test_unbounded_label_benign_names_not_flagged(tmp_path):
    # bounded-domain labels (table/tier/shard/replica) and literal
    # values don't match the unbounded-id pattern — and `table_id`-like
    # SUBSTRINGS only match on whole _-tokens (`id` does, `idx` not)
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def bind(reg, tier, shard_idx):
            a = reg.counter("fam", tier=tier, shard=str(shard_idx))
            b = reg.gauge("fam2", table="0")
            return a, b
    """)
    assert diags == []


def test_unbounded_label_ignore_comment_suppresses(tmp_path):
    diags = _obs_diags(tmp_path, """
        from paddle_tpu.obs import registry

        def bind(reg, job_id):
            return reg.counter("fam", job=job_id)  # graftlint: ignore[unbounded-label]
    """)
    assert diags == []


# -- anonymous-thread (ISSUE 10 satellite) ----------------------------------

def test_anonymous_thread_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        import threading

        def start(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
    """)
    assert _rules(diags) == {"anonymous-thread"}


def test_anonymous_thread_from_import_and_alias_flagged(tmp_path):
    diags = _conv_diags(tmp_path, """
        from threading import Thread as T

        def start(fn):
            T(target=fn).start()
    """)
    assert _rules(diags) == {"anonymous-thread"}
    diags = _conv_diags(tmp_path, """
        import threading as th

        def start(fn):
            th.Thread(target=fn).start()
    """)
    assert _rules(diags) == {"anonymous-thread"}


def test_named_thread_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        import threading

        def start(fn, shard):
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"ps-repl:{shard}")
            t.start()
    """)
    assert "anonymous-thread" not in _rules(diags)


def test_non_thread_call_named_thread_elsewhere_ok(tmp_path):
    # only the threading module's Thread counts — an unrelated Thread
    # symbol (a local class, another library) is not this rule's business
    diags = _conv_diags(tmp_path, """
        class Thread:
            def __init__(self, target=None):
                self.target = target

        def start(fn):
            Thread(target=fn)
    """)
    assert "anonymous-thread" not in _rules(diags)


def test_anonymous_thread_ignore_comment(tmp_path):
    diags = _conv_diags(tmp_path, """
        import threading

        def start(fn):
            threading.Thread(target=fn).start()  # graftlint: ignore[anonymous-thread]
    """)
    assert "anonymous-thread" not in _rules(diags)


def test_anonymous_thread_checked_in_tools_scope(tmp_path):
    # tools/ demo drivers run threads that land in the same merged
    # traces — the rule applies there too (unlike most conventions)
    (tmp_path / "paddle_tpu").mkdir(exist_ok=True)
    (tmp_path / "paddle_tpu" / "__init__.py").write_text("")
    (tmp_path / "tools").mkdir(exist_ok=True)
    (tmp_path / "tools" / "demo.py").write_text(textwrap.dedent("""
        import threading

        t = threading.Thread(target=print)
    """))
    diags = conventions.run(str(tmp_path))
    assert ("tools/demo.py", "anonymous-thread") in {
        (d.path, d.rule) for d in diags}


# ---------------------------------------------------------------------------
# pass 6: control-loop timing injectability (uninjectable-clock)
# ---------------------------------------------------------------------------

import control_loops  # noqa: E402


def _loop_diags(tmp_path, source, fname="paddle_tpu/mod.py"):
    p = tmp_path / fname
    p.parent.mkdir(parents=True, exist_ok=True)
    init = tmp_path / "paddle_tpu" / "__init__.py"
    if not init.exists():
        init.write_text("")
    p.write_text(textwrap.dedent(source))
    return control_loops.run(str(tmp_path))


_LOOP_BODY = """
    import threading
    import time

    class Poller:
        def __init__(self{extra}):
            self._stop = threading.Event()
            self._t = threading.Thread(target=self._loop, daemon=True,
                                       name="poller")

        def _loop(self):
            while not self._stop.is_set():
                time.sleep(0.1)
"""


def test_uninjectable_clock_flagged(tmp_path):
    diags = _loop_diags(tmp_path, _LOOP_BODY.format(extra=""))
    assert _rules(diags) == {"uninjectable-clock"}


def test_uninjectable_clock_cadence_param_passes(tmp_path):
    diags = _loop_diags(tmp_path,
                        _LOOP_BODY.format(extra=", poll_s=0.1"))
    assert not diags


def test_uninjectable_clock_clock_param_passes(tmp_path):
    diags = _loop_diags(tmp_path,
                        _LOOP_BODY.format(extra=", clock=time.monotonic"))
    assert not diags


def test_uninjectable_clock_event_wait_deadline_flagged(tmp_path):
    # <event>.wait(x) IS the loop cadence; a bare .wait() is a signal
    diags = _loop_diags(tmp_path, """
        import threading

        class Waiter:
            def __init__(self):
                self._stop = threading.Event()
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="w")

            def _loop(self):
                while not self._stop.wait(0.5):
                    pass
    """)
    assert _rules(diags) == {"uninjectable-clock"}


def test_uninjectable_clock_bare_wait_passes(tmp_path):
    diags = _loop_diags(tmp_path, """
        import threading

        class Waiter:
            def __init__(self):
                self._go = threading.Event()
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="w")

            def _loop(self):
                while True:
                    self._go.wait()
    """)
    assert not diags


def test_uninjectable_clock_helper_one_level_flagged(tmp_path):
    # the _loop delegates its waiting to a self._helper(): still a
    # control loop — the one-level closure catches it
    diags = _loop_diags(tmp_path, """
        import threading
        import time

        class Delegating:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="d")

            def _tick(self):
                time.sleep(0.01)

            def _loop(self):
                while True:
                    self._tick()
    """)
    assert _rules(diags) == {"uninjectable-clock"}


def test_uninjectable_clock_no_thread_passes(tmp_path):
    # sleeping WITHOUT running a thread control loop is not this rule's
    # business (sleep-no-backoff covers retry loops)
    diags = _loop_diags(tmp_path, """
        import time

        class Plain:
            def wait_a_bit(self):
                time.sleep(0.1)
    """)
    assert not diags


def test_uninjectable_clock_ignore_comment(tmp_path):
    diags = _loop_diags(tmp_path, """
        import threading
        import time

        class Poller:  # graftlint: ignore[uninjectable-clock]
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="p")

            def _loop(self):
                time.sleep(0.1)
    """)
    assert not diags


def test_uninjectable_clock_reshard_and_autoscale_ship_clean():
    # the satellite contract: the new control-plane classes themselves
    # pass the rule they motivated
    import os as _os
    from common import REPO_ROOT
    for mod in ("paddle_tpu/ps/reshard.py", "paddle_tpu/ps/autoscale.py"):
        diags = control_loops.check_file(
            _os.path.join(REPO_ROOT, mod), REPO_ROOT)
        assert not diags, diags


# -- pass 6b: control-loop rng injectability (uninjectable-rng) -------------

_RNG_LOOP_BODY = """
    import random
    import threading

    class Chooser:
        def __init__(self{extra}, poll_s=0.1):
            self._stop = threading.Event()
            self._t = threading.Thread(target=self._loop, daemon=True,
                                       name="chooser")

        def _loop(self):
            while not self._stop.is_set():
                _ = random.{draw}
"""


def test_uninjectable_rng_flagged(tmp_path):
    diags = _loop_diags(tmp_path,
                        _RNG_LOOP_BODY.format(extra="", draw="random()"))
    assert _rules(diags) == {"uninjectable-rng"}


def test_uninjectable_rng_choice_flagged(tmp_path):
    diags = _loop_diags(tmp_path, _RNG_LOOP_BODY.format(
        extra="", draw="choice([1, 2])"))
    assert _rules(diags) == {"uninjectable-rng"}


def test_uninjectable_rng_rng_param_passes(tmp_path):
    diags = _loop_diags(tmp_path,
                        _RNG_LOOP_BODY.format(extra=", rng=None",
                                              draw="random()"))
    assert not diags


def test_uninjectable_rng_seed_param_passes(tmp_path):
    diags = _loop_diags(tmp_path,
                        _RNG_LOOP_BODY.format(extra=", jitter_seed=0",
                                              draw="random()"))
    assert not diags


def test_uninjectable_rng_np_random_flagged(tmp_path):
    diags = _loop_diags(tmp_path, """
        import threading
        import numpy as np

        class NpChooser:
            def __init__(self, poll_s=0.1):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="np-chooser")

            def _loop(self):
                while True:
                    _ = np.random.randint(0, 4)
    """)
    assert _rules(diags) == {"uninjectable-rng"}


def test_uninjectable_rng_instance_rng_draw_passes(tmp_path):
    # drawing from an INJECTED generator is exactly the sanctioned
    # pattern — self._rng.choice is not a global draw
    diags = _loop_diags(tmp_path, """
        import random
        import threading

        class Seeded:
            def __init__(self, rng=None, poll_s=0.1):
                self._rng = rng or random.Random()
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="seeded")

            def _loop(self):
                while True:
                    _ = self._rng.choice([1, 2])
    """)
    assert not diags


def test_uninjectable_rng_draw_outside_loop_passes(tmp_path):
    # one-shot construction-time jitter (no thread target draws) is
    # not a control-loop decision
    diags = _loop_diags(tmp_path, """
        import random
        import threading

        class JitterAtBirth:
            def __init__(self, poll_s=0.1):
                self.offset = random.random()
                self._go = threading.Event()
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="jab")

            def _loop(self):
                while True:
                    self._go.wait()
    """)
    assert not diags


def test_uninjectable_rng_helper_one_level_flagged(tmp_path):
    diags = _loop_diags(tmp_path, """
        import random
        import threading

        class Delegating:
            def __init__(self, poll_s=0.1):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="d")

            def _pick(self):
                return random.randint(0, 3)

            def _loop(self):
                while True:
                    self._pick()
    """)
    assert _rules(diags) == {"uninjectable-rng"}


def test_uninjectable_rng_ignore_comment(tmp_path):
    diags = _loop_diags(tmp_path, """
        import random
        import threading

        class Chaos:  # graftlint: ignore[uninjectable-rng]
            def __init__(self, poll_s=0.1):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="c")

            def _loop(self):
                while True:
                    random.random()
    """)
    assert not diags


def test_uninjectable_rng_router_ships_clean():
    # the motivating classes pass the rule they motivated
    import os as _os
    from common import REPO_ROOT
    for mod in ("paddle_tpu/serving/router.py",
                "paddle_tpu/serving/fleet.py",
                "paddle_tpu/serving/rollout.py"):
        diags = control_loops.check_file(
            _os.path.join(REPO_ROOT, mod), REPO_ROOT)
        assert not diags, diags


# ---------------------------------------------------------------------------
# pass 7: Python lock discipline (py_locks)
# ---------------------------------------------------------------------------

import py_locks  # noqa: E402


def _pylock_diags(tmp_path, source, fname="paddle_tpu/mod.py"):
    p = tmp_path / fname
    p.parent.mkdir(parents=True, exist_ok=True)
    init = tmp_path / "paddle_tpu" / "__init__.py"
    if not init.exists():
        init.write_text("")
    p.write_text(textwrap.dedent(source))
    return py_locks.run(str(tmp_path))


def test_pylock_sleep_under_lock_flagged(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def f(self):
                with self._mu:
                    time.sleep(0.1)
    """)
    assert _rules(diags) == {"blocking-under-lock"}
    assert diags[0].line == 11


def test_pylock_sleep_outside_lock_ok(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def f(self):
                with self._mu:
                    x = 1
                time.sleep(0.1)
                return x
    """)
    assert diags == []


def test_pylock_bounded_queue_put_under_lock_flagged(tmp_path):
    # the JobCheckpointManager writer-path bug shape this rule was
    # built for: a backpressured put parks every thread needing _mu
    diags = _pylock_diags(tmp_path, """
        import queue
        import threading

        class W:
            def __init__(self, cap):
                self._mu = threading.Lock()
                self._wq = queue.Queue(maxsize=cap)

            def submit(self, item):
                with self._mu:
                    self._wq.put(item)
    """)
    assert _rules(diags) == {"blocking-under-lock"}


def test_pylock_put_nowait_and_unbounded_put_ok(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import queue
        import threading

        class W:
            def __init__(self, cap):
                self._mu = threading.Lock()
                self._wq = queue.Queue(maxsize=cap)
                self._log = queue.Queue()

            def submit(self, item):
                with self._mu:
                    self._wq.put_nowait(item)
                    self._log.put(item)   # unbounded: never blocks
    """)
    assert "blocking-under-lock" not in _rules(diags)


def test_pylock_queue_get_under_lock_flagged(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import queue
        import threading

        class W:
            def __init__(self):
                self._mu = threading.Lock()
                self._wq = queue.Queue()

            def pop(self):
                with self._mu:
                    return self._wq.get()
    """)
    assert _rules(diags) == {"blocking-under-lock"}


def test_pylock_rpc_call_under_lock_flagged_lock_ok_escapes(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self, conn):
                self._mu = threading.Lock()
                self.conn = conn

            def f(self):
                with self._mu:
                    return self.conn.call(3)
    """)
    assert _rules(diags) == {"blocking-under-lock"}
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self, conn):
                self._mu = threading.Lock()
                self.conn = conn

            def f(self):
                with self._mu:
                    return self.conn.call(3)  # graftlint: lock-ok wire mutex serializes exactly this
    """)
    assert diags == []


def test_pylock_lock_ok_without_reason_is_syntax_error(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def f(self):
                with self._mu:
                    time.sleep(1)  # graftlint: lock-ok
    """)
    assert _rules(diags) == {"lock-ok-syntax"}


def test_pylock_thread_join_under_lock_flagged_str_join_ok(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self, t):
                self._mu = threading.Lock()
                self._t = t

            def stop(self):
                with self._mu:
                    self._t.join()

            def render(self, parts, sep):
                with self._mu:
                    return ",".join(parts) + sep.join(parts)
    """)
    assert [d.rule for d in diags] == ["blocking-under-lock"]
    assert diags[0].line == 11


def test_pylock_event_wait_under_lock_flagged_cv_wait_ok(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)
                self._ev = threading.Event()

            def bad(self):
                with self._mu:
                    self._ev.wait(1.0)

            def good(self):
                with self._cv:
                    while not self.ready:
                        self._cv.wait()
                    self._cv.notify_all()
    """)
    assert [d.rule for d in diags] == ["blocking-under-lock"]
    assert diags[0].line == 12


def test_pylock_param_callback_under_lock_flagged(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def subscribe_and_fire(self, fn):
                with self._mu:
                    fn()
    """)
    assert _rules(diags) == {"callback-under-lock"}


def test_pylock_subscriber_loop_under_lock_flagged_snapshot_ok(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._mu = threading.Lock()
                self._on_fire = []

            def bad(self, alert):
                with self._mu:
                    for fn in self._on_fire:
                        fn(alert)

            def good(self, alert):
                with self._mu:
                    subs = list(self._on_fire)
                for fn in subs:
                    fn(alert)
    """)
    assert [d.rule for d in diags] == ["callback-under-lock"]
    assert diags[0].line == 12


def test_pylock_notify_method_under_lock_flagged(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._mu = threading.Lock()

            def transition(self, alert):
                with self._mu:
                    self.state = "open"
                    self._notify(alert)

            def _notify(self, alert):
                pass
    """)
    assert _rules(diags) == {"callback-under-lock"}


def test_pylock_order_inversion_and_unannotated(tmp_path):
    diags = _pylock_diags(tmp_path, """
        # LOCK ORDER: outer_mu < inner_mu
        import threading

        class C:
            def __init__(self):
                self.outer_mu = threading.Lock()
                self.inner_mu = threading.Lock()
                self.other_mu = threading.Lock()

            def inverted(self):
                with self.inner_mu:
                    with self.outer_mu:
                        pass

            def unannotated(self):
                with self.outer_mu:
                    with self.other_mu:
                        pass
    """)
    assert _rules(diags) == {"lock-order", "lock-unannotated"}
    diags = _pylock_diags(tmp_path, """
        # LOCK ORDER: outer_mu < inner_mu
        import threading

        class C:
            def __init__(self):
                self.outer_mu = threading.Lock()
                self.inner_mu = threading.Lock()

            def ordered(self):
                with self.outer_mu:
                    with self.inner_mu:
                        pass
    """)
    assert diags == []


def test_pylock_leaf_violation_and_leaf_nests_under_outer(tmp_path):
    diags = _pylock_diags(tmp_path, """
        # LOCK LEAF: hot_mu
        import threading

        class C:
            def __init__(self):
                self.hot_mu = threading.Lock()
                self.big_mu = threading.Lock()

            def bad(self):
                with self.hot_mu:
                    with self.big_mu:
                        pass
    """)
    assert _rules(diags) == {"lock-leaf"}
    diags = _pylock_diags(tmp_path, """
        # LOCK ORDER: big_mu < mid_mu
        # LOCK LEAF: hot_mu
        import threading

        class C:
            def __init__(self):
                self.hot_mu = threading.Lock()
                self.big_mu = threading.Lock()

            def good(self):
                with self.big_mu:
                    with self.hot_mu:
                        pass
    """)
    assert diags == []


def test_pylock_cycle_flagged(tmp_path):
    diags = _pylock_diags(tmp_path, """
        # LOCK ORDER: a_mu < b_mu
        # LOCK ORDER: b_mu < a_mu
        import threading
    """)
    assert _rules(diags) == {"lock-order-cycle"}


def test_pylock_acquire_release_region(tmp_path):
    # acquire()/release() pairs scope a region in statement order
    diags = _pylock_diags(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def bad(self):
                self._mu.acquire()
                time.sleep(0.1)
                self._mu.release()

            def good(self):
                self._mu.acquire()
                x = 1
                self._mu.release()
                time.sleep(0.1)
                return x
    """)
    assert [d.rule for d in diags] == ["blocking-under-lock"]
    assert diags[0].line == 11


def test_pylock_lock_tag_names_acquisition(tmp_path):
    # `# LOCK: name` renames an acquisition for ORDER/LEAF purposes
    diags = _pylock_diags(tmp_path, """
        # LOCK LEAF: breaker_mu
        import threading

        class Breaker:
            def __init__(self):
                self._mu = threading.Lock()
                self._aux_mu = threading.Lock()

            def bad(self):
                with self._mu:  # LOCK: breaker_mu
                    with self._aux_mu:
                        pass
    """)
    assert _rules(diags) == {"lock-leaf"}


def test_pylock_nested_def_under_lock_not_flagged(tmp_path):
    # a def inside the region does not EXECUTE under the lock
    diags = _pylock_diags(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def f(self):
                with self._mu:
                    def later():
                        time.sleep(1)
                    self.cb = later
    """)
    assert diags == []


def test_pylock_ignore_comment(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def f(self):
                with self._mu:
                    time.sleep(1)  # graftlint: ignore[blocking-under-lock]
    """)
    assert diags == []


def test_pylock_real_tree_is_clean():
    # the 12 annotated threading modules (and everything else) pass
    assert py_locks.run(REPO) == []


# ---------------------------------------------------------------------------
# pass 8: cross-language wire contract (wire_contract)
# ---------------------------------------------------------------------------

import shutil  # noqa: E402

import wire_contract  # noqa: E402


def _wire_tree(tmp_path):
    """Scratch copy of every file the pass reads."""
    for rel in wire_contract.RELEVANT_FILES:
        src = os.path.join(REPO, rel)
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        shutil.copy(src, dst)
    return str(tmp_path)


def _perturb(tmp_path, rel, old, new):
    p = tmp_path / rel
    s = p.read_text()
    assert old in s, f"fixture drift: {old!r} not in {rel}"
    p.write_text(s.replace(old, new))


def test_wire_clean_tree_passes(tmp_path):
    root = _wire_tree(tmp_path)
    assert wire_contract.run(root) == []


def test_wire_cmd_id_perturbation_fails(tmp_path):
    root = _wire_tree(tmp_path)
    _perturb(tmp_path, "paddle_tpu/csrc/ps_service.cc",
             "kObsSnap = 43", "kObsSnap = 45")
    assert "wire-cmd-drift" in _rules(wire_contract.run(root))


def test_wire_python_mirror_perturbation_fails(tmp_path):
    root = _wire_tree(tmp_path)
    _perturb(tmp_path, "paddle_tpu/ps/rpc.py", "_RETAIN = 44", "_RETAIN = 46")
    assert "wire-cmd-mirror" in _rules(wire_contract.run(root))


def test_wire_missing_mirror_fails(tmp_path):
    root = _wire_tree(tmp_path)
    _perturb(tmp_path, "paddle_tpu/ps/rpc.py", "_OBS_SNAP = 43", "")
    assert "wire-cmd-mirror" in _rules(wire_contract.run(root))


def test_wire_error_code_perturbation_fails_both_sides(tmp_path):
    root = _wire_tree(tmp_path)
    _perturb(tmp_path, "paddle_tpu/ps/ha.py",
             "_rpc_err_stale_epoch = -5", "_rpc_err_stale_epoch = -55")
    assert "wire-err-mirror" in _rules(wire_contract.run(root))
    root2 = _wire_tree(tmp_path / "b")
    _perturb(tmp_path / "b", "paddle_tpu/csrc/ps_service.cc",
             "kErrSeqGap = -6", "kErrSeqGap = -66")
    got = _rules(wire_contract.run(root2))
    assert "wire-err-drift" in got


def test_wire_header_perturbation_fails(tmp_path):
    root = _wire_tree(tmp_path)
    _perturb(tmp_path, "paddle_tpu/ps/ha.py",
             '_HDR = struct.Struct("<QIIqiQQ")',
             '_HDR = struct.Struct("<QIIqiQ")')
    assert "wire-header-drift" in _rules(wire_contract.run(root))


def test_wire_classification_perturbation_fails(tmp_path):
    # dropping a cmd from the ownership-fence scan must not pass review
    root = _wire_tree(tmp_path)
    _perturb(tmp_path, "paddle_tpu/csrc/ps_service.cc",
             "inline bool is_keyed_data_cmd(uint32_t cmd) {\n  switch (cmd) {\n    case kPullSparse:",
             "inline bool is_keyed_data_cmd(uint32_t cmd) {\n  switch (cmd) {")
    assert "wire-class-drift" in _rules(wire_contract.run(root))


def test_wire_untapped_mutation_rule(monkeypatch):
    # a gate-checked mutation that is neither tapped nor local_only is
    # exactly the replication hole the rule exists for
    spec = wire_contract.CONTRACT["kLoadCold"]
    broken = wire_contract.CmdSpec(spec.id, spec.py, tap="no",
                                   gate=spec.gate, keyed=spec.keyed)
    monkeypatch.setitem(wire_contract.CONTRACT, "kLoadCold", broken)
    got = _rules(wire_contract.run(REPO))
    assert "wire-untapped-mutation" in got
    assert "wire-class-drift" in got   # tap mismatch vs csrc too


def test_wire_contract_real_tree_is_clean():
    assert wire_contract.run(REPO) == []


# ---------------------------------------------------------------------------
# driver satellites: stale-allowlist gate, --changed, per-pass timings
# ---------------------------------------------------------------------------

def _lint_runner():
    """Load tools/lint/run.py under a unique module name: a bare
    `import run` collides with tools/sched/run.py (test_sched.py puts
    that dir on sys.path too, and sys.modules caches whichever `run`
    wins the path race)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "paddle_lint_run", os.path.join(REPO, "tools", "lint", "run.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

def test_stale_allowlist_entry_fails_full_gate(tmp_path, monkeypatch):
    runner = _lint_runner()
    bad = tmp_path / "tree" / "paddle_tpu"
    bad.mkdir(parents=True)
    (bad / "__init__.py").write_text("")
    (bad / "hot.py").write_text(
        "import jax\n\n@jax.jit\ndef step(x):\n    return x.item()\n")
    (tmp_path / "tree" / "tools").mkdir()
    allow = tmp_path / "allow.txt"
    # entry at the WRONG line: the finding is new AND the entry is stale
    allow.write_text("paddle_tpu/hot.py:99:host-sync-item  # why: moved\n")
    monkeypatch.setattr(runner, "ALLOW_PATH", str(allow))
    assert runner.main(["--root", str(tmp_path / "tree")]) == 1
    # fixing the line makes both go away
    allow.write_text("paddle_tpu/hot.py:5:host-sync-item  # why: legit\n")
    assert runner.main(["--root", str(tmp_path / "tree")]) == 0
    # stale-only (violation gone, entry remains) still fails
    (bad / "hot.py").write_text("def step(x):\n    return x\n")
    assert runner.main(["--root", str(tmp_path / "tree")]) == 1


def test_changed_mode_filters_and_skips_staleness(tmp_path, monkeypatch):
    runner = _lint_runner()
    tree = tmp_path / "tree"
    pkg = tree / "paddle_tpu"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "hot.py").write_text(
        "import jax\n\n@jax.jit\ndef step(x):\n    return x.item()\n")
    (pkg / "other.py").write_text(
        "import jax\n\n@jax.jit\ndef leak(x):\n    return x.tolist()\n")
    (tree / "tools").mkdir()
    allow = tmp_path / "allow.txt"
    allow.write_text("gone.py:1:bare-except  # why: stale on purpose\n")
    monkeypatch.setattr(runner, "ALLOW_PATH", str(allow))
    # full run: both violations + the stale entry -> red
    assert runner.main(["--root", str(tree)]) == 1
    # changed = only other.py: hot.py's violation invisible, staleness
    # skipped; other.py's violation still gates
    monkeypatch.setattr(runner, "changed_files",
                        lambda root: {"paddle_tpu/other.py"})
    summary = tmp_path / "s.json"
    assert runner.main(["--root", str(tree), "--changed",
                        "--json", str(summary)]) == 1
    s = json.loads(summary.read_text())
    assert s["changed_mode"] and s["changed_files"] == ["paddle_tpu/other.py"]
    assert {v["rule"] for v in s["violations"]} == {"host-sync-item"}
    assert s["stale_allowlist_entries"] == []
    # empty changed set short-circuits green
    monkeypatch.setattr(runner, "changed_files", lambda root: set())
    assert runner.main(["--root", str(tree), "--changed"]) == 0


def test_json_summary_carries_timings_and_why(tmp_path, monkeypatch):
    runner = _lint_runner()
    tree = tmp_path / "tree"
    pkg = tree / "paddle_tpu"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "hot.py").write_text(
        "import jax\n\n@jax.jit\ndef step(x):\n    return x.item()\n")
    (tree / "tools").mkdir()
    allow = tmp_path / "allow.txt"
    allow.write_text(
        "paddle_tpu/hot.py:5:host-sync-item  # why: demo justification\n")
    monkeypatch.setattr(runner, "ALLOW_PATH", str(allow))
    summary = tmp_path / "s.json"
    assert runner.main(["--root", str(tree), "--json", str(summary)]) == 0
    s = json.loads(summary.read_text())
    assert set(s["per_pass"]) == {
        "tracer_safety", "hot_path", "lock_order", "py_locks",
        "wire_contract", "conventions", "obs_metrics", "control_loops",
        "sync_shim", "actuation"}
    for rec in s["per_pass"].values():
        assert rec["wall_ms"] >= 0 and rec["violations"] >= 0
    assert s["wall_s"] >= 0
    v = [x for x in s["violations"] if x["allowlisted"]]
    assert v and v[0]["why"] == "demo justification"


def test_pylock_malformed_decl_flagged(tmp_path):
    diags = _pylock_diags(tmp_path, """
        # LOCK ORDER: a_mu <
        # LOCK LEAF: bad-name!
        import threading
    """)
    assert _rules(diags) == {"lock-order-syntax"}
    diags = _pylock_diags(tmp_path, """
        # LOCK ORDER: a_mu < b_mu
        # LOCK LEAF: c_mu
        import threading
    """)
    assert diags == []


def test_time_budget_warning_is_soft(tmp_path, monkeypatch, capsys):
    runner = _lint_runner()
    tree = tmp_path / "tree"
    (tree / "paddle_tpu").mkdir(parents=True)
    (tree / "paddle_tpu" / "__init__.py").write_text("")
    (tree / "tools").mkdir()
    allow = tmp_path / "allow.txt"
    allow.write_text("")
    monkeypatch.setattr(runner, "ALLOW_PATH", str(allow))
    monkeypatch.setattr(runner, "TIME_BUDGET_S", 0.0)
    # over budget still exits 0 (soft), but names the slowest pass
    assert runner.main(["--root", str(tree)]) == 0
    err = capsys.readouterr().err
    assert "soft budget" in err and "slowest pass" in err


def test_pylock_lambda_under_lock_not_flagged(tmp_path):
    # a lambda stored under the lock runs LATER, not under it
    diags = _pylock_diags(tmp_path, """
        import threading
        import time

        class C:
            def __init__(self):
                self._mu = threading.Lock()

            def f(self):
                with self._mu:
                    self.cb = lambda: time.sleep(1)
    """)
    assert diags == []


def test_pylock_cv_wait_bound_to_other_lock_flagged(tmp_path):
    # Condition(self._other).wait() under _mu releases _other, NOT the
    # held _mu — the held lock stays parked for the whole wait
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._other = threading.Lock()
                self._cv = threading.Condition(self._other)

            def bad(self):
                with self._mu:
                    self._cv.wait()
    """)
    # the no-predicate rule (ISSUE 16) independently fires on the same
    # site: the wait is both under the wrong lock AND unlooped
    assert _rules(diags) == {"blocking-under-lock",
                             "cond-wait-no-predicate"}


def test_pylock_cv_wait_bound_to_held_lock_ok(tmp_path):
    # the JobCheckpointManager pattern: Condition(self._mu).wait()
    # under `with self._mu:` IS the cv protocol (it releases _mu)
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._quiesced = threading.Condition(self._mu)

            def good(self):
                with self._mu:
                    while self.busy:
                        self._quiesced.wait()
                    self._quiesced.notify_all()
    """)
    assert diags == []


def test_pylock_lock_ok_does_not_waive_ordering_rules(tmp_path):
    # lock-ok is scoped to callback/blocking; an ordering violation on
    # the same line still fires (only the audited allowlist may waive it)
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._other_mu = threading.Lock()

            def f(self):
                with self._mu:
                    with self._other_mu:  # graftlint: lock-ok not a waiver for ordering
                        pass
    """)
    assert _rules(diags) == {"lock-unannotated"}


def test_changed_files_handles_spaces_in_paths(tmp_path):
    import subprocess as sp

    runner = _lint_runner()
    repo = tmp_path / "r"
    repo.mkdir()

    def g(*args):
        sp.run(["git", "-C", str(repo), "-c", "user.email=t@t",
                "-c", "user.name=t", *args], check=True,
               capture_output=True)

    g("init", "-q")
    (repo / "base.py").write_text("x = 1\n")
    g("add", "-A")
    g("commit", "-qm", "base")
    (repo / "my mod.py").write_text("y = 2\n")   # untracked, space in name
    (repo / "base.py").write_text("x = 3\n")     # modified
    got = runner.changed_files(str(repo))
    assert got == {"base.py", "my mod.py"}


# ---------------------------------------------------------------------------
# pass 9: sync-shim discipline (sync_shim)
# ---------------------------------------------------------------------------

import sync_shim  # noqa: E402


def _shim_diags(tmp_path, source, fname="paddle_tpu/ps/mod.py"):
    p = tmp_path / fname
    p.parent.mkdir(parents=True, exist_ok=True)
    init = tmp_path / "paddle_tpu" / "__init__.py"
    if not init.exists():
        init.write_text("")
    p.write_text(textwrap.dedent(source))
    return sync_shim.run(str(tmp_path))


def test_raw_sync_in_migrated_module_flagged(tmp_path):
    diags = _shim_diags(tmp_path, """
        import threading
        import queue

        from ..core import sync as _sync

        class C:
            def __init__(self):
                self._mu = _sync.Lock()
                self._raw = threading.Lock()
                self._ev = threading.Event()
                self._q = queue.Queue(maxsize=4)
                self._t = threading.Thread(target=self.f, name="w")
    """)
    assert _rules(diags) == {"raw-sync"}
    assert len(diags) == 4  # Lock + Event + Queue + Thread
    assert "_sync.Lock(" in diags[0].message


def test_raw_sync_unmigrated_module_ok(tmp_path):
    # no shim import: raw construction is NOT a violation — migration
    # is deliberate, the pass is a ratchet not a mandate
    diags = _shim_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._t = threading.Thread(target=self.f, name="w")
    """)
    assert diags == []


def test_raw_sync_escape_with_reason_ok_without_reason_syntax(tmp_path):
    diags = _shim_diags(tmp_path, """
        import threading

        from ..core import sync as _sync

        class C:
            def __init__(self):
                self._mu = _sync.Lock()
                self._wd = threading.Thread(  # graftlint: raw-sync watchdog outlives the test run
                    target=self.f, name="w")
                self._bad = threading.Lock()  # graftlint: raw-sync
    """)
    assert _rules(diags) == {"raw-sync-syntax"}


def test_raw_sync_ignore_comment_and_alias_forms(tmp_path):
    # ignore[] suppresses too, and the level-0 import form + a renamed
    # alias are both recognized as migration markers
    diags = _shim_diags(tmp_path, """
        import threading

        from paddle_tpu.core import sync as S

        class C:
            def __init__(self):
                self._mu = S.Lock()
                self._raw = threading.RLock()  # graftlint: ignore[raw-sync]
                self._cv = threading.Condition()
    """)
    assert _rules(diags) == {"raw-sync"}
    assert len(diags) == 1


def test_raw_sync_shim_and_testing_modules_skipped(tmp_path):
    # the shim itself and the explorer construct raw primitives BY
    # DESIGN
    for fname in ("paddle_tpu/core/sync.py", "paddle_tpu/testing/sched.py"):
        diags = _shim_diags(tmp_path, """
            import threading

            from ..core import sync as _sync

            _mu = threading.Lock()
        """, fname=fname)
        assert diags == []


def test_real_tree_shim_migration_is_complete():
    diags = sync_shim.run(REPO)
    assert diags == [], diags


# ---------------------------------------------------------------------------
# py_locks: cond-wait-no-predicate + sync-shim recognition
# ---------------------------------------------------------------------------

def test_cond_wait_outside_while_flagged(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)

            def bad(self):
                with self._mu:
                    if not self.ready:
                        self._cv.wait()
    """)
    assert "cond-wait-no-predicate" in _rules(diags)


def test_cond_wait_in_while_ok(tmp_path):
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)

            def good(self):
                with self._mu:
                    while not self.ready:
                        self._cv.wait()
    """)
    assert diags == []


def test_cond_wait_nested_def_resets_loop_context(tmp_path):
    # a closure's body does not inherit the enclosing while
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)

            def bad(self):
                while True:
                    def inner():
                        with self._mu:
                            self._cv.wait()
                    inner()
    """)
    assert "cond-wait-no-predicate" in _rules(diags)


def test_cond_wait_event_wait_not_flagged(tmp_path):
    # Events are level-triggered: wait() needs no predicate loop (the
    # rule keys on tracked Conditions only)
    diags = _pylock_diags(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._stop = threading.Event()

            def loop(self):
                self._stop.wait(0.05)
    """)
    assert diags == []


def test_pylock_sync_shim_condition_recognized(tmp_path):
    # the shim's Condition binds to its lock exactly like threading's:
    # cv protocol under the bound lock is exempt, and the shim Queue's
    # boundedness feeds blocking-under-lock
    diags = _pylock_diags(tmp_path, """
        from ..core import sync as _sync

        class C:
            def __init__(self):
                self._mu = _sync.Lock()
                self._cv = _sync.Condition(self._mu)
                self._wq = _sync.Queue(maxsize=2)

            def good(self):
                with self._mu:
                    while self.busy:
                        self._cv.wait()
                    self._cv.notify_all()

            def bad(self):
                with self._mu:
                    self._wq.put(1)
    """)
    assert _rules(diags) == {"blocking-under-lock"}
    assert len(diags) == 1


# ---------------------------------------------------------------------------
# conventions: sync-shim recognition
# ---------------------------------------------------------------------------

def test_conventions_sync_shim_queue_and_thread(tmp_path):
    diags = _conv_diags(tmp_path, """
        from ..core import sync as _sync

        class C:
            def __init__(self):
                self._q = _sync.Queue()
                self._t = _sync.Thread(target=self.f)
    """)
    assert _rules(diags) == {"unbounded-queue", "anonymous-thread"}


def test_conventions_sync_shim_bounded_named_ok(tmp_path):
    diags = _conv_diags(tmp_path, """
        from ..core import sync as _sync

        class C:
            def __init__(self):
                self._q = _sync.Queue(maxsize=8)
                self._t = _sync.Thread(target=self.f, name="c:writer")
    """)
    assert diags == []


# ---------------------------------------------------------------------------
# --changed must re-run cross-file passes over the whole tree
# ---------------------------------------------------------------------------

def test_changed_mode_runs_cross_file_passes_fully(tmp_path, monkeypatch):
    import subprocess as sp

    runner = _lint_runner()
    repo = tmp_path / "r"
    pkg = repo / "paddle_tpu"
    pkg.mkdir(parents=True)
    (repo / "tools").mkdir()
    (pkg / "__init__.py").write_text("")
    # UNCHANGED file with a py_locks violation a partial view would miss
    (pkg / "steady.py").write_text(textwrap.dedent("""
        import time
        import threading

        _mu = threading.Lock()

        def f():
            with _mu:
                time.sleep(1.0)
    """))
    (pkg / "touched.py").write_text("x = 1\n")

    def g(*args):
        sp.run(["git", "-C", str(repo), "-c", "user.email=t@t",
                "-c", "user.name=t", *args], check=True,
               capture_output=True)

    g("init", "-q")
    g("add", "-A")
    g("commit", "-qm", "base")
    (pkg / "touched.py").write_text("x = 2\n")   # the only change

    allow = tmp_path / "allow.txt"
    allow.write_text("")
    monkeypatch.setattr(runner, "ALLOW_PATH", str(allow))
    summary = tmp_path / "s.json"
    rc = runner.main(["--root", str(repo), "--changed",
                      "--json", str(summary)])
    s = json.loads(summary.read_text())
    assert s["changed_files"] == ["paddle_tpu/touched.py"]
    # the cross-file py_locks pass saw the WHOLE tree: the violation in
    # the unchanged file is reported and the gate goes red
    assert any(v["rule"] == "blocking-under-lock"
               and v["path"] == "paddle_tpu/steady.py"
               for v in s["violations"]), s["violations"]
    assert rc == 1


# ---------------------------------------------------------------------------
# pass 10: one actuator — control loops must not actuate (actuation)
# ---------------------------------------------------------------------------

import actuation  # noqa: E402


def _act_diags(tmp_path, source, fname="paddle_tpu/mod.py"):
    p = tmp_path / fname
    p.parent.mkdir(parents=True, exist_ok=True)
    init = tmp_path / "paddle_tpu" / "__init__.py"
    if not init.exists():
        init.write_text("")
    p.write_text(textwrap.dedent(source))
    return actuation.run(str(tmp_path))


_ACT_BODY = """
    import threading

    class Scaler:
        def __init__(self, controller, poll_s=0.1):
            self.controller = controller
            self._t = threading.Thread(target=self._loop, daemon=True,
                                       name="scaler")

        def _loop(self):
            self._tick()

        def _tick(self):
            self._deep()

        def _deep(self):
            self.controller.grow(2){escape}
"""


def test_direct_actuation_flagged_transitively(tmp_path):
    # grow() is two helper hops below the thread target — the closure
    # is transitive, unlike the clock rule's one-level scan
    diags = _act_diags(tmp_path, _ACT_BODY.format(escape=""))
    assert _rules(diags) == {"direct-actuation"}
    assert "propose" in diags[0].message


def test_direct_actuation_actuate_ok_with_reason_passes(tmp_path):
    diags = _act_diags(tmp_path, _ACT_BODY.format(
        escape="  # graftlint: actuate-ok standalone mode, no reconciler"))
    assert not diags


def test_direct_actuation_bare_actuate_ok_still_flagged(tmp_path):
    # the escape hatch without a WHY is itself a violation
    diags = _act_diags(tmp_path, _ACT_BODY.format(
        escape="  # graftlint: actuate-ok"))
    assert _rules(diags) == {"direct-actuation"}
    assert "reason" in diags[0].message


def test_direct_actuation_ignore_comment_passes(tmp_path):
    diags = _act_diags(tmp_path, _ACT_BODY.format(
        escape="  # graftlint: ignore[direct-actuation]"))
    assert not diags


def test_direct_actuation_self_calls_pass(tmp_path):
    # a class driving ITS OWN lifecycle (self.promote()) is not
    # cross-subsystem actuation
    diags = _act_diags(tmp_path, """
        import threading

        class Rollout:
            def __init__(self, poll_s=0.1):
                self._t = threading.Thread(target=self._loop, daemon=True,
                                           name="r")

            def _loop(self):
                self.promote()

            def promote(self):
                pass
    """)
    assert not diags


def test_direct_actuation_non_loop_class_passes(tmp_path):
    # no thread target → not a control loop → out of scope (the
    # reconciler calls these primitives from plain methods)
    diags = _act_diags(tmp_path, """
        class Plain:
            def __init__(self, controller):
                self.controller = controller

            def act(self):
                self.controller.grow(2)
    """)
    assert not diags


def test_direct_actuation_reconciler_module_exempt(tmp_path):
    diags = _act_diags(tmp_path, _ACT_BODY.format(escape=""),
                       fname="paddle_tpu/ps/reconcile.py")
    assert not diags


def test_direct_actuation_ship_tree_clean():
    # the committed tree's only direct-actuation sites carry justified
    # actuate-ok escapes (the autoscaler's standalone-mode branch)
    assert actuation.run(REPO) == []
