"""Crash-consistent unified job checkpointing (io/job_checkpoint.py).

Layers under test, bottom-up: the CRC32C primitive and durability
helpers (io/fs.py), the manifest/verify/fallback protocol over
dense-only checkpoints (no native toolchain needed), the save-path
faultpoints (torn writes are *scheduled*, not hoped-for), the
consistent-cut gate under concurrent PS traffic, trainer-integrated
checkpoint/resume bit-identity against an uninterrupted oracle, and THE
acceptance run — SIGKILL the whole job (trainers + in-process PS
cluster) mid-save in a subprocess, restart, resume from the newest
verified checkpoint with the newest published one deliberately
corrupted (checksum-detected fallback), final params bit-identical."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.core.enforce import NotFoundError
from paddle_tpu.io import checkpoint as ckpt
from paddle_tpu.io.fs import crc32c, crc32c_file, publish_atomic
from paddle_tpu.io.job_checkpoint import (CorruptCheckpointError,
                                          JobCheckpointManager,
                                          combined_digest, verify_checkpoint)
from paddle_tpu.ps.faultpoints import (FaultInjected, arm_faultpoint,
                                       disarm_faultpoints)

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    yield
    disarm_faultpoints()


def _dense(seed=0):
    rng = np.random.default_rng(seed)
    return {"state": {"w": rng.normal(size=32).astype(np.float32),
                      "b": rng.normal(size=4).astype(np.float32)},
            "opt": {"m": rng.normal(size=32).astype(np.float32)}}


def _flip_byte(path, off=None):
    size = os.path.getsize(path)
    off = size // 2 if off is None else off
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


# ---------------------------------------------------------------------------
# CRC32C + durability primitives
# ---------------------------------------------------------------------------

def test_crc32c_known_vectors_and_chaining(tmp_path):
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283  # the Castagnoli check word
    # RFC 3720 B.4: 32 zero bytes
    assert crc32c(bytes(32)) == 0x8A9136AA
    data = np.random.default_rng(0).integers(
        0, 256, 200_003, dtype=np.uint8).tobytes()
    one = crc32c(data)
    acc = 0
    for lo in range(0, len(data), 7001):  # chaining == one-shot
        acc = crc32c(data[lo:lo + 7001], acc)
    assert acc == one
    p = tmp_path / "blob"
    p.write_bytes(data)
    assert crc32c_file(str(p), chunk=4096) == one


def test_publish_atomic_directory(tmp_path):
    tmp = tmp_path / "stage.tmp"
    tmp.mkdir()
    (tmp / "a").write_text("payload")
    final = tmp_path / "published"
    publish_atomic(str(tmp), str(final))
    assert not tmp.exists() and (final / "a").read_text() == "payload"


# ---------------------------------------------------------------------------
# manifest / verify / corruption fallback (dense-only: no native needed)
# ---------------------------------------------------------------------------

def _mgr(tmp_path, **kw):
    return JobCheckpointManager(str(tmp_path / "ckpt"), **kw)


def _save_n(mgr, n, start=0):
    for i in range(start, start + n):
        mgr.save(step=i, cursor={"batch": i}, dense=_dense(i), blocking=True)


def test_save_load_roundtrip_and_manifest(tmp_path):
    mgr = _mgr(tmp_path)
    _save_n(mgr, 2)
    r = mgr.load_latest()
    assert r.step == 1 and r.cursor == {"batch": 1}
    want = _dense(1)
    np.testing.assert_array_equal(r.dense["state"]["w"], want["state"]["w"])
    np.testing.assert_array_equal(r.dense["opt"]["m"], want["opt"]["m"])
    man = verify_checkpoint(os.path.join(mgr.root, "ckpt_1"))
    assert man["step"] == 1 and man["dense"] is True
    assert set(man["artifacts"]) == {"dense.npz", "dense.meta.json"}
    mgr.stop()


def test_async_writer_publishes_and_latches_failures(tmp_path):
    mgr = _mgr(tmp_path)
    mgr.save(step=0, cursor={"batch": 0}, dense=_dense(0))
    mgr.wait()
    assert mgr.load_latest().step == 0
    # a write failure on the background thread surfaces at the NEXT
    # save (the communicator push-failure contract), never silently
    arm_faultpoint("ckpt.artifact", "drop-frame")
    mgr.save(step=1, cursor={"batch": 1}, dense=_dense(1))
    with pytest.raises(FaultInjected):
        mgr.wait()
    disarm_faultpoints()
    # the failed snapshot never published; the manager keeps working
    mgr.save(step=2, cursor={"batch": 2}, dense=_dense(2))
    mgr.stop()
    assert mgr.load_latest().step == 2


def test_truncated_artifact_falls_back(tmp_path):
    mgr = _mgr(tmp_path)
    _save_n(mgr, 2)
    # torn write: the crash landed between write and fsync
    path = os.path.join(mgr.root, "ckpt_1", "dense.npz")
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    r = mgr.load_latest()
    assert r.step == 0
    assert mgr.fallbacks and "truncated" in mgr.fallbacks[0][1]
    mgr.stop()


def test_bit_flipped_artifact_falls_back(tmp_path):
    mgr = _mgr(tmp_path)
    _save_n(mgr, 2)
    _flip_byte(os.path.join(mgr.root, "ckpt_1", "dense.npz"))
    r = mgr.load_latest()
    assert r.step == 0
    assert mgr.fallbacks and "CRC32C" in mgr.fallbacks[0][1]
    mgr.stop()


def test_missing_and_partial_manifest_fall_back(tmp_path):
    mgr = _mgr(tmp_path, max_keep=5)
    _save_n(mgr, 3)
    os.remove(os.path.join(mgr.root, "ckpt_2", "manifest.json"))
    with open(os.path.join(mgr.root, "ckpt_1", "manifest.json"),
              "r+") as f:  # torn mid-write: valid prefix, invalid JSON
        f.truncate(20)
    r = mgr.load_latest()
    assert r.step == 0
    reasons = dict(mgr.fallbacks)
    assert "missing" in reasons[2] and "unreadable" in reasons[1]
    mgr.stop()


def test_parseable_manifest_corruption_falls_back(tmp_path):
    """A flipped byte can leave manifest.json PARSEABLE — a cursor
    digit changes, every artifact CRC still verifies, and the job would
    silently resume at the wrong stream position. Only the manifest's
    own self-checksum catches this class."""
    mgr = _mgr(tmp_path, max_keep=5)
    _save_n(mgr, 2)
    mpath = os.path.join(mgr.root, "ckpt_1", "manifest.json")
    with open(mpath) as f:
        text = f.read()
    assert '"batch": 1' in text
    with open(mpath, "w") as f:
        f.write(text.replace('"batch": 1', '"batch": 9'))
    r = mgr.load_latest()
    assert r.step == 0
    assert mgr.fallbacks and "self-CRC32C" in mgr.fallbacks[0][1]
    # stripping the self-checksum entirely is corruption too, not a
    # downgrade to unchecked mode
    man = json.loads(text)
    del man["manifest_crc32c"]
    with open(mpath, "w") as f:
        json.dump(man, f)
    with pytest.raises(CorruptCheckpointError, match="self-checksum"):
        verify_checkpoint(os.path.join(mgr.root, "ckpt_1"))
    mgr.stop()


def test_no_verified_checkpoint_raises_notfound(tmp_path):
    mgr = _mgr(tmp_path)
    with pytest.raises(NotFoundError):
        mgr.load_latest()
    _save_n(mgr, 1)
    _flip_byte(os.path.join(mgr.root, "ckpt_0", "dense.npz"))
    with pytest.raises(NotFoundError):
        mgr.load_latest()
    with pytest.raises(CorruptCheckpointError):
        verify_checkpoint(os.path.join(mgr.root, "ckpt_0"))
    mgr.stop()


def test_faultpoint_truncate_and_flip_are_checksum_detected(tmp_path):
    """The armed save-path faults corrupt AFTER the checksum snapshot —
    exactly a torn write — so the verifier must catch them."""
    mgr = _mgr(tmp_path, max_keep=5)
    _save_n(mgr, 1)
    arm_faultpoint("ckpt.artifact", "truncate-artifact")
    _save_n(mgr, 1, start=1)   # publishes, but torn
    disarm_faultpoints()
    arm_faultpoint("ckpt.artifact", "flip-bytes")
    _save_n(mgr, 1, start=2)   # publishes, but bit-flipped
    disarm_faultpoints()
    r = mgr.load_latest()
    assert r.step == 0 and len(mgr.fallbacks) == 2
    mgr.stop()


def test_kill_before_publish_leaves_no_published_ckpt(tmp_path):
    """A crash before the os.replace (here: drop-frame at ckpt.publish)
    leaves only an unpublished .tmp — invisible to load, cleaned by the
    next manager."""
    mgr = _mgr(tmp_path)
    _save_n(mgr, 1)
    arm_faultpoint("ckpt.publish", "drop-frame")
    mgr.save(step=1, cursor={"batch": 1}, dense=_dense(1))
    with pytest.raises(FaultInjected):
        mgr.wait()
    disarm_faultpoints()
    assert mgr._ids() == [0]
    assert os.path.isdir(os.path.join(mgr.root, "ckpt_1.tmp"))
    assert mgr.load_latest().step == 0
    mgr.stop()
    mgr2 = JobCheckpointManager(mgr.root)   # restart: stale tmp cleared
    assert not os.path.exists(os.path.join(mgr.root, "ckpt_1.tmp"))
    assert mgr2._ids() == [0]
    mgr2.stop()


def test_gc_keeps_max_keep_newest(tmp_path):
    mgr = _mgr(tmp_path, max_keep=2)
    _save_n(mgr, 4)
    assert mgr._ids() == [2, 3]
    mgr.stop()


# ---------------------------------------------------------------------------
# sparse tables + gate (native toolchain)
# ---------------------------------------------------------------------------

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

native_mark = pytest.mark.skipif(not rpc.rpc_available(),
                                 reason="native toolchain unavailable")

from paddle_tpu.ps import ha  # noqa: E402
from paddle_tpu.ps.accessor import AccessorConfig  # noqa: E402
from paddle_tpu.ps.sgd_rule import SGDRuleConfig  # noqa: E402
from paddle_tpu.ps.table import (MemorySparseTable, TableConfig,  # noqa: E402
                                 row_digest)


def _cfg():
    return TableConfig(shard_num=4, accessor_config=AccessorConfig(
        sgd=SGDRuleConfig(initial_range=0.0)))


@native_mark
def test_table_snapshot_restore_bit_exact(tmp_path):
    t = MemorySparseTable(_cfg())
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 4096, 700).astype(np.uint64)
    t.pull_sparse(keys, create=True)
    push = np.zeros((len(keys), 12), np.float32)
    push[:, 1] = 1.0
    push[:, 3:] = rng.normal(0, 0.1, (len(keys), 9)).astype(np.float32)
    t.push_sparse(keys, push)
    mgr = _mgr(tmp_path)
    mgr.register_sparse("ctr", t)
    mgr.save(step=1, dense=None, blocking=True)
    r = mgr.load_latest()
    fresh = MemorySparseTable(_cfg())
    n = r.restore_sparse("ctr", fresh)
    assert n == len(np.unique(keys))
    assert fresh.digest() == t.digest()   # bit-identical content
    # a corrupted restore target / drifted content is digest-detected
    bad = MemorySparseTable(_cfg())
    bad.pull_sparse(np.asarray([1 << 40], np.uint64), create=True)
    with pytest.raises(CorruptCheckpointError):
        r.restore_sparse("ctr", bad)
    mgr.stop()


@native_mark
def test_ssd_table_snapshot_restore_across_tiers(tmp_path):
    """Two-tier tables checkpoint through the same surface: snapshot
    covers hot + cold rows and the restored digest (sst_digest, both
    tiers) matches — this pinned a missing python binding for
    sst_digest found while driving the manager over SSD tables."""
    from paddle_tpu.ps.table import SsdSparseTable

    cfg = TableConfig(shard_num=4, storage="ssd",
                      accessor_config=AccessorConfig(
                          sgd=SGDRuleConfig(initial_range=0.0)))
    t = SsdSparseTable(str(tmp_path / "ssd_a"), cfg)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 30, 1500).astype(np.uint64)
    t.pull_sparse(keys, create=True)
    push = np.zeros((len(keys), 12), np.float32)
    push[:, 1] = 1.0
    push[:, 3:] = rng.normal(0, 0.1, (len(keys), 9)).astype(np.float32)
    t.push_sparse(keys, push)
    t.spill(300)   # most rows live in the cold tier at capture time
    mgr = _mgr(tmp_path)
    mgr.register_sparse("ssd", t)
    mgr.save(step=1, blocking=True)
    r = mgr.load_latest()
    fresh = SsdSparseTable(str(tmp_path / "ssd_b"), cfg)
    assert r.restore_sparse("ssd", fresh) == len(np.unique(keys))
    assert fresh.digest() == t.digest()
    mgr.stop()
    t.close()
    fresh.close()


@native_mark
def test_gate_cut_is_consistent_under_concurrent_pushes(tmp_path):
    """Captures taken while another client hammers pushes must be
    self-consistent: the manifest digest (taken under the gate) must
    equal the python row_digest of the arrays that were captured —
    a torn cut (rows moving mid-export) cannot hash equal."""
    import threading

    with ha.HACluster(num_shards=2, replication=2, sync=True) as cluster:
        cli = cluster.client()
        cli.create_sparse_table(0, _cfg())
        remote = rpc.RemoteSparseTable(cli, 0, _cfg())
        stop = threading.Event()
        rng = np.random.default_rng(1)

        def hammer():
            cli2 = cluster.client()
            r = np.random.default_rng(2)
            while not stop.is_set():
                ks = r.integers(0, 512, 64).astype(np.uint64)
                push = np.zeros((64, 12), np.float32)
                push[:, 1] = 1.0
                push[:, 3:] = r.normal(0, 0.1, (64, 9)).astype(np.float32)
                cli2.push_sparse(0, ks, push)

        seed_keys = rng.integers(0, 512, 256).astype(np.uint64)
        cli.pull_sparse(0, seed_keys, create=True)
        th = threading.Thread(target=hammer)
        th.start()
        try:
            mgr = _mgr(tmp_path, gate=cluster.checkpoint_gate(), max_keep=8)
            mgr.register_sparse("ctr", remote)
            for i in range(3):
                mgr.save(step=i, blocking=True)
        finally:
            stop.set()
            th.join()
        for no in mgr._ids():
            path = os.path.join(mgr.root, f"ckpt_{no}")
            man = verify_checkpoint(path)
            snap = ckpt.load(os.path.join(path, "sparse_ctr"))
            assert row_digest(
                np.ascontiguousarray(snap["keys"], np.uint64),
                np.ascontiguousarray(snap["values"], np.float32)) \
                == man["tables"]["ctr"]["digest"]
        assert mgr.stats()["pause_ms_last"] > 0.0
        mgr.stop()


# ---------------------------------------------------------------------------
# trainer-integrated resume: bit-identical to an uninterrupted oracle
# ---------------------------------------------------------------------------

def _make_stream_data(n=640, S=3, D=2, seed=0):
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc

    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        ids = rng.integers(0, 48, S)
        dense = rng.normal(size=D)
        label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
        lines.append(" ".join([f"1 {v}" for v in ids]
                              + [f"1 {v:.4f}" for v in dense]
                              + [f"1 {label}"]))
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines)
    return ds


def _make_trainer(table, S=3, D=2):
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    pt.seed(0)
    return CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), table, embedx_dim=8,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")


def _leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


@native_mark
def test_stream_trainer_checkpoint_resume_bit_identical(tmp_path):
    """Local-table stream training with the in-loop checkpoint hook:
    restart from a mid-stream snapshot, replay the tail, and land
    BIT-identical (params, opt state, table digest) to a run that never
    stopped."""
    ds = _make_stream_data()

    oracle_tab = MemorySparseTable(_cfg())
    oracle = _make_trainer(oracle_tab)
    oracle.train_from_dataset(ds, batch_size=128)   # 5 batches

    job_tab = MemorySparseTable(_cfg())
    job = _make_trainer(job_tab)
    mgr = _mgr(tmp_path, max_keep=8)
    mgr.register_sparse("ctr", job_tab)
    job.train_from_dataset(ds, batch_size=128, checkpoint=mgr,
                           checkpoint_every=2)
    mgr.wait()

    # "restart": fresh table + trainer grafted from the batch-4 snapshot
    restored = mgr.load_latest()
    assert restored.cursor["batch"] == 4
    fresh_tab = MemorySparseTable(_cfg())
    resumed = _make_trainer(fresh_tab)
    restored.restore_sparse("ctr", fresh_tab)
    resumed.restore_train_state(restored.dense)
    # resume with the cursor DICT: a mismatched batch_size is a wrong
    # RECORD offset and must be rejected, not silently retrained
    with pytest.raises(Exception, match="record offset"):
        resumed.train_from_dataset(ds, batch_size=64,
                                   start_batch=restored.cursor)
    out = resumed.train_from_dataset(ds, batch_size=128,
                                     start_batch=restored.cursor)
    assert out["steps"] == 1.0   # only the tail replayed
    assert fresh_tab.digest() == oracle_tab.digest()
    for a, b in zip(_leaves(resumed.params), _leaves(oracle.params)):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_leaves(resumed.opt_state), _leaves(oracle.opt_state)):
        np.testing.assert_array_equal(a, b)
    mgr.stop()


# ---------------------------------------------------------------------------
# THE acceptance run: SIGKILL the whole job mid-save, restart, resume
# ---------------------------------------------------------------------------

_JOB_SCRIPT = r"""
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
from paddle_tpu.io import checkpoint as ckpt
from paddle_tpu.io.job_checkpoint import JobCheckpointManager
from paddle_tpu.models.ctr import CtrConfig, DeepFM
from paddle_tpu.ps import ha, rpc
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.communicator import SyncCommunicator
from paddle_tpu.ps.faultpoints import arm_faultpoint
from paddle_tpu.ps.ps_trainer import CtrStreamTrainer
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

phase, root, out = sys.argv[1], sys.argv[2], sys.argv[3]
S, D, B, ROWS = 3, 2, 128, 640
rng = np.random.default_rng(0)
lines = []
for _ in range(ROWS):
    ids = rng.integers(0, 48, S)
    dense = rng.normal(size=D)
    label = int((ids % 5 == 0).sum() + dense[0] > 1.0)
    lines.append(" ".join([f"1 {v}" for v in ids]
                          + [f"1 {v:.4f}" for v in dense]
                          + [f"1 {label}"]))
slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
         + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
         + [SlotDesc("label", is_float=True, max_len=1)])
ds = InMemoryDataset(slots, seed=0)
ds.load_from_lines(lines)
cfg = TableConfig(shard_num=4, accessor_config=AccessorConfig(
    sgd=SGDRuleConfig(initial_range=0.0)))

with ha.HACluster(num_shards=2, replication=2, sync=True) as cluster:
    cli = cluster.client()
    cli.create_sparse_table(0, cfg)
    comm = SyncCommunicator(cli)
    comm.start()
    pt.seed(0)
    tr = CtrStreamTrainer(
        DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=8,
                         dnn_hidden=(8,))),
        optimizer.Adam(1e-2), None, communicator=comm, table_id=0,
        embedx_dim=8, sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    remote = rpc.RemoteSparseTable(cli, 0, cfg)
    if phase == "oracle":
        tr.train_from_dataset(ds, batch_size=B)
    elif phase == "victim":
        # die by SIGKILL during the THIRD checkpoint's manifest write:
        # ckpt 0 and 1 publish fully, ckpt 2 is torn mid-save — the
        # whole job (trainer + both PS shards + coordinator) vanishes
        arm_faultpoint("ckpt.manifest", "kill-job", after=3)
        mgr = JobCheckpointManager(root, gate=cluster.checkpoint_gate(),
                                   max_keep=10)
        mgr.register_sparse("ctr", remote)
        tr.train_from_dataset(ds, batch_size=B, checkpoint=mgr,
                              checkpoint_every=1)
        mgr.stop()   # drains the writer: the armed kill MUST fire
        print("SURVIVED", flush=True)   # unreachable
        sys.exit(3)
    elif phase == "resume":
        mgr = JobCheckpointManager(root, gate=cluster.checkpoint_gate(),
                                   max_keep=10)
        mgr.register_sparse("ctr", remote)
        r = mgr.load_latest()
        r.restore_sparse("ctr", remote)
        tr.restore_train_state(r.dense)
        tr.train_from_dataset(ds, batch_size=B,
                              start_batch=r.cursor)
        print("META", r.ckpt_id, r.cursor["batch"], len(mgr.fallbacks),
              flush=True)
        mgr.stop()
    comm.stop()
    probe = np.unique(
        (np.arange(0, 48, dtype=np.uint64)[None, :]
         + (np.arange(S, dtype=np.uint64)[:, None] << np.uint64(32)))
        .reshape(-1))
    pulled = cli.pull_sparse(0, probe, create=False)
    ckpt.save({"pulled": pulled, "params": tr.params,
               "opt": tr.opt_state}, out)
print("DONE", flush=True)
"""


def _run_job(phase, root, out, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-c", _JOB_SCRIPT, phase, str(root), str(out)],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout)


@native_mark
@pytest.mark.slow
def test_job_sigkill_mid_save_resume_bit_identical(tmp_path):
    """E2E acceptance: SIGKILL the full job mid-save during
    CtrStreamTrainer training, corrupt the newest PUBLISHED checkpoint
    on top, restart — load falls back to the previous verified snapshot
    (checksum-detected) and the resumed run's final params/opt/table
    rows are BIT-identical to a fault-free oracle."""
    root = tmp_path / "jobckpt"
    oracle_out = tmp_path / "oracle"
    resume_out = tmp_path / "resume"

    p = _run_job("oracle", root, oracle_out)
    assert p.returncode == 0 and "DONE" in p.stdout, p.stdout + p.stderr

    p = _run_job("victim", root, tmp_path / "victim")
    assert p.returncode == -9, (p.returncode, p.stdout, p.stderr)  # SIGKILL
    assert "SURVIVED" not in p.stdout
    ids = sorted(int(d.split("_")[1]) for d in os.listdir(root)
                 if d.startswith("ckpt_") and not d.endswith(".tmp"))
    assert ids == [0, 1]   # ckpt 2 died unpublished

    # deliberately corrupt the newest PUBLISHED checkpoint: the restart
    # must detect it via checksums and fall back to ckpt_0
    _flip_byte(os.path.join(root, "ckpt_1", "sparse_ctr.npz"))

    p = _run_job("resume", root, resume_out)
    assert p.returncode == 0 and "DONE" in p.stdout, p.stdout + p.stderr
    meta = [l for l in p.stdout.splitlines() if l.startswith("META")][0]
    _, ckpt_id, cursor, fallbacks = meta.split()
    assert (int(ckpt_id), int(cursor), int(fallbacks)) == (0, 1, 1)

    want = ckpt.load(str(oracle_out))
    got = ckpt.load(str(resume_out))
    np.testing.assert_array_equal(got["pulled"], want["pulled"])
    for a, b in zip(_leaves(got["params"]), _leaves(want["params"])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(_leaves(got["opt"]), _leaves(want["opt"])):
        np.testing.assert_array_equal(a, b)


def test_backpressured_save_does_not_hold_lifecycle_lock(tmp_path):
    """Regression (py_locks blocking-under-lock): a save() parked on a
    FULL writer queue must not hold _mu — other savers' admission/id
    allocation and stop() stay responsive while it waits, and stop()
    still orders its shutdown sentinel BEHIND every admitted
    snapshot."""
    import threading

    mgr = _mgr(tmp_path, queue_depth=1)
    release = threading.Event()
    wrote = []
    real_write = mgr._write

    def slow_write(snap):
        release.wait(20)
        real_write(snap)
        wrote.append(snap.ckpt_id)

    mgr._write = slow_write
    # writer busy on snap 0; snap 1 fills the queue; snap 2 must park
    # on the bounded put — formerly while holding _mu
    mgr.save(step=0, dense=_dense(0))
    t2 = threading.Thread(
        target=lambda: [mgr.save(step=1, dense=_dense(1)),
                        mgr.save(step=2, dense=_dense(2))],
        name="ckpt-producer")
    t2.start()
    deadline = time.perf_counter() + 10
    while mgr._wq.qsize() < 1 and time.perf_counter() < deadline:
        time.sleep(0.01)
    # the lifecycle lock must be FREE while the producer is parked
    got_mu = mgr._mu.acquire(timeout=2)
    assert got_mu, "_mu held through a backpressured queue put"
    mgr._mu.release()
    # stop() (concurrent with the parked producer) must not deadlock
    # and must write everything that was admitted
    stopper = threading.Thread(target=mgr.stop, name="ckpt-stopper")
    stopper.start()
    time.sleep(0.05)
    release.set()
    t2.join(timeout=20)
    stopper.join(timeout=20)
    assert not t2.is_alive() and not stopper.is_alive()
    assert wrote == [0, 1, 2]          # FIFO, nothing behind the sentinel
    assert mgr.load_latest().step == 2
