"""Multi-chip sharded embedding serving (ps/sharded_cache.py) vs the
single-device cache: HeterComm pull/push parity (heter_comm_inl.h:441-616,
ps_gpu_wrapper.cc:825-893) on an 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.models.ctr import CtrConfig, DeepFM, make_ctr_train_step
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import (CacheConfig, HbmEmbeddingCache,
                                           cache_pull, cache_push)
from paddle_tpu.ps.sharded_cache import (check_route_overflow,
                                         make_sharded_ctr_train_step,
                                         route_bucket_capacity,
                                         routed_cache_pull,
                                         routed_cache_push,
                                         shard_spread_rows,
                                         shard_unspread_rows,
                                         sharded_cache_pull,
                                         sharded_cache_push)
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

K = 8  # shard axis size (test mesh)


def _mesh():
    return mesh_mod.make_mesh({"ps": K})


def _fresh_state(capacity, dim, rng):
    n = capacity
    return {
        "show": jnp.asarray(rng.uniform(0, 5, n).astype(np.float32)),
        "click": jnp.asarray(rng.uniform(0, 2, n).astype(np.float32)),
        "embed_w": jnp.asarray(rng.normal(size=(n, 1)).astype(np.float32)),
        "embed_state": jnp.asarray(rng.uniform(0, 1, (n, 1)).astype(np.float32)),
        "embedx_w": jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32)),
        "embedx_state": jnp.asarray(rng.uniform(0, 1, (n, 1)).astype(np.float32)),
        "has_embedx": jnp.asarray((rng.random(n) < 0.5).astype(np.float32)),
    }


def test_spread_roundtrip():
    rows = np.arange(1000, dtype=np.int32)
    s = shard_spread_rows(rows, 1 << 12, 8)
    assert len(np.unique(s)) == len(rows)
    # round-robin balance: each shard block gets 125 rows
    blocks = s // ((1 << 12) // 8)
    assert (np.bincount(blocks, minlength=8) == 125).all()
    np.testing.assert_array_equal(shard_unspread_rows(s, 1 << 12, 8), rows)


def test_sharded_pull_push_bitwise_parity(rng):
    """Serving parity: sharded pull returns identical values, sharded push
    leaves bit-identical state vs the single-device cache."""
    capacity, dim, n = 1 << 10, 4, 256
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim, embedx_threshold=3.0)
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    state_sharded = {k: jax.device_put(v, shard) for k, v in state.items()}

    rows = jnp.asarray(rng.integers(0, capacity, n), jnp.int32)
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))

    # single-device reference (jitted: eager mode fuses FMAs differently
    # at the 1e-7 level; compiled-vs-compiled is bit-identical)
    ref_pull_fn = jax.jit(cache_pull)
    ref_push_fn = jax.jit(
        lambda st, r, g, s, c: cache_push(st, r, g, s, c, cfg))
    ref_pull = ref_pull_fn(state, rows)
    ref_state = ref_push_fn(state, rows, grads, shows, clicks)

    pull_fn = jax.jit(shard_map(
        lambda st, r: sharded_cache_pull(st, r, "ps"),
        mesh=mesh, in_specs=(P("ps"), P("ps")), out_specs=P("ps"),
        check_vma=False))
    push_fn = jax.jit(shard_map(
        lambda st, r, g, s, c: sharded_cache_push(st, r, g, s, c, cfg, "ps"),
        mesh=mesh, in_specs=(P("ps"),) + (P("ps"),) * 4, out_specs=P("ps"),
        check_vma=False))

    got_pull = pull_fn(state_sharded, rows)
    np.testing.assert_array_equal(np.asarray(got_pull), np.asarray(ref_pull))

    got_state = push_fn(state_sharded, rows, grads, shows, clicks)
    for k in ref_state:
        np.testing.assert_array_equal(
            np.asarray(got_state[k]), np.asarray(ref_state[k]),
            err_msg=f"state[{k}] diverged")

    # multiple chained pushes stay bit-identical
    for it in range(3):
        r2 = jnp.asarray(rng.integers(0, capacity, n), jnp.int32)
        g2 = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
        c2 = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))
        ref_state = ref_push_fn(ref_state, r2, g2, shows, c2)
        got_state = push_fn(got_state, r2, g2, shows, c2)
    for k in ref_state:
        np.testing.assert_array_equal(
            np.asarray(got_state[k]), np.asarray(ref_state[k]),
            err_msg=f"state[{k}] diverged after chained pushes")


def _routed_fns(mesh, cfg, cap_factor=2.0, pre_dedup=True):
    pull = jax.jit(shard_map(
        lambda st, r: routed_cache_pull(st, r, "ps", cap_factor, pre_dedup),
        mesh=mesh, in_specs=(P("ps"), P("ps")), out_specs=(P("ps"), P()),
        check_vma=False))
    push = jax.jit(shard_map(
        lambda st, r, g, s, c: routed_cache_push(
            st, r, g, s, c, cfg, "ps", cap_factor, pre_dedup),
        mesh=mesh, in_specs=(P("ps"),) + (P("ps"),) * 4,
        out_specs=(P("ps"), P()), check_vma=False))
    return pull, push


def test_routed_pull_push_bitwise_parity(rng):
    """Key-routed all-to-all serving (split_input_to_shard analogue) is
    bit-identical to the single-device cache with pre_dedup=False (same
    per-row scatter-add sequence → same f32 rounding). pre_dedup=True
    pre-merges duplicates, which changes how many updates XLA's fused
    scatter applies per row (segment_sum+add folds into sequential
    scatter-adds onto the state), so it is ~1-ulp-close, not bitwise —
    asserted at rtol 2e-6. Pull is exact either way (no summation)."""
    capacity, dim, n = 1 << 10, 4, 256
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim, embedx_threshold=3.0)
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    state_sharded = {k: jax.device_put(v, shard) for k, v in state.items()}

    rows = jnp.asarray(rng.integers(0, capacity, n), jnp.int32)  # x-device dups
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))

    ref_pull = jax.jit(cache_pull)(state, rows)
    ref_state = jax.jit(
        lambda st, r, g, s, c: cache_push(st, r, g, s, c, cfg))(
            state, rows, grads, shows, clicks)

    for pre_dedup in (False, True):
        pull_fn, push_fn = _routed_fns(mesh, cfg, pre_dedup=pre_dedup)
        got_pull, ov = pull_fn(state_sharded, rows)
        assert int(ov) == 0
        np.testing.assert_array_equal(np.asarray(got_pull),
                                      np.asarray(ref_pull),
                                      err_msg=f"pull pre_dedup={pre_dedup}")
        got_state, ov = push_fn(state_sharded, rows, grads, shows, clicks)
        assert int(ov) == 0
        for k in ref_state:
            assert_fn = (np.testing.assert_array_equal if not pre_dedup else
                         lambda a, b, err_msg: np.testing.assert_allclose(
                             a, b, rtol=2e-6, atol=1e-7, err_msg=err_msg))
            assert_fn(np.asarray(got_state[k]), np.asarray(ref_state[k]),
                      err_msg=f"state[{k}] pre_dedup={pre_dedup}")



def test_routed_chained_pushes_match_gathered(rng):
    """The routed path and the dense all_gather fallback walk identical
    state trajectories (bitwise, pre_dedup=False) across chained pushes."""
    capacity, dim, n = 1 << 9, 4, 128
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim, embedx_threshold=2.0)
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    routed = {k: jax.device_put(v, shard) for k, v in state.items()}
    gathered = {k: jax.device_put(v, shard) for k, v in state.items()}

    _, push_routed = _routed_fns(mesh, cfg, pre_dedup=False)
    push_gathered = jax.jit(shard_map(
        lambda st, r, g, s, c: sharded_cache_push(st, r, g, s, c, cfg, "ps"),
        mesh=mesh, in_specs=(P("ps"),) + (P("ps"),) * 4, out_specs=P("ps"),
        check_vma=False))

    for it in range(4):
        rows = jnp.asarray(rng.integers(0, capacity, n), jnp.int32)
        grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
        shows = jnp.ones((n,), jnp.float32)
        clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))
        routed, ov = push_routed(routed, rows, grads, shows, clicks)
        assert int(ov) == 0
        gathered = push_gathered(gathered, rows, grads, shows, clicks)
    for k in routed:
        np.testing.assert_array_equal(np.asarray(routed[k]),
                                      np.asarray(gathered[k]),
                                      err_msg=f"state[{k}]")


def test_routed_overflow_detection(rng):
    """Bucket overflow is reported loudly, never silently dropped: an
    adversarial batch (every row owned by shard 0) with a sub-unit
    cap_factor must produce a positive overflow count, and
    check_route_overflow must raise on it."""
    capacity, dim, n = 1 << 10, 4, 256
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim)
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    state_sharded = {k: jax.device_put(v, shard) for k, v in state.items()}
    block = capacity // K
    # distinct rows, all in shard 0's block → one bucket takes the world
    rows = jnp.asarray(rng.permutation(block)[:n // K].repeat(K), jnp.int32)
    pull_fn, _ = _routed_fns(mesh, cfg, cap_factor=0.25, pre_dedup=False)
    _, ov = pull_fn(state_sharded, rows)
    assert int(ov) > 0
    with pytest.raises(Exception, match="overflow"):
        check_route_overflow(ov)
    # same batch at the default factor is clean: dedup collapses the
    # cross-device duplicates and capacity min()s at m
    pull_ok, _ = _routed_fns(mesh, cfg, cap_factor=2.0, pre_dedup=True)
    vals, ov = pull_ok(state_sharded, rows)
    assert int(ov) == 0
    np.testing.assert_array_equal(
        np.asarray(vals), np.asarray(jax.jit(cache_pull)(state, rows)))


def test_routed_negative_sentinel_rows(rng):
    """Negative row ids (miss sentinels) pull zeros and drop pushes on
    the routed path — including with pre_dedup, where the sorted-unique
    owner-order invariant must hold despite negatives sorting first."""
    capacity, dim, n = 1 << 9, 4, 64
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim)
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    ss = {k: jax.device_put(v, shard) for k, v in state.items()}
    rows = np.asarray(rng.integers(0, capacity, n), np.int32)
    rows[:: 3] = -1  # a third of the batch misses
    rows = jnp.asarray(rows)
    ref = np.array(jax.jit(cache_pull)(state, jnp.maximum(rows, 0)))
    ref[np.asarray(rows) < 0] = 0.0
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.zeros((n,), jnp.float32)
    for pre_dedup in (False, True):
        pull_fn, push_fn = _routed_fns(mesh, cfg, pre_dedup=pre_dedup)
        vals, ov = pull_fn(ss, rows)
        assert int(ov) == 0
        np.testing.assert_array_equal(np.asarray(vals), ref,
                                      err_msg=f"pre_dedup={pre_dedup}")
        new_state, ov = push_fn(ss, rows, grads, shows, clicks)
        assert int(ov) == 0
        # pushed only to valid rows: every row NOT in the batch unchanged
        touched = set(np.asarray(rows)[np.asarray(rows) >= 0].tolist())
        untouched = np.setdiff1d(np.arange(capacity), sorted(touched))
        np.testing.assert_array_equal(
            np.asarray(new_state["embed_w"])[untouched],
            np.asarray(state["embed_w"])[untouched])


def test_routed_hot_key_batches_fit_with_dedup(rng):
    """Production-shaped adversarial load: a super-hot key in ~35% of
    the batch (the default-feasign pattern in real CTR data). Without
    dedup that shard's bucket would need 0.35·m > cap at factor 2/K=8;
    local pre-dedup (the default) collapses the duplicates so the batch
    routes overflow-free, and results still match the oracle."""
    capacity, dim, n = 1 << 10, 4, 512
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim, embedx_threshold=3.0)
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    ss = {k: jax.device_put(v, shard) for k, v in state.items()}
    rows = np.asarray(rng.integers(0, capacity, n), np.int32)
    hot = int(rows[0])
    rows[rng.random(n) < 0.35] = hot  # one key dominates the batch
    rows = jnp.asarray(rows)
    pull_fn, push_fn = _routed_fns(mesh, cfg, pre_dedup=True)
    vals, ov = pull_fn(ss, rows)
    assert int(ov) == 0, "hot-key batch overflowed despite pre-dedup"
    np.testing.assert_array_equal(np.asarray(vals),
                                  np.asarray(jax.jit(cache_pull)(state, rows)))
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))
    new_state, ov = push_fn(ss, rows, grads, shows, clicks)
    assert int(ov) == 0
    ref = jax.jit(lambda st, r, g, s, c: cache_push(st, r, g, s, c, cfg))(
        state, rows, grads, shows, clicks)
    for k in ref:
        np.testing.assert_allclose(np.asarray(new_state[k]),
                                   np.asarray(ref[k]), rtol=3e-5, atol=1e-6,
                                   err_msg=f"state[{k}]")
    # the same batch WITHOUT dedup must report the overflow loudly
    _, push_raw = _routed_fns(mesh, cfg, pre_dedup=False)
    _, ov_raw = push_raw(ss, rows, grads, shows, clicks)
    assert int(ov_raw) > 0, "raw routing should overflow on the hot key"


def test_routed_work_scales_inverse_with_shards():
    """VERDICT r2 #2 'done' criterion: per-shard touched rows are
    O(batch·cap_factor), independent of the shard count K — vs the
    gathered path's O(batch·K). The bucket geometry is static, so this
    is a shape-level property of route_bucket_capacity."""
    m, f = 1 << 16, 2.0
    per_shard = {K: K * route_bucket_capacity(m, K, f) for K in (2, 4, 8, 32)}
    for K, touched in per_shard.items():
        assert touched <= f * m + 16 * K, (K, touched)  # ~f·m, not K·m
        assert touched < 3 * m  # gathered path would touch K·m
    # monotone shrink per shard: each shard's own slice is m·f/K
    assert route_bucket_capacity(m, 32, f) < route_bucket_capacity(m, 2, f)


def test_routed_pull_hlo_has_no_allgather(rng):
    """The routed pull compiles to all-to-all routing with NO all_gather
    of the batch (the gathered fallback's signature op)."""
    capacity, dim, n = 1 << 10, 4, 256
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    state_sharded = {k: jax.device_put(v, shard) for k, v in state.items()}
    rows = jnp.asarray(rng.integers(0, capacity, n), jnp.int32)
    fn = shard_map(lambda st, r: routed_cache_pull(st, r, "ps"),
                   mesh=mesh, in_specs=(P("ps"), P("ps")),
                   out_specs=(P("ps"), P()), check_vma=False)
    hlo = jax.jit(fn).lower(state_sharded, rows).compile().as_text()
    assert "all-to-all" in hlo
    assert "all-gather" not in hlo


@pytest.mark.slow
def test_sharded_ctr_end_to_end_vs_single_device(rng):
    """Full pass lifecycle on a row-sharded cache (begin_pass → sharded
    train steps → end_pass) converges to the same host table contents as
    the single-device cache path."""
    dim = 4
    ccfg = CtrConfig(num_sparse_slots=6, num_dense=5, embedx_dim=dim,
                     dnn_hidden=(16,))
    cache_cfg = CacheConfig(capacity=1 << 12, embedx_dim=dim,
                            embedx_threshold=0.0)
    n_keys, batch, steps = 300, 32, 4
    pool = rng.integers(1, 1 << 40, size=(n_keys, ccfg.num_sparse_slots)).astype(np.uint64)
    batches = []
    for _ in range(steps):
        idx = rng.integers(0, n_keys, size=batch)
        batches.append((
            pool[idx],
            rng.normal(size=(batch, ccfg.num_dense)).astype(np.float32),
            (rng.random(batch) < 0.3).astype(np.int32),
        ))

    def run(mesh):
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(embedx_dim=dim)))
        model = DeepFM(ccfg)
        opt = optimizer.Adam(learning_rate=1e-3)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        opt_state = opt.init(params)
        if mesh is None:
            cache = HbmEmbeddingCache(table, cache_cfg)
            step = make_ctr_train_step(model, opt, cache_cfg, donate=False)
        else:
            cache = HbmEmbeddingCache(table, cache_cfg, mesh=mesh, axis="ps")
            step = make_sharded_ctr_train_step(model, opt, cache_cfg, mesh,
                                               axis="ps", donate=False)
        cache.begin_pass(pool.reshape(-1))
        for keys, dense, labels in batches:
            rows = jnp.asarray(cache.lookup(keys.reshape(-1)).reshape(keys.shape))
            out = step(params, opt_state, cache.state, rows,
                       jnp.asarray(dense), jnp.asarray(labels))
            params, opt_state, cache.state, loss = out[:4]
            if len(out) == 5:
                check_route_overflow(out[4])
        cache.end_pass()
        vals, found = table.export_full(pool.reshape(-1))
        assert found.all()
        return vals, float(loss)

    ref_vals, ref_loss = run(None)
    got_vals, got_loss = run(_mesh())
    assert np.isfinite(got_loss)
    np.testing.assert_allclose(got_loss, ref_loss, rtol=1e-4)
    np.testing.assert_allclose(got_vals, ref_vals, rtol=2e-4, atol=1e-5)


def test_select_routing_rule(monkeypatch):
    """The calibrated decision rule (tools/routed_grid.py →
    ROUTED_GRID.json): never mix sides (mixed combos pay both the dedup
    sort and the full-batch gather — measured worst), route both at
    K ≥ 4, gather both below — EXCEPT across processes, where the
    multihost sweeps (ROUTED_MULTIHOST*.json: 0.92× at K=2 dense)
    show routing wins at every K."""
    import jax as _jax

    from paddle_tpu.ps import sharded_cache as sc

    for push_mode in ("dense", "sparse"):
        assert sc.select_routing(1024, 1 << 14, 2, push_mode) == (
            "allgather", "allgather")
        for k in (4, 8, 64):
            assert sc.select_routing(1024, 1 << 14, k, push_mode) == (
                "alltoall", "alltoall")
    with pytest.raises(Exception, match="push_mode"):
        sc.select_routing(1024, 1 << 14, 8, "bogus")

    # multi-process regime: DENSE routes at every K (measured 0.92x at
    # K=2); SPARSE keeps the K>=4 threshold (measured 1.28x at K=2 —
    # the dedup sort loses at tiny K even across a process boundary)
    monkeypatch.setattr(_jax, "process_count", lambda: 2)
    for k in (2, 4, 8):
        assert sc.select_routing(1024, 1 << 14, k, "dense") == (
            "alltoall", "alltoall")
    assert sc.select_routing(1024, 1 << 14, 2, "sparse") == (
        "allgather", "allgather")
    for k in (4, 8):
        assert sc.select_routing(1024, 1 << 14, k, "sparse") == (
            "alltoall", "alltoall")


def test_routing_arg_validation():
    from paddle_tpu.core.enforce import EnforceNotMet

    ccfg = CtrConfig(num_sparse_slots=2, num_dense=2, embedx_dim=4)
    cache_cfg = CacheConfig(capacity=1 << 10, embedx_dim=4)
    model = DeepFM(ccfg)
    opt = optimizer.Adam(1e-3)
    for bad in ("routed", ("alltoall",), ("alltoall", "nope"), 7):
        with pytest.raises(EnforceNotMet, match="routing"):
            make_sharded_ctr_train_step(model, opt, cache_cfg, _mesh(),
                                        routing=bad)


@pytest.mark.parametrize("routing", ["alltoall", "allgather", "auto",
                                     ("alltoall", "allgather"),
                                     ("allgather", "alltoall")])
def test_sharded_key_fed_matches_row_fed(rng, routing):
    """In-graph lookup + sharded serving: identical trajectory to the
    host-lookup sharded step (the complete multi-chip GPUPS worker),
    for both the key-routed path and the dense allgather fallback."""
    from paddle_tpu.ps.sharded_cache import make_sharded_ctr_train_step_from_keys

    dim, S = 4, 5
    ccfg = CtrConfig(num_sparse_slots=S, num_dense=3, embedx_dim=dim,
                     dnn_hidden=(8,))
    cache_cfg = CacheConfig(capacity=1 << 12, embedx_dim=dim,
                            embedx_threshold=0.0)
    lo = rng.integers(0, 1 << 20, size=(200, S)).astype(np.uint64)
    pool = lo + (np.arange(S, dtype=np.uint64) << np.uint64(32))
    mesh = _mesh()

    def build(device_map):
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(embedx_dim=dim)))
        cache = HbmEmbeddingCache(table, cache_cfg, mesh=mesh, axis="ps",
                                  device_map=device_map)
        cache.begin_pass(pool.reshape(-1))
        model = DeepFM(ccfg)
        opt = optimizer.Adam(learning_rate=1e-3)
        params = {"params": dict(model.named_parameters()), "buffers": {}}
        return cache, model, opt, params, opt.init(params)

    idx = rng.integers(0, 200, size=(3, 16))
    dense = rng.normal(size=(3, 16, 3)).astype(np.float32)
    labels = (rng.random((3, 16)) < 0.4).astype(np.int32)

    c1, m1, o1, p1, s1 = build(device_map=False)
    step1 = make_sharded_ctr_train_step(m1, o1, cache_cfg, mesh, axis="ps",
                                        donate=False, routing=routing)
    for t in range(3):
        keys = pool[idx[t]]
        rows = jnp.asarray(c1.lookup(keys.reshape(-1)).reshape(keys.shape))
        p1, s1, c1.state, loss1, ov1 = step1(p1, s1, c1.state, rows,
                                             jnp.asarray(dense[t]),
                                             jnp.asarray(labels[t]))
        check_route_overflow(ov1)

    c2, m2, o2, p2, s2 = build(device_map=True)
    step2 = make_sharded_ctr_train_step_from_keys(
        m2, o2, cache_cfg, mesh, slot_ids=np.arange(S), axis="ps",
        donate=False, routing=routing)
    for t in range(3):
        lo32 = (pool[idx[t]] & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        p2, s2, c2.state, loss2, ov2 = step2(p2, s2, c2.state,
                                             c2.device_map.state,
                                             jnp.asarray(lo32),
                                             jnp.asarray(dense[t]),
                                             jnp.asarray(labels[t]))
        check_route_overflow(ov2)

    np.testing.assert_array_equal(np.asarray(loss1), np.asarray(loss2))
    for k in c1.state:
        np.testing.assert_array_equal(np.asarray(c1.state[k]),
                                      np.asarray(c2.state[k]),
                                      err_msg=f"state[{k}]")


def test_shared_dedup_matches_per_call(rng):
    """The step's shared routed_dedup (sort once, use in pull AND push)
    is bit-identical to each call doing its own dedup — including with
    negative miss markers, which routed_dedup canonicalizes itself."""
    from paddle_tpu.ps.sharded_cache import routed_dedup

    capacity, dim, n = 1 << 9, 4, 128
    cfg = CacheConfig(capacity=capacity, embedx_dim=dim)
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    ss = {k: jax.device_put(v, shard) for k, v in state.items()}
    rows = np.asarray(rng.integers(0, capacity, n), np.int32)
    rows[:: 5] = -1  # miss markers: dedup must canonicalize them
    rows = jnp.asarray(rows)
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))

    def run(shared):
        def body(st, r, g, s, c):
            d = routed_dedup(r, capacity) if shared else None
            vals, ov1 = routed_cache_pull(st, r, "ps", dedup=d)
            new, ov2 = routed_cache_push(st, r, g, s, c, cfg, "ps", dedup=d)
            return new, vals, ov1 + ov2

        fn = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("ps"),) + (P("ps"),) * 4,
            out_specs=(P("ps"), P("ps"), P()), check_vma=False))
        return fn(ss, rows, grads, shows, clicks)

    st1, v1, ov1 = run(shared=True)
    st2, v2, ov2 = run(shared=False)
    assert int(ov1) == int(ov2) == 0
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    for k in st1:
        np.testing.assert_array_equal(np.asarray(st1[k]),
                                      np.asarray(st2[k]), err_msg=k)


def test_routed_push_dense_mode_matches_oracle(rng):
    """The routed all-to-all push with push_mode="dense" (the per-shard
    TPU hot path: scatter-add + masked O(C/K) table streaming inside
    shard_map) matches the single-device sparse oracle — the dense mode
    composes with key routing with no routed-layer changes."""
    capacity, dim, n = 1 << 10, 4, 256
    cfg_d = CacheConfig(capacity=capacity, embedx_dim=dim,
                        embedx_threshold=3.0, push_mode="dense")
    cfg_s = CacheConfig(capacity=capacity, embedx_dim=dim,
                        embedx_threshold=3.0, push_mode="sparse")
    state = _fresh_state(capacity, dim, rng)
    mesh = _mesh()
    shard = NamedSharding(mesh, P("ps"))
    state_sharded = {k: jax.device_put(v, shard) for k, v in state.items()}

    rows = jnp.asarray(rng.integers(0, capacity, n), jnp.int32)
    grads = jnp.asarray(rng.normal(size=(n, 1 + dim)).astype(np.float32))
    shows = jnp.ones((n,), jnp.float32)
    clicks = jnp.asarray((rng.random(n) < 0.4).astype(np.float32))

    ref_state = jax.jit(
        lambda st, r, g, s, c: cache_push(st, r, g, s, c, cfg_s))(
            state, rows, grads, shows, clicks)
    _, push_fn = _routed_fns(mesh, cfg_d)
    got_state, ov = push_fn(state_sharded, rows, grads, shows, clicks)
    assert int(ov) == 0
    for k in ref_state:
        np.testing.assert_allclose(
            np.asarray(got_state[k]), np.asarray(ref_state[k]),
            rtol=2e-5, atol=1e-6, err_msg=f"state[{k}]")
