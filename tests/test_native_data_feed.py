"""Native channel data feed (csrc/data_feed.cc): multithreaded
file->parse->channel, parity with the single-threaded Python load
(reference: channel-based DataFeed, framework/data_feed.cc)."""

import numpy as np
import pytest

from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
from paddle_tpu.ps.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable")


def _slots():
    return [
        SlotDesc("click", is_float=False, max_len=1),
        SlotDesc("feat", is_float=False, max_len=3),
        SlotDesc("price", is_float=True, max_len=1),
    ]


def _write_files(tmp_path, n_files=6, lines_per=50):
    rng = np.random.default_rng(0)
    files = []
    all_rows = []
    for i in range(n_files):
        p = tmp_path / f"part-{i:03d}.txt"
        with open(p, "w") as f:
            for _ in range(lines_per):
                click = rng.integers(0, 2)
                feats = rng.integers(1, 1000, rng.integers(1, 4))
                price = rng.uniform(0, 10)
                row = (int(click), tuple(int(x) for x in feats), round(float(price), 3))
                all_rows.append(row)
                f.write(f"1 {click} {len(feats)} " +
                        " ".join(str(x) for x in feats) +
                        f" 1 {price:.3f}\n")
        files.append(str(p))
    return files, all_rows


def test_parallel_load_matches_serial(tmp_path):
    files, rows = _write_files(tmp_path)
    ds_native = InMemoryDataset(_slots())
    ds_native.set_filelist(files)
    n = ds_native.load_into_memory(num_threads=4)
    assert n == len(rows)
    assert ds_native.parse_errors == 0

    # records may arrive in any chunk order; compare as multisets
    def record_set(ds):
        recs = []
        for batch in ds.batch_iter(1, drop_last=False):
            click = int(batch["click"][0][0, 0])
            lens = int(batch["feat"][1][0])
            feats = tuple(int(x) for x in batch["feat"][0][0, :lens])
            price = round(float(batch["price"][0][0, 0]), 3)
            recs.append((click, feats, price))
        return sorted(recs)

    expected = sorted((c, f, p) for c, f, p in rows)
    assert record_set(ds_native) == expected


def test_native_feed_chunks_stream(tmp_path):
    files, rows = _write_files(tmp_path, n_files=3, lines_per=10)
    from paddle_tpu.ps.native import NativeDataFeed

    feed = NativeDataFeed([("click", False, True), ("feat", False, True),
                           ("price", True, True)], files, num_threads=2)
    total = 0
    chunks = 0
    for parsed in feed:
        vals, lens = parsed["click"]
        total += len(lens)
        chunks += 1
        assert parsed["price"][0].dtype == np.float32
        assert parsed["feat"][0].dtype == np.uint64
    assert total == 30 and chunks == 3
    feed.close()


def test_native_feed_empty_filelist():
    from paddle_tpu.ps.native import NativeDataFeed

    feed = NativeDataFeed([("a", False, True)], [], num_threads=2)
    assert list(feed) == []
    feed.close()


def test_native_feed_counts_bad_lines(tmp_path):
    p = tmp_path / "bad.txt"
    p.write_text("1 5 2 7 8 1 0.5\nGARBAGE LINE\n1 3 1 9 1 1.5\n")
    from paddle_tpu.ps.native import NativeDataFeed

    feed = NativeDataFeed([("click", False, True), ("feat", False, True),
                           ("price", True, True)], [str(p)])
    chunks = list(feed)
    assert sum(len(c["click"][1]) for c in chunks) == 2
    assert feed.errors == 1
    feed.close()
