"""Distributed graph sampling over the PS transport
(csrc/graph_store.h + ps/graph_client.py): multi-server partition vs
the local ps/graph_table.py GraphTable oracle.

Reference: common_graph_table.cc served through the graph brpc service
(graph_brpc_server/client) — node-id partitioning, per-server sampling,
client-side join.
"""

import numpy as np
import pytest

from paddle_tpu.core.enforce import NotFoundError
from paddle_tpu.ps.graph_table import GraphTable

rpc = pytest.importorskip("paddle_tpu.ps.rpc")
from paddle_tpu.ps.graph_client import DistGraphClient  # noqa: E402

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

N_SERVERS = 3
FEAT_DIM = 5


@pytest.fixture
def cluster():
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(N_SERVERS)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    yield client
    client.close()
    for s in servers:
        s.close()


def _build(graph_like, rng):
    """Same deterministic graph into any GraphTable-shaped object."""
    nodes = np.arange(1, 201, dtype=np.uint64)
    feats = rng.normal(size=(len(nodes), FEAT_DIM)).astype(np.float32)
    graph_like.add_graph_node(nodes, feats)
    src = rng.choice(nodes, 1200)
    dst = rng.choice(nodes, 1200)
    w = rng.uniform(0.1, 2.0, 1200).astype(np.float32)
    w[::7] = 0.0  # zero-weight edges: legal input, unsamplable weighted
    graph_like.add_edges(src, dst, w)
    return nodes, feats, src, dst, w


def test_partitioned_sampling_matches_local_oracle(cluster):
    rng = np.random.default_rng(0)
    dist = DistGraphClient(cluster, table_id=7)
    nodes, feats, src, dst, w = _build(dist, rng)
    local = GraphTable(shard_num=4)
    _build(local, np.random.default_rng(0))

    # topology counters agree across the partition
    assert dist.node_count == local.node_count == len(nodes)
    assert dist.edge_count == local.edge_count == len(src)

    # degrees: exact per-node parity with the local table
    q = rng.choice(nodes, 64, replace=False)
    np.testing.assert_array_equal(dist.get_node_degree(q),
                                  local.get_node_degree(q))

    # features: bit-exact roundtrip through the owner servers
    idx = {int(n): i for i, n in enumerate(nodes)}
    got = dist.get_node_feat(q, FEAT_DIM)
    want = np.stack([feats[idx[int(n)]] for n in q])
    np.testing.assert_array_equal(got, want)

    # neighbor sampling: per-node mask count = min(k, samplable degree),
    # every sampled id is a true neighbor, zero-weight edges never appear
    adj, wpos, adj_cnt, wpos_cnt = {}, {}, {}, {}
    for s, d, ww in zip(src, dst, w):
        adj.setdefault(int(s), set()).add(int(d))
        adj_cnt[int(s)] = adj_cnt.get(int(s), 0) + 1
        if ww > 0:
            wpos.setdefault(int(s), set()).add(int(d))
            wpos_cnt[int(s)] = wpos_cnt.get(int(s), 0) + 1
    for weighted in (True, False):
        k = 6
        nbrs, mask = dist.sample_neighbors(q, k, weighted=weighted)
        assert nbrs.shape == mask.shape == (len(q), k)
        for i, n in enumerate(q):
            cand = (wpos if weighted else adj).get(int(n), set())
            cnt = (wpos_cnt if weighted else adj_cnt).get(int(n), 0)
            got_n = set(nbrs[i][mask[i]].tolist())
            assert got_n <= cand, (n, got_n - cand)
            # without replacement over EDGES (parallel edges count
            # separately — multigraph semantics, as in the local table)
            assert mask[i].sum() == min(k, cnt), n

    # uniform node sampling covers only real nodes, from every server
    samp = dist.sample_nodes(300)
    assert len(samp) == 300
    assert set(samp.tolist()) <= set(int(n) for n in nodes)
    assert len({int(s) % N_SERVERS for s in samp}) == N_SERVERS


def test_sample_nodes_without_replacement_and_zero_weight_fallback(cluster):
    """Oracle-parity details: sample_nodes(population) is a permutation
    (no duplicates, full coverage), and a node whose edges ALL have zero
    weight still samples uniformly under weighted=True (the local
    table's w.sum()>0 fallback)."""
    dist = DistGraphClient(cluster, table_id=13)
    nodes = np.arange(1, 61, dtype=np.uint64)
    dist.add_graph_node(nodes)
    dist.add_edges([7, 7, 7], [8, 9, 10], [0.0, 0.0, 0.0])
    samp = dist.sample_nodes(len(nodes))
    assert sorted(samp.tolist()) == sorted(int(n) for n in nodes)
    nbrs, mask = dist.sample_neighbors([7], 2, weighted=True)
    assert mask[0].sum() == 2
    assert set(nbrs[0][mask[0]].tolist()) <= {8, 9, 10}


def test_set_node_feat_and_missing_node(cluster):
    rng = np.random.default_rng(1)
    dist = DistGraphClient(cluster, table_id=9)
    nodes = np.arange(10, 20, dtype=np.uint64)
    dist.add_graph_node(nodes)
    new = rng.normal(size=(len(nodes), FEAT_DIM)).astype(np.float32)
    dist.set_node_feat(nodes, new)
    np.testing.assert_array_equal(dist.get_node_feat(nodes, FEAT_DIM), new)
    with pytest.raises(NotFoundError):
        dist.set_node_feat(np.asarray([999], np.uint64),
                           np.zeros((1, FEAT_DIM), np.float32))


def test_graph_trainer_swaps_local_for_distributed(cluster):
    """The swap contract: a sampling loop written against GraphTable
    runs unchanged against DistGraphClient (same padded shapes)."""
    rng = np.random.default_rng(2)

    def two_hop(g):
        seeds = np.asarray([1, 2, 3], np.uint64)
        n1, m1 = g.sample_neighbors(seeds, 4, weighted=False)
        n2, m2 = g.sample_neighbors(n1.reshape(-1), 4, weighted=False)
        return (n1.shape, m1.shape, n2.shape, m2.shape)

    local = GraphTable(shard_num=2)
    _build(local, np.random.default_rng(3))
    dist = DistGraphClient(cluster, table_id=11)
    _build(dist, np.random.default_rng(3))
    assert two_hop(local) == two_hop(dist)
