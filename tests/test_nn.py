import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn


def test_linear_shapes_and_registration():
    pt.seed(0)
    layer = nn.Linear(4, 3)
    y = layer(jnp.ones((2, 4)))
    assert y.shape == (2, 3)
    names = dict(layer.named_parameters())
    assert set(names) == {"weight", "bias"}


def test_sublayer_traversal_and_state_dict():
    pt.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = model.state_dict()
    assert "0.weight" in sd and "2.bias" in sd
    # round-trip
    sd2 = {k: np.asarray(v) + 1 for k, v in sd.items()}
    model.set_state_dict(sd2)
    assert np.allclose(np.asarray(model.state_dict()["0.weight"]), sd2["0.weight"])


def test_functional_call_pure():
    pt.seed(0)
    model = nn.Linear(4, 2)
    state = nn.get_state(model)
    zeros = {"params": {k: jnp.zeros_like(v) for k, v in state["params"].items()}, "buffers": {}}
    out, _ = nn.functional_call(model, zeros, jnp.ones((1, 4)))
    assert np.allclose(np.asarray(out), 0.0)
    # original params restored after functional_call
    out2 = model(jnp.ones((1, 4)))
    assert not np.allclose(np.asarray(out2), 0.0)


def test_batchnorm_buffers_update_in_training():
    pt.seed(0)
    bn = nn.BatchNorm2D(3)
    x = jnp.asarray(np.random.default_rng(0).normal(2.0, 1.0, (4, 3, 5, 5)).astype(np.float32))
    bn.train()
    y = bn(x)
    assert y.shape == x.shape
    assert not np.allclose(np.asarray(bn._mean), 0.0)  # running mean moved
    bn.eval()
    y2 = bn(x)
    assert y2.shape == x.shape


def test_dropout_train_vs_eval():
    pt.seed(0)
    d = nn.Dropout(0.5)
    x = jnp.ones((100,))
    d.train()
    y = d(x)
    assert float(jnp.sum(y == 0)) > 0
    d.eval()
    assert np.allclose(np.asarray(d(x)), 1.0)


def test_conv_pool_shapes():
    pt.seed(0)
    conv = nn.Conv2D(1, 6, 3, padding=1)
    x = jnp.ones((2, 1, 28, 28))
    y = conv(x)
    assert y.shape == (2, 6, 28, 28)
    p = nn.functional.max_pool2d(y, 2, 2)
    assert p.shape == (2, 6, 14, 14)
    a = nn.functional.avg_pool2d(y, 2, 2)
    assert a.shape == (2, 6, 14, 14)


def test_cross_entropy_matches_manual():
    logits = jnp.asarray([[2.0, 1.0, 0.1]])
    labels = jnp.asarray([0])
    loss = nn.functional.cross_entropy(logits, labels)
    manual = -jax.nn.log_softmax(logits)[0, 0]
    assert np.allclose(float(loss), float(manual), atol=1e-6)


def test_embedding_padding_idx():
    pt.seed(0)
    emb = nn.Embedding(10, 4, padding_idx=0)
    out = emb(jnp.asarray([[0, 1]]))
    assert np.allclose(np.asarray(out[0, 0]), 0.0)
    assert not np.allclose(np.asarray(out[0, 1]), 0.0)


def test_layer_norm():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32))
    ln = nn.LayerNorm(8)
    y = ln(x)
    assert np.allclose(np.asarray(y.mean(-1)), 0.0, atol=1e-5)


def test_auto_cast_linear_and_conv_compute_bf16():
    """amp.auto_cast's contract: dense ops consult the amp state at
    trace time — the matmul/conv runs in bf16 with f32 accumulation and
    the output (and gradients) stay f32."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu import amp
    from paddle_tpu.nn import functional as F

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
    b = jnp.zeros((8,), jnp.float32)
    ref = F.linear(x, w, b)
    with amp.auto_cast(enable=True):
        out = jax.jit(F.linear)(x, w, b)
        g = jax.jit(jax.grad(lambda w: F.linear(x, w, b).sum()))(w)
    assert out.dtype == jnp.float32 and g.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    # the cast must actually be in the traced program (backend-neutral
    # check: on TPU the DEFAULT precision also rounds to bf16, so value
    # comparison can't distinguish the paths). Fresh wrapper per mode:
    # jax caches traces per function object, so re-tracing F.linear
    # itself would replay the amp-on jaxpr — the exact trace-time
    # pitfall auto_cast's docstring warns about.
    with amp.auto_cast(enable=True):
        jaxpr_on = str(jax.make_jaxpr(lambda x, w, b: F.linear(x, w, b))(x, w, b))
    assert "bfloat16" in jaxpr_on, jaxpr_on
    jaxpr_off = str(jax.make_jaxpr(lambda x, w, b: F.linear(x, w, b))(x, w, b))
    assert "bfloat16" not in jaxpr_off, jaxpr_off

    xc = jnp.asarray(rng.normal(size=(2, 3, 8, 8)).astype(np.float32))
    wc = jnp.asarray(rng.normal(size=(4, 3, 3, 3)).astype(np.float32))
    refc = F.conv2d(xc, wc)
    with amp.auto_cast(enable=True):
        outc = jax.jit(lambda x, w: F.conv2d(x, w))(xc, wc)
    assert outc.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(outc), np.asarray(refc),
                               rtol=5e-2, atol=5e-2)
