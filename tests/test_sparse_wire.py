"""Quantized sparse push wire (TableConfig.push_wire_dtype) + the SSD
fp16 record format's client-visible half.

Covers the ISSUE 14 tentpole leg 1 contracts:
- the PR 8 per-table byte counters measure the ENCODED wire (the ≥3x
  int8-vs-fp32 reduction the CI gate asserts);
- server dequant ≡ client dequant bit-for-bit (an fp32-wire push of the
  client-side dequantized values lands the identical table state);
- error-feedback residuals live per (table, key) on the client, fold
  into the next push, survive merge/dedup, and DRAIN at
  Communicator.quiesce() — zero residual rows after a cut (the
  digest-consistency contract) — with int8-wire training pinned against
  the fp32-wire oracle at a stated tolerance;
- a replicated backup replaying the TAPPED quantized frames converges
  bit-identically to the primary;
- malformed quantized frames reject whole (kErrBadSize) before any
  state change.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not __import__("paddle_tpu.ps.rpc", fromlist=["rpc_available"]
                   ).rpc_available(),
    reason="native PS service unavailable")

import paddle_tpu as pt  # noqa: E402
from paddle_tpu.ps import ha  # noqa: E402
from paddle_tpu.ps.accessor import AccessorConfig  # noqa: E402
from paddle_tpu.ps.communicator import SyncCommunicator  # noqa: E402
from paddle_tpu.ps.rpc import (NativePsServer, RpcPsClient,  # noqa: E402
                               _PUSH_WIRE_BLOCK_SHIFT, _PUSH_WIRE_I8,
                               _PUSH_SPARSE, _dequant_push_int8,
                               _quant_push_int8)
from paddle_tpu.ps.table import TableConfig, row_digest  # noqa: E402

MASK = 0xFFFFFFFFFFFFFFFF


def _acc(xd=64, th=0.0):
    # embedx_threshold 0: embedx initializes on the first push, so the
    # quantized gradient block actually lands in embedx weights
    return AccessorConfig(embedx_dim=xd, embedx_threshold=th)


def _mk_cluster(n=2):
    srvs = [NativePsServer() for _ in range(n)]
    return srvs, [f"127.0.0.1:{s.port}" for s in srvs]


def _stop(srvs):
    for s in srvs:
        s.stop()
        s.close()


def _pushes(cli, tid, keys, gd, steps, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        push = np.zeros((len(keys), 3 + gd), np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = rng.normal(0, 0.1, (len(keys), gd)).astype(np.float32)
        cli.push_sparse(tid, keys, push)


def _push_bytes(cli, tid):
    from paddle_tpu.obs import registry as _reg

    snap = _reg.REGISTRY.snapshot()["metrics"]
    fam = snap.get("ps_client_wire_bytes", {"series": []})
    return sum(s["value"] for s in fam["series"]
               if s["labels"].get("dir") == "push"
               and s["labels"].get("table") == str(tid))


def test_push_wire_byte_ratio_int8_ge_3x():
    """THE wire-byte acceptance: identical workload, per-table byte
    counters; int8 moves ≥3x fewer push bytes than fp32 (fp16 sits in
    between). The counters measure the ENCODED payload."""
    from paddle_tpu.obs import registry as _reg

    got = {}
    for tid, wire in ((1, "fp32"), (2, "fp16"), (3, "int8")):
        srvs, eps = _mk_cluster()
        try:
            cli = RpcPsClient(eps)
            cli.create_sparse_table(tid, TableConfig(
                table_id=tid, accessor_config=_acc(64), seed=5,
                push_wire_dtype=wire))
            keys = np.arange(1, 301, dtype=np.uint64)
            cli.pull_sparse(tid, keys)
            before = _push_bytes(cli, tid)
            _pushes(cli, tid, keys, 65, steps=4)
            got[wire] = _push_bytes(cli, tid) - before
            cli.close()
        finally:
            _stop(srvs)
    assert got["fp32"] >= 3.0 * got["int8"], got
    assert got["int8"] < got["fp16"] < got["fp32"], got
    _reg.REGISTRY.reset()


def test_server_dequant_matches_client_dequant_bitwise():
    """Cluster A pushes over the int8 wire; cluster B pushes the
    client-side DEQUANTIZED values over the fp32 wire. Final digests
    must be equal bit-for-bit — the server's decode multiplies the same
    int8 by the same f32 scale."""
    digs = []
    rng = np.random.default_rng(3)
    keys = rng.integers(1, 1 << 40, 200).astype(np.uint64)
    grads = [rng.normal(0, 0.2, (len(keys), 9)).astype(np.float32)
             for _ in range(3)]
    for mode in ("int8", "predequantized_fp32"):
        srvs, eps = _mk_cluster()
        try:
            cli = RpcPsClient(eps)
            cli.create_sparse_table(0, TableConfig(
                accessor_config=_acc(8), seed=9,
                push_wire_dtype="int8" if mode == "int8" else "fp32",
                push_error_feedback=False))
            cli.pull_sparse(0, keys)
            for g in grads:
                push = np.zeros((len(keys), 12), np.float32)
                push[:, 1] = 1.0
                if mode == "int8":
                    push[:, 3:] = g
                else:  # blk = min(push_wire_block=128, gd=9) client-side
                    q, sc = _quant_push_int8(g, 9)
                    push[:, 3:] = _dequant_push_int8(q, sc, 9)
                cli.push_sparse(0, keys, push)
            digs.append(sum(cli.digest(0)) & MASK)
            cli.close()
        finally:
            _stop(srvs)
    assert digs[0] == digs[1]


def test_error_feedback_survives_and_drains_at_quiesce():
    """int8 + EF: residuals accumulate per (table, key), quiesce()
    drains them over the fp32 wire (zero rows left — the checkpoint cut
    is digest-complete), and the final embedding weights land within a
    stated tolerance of the fp32-wire oracle."""
    results = {}
    for wire in ("fp32", "int8"):
        srvs, eps = _mk_cluster()
        try:
            cli = RpcPsClient(eps)
            comm = SyncCommunicator(cli)
            comm.start()
            cli.create_sparse_table(0, TableConfig(
                accessor_config=_acc(8), seed=11, push_wire_dtype=wire))
            keys = np.arange(1, 129, dtype=np.uint64)
            cli.pull_sparse(0, keys)
            rng = np.random.default_rng(1)
            for _ in range(20):
                push = np.zeros((len(keys), 12), np.float32)
                push[:, 1] = 1.0
                push[:, 3:] = rng.normal(0, 0.05,
                                         (len(keys), 9)).astype(np.float32)
                comm.send_sparse(0, keys, push)
            if wire == "int8":
                assert cli.push_residual_rows(0) == len(keys)
            comm.quiesce()  # drains queued pushes AND EF residuals
            assert cli.push_residual_rows() == 0
            k, v = cli.snapshot_items(0)
            order = np.argsort(k)
            results[wire] = v[order]
            comm.stop()
            cli.close()
        finally:
            _stop(srvs)
    a, b = results["fp32"], results["int8"]
    # stated tolerance: block-int8 with error feedback + terminal drain
    # tracks the fp32 wire to ~1e-3 absolute on these magnitudes
    emb = slice(5, 6)  # embed_w column
    np.testing.assert_allclose(b[:, 5], a[:, 5], atol=2e-3)
    np.testing.assert_allclose(b[:, 8:17], a[:, 8:17], atol=2e-3)
    assert not np.array_equal(b, a)  # quantization really happened


def test_merge_dedup_folds_one_residual_per_key():
    """Duplicate keys in one push merge BEFORE quantization — exactly
    one residual row per unique key."""
    srvs, eps = _mk_cluster(1)
    try:
        cli = RpcPsClient(eps)
        cli.create_sparse_table(0, TableConfig(
            accessor_config=_acc(8), seed=2, push_wire_dtype="int8"))
        keys = np.array([7, 7, 9, 9, 9, 11], np.uint64)
        cli.pull_sparse(0, keys)
        push = np.zeros((len(keys), 12), np.float32)
        push[:, 1] = 1.0
        push[:, 3:] = np.random.default_rng(0).normal(
            0, 0.1, (len(keys), 9)).astype(np.float32)
        cli.push_sparse(0, keys, push)
        assert cli.push_residual_rows(0) == 3  # unique keys only
        cli.close()
    finally:
        _stop(srvs)


def test_ef_store_overflow_drains_itself():
    """Past FLAGS_ps_push_ef_max_rows the whole table's residuals drain
    over the fp32 wire — client RAM stays bounded, signal is kept."""
    srvs, eps = _mk_cluster(1)
    try:
        cli = RpcPsClient(eps)
        cli.create_sparse_table(0, TableConfig(
            accessor_config=_acc(8), seed=2, push_wire_dtype="int8"))
        keys = np.arange(1, 65, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        pt.set_flags({"ps_push_ef_max_rows": 16})
        try:
            push = np.zeros((len(keys), 12), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = 0.01
            cli.push_sparse(0, keys, push)
            assert cli.push_residual_rows(0) == 0  # 64 > 16 → drained
        finally:
            pt.set_flags({"ps_push_ef_max_rows": 1 << 20})
        cli.close()
    finally:
        _stop(srvs)


def test_quantized_frames_replicate_bit_identically():
    """Sync replication with an int8 push wire: the backup replays the
    TAPPED quantized frames (same aux, same bytes) and converges
    bit-identically to the primary."""
    with ha.HACluster(num_shards=2, replication=2, sync=True) as c:
        cli = c.client()
        cli.create_sparse_table(0, TableConfig(
            table_id=0, shard_num=4, accessor_config=_acc(8),
            push_wire_dtype="int8"))
        keys = np.arange(1, 201, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        _pushes(cli, 0, keys, 9, steps=4, seed=4)
        cli.drain_push_residuals()
        c.drain()
        for shard in range(2):
            dg = c.digests(0, shard)
            assert len(set(dg.values())) == 1, dg


def test_malformed_quantized_frame_rejects_whole():
    """A quantized push whose payload length disagrees with its aux
    flags bounces kErrBadSize BEFORE any apply — and a quantized push
    to a gradient-less table (pd <= 3) is likewise refused."""
    srvs, eps = _mk_cluster(1)
    try:
        cli = RpcPsClient(eps)
        cli.create_sparse_table(0, TableConfig(
            accessor_config=_acc(8), seed=2))
        keys = np.arange(1, 9, dtype=np.uint64)
        cli.pull_sparse(0, keys)
        dig0 = cli.digest(0)
        conn = cli._conns[0]
        # int8 flags but an fp32-sized payload
        bad = np.zeros((len(keys), 12), np.float32)
        aux = _PUSH_WIRE_I8 | (128 << _PUSH_WIRE_BLOCK_SHIFT)
        status, _ = conn.call(_PUSH_SPARSE, 0, n=len(keys), aux=aux,
                              payload=(keys, bad))
        assert status == -3  # kErrBadSize
        # block size 0 is refused
        status, _ = conn.call(_PUSH_SPARSE, 0, n=len(keys),
                              aux=_PUSH_WIRE_I8, payload=(keys, bad))
        assert status == -3
        # hostile header: a huge n with a tiny payload must reject with
        # kErrBadSize BEFORE the decode scratch is sized from n (a
        # resize-first would throw and take the server down)
        status, _ = conn.call(_PUSH_SPARSE, 0, n=1 << 31, aux=aux,
                              payload=keys)
        assert status == -3
        assert cli.digest(0) == dig0  # nothing applied, server alive
        cli.close()
    finally:
        _stop(srvs)


def test_ragged_block_and_multi_block_rows():
    """Block sizes that do not divide the gradient width quantize and
    decode correctly (the last block of each row is ragged)."""
    for block in (4, 7, 9, 128):
        srvs, eps = _mk_cluster(1)
        try:
            cli = RpcPsClient(eps)
            cli.create_sparse_table(0, TableConfig(
                accessor_config=_acc(8), seed=2, push_wire_dtype="int8",
                push_wire_block=block, push_error_feedback=False))
            keys = np.arange(1, 33, dtype=np.uint64)
            cli.pull_sparse(0, keys)
            g = np.random.default_rng(block).normal(
                0, 0.1, (len(keys), 9)).astype(np.float32)
            push = np.zeros((len(keys), 12), np.float32)
            push[:, 1] = 1.0
            push[:, 3:] = g
            cli.push_sparse(0, keys, push)  # must not raise
            # server state equals an fp32 push of the dequantized grads
            blk = min(block, 9)
            q, sc = _quant_push_int8(g, blk)
            deq = _dequant_push_int8(q, sc, blk)
            # per-element error ≤ scale/2 = block_absmax/254; the global
            # absmax bounds every block's scale
            np.testing.assert_allclose(deq, g,
                                       atol=float(np.abs(g).max()) / 254
                                       * 1.01)
            cli.close()
        finally:
            _stop(srvs)
