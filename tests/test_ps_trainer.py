"""train_from_dataset parity: text files → InMemoryDataset → CtrPassTrainer
pass lifecycle (Executor::RunFromDataset → PSGPUTrainer/worker loop,
executor.cc:157, ps_gpu_worker.cc:121) — learns and flushes to the table.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer
from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
from paddle_tpu.models.ctr import CtrConfig, DeepFM
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.embedding_cache import CacheConfig
from paddle_tpu.ps.ps_trainer import CtrPassTrainer
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

S, D = 4, 3


def _lines(rng, n, vocab=64):
    """MultiSlot text: 4 sparse slots (1 id each), 3 dense, 1 label."""
    lines = []
    for _ in range(n):
        ids = rng.integers(0, vocab, S)
        dense = rng.normal(size=D)
        clicky = (ids % 5 == 0).sum()
        label = int(clicky + dense[0] + rng.normal(scale=0.5) > 1.0)
        parts = []
        for v in ids:
            parts.append(f"1 {v}")
        for v in dense:
            parts.append(f"1 {v:.4f}")
        parts.append(f"1 {label}")
        lines.append(" ".join(parts))
    return lines


def _slots():
    return ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
            + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
            + [SlotDesc("label", is_float=True, max_len=1)])


def test_train_from_dataset_learns_and_flushes(rng):
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 2048))
    ds.local_shuffle()

    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16, 16))
    cache_cfg = CacheConfig(capacity=1 << 10, embedx_dim=4,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table, cache_cfg,
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)],
        label_slot="label")

    first = tr.train_from_dataset(ds, batch_size=256)
    assert first["steps"] == 8 and first["samples"] == 2048
    assert np.isfinite(first["loss"]) and first["samples_per_sec"] > 0
    # features flushed back to the host table after end_pass
    assert table.size() > 0

    losses = [first["loss"]]
    for _ in range(4):
        losses.append(tr.train_from_dataset(ds, batch_size=256)["loss"])
    assert losses[-1] < losses[0] * 0.9, losses


def test_pass_lifecycle_reset_between_passes(rng):
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 512))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(8,))
    cache_cfg = CacheConfig(capacity=1 << 10, embedx_dim=4,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(DeepFM(cfg), optimizer.Adam(1e-2), table, cache_cfg,
                        sparse_slots=[f"s{i}" for i in range(S)],
                        dense_slots=[f"d{i}" for i in range(D)],
                        label_slot="label")
    tr.train_from_dataset(ds, batch_size=128)
    assert tr.cache.state is None  # end_pass released the working set
    tr.train_from_dataset(ds, batch_size=128)  # second pass rebuilds
    assert tr.cache.state is None


def test_executor_train_from_dataset(rng):
    """Dense-path Executor.train_from_dataset over an InMemoryDataset."""
    from paddle_tpu import nn
    from paddle_tpu.executor import Trainer

    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 1024))

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(D, 16)
            self.l2 = nn.Linear(16, 1)

        def forward(self, x):
            return self.l2(nn.functional.relu(self.l1(x)))[..., 0]

    def feed(batch):
        dense = np.concatenate([batch[f"d{i}"][0] for i in range(D)], axis=1)
        label = batch["label"][0][:, 0].astype(np.float32)
        return dense.astype(np.float32), label

    tr = Trainer(MLP(), optimizer.Adam(1e-2),
                 nn.functional.binary_cross_entropy_with_logits)
    losses = tr.train_from_dataset(ds, feed, batch_size=128, epochs=4)
    assert len(losses) == 4 and all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses


def test_evaluate_auc_improves(rng):
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 2048))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16, 16))
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    auc0 = tr.evaluate(ds)["auc"]  # untrained: ~0.5 (unseen → zeros)
    for _ in range(5):
        tr.train_from_dataset(ds, batch_size=256)
    auc1 = tr.evaluate(ds)["auc"]
    assert auc1 > max(auc0, 0.5) + 0.05, (auc0, auc1)


def test_save_load_resume(rng, tmp_path):
    """Pass-boundary checkpoint: table + dense snapshot round-trips and
    training resumes with an identical next-pass trajectory."""
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 512))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(8,))

    def fresh():
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
        return CtrPassTrainer(
            DeepFM(cfg), optimizer.Adam(1e-2), table,
            CacheConfig(capacity=1 << 10, embedx_dim=4,
                        embedx_threshold=0.0),
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label")

    pt.seed(0)
    a = fresh()
    a.train_from_dataset(ds, batch_size=128)
    a.save(str(tmp_path / "ck"))
    la = a.train_from_dataset(ds, batch_size=128)["loss"]

    pt.seed(0)
    b = fresh()
    b.load(str(tmp_path / "ck"))
    lb = b.train_from_dataset(ds, batch_size=128)["loss"]
    np.testing.assert_allclose(lb, la, rtol=1e-5)


def test_stream_trainer_sync_learns(rng):
    """CtrStreamTrainer (the_one_ps CPU-table worker loop): direct
    pull/push against the host table learns the synthetic signal."""
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 2048))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16, 16))
    table = MemorySparseTable(TableConfig(
        shard_num=4,
        accessor_config=AccessorConfig(embedx_dim=4, embedx_threshold=0.0)))
    tr = CtrStreamTrainer(DeepFM(cfg), optimizer.Adam(1e-2), table,
                          sparse_slots=[f"s{i}" for i in range(S)],
                          dense_slots=[f"d{i}" for i in range(D)],
                          label_slot="label")
    # 10 epochs: jax 0.4.37's numerics converge on a slightly slower
    # trajectory than the version the 5-epoch bound was tuned on
    # (0.482 vs the 0.473 cutoff at epoch 5; same steady descent)
    losses = [tr.train_from_dataset(ds, batch_size=256)["loss"]
              for _ in range(10)]
    assert losses[-1] < losses[0] * 0.8, losses
    assert table.size() > 0


def test_stream_trainer_async_communicator(rng):
    """Async push through the Communicator queue converges too (stale
    pushes tolerated — the a_sync mode semantics)."""
    from paddle_tpu.ps.client import LocalPsClient, PsServerHandle
    from paddle_tpu.ps.communicator import AsyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 2048))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16, 16))
    server = PsServerHandle()
    table = server.create_sparse_table(0, TableConfig(
        shard_num=4,
        accessor_config=AccessorConfig(embedx_dim=4, embedx_threshold=0.0)))
    comm = AsyncCommunicator(LocalPsClient(server))
    comm.start()
    try:
        tr = CtrStreamTrainer(DeepFM(cfg), optimizer.Adam(1e-2), table,
                              sparse_slots=[f"s{i}" for i in range(S)],
                              dense_slots=[f"d{i}" for i in range(D)],
                              label_slot="label", communicator=comm,
                              table_id=0)
        losses = [tr.train_from_dataset(ds, batch_size=256)["loss"]
                  for _ in range(5)]
    finally:
        comm.stop()
    assert losses[-1] < losses[0] * 0.85, losses


def test_stream_trainer_queue_dataset(rng, tmp_path):
    """Streaming source (QueueDataset) drives the worker loop — no
    pass-wide key scan needed."""
    from paddle_tpu.data.dataset import QueueDataset
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    pt.seed(0)
    path = tmp_path / "part-0.txt"
    path.write_text("\n".join(_lines(rng, 1024)))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16,))
    table = MemorySparseTable(TableConfig(
        shard_num=4,
        accessor_config=AccessorConfig(embedx_dim=4, embedx_threshold=0.0)))
    tr = CtrStreamTrainer(DeepFM(cfg), optimizer.Adam(1e-2), table,
                          sparse_slots=[f"s{i}" for i in range(S)],
                          dense_slots=[f"d{i}" for i in range(D)],
                          label_slot="label")
    losses = []
    for _ in range(3):
        qd = QueueDataset(_slots())
        qd.set_filelist([str(path)])
        losses.append(tr.train_from_dataset(qd, batch_size=128)["loss"])
    assert losses[-1] < losses[0], losses


def test_tail_batch_padded_not_recompiled(rng):
    """drop_last=False: the short tail batch pads to the fixed step shape
    (one compiled shape; padded rows excluded from loss/samples)."""
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 300))  # 300 = 2*128 + 44 tail
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(8,))
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    out = tr.train_from_dataset(ds, batch_size=128, drop_last=False)
    assert out["steps"] == 3
    assert out["samples"] == 300  # padding rows not counted
    assert np.isfinite(out["loss"])


def test_multi_day_lifecycle(rng, tmp_path):
    """Day simulation (A.3 lifecycle semantics at trainer level): train
    pass → daily shrink → delta save (mode 1) each day; base save
    (mode 0) at the end; reload continues training."""
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 1024))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(8,))
    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         delete_threshold=0.0,
                         delete_after_unseen_days=30.0)
    table = MemorySparseTable(TableConfig(shard_num=4, accessor_config=acc))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")

    for day in range(3):
        tr.train_from_dataset(ds, batch_size=256)
        deleted = table.shrink()           # daily decay (A.3)
        assert deleted >= 0
        n_delta = table.save(str(tmp_path / f"delta-{day}"), mode=1)
        assert n_delta >= 0
    n_before = table.size()
    assert n_before > 0
    tr.save(str(tmp_path / "base"), mode=0)

    # reload into a fresh trainer; continue training
    table2 = MemorySparseTable(TableConfig(shard_num=4, accessor_config=acc))
    pt.seed(0)
    tr2 = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table2,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    tr2.load(str(tmp_path / "base"))
    assert table2.size() == n_before
    out = tr2.train_from_dataset(ds, batch_size=256)
    assert np.isfinite(out["loss"])


def test_nan_guard_flags(rng):
    """FLAGS_check_nan_inf surfaces a diverged pass loudly."""
    import paddle_tpu as ptx
    from paddle_tpu.core.enforce import PreconditionNotMetError

    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 256))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(8,))
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(float("nan")), table,  # poison lr → NaN
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    ptx.set_flags({"check_nan_inf": True})
    try:
        import pytest as _pytest
        with _pytest.raises(PreconditionNotMetError):
            for _ in range(3):
                tr.train_from_dataset(ds, batch_size=128)
    finally:
        ptx.set_flags({"check_nan_inf": False})


def test_nan_guard_discards_pass(rng, tmp_path):
    """A diverged pass is dropped without flushing: the host table keeps
    the last-good state and remains checkpointable."""
    import paddle_tpu as ptx
    from paddle_tpu.core.enforce import PreconditionNotMetError

    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 256))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(8,))
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(float("nan")), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    ptx.set_flags({"check_nan_inf": True})
    try:
        import pytest as _pytest
        with _pytest.raises(PreconditionNotMetError):
            for _ in range(3):
                tr.train_from_dataset(ds, batch_size=128)
    finally:
        ptx.set_flags({"check_nan_inf": False})
    assert tr.cache.state is None          # pass discarded, HBM released
    tr.save(str(tmp_path / "ck"))          # still checkpointable
    # the flush was skipped: the host table's rows stay finite
    keys, _ = tr.cache.table.export_full(
        np.zeros(1, np.uint64))            # probe API stays functional
    table2 = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    table2.load(str(tmp_path / "ck") + "/sparse")
    vals, found = table2.export_full(np.zeros(1, np.uint64))
    assert np.isfinite(vals).all()


def test_ctr_serving_export(rng, tmp_path):
    """The PS serving split: exported dense graph (batch-polymorphic)
    scores (pulled emb, dense) identically to evaluate()'s infer."""
    from paddle_tpu.io.inference import load_inference_model

    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 512))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(8,))
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    tr.train_from_dataset(ds, batch_size=128)
    tr.save_inference_model(str(tmp_path / "serve"))

    pred = load_inference_model(str(tmp_path / "serve"))
    # serving: pull embeddings from the table, score with the artifact
    for B in (3, 17):  # batch-polymorphic
        keys = rng.integers(0, 64, size=(B, S)).astype(np.uint64)
        tagged = (keys + (np.arange(S, dtype=np.uint64) << np.uint64(32)))
        pulled = table.pull_sparse(tagged.reshape(-1), create=False)
        emb = pulled[:, -5:].reshape(B, S, 5).astype(np.float32)
        dense = rng.normal(size=(B, D)).astype(np.float32)
        probs = np.asarray(pred(emb, dense))
        assert probs.shape == (B,)
        # parity with the in-framework inference on identical inputs
        from paddle_tpu import nn as _nn
        import jax.numpy as _jnp
        out, _ = _nn.functional_call(tr.model, tr.params, _jnp.asarray(emb),
                                     _jnp.asarray(dense), training=False)
        want = np.asarray(1.0 / (1.0 + np.exp(-np.asarray(out))))
        np.testing.assert_allclose(probs, want, rtol=1e-5, atol=1e-6)


def test_evaluate_wuauc(rng):
    """user_slot adds the user-weighted AUC (WuaucCalculator role)."""
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 1024))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16,))
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
    for _ in range(3):
        tr.train_from_dataset(ds, batch_size=256)
    out = tr.evaluate(ds, user_slot="s0")  # slot 0 doubles as the uid
    assert 0.0 <= out["wuauc"] <= 1.0
    assert out["wuauc"] > 0.5  # learned signal ranks within users too


def test_train_passes_overlapped_matches_sequential(rng):
    """train_passes (background next-pass prepare, the pre_build_thread
    pattern) must produce bit-identical table state to sequential
    train_from_dataset calls over the same day stream."""
    def build():
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(
                embedx_dim=4, embedx_threshold=0.0)))
        tr = CtrPassTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                             dnn_hidden=(8,))),
            optimizer.Adam(1e-2), table,
            CacheConfig(capacity=1 << 10, embedx_dim=4,
                        embedx_threshold=0.0),
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
        return table, tr

    days = []
    for day in range(3):
        day_rng = np.random.default_rng(100 + day)
        ds = InMemoryDataset(_slots(), seed=day)
        ds.load_from_lines(_lines(day_rng, 384, vocab=48))
        days.append(ds)

    t1, tr1 = build()
    r1 = tr1.train_passes(days, batch_size=128)
    t2, tr2 = build()
    r2 = [tr2.train_from_dataset(d, batch_size=128) for d in days]

    for a, b in zip(r1, r2):
        np.testing.assert_allclose(a["loss"], b["loss"], rtol=1e-6)
    probe = np.arange(0, 5000, dtype=np.uint64)
    np.testing.assert_array_equal(t1.pull_sparse(probe, create=False),
                                  t2.pull_sparse(probe, create=False))
    assert len(r1) == 3


def test_auto_checkpoint_resumes_day_stream(tmp_path, rng):
    """Compose auto-checkpoint's resumable epoch range with the pass
    trainer's day loop: a 'crashed' job restarted over the same
    checkpoint dir skips finished days and ends bit-identical to an
    uninterrupted run (acp TrainEpochRange + fleet.save_persistables
    composition — the reference's elastic-restart story)."""
    import os

    from paddle_tpu.io.auto_checkpoint import TrainEpochRange

    n_days = 4

    def make_days():
        days = []
        for day in range(n_days):
            day_rng = np.random.default_rng(500 + day)
            ds = InMemoryDataset(_slots(), seed=day)
            ds.load_from_lines(_lines(day_rng, 256, vocab=48))
            days.append(ds)
        return days

    def build():
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(
                embedx_dim=4, embedx_threshold=0.0)))
        tr = CtrPassTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                             dnn_hidden=(8,))),
            optimizer.Adam(1e-2), table,
            CacheConfig(capacity=1 << 10, embedx_dim=4,
                        embedx_threshold=0.0),
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label")
        return table, tr

    def run(ckpt_dir, crash_after=None):
        table, tr = build()
        days = make_days()
        r = TrainEpochRange(n_days, "daily", checkpoint_dir=ckpt_dir)
        r.set_state_getter(lambda: None)  # table/dense saved via tr.save
        done = []

        def setter(_):
            tr.load(os.path.join(ckpt_dir, "model"))

        r.set_state_setter(setter)
        for day in r:
            tr.train_from_dataset(days[day], batch_size=128)
            tr.save(os.path.join(ckpt_dir, "model"))
            # record the acp position at the SAME point the model is
            # persisted (the explicit mid-loop save) — a crash between
            # the two would otherwise re-train an already-applied day
            r.save(day)
            done.append(day)
            if crash_after is not None and day == crash_after:
                return table, done  # simulated preemption
        return table, done

    # uninterrupted reference
    t_ref, days_ref = run(str(tmp_path / "ref"))
    assert days_ref == [0, 1, 2, 3]

    # crash after day 1, restart over the same checkpoint dir
    t1, done1 = run(str(tmp_path / "acp"), crash_after=1)
    assert done1 == [0, 1]
    t2, done2 = run(str(tmp_path / "acp"))
    assert done2 == [2, 3]  # finished days skipped

    probe = np.arange(0, 4000, dtype=np.uint64)
    # near-exact: the resumed run's table passed through the text
    # checkpoint's %.8g round-trip once, the reference's never did
    np.testing.assert_allclose(
        t2.pull_sparse(probe, create=False),
        t_ref.pull_sparse(probe, create=False), rtol=1e-6, atol=1e-8)


def test_slab_pass_matches_single_step_pass():
    """CtrPassTrainer with slab>1 (scan-dispatched groups) walks a
    bitwise-identical trajectory to slab=1, including a tail that
    doesn't fill the last slab."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.embedding_cache import CacheConfig
    from paddle_tpu.ps.ps_trainer import CtrPassTrainer
    from paddle_tpu.ps.table import MemorySparseTable, TableConfig

    S, D = 4, 3
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1) for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1) for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(330):  # 330 rows / batch 32 → 10 full + tail of 10
        parts = [f"1 {rng.integers(1, 64)}" for _ in range(S)]
        parts += [f"1 {rng.normal():.4f}" for _ in range(D)]
        parts.append(f"1 {rng.integers(0, 2)}")
        lines.append(" ".join(parts))

    def run(slab):
        pt.seed(0)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
        tr = CtrPassTrainer(
            DeepFM(CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                             dnn_hidden=(16,))),
            optimizer.Adam(1e-2), table,
            CacheConfig(capacity=1 << 12, embedx_dim=4,
                        embedx_threshold=0.0),
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
            slab=slab)
        ds = InMemoryDataset(slots, seed=1)
        ds.load_from_lines(lines)
        out = tr.train_from_dataset(ds, batch_size=32, drop_last=False)
        keys = np.unique(tr._tagged_pass_keys(ds))
        vals, found = table.export_full(keys)
        assert found.all()
        return out, vals

    out1, vals1 = run(slab=1)
    out4, vals4 = run(slab=4)
    assert out1["steps"] == out4["steps"] == 11
    assert out1["samples"] == out4["samples"] == 330
    np.testing.assert_allclose(out4["loss"], out1["loss"], rtol=1e-6)
    np.testing.assert_array_equal(vals4, vals1)


@pytest.mark.slow
def test_dense_push_trajectory_matches_sparse(rng):
    """Chained-trajectory parity of the TPU hot path: the SAME pass
    trainer run with push_mode="dense" vs "sparse" over multiple passes
    stays numerically together (per-step parity is exact to f32
    reassociation; this pins that the drift doesn't compound over
    hundreds of steps of feedback through the cache)."""
    results = {}
    for mode in ("sparse", "dense"):
        pt.seed(0)
        ds = InMemoryDataset(_slots(), seed=0)
        r = np.random.default_rng(7)
        lines = []
        for _ in range(2048):
            ids = r.integers(0, 64, S)
            dense = r.normal(size=D)
            label = int((ids % 5 == 0).sum() + dense[0]
                        + r.normal(scale=0.5) > 1.0)
            parts = [f"1 {v}" for v in ids] + \
                    [f"1 {v:.4f}" for v in dense] + [f"1 {label}"]
            lines.append(" ".join(parts))
        ds.load_from_lines(lines)

        cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                        dnn_hidden=(16, 16))
        cache_cfg = CacheConfig(capacity=1 << 10, embedx_dim=4,
                                embedx_threshold=0.0, push_mode=mode)
        table = MemorySparseTable(TableConfig(
            shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
        tr = CtrPassTrainer(
            DeepFM(cfg), optimizer.Adam(1e-2), table, cache_cfg,
            sparse_slots=[f"s{i}" for i in range(S)],
            dense_slots=[f"d{i}" for i in range(D)],
            label_slot="label")
        losses = [tr.train_from_dataset(ds, batch_size=256)["loss"]
                  for _ in range(5)]  # 5 passes x 8 steps, cache feedback
        auc = tr.evaluate(ds, batch_size=256)["auc"]
        results[mode] = (np.asarray(losses), auc, table)

    l_s, auc_s, t_s = results["sparse"]
    l_d, auc_d, t_d = results["dense"]
    np.testing.assert_allclose(l_d, l_s, rtol=2e-3, atol=2e-4)
    assert abs(auc_d - auc_s) < 2e-3, (auc_s, auc_d)
    # flushed host tables agree row-for-row over the dataset's feasigns
    assert t_s.size() == t_d.size()
    sample = (np.arange(64, dtype=np.uint64)
              + (np.uint64(0) << np.uint64(32)))  # slot-0 vocabulary
    v_s, f_s = t_s.export_full(sample)
    v_d, f_d = t_d.export_full(sample)
    np.testing.assert_array_equal(f_d, f_s)
    assert f_s.sum() > 32  # the sample really hits trained rows
    np.testing.assert_allclose(v_d[f_d], v_s[f_s], rtol=2e-3, atol=2e-4)


def test_pass_trainer_save_inference_model(tmp_path, rng):
    """Trainer-level deploy: train passes, flush, then export the
    serving program over a chosen key universe; the loaded predictor
    scores with the TRAINED params (donation-safe) and table values."""
    import jax

    from paddle_tpu.io.inference import load_inference_model

    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 1024))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16,))
    cache_cfg = CacheConfig(capacity=1 << 10, embedx_dim=4,
                            embedx_threshold=0.0)
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(DeepFM(cfg), optimizer.Adam(1e-2), table, cache_cfg,
                        sparse_slots=[f"s{i}" for i in range(S)],
                        dense_slots=[f"d{i}" for i in range(D)],
                        label_slot="label")
    tr.train_from_dataset(ds, batch_size=256)  # ends with end_pass

    # serving universe: slot-tagged vocab 0..63 per slot
    vocab = np.arange(64, dtype=np.uint64)
    keys = np.concatenate([
        vocab + (np.uint64(si) << np.uint64(32)) for si in range(S)])
    tr.save_inference_model(str(tmp_path / "serve"), fused=True, keys=keys)
    pred = load_inference_model(str(tmp_path / "serve"))

    import jax.numpy as jnp

    lo32 = rng.integers(0, 64, size=(8, S)).astype(np.uint32)
    dense = rng.normal(size=(8, D)).astype(np.float32)
    p = np.asarray(pred(jnp.asarray(lo32), jnp.asarray(dense)))
    assert p.shape == (8,) and ((p > 0) & (p < 1)).all()

    # the export really carries the TRAINED dense params
    from paddle_tpu.io.checkpoint import load_checkpoint
    saved = load_checkpoint(str(tmp_path / "serve" / "params"))["model"]
    for k, v in tr.params["params"].items():
        np.testing.assert_array_equal(np.asarray(saved["model"]["params"][k]),
                                      np.asarray(v), err_msg=k)


def test_pass_trainer_amp_trains(rng):
    """CtrPassTrainer(amp=True): bf16 contractions are in the compiled
    step (precision is a step property, not a call-site context) and
    training still learns."""
    pt.seed(0)
    ds = InMemoryDataset(_slots(), seed=0)
    ds.load_from_lines(_lines(rng, 1024))
    cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                    dnn_hidden=(16,))
    table = MemorySparseTable(TableConfig(
        shard_num=4, accessor_config=AccessorConfig(embedx_dim=4)))
    tr = CtrPassTrainer(
        DeepFM(cfg), optimizer.Adam(1e-2), table,
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        sparse_slots=[f"s{i}" for i in range(S)],
        dense_slots=[f"d{i}" for i in range(D)], label_slot="label",
        amp=True)
    losses = [tr.train_from_dataset(ds, batch_size=256)["loss"]
              for _ in range(4)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses

    from paddle_tpu.models.ctr import make_ctr_train_step_packed
    step = make_ctr_train_step_packed(
        DeepFM(cfg), optimizer.Adam(1e-2),
        CacheConfig(capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        slot_ids=np.arange(S), batch_size=8, num_dense=D, donate=False,
        amp=True)
    # bf16 must be IN the lowered program regardless of call site
    import jax
    from paddle_tpu.models.ctr import make_random_packs
    from paddle_tpu.ps.embedding_cache import HbmEmbeddingCache

    cache = HbmEmbeddingCache(table, CacheConfig(
        capacity=1 << 10, embedx_dim=4, embedx_threshold=0.0),
        device_map=True)
    pool = np.arange(64, dtype=np.uint64).reshape(-1, 1) + \
        (np.arange(S, dtype=np.uint64) << np.uint64(32))[None, :]
    cache.begin_pass(pool.reshape(-1))
    m = DeepFM(cfg)
    params = {"params": dict(m.named_parameters()), "buffers": {}}
    opt_state = optimizer.Adam(1e-2).init(params)
    packs = make_random_packs(np.random.default_rng(0), pool, 8, D, 1)
    import jax.numpy as jnp
    txt = step.lower(params, opt_state, cache.state,
                     cache.device_map.state,
                     jnp.asarray(packs[0])).as_text()
    assert "bf16" in txt
