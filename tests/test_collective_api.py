"""Process-level collective API tests (reference
python/paddle/fluid/tests/unittests/test_collective_api_base.py and
test_tcp_store.py patterns — single-process paths here; the
multi-process path is exercised by the launcher integration)."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed import (
    Group,
    ParallelEnv,
    TCPStore,
    all_gather,
    all_reduce,
    alltoall,
    barrier,
    broadcast,
    get_rank,
    get_world_size,
    new_group,
    scatter,
)


class TestTCPStore:
    def test_set_get(self):
        store = TCPStore(is_master=True)
        try:
            store.set("k", "v")
            assert store.get("k") == "v"
            assert store.get("missing") is None
        finally:
            store.close()

    def test_add_atomic_across_clients(self):
        master = TCPStore(is_master=True)
        clients = [TCPStore(port=master.port) for _ in range(4)]
        try:
            def bump(c):
                for _ in range(50):
                    c.add("ctr", 1)

            threads = [threading.Thread(target=bump, args=(c,)) for c in clients]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert master.get("ctr") == "200"
        finally:
            for c in clients:
                c.close()
            master.close()

    def test_wait_blocks_until_set(self):
        master = TCPStore(is_master=True)
        client = TCPStore(port=master.port)
        try:
            done = []

            def waiter():
                client.wait(["flag"], timeout=10.0)
                done.append(True)

            t = threading.Thread(target=waiter)
            t.start()
            assert not done
            master.set("flag", "1")
            t.join(timeout=10.0)
            assert done
        finally:
            client.close()
            master.close()

    def test_wait_timeout(self):
        store = TCPStore(is_master=True)
        try:
            with pytest.raises(Exception):
                store.wait(["never"], timeout=0.3)
        finally:
            store.close()

    def test_barrier_reusable_name(self):
        """A barrier name reused across rounds must re-synchronize each
        round (per-round generation keys)."""
        master = TCPStore(is_master=True)
        c2 = TCPStore(port=master.port)
        try:
            order = []

            def late_second_round(store, tag, delay):
                store.barrier("epoch", 2, timeout=10.0)
                time.sleep(delay)
                store.barrier("epoch", 2, timeout=10.0)
                order.append(tag)

            t1 = threading.Thread(target=late_second_round,
                                  args=(master, "fast", 0.0))
            t2 = threading.Thread(target=late_second_round,
                                  args=(c2, "slow", 0.4))
            t1.start(); t2.start()
            t1.join(10); t2.join(10)
            assert sorted(order) == ["fast", "slow"]
        finally:
            c2.close()
            master.close()

    def test_barrier(self):
        master = TCPStore(is_master=True)
        c2 = TCPStore(port=master.port)
        try:
            results = []

            def enter(store, name):
                store.barrier("b0", 2, timeout=10.0)
                results.append(name)

            t1 = threading.Thread(target=enter, args=(master, "a"))
            t2 = threading.Thread(target=enter, args=(c2, "b"))
            t1.start(); t2.start()
            t1.join(10); t2.join(10)
            assert sorted(results) == ["a", "b"]
        finally:
            c2.close()
            master.close()


class TestParallelEnv:
    def test_defaults(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
        monkeypatch.delenv("RANK", raising=False)
        env = ParallelEnv()
        assert env.rank == 0 and env.world_size == 1

    def test_paddle_env_vars(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "4")
        monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS",
                           "h0:1,h1:1,h2:1,h3:1")
        env = ParallelEnv()
        assert env.rank == 2 and env.world_size == 4
        assert env.current_endpoint == "h2:1"
        assert env.nranks == 4


class TestGroups:
    def test_new_group(self):
        g = new_group([0])
        assert g.nranks == 1 and 0 in g
        assert g.get_group_rank(0) == 0
        assert g.get_group_rank(5) == -1

    def test_group_ids_unique(self):
        assert new_group([0]).id != new_group([0]).id


class TestEagerCollectivesSingleProcess:
    def test_all_reduce(self):
        out = all_reduce(np.asarray([1.0, 2.0]), op="sum")
        np.testing.assert_allclose(out, [1.0, 2.0])

    def test_all_reduce_ops(self):
        for op in ("sum", "avg", "max", "min", "prod"):
            out = all_reduce(np.asarray([2.0]), op=op)
            np.testing.assert_allclose(out, [2.0])

    def test_all_gather(self):
        outs = all_gather(np.asarray([3]))
        assert len(outs) == 1
        np.testing.assert_array_equal(outs[0], [3])

    def test_broadcast_scatter_alltoall_barrier(self):
        np.testing.assert_array_equal(broadcast(np.asarray([5])), [5])
        np.testing.assert_array_equal(scatter([np.asarray([7])]), [7])
        outs = alltoall([np.asarray([9])])
        np.testing.assert_array_equal(outs[0], [9])
        barrier()  # no-op single process

    def test_rank_world(self):
        assert get_rank() == 0
        assert get_world_size() >= 1
