import numpy as np
import pytest

from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.parallel import CommunicateTopology, HybridCommunicateGroup


def test_rank_coord_roundtrip():
    topo = CommunicateTopology(["dp", "pp", "mp"], [2, 2, 2])
    assert topo.world_size() == 8
    for r in range(8):
        coord = topo.get_coord(r)
        assert topo.get_rank(**coord) == r


def test_comm_lists():
    topo = CommunicateTopology(["dp", "mp"], [2, 4])
    mp_groups = topo.get_comm_list("mp")
    assert len(mp_groups) == 2 and all(len(g) == 4 for g in mp_groups)
    dp_groups = topo.get_comm_list("dp")
    assert len(dp_groups) == 4 and all(len(g) == 2 for g in dp_groups)
    # groups partition the world
    assert sorted(sum(mp_groups, [])) == list(range(8))


def test_axis_list():
    topo = CommunicateTopology(["dp", "mp"], [2, 4])
    assert topo.get_axis_list("dp", 0) == [0, 1, 2, 3]
    assert topo.get_axis_list("dp", 1) == [4, 5, 6, 7]


def test_hybrid_group_queries():
    topo = CommunicateTopology(["dp", "sharding", "pp", "mp"], [2, 1, 2, 2])
    hcg = HybridCommunicateGroup(topo, global_rank=5)  # coords dp=1,sh=0,pp=0,mp=1
    assert hcg.get_data_parallel_rank() == 1
    assert hcg.get_model_parallel_rank() == 1
    assert hcg.get_stage_id() == 0
    assert hcg.is_first_stage() and not hcg.is_last_stage()
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_data_parallel_group() == [1, 5]


def test_from_mesh():
    m = mesh_mod.make_hybrid_mesh(dp=2, mp=4)
    topo = CommunicateTopology.from_mesh(m)
    assert topo.world_size() == 8
    assert topo.get_dim("mp") == 4
