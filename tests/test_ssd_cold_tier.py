"""Trillion-feature cold tier: Bloom-gated admission, compact key
index, block-compressed values, io-budgeted background compaction.

Covers the four cost attacks of the cold-tier scale work end to end
through the Python table layer:

* admission — a key earns an embedding row only after the configured
  number of push observations; unadmitted reads serve the deterministic
  init row (byte-equal to what create would have made), and the sketch
  decays with the lifecycle shrink;
* index — measured bytes/row of the open-addressing compact index stays
  under the 16 B/row target (vs ~44.7 for the hash-map baseline);
* storage — fp16 + block-compressed value logs round-trip digest-exact
  through write → shrink → compact → checkpoint → restore → replay;
* io-budget isolation — the background compactor is digest-invariant
  under churn, and a SIGKILL landing mid-copy (armed via
  ps/faultpoints.py at the ``ssd.compact`` site) never loses durable
  rows: the orphan ``.compact`` temp is ignored on recovery.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.native import native_available
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import SsdSparseTable, TableConfig

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native library unavailable")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _acc(**kw):
    kw.setdefault("sgd", SGDRuleConfig(initial_range=0.0))
    kw.setdefault("embedx_dim", 4)
    kw.setdefault("embedx_threshold", 0.0)
    return AccessorConfig(**kw)


def _cfg(**kw):
    kw.setdefault("shard_num", 4)
    kw.setdefault("storage", "ssd")
    kw.setdefault("accessor_config", _acc())
    return TableConfig(**kw)


def _grad(table, keys, seed=0):
    rng = np.random.default_rng(seed)
    push = np.zeros((len(keys), table.accessor.push_dim), np.float32)
    push[:, 0] = (keys % 8).astype(np.float32)
    push[:, 1] = 1.0
    push[:, 3:] = rng.normal(size=(len(keys), push.shape[1] - 3)) \
        .astype(np.float32)
    return push


def _fill_cold(table, n=400, seed=0, scale=1.0):
    """Cold-tier population with realistic sparsity (zero opt state,
    nonzero show + embedding) so block compression has signal."""
    rng = np.random.default_rng(seed)
    keys = np.unique(rng.integers(1, 1 << 40, n).astype(np.uint64))
    vals = np.zeros((len(keys), table.full_dim), np.float32)
    vals[:, 3] = 1.0                                      # show
    vals[:, 5] = scale * rng.normal(size=len(keys)).astype(np.float32)
    table.import_full(keys, vals)
    return keys, vals


# ---------------------------------------------------------------------------
# admission (counting-Bloom pre-filter)
# ---------------------------------------------------------------------------

def test_admission_gate_defers_row_creation(tmp_path):
    """threshold=2: pulls never admit, the first push only bumps the
    sketch (gradient dropped), the second push creates the row and
    applies its gradient — byte-equal to one push on an ungated table."""
    gated = SsdSparseTable(str(tmp_path / "g"),
                           _cfg(ssd_admission_threshold=2))
    plain = SsdSparseTable(str(tmp_path / "p"), _cfg())
    keys = np.arange(1, 201, dtype=np.uint64)

    gated.pull_sparse(keys, create=True)
    assert gated.size() == 0, "pull admitted rows below threshold"

    g1 = _grad(gated, keys, seed=1)
    gated.push_sparse(keys, g1)
    assert gated.size() == 0, "first push admitted below threshold"

    g2 = _grad(gated, keys, seed=2)
    gated.push_sparse(keys, g2)
    assert gated.size() == len(keys)
    # the admitting push applies ITS gradient (the sub-threshold one
    # was dropped): mirror = a single push on the ungated table
    plain.push_sparse(keys, g2)
    np.testing.assert_array_equal(
        gated.pull_sparse(keys, create=False),
        plain.pull_sparse(keys, create=False))


def test_unadmitted_pull_serves_init_rows(tmp_path):
    """Below-threshold pulls return the deterministic init row — the
    exact bytes create would have produced — so training code can't
    tell a gated key from a fresh one."""
    acc = _acc(sgd=SGDRuleConfig(initial_range=0.1))
    gated = SsdSparseTable(str(tmp_path / "g"),
                           _cfg(accessor_config=acc,
                                ssd_admission_threshold=3))
    plain = SsdSparseTable(str(tmp_path / "p"), _cfg(accessor_config=acc))
    keys = np.arange(1, 301, dtype=np.uint64)
    np.testing.assert_array_equal(gated.pull_sparse(keys, create=True),
                                  plain.pull_sparse(keys, create=True))
    assert gated.size() == 0 and plain.size() == len(keys)


def test_admission_sketch_decays_with_shrink(tmp_path):
    """shrink() halves every sketch counter: stale near-admissions age
    out instead of accumulating forever."""
    t = SsdSparseTable(str(tmp_path / "t"), _cfg(ssd_admission_threshold=2))
    keys = np.arange(1, 101, dtype=np.uint64)
    t.push_sparse(keys, _grad(t, keys))    # count 1
    t.shrink()                             # decay: 1 -> 0
    t.push_sparse(keys, _grad(t, keys))    # count 1 again — not 2
    assert t.size() == 0, "decayed sketch still admitted"
    t.push_sparse(keys, _grad(t, keys))    # count 2 -> admit
    assert t.size() == len(keys)


def test_admission_stats_and_table_config_threshold(tmp_path):
    """The stat vector tells the admission story: checks = gated push
    observations, rejects + admitted partition them."""
    t = SsdSparseTable(str(tmp_path / "t"), _cfg(ssd_admission_threshold=2))
    keys = np.arange(1, 151, dtype=np.uint64)
    t.push_sparse(keys, _grad(t, keys))
    t.push_sparse(keys, _grad(t, keys))
    st = t.stats()
    assert st["admit_checks"] >= 2 * len(keys)
    assert st["admit_admitted"] == len(keys)
    assert st["admit_rejects"] >= len(keys)
    assert st["sketch_bytes"] > 0


def test_accessor_admission_threshold_default(tmp_path):
    """AccessorConfig.admission_threshold flows through when the table
    knob is unset (TableConfig.ssd_admission_threshold overrides)."""
    t = SsdSparseTable(
        str(tmp_path / "t"),
        _cfg(accessor_config=_acc(admission_threshold=2)))
    keys = np.arange(1, 51, dtype=np.uint64)
    t.push_sparse(keys, _grad(t, keys))
    assert t.size() == 0
    t.push_sparse(keys, _grad(t, keys))
    assert t.size() == len(keys)


# ---------------------------------------------------------------------------
# compact index
# ---------------------------------------------------------------------------

def test_index_bytes_per_row_within_target(tmp_path):
    """The acceptance bound: measured index bytes/row <= 16 (6-byte
    slots at <= 75% occupancy + power-of-two growth headroom)."""
    t = SsdSparseTable(str(tmp_path / "t"), _cfg())
    _fill_cold(t, n=60_000, seed=0)
    t.spill(0)
    st = t.stats()
    assert st["cold_rows"] > 50_000
    assert st["index_bytes"] > 0
    assert st["index_bytes_per_row"] <= 16.0, st["index_bytes_per_row"]


# ---------------------------------------------------------------------------
# block-compressed fp16 value files
# ---------------------------------------------------------------------------

def _comp_cfg(**kw):
    kw.setdefault("ssd_value_dtype", "fp16")
    kw.setdefault("ssd_block_compress", True)
    # shrink in these lifecycle tests must age rows, not delete them
    kw.setdefault("accessor_config", _acc(delete_threshold=0.0))
    return _cfg(**kw)


def test_block_compress_roundtrip_digest_exact(tmp_path):
    """The full lifecycle on the compressed format: write → spill →
    shrink → compact → crash-replay (reopen) → checkpoint → restore,
    digest-exact at every hop."""
    path = str(tmp_path / "a")
    t = SsdSparseTable(path, _comp_cfg())
    keys, _ = _fill_cold(t, n=3000, seed=1)
    t.spill(0)
    t.shrink()           # ages + rewrites every live cold row
    assert t.size() == len(keys), "shrink deleted rows it should age"
    dg = t.digest()
    want = t.pull_sparse(keys[:64], create=False)
    t.spill(0)           # re-spill what the pull promoted
    assert t.digest() == dg

    t.compact()
    assert t.digest() == dg

    t.flush()
    t.close()            # no clean-shutdown protocol: reopen = replay
    t2 = SsdSparseTable(path, _comp_cfg())
    assert t2.digest() == dg
    np.testing.assert_array_equal(
        t2.pull_sparse(keys[:64], create=False), want)

    n = t2.save_file(str(tmp_path / "ck.raw"), fmt="raw")
    assert n == len(keys)
    t3 = SsdSparseTable(str(tmp_path / "b"), _comp_cfg())
    assert t3.load_file(str(tmp_path / "ck.raw"), fmt="raw") == n
    assert t3.digest() == dg
    t2.close(); t3.close()


def test_block_compress_shrinks_disk_bytes(tmp_path):
    """The point of the format: sparse CTR rows (zero opt state) pack
    materially smaller than the raw fp16 log."""
    sizes = {}
    for name, cfg in (("raw", _cfg(ssd_value_dtype="fp16")),
                      ("comp", _comp_cfg())):
        t = SsdSparseTable(str(tmp_path / name), cfg)
        _fill_cold(t, n=4000, seed=2)
        t.spill(0)
        t.flush()
        sizes[name] = t.stats()["disk_bytes"]
        t.close()
    assert sizes["comp"] < 0.7 * sizes["raw"], sizes


def test_block_compress_torn_tail_recovers_prefix(tmp_path):
    """A crash can tear the last block write: replay must keep every
    sealed block before the tear and drop the torn tail, not refuse
    the file."""
    path = str(tmp_path / "t")
    t = SsdSparseTable(path, _comp_cfg(shard_num=1))
    keys, _ = _fill_cold(t, n=2000, seed=3)
    t.spill(0)
    t.flush()
    t.close()
    shard = glob.glob(os.path.join(path, "*"))
    shard = [f for f in shard if not f.endswith(".compact")]
    assert len(shard) == 1
    size = os.path.getsize(shard[0])
    with open(shard[0], "r+b") as f:   # tear mid-block
        f.truncate(size - 37)
    t2 = SsdSparseTable(path, _comp_cfg(shard_num=1))
    st = t2.stats()
    # sealed prefix survives (128-record blocks: at most one block lost)
    assert 0 < st["cold_rows"] >= len(keys) - 128
    got = t2.pull_sparse(keys, create=False)
    assert np.isfinite(got).all()
    t2.close()


# ---------------------------------------------------------------------------
# background compaction + io budget
# ---------------------------------------------------------------------------

def test_bg_compaction_digest_invariant_under_churn(tmp_path):
    """TableConfig.ssd_bg_compact=True moves compaction off the push
    path; content digests must be invariant through the churn it
    absorbs, and the backlog must drain."""
    t = SsdSparseTable(str(tmp_path / "t"),
                       _cfg(ssd_bg_compact=True, ssd_io_budget_mbps=64.0))
    keys, _ = _fill_cold(t, n=2000, seed=4)
    t.spill(0)
    dg = t.digest()
    for _ in range(4):                   # content-invariant churn
        t.pull_sparse(keys, create=False)
        t.spill(0)
    t.compact_async()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        st = t.stats()
        if st["bg_compactions"] > 0 and st["bg_backlog"] == 0:
            break
        time.sleep(0.05)
    st = t.stats()
    assert st["bg_compactions"] > 0, "background worker never compacted"
    assert st["bg_backlog"] == 0, "forced compaction backlog never drained"
    assert t.digest() == dg
    t.close()


def test_io_budget_meters_background_bytes(tmp_path):
    """With a starved budget the worker pays wall-clock for its bytes:
    bg_wait_ms becomes visible in the stat vector."""
    t = SsdSparseTable(str(tmp_path / "t"), _cfg(shard_num=2))
    keys, _ = _fill_cold(t, n=4000, seed=5)
    t.spill(0)
    for _ in range(3):
        t.pull_sparse(keys, create=False)
        t.spill(0)
    t._native.io_budget(256 * 1024, 64 * 1024)   # 256 KB/s, 64 KB bucket
    t._native.bg_start(20)
    t.compact_async()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        st = t.stats()
        if st["bg_compactions"] >= 2 and st["bg_backlog"] == 0:
            break
        time.sleep(0.05)
    st = t.stats()
    assert st["bg_compactions"] >= 2
    assert st["io_bg_bytes"] > 0
    assert st["io_bg_wait_ms"] > 0, "starved budget never made the bg wait"
    assert t.digest() is not None
    t.close()


_CRASH_CHILD = textwrap.dedent("""
    import os, sys, time
    import numpy as np
    sys.path.insert(0, {repo!r})
    from paddle_tpu.ps.accessor import AccessorConfig
    from paddle_tpu.ps.faultpoints import arm_faultpoint
    from paddle_tpu.ps.sgd_rule import SGDRuleConfig
    from paddle_tpu.ps.table import SsdSparseTable, TableConfig

    path = sys.argv[1]
    cfg = TableConfig(
        shard_num=2, storage="ssd", ssd_value_dtype="fp16",
        ssd_block_compress=True,
        accessor_config=AccessorConfig(
            sgd=SGDRuleConfig(initial_range=0.0), embedx_dim=4,
            embedx_threshold=0.0))
    t = SsdSparseTable(path, cfg)
    rng = np.random.default_rng(0)
    keys = np.arange(1, 20001, dtype=np.uint64)
    vals = np.zeros((len(keys), t.full_dim), np.float32)
    vals[:, 3] = 1.0
    vals[:, 5] = rng.normal(size=len(keys)).astype(np.float32)
    t.import_full(keys, vals)
    t.spill(0)
    # content-invariant churn so the logs carry garbage worth compacting
    for _ in range(2):
        t.pull_sparse(keys, create=False)
        t.spill(0)
    t.flush()
    print("DIGEST", t.digest(), flush=True)
    # starved budget: shard 0's copy passes on the full bucket, shard 1
    # parks in acquire_bg for ~10s with its .compact already created
    t._native.io_budget(64 * 1024, 64 * 1024)
    t._native.bg_start(20)
    t.compact_async()
    time.sleep(1.0)
    arm_faultpoint("ssd.compact", "kill-job")
    t.compact_async()      # the armed site SIGKILLs the process
    print("SURVIVED", flush=True)
    sys.exit(3)
""")


def test_crash_mid_compaction_preserves_durable_rows(tmp_path):
    """SIGKILL with the background sweep mid-copy (ps/faultpoints.py
    ``ssd.compact`` site): recovery replays the durable log, ignores
    the orphan ``.compact`` temp, and the digest is exact."""
    path = str(tmp_path / "t")
    proc = subprocess.run(
        [sys.executable, "-c", _CRASH_CHILD.format(repo=REPO), path],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                proc.stdout, proc.stderr)
    assert "SURVIVED" not in proc.stdout
    dg_line = [ln for ln in proc.stdout.splitlines()
               if ln.startswith("DIGEST ")]
    assert dg_line, proc.stdout
    want = int(dg_line[0].split()[1])

    # the kill landed mid-copy: the torn temp is still on disk
    orphans = glob.glob(os.path.join(path, "*.compact"))
    assert orphans, "no .compact temp at crash time — kill landed too late"

    cfg = _comp_cfg(shard_num=2)
    back = SsdSparseTable(path, cfg)
    assert back.digest() == want, \
        "durable rows lost across a crash mid-compaction"
    # and compaction of the recovered table is still digest-exact
    back.compact()
    assert back.digest() == want
    back.close()


# ---------------------------------------------------------------------------
# observability + client plumbing
# ---------------------------------------------------------------------------

def test_obs_probe_exports_cold_tier_series(tmp_path):
    from paddle_tpu.obs import registry as obs_registry

    t = SsdSparseTable(str(tmp_path / "t"),
                       _cfg(table_id=7, ssd_admission_threshold=2))
    keys = np.arange(1, 101, dtype=np.uint64)
    t.push_sparse(keys, _grad(t, keys))
    t.push_sparse(keys, _grad(t, keys))
    t.spill(0)
    t.obs_probe()
    fams = obs_registry.REGISTRY.snapshot()["metrics"]
    for fam in ("ssd_admit_checks", "ssd_admit_rejects", "ssd_cold_rows",
                "ssd_index_bytes_per_row", "ssd_bg_backlog"):
        assert fam in fams, f"{fam} not exported"
        series = fams[fam]["series"]
        assert any(s["labels"].get("table") == "7" for s in series)


def test_cold_tier_slo_rules_construct():
    from paddle_tpu.obs.slo import cold_tier_rules

    rules = cold_tier_rules()
    names = {r.name for r in rules}
    assert names == {"cold_compaction_starved", "cold_io_budget_tight",
                     "cold_index_bloat"}
    fams = {r.family for r in rules}
    assert "ssd_bg_backlog" in fams and "ssd_index_bytes_per_row" in fams


def test_client_table_stats_passthrough(tmp_path):
    from paddle_tpu.ps.client import LocalPsClient, PsServerHandle

    server = PsServerHandle()
    cli = LocalPsClient(server)
    server.create_sparse_table(
        0, _cfg(table_id=0, ssd_path=str(tmp_path / "t")))
    server.create_sparse_table(1, TableConfig(table_id=1,
                                              accessor_config=_acc()))
    keys = np.arange(1, 51, dtype=np.uint64)
    cli.pull_sparse(0, keys)
    st = cli.table_stats(0)
    assert st["hot_rows"] == len(keys)
    assert "admit_checks" in st and "index_bytes" in st
    assert cli.table_stats(1) == {}


def test_config_file_cold_tier_knobs():
    from paddle_tpu.ps.config import load_ps_config

    job = load_ps_config({
        "hyper_parameters": {"sparse_feature_dim": 9},
        "table_parameters": {
            "storage": "ssd",
            "ssd_value_dtype": "fp16",
            "ssd_block_compress": True,
            "ssd_admission_threshold": 5,
            "ssd_admission_sketch_kb": 32,
            "ssd_bg_compact": True,
            "ssd_io_budget_mbps": 128.0,
        },
    })
    t = job.table
    assert t.ssd_value_dtype == "fp16"
    assert t.ssd_block_compress is True
    assert t.ssd_admission_threshold == 5
    assert t.ssd_admission_sketch_kb == 32
    assert t.ssd_bg_compact is True
    assert t.ssd_io_budget_mbps == 128.0

    # defaults when the block omits the cold-tier knobs
    d = load_ps_config({"hyper_parameters": {}}).table
    assert d.ssd_block_compress is False
    assert d.ssd_admission_threshold == 0
    assert d.ssd_bg_compact is False


# ---------------------------------------------------------------------------
# endurance demo — full profile (quick profile runs in `ci.sh endurance`)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_endurance_demo_full_profile(tmp_path):
    """The committed-artifact gates at 4x the quick-profile stream: a
    2M-key universe over a 40k hot budget (50x) must still clear the
    admission-leverage, index-bytes, p99-isolation and digest-exact
    acceptance bounds asserted by ``ci.sh endurance``."""
    env = dict(os.environ,
               PYTHONPATH=REPO, JAX_PLATFORMS="cpu",
               SSD_END_UNIVERSE="2000000", SSD_END_HOT="40000",
               SSD_END_BATCHES="120", SSD_END_BATCH_KEYS="8192",
               SSD_END_PULL_BATCHES="400",
               SSD_END_DIR=str(tmp_path / "end"))
    (tmp_path / "end").mkdir()
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "ssd_endurance_demo.py")],
        env=env, capture_output=True, text=True, timeout=600)
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    d = json.loads(line)
    assert "error" not in d, d
    assert d["universe"] >= 10 * d["hot_budget"], d
    assert d["offered_over_admitted"] >= 3.0, d
    assert 0 < d["index_bytes_per_row"] <= 16.0, d
    assert d["pull_p99_ratio"] <= 10.0, d
    assert d["bg_compactions"] > 0 and d["bg_backlog_final"] == 0, d
    assert d["digest_exact"] and d["digest_stable_under_churn"], d
    assert d["rss_growth_bytes"] <= 512 * 1024 * 1024, d
