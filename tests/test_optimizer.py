import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import optimizer as opt_mod


def quad_loss(params):
    return jnp.sum((params["w"] - 3.0) ** 2)


import jax


@pytest.mark.parametrize(
    "opt",
    [
        opt_mod.SGD(learning_rate=0.1),
        opt_mod.Momentum(learning_rate=0.05, momentum=0.9),
        opt_mod.Adam(learning_rate=0.2),
        opt_mod.AdamW(learning_rate=0.2, weight_decay=0.001),
        opt_mod.Adagrad(learning_rate=0.9),
    ],
)
def test_optimizers_converge_on_quadratic(opt):
    params = {"w": jnp.zeros(4)}
    state = opt.init(params)
    for _ in range(150):
        grads = jax.grad(quad_loss)(params)
        params, state = opt.update(grads, state, params)
    assert np.allclose(np.asarray(params["w"]), 3.0, atol=0.15)


def test_grad_clip_global_norm():
    clip = opt_mod.ClipGradByGlobalNorm(1.0)
    grads = {"a": jnp.ones(4) * 10}
    clipped = clip(grads)
    norm = float(jnp.sqrt(jnp.sum(clipped["a"] ** 2)))
    assert abs(norm - 1.0) < 1e-5


def test_lr_schedule_cosine():
    sched = opt_mod.lr.cosine_decay(1.0, t_max=100)
    assert abs(float(sched(jnp.asarray(0))) - 1.0) < 1e-6
    assert float(sched(jnp.asarray(100))) < 1e-6


def test_step_counter_advances():
    opt = opt_mod.SGD(learning_rate=0.1)
    params = {"w": jnp.zeros(2)}
    state = opt.init(params)
    _, state = opt.update({"w": jnp.ones(2)}, state, params)
    assert int(state["step"]) == 1
