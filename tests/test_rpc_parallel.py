"""Concurrent PS transport: the parallel per-server fan-out
(FLAGS_ps_rpc_parallel), the scatter-gather zero-copy framing, the fp16
pull wire format, and the communicator's double-buffered pull prefetch.

The contract under test: with the fan-out ON, every multi-shard client
op must return bit-identical results and leave bit-identical table
state vs the serial per-server loop — concurrency changes wall-clock
only. Interleaved pull/push from multiple trainer threads must stay
frame-correct on shared connections (the per-connection mutex), which
is exactly the surface the ci.sh sanitizer matrix sweeps.
"""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

N_SERVERS = 4


def _acc(dim=8):
    # initial_range=0 → insert-on-miss rows are zeros: both paths create
    # rows deterministically, so state comparison is exact
    return AccessorConfig(embedx_dim=dim,
                          sgd=SGDRuleConfig(initial_range=0.0))


@pytest.fixture
def cluster():
    servers = [rpc.NativePsServer(n_trainers=1) for _ in range(N_SERVERS)]
    client = rpc.RpcPsClient([f"127.0.0.1:{s.port}" for s in servers])
    yield servers, client
    client.close()
    for s in servers:
        s.close()


@pytest.fixture
def parallel_flag():
    """Restore FLAGS_ps_rpc_parallel after tests that flip it."""
    old = pt.get_flags("ps_rpc_parallel")["ps_rpc_parallel"]
    yield
    pt.set_flags({"ps_rpc_parallel": old})


def _mk_push(rng, keys, dim=8):
    push = rng.normal(0, 0.1, (len(keys), 4 + dim)).astype(np.float32)
    push[:, 0] = (keys % 26).astype(np.float32)
    push[:, 1] = 1.0
    push[:, 2] = (keys % 2).astype(np.float32)
    return push


def _drive(cli, table_id, rng):
    """One deterministic op sequence over every fanned-out surface;
    returns everything the client observed."""
    keys = rng.integers(1, 1 << 20, 4096).astype(np.uint64)
    obs = [cli.pull_sparse(table_id, keys)]
    for _ in range(3):
        cli.push_sparse(table_id, keys, _mk_push(rng, keys))
        obs.append(cli.pull_sparse(table_id, keys))
    vals, found = cli.export_full(table_id, keys[:512])
    obs += [vals, found.astype(np.float32)]
    cli.create_dense_table(table_id, 301, optimizer="adam", lr=0.01)
    for _ in range(3):
        cli.push_dense(table_id, rng.normal(0, 1, 301).astype(np.float32))
    obs.append(cli.pull_dense(table_id))
    cli.create_geo_table(table_id, 8)
    gk = rng.integers(1, 5000, 256).astype(np.uint64)
    cli.push_geo(table_id, gk, rng.normal(0, 1, (256, 8)).astype(np.float32))
    pk, pd = cli.pull_geo(table_id)
    order = np.argsort(pk)
    obs += [pk[order].astype(np.float64), pd[order]]
    obs.append(np.asarray([cli.size(table_id)], np.float64))
    return obs


def test_parallel_matches_serial_bitwise(cluster, parallel_flag):
    """Every fanned-out op: bit-identical client results AND table state
    between the parallel and serial paths."""
    _, cli = cluster
    state = {}
    for par, tid in ((True, 0), (False, 1)):
        pt.set_flags({"ps_rpc_parallel": par})
        cli.create_sparse_table(tid, TableConfig(shard_num=4,
                                                 accessor_config=_acc()))
        state[par] = _drive(cli, tid, np.random.default_rng(7))
    assert len(state[True]) == len(state[False])
    for a, b in zip(state[True], state[False]):
        np.testing.assert_array_equal(a, b)


def test_interleaved_pull_push_threads(cluster, parallel_flag):
    """Interleaved pull/push from several trainer threads through ONE
    client (shared connections): the per-connection mutex keeps frames
    correct, and per-key state ends bit-identical to the serial path —
    threads own disjoint key ranges so the final state is
    order-independent."""
    _, cli = cluster
    n_threads, rounds = 4, 6

    def run(tid):
        errs = []

        def worker(w):
            try:
                rng = np.random.default_rng(100 + w)
                # disjoint ranges, but every shard hit by every thread
                keys = (rng.integers(0, 1 << 16, 2048).astype(np.uint64)
                        * np.uint64(n_threads) + np.uint64(w))
                push = _mk_push(rng, keys)
                width = cli._dims(tid)[0]
                for _ in range(rounds):
                    got = cli.pull_sparse(tid, keys)
                    assert got.shape == (len(keys), width)
                    cli.push_sparse(tid, keys, push)
                return keys
            except Exception as e:  # surfaced below — don't hang join
                errs.append(e)
                raise

        out = [None] * n_threads
        ts = [threading.Thread(target=lambda i=i: out.__setitem__(
            i, worker(i))) for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120)
        assert not errs, errs
        all_keys = np.concatenate([k for k in out])
        return cli.export_full(tid, np.unique(all_keys))

    pt.set_flags({"ps_rpc_parallel": True})
    cli.create_sparse_table(0, TableConfig(shard_num=4,
                                           accessor_config=_acc()))
    vals_par, found_par = run(0)

    pt.set_flags({"ps_rpc_parallel": False})
    cli.create_sparse_table(1, TableConfig(shard_num=4,
                                           accessor_config=_acc()))
    vals_ser, found_ser = run(1)

    np.testing.assert_array_equal(found_par, found_ser)
    np.testing.assert_array_equal(vals_par, vals_ser)


def test_fp16_pull_wire(cluster):
    """pull_wire_dtype='fp16': pulled values are exactly the fp32 values
    squeezed through IEEE half (RNE) — half the response bytes, same
    table state (pushes stay fp32)."""
    _, cli = cluster
    rng = np.random.default_rng(3)
    keys = rng.integers(1, 1 << 18, 3000).astype(np.uint64)
    push = _mk_push(rng, keys)

    cli.create_sparse_table(0, TableConfig(shard_num=4,
                                           accessor_config=_acc()))
    cli.create_sparse_table(1, TableConfig(shard_num=4,
                                           accessor_config=_acc(),
                                           pull_wire_dtype="fp16"))
    for tid in (0, 1):
        cli.push_sparse(tid, keys, push)
    exact = cli.pull_sparse(0, keys)
    half = cli.pull_sparse(1, keys)
    np.testing.assert_array_equal(
        half, exact.astype(np.float16).astype(np.float32))
    # server state itself is full precision — export is unaffected
    v0, _ = cli.export_full(0, keys)
    v1, _ = cli.export_full(1, keys)
    np.testing.assert_array_equal(v0, v1)


def test_bad_wire_dtype_rejected(cluster):
    _, cli = cluster
    with pytest.raises(Exception, match="pull_wire_dtype"):
        cli.create_sparse_table(0, TableConfig(
            shard_num=2, accessor_config=_acc(), pull_wire_dtype="bf16"))


def test_pull_ahead_drains_on_barrier(cluster):
    """communicator.pull_sparse_async: barrier() must not return while a
    prefetched pull is still in flight (Sync/HalfAsync join semantics)."""
    from paddle_tpu.ps.communicator import HalfAsyncCommunicator

    _, cli = cluster
    cli.create_sparse_table(0, TableConfig(shard_num=4,
                                           accessor_config=_acc()))
    comm = HalfAsyncCommunicator(cli)
    comm.start()
    try:
        rng = np.random.default_rng(0)
        keys = rng.integers(1, 1 << 18, 8192).astype(np.uint64)
        futs = [comm.pull_sparse_async(0, keys) for _ in range(4)]
        comm.barrier()
        assert all(f.done() for f in futs)
        ref = cli.pull_sparse(0, keys)
        for f in futs:
            np.testing.assert_array_equal(f.result(), ref)
    finally:
        comm.stop()
    assert not comm._inflight_pulls


def test_stream_trainer_pull_ahead_matches_depth0(cluster):
    """The double-buffered stream trainer (pull_ahead=1 over a HalfAsync
    communicator) converges like the no-prefetch loop: same data, same
    model seed — final losses within a small band (pulls are stale by at
    most the queued pushes, which a drained queue between passes makes
    empty here)."""
    from paddle_tpu import optimizer
    from paddle_tpu.data.dataset import InMemoryDataset, SlotDesc
    from paddle_tpu.models.ctr import CtrConfig, DeepFM
    from paddle_tpu.ps.communicator import HalfAsyncCommunicator
    from paddle_tpu.ps.ps_trainer import CtrStreamTrainer

    _, cli = cluster
    S, D = 4, 3
    slots = ([SlotDesc(f"s{i}", is_float=False, max_len=1)
              for i in range(S)]
             + [SlotDesc(f"d{i}", is_float=True, max_len=1)
                for i in range(D)]
             + [SlotDesc("label", is_float=True, max_len=1)])
    rng = np.random.default_rng(0)

    def lines(n):
        out = []
        for _ in range(n):
            ks = rng.integers(0, 400, S)
            ds = rng.normal(0, 1, D)
            y = int((ks.sum() + ds.sum() * 50) % 2)
            parts = [f"1 {k}" for k in ks]
            parts += [f"1 {v:.4f}" for v in ds]
            parts.append(f"1 {y}")
            out.append(" ".join(parts))
        return out

    ds = InMemoryDataset(slots, seed=0)
    ds.load_from_lines(lines(1024))

    results = {}
    for depth, tid in ((1, 0), (0, 1)):
        pt.seed(0)
        cli.create_sparse_table(tid, TableConfig(
            shard_num=4, accessor_config=_acc(4)))
        comm = HalfAsyncCommunicator(cli)
        comm.start()
        try:
            cfg = CtrConfig(num_sparse_slots=S, num_dense=D, embedx_dim=4,
                            dnn_hidden=(8,))
            tr = CtrStreamTrainer(
                DeepFM(cfg), optimizer.Adam(1e-2), None,
                sparse_slots=[f"s{i}" for i in range(S)],
                dense_slots=[f"d{i}" for i in range(D)],
                label_slot="label", communicator=comm, table_id=tid,
                embedx_dim=4, pull_ahead=depth)
            assert tr.pull_ahead == depth
            losses = [tr.train_from_dataset(ds, batch_size=128)["loss"]
                      for _ in range(3)]
        finally:
            comm.stop()
        assert not comm._inflight_pulls
        results[depth] = losses
    # both learn, and the stale-by-one trajectory stays close
    for d in (0, 1):
        assert results[d][-1] < results[d][0]
    assert abs(results[1][-1] - results[0][-1]) < 0.1, results


@pytest.mark.slow
def test_parallel_pull_not_slower_than_serial(cluster, parallel_flag):
    """Microbench (the acceptance gate): on a 4-shard cluster the
    parallel pull+push round-trip must be measurably cheaper than the
    serial loop — latency is max(shards), not sum(shards). shard_num=1
    keeps each server's engine single-threaded so the comparison
    measures transport overlap, not engine thread-pool luck."""
    _, cli = cluster
    cli.create_sparse_table(0, TableConfig(shard_num=1,
                                           accessor_config=_acc()))
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 1 << 22, 20000).astype(np.uint64)
    push = _mk_push(rng, keys)

    def round_trip():
        cli.pull_sparse(0, keys)
        cli.push_sparse(0, keys, push)

    def measure():
        for _ in range(3):
            round_trip()  # warm connections, buffers, table rows
        best = float("inf")
        for _ in range(9):
            t0 = time.perf_counter()
            round_trip()
            best = min(best, time.perf_counter() - t0)
        return best

    pt.set_flags({"ps_rpc_parallel": True})
    t_par = measure()
    pt.set_flags({"ps_rpc_parallel": False})
    t_ser = measure()
    # locally ~0.75-0.85x; the gate allows noise but demands "not slower"
    assert t_par <= t_ser * 1.05, (
        f"parallel fan-out slower than serial: {t_par*1e3:.2f}ms vs "
        f"{t_ser*1e3:.2f}ms")
