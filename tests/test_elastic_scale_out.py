"""Elastic scale-OUT end-to-end (VERDICT r3 #7): a solo worker + 1 PS
server; a NEW worker announces itself mid-job, the leader's
ElasticManager sees the grown world (watch_once → RESTART,
manager.py:465 _update_elastic_scale_out), adopts it (np 1→2, endpoint
rewrite), redistributes partitions to the joiner, and the job finishes
with every (pass, partition) applied exactly once — the join boundary
neither drops nor double-applies work.

Mirror of test_elastic_e2e's scale-in flow; the consistency oracle is
the same additive show counter.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed.elastic import FileStore

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not rpc.rpc_available(),
                       reason="native toolchain unavailable"),
]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SERVER_SCRIPT = """
import sys, time
from paddle_tpu.ps.rpc import NativePsServer
s = NativePsServer(port=0, n_trainers=1)
print("READY", s.port, flush=True)
time.sleep(3600)
"""

# Leader (worker-0) starts SOLO owning both partitions. At the pass
# boundary where the joiner's heartbeat appears, watch_once returns
# RESTART (n=2 > np=1), the leader adopts the larger world, hands
# partition 1 to the joiner from the NEXT pass, and both soft-sync
# through the store (done/completed keys) exactly like the scale-in
# test. The leader deliberately holds at pass 3 until the join lands so
# the scenario is deterministic.
_WORKER_SCRIPT = """
import json, os, sys, time
import numpy as np
from paddle_tpu.distributed.elastic import (ElasticManager, ElasticStatus,
                                            FileStore)
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.rpc import RpcPsClient
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

store_spec, store_dir, endpoint, host, n_passes = sys.argv[1:6]
P, NPART = int(n_passes), 2
rank = int(host.split("-")[1])
from paddle_tpu.distributed.elastic import store_from_spec
store = store_from_spec(store_spec)
em = ElasticManager(store, "job", np=1 if rank == 0 else 2, host=host,
                    heartbeat_interval=0.2, heartbeat_ttl=1.2,
                    elastic_timeout=1.0, min_np=1, max_np=2)
em.start()

cfg = TableConfig(shard_num=4, accessor_config=AccessorConfig(
    sgd=SGDRuleConfig(initial_range=0.0)))
cli = RpcPsClient([endpoint])
cli.create_sparse_table(0, cfg)  # idempotent across trainers
push_dim = 12


def keys_of(part):
    return (1 + part * 1000 + np.arange(50)).astype(np.uint64)


def train(p, part):
    keys = keys_of(part)
    cli.pull_sparse(0, keys)
    push = np.zeros((len(keys), push_dim), np.float32)
    push[:, 1] = 1.0            # show += 1: the exactly-once oracle
    push[:, 3:] = 0.01 * (p + 1)
    cli.push_sparse(0, keys, push)
    store.put(f"done/{p}/{part}", "1")


def ckpt_dir(p):
    return os.path.join(store_dir, f"table_ckpt_{p}")


if rank == 1:
    # joiner: heartbeat announces us; wait for the leader's assignment,
    # then own partition 1 from the published resume pass onward
    gate = time.time() + 60
    while store.get("parts/worker-1") is None and time.time() < gate:
        time.sleep(0.05)
    assert store.get("parts/worker-1") == "1", "never assigned a partition"
    start_pass = int(store.get("resume_from"))
    for p in range(start_pass, P):
        train(p, 1)
        store.put("joiner_passes", str(p - start_pass + 1))
        while int(store.get("completed") or -1) < p:
            time.sleep(0.05)
    em.stop()
    cli.close()
    print("JOINER_DONE", flush=True)
    sys.exit(0)

# leader (worker-0): solo start, scale out when the joiner appears
my_parts = [0, 1]
scaled = False
for p in range(P):
    if not scaled:
        if p == 3:
            # hold the job open until the join lands (deterministic)
            gate = time.time() + 60
            while em.watch_once() != ElasticStatus.RESTART:
                assert time.time() < gate, "joiner never announced"
                time.sleep(0.05)
            st = ElasticStatus.RESTART
        else:
            st = em.watch_once()
        if st == ElasticStatus.RESTART:
            new_np = em.adopt_world()          # scale OUT: np 1 -> 2
            assert new_np == 2, new_np
            store.put("scaled_out", "1")
            store.put("resume_from", str(p))   # joiner starts at pass p
            store.put("parts/worker-1", "1")   # redistribute
            my_parts = [0]
            scaled = True
    for part in my_parts:
        train(p, part)
    if scaled:
        # wait for the joiner's partition before sealing the pass
        gate = time.time() + 60
        while not store.get(f"done/{p}/1"):
            assert time.time() < gate, f"joiner stalled at pass {p}"
            time.sleep(0.05)
    cli.save(0, ckpt_dir(p))
    store.put("completed", str(p))

assert scaled, "job finished without ever scaling out"
em.stop()
# let the joiner observe the final completed key before the server stops
time.sleep(0.5)
cli.stop_servers()
cli.close()
print("LEADER_DONE", flush=True)
"""


@pytest.mark.parametrize("backend", ["file", "tcp"])
def test_elastic_scale_out_redistributes_exactly_once(tmp_path, backend):
    """Parametrized over the store backend: FileStore and the
    cross-host TcpElasticStore (lease-TTL heartbeats over the cluster
    TCPStore — the reference's etcd role, VERDICT r4 #6)."""
    from paddle_tpu.distributed.elastic import TcpElasticStore

    n_passes = 6
    store_dir = str(tmp_path / "store")
    master = None
    if backend == "file":
        store_spec = f"file:{store_dir}"
        store = FileStore(store_dir)
    else:
        master = TcpElasticStore(is_master=True)
        store_spec = f"tcp:127.0.0.1:{master.port}"
        store = master
    server = subprocess.Popen([sys.executable, "-c", _SERVER_SCRIPT],
                              stdout=subprocess.PIPE, text=True,
                              cwd=_REPO_ROOT)
    procs = [server]
    try:
        line = server.stdout.readline().strip()
        assert line.startswith("READY"), line
        endpoint = f"127.0.0.1:{line.split()[1]}"

        def spawn(host):
            return subprocess.Popen(
                [sys.executable, "-c", _WORKER_SCRIPT, store_spec, store_dir,
                 endpoint, host, str(n_passes)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=_REPO_ROOT)

        leader = spawn("worker-0")
        procs.append(leader)

        # let the solo leader make progress, THEN join a new worker
        deadline = time.monotonic() + 60
        while int(store.get("completed") or -1) < 1:
            assert time.monotonic() < deadline, "leader made no progress"
            assert leader.poll() is None, leader.communicate()[0]
            time.sleep(0.1)
        joiner = spawn("worker-1")
        procs.append(joiner)

        out, _ = leader.communicate(timeout=120)
        assert leader.returncode == 0, out
        assert "LEADER_DONE" in out, out
        jout, _ = joiner.communicate(timeout=60)
        assert joiner.returncode == 0, jout
        assert "JOINER_DONE" in jout, jout
        assert store.get("scaled_out") == "1", "leader never scaled out"
        # the joiner really did a share of the passes
        assert int(store.get("joiner_passes") or 0) >= 1
        # adopt_world rewrote the endpoint set to the larger world
        eps = json.loads(store.get("elastic/job/endpoints") or "[]")
        assert eps == ["worker-0", "worker-1"], eps

        # consistency oracle: every (pass, partition) exactly once —
        # show == n_passes on every key of both partitions, across the
        # ownership handoff
        final = os.path.join(store_dir, f"table_ckpt_{n_passes - 1}")
        assert os.path.isdir(final)
        with open(os.path.join(final, "meta.json")) as f:
            meta = json.load(f)
        rows = {}
        for s in range(meta["shard_num"]):
            path = os.path.join(final, f"part-{s:05d}.shard")
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for ln in f:
                    parts = ln.split()
                    if parts:
                        rows[int(parts[0])] = float(parts[4])  # show col
        expect = {int(k) for part in range(2)
                  for k in (1 + part * 1000 + np.arange(50))}
        assert set(rows) == expect, (len(rows), len(expect))
        bad = {k: v for k, v in rows.items() if v != n_passes}
        assert not bad, f"{len(bad)} keys wrong: {list(bad.items())[:5]}"
    finally:
        for pproc in procs:
            if pproc.poll() is None:
                pproc.kill()
        if master is not None:
            master.close()
