"""Elastic end-to-end with the PS stack: 2 workers + 1 PS server; a
worker is SIGKILLed mid-pass, the survivor's ElasticManager detects the
heartbeat loss, scales the world in, restores the table from the last
complete auto-checkpoint and finishes the job solo — final table state
is exactly-once consistent.

Reference loop: fleet/elastic/manager.py:439-532 (watch → RESTART →
endpoint rewrite) + incubate/checkpoint/auto_checkpoint.py resume; the
consistency oracle is the additive show counter (every (pass, partition)
must land exactly once despite the crash + replay).
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed.elastic import FileStore

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SERVER_SCRIPT = """
import sys, time
from paddle_tpu.ps.rpc import NativePsServer
s = NativePsServer(port=0, n_trainers=1)
print("READY", s.port, flush=True)
time.sleep(3600)
"""

# Per-pass work: worker w pulls+pushes show=1 on its partition's keys.
# The leader (rank 0) soft-syncs pass completion through the elastic
# store (the BarrierTable is n_trainers-static, so dynamic membership
# coordinates through the store like the reference's etcd), checkpoints
# the table each completed pass, and on RESTART adopts the smaller
# world, reloads the last complete checkpoint and replays from there.
_WORKER_SCRIPT = """
import json, os, sys, time
import numpy as np
from paddle_tpu.distributed.elastic import (ElasticManager, ElasticStatus,
                                            FileStore)
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.rpc import RpcPsClient
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import TableConfig

store_spec, store_dir, endpoint, host, n_passes = sys.argv[1:6]
P, NPART = int(n_passes), 2
rank = int(host.split("-")[1])
from paddle_tpu.distributed.elastic import store_from_spec
store = store_from_spec(store_spec)
em = ElasticManager(store, "job", np=2, host=host,
                    heartbeat_interval=0.2, heartbeat_ttl=1.2,
                    elastic_timeout=1.0, min_np=1, max_np=2)
em.start()

cfg = TableConfig(shard_num=4, accessor_config=AccessorConfig(
    sgd=SGDRuleConfig(initial_range=0.0)))
cli = RpcPsClient([endpoint])
cli.create_sparse_table(0, cfg)  # idempotent across trainers
push_dim = 12

# start gate: wait for BOTH members to heartbeat before training, or the
# leader's first watch could scale in against a peer that is still
# booting its interpreter (the reference's launcher joins the etcd
# prefix before exec'ing trainers for the same reason)
gate = time.time() + 30
while len(em.alive_hosts()) < 2 and time.time() < gate:
    time.sleep(0.1)
assert len(em.alive_hosts()) == 2, em.alive_hosts()
em._last_change = time.monotonic()  # membership settled; arm the timer


def keys_of(part):
    return (1 + part * 1000 + np.arange(50)).astype(np.uint64)


def train(p, part):
    keys = keys_of(part)
    cli.pull_sparse(0, keys)
    push = np.zeros((len(keys), push_dim), np.float32)
    push[:, 1] = 1.0            # show += 1: the exactly-once oracle
    push[:, 3:] = 0.01 * (p + 1)
    cli.push_sparse(0, keys, push)
    store.put(f"done/{p}/{part}", "1")


def ckpt_dir(p):
    return os.path.join(store_dir, f"table_ckpt_{p}")


if rank == 1:
    # victim: finishes passes 0..1, then stalls mid-pass 2 (after pull,
    # before push) and waits for the SIGKILL the test delivers
    for p in range(P):
        if p == 2:
            cli.pull_sparse(0, keys_of(1))
            store.put("victim_at_pass", "2")
            time.sleep(3600)
        train(p, 1)
        while int(store.get("completed") or -1) < p:
            time.sleep(0.05)
    sys.exit(0)

# leader (rank 0)
my_parts = [0]
p = 0
while p < P:
    for part in my_parts:
        train(p, part)
    # wait for every partition of pass p (soft barrier over the store)
    redo = False
    while not all(store.get(f"done/{p}/{part}") for part in range(NPART)):
        st = em.watch_once()
        if st == ElasticStatus.RESTART:
            new_np = em.adopt_world()
            assert new_np == 1, new_np
            store.put("scaled_in", "1")
            my_parts = list(range(NPART))   # survivor owns all partitions
            lp = int(store.get("completed") or -1)
            if lp >= 0:
                # restore: overwrite the live table from the last COMPLETE
                # pass checkpoint (discards the aborted pass's partial
                # pushes, ours included) and replay from there
                cli.load(0, ckpt_dir(lp))
            p = lp  # incremented below; replay starts at lp + 1
            redo = True
            break
        assert st != ElasticStatus.ERROR, "dropped below min_np"
        time.sleep(0.05)
    if not redo:
        cli.save(0, ckpt_dir(p))
        store.put("completed", str(p))
    p += 1

em.stop()
cli.stop_servers()
cli.close()
print("LEADER_DONE", flush=True)
"""


@pytest.mark.parametrize("backend", ["file", "tcp"])
def test_elastic_scale_in_resumes_consistently(tmp_path, backend):
    """Parametrized over the store backend: FileStore (shared FS) and
    TcpElasticStore (cluster TCPStore with lease-TTL heartbeats — the
    reference's etcd role, VERDICT r4 #6); same membership semantics,
    same exactly-once outcome."""
    from paddle_tpu.distributed.elastic import TcpElasticStore

    n_passes = 6
    store_dir = str(tmp_path / "store")
    master = None
    if backend == "file":
        store_spec = f"file:{store_dir}"
        store = FileStore(store_dir)
    else:
        master = TcpElasticStore(is_master=True)
        store_spec = f"tcp:127.0.0.1:{master.port}"
        store = master
    server = subprocess.Popen([sys.executable, "-c", _SERVER_SCRIPT],
                              stdout=subprocess.PIPE, text=True,
                              cwd=_REPO_ROOT)
    procs = [server]
    try:
        line = server.stdout.readline().strip()
        assert line.startswith("READY"), line
        endpoint = f"127.0.0.1:{line.split()[1]}"

        def spawn(host):
            return subprocess.Popen(
                [sys.executable, "-c", _WORKER_SCRIPT, store_spec, store_dir,
                 endpoint, host, str(n_passes)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=_REPO_ROOT)

        leader = spawn("worker-0")
        victim = spawn("worker-1")
        procs += [leader, victim]

        # wait for the victim to stall mid-pass, then SIGKILL it
        deadline = time.monotonic() + 60
        while store.get("victim_at_pass") is None:
            assert time.monotonic() < deadline, "victim never reached pass 2"
            assert victim.poll() is None, victim.communicate()[0]
            time.sleep(0.1)
        victim.kill()
        victim.wait()

        out, _ = leader.communicate(timeout=120)
        assert leader.returncode == 0, out
        assert "LEADER_DONE" in out, out
        assert store.get("scaled_in") == "1", "leader never scaled in"

        # consistency: every (pass, partition) applied exactly once —
        # show == n_passes on every key of BOTH partitions, including the
        # dead worker's partition replayed by the survivor (the leader
        # stopped the server after training, so read the final pass's
        # published checkpoint)
        final = os.path.join(store_dir, f"table_ckpt_{n_passes - 1}")
        assert os.path.isdir(final)
        import json

        with open(os.path.join(final, "meta.json")) as f:
            meta = json.load(f)
        rows = {}
        for s in range(meta["shard_num"]):
            path = os.path.join(final, f"part-{s:05d}.shard")
            if not os.path.exists(path):
                continue
            with open(path) as f:
                for ln in f:
                    parts = ln.split()
                    if parts:
                        rows[int(parts[0])] = float(parts[4])  # show column
        expect = {int(k) for part in range(2)
                  for k in (1 + part * 1000 + np.arange(50))}
        assert set(rows) == expect, (len(rows), len(expect))
        bad = {k: v for k, v in rows.items() if v != n_passes}
        assert not bad, f"{len(bad)} keys with wrong show count: {list(bad.items())[:5]}"
    finally:
        for pproc in procs:
            if pproc.poll() is None:
                pproc.kill()
        if master is not None:
            master.close()
