"""tools/hlo_bytes.py: the HLO collective byte/type reporter that backs
the comm-compression acceptance gates (element types, wire bytes,
conditional placement)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu  # noqa: F401  (shims)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import hlo_bytes  # noqa: E402

_HAND = """\
HloModule toy

%add (a: f32[], b: f32[]) -> f32[] {
  %a = f32[] parameter(0)
  %b = f32[] parameter(1)
  ROOT %s = f32[] add(f32[] %a, f32[] %b)
}

%branch_true (p: f32[1,256]) -> f32[1,256] {
  %p = f32[1,256]{1,0} parameter(0)
  ROOT %ar = f32[1,256]{1,0} all-reduce(f32[1,256]{1,0} %p), replica_groups={{0,1,2,3}}, to_apply=%add
}

%branch_false (p: f32[1,256]) -> f32[1,256] {
  ROOT %p = f32[1,256]{1,0} parameter(0)
}

ENTRY %main (x: f32[1,256], k: s32[]) -> f32[1,256] {
  %x = f32[1,256]{1,0} parameter(0)
  %k = s32[] parameter(1)
  %rs = bf16[64]{0} reduce-scatter(bf16[256]{0} %conv), replica_groups=[2,4]<=[8], to_apply=%add
  %ag = s8[8,256]{1,0} all-gather(s8[1,256]{1,0} %q), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  ROOT %c = (f32[1,256]{1,0}) conditional(s32[] %k, f32[1,256]{1,0} %x, f32[1,256]{1,0} %x), branch_computations={%branch_true, %branch_false}
}
"""


def test_parses_ops_dtypes_bytes_groups():
    rep = hlo_bytes.report(_HAND, num_devices=8)
    by_op = {c["op"]: c for c in rep["collectives"]}
    assert set(by_op) == {"all-reduce", "reduce-scatter", "all-gather"}
    ar = by_op["all-reduce"]
    assert ar["dtype"] == "f32" and ar["result_bytes"] == 256 * 4
    assert ar["group_size"] == 4
    # ring all-reduce: 2*(3/4)*1024
    assert abs(ar["wire_bytes"] - 2 * 0.75 * 1024) < 1e-6
    rs = by_op["reduce-scatter"]
    assert rs["dtype"] == "bf16" and rs["result_bytes"] == 64 * 2
    assert rs["group_size"] == 4        # iota form [2,4]<=[8]
    assert rs["operand_bytes"] == 256 * 2
    ag = by_op["all-gather"]
    assert ag["dtype"] == "s8" and ag["result_bytes"] == 8 * 256
    assert abs(ag["wire_bytes"] - (7 / 8) * 8 * 256) < 1e-6


def test_conditional_reachability():
    rep = hlo_bytes.report(_HAND, num_devices=8)
    flags = {c["op"]: c["in_conditional"] for c in rep["collectives"]}
    assert flags["all-reduce"] is True      # lives in %branch_true
    assert flags["reduce-scatter"] is False
    assert flags["all-gather"] is False


def test_grad_collectives_filters_scalars():
    small = """\
ENTRY %m (x: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  ROOT %ar = f32[] all-reduce(f32[] %x), replica_groups={{0,1}}, to_apply=%a
}
"""
    rep = hlo_bytes.report(small, num_devices=2)
    assert rep["n_collectives"] == 1
    assert hlo_bytes.grad_collectives(rep) == []


def test_compiled_psum_program_report():
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("dp",))

    def f(x):
        return lax.psum(x, "dp")

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("dp"),),
                           out_specs=P("dp")))
    x = jnp.zeros((8, 1024), jnp.float32)
    rep = hlo_bytes.report_compiled(fn.lower(x).compile(), num_devices=8)
    ar = [c for c in rep["collectives"] if c["op"] == "all-reduce"]
    assert len(ar) == 1
    assert ar[0]["dtype"] == "f32" and ar[0]["result_bytes"] == 1024 * 4
    assert ar[0]["group_size"] == 8


def test_cli_one_json(tmp_path):
    p = tmp_path / "m.hlo"
    p.write_text(_HAND)
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.dirname(
             os.path.abspath(__file__))), "tools", "hlo_bytes.py"),
         str(p), "--devices", "8"],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    d = json.loads(out.stdout)
    assert d["n_collectives"] == 3
    assert d["wire_bytes_by_dtype"]["s8"] > 0
