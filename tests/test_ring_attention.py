"""Context parallelism: ring attention and Ulysses must match full
(serial) attention, causal and non-causal, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from paddle_tpu.core import mesh as mesh_mod
from paddle_tpu.ops import collectives as coll
from paddle_tpu.parallel.ring_attention import (
    local_attention,
    ring_attention,
    ulysses_attention,
)

B, L, H, D = 2, 16, 4, 8  # global seq L over cp=4 → 4 per rank


@pytest.fixture(scope="module")
def mesh():
    return mesh_mod.make_mesh({"dp": 2, "cp": 4})


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    mk = lambda: rng.normal(size=(B, L, H, D)).astype(np.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh, qkv, causal):
    q, k, v = qkv
    ref = local_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal)

    out = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="cp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=P(None, "cp"),
    )(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(mesh, qkv, causal):
    q, k, v = qkv
    ref = local_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal)

    out = shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis="cp", causal=causal),
        mesh=mesh,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=P(None, "cp"),
    )(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_ring_attention_backward_matches_full(mesh, qkv):
    q, k, v = qkv

    def ref_loss(q, k, v):
        return jnp.sum(local_attention(q, k, v, causal=True) ** 2)

    ref_grads = jax.grad(ref_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )

    def ring_loss(q, k, v):
        out = ring_attention(q, k, v, axis="cp", causal=True)
        # pinned-VJP psum: the loss cotangent is replicated over cp, and
        # jax-0.4.x shard_map transposes a plain psum into another psum,
        # scaling every grad by the axis size (the parallel_cross_entropy
        # drift fixed in PR 2/3) — psum_replicated pins the identity
        # backward so per-rank cotangents stay unscaled
        return coll.psum_replicated(jnp.sum(out ** 2), "cp")

    grads = shard_map(
        jax.grad(ring_loss, argnums=(0, 1, 2)),
        mesh=mesh,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
    )(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg), rtol=3e-4, atol=3e-5)


@pytest.mark.slow
def test_ring_attention_long_sequence_cp8():
    """Long-context: a 2048-token causal sequence over the full 8-way cp
    axis (256 tokens/rank) still matches full attention — the scale
    regime the ring exists for, not just the toy lengths above. Also
    runs fwd+bwd so the rotation's VJP is exercised at length."""
    mesh8 = mesh_mod.make_mesh({"cp": 8})
    Bl, Ll, Hl, Dl = 1, 2048, 4, 32
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(Bl, Ll, Hl, Dl)), jnp.float32)
               for _ in range(3))

    # differentiate w.r.t. ALL of q, k, v — the k/v cotangents flow
    # through the ppermute rotation's transpose, the path this test
    # exists to pin at length
    ref_loss, ref_grads = jax.value_and_grad(
        lambda q, k, v: jnp.sum(local_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="cp", causal=True),
        mesh=mesh8,
        in_specs=(P(None, "cp"), P(None, "cp"), P(None, "cp")),
        out_specs=P(None, "cp"))
    loss, grads = jax.jit(jax.value_and_grad(
        lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)
    for g, rg in zip(grads, ref_grads):
        np.testing.assert_allclose(np.asarray(g), np.asarray(rg),
                                   rtol=2e-3, atol=2e-3)
