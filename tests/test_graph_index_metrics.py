"""Tests for GraphTable (reference distributed/test/graph_node_test.cc
patterns), TreeIndex/LayerWiseSampler (unittests/test_index_dataset.py),
basic metrics (metrics.h BasicAucCalculator variants), and the profiler
chrome-tracing export."""

import json
import os

import numpy as np
import pytest

from paddle_tpu.core.profiler import (
    RecordEvent,
    export_chrome_tracing,
    start_timeline,
    stop_timeline,
)
from paddle_tpu.data import LayerWiseSampler, TreeIndex
from paddle_tpu.metrics import MAE, RMSE, WuAUC
from paddle_tpu.ps import GraphTable


class TestGraphTable:
    def _toy(self):
        g = GraphTable(shard_num=4, seed=0)
        g.add_graph_node([0, 1, 2, 3, 4])
        g.add_edges([0, 0, 0, 1, 2], [1, 2, 3, 2, 3], [1.0, 2.0, 3.0, 1.0, 1.0])
        return g

    def test_counts_and_degree(self):
        g = self._toy()
        assert g.node_count == 5
        assert g.edge_count == 5
        np.testing.assert_array_equal(g.get_node_degree([0, 1, 2, 3, 4]),
                                      [3, 1, 1, 0, 0])

    def test_sample_neighbors_padded(self):
        g = self._toy()
        nbrs, mask = g.sample_neighbors([0, 3, 1], sample_size=4)
        assert nbrs.shape == (3, 4) and mask.shape == (3, 4)
        assert mask[0].sum() == 3          # node 0 has 3 neighbors
        assert set(nbrs[0][mask[0]]) == {1, 2, 3}
        assert mask[1].sum() == 0          # node 3 has none
        assert mask[2].sum() == 1 and nbrs[2, 0] == 2

    def test_weighted_sampling_bias(self):
        g = GraphTable(shard_num=2, seed=1)
        g.add_edges([0] * 2, [1, 2], [100.0, 1.0])
        hits = 0
        for _ in range(50):
            nbrs, mask = g.sample_neighbors([0], sample_size=1)
            if nbrs[0, 0] == 1:
                hits += 1
        assert hits > 40  # heavy-weight neighbor dominates

    def test_features(self):
        g = GraphTable(shard_num=2)
        g.add_graph_node([7, 8], np.asarray([[1, 2], [3, 4]], np.float32))
        feats = g.get_node_feat([7, 8, 99], feat_dim=2)
        np.testing.assert_allclose(feats[:2], [[1, 2], [3, 4]])
        np.testing.assert_allclose(feats[2], 0)
        g.set_node_feat([7], np.asarray([[9, 9]], np.float32))
        np.testing.assert_allclose(g.get_node_feat([7], 2), [[9, 9]])
        with pytest.raises(Exception):
            g.set_node_feat([12345], np.zeros((1, 2), np.float32))

    def test_load_files(self, tmp_path):
        ef = tmp_path / "edges.txt"
        ef.write_text("0\t1\t2.0\n1\t2\n")
        nf = tmp_path / "nodes.txt"
        nf.write_text("0\t0.5\t0.5\n1\n2\n")
        g = GraphTable(shard_num=2)
        assert g.load_edges(str(ef)) == 2
        assert g.load_nodes(str(nf)) == 3
        assert g.node_count == 3
        np.testing.assert_allclose(g.get_node_feat([0], 2), [[0.5, 0.5]])

    def test_zero_weight_edges_sampled_safely(self):
        g = GraphTable(shard_num=2, seed=0)
        g.add_edges([0, 0, 0], [1, 2, 3], [1.0, 0.0, 0.0])
        nbrs, mask = g.sample_neighbors([0], sample_size=3)
        # only the positive-weight neighbor is samplable
        assert mask[0].sum() == 1 and nbrs[0, 0] == 1

    def test_sample_nodes(self):
        g = self._toy()
        s = g.sample_nodes(10)
        assert len(s) == 10
        assert set(s).issubset({0, 1, 2, 3, 4})


class TestTreeIndex:
    def test_structure(self):
        t = TreeIndex(list(range(100, 108)), branch=2)
        assert t.height == 3
        assert len(t.get_layer_codes(0)) == 1
        assert len(t.get_layer_codes(1)) == 2
        assert len(t.get_layer_codes(3)) == 8

    def test_travel_path(self):
        t = TreeIndex(list(range(100, 108)), branch=2)
        path = t.get_travel_codes(100)  # first leaf
        assert path[-1] == 0            # ends at root
        assert len(path) == t.height + 1
        # each step is the parent of the previous
        for a, b in zip(path, path[1:]):
            assert (a - 1) // 2 == b

    def test_items_of_codes(self):
        t = TreeIndex([5, 6, 7], branch=2)
        leaf = t.get_travel_codes(6)[0]
        assert t.get_items_of_codes([leaf]) == [6]
        assert t.get_items_of_codes([0]) == [None]

    def test_missing_item(self):
        t = TreeIndex([1, 2], branch=2)
        with pytest.raises(Exception):
            t.get_travel_codes(999)

    def test_layerwise_sampler(self):
        t = TreeIndex(list(range(16)), branch=2)  # height 4
        sampler = LayerWiseSampler(t, layer_counts=[1, 2, 2, 3], seed=0)
        idx, codes, labels = sampler.sample([3, 9])
        assert len(idx) == len(codes) == len(labels)
        # positives: one per layer per item
        assert labels.sum() == 2 * 4
        # negatives never equal the positive of their layer
        for pi in (0, 1):
            sel = idx == pi
            pos_codes = set(codes[sel][labels[sel] == 1].tolist())
            neg_codes = set(codes[sel][labels[sel] == 0].tolist())
            assert not pos_codes & neg_codes


class TestBasicMetrics:
    def test_mae_rmse(self):
        mae, rmse = MAE(), RMSE()
        preds = np.asarray([1.0, 2.0, 3.0])
        labels = np.asarray([1.5, 2.0, 5.0])
        mae.update(preds, labels)
        rmse.update(preds, labels)
        np.testing.assert_allclose(mae.accumulate(), (0.5 + 0 + 2) / 3)
        np.testing.assert_allclose(rmse.accumulate(),
                                   np.sqrt((0.25 + 0 + 4) / 3))

    def test_mask(self):
        mae = MAE()
        mae.update([1.0, 100.0], [0.0, 0.0], mask=[1, 0])
        np.testing.assert_allclose(mae.accumulate(), 1.0)

    def test_merge_across_workers(self):
        a, b = MAE(), MAE()
        a.update([1.0], [0.0])
        b.update([3.0], [0.0])
        a.merge(b.state)
        np.testing.assert_allclose(a.accumulate(), 2.0)

    def test_wuauc_perfect_and_random(self):
        m = WuAUC()
        # user 1: perfectly ranked; user 2: inverted
        m.update([1, 1, 1, 1], [0.1, 0.2, 0.8, 0.9], [0, 0, 1, 1])
        m.update([2, 2], [0.9, 0.1], [0, 1])
        # user1 auc=1 (w=4), user2 auc=0 (w=2) → 4/6
        np.testing.assert_allclose(m.accumulate(), 4 / 6)

    def test_wuauc_single_class_user_skipped(self):
        m = WuAUC()
        m.update([1, 1], [0.5, 0.6], [1, 1])     # no negatives: skipped
        m.update([2, 2], [0.2, 0.9], [0, 1])     # auc 1
        np.testing.assert_allclose(m.accumulate(), 1.0)

    def test_wuauc_merge(self):
        a, b = WuAUC(), WuAUC()
        a.update([1, 1], [0.2, 0.9], [0, 1])
        b.update([1, 1], [0.3, 0.8], [0, 1])
        a.merge(b.state)
        assert a.accumulate() == 1.0

    def test_wuauc_ties_average(self):
        m = WuAUC()
        # all predictions tied: AUC must be exactly 0.5
        m.update([1] * 6, [0.5] * 6, [0, 1, 0, 1, 0, 1])
        np.testing.assert_allclose(m.accumulate(), 0.5)

    def test_wuauc_large_user_fast(self):
        import time as _t

        rng = np.random.default_rng(0)
        n = 200_000
        m = WuAUC()
        m.update(np.ones(n), rng.random(n), rng.integers(0, 2, n))
        t0 = _t.monotonic()
        v = m.accumulate()
        assert _t.monotonic() - t0 < 5.0  # O(n log n), not O(n^2)
        assert 0.45 < v < 0.55


class TestChromeTracing:
    def test_export(self, tmp_path):
        start_timeline()
        with RecordEvent("phase_a"):
            with RecordEvent("phase_b"):
                pass
        stop_timeline()
        out = export_chrome_tracing(str(tmp_path / "trace.json"))
        blob = json.load(open(out))
        names = [e["name"] for e in blob["traceEvents"]]
        assert "phase_a" in names and "phase_b" in names
        for e in blob["traceEvents"]:
            assert e["ph"] == "X" and e["dur"] >= 0
