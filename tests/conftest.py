"""Test config: force an 8-device virtual CPU platform so sharding and
collective tests exercise real multi-device lowering without TPU hardware
(SURVEY §4 TPU translation of the localhost-subprocess harness).

The container's sitecustomize imports jax at interpreter boot with
JAX_PLATFORMS=axon, so env vars alone are too late — use jax.config
updates, which take effect as long as no backend has been initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: the XLA_FLAGS fallback above provides the 8 devices

# jax < 0.5: paddle_tpu installs compat shims (jax.shard_map with
# check_vma translation, lax.axis_size) on import — pull them in before
# any test module does `from jax import shard_map`
import paddle_tpu  # noqa: E402,F401

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Measured >5s each on the 1-core CI host (round-2 --durations run); the
# default gate (pytest.ini addopts) excludes them — run all with -m "".
_SLOW = {
    "test_tdm_learns_and_retrieves",
    "test_pass_trainer_amp_trains",
    "test_tp_grads_match_serial",
    "test_moe_ep_matches_serial",
    "test_causal_cp_matches_serial",
    "test_cp_matches_serial",
    "test_tp_matches_serial",
    "test_mobilenet_v2_shapes",
    "test_vgg11_shapes",
    "test_mobilenet_trains",
    "test_mobilenet_v1_shapes_and_scale",
    "test_vgg16_bn_shapes",
    "test_resnet50_forward_shape",
    "test_resnet18_trains",
    "test_multiprocess_cluster",
    "test_fleet_rpc_cluster",
    "test_multiprocess_failover_kill_minus_nine",
    "test_stream_trainer_survives_kill_shard_bit_identical",
    "test_ring_attention_backward_matches_full",
    "test_ring_attention_matches_full",
    "test_hybrid_moe_runs",
    "test_hybrid_loss_decreases",
    "test_hybrid_first_loss_matches_serial",
    "test_moe_single_rank_runs_and_grads",
    "test_moe_expert_parallel_matches_single_rank",
    "test_lenet_forward_and_one_step",
    "test_pipeline_training_matches_serial",
    "test_launch_local_trainers",
    "test_hybrid_save_load_resume",
    "test_pipeline_trainer_save_load_resume",
    "test_auto_checkpoint_resumes_day_stream",
    "test_train_passes_overlapped_matches_sequential",
    "test_launch_propagates_failure",
    "test_elastic_launch_restarts_and_completes",
    "test_elastic_launch_gives_up_below_min_np",
    "test_dssm_learns_pairing_and_ranks_true_doc",
    "test_sharded_key_fed_matches_row_fed",
    "test_elastic_scale_in_resumes_consistently",
    "test_hybrid_sharding_axis_shards_opt_state",
    "test_routed_hot_key_batches_fit_with_dedup",
    "test_routed_negative_sentinel_rows",
    "test_din_learns_match_signal_and_ignores_padding",
    "test_multitask_learns_both_tasks",
    "test_slab_pass_matches_single_step_pass",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name.split("[")[0] in _SLOW:
            item.add_marker(pytest.mark.slow)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def launch_two_workers(worker_src: str, tmp_path, timeout: float = 240):
    """Spawn two localhost jax.distributed worker processes running
    ``worker_src`` (argv: rank world port) and return their outputs.
    Guarantees cleanup: workers are killed on timeout or assertion
    failure — never leak distributed processes into later tests."""
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(DISTRIBUTED_WORKER_PREAMBLE + worker_src)
    procs = []
    for r in range(2):
        env = dict(os.environ,
                   PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_PLATFORMS", None)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), str(r), "2", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True))
    outs = []
    try:
        for r, p in enumerate(procs):
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
            assert f"WORKER_OK {r}" in out, out[-3000:]
    finally:
        for p in procs:  # never leak distributed workers on failure
            if p.poll() is None:
                p.kill()
    return outs


#: shared bootstrap for two-process jax.distributed worker scripts
#: (argv: rank world port); launch_two_workers prepends this to the
#: worker source so the env/config dance lives in exactly one place
DISTRIBUTED_WORKER_PREAMBLE = """
import os, sys
import numpy as np

rank = int(sys.argv[1]); world = int(sys.argv[2]); port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["RANK"] = str(rank)
os.environ["WORLD_SIZE"] = str(world)
os.environ["PADDLE_MASTER"] = f"127.0.0.1:{port}"
import jax
jax.config.update("jax_platforms", "cpu")

from paddle_tpu.distributed import collective as C

env = C.init_parallel_env()
assert env.rank == rank and env.world_size == world
assert len(jax.devices()) == world * 4, len(jax.devices())
"""
