"""Test config: force an 8-device virtual CPU platform so sharding and
collective tests exercise real multi-device lowering without TPU hardware
(SURVEY §4 TPU translation of the localhost-subprocess harness).

The container's sitecustomize imports jax at interpreter boot with
JAX_PLATFORMS=axon, so env vars alone are too late — use jax.config
updates, which take effect as long as no backend has been initialized.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
