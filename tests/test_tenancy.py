"""Multi-tenant PS cloud (ps/tenancy.py + the csrc tenancy fence;
ISSUE 19).

Layers under test, bottom-up: the namespace/shift constants pinned
against the csrc enums, connection binding (kTenantHello: token check,
rebind refusal, replay after reconnect), the wire-enforced namespace
fence (kErrWrongTenant), operator-plane-only kTenantConfig, enforced
quotas (RAM rows + SSD bytes — refusal, never eviction), the
token-bucket admission classes (batch sheds with retry_after, serve
queues briefly), the TenantDirectory control plane over an HACluster
(register-to-every-replica, tenant-bound clients across failover,
billing meters, restarted-replica re-sync), hot-tier per-tenant HBM
slot caps, the per-tenant SLO/flight-recorder scoping, and the slow
interference e2e: three well-behaved tenants + one abusive tenant on
ONE shared cluster, with p99 isolation and digest-proven zero
cross-tenant writes.
"""

import os
import threading
import time

import numpy as np
# numpy lazy-loads np.testing, and ITS import runs a subprocess (SVE
# probe). Under the TSAN sweep, a fork once cluster threads are live
# deadlocks the child — import it NOW, while this is the only thread.
import numpy.testing  # noqa: F401
import pytest

from paddle_tpu.core.enforce import (QuotaExceededError, ThrottledError,
                                     WrongTenantError)
from paddle_tpu.ps.accessor import AccessorConfig
from paddle_tpu.ps.sgd_rule import SGDRuleConfig
from paddle_tpu.ps.table import MemorySparseTable, TableConfig

rpc = pytest.importorskip("paddle_tpu.ps.rpc")

pytestmark = pytest.mark.skipif(
    not rpc.rpc_available(), reason="native toolchain unavailable")

from paddle_tpu.ps import ha, tenancy  # noqa: E402
from paddle_tpu.ps.tenancy import (Tenant, TenantDirectory,  # noqa: E402
                                   namespace_keys, split_table_id,
                                   tenant_flight_recorder, tenant_of_keys,
                                   tenant_slo_rules, tenant_table_id)

_CSRC = os.path.join(os.path.dirname(__file__), os.pardir,
                     "paddle_tpu", "csrc", "ps_service.cc")


def _acc():
    return AccessorConfig(sgd=SGDRuleConfig(initial_range=0.0))


def _cfg(shards=4):
    return TableConfig(shard_num=shards, accessor_config=_acc())


@pytest.fixture
def server():
    s = rpc.NativePsServer(n_trainers=1)
    yield s
    s.close()


def _op(server):
    """Operator-plane conn (no hello — tenant 0)."""
    return rpc.make_conn(f"127.0.0.1:{server.port}")


def _register(server, tid, token=b"", **kw):
    conn = _op(server)
    try:
        conn.tenant_config(tid, token=token, **kw)
    finally:
        conn.close()


def _client(server, tid, token=b""):
    return rpc.RpcPsClient([f"127.0.0.1:{server.port}"],
                           tenant=(tid, token))


def _fill(cli, table, keys):
    """Push non-trivial rows (width from the client's dims cache)."""
    width = cli._dims(table)[1]
    push = np.zeros((len(keys), width), np.float32)
    push[:, 1] = 1.0
    cli.push_sparse(table, np.asarray(keys, np.uint64), push)


# ---------------------------------------------------------------------------
# namespace constants + helpers
# ---------------------------------------------------------------------------


def test_shift_constants_pinned_against_csrc():
    # one byte of tenant tag in the 32-bit table id, top byte of u64
    # keys for shared tiers — pinned on BOTH sides of the wire
    assert tenancy.TENANT_SHIFT == rpc._TENANT_SHIFT == 24
    assert tenancy.KEY_TENANT_SHIFT == 56
    assert tenancy.MAX_TENANTS == 255
    src = open(_CSRC, encoding="utf-8").read()
    assert "kTenantShift = 24" in src, \
        "csrc kTenantShift moved without updating ps/tenancy.py"

    t = tenant_table_id(7, 42)
    assert split_table_id(t) == (7, 42)
    assert split_table_id(42) == (0, 42)       # operator plane untagged
    with pytest.raises(Exception):
        tenant_table_id(0, 1)                  # 0 is the operator plane
    with pytest.raises(Exception):
        tenant_table_id(256, 1)
    with pytest.raises(Exception):
        tenant_table_id(1, 1 << 24)

    keys = np.asarray([1, 2, (1 << 56) - 1], np.uint64)
    nk = namespace_keys(9, keys)
    assert (tenant_of_keys(nk) == 9).all()
    # the low 56 bits ride through untouched
    mask = np.uint64((1 << 56) - 1)
    np.testing.assert_array_equal(nk & mask, keys & mask)


# ---------------------------------------------------------------------------
# wire fence: hello, namespace, operator plane
# ---------------------------------------------------------------------------


def test_hello_binds_and_namespace_is_wire_enforced(server):
    _register(server, 1, token=b"alpha")
    _register(server, 2, token=b"beta")
    c1 = _client(server, 1, b"alpha")
    t1 = tenant_table_id(1, 0)
    c1.create_sparse_table(t1, _cfg())
    keys = np.arange(1, 9, dtype=np.uint64)
    out = c1.pull_sparse(t1, keys)
    assert out.shape[0] == 8
    _fill(c1, t1, keys)
    assert c1.size(t1) == 8

    # another tenant's namespace — and the operator's — bounce ON THE
    # WIRE with kErrWrongTenant (size() goes straight to the server:
    # no client-side dims cache softens the probe)
    with pytest.raises(WrongTenantError):
        c1.size(tenant_table_id(2, 0))
    with pytest.raises(WrongTenantError):
        c1.size(0)
    # the refused probes changed nothing: the table still answers
    assert c1.size(t1) == 8
    c1.close()


def test_unknown_tenant_bad_token_and_rebind_refused(server):
    _register(server, 1, token=b"alpha")
    # wrong token: refused at BIND time (client construction connects)
    with pytest.raises(WrongTenantError):
        _client(server, 1, b"wrong")
    # unknown tenant id: same refusal (no information leak about which)
    with pytest.raises(WrongTenantError):
        _client(server, 9, b"")
    # a bound connection cannot rebind (no tenant hopping mid-stream)
    conn = _op(server)
    try:
        conn.tenant_hello(1, b"alpha")
        with pytest.raises(WrongTenantError):
            conn.tenant_hello(1, b"alpha")
    finally:
        conn.close()


def test_tenant_config_is_operator_plane_only(server):
    _register(server, 1, token=b"alpha")
    conn = _op(server)
    try:
        conn.tenant_hello(1, b"alpha")
        # a bound (tenant) connection may neither install envelopes nor
        # read other meters — the config/billing plane is tenant 0's
        with pytest.raises(WrongTenantError):
            conn.tenant_config(3, token=b"x")
        with pytest.raises(WrongTenantError):
            conn.tenant_usage(1)
    finally:
        conn.close()
    # the operator reads the meter fine
    op = _op(server)
    try:
        u = op.tenant_usage(1)
        assert u["rows"] == 0 and u["pclass"] == 1
    finally:
        op.close()


# ---------------------------------------------------------------------------
# quotas: RAM rows + SSD bytes — refuse, never evict
# ---------------------------------------------------------------------------


def test_row_quota_refuses_and_never_touches_neighbors(server):
    _register(server, 1, token=b"a", max_rows=8)
    _register(server, 2, token=b"b")
    c1, c2 = _client(server, 1, b"a"), _client(server, 2, b"b")
    t1, t2 = tenant_table_id(1, 0), tenant_table_id(2, 0)
    c1.create_sparse_table(t1, _cfg())
    c2.create_sparse_table(t2, _cfg())
    _fill(c2, t2, np.arange(1, 21, dtype=np.uint64))
    assert c2.size(t2) == 20

    # quota is enforced at BATCH granularity: an under-cap tenant's
    # batch may land whole (documented overshoot ≤ one batch), the
    # next row-creating frame refuses
    refused = False
    for i in range(10):
        try:
            _fill(c1, t1, np.arange(i * 4 + 1, i * 4 + 5, dtype=np.uint64))
        except QuotaExceededError:
            refused = True
            break
    assert refused, "row quota never refused"
    rows_at_refusal = c1.size(t1)
    assert rows_at_refusal <= 8 + 4          # cap + one batch overshoot

    # refusal is REFUSAL: repeated over-quota attempts neither grow the
    # tenant nor evict anyone — the neighbor's rows are untouchable
    with pytest.raises(QuotaExceededError):
        _fill(c1, t1, np.asarray([777], np.uint64))
    assert c1.size(t1) == rows_at_refusal
    assert c2.size(t2) == 20
    _fill(c2, t2, np.asarray([999], np.uint64))   # neighbor still grows
    assert c2.size(t2) == 21

    op = _op(server)
    try:
        u = op.tenant_usage(1)
        assert u["rows"] == rows_at_refusal and u["quota_refused"] >= 2
        assert op.tenant_usage(2)["quota_refused"] == 0
    finally:
        op.close()
    c1.close()
    c2.close()


def test_ssd_bytes_quota_metered_from_live_sst_stats(server, tmp_path):
    acc = AccessorConfig(embedx_dim=4, embedx_threshold=0.0,
                         sgd=SGDRuleConfig(initial_range=0.0))
    cfg = TableConfig(shard_num=4, accessor_config=acc, storage="ssd",
                      ssd_path=str(tmp_path / "tiers"))
    _register(server, 5, token=b"ssd")
    c = _client(server, 5, b"ssd")
    t = tenant_table_id(5, 0)
    c.create_sparse_table(t, cfg)
    keys = np.arange(1, 201, dtype=np.uint64)
    _fill(c, t, keys)
    # spill the working set cold: SSD bytes appear on the meter
    assert c.spill(t, hot_budget=0) == 200
    op = _op(server)
    try:
        bytes_used = op.tenant_usage(5)["ssd_bytes"]
    finally:
        op.close()
    assert bytes_used > 0

    # the operator tightens the envelope below current usage: every
    # further row-creating frame refuses (rows stay put — quota is
    # admission control, not eviction)
    _register(server, 5, token=b"ssd", max_ssd_bytes=1)
    with pytest.raises(QuotaExceededError):
        _fill(c, t, np.asarray([10_001], np.uint64))
    assert c.size(t) == 200
    # reads are NOT row-creating: the tenant still serves its data
    got = c.pull_sparse(t, keys[:8], create=False)
    assert got.shape[0] == 8
    c.close()


# ---------------------------------------------------------------------------
# weighted admission: batch sheds, serve queues
# ---------------------------------------------------------------------------


def test_batch_class_sheds_with_retry_after(server):
    # rate 5/s, burst 10, cost = 1 + n keys = 4 per pull → two pulls
    # fit the bucket, the third sheds (refill over test time ≪ 1 token)
    _register(server, 1, token=b"a", pclass=1, rate=5.0, burst=10.0)
    _register(server, 2, token=b"b")
    c1 = _client(server, 1, b"a")
    t1 = tenant_table_id(1, 0)
    c1.create_sparse_table(t1, _cfg())
    keys = np.arange(1, 4, dtype=np.uint64)
    shed = None
    for _ in range(4):
        try:
            c1.pull_sparse(t1, keys)
        except ThrottledError as e:
            shed = e
            break
    assert shed is not None, "token bucket never shed"
    assert shed.retry_after_ms >= 1          # the hint is actionable

    # the neighbor's bucket is untouched — admission is per-tenant
    c2 = _client(server, 2, b"b")
    t2 = tenant_table_id(2, 0)
    c2.create_sparse_table(t2, _cfg())
    for _ in range(6):
        c2.pull_sparse(t2, keys)
    op = _op(server)
    try:
        assert op.tenant_usage(1)["throttled"] >= 1
        assert op.tenant_usage(2)["throttled"] == 0
    finally:
        op.close()
    c1.close()
    c2.close()


def test_serve_class_queues_briefly_instead_of_shedding(server):
    # serve (pclass 0) at a refill rate that recovers within the
    # server's brief wait: a modest overload RIDES THROUGH — no
    # ThrottledError surfaces to the serving path
    _register(server, 3, token=b"s", pclass=0, rate=2000.0, burst=5.0)
    c = _client(server, 3, b"s")
    t = tenant_table_id(3, 0)
    c.create_sparse_table(t, _cfg())
    keys = np.arange(1, 4, dtype=np.uint64)
    for _ in range(10):
        c.pull_sparse(t, keys)               # must not raise
    op = _op(server)
    try:
        assert op.tenant_usage(3)["throttled"] == 0
    finally:
        op.close()
    c.close()


def test_reconnect_replays_hello(server):
    _register(server, 1, token=b"a")
    c = _client(server, 1, b"a")
    t = tenant_table_id(1, 0)
    c.create_sparse_table(t, _cfg())
    _fill(c, t, np.arange(1, 9, dtype=np.uint64))
    # sever every transport socket under the client: the next call
    # reconnects and MUST replay the hello first — a bare reconnect
    # would bounce off the namespace fence as tenant 0
    for conn in c._conns:
        conn.close()
    assert c.size(t) == 8
    with pytest.raises(WrongTenantError):
        c.size(tenant_table_id(2, 0))
    c.close()


# ---------------------------------------------------------------------------
# TenantDirectory over an HACluster
# ---------------------------------------------------------------------------


def test_tenant_directory_register_client_usage_failover():
    from paddle_tpu.obs.registry import REGISTRY
    REGISTRY.reset()
    with ha.HACluster(num_shards=2, replication=2, sync=True) as cluster:
        d = TenantDirectory(cluster)
        ctr = d.register(Tenant(name="ctr", tid=1, token=b"ctr",
                                max_rows=10_000))
        d.register(Tenant(name="moe", tid=2, token=b"moe"))
        # one id, one tenant
        with pytest.raises(Exception):
            d.register(Tenant(name="imposter", tid=1))

        cli = d.client("ctr")
        t = ctr.table_id(0)
        cli.create_sparse_table(t, _cfg())
        keys = np.arange(1, 65, dtype=np.uint64)
        width = cli._dims(t)[1]
        push = np.zeros((len(keys), width), np.float32)
        push[:, 1] = 1.0
        cli.pull_sparse(t, keys)
        cli.push_sparse(t, keys, push)
        cluster.drain()
        assert d.usage("ctr")["rows"] == 64
        assert d.usage("moe")["rows"] == 0

        # the billing feed: tenant-labeled gauges export the meter
        usages = d.refresh_usage()
        assert usages["ctr"]["rows"] == 64
        snap = REGISTRY.snapshot()["metrics"]["tenant_rows"]
        by_tenant = {s["labels"]["tenant"]: s["value"]
                     for s in snap["series"]}
        assert by_tenant["ctr"] == 64 and by_tenant["moe"] == 0

        # kill the primary of shard 0: register() installed the
        # envelope on the BACKUPS too, and the tenant-bound client's
        # replacement conns replay the hello — the tenant rides the
        # failover with the fence intact
        before = cli.pull_sparse(t, keys, create=False)
        dead = cluster.kill_primary(0)
        after = cli.pull_sparse(t, keys, create=False)
        np.testing.assert_array_equal(before, after)
        assert cluster.wait_promoted(0, dead) != dead
        with pytest.raises(WrongTenantError):
            cli.size(tenant_table_id(2, 0))
        assert d.usage("ctr")["rows"] == 64

        # a restarted replica rejoins with an EMPTY tenant registry —
        # sync_server is the runbook step that re-arms it
        back = cluster.restart_replica(0, dead)
        assert d.sync_server(back.endpoint) == 2


# ---------------------------------------------------------------------------
# hot-tier HBM slot caps
# ---------------------------------------------------------------------------


def test_hot_tier_tenant_caps_evict_own_rows_only():
    table = MemorySparseTable(TableConfig(shard_num=2, accessor="ctr"))
    from paddle_tpu.ps.hot_tier import HotEmbeddingTier, HotTierConfig
    tier = HotEmbeddingTier(table, HotTierConfig(
        capacity=64, tenant_slots={1: 8}))

    t2_keys = namespace_keys(2, np.arange(1, 17, dtype=np.uint64))
    tier.ensure(t2_keys)                      # uncapped tenant resident
    # tenant 1 streams 3 batches of 8 through an 8-slot cap: each batch
    # fits by evicting tenant 1's OWN previous batch
    for i in range(3):
        tier.ensure(namespace_keys(
            1, np.arange(100 + i * 8, 108 + i * 8, dtype=np.uint64)))
        res = tier.tenant_residency()
        assert res.get(1, 0) <= 8, res

    st = tier.stats()
    assert st["tenants"][1] <= 8              # per-tenant residency view
    assert tier.counters["tenant_cap_evictions"] >= 16

    # tenant 2's working set was NEVER collateral: re-touching it is
    # all hits (no misses added — its rows stayed resident throughout)
    misses_before = tier.stats()["misses"]
    tier.ensure(t2_keys)
    assert tier.stats()["misses"] == misses_before
    assert tier.tenant_residency()[2] == 16

    # an incoming batch larger than the cap can never fit: loud error,
    # not silent thrash
    with pytest.raises(Exception):
        tier.ensure(namespace_keys(
            1, np.arange(500, 512, dtype=np.uint64)))


# ---------------------------------------------------------------------------
# per-tenant control plane: SLO rules + scoped flight recorder
# ---------------------------------------------------------------------------


def test_tenant_slo_rules_fire_per_tenant_only(tmp_path):
    import json

    from paddle_tpu.obs import slo as slo_mod
    from paddle_tpu.obs.registry import Registry
    from paddle_tpu.obs.timeseries import MetricRing

    reg = Registry()
    ring = MetricRing()
    g_a = reg.gauge("tenant_pull_s", max_series=8, tenant="ctr")
    g_b = reg.gauge("tenant_pull_s", max_series=8, tenant="moe")
    for i in range(4):
        g_a.set(0.2)                          # ctr breaches 50 ms
        g_b.set(0.001)                        # moe is healthy
        ring.append(reg.snapshot(), t=float(i))

    rules = tenant_slo_rules("ctr") + tenant_slo_rules("moe")
    wd = slo_mod.SloWatchdog(ring, rules)
    fired = {a.rule for a in wd.evaluate(now=3.0)}
    assert "ctr_pull_p99" in fired
    assert not any(r.startswith("moe_") for r in fired)

    # the scoped recorder: a ctr postmortem bundle carries ONLY
    # ctr-labeled alerts, and stamps its scope in the manifest
    rec = tenant_flight_recorder(str(tmp_path), "ctr", ring=ring,
                                 watchdog=wd, min_interval_s=0.0)
    path = rec.trigger("tenant_slo")
    assert path is not None and "tenant_ctr" in path
    man = json.load(open(os.path.join(path, "manifest.json")))
    assert man["scope"] == {"tenant": "ctr"}
    alerts = json.load(open(os.path.join(path, "alerts.json")))["alerts"]
    assert alerts, "scoped bundle dropped the tenant's own alerts"
    assert all((a.get("labels") or {}).get("tenant") == "ctr"
               for a in alerts)


def test_tenant_autoscaler_lever_is_scoped():
    """A per-tenant Autoscaler subscribes to ONE tenant's rules and
    journals under its tenant tag — the per-tenant scaling lever."""
    from paddle_tpu.ps.autoscale import AutoscaleConfig, Autoscaler
    from tests.test_autoscale import _FakeController, _Alert

    rules = tenant_slo_rules("ctr", pull_p99_s=0.05)
    ctrl = _FakeController()
    t = [0.0]
    a = Autoscaler(ctrl, config=AutoscaleConfig(
        min_shards=2, max_shards=8, cooldown_up_s=5.0,
        cooldown_down_s=10.0, clear_hold_s=4.0,
        up_rules=("ctr_pull_p99",)), clock=lambda: t[0], tenant="ctr")
    a.notify_fire(_Alert("moe_pull_p99"))     # neighbor's burn: ignored
    assert a.step() is None
    a.notify_fire(_Alert(rules[0].name))
    assert a.step() == "up"
    assert a.events[-1]["tenant"] == "ctr"


# ---------------------------------------------------------------------------
# the interference e2e (slow): shared cluster, abusive neighbor
# ---------------------------------------------------------------------------


def _run_tenant_loop(cli, table, shape, stop, lat, push_every=0):
    """One tenant's serving loop: pull `shape` keys; optionally push."""
    rng = np.random.default_rng(hash(table) & 0xffff)
    width = cli._dims(table)[1]
    i = 0
    while not stop.is_set():
        keys = rng.integers(1, 2000, shape).astype(np.uint64)
        t0 = time.perf_counter()
        cli.pull_sparse(table, keys)
        lat.append(time.perf_counter() - t0)
        if push_every and i % push_every == 0:
            push = np.zeros((len(keys), width), np.float32)
            push[:, 1] = 1.0
            cli.push_sparse(table, keys, push)
        i += 1


def _p99(xs):
    return float(np.percentile(np.asarray(xs), 99)) if xs else 0.0


@pytest.mark.slow
def test_interference_e2e_abusive_tenant_cannot_move_neighbor_p99():
    """Three well-behaved tenants + one deliberately abusive tenant on
    ONE shared cluster: the abuser is throttled and quota-refused; each
    well-behaved tenant's pull p99 stays within the CI-gated bound of
    its solo baseline; per-tenant digests prove the abuser changed ZERO
    bytes outside its own namespace."""
    with ha.HACluster(num_shards=2, replication=1, sync=True) as cluster:
        d = TenantDirectory(cluster)
        wb_names = ["ctr", "moe", "tdm"]
        shapes = {"ctr": 64, "moe": 16, "tdm": 8}
        for i, name in enumerate(wb_names):
            d.register(Tenant(name=name, tid=i + 1,
                              token=name.encode()))
        # the abuser: metered hard (shallow bucket) and row-capped
        d.register(Tenant(name="abuse", tid=9, token=b"abuse", pclass=1,
                          rate=500.0, burst=500.0, max_rows=500))

        clis, tables = {}, {}
        for name in wb_names + ["abuse"]:
            cli = d.client(name)
            t = d.get(name).table_id(0)
            cli.create_sparse_table(t, _cfg())
            width = cli._dims(t)[1]
            keys = np.arange(1, 2001, dtype=np.uint64)
            push = np.zeros((len(keys), width), np.float32)
            push[:, 1] = 1.0
            if name != "abuse":
                cli.push_sparse(t, keys, push)
            clis[name], tables[name] = cli, t
        cluster.drain()

        def measure(active, duration):
            stop = threading.Event()
            lats = {n: [] for n in active}
            thr = [threading.Thread(
                target=_run_tenant_loop,
                args=(clis[n], tables[n], shapes[n], stop, lats[n]),
                kwargs=dict(push_every=4 if n == "ctr" else 0),
                daemon=True, name=f"tenant-{n}") for n in active]
            for th in thr:
                th.start()
            time.sleep(duration)
            stop.set()
            for th in thr:
                th.join(10)
            return {n: _p99(v) for n, v in lats.items()}

        def abuse_flood(stop):
            """Fat pulls + row-creation churn + cross-tenant probes."""
            cli, t = clis["abuse"], tables["abuse"]
            rng = np.random.default_rng(7)
            while not stop.is_set():
                keys = rng.integers(1, 1 << 40, 512).astype(np.uint64)
                try:
                    cli.pull_sparse(t, keys, create=True)
                except (ThrottledError, QuotaExceededError):
                    pass
                try:
                    cli.size(tables["ctr"])   # cross-tenant probe
                except WrongTenantError:
                    pass

        # solo baselines (abuser idle)
        solo = measure(wb_names, 1.0)
        digests_before = {n: clis[n].digest(tables[n])
                          for n in wb_names}
        rows_before = {n: d.usage(n)["rows"] for n in wb_names}

        # contention: all three + the abusive flood
        stop = threading.Event()
        flood = threading.Thread(target=abuse_flood, args=(stop,),
                                 daemon=True, name="tenant-abuse")
        flood.start()
        loaded = measure(wb_names, 1.5)
        stop.set()
        flood.join(10)

        # the gate the bench CI-asserts too: p99 under abuse within
        # 5× solo + 20 ms scheduling slack (loose on shared CI boxes;
        # without admission control the abuser inflates this 100×)
        for n in wb_names:
            bound = 5.0 * solo[n] + 0.020
            assert loaded[n] <= bound, \
                (n, "p99 moved", solo[n], loaded[n], bound)

        # the abuser was actually contained
        au = d.usage("abuse")
        assert au["throttled"] > 0, "flood never throttled"
        # max_rows is PER SHARD (usage() aggregates): cap + one batch
        # of overshoot on each of the two shards
        assert au["rows"] <= 2 * (500 + 512)

        # zero cross-tenant writes: each well-behaved namespace is
        # digest-identical (the ctr pushes stopped before the digest)
        for n in wb_names:
            if n == "ctr":
                continue                     # its own loop only pulls
            assert clis[n].digest(tables[n]) == digests_before[n], n
            assert d.usage(n)["rows"] == rows_before[n]
